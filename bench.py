#!/usr/bin/env python
"""Headline benchmark: the 10k x 1024-node what-if sweep.

Task (BASELINE.md north star): full SPF results (f32 distances +
all-shortest-paths first-hop lane sets) for 10,240 single-link-failure
perturbations of a 1024-node WAN LSDB, one vantage root.

Measured engines:
  * **native**  — single-threaded C++ heap Dijkstra (native/spf_scalar.cc),
    the honest stand-in for the reference's SpfSolver hot loop
    (LinkState.cpp:721-800).  This is the baseline denominator.  The
    reference re-solves every perturbed topology (its SPF memo is
    invalidated on each change), so the naive full sweep is its true
    behavior; a dedup-assisted variant is reported too for transparency.
  * **python**  — the repo's scalar oracle (pure-Python Dijkstra), shown
    because round 1 mistakenly used it as the only denominator.
  * **device raw** — the warm-start repair kernel (ops/repair.py): every
    one of the 10,240 snapshots is solved independently on device (no
    dedup, no base aliasing — duplicates and off-DAG failures are solved
    like everything else), with snapshots depth-sorted into chunks.  The
    warm start is exact (see ops/repair.py docstring); its one-time
    preprocessing cost is reported separately as base_solve_ms +
    repair_plan_build_ms (the throughput numbers are warm steady-state).
    The COLD kernel (ops/spf.py, what round 2 reported) is kept as a
    detail line.
  * **device engine** — the what-if engine (ops/whatif.py): repair
    kernel + base aliasing + off-DAG skip + dedup.  Steady-state
    throughput: work dispatched async, one sync — over a tunneled TPU a
    sync round trip costs ~65ms, so single-shot numbers would measure
    the tunnel, not the chip.  Results stay device-resident (downstream
    route selection consumes them there); the host fetch of the
    unique-solve tables is timed separately.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline = device engine throughput / native naive throughput.
"""

import json
import sys
import time

import numpy as np


def main() -> None:
    t_start = time.time()
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.emulation.topology import build_adj_dbs, random_connected_edges
    from openr_tpu.ops.csr import encode_link_state
    from openr_tpu.ops.native_spf import NativeSpf
    from openr_tpu.ops.whatif import LinkFailureSweep

    import jax

    # ---- the 1024-node WAN + 10,240 perturbations ------------------------
    n_nodes = 1024
    total = 10_240
    edges = random_connected_edges(n_nodes, 2048, seed=7)
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    topo = encode_link_state(ls)
    rng = np.random.default_rng(0)
    fails = rng.integers(0, len(topo.links), size=total).astype(np.int32)

    # ---- native C++ single-threaded baseline -----------------------------
    native = NativeSpf(topo, "node0")
    native.sweep(fails[:32])  # warm caches
    t0 = time.perf_counter()
    native.sweep(fails)
    native_naive_s = time.perf_counter() - t0
    native_sps = total / native_naive_s
    uniq = np.unique(fails)
    t0 = time.perf_counter()
    native.sweep(uniq)
    native_dedup_s = time.perf_counter() - t0
    native_dedup_sps = total / native_dedup_s

    # ---- pure-Python oracle (round-1's flattering denominator) -----------
    ls.run_spf("node0", links_to_ignore=frozenset([topo.links[0]]))
    best = float("inf")
    for rep in range(3):
        t0 = time.perf_counter()
        for i in range(8):
            link = topo.links[int(fails[rep * 8 + i])]
            ls.run_spf("node0", links_to_ignore=frozenset([link]))
        best = min(best, (time.perf_counter() - t0) / 8)
    python_sps = 1.0 / best

    # ---- device: engine setup (base solve + repair plan) -----------------
    import jax.numpy as jnp

    eng = LinkFailureSweep(topo, "node0")
    t0 = time.perf_counter()
    eng.base_solve()
    base_solve_ms = (time.perf_counter() - t0) * 1000
    t0 = time.perf_counter()
    eng.plan()
    plan_build_ms = (time.perf_counter() - t0) * 1000
    rs = eng.repair_sweep()

    # measure the tunnel/dispatch sync cost once, for the detail split
    (jnp.zeros(8) + 1).block_until_ready()
    t0 = time.perf_counter()
    (jnp.zeros(8) + 1).block_until_ready()
    sync_ms = (time.perf_counter() - t0) * 1000

    # ---- device raw: every snapshot solved via the repair kernel ---------
    from openr_tpu.ops.repair import sort_by_depth

    chunk = 4096
    sfails, _ = sort_by_depth(eng.plan(), fails)

    def raw_sweep(fl):
        outs = []
        for off in range(0, total, chunk):
            c = fl[off : off + chunk]
            if len(c) % 32:
                c = np.concatenate(
                    [c, np.full(32 - len(c) % 32, -1, np.int32)]
                )
            outs.append(rs.solve(c))
        return outs

    outs = raw_sweep(sfails)
    jax.block_until_ready(outs[-1][0])  # jit warm-up (excluded)
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        outs = raw_sweep(sfails)
    jax.block_until_ready(outs[-1][0])
    device_raw_sps = reps * total / (time.perf_counter() - t0)
    raw_rounds = [(int(o[2]), int(o[3])) for o in outs]

    # ---- device cold kernel (round-2's raw path, for transparency) -------
    from openr_tpu.ops.spf import sweep_spf_link_failures

    D_cold = topo.max_out_degree()
    cold_args = (
        jnp.asarray(topo.src),
        jnp.asarray(topo.dst),
        jnp.asarray(topo.w),
        jnp.asarray(topo.edge_ok),
        jnp.asarray(topo.link_index),
    )
    ovl = jnp.asarray(topo.overloaded)
    root = jnp.int32(topo.node_id("node0"))

    def cold_sweep():
        last = None
        for off in range(0, total, 2048):
            f = jnp.asarray(fails[off : off + 2048])
            d, nh = sweep_spf_link_failures(
                *cold_args, f, ovl, root, max_degree=D_cold, packed=True
            )
            last = d
        return last

    cold_sweep().block_until_ready()
    t0 = time.perf_counter()
    last = None
    for _ in range(reps):
        last = cold_sweep()
    last.block_until_ready()
    device_cold_sps = reps * total / (time.perf_counter() - t0)

    # ---- device: what-if engine (repair + alias + off-DAG + dedup) -------
    res = eng.run(fails, fetch=False)
    res.block()  # warm-up (compiles the bucket shapes)
    t0 = time.perf_counter()
    results = [eng.run(fails, fetch=False) for _ in range(reps)]
    results[-1].block()
    engine_sps = reps * total / (time.perf_counter() - t0)
    # single-shot latency (what one cold rebuild tick would see)
    t0 = time.perf_counter()
    single = eng.run(fails, fetch=False)
    single.block()
    engine_latency_ms = (time.perf_counter() - t0) * 1000
    # ---- sweep → routes: on-device selection + delta-only fetch ----------
    # (ops/sweep_select.py): 1024 loopback prefixes selected against every
    # snapshot ON DEVICE, diffed vs the base route table on device, and
    # only the changed route rows cross the tunnel — the full end-to-end
    # sweep→routes story, replacing the old multi-MB unique-table fetch
    from openr_tpu.ops.sweep_select import SweepCandidates, SweepRouteSelector

    sel = SweepRouteSelector(
        topo,
        "node0",
        SweepCandidates.single_advertiser(np.arange(n_nodes)),
        max_degree=eng.D,
    )
    deltas = sel.run(single)  # warm-up (compiles chunk + gather shapes)
    t0 = time.perf_counter()
    sweep2 = eng.run(fails, fetch=False)
    deltas = sel.run(sweep2)
    routes_pipeline_ms = (time.perf_counter() - t0) * 1000
    # route parity vs native for sample snapshots (base + changed rows)
    for s in (3, 1007, 9000):
        native.solve(failed_link=int(fails[s]))
        valid, metric, lanes = deltas.routes_of(s)
        nd = native.dist[:n_nodes]
        nl = native.lanes_dense(eng.D)[:n_nodes]
        # valid = advertiser reachable with a first-hop set, and not the
        # root's own prefix (skip-if-self)
        exp_valid = (
            np.isfinite(nd)
            & nl.any(axis=1)
            & (np.arange(n_nodes) != topo.node_id("node0"))
        )
        assert np.array_equal(valid, exp_valid), f"route valid parity {s}"
        assert np.array_equal(metric[exp_valid], nd[exp_valid]), (
            f"route metric parity {s}"
        )
        assert np.array_equal(lanes[exp_valid], nl[exp_valid]), (
            f"route lane parity {s}"
        )

    # host fetch of the unique tables (tunnel-bound; reported, not part
    # of the throughput number — the routes pipeline above is what
    # downstream consumes; this line kept for the before/after contrast)
    t0 = time.perf_counter()
    single.materialize()
    fetch_ms = (time.perf_counter() - t0) * 1000

    # ---- parity: device results == native results ------------------------
    for s in (3, 1007, 9000):
        native.solve(failed_link=int(fails[s]))
        finite = np.isfinite(native.dist)
        assert np.array_equal(
            native.dist[finite], single.dist_of(s)[finite]
        ), f"distance parity failure at snapshot {s}"
        assert np.array_equal(
            native.lanes_dense(eng.D)[finite], single.nh_of(s)[finite]
        ), f"lane parity failure at snapshot {s}"

    print(
        json.dumps(
            {
                "metric": "whatif_sweep_snapshots_per_sec_10k_x_1024node",
                "value": round(engine_sps, 1),
                "unit": "snapshots/s",
                "vs_baseline": round(engine_sps / native_sps, 2),
                "detail": {
                    "native_cxx_solves_per_sec": round(native_sps, 1),
                    "native_cxx_dedup_effective_per_sec": round(
                        native_dedup_sps, 1
                    ),
                    "python_solves_per_sec": round(python_sps, 1),
                    "device_raw_solves_per_sec": round(device_raw_sps, 1),
                    "device_cold_solves_per_sec": round(device_cold_sps, 1),
                    "vs_native_raw_kernel_only": round(
                        device_raw_sps / native_sps, 2
                    ),
                    "vs_native_cold_kernel": round(
                        device_cold_sps / native_sps, 2
                    ),
                    "vs_native_dedup": round(engine_sps / native_dedup_sps, 2),
                    "vs_python": round(engine_sps / python_sps, 2),
                    "engine_latency_ms": round(engine_latency_ms, 1),
                    "base_solve_ms": round(base_solve_ms, 1),
                    "repair_plan_build_ms": round(plan_build_ms, 1),
                    "routes_pipeline_ms": round(routes_pipeline_ms, 1),
                    "route_deltas": int(deltas.num_deltas),
                    "route_delta_fetch_bytes": int(deltas.fetch_bytes),
                    "host_fetch_unique_tables_ms": round(fetch_ms, 1),
                    "dispatch_sync_ms": round(sync_ms, 1),
                    "unique_device_solves": int(single.num_device_solves),
                    "on_dag_link_fraction": round(
                        float(eng.on_dag_links().mean()), 3
                    ),
                    "raw_chunk_rounds_dist_lanes": raw_rounds,
                    "batch_total": total,
                    "nodes": n_nodes,
                    "directed_edges": topo.num_edges,
                    "lanes": eng.D,
                    "devices": [str(d) for d in jax.devices()],
                    "wall_s": round(time.time() - t_start, 1),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
