#!/usr/bin/env python
"""Headline benchmark: the 10k x 1024-node what-if sweep, END TO END.

Task (BASELINE.md north star): route tables for 10,240 single-link-
failure perturbations of a 1024-node WAN LSDB, one vantage root, 1024
advertised prefixes.

The HEADLINE is the full operator-visible pipeline — sweep in, route
deltas out: warm-start repair SPF (ops/repair.py) + on-device route
selection diffed against the base table (ops/sweep_select.py) with
delta-only host fetch, chunk selection dispatched behind the next
chunk's SPF.  SPF-tables-only throughput (what rounds 2-3 headlined) is
reported as a detail line (VERDICT r3 weak #2).

The engine runs through the SAME mesh-sharded code path the multichip
dryrun validates (shard_map over the batch axis; on the single bench
chip the mesh has one device).

Baselines (single-threaded C++, native/spf_scalar.cc):
  * **naive** — from-scratch heap Dijkstra per snapshot, the reference's
    true behavior (its SPF memo is invalidated per topology change,
    LinkState.h:346-390).  Median of NATIVE_REPS sweeps with spread
    (VERDICT r3 weak #1: a single timing swung -33% between rounds).
  * **dedup** — Dijkstra once per unique failed link (the courtesy the
    reference's memo would give within one unchanged topology).
  * **warm-start** — the SAME incremental-repair trick the device kernel
    uses, in C++ (spf_warm_sweep: off-DAG skip + affected-region
    Dijkstra seeded from the base solve).  The demanding apples-to-
    apples line: it separates "TPU is fast" from "incremental beats
    from-scratch" (VERDICT r3 missing #2).  SPF tables only.
  * **native engine end-to-end** — C++ warm sweep + numpy selection +
    base diff per unique on-DAG failure: the actual off-device engine
    the Decision what-if API runs, producing ROUTES OUT like the
    headline (and asserted to find the identical delta count).
  * **python** — the pure-Python oracle (round-1's flattering
    denominator, kept for transparency).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
value = end-to-end snapshots->route-deltas throughput;
vs_baseline = that / native naive median.
"""

import json
import statistics
import sys
import time
from typing import Optional

import numpy as np

NATIVE_REPS = 5
DEVICE_REPS = 3


def env_stamp() -> dict:
    """Host/chip environment recorded into every bench artifact: the
    native denominator swings ~2x across machine-days (r4 review weak
    #3), so cross-round ratios are only comparable with the environment
    pinned alongside them."""
    import os
    import platform

    cpu_model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    cpu_model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    governor = ""
    try:
        with open(
            "/sys/devices/system/cpu/cpu0/cpufreq/scaling_governor"
        ) as f:
            governor = f.read().strip()
    except OSError:
        pass
    try:
        load1, load5, _ = os.getloadavg()
    except OSError:
        load1 = load5 = -1.0
    import jax

    from openr_tpu.ops import platform_env as _pe

    return {
        "accelerator_fallback": _pe.ACCEL_FALLBACK_ACTIVE,
        "cpu_model": cpu_model,
        "cpu_count": os.cpu_count(),
        "cpu_governor": governor,
        "loadavg_1m": round(load1, 2),
        "loadavg_5m": round(load5, 2),
        "python": platform.python_version(),
        "jax": jax.__version__,
        # the accelerator identity triple every BENCH_* artifact must
        # carry so perf points are comparable across environments
        # (ISSUE 6 satellite): chip kind, jax version, visible devices
        "platform": jax.default_backend(),
        "device_count": len(jax.devices()),
    }


def build_headline_world(n_nodes: int = 1024):
    """The benchmark's canonical world: 1024-node WAN, 2048 undirected
    links, seed 7, one loopback prefix per node.  Shared with
    benchmarks/soak.py so the soak can never silently measure a
    different workload than the headline it pins (r5 review).
    Returns (link_state, topo, cands)."""
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.emulation.topology import (
        build_adj_dbs,
        random_connected_edges,
    )
    from openr_tpu.ops.csr import encode_link_state
    from openr_tpu.ops.sweep_select import SweepCandidates

    edges = random_connected_edges(n_nodes, 2 * n_nodes, seed=7)
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    topo = encode_link_state(ls)
    cands = SweepCandidates.single_advertiser(np.arange(n_nodes))
    return ls, topo, cands


def validate_convergence_bench(doc: dict) -> None:
    """Schema contract for BENCH_CONVERGENCE_r*.json — shared by the
    bench emitter and the tier-1 artifact gate.  Virtual-time
    percentiles of the 9-node flap sweep; deterministic across hosts,
    so the benchtrack ratchet holds this headline tightly."""
    assert doc["metric"] == "convergence_event_to_fib_ms_9node_grid"
    assert doc["unit"] == "ms_p50_virtual"
    d = doc["detail"]
    assert d["samples"] > 0
    assert 0 < d["p50_ms"] <= d["p95_ms"] <= d["p99_ms"] <= d["max_ms"]
    assert doc["value"] == d["p50_ms"]
    assert d["nodes"] == 9
    assert d["virtual_time"] is True
    assert d["dropped_spans"] == 0
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"


def convergence_main(seed: Optional[int] = None) -> None:
    """Trace-derived convergence percentiles: p50/p95/p99 of
    `convergence.event_to_fib_ms` over every single-link flap (fail +
    restore) of the 9-node emulated grid, measured by the tracing layer
    end to end (Spark/LinkMonitor origin → KvStore flood → Decision
    rebuild → Fib ack) in deterministic virtual time.  This is the
    protocol-plane convergence trajectory point (the device headline
    above measures the compute plane); emitted as one JSON line for the
    BENCH_* artifact series.  ``seed`` shuffles the flap order (None =
    the canonical edge order the checked-in rounds use)."""
    import asyncio
    import random as _random

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import grid_edges

    edges = grid_edges(3)
    if seed is not None:
        _random.Random(seed).shuffle(edges)

    async def run():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(edges)
        net.start()
        await clock.run_for(20.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        # drain cold-boot samples: only flap-driven convergence is scored
        for node in net.nodes.values():
            node.counters.clear()
        for a, b, _m in edges:
            net.fail_link(a, b)
            await clock.run_for(4.0)
            net.restore_link(a, b)
            await clock.run_for(4.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        conv = net.merged_histogram("convergence.event_to_fib_ms")
        spf = net.merged_histogram("decision.spf_ms")
        spans = len(net.all_spans())
        dropped = sum(
            n.tracer.num_dropped for n in net.nodes.values()
        )
        await net.stop()
        return conv, spf, spans, dropped

    conv, spf, spans, dropped = asyncio.new_event_loop().run_until_complete(
        run()
    )
    assert conv is not None and conv.count > 0, "no convergence samples"
    pct = conv.percentiles()
    doc = {
        "metric": "convergence_event_to_fib_ms_9node_grid",
        "value": round(pct["p50"], 2),
        "unit": "ms_p50_virtual",
        "detail": {
            "p50_ms": round(pct["p50"], 2),
            "p95_ms": round(pct["p95"], 2),
            "p99_ms": round(pct["p99"], 2),
            "max_ms": round(conv.vmax, 2),
            "samples": conv.count,
            "spf_p50_ms": (
                round(spf.percentile(50), 4) if spf else None
            ),
            "spans_recorded": spans,
            "dropped_spans": dropped,
            "link_flaps": len(edges) * 2,
            "nodes": 9,
            "topology": "grid3x3",
            "virtual_time": True,
            "seed": seed,
            "note": "SimClock: latencies are modeled protocol "
            "time (spark timers, debounce, flood hops), "
            "deterministic across hosts",
            "env": env_stamp(),
        },
    }
    validate_convergence_bench(doc)
    print(json.dumps(doc))


RESILIENCE_SAMPLE_EVERY = 8
RESILIENCE_BUILDS_PER_SIDE = 64


def validate_resilience_bench(doc: dict) -> None:
    """Schema contract for BENCH_RESILIENCE_r*.json — shared by the
    bench emitter and the tier-1 smoke test so the artifact can never
    drift from what the test validates.  The headline value is the
    shadow-verification overhead on the rebuild p50, and the acceptance
    bound (ISSUE 5) is <= 5%."""
    assert doc["metric"] == "resilience_shadow_overhead_pct_rebuild_p50"
    assert doc["unit"] == "pct"
    assert isinstance(doc["value"], (int, float))
    assert doc["value"] <= 5.0, "shadow overhead must stay <= 5% on p50"
    d = doc["detail"]
    assert d["rebuild_p50_ms_shadow_off"] > 0
    assert d["rebuild_p50_ms_shadow_on"] > 0
    assert d["rebuild_p95_ms_shadow_on"] >= d["rebuild_p50_ms_shadow_on"]
    assert d["builds_per_side"] >= 32
    assert d["shadow_sample_every"] >= 2
    assert d["shadow_checks_during_run"] >= 1
    sc = d["sdc_scenario"]
    assert sc["detected"] is True
    assert sc["recovered"] is True
    assert 1 <= sc["rebuilds_to_detect"] <= d["shadow_sample_every"]
    assert sc["shadow_mismatches"] >= 1
    assert sc["probes"] >= 1
    assert sc["deterministic_replay"] is True
    for key in ("world", "env", "mode"):
        assert key in d, key
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"
    assert d["env"]["device_count"] >= 1


def _resilience_sdc_scenario(seed: int = 7):
    """Seeded 9-node emulation with a ``tpu_corrupt`` fault: corruption
    detected within one shadow-sample interval, device quarantined,
    routes served from the scalar engine (InvariantChecker green
    throughout), device restored by a half-open probe after heal.  Run
    twice from one seed; byte-identical counter dumps prove the replay
    contract.  Returns the scenario detail dict."""
    import asyncio

    from openr_tpu.chaos import ChaosController, FaultPlan, InvariantChecker
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import ResilienceConfig
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import grid_edges
    from openr_tpu.types import PrefixEntry

    sample_every = 2
    victim = "node4"

    def overrides(cfg):
        cfg.watchdog_config.interval_s = 1.0
        cfg.tpu_compute_config.min_device_prefixes = 0  # always device
        cfg.resilience_config = ResilienceConfig(
            shadow_sample_every=sample_every,
            failure_threshold=2,
            probe_backoff_initial_s=0.5,
            probe_backoff_max_s=4.0,
            jitter_pct=0.1,
            seed=seed,
        )

    async def one_run():
        clock = SimClock()
        net = EmulatedNetwork(
            clock, use_tpu_backend=True, config_overrides=overrides
        )
        net.build(grid_edges(3))
        net.start()
        checker = InvariantChecker(net)
        plan = FaultPlan().tpu_corrupt(victim, at=2.0, duration=10.0)
        controller = ChaosController(net, plan, seed=seed)
        await clock.run_for(18.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        gov = net.nodes[victim].decision.backend.governor
        controller.start()
        await clock.run_for(3.0)  # corruption live at t=+2
        rebuilds_to_detect = 0
        for i in range(sample_every):
            net.nodes["node0"].advertise_prefixes(
                [PrefixEntry(f"10.99.{i}.0/24")]
            )
            await clock.run_for(1.5)
            checker.sample()
            if not gov.quarantined:
                continue
            rebuilds_to_detect = i + 1
            break
        detected = gov.quarantined
        checker.check_no_blackholes()  # scalar engine serving, no holes
        await clock.run_for(8.0)  # heal fires at t=+12
        net.nodes["node0"].advertise_prefixes([PrefixEntry("10.99.8.0/24")])
        await clock.run_for(4.0)
        recovered = not gov.quarantined
        await clock.run_for(8.0)
        checker.check_all()
        detail = {
            "detected": detected,
            "rebuilds_to_detect": rebuilds_to_detect,
            "recovered": recovered,
            "shadow_mismatches": gov.num_shadow_mismatches,
            "probes": gov.breaker.num_probes,
            "restores": gov.num_restores,
        }
        dumps = (
            controller.counter_dump(),
            net.nodes[victim].counters.dump("resilience."),
        )
        await controller.stop()
        await net.stop()
        return detail, dumps

    def run(coro):
        loop = asyncio.new_event_loop()
        try:
            return loop.run_until_complete(coro)
        finally:
            loop.close()

    detail_a, dumps_a = run(one_run())
    _detail_b, dumps_b = run(one_run())
    detail_a["deterministic_replay"] = dumps_a == dumps_b
    detail_a["seed"] = seed
    detail_a["shadow_sample_every"] = sample_every
    return detail_a


def resilience_main(seed: Optional[int] = None) -> None:
    """Resilience benchmark (the BENCH_RESILIENCE_r* artifact).

    Part A — shadow-verification overhead on the rebuild p50: one
    256-node LSDB, prefix-churn rebuild ticks through the SAME TpuBackend
    incremental path the daemon runs, measured with the governor's
    sampling off vs every-8th-build.  Sampled builds pay a full scalar
    solve, but they are 1-in-8 tail events, so the p50 (the acceptance
    metric: <= 5%) is expected ~flat — the artifact records the honest
    p50 AND p95 so the tail cost is visible, not hidden.

    Part B — the seeded tpu_corrupt emulation scenario (detection within
    one sample interval, scalar serving with invariants green, probed
    recovery, deterministic replay).  Emits one JSON line."""
    from openr_tpu.ops.platform_env import (
        enable_persistent_compile_cache,
        fallback_to_cpu_if_unreachable,
        honor_cpu_platform_request,
    )

    honor_cpu_platform_request()
    fallback_to_cpu_if_unreachable()
    enable_persistent_compile_cache()

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import ResilienceConfig
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import (
        build_adj_dbs,
        random_connected_edges,
    )
    from openr_tpu.types import PrefixEntry

    # historical defaults (world 11, SDC scenario 7) keep the checked-in
    # rounds reproducible when --seed is omitted
    sdc_seed = 7 if seed is None else seed
    n_nodes, n_links, seed = 256, 512, (11 if seed is None else seed)
    edges = random_connected_edges(n_nodes, n_links, seed=seed)
    ls = LinkState("0", "node0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(n_nodes):
        ps.update_prefix(
            f"node{i}", "0", PrefixEntry(f"10.{i // 256}.{i % 256}.0/24")
        )
    als = {"0": ls}
    churn_prefix = "10.200.0.0/24"

    def measure(sample_every: int):
        backend = TpuBackend(
            SpfSolver("node0"),
            clock=SimClock(),
            resilience=ResilienceConfig(
                shadow_sample_every=sample_every, jitter_pct=0.0
            ),
        )
        backend.build_route_db(als, ps)  # warm-up: compile + first build
        for i in range(2):  # warm the incremental row-selection bucket too
            if i % 2 == 0:
                ps.update_prefix("node3", "0", PrefixEntry(churn_prefix))
            else:
                ps.delete_prefix("node3", "0", churn_prefix)
            backend.build_route_db(als, ps, changed_prefixes={churn_prefix})
        lat = []
        for i in range(RESILIENCE_BUILDS_PER_SIDE):
            # alternate advertise/withdraw of one prefix: a realistic
            # prefix-churn rebuild tick (incremental device path)
            if i % 2 == 0:
                ps.update_prefix("node3", "0", PrefixEntry(churn_prefix))
            else:
                ps.delete_prefix("node3", "0", churn_prefix)
            t0 = time.perf_counter()
            backend.build_route_db(
                als, ps, changed_prefixes={churn_prefix}
            )
            lat.append((time.perf_counter() - t0) * 1000.0)
        # leave the churn prefix withdrawn for the next side
        ps.delete_prefix("node3", "0", churn_prefix)
        lat.sort()
        return lat, backend.governor.num_shadow_checks

    lat_off, _ = measure(0)
    lat_on, shadow_checks = measure(RESILIENCE_SAMPLE_EVERY)

    def pct(lat, q):
        return lat[min(len(lat) - 1, int(len(lat) * q))]

    p50_off, p50_on = pct(lat_off, 0.50), pct(lat_on, 0.50)
    overhead_pct = (p50_on - p50_off) / p50_off * 100.0

    sdc = _resilience_sdc_scenario(seed=sdc_seed)

    doc = {
        "metric": "resilience_shadow_overhead_pct_rebuild_p50",
        "value": round(overhead_pct, 2),
        "unit": "pct",
        "detail": {
            "rebuild_p50_ms_shadow_off": round(p50_off, 3),
            "rebuild_p50_ms_shadow_on": round(p50_on, 3),
            "rebuild_p95_ms_shadow_off": round(pct(lat_off, 0.95), 3),
            "rebuild_p95_ms_shadow_on": round(pct(lat_on, 0.95), 3),
            "rebuild_max_ms_shadow_on": round(lat_on[-1], 3),
            "builds_per_side": RESILIENCE_BUILDS_PER_SIDE,
            "shadow_sample_every": RESILIENCE_SAMPLE_EVERY,
            "shadow_checks_during_run": shadow_checks,
            "sdc_scenario": sdc,
            "world": {
                "nodes": n_nodes,
                "links": n_links,
                "prefixes": n_nodes,
                "topology": "random_connected",
                "seed": seed,
            },
            "mode": (
                "part A: direct TpuBackend incremental rebuild ticks "
                "(wall clock); part B: 9-node grid SimClock emulation "
                "with chaos tpu_corrupt"
            ),
            "env": env_stamp(),
        },
    }
    validate_resilience_bench(doc)
    print(json.dumps(doc))


PIPELINE_DEVICES = (1, 8)
PIPELINE_REBUILDS = 3
PIPELINE_GAP_BOUND_PCT = 10.0


def validate_pipeline_bench(doc: dict) -> None:
    """Schema contract for BENCH_PIPELINE_r*.json — shared by the bench
    emitter and the tier-1 schema gate (tests/test_bench_artifacts).

    The headline value is the UNATTRIBUTED GAP on the grid4096 full
    rebuild: the fraction of measured end-to-end wall time NOT covered
    by a `pipeline.{phase}.ms` sample.  The ISSUE-7 acceptance bound is
    <= 10% — below that, the per-phase table is trustworthy enough to
    baseline the pipelining work against.

    Two artifact eras validate here.  r01 predates the streamed
    pipeline: its dispatch loop ended in ONE blocking device_get
    barrier (no stream_drain/pad_pack at 1 device, busy fractions
    overlap-counted up to 1.5).  From r02 on (detected by a
    ``stream_drain`` sample), the ISSUE-11 contract binds: every shard
    drains as a streamed completion (stream_drain + pad_pack required
    at EVERY device count), ``device_get`` — now just the host copy of
    ready bytes — must no longer be the dominant phase, per-chip busy
    fractions are honest (<= 1, each wait window charged to exactly
    one chip), and a ``delta_round`` must prove the on-device
    delta-extraction path fetches only changed rows."""
    from openr_tpu.tracing.pipeline import (
        DELTA_PHASES,
        DEVICE_GET,
        DEVICE_SELECT,
        PAD_PACK,
        PHASES,
        PROTECTION_PHASES,
        STREAM_DRAIN,
        SWEEP_PHASES,
        WARM_PHASES,
    )

    assert doc["metric"] == "pipeline_attribution_gap_pct_grid4096_rebuild"
    assert doc["unit"] == "pct_of_rebuild_wall"
    assert isinstance(doc["value"], (int, float))
    assert abs(doc["value"]) <= PIPELINE_GAP_BOUND_PCT
    d = doc["detail"]
    rounds = d["rebuild_rounds"]
    assert [r["devices"] for r in rounds] == list(PIPELINE_DEVICES)
    streamed = any(
        STREAM_DRAIN in r["phases_ms"] for r in rounds
    )
    for r in rounds:
        assert r["rebuilds"] >= 2
        assert r["wall_ms"] > 0
        assert abs(r["gap_pct"]) <= PIPELINE_GAP_BOUND_PCT
        assert r["attributed_ms"] > 0
        phases = r["phases_ms"]
        assert set(phases) <= set(PHASES)
        # a full rebuild exercises the whole lifecycle: every phase
        # must have recorded real time (delta_extract rides the diff).
        # warm_plan/warm_repair fire only on warm-start rebuilds
        # (BENCH_WARMSTART), device_select only on delta builds, the
        # sweep phases only in the capacity-sweep orchestrator, and the
        # protection phases only with a live protection tier — never on
        # the cold lifecycle these rounds measure.
        required = (
            set(PHASES)
            - set(WARM_PHASES)
            - set(DELTA_PHASES)
            - set(SWEEP_PHASES)
            - set(PROTECTION_PHASES)
        )
        if not streamed:
            required.discard(STREAM_DRAIN)
            if r["devices"] == 1:
                required.discard(PAD_PACK)
        for phase in sorted(required):
            assert phases.get(phase, 0.0) > 0.0, f"phase {phase} empty"
        if streamed:
            # the dispatch-sync wall is dead: the blocking fetch
            # barrier may no longer dominate the phase table
            assert phases[DEVICE_GET] < max(phases.values()), (
                "device_get is still the dominant phase"
            )
        assert 0.0 <= r["host_share_pct"] <= 100.0
        assert abs(
            r["host_share_pct"] + r["device_share_pct"] - 100.0
        ) < 0.5
        busy = r["per_chip_busy"]
        assert len(busy) == r["devices"]
        busy_bound = 1.05 if streamed else 1.5  # honest vs overlap-counted
        for row in busy.values():
            assert row["busy_ms"] >= 0.0
            assert 0.0 <= row["busy_fraction"] <= busy_bound
    if streamed:
        dr = d["delta_round"]
        assert dr["rebuilds"] >= 2 and dr["wall_ms"] > 0
        assert dr["delta_builds"] == dr["rebuilds"]
        assert dr["rows_fetched"] >= 1
        # the DeltaPath claim: a small perturbation's rebuild moves
        # only changed rows over the host boundary
        assert dr["rows_skipped"] > dr["rows_fetched"]
        assert dr["phases_ms"].get(DEVICE_SELECT, 0.0) > 0.0
        assert (
            dr["wall_ms"] / dr["rebuilds"]
            < rounds[0]["wall_ms"] / rounds[0]["rebuilds"]
        )
    for key in ("fleet_round", "whatif_round"):
        eng = d[key]
        assert eng["devices"] == PIPELINE_DEVICES[-1]
        assert eng["wall_ms"] > 0
        assert eng["phases_ms"]
        assert set(eng["phases_ms"]) <= set(PHASES)
        assert eng["pool_dispatches"] >= eng["devices"]
    for key in ("world", "env", "mode"):
        assert key in d, key
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"
    assert d["env"]["device_count"] >= 8


def pipeline_main(seed: Optional[int] = None) -> None:
    """Pipeline-attribution benchmark (BENCH_PIPELINE_r*): phase-level
    accounting of the grid4096 full rebuild at 1 and 8 forced host
    devices, plus fleet and what-if rounds over the 8-chip pool.

    Methodology.  Each rebuild round drives PIPELINE_REBUILDS full
    device builds (a link-metric flip between builds bumps the
    topology seq, so every build re-encodes, re-solves the SPF tables
    and re-runs selection — the true cold-rebuild lifecycle, not a
    cache replay) and diffs each result against the previous RouteDb
    (the delta_extract tail).  Wall time is measured around exactly
    that window; attribution is the delta of every
    `pipeline.{phase}.ms` histogram over the same window.  The
    headline is the worst-round unattributed gap — the ISSUE-7
    acceptance demands the phase table explain >= 90% of the wall.
    Per-chip busy fractions come from the probe's busy ledger
    (committed per-shard dispatch time + the blocking drain window
    each chip had work outstanding in; on forced HOST devices chips
    share physical cores, so fractions measure dispatch-plane
    structure, not silicon occupancy).  The governor is disabled for
    the measured rounds: shadow verification is a resilience cost,
    priced separately in BENCH_RESILIENCE."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    from openr_tpu.ops.platform_env import (
        enable_persistent_compile_cache,
        fallback_to_cpu_if_unreachable,
        honor_cpu_platform_request,
    )

    honor_cpu_platform_request()
    fallback_to_cpu_if_unreachable()
    enable_persistent_compile_cache()

    from openr_tpu.common.runtime import CounterMap, WallClock
    from openr_tpu.config import ParallelConfig, ResilienceConfig
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.fleet import FleetRibEngine
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.decision.whatif_api import MultiAreaWhatIfEngine
    from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
    from openr_tpu.tracing import pipeline
    from openr_tpu.types import PrefixEntry

    side = 64  # grid4096: the ROADMAP's canonical scale point
    edges = grid_edges(side)
    adj_dbs = build_adj_dbs(edges)
    ls = LinkState("0")
    for db in adj_dbs.values():
        ls.update_adjacency_database(db)
    n_nodes = side * side
    ps = PrefixState()
    for i in range(n_nodes):
        ps.update_prefix(
            f"node{i}",
            "0",
            PrefixEntry(f"10.{(i >> 8) & 0xFF}.{i & 0xFF}.0/24"),
        )
    als = {"0": ls}
    # the measured lifecycle is seed-invariant (full rebuilds); the
    # seed only picks WHICH adjacency flips between builds
    victim = (
        "node0"
        if seed is None
        else f"node{np.random.default_rng(seed).integers(n_nodes)}"
    )
    flip_db = adj_dbs[victim]

    def flip_topology(step: int) -> None:
        # alternate one adjacency metric: a real topology change, so
        # the encode cache and the device SPF tables must rebuild
        for adj in flip_db.adjacencies:
            adj.metric = 1 + (step % 2)
        ls.update_adjacency_database(flip_db)

    def fresh_backend(num_devices: int) -> TpuBackend:
        return TpuBackend(
            SpfSolver("node0"),
            min_device_prefixes=0,  # always device
            clock=WallClock(),
            counters=CounterMap(),
            resilience=ResilienceConfig(enabled=False),
            parallel=ParallelConfig(
                max_devices=num_devices, min_shard_rows=0
            ),
        )

    def phase_totals(counters: CounterMap) -> dict:
        out = {}
        for phase in pipeline.PHASES:
            h = counters.histogram(pipeline.hist_key(phase))
            if h is not None:
                out[phase] = h.total
        return out

    def rebuild_round(num_devices: int) -> dict:
        backend = fresh_backend(num_devices)
        probe = backend.probe
        counters = probe.counters
        flip_topology(0)
        prev = backend.build_route_db(als, ps, force_full=True)  # warm
        t0_phase = phase_totals(counters)
        t0_busy = probe.busy_snapshot()
        walls = []
        t_round = time.perf_counter()
        for step in range(1, PIPELINE_REBUILDS + 1):
            flip_topology(step)
            t0 = time.perf_counter()
            db = backend.build_route_db(als, ps, force_full=True)
            with probe.phase(pipeline.DELTA_EXTRACT):
                update = prev.calculate_update(db)
            walls.append((time.perf_counter() - t0) * 1000.0)
            assert not update.empty()  # the metric flip moved routes
            prev = db
        wall_ms = (time.perf_counter() - t_round) * 1000.0
        t1_phase = phase_totals(counters)
        t1_busy = probe.busy_snapshot()
        phases_ms = {
            k: round(t1_phase.get(k, 0.0) - t0_phase.get(k, 0.0), 3)
            for k in pipeline.PHASES
            if t1_phase.get(k, 0.0) - t0_phase.get(k, 0.0) > 0.0
        }
        attributed = sum(phases_ms.values())
        host_ms = sum(
            phases_ms.get(p, 0.0) for p in pipeline.HOST_PHASES
        )
        device_ms = sum(
            phases_ms.get(p, 0.0) for p in pipeline.DEVICE_PHASES
        )
        per_chip = {}
        for dev in range(num_devices):
            busy = t1_busy.get(dev, 0.0) - t0_busy.get(dev, 0.0)
            per_chip[f"dev{dev}"] = {
                "busy_ms": round(busy, 3),
                "busy_fraction": round(busy / wall_ms, 4),
            }
        return {
            "devices": num_devices,
            "rebuilds": PIPELINE_REBUILDS,
            "wall_ms": round(wall_ms, 3),
            "rebuild_ms_each": [round(w, 3) for w in walls],
            "attributed_ms": round(attributed, 3),
            "gap_pct": round((wall_ms - attributed) / wall_ms * 100.0, 3),
            "phases_ms": phases_ms,
            "host_ms": round(host_ms, 3),
            "device_ms": round(device_ms, 3),
            "host_share_pct": round(host_ms / attributed * 100.0, 2),
            "device_share_pct": round(device_ms / attributed * 100.0, 2),
            "per_chip_busy": per_chip,
            "routes": len(prev.unicast_routes),
        }

    def engine_round(kind: str) -> dict:
        # fleet/what-if attribution rides a 256-node world: the point
        # is phase coverage of the pooled dispatch paths, and a
        # 4096-root fleet batch (4096 SPF solves) would turn the bench
        # into a soak on host devices
        eside = 16
        e_edges = grid_edges(eside)
        e_ls = LinkState("0")
        for db in build_adj_dbs(e_edges).values():
            e_ls.update_adjacency_database(db)
        e_ps = PrefixState()
        for i in range(eside * eside):
            e_ps.update_prefix(
                f"node{i}", "0", PrefixEntry(f"10.77.{i % 256}.0/24")
            )
        e_als = {"0": e_ls}
        backend = fresh_backend(PIPELINE_DEVICES[-1])
        probe = backend.probe
        pool = backend.dispatch_pool()
        assert pool is not None and pool.size == PIPELINE_DEVICES[-1]
        solver = SpfSolver("node0")
        if kind == "fleet":
            eng = FleetRibEngine(solver, pool=pool, probe=probe)

            def run_once(seq):
                return eng.fleet_summary(e_als, e_ps, seq)
        else:
            eng = MultiAreaWhatIfEngine(solver, pool=pool, probe=probe)
            failures = [
                (f"node{i}", f"node{i + 1}") for i in range(0, 48)
                if (i + 1) % eside  # same-row neighbors only
            ]

            def run_once(seq):
                return eng.run(failures, e_als, e_ps, seq)

        run_once(1)  # warm compile (cold kernels)
        run_once(2)  # warm compile (generation-delta kernels)
        t0_phase = phase_totals(probe.counters)
        t0 = time.perf_counter()
        run_once(3)  # fresh generation: tables rebuilt, real dispatches
        wall_ms = (time.perf_counter() - t0) * 1000.0
        t1_phase = phase_totals(probe.counters)
        phases_ms = {
            k: round(t1_phase.get(k, 0.0) - t0_phase.get(k, 0.0), 3)
            for k in pipeline.PHASES
            if t1_phase.get(k, 0.0) - t0_phase.get(k, 0.0) > 0.0
        }
        return {
            "devices": PIPELINE_DEVICES[-1],
            "world_nodes": eside * eside,
            "wall_ms": round(wall_ms, 3),
            "attributed_ms": round(sum(phases_ms.values()), 3),
            "phases_ms": phases_ms,
            "pool_dispatches": int(sum(pool.num_dispatches)),
        }

    def delta_round() -> dict:
        """The on-device delta-extraction path (ISSUE 11): a FAR-corner
        victim perturbs routes to a handful of prefixes; consecutive
        full rebuilds with an exact (empty) prefix-churn delta then run
        the fused select+diff kernel and move only the changed rows
        over the host boundary (device_select gather), patching the
        rest through object-identically."""
        backend = fresh_backend(1)
        counters = backend.probe.counters
        far = f"node{n_nodes - 1}"
        far_db = adj_dbs[far]

        def flip_far(step: int) -> None:
            for a in far_db.adjacencies:
                a.metric = 1 + (step % 2)
            ls.update_adjacency_database(far_db)

        flip_far(0)
        prev = backend.build_route_db(
            als, ps, changed_prefixes=set(), force_full=True
        )
        # one unmeasured delta build compiles the fused select+diff and
        # gather kernels (the rebuild rounds warm the non-delta shapes
        # the same way via their own warm-up build)
        flip_far(1)
        prev = backend.build_route_db(
            als, ps, changed_prefixes=set(), force_full=True
        )
        assert backend.num_delta_builds == 1
        backend.take_last_changed_prefixes()
        backend.num_delta_builds = 0
        backend.num_delta_rows_fetched = 0
        backend.num_delta_rows_skipped = 0
        t0_phase = phase_totals(counters)
        walls = []
        t_round = time.perf_counter()
        for step in range(2, PIPELINE_REBUILDS + 2):
            flip_far(step)
            t0 = time.perf_counter()
            db = backend.build_route_db(
                als, ps, changed_prefixes=set(), force_full=True
            )
            changed = backend.take_last_changed_prefixes()
            with backend.probe.phase(pipeline.DELTA_EXTRACT):
                update = prev.calculate_update(db)
            walls.append((time.perf_counter() - t0) * 1000.0)
            assert not update.empty() and changed
            prev = db
        wall_ms = (time.perf_counter() - t_round) * 1000.0
        t1_phase = phase_totals(counters)
        phases_ms = {
            k: round(t1_phase.get(k, 0.0) - t0_phase.get(k, 0.0), 3)
            for k in pipeline.PHASES
            if t1_phase.get(k, 0.0) - t0_phase.get(k, 0.0) > 0.0
        }
        return {
            "devices": 1,
            "rebuilds": PIPELINE_REBUILDS,
            "victim": far,
            "wall_ms": round(wall_ms, 3),
            "rebuild_ms_each": [round(w, 3) for w in walls],
            "delta_builds": backend.num_delta_builds,
            "rows_fetched": backend.num_delta_rows_fetched,
            "rows_skipped": backend.num_delta_rows_skipped,
            "phases_ms": phases_ms,
        }

    rounds = [rebuild_round(n) for n in PIPELINE_DEVICES]
    for r in rounds:
        print(
            f"# {r['devices']} device(s): wall {r['wall_ms']}ms, "
            f"attributed {r['attributed_ms']}ms "
            f"(gap {r['gap_pct']}%), host {r['host_share_pct']}%",
            file=sys.stderr,
        )
    dround = delta_round()
    print(
        f"# delta round: wall {dround['wall_ms']}ms, rows fetched "
        f"{dround['rows_fetched']} vs skipped {dround['rows_skipped']}",
        file=sys.stderr,
    )
    fleet_round = engine_round("fleet")
    whatif_round = engine_round("whatif")
    worst_gap = max((abs(r["gap_pct"]) for r in rounds), key=abs)
    doc = {
        "metric": "pipeline_attribution_gap_pct_grid4096_rebuild",
        "value": worst_gap,
        "unit": "pct_of_rebuild_wall",
        "detail": {
            "rebuild_rounds": rounds,
            "delta_round": dround,
            "fleet_round": fleet_round,
            "whatif_round": whatif_round,
            "world": {
                "nodes": n_nodes,
                "topology": f"grid{side}x{side}",
                "prefixes": n_nodes,
                "engine_world_nodes": 256,
            },
            "mode": (
                "emulate (in-process LSDB, WallClock probe, 8 forced "
                "virtual host devices sharing physical cores — per-chip "
                "busy fractions measure dispatch-plane structure, not "
                "silicon occupancy; streamed drains charge each wait "
                "window to the completing chip only, so fractions are "
                "honest under overlap)"
            ),
            "gap_definition": (
                "wall_ms measured around build_route_db(force_full) + "
                "RouteDb diff; attributed_ms = delta of every "
                "pipeline.{phase}.ms histogram total over the same "
                "window; gap = (wall - attributed) / wall"
            ),
            "env": env_stamp(),
        },
    }
    validate_pipeline_bench(doc)
    print(json.dumps(doc))


SERVING_CONCURRENCY = (1, 8, 64, 512)


def validate_serving_bench(doc: dict) -> None:
    """Schema contract for BENCH_SERVING_r*.json — shared by the bench
    emitter and the tier-1 smoke test so the artifact can never drift
    from what the test validates."""
    assert doc["metric"] == "serving_route_db_queries_per_sec_64_clients"
    assert doc["unit"] == "queries/s"
    assert doc["value"] > 0
    assert doc["vs_baseline"] > 0
    detail = doc["detail"]
    rounds = detail["rounds"]
    assert [r["clients"] for r in rounds] == list(SERVING_CONCURRENCY)
    for r in rounds:
        assert r["waves"] >= 2 and r["distinct_queries"] >= 1
        for side in ("steady", "cold", "unbatched"):
            res = r[side]
            assert res["qps"] > 0
            assert 0 <= res["p50_ms"] <= res["p99_ms"]
            assert res["queries"] >= r["clients"]
        assert r["speedup_steady"] > 0 and r["speedup_cold"] > 0
        assert 0 <= r["steady"]["cache_hit_ratio"] <= 1
        assert r["steady"]["batches"] >= 1
    wf = detail["whatif_coalescing_64"]
    assert wf["batched_ms"] > 0 and wf["unbatched_device_ms"] > 0
    for key in ("world", "serving_config", "env", "mode"):
        assert key in detail, key
    for key in ("platform", "jax", "device_count"):
        assert key in detail["env"], f"env.{key}"
    assert detail["env"]["device_count"] >= 1


def serving_main(seed: Optional[int] = None) -> None:
    """Serving-plane benchmark (the BENCH_SERVING_r* artifact): the
    micro-batched/cached serving path vs the unbatched path — one fresh
    scalar SpfSolver pass per call, the reference's getRouteDbComputed
    behavior (Decision.cpp:342) — at 1/8/64/512 concurrent clients
    against one in-process emulated LSDB.  Emits one JSON line.

    Methodology.  Each concurrency round runs W waves of K concurrent
    route_db clients re-sweeping a closed query set (client i queries
    vantage i mod min(K, |V|)) against ONE serving Decision at a fixed
    LSDB generation — the steady state between routing changes (query
    rate >> LSDB churn in the millions-of-users regime).  Two batched
    measurements per round keep the claim honest:

    * ``steady`` — the serving plane as deployed: result cache ON.
      Wave 1 pays the fleet batch solve + decodes; later waves hit the
      content-addressed cache.  This is the headline (value /
      vs_baseline at 64 clients).
    * ``cold`` — cache CLEARED between waves: isolates micro-batching +
      the engines' per-generation table reuse with the result cache
      handicapped off.

    The unbatched side pays one fresh scalar build per request,
    strictly sequential, no reuse of any kind — exactly what the
    reference does per ctrl call (it has no result cache).  jit compile
    happens in an excluded warm-up; latencies are per-request
    (submit→answer).  A what-if coalescing measurement (64 distinct
    single-link queries: one coalesced engine sweep vs 64 per-query
    dispatches, device and native engines) rides in the detail."""
    import asyncio

    from openr_tpu.ops.platform_env import (
        enable_persistent_compile_cache,
        fallback_to_cpu_if_unreachable,
        honor_cpu_platform_request,
    )

    honor_cpu_platform_request()
    fallback_to_cpu_if_unreachable()
    enable_persistent_compile_cache()

    from openr_tpu.common.runtime import WallClock
    from openr_tpu.config import DecisionConfig, ServingConfig
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.decision import Decision
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import (
        build_adj_dbs,
        random_connected_edges,
    )
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.serving.service import QueryService
    from openr_tpu.types import PrefixEntry

    n_nodes, n_links, seed = 256, 512, (11 if seed is None else seed)
    min_queries = 640  # per round, so the one-time solve amortizes
    edges = random_connected_edges(n_nodes, n_links, seed=seed)
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(n_nodes):
        ps.update_prefix(
            f"node{i}", "0", PrefixEntry(f"10.{i // 256}.{i % 256}.0/24")
        )
    als = {"0": ls}
    serving_cfg = ServingConfig(max_batch=64, max_wait_ms=2)

    def fresh_decision() -> Decision:
        solver = SpfSolver("node0")
        d = Decision(
            "node0",
            WallClock(),
            DecisionConfig(),
            ReplicateQueue("routes"),
            backend=TpuBackend(solver),
            solver=solver,
        )
        d.area_link_states = als
        d.prefix_state = ps
        d._change_seq = 1
        return d

    def unbatched_round(k: int, waves: int, distinct: int):
        """The reference path: one fresh scalar vantage solve + wire
        serialization per call, strictly sequential, no reuse."""
        lat = []
        t0 = time.perf_counter()
        for _w in range(waves):
            for i in range(k):
                node = f"node{i % distinct}"
                t1 = time.perf_counter()
                SpfSolver(node).build_route_db(als, ps).to_route_database(
                    node
                ).to_wire()
                lat.append((time.perf_counter() - t1) * 1000.0)
        wall = time.perf_counter() - t0
        return wall, lat

    async def batched_round(k: int, waves: int, distinct: int, cold: bool):
        clock = WallClock()
        d = fresh_decision()
        sv = QueryService(
            "node0", clock, serving_cfg, d, counters=d.counters
        )
        sv.start()
        lat = []

        async def client(i: int):
            t1 = time.perf_counter()
            await sv.submit(
                "route_db",
                {"node": f"node{i % distinct}"},
                client_id=f"client{i}",
            )
            lat.append((time.perf_counter() - t1) * 1000.0)

        t0 = time.perf_counter()
        for _w in range(waves):
            await asyncio.gather(*[client(i) for i in range(k)])
            if cold:
                sv.cache.clear()
        wall = time.perf_counter() - t0
        total = k * waves
        stats = dict(
            batches=sv.num_batches,
            batch_solves=sv.num_batch_solves,
            dedup_hits=sv.num_dedup_hits,
            cache_hit_ratio=round(
                d.counters.get("serving.cache.hits") / total, 3
            ),
        )
        await sv.stop()
        return wall, lat, stats

    def pcts(lat):
        srt = sorted(lat)
        return (
            srt[len(srt) // 2],
            srt[min(len(srt) - 1, int(len(srt) * 0.99))],
        )

    def whatif_coalescing_detail():
        """64 distinct single-link what-ifs: one coalesced sweep (what
        the serving batcher dispatches) vs 64 per-query dispatches on
        the device engine, with the native engine's per-query cost
        reported for transparency (the repo's auto engine choice at
        small scale)."""
        pairs = [(a, b) for a, b, _m in edges][:64]
        d = fresh_decision()
        d.backend.auto_dispatch_rt_ms = 0.0  # pin the device engine
        d.get_link_failure_whatif([list(pairs[0])])  # warm compile
        d.get_link_failure_whatif([list(p) for p in pairs])
        t0 = time.perf_counter()
        for p in pairs:
            d.get_link_failure_whatif([list(p)])
        un_ms = (time.perf_counter() - t0) * 1000.0
        t0 = time.perf_counter()
        d.get_link_failure_whatif([list(p) for p in pairs])
        b_ms = (time.perf_counter() - t0) * 1000.0
        dn = fresh_decision()
        dn.backend.auto_dispatch_rt_ms = 1000.0  # pin the native engine
        dn.get_link_failure_whatif([list(pairs[0])])
        t0 = time.perf_counter()
        for p in pairs:
            dn.get_link_failure_whatif([list(p)])
        nat_ms = (time.perf_counter() - t0) * 1000.0
        return {
            "queries": 64,
            "batched_ms": round(b_ms, 1),
            "unbatched_device_ms": round(un_ms, 1),
            "unbatched_native_ms": round(nat_ms, 1),
            "speedup_vs_device": round(un_ms / b_ms, 2),
            "speedup_vs_native": round(nat_ms / b_ms, 2),
        }

    def side(wall, lat, total, extra=None):
        p50, p99 = pcts(lat)
        out = {
            "qps": round(total / wall, 1),
            "p50_ms": round(p50, 2),
            "p99_ms": round(p99, 2),
            "wall_s": round(wall, 4),
            "queries": total,
        }
        if extra:
            out.update(extra)
        return out

    async def run_all():
        await batched_round(8, 2, 8, cold=True)  # compile warm-up
        unbatched_round(2, 1, 2)
        rounds = []
        for k in SERVING_CONCURRENCY:
            waves = max(2, -(-min_queries // k))  # ceil, >= 2 waves
            distinct = min(k, n_nodes)
            total = k * waves
            uw, ulat = unbatched_round(k, waves, distinct)
            sw, slat, sstats = await batched_round(
                k, waves, distinct, cold=False
            )
            cw, clat, cstats = await batched_round(
                k, waves, distinct, cold=True
            )
            rounds.append(
                {
                    "clients": k,
                    "waves": waves,
                    "distinct_queries": distinct,
                    "steady": side(sw, slat, total, sstats),
                    "cold": side(cw, clat, total, cstats),
                    "unbatched": side(uw, ulat, total),
                    "speedup_steady": round(uw / sw, 2),
                    "speedup_cold": round(uw / cw, 2),
                }
            )
        return rounds

    rounds = asyncio.new_event_loop().run_until_complete(run_all())
    whatif_detail = whatif_coalescing_detail()
    r64 = next(r for r in rounds if r["clients"] == 64)
    doc = {
        "metric": "serving_route_db_queries_per_sec_64_clients",
        "value": r64["steady"]["qps"],
        "unit": "queries/s",
        "vs_baseline": r64["speedup_steady"],
        "detail": {
            "rounds": rounds,
            "whatif_coalescing_64": whatif_detail,
            "world": {
                "nodes": n_nodes,
                "links": n_links,
                "prefixes": n_nodes,
                "topology": "random_connected",
                "seed": seed,
            },
            "serving_config": {
                "max_batch": serving_cfg.max_batch,
                "max_wait_ms": serving_cfg.max_wait_ms,
            },
            "mode": "emulate (in-process LSDB, WallClock serving actor)",
            "steady_definition": (
                "serving plane as deployed (result cache ON), W waves "
                "of K clients re-sweeping a closed query set at one "
                "LSDB generation"
            ),
            "cold_definition": (
                "result cache cleared between waves: micro-batching + "
                "engine table reuse only"
            ),
            "unbatched_definition": (
                "one fresh scalar SpfSolver vantage build per request, "
                "sequential (the reference getRouteDbComputed path, "
                "Decision.cpp:342; no cache of any kind)"
            ),
            "env": env_stamp(),
        },
    }
    validate_serving_bench(doc)
    print(json.dumps(doc))


SERVING_MULTICHIP_DEVICES = (1, 2, 4, 8)


def validate_multichip_serving_bench(doc: dict) -> None:
    """Schema contract for BENCH_MULTICHIP_SERVING_r*.json — shared by
    the bench emitter and the tier-1 smoke test.  The headline value is
    serving throughput with the full 8-chip pool; the degraded round
    proves a 7-of-8 pool (one chip quarantined) KEEPS serving through
    the device engines (`serving_stayed_available`)."""
    assert doc["metric"] == "multichip_serving_route_db_qps_8dev"
    assert doc["unit"] == "queries/s"
    assert doc["value"] > 0
    assert doc["vs_baseline"] > 0
    d = doc["detail"]
    rounds = d["rounds"]
    assert [r["devices"] for r in rounds] == list(SERVING_MULTICHIP_DEVICES)
    for r in rounds:
        assert r["qps"] > 0
        assert 0 <= r["p50_ms"] <= r["p99_ms"]
        assert r["queries"] >= 64
        assert r["healthy_devices"] == r["devices"]
        # multi-chip rounds must actually dispatch over the pool
        assert r["pool_dispatches"] >= (1 if r["devices"] > 1 else 0)
    deg = d["degraded_7of8"]
    assert deg["healthy_devices"] == 7
    assert 0 <= deg["quarantined_device"] < 8
    assert deg["qps"] > 0
    assert deg["serving_stayed_available"] is True
    assert deg["device_failed"] is False
    for key in ("world", "env", "mode"):
        assert key in d, key
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"
    assert d["env"]["device_count"] >= 8


def multichip_serving_main(seed: Optional[int] = None) -> None:
    """Multi-chip serving benchmark (BENCH_MULTICHIP_SERVING_r*): fleet
    route_db serving throughput through QueryService at a 1/2/4/8-chip
    DevicePool, plus a 7-of-8 degraded round with one chip quarantined
    by the health governor — proving the serving plane keeps answering
    on the survivors with `Decision.device_available()` still true.

    Methodology: one in-process LSDB (random connected graph), a fresh
    Decision + QueryService per round, W waves of K=64 concurrent
    route_db clients over distinct vantages with the RESULT CACHE
    CLEARED between waves — each wave pays real engine work (one pooled
    fleet batch solve on the first wave, per-vantage decodes after), so
    the number measures the compute path, not cache hits.  On forced
    virtual host devices (this artifact's environment) all chips share
    the physical cores, so scaling is STRUCTURAL (shard routing,
    re-packing, health governance) rather than physical — the round
    shape is what transfers to a real mesh."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import asyncio

    from openr_tpu.ops.platform_env import (
        enable_persistent_compile_cache,
        fallback_to_cpu_if_unreachable,
        honor_cpu_platform_request,
    )

    honor_cpu_platform_request()
    fallback_to_cpu_if_unreachable()
    enable_persistent_compile_cache()

    from openr_tpu.common.runtime import WallClock
    from openr_tpu.config import (
        DecisionConfig,
        ParallelConfig,
        ServingConfig,
    )
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.decision import Decision
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import (
        build_adj_dbs,
        random_connected_edges,
    )
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.serving.service import QueryService
    from openr_tpu.types import PrefixEntry

    n_nodes, n_links, seed = 128, 256, (11 if seed is None else seed)
    clients, waves = 64, 3
    edges = random_connected_edges(n_nodes, n_links, seed=seed)
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(n_nodes):
        ps.update_prefix(
            f"node{i}", "0", PrefixEntry(f"10.{i // 256}.{i % 256}.0/24")
        )
    als = {"0": ls}
    serving_cfg = ServingConfig(max_batch=64, max_wait_ms=2)

    def fresh_decision(num_devices: int) -> Decision:
        solver = SpfSolver("node0")
        d = Decision(
            "node0",
            WallClock(),
            DecisionConfig(),
            ReplicateQueue("routes"),
            backend=TpuBackend(
                solver,
                parallel=ParallelConfig(
                    max_devices=num_devices, min_shard_rows=0
                ),
            ),
            solver=solver,
        )
        d.area_link_states = als
        d.prefix_state = ps
        d._change_seq = 1
        return d

    async def serve_round(d: Decision):
        clock = WallClock()
        sv = QueryService(
            "node0", clock, serving_cfg, d, counters=d.counters
        )
        sv.start()
        lat = []

        async def client(i: int):
            t1 = time.perf_counter()
            await sv.submit(
                "route_db",
                {"node": f"node{i % n_nodes}"},
                client_id=f"client{i}",
            )
            lat.append((time.perf_counter() - t1) * 1000.0)

        t0 = time.perf_counter()
        for _w in range(waves):
            await asyncio.gather(*[client(i) for i in range(clients)])
            # advance the computed-result generation so the NEXT wave
            # pays a fresh pooled fleet batch solve — the number must
            # measure the compute path (pool-sharded solve + decodes),
            # not the result cache or the engine's per-generation
            # table cache
            d._change_seq += 1
            sv.cache.clear()
        wall = time.perf_counter() - t0
        await sv.stop()
        total = clients * waves
        srt = sorted(lat)
        return {
            "qps": round(total / wall, 1),
            "p50_ms": round(srt[len(srt) // 2], 2),
            "p99_ms": round(srt[min(len(srt) - 1, int(len(srt) * 0.99))], 2),
            "wall_s": round(wall, 4),
            "queries": total,
        }

    loop = asyncio.new_event_loop()

    def run_round(num_devices: int, quarantine=None):
        d = fresh_decision(num_devices)
        gov = d.backend.governor
        if quarantine is not None:
            gov.force_quarantine_device(quarantine, reason="bench")
        # warm compile OUTSIDE the measured window
        loop.run_until_complete(serve_round(d))
        fleet = d._fleet_engine
        dispatch_before = fleet.num_pool_dispatches if fleet else 0
        res = loop.run_until_complete(serve_round(d))
        fleet = d._fleet_engine
        pool = d.backend.pool
        res.update(
            {
                "healthy_devices": pool.num_healthy,
                "pool_dispatches": (
                    (fleet.num_pool_dispatches - dispatch_before)
                    if fleet
                    else 0
                ),
                "device_available": d.device_available(),
            }
        )
        return res

    rounds = []
    for n in SERVING_MULTICHIP_DEVICES:
        r = run_round(n)
        r["devices"] = n
        rounds.append(r)
        print(
            f"# {n} device(s): {r['qps']} q/s p50={r['p50_ms']}ms",
            file=sys.stderr,
        )
    bad_chip = 3
    deg = run_round(8, quarantine=bad_chip)
    deg.update(
        {
            "quarantined_device": bad_chip,
            "serving_stayed_available": deg.pop("device_available"),
            "device_failed": False,
        }
    )
    print(
        f"# 7-of-8 degraded: {deg['qps']} q/s (chip {bad_chip} "
        "quarantined)",
        file=sys.stderr,
    )

    r8 = rounds[-1]
    doc = {
        "metric": "multichip_serving_route_db_qps_8dev",
        "value": r8["qps"],
        "unit": "queries/s",
        "vs_baseline": round(r8["qps"] / rounds[0]["qps"], 2),
        "detail": {
            "rounds": rounds,
            "degraded_7of8": deg,
            "clients": clients,
            "waves": waves,
            "world": {
                "nodes": n_nodes,
                "links": n_links,
                "prefixes": n_nodes,
                "topology": "random_connected",
                "seed": seed,
            },
            "mode": (
                "emulate (in-process LSDB, WallClock serving actor, 8 "
                "forced virtual host devices sharing physical cores — "
                "scaling is structural, not physical)"
            ),
            "degraded_definition": (
                "chip 3 hard-quarantined via the health governor "
                "before the round: fleet chunks re-pack onto the 7 "
                "survivors, Decision.device_available() stays true, "
                "serving keeps answering through the device engines"
            ),
            "env": env_stamp(),
        },
    }
    validate_multichip_serving_bench(doc)
    print(json.dumps(doc))


HEALTH_OVERHEAD_BOUND_PCT = 2.0
HEALTH_FLEET_NODES = 9
HEALTH_SEEDS = (7, 11, 13)
HEALTH_FAULT_FAMILIES = ("partition", "tpu_corrupt", "fib_burst", "actor_kill")


def validate_health_bench(doc: dict) -> None:
    """Schema contract for BENCH_HEALTH_r*.json — shared by the bench
    emitter and the tier-1 smoke test (tests/test_health_bench_schema).
    The headline is the fleet-health aggregator's sweep overhead on the
    serving p50 (acceptance bound <= 2%); the detail records the
    fault-injection -> alert detection-latency distribution per fault
    family over a seeded 9-node sweep."""
    assert doc["metric"] == "health_sweep_overhead_pct_serving_p50"
    assert doc["unit"] == "pct"
    assert isinstance(doc["value"], (int, float))
    assert doc["value"] <= HEALTH_OVERHEAD_BOUND_PCT, (
        "aggregator sweep overhead must stay <= 2% on serving p50"
    )
    d = doc["detail"]
    assert d["serving_p50_ms_health_off"] > 0
    assert d["serving_p50_ms_health_on"] > 0
    assert d["serving_p99_ms_health_on"] >= d["serving_p50_ms_health_on"]
    assert d["sweeps_during_run"] >= 10
    assert d["fleet_nodes"] == HEALTH_FLEET_NODES
    assert d["queries_per_sweep"] <= 64, (
        "the measured cadence must be far more aggressive than prod"
    )
    det = d["detection"]
    assert set(det) == set(HEALTH_FAULT_FAMILIES)
    for family, row in det.items():
        assert row["samples"] >= len(HEALTH_SEEDS), family
        assert row["detected"] == row["samples"], (
            f"{family}: every seeded injection must be detected"
        )
        assert 0.0 <= row["p50_ms"] <= row["max_ms"], family
        assert row["alert"], family
        assert row["max_sweeps"] >= 1, family
    assert d["deterministic_replay"] is True
    for key in ("env", "mode"):
        assert key in d, key
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"


def _health_detection_sweep(seeds=HEALTH_SEEDS) -> dict:
    """Part B: for each fault family, a seeded 9-node SimClock emulation
    measuring fault-injection -> first-alert latency (virtual ms) at a
    500ms sweep cadence, across HEALTH_SEEDS.  The partition family is
    additionally replayed to assert byte-identical alert logs."""
    import asyncio
    import json as _json

    from openr_tpu.chaos import ChaosController, FaultPlan, Supervisor
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import ParallelConfig, ResilienceConfig
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import grid_edges
    from openr_tpu.types import PrefixEntry

    SWEEP_S = 0.5
    FAULT_AT = 2.0

    def overrides(cfg, tpu=False):
        hc = cfg.health_config
        hc.sweep_interval_s = SWEEP_S
        hc.skew_min_generations = 2
        hc.skew_hold_s = 2.0
        cfg.watchdog_config.interval_s = 1.0
        if tpu:
            cfg.tpu_compute_config.min_device_prefixes = 0
            cfg.parallel_config = ParallelConfig(min_shard_rows=0)
            cfg.resilience_config = ResilienceConfig(
                shadow_sample_every=1,
                failure_threshold=2,
                probe_backoff_initial_s=0.5,
                probe_backoff_max_s=4.0,
                jitter_pct=0.1,
                seed=7,
            )

    async def one_family(family: str, seed: int):
        clock = SimClock()
        tpu = family == "tpu_corrupt"
        net = EmulatedNetwork(
            clock,
            use_tpu_backend=tpu,
            config_overrides=lambda cfg: overrides(cfg, tpu=tpu),
        )
        net.build(grid_edges(3))
        net.start()
        supervisor = None
        if family == "actor_kill":
            supervisor = Supervisor(
                clock, initial_backoff_s=0.25, max_backoff_s=5.0
            )
            supervisor.start()
            for name, node in net.nodes.items():
                supervisor.supervise(name, node, net.restart_node)
        await clock.run_for(18.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        if tpu:
            net.nodes["node0"].advertise_prefixes(
                [PrefixEntry(f"10.99.{i}.0/24") for i in range(9)]
            )
            await clock.run_for(3.0)
        plan = FaultPlan()
        expected = {
            "partition": "generation_skew",
            "tpu_corrupt": "chip_quarantine",
            "fib_burst": "breaker_open",
            "actor_kill": "node_crash",
        }[family]
        if family == "partition":
            plan.partition(
                [f"node{i}" for i in range(8)], ["node8"],
                at=FAULT_AT, duration=30.0,
            )
        elif family == "tpu_corrupt":
            plan.tpu_corrupt(
                "node4", at=FAULT_AT, duration=30.0, device_index=3
            )
        elif family == "fib_burst":
            plan.fib_burst("node4", at=FAULT_AT, duration=20.0)
        else:
            plan.actor_kill("node4", "decision", at=FAULT_AT)
        controller = ChaosController(net, plan, seed=seed)
        t_fault_ms = (clock.now() + FAULT_AT) * 1000.0
        controller.start()
        h = net.nodes["node0"].health
        sweeps_at_fault = h.num_sweeps
        detect_ms = None
        for i in range(60):  # bounded: 30s of virtual time
            fired = [
                _json.loads(line)
                for line in h.alert_log()
                if _json.loads(line)["event"] == "fired"
            ]
            hit = [e for e in fired if e["name"] == expected]
            if hit:
                detect_ms = hit[0]["ts_ms"] - t_fault_ms
                break
            # drive the churn the family needs to surface
            if family in ("partition", "fib_burst"):
                net.nodes["node0"].advertise_prefixes(
                    [PrefixEntry(f"10.9{i % 10}.{i}.0/24")]
                )
            elif family == "tpu_corrupt" and i % 2 == 0:
                pair = [("node0", "node1"), ("node1", "node2")][
                    (i // 2) % 2
                ]
                net.fail_link(*pair)
            await clock.run_for(SWEEP_S)
        sweeps_to_detect = h.num_sweeps - sweeps_at_fault
        log = h.sink.log_bytes()
        if supervisor is not None:
            await supervisor.stop()
        await controller.stop()
        await net.stop()
        return detect_ms, sweeps_to_detect, log

    def run(coro):
        loop = asyncio.new_event_loop()
        try:
            return loop.run_until_complete(coro)
        finally:
            loop.close()

    detection = {}
    replay_identical = True
    for family in HEALTH_FAULT_FAMILIES:
        lats, sweeps, detected = [], [], 0
        for seed in seeds:
            detect_ms, n_sweeps, log = run(one_family(family, seed))
            if detect_ms is not None:
                detected += 1
                lats.append(detect_ms)
                sweeps.append(n_sweeps)
            if family == "partition" and seed == seeds[0]:
                _ms2, _n2, log2 = run(one_family(family, seed))
                replay_identical = replay_identical and log == log2
        lats.sort()
        detection[family] = {
            "alert": {
                "partition": "generation_skew",
                "tpu_corrupt": "chip_quarantine",
                "fib_burst": "breaker_open",
                "actor_kill": "node_crash",
            }[family],
            "samples": len(seeds),
            "detected": detected,
            "p50_ms": round(lats[len(lats) // 2], 1) if lats else -1.0,
            "max_ms": round(lats[-1], 1) if lats else -1.0,
            "max_sweeps": max(sweeps) if sweeps else 0,
        }
    return {
        "families": detection,
        "replay_identical": replay_identical,
        "sweep_interval_ms": SWEEP_S * 1000.0,
    }


def health_main(seed: Optional[int] = None) -> None:
    """Fleet-health benchmark (the BENCH_HEALTH_r* artifact).

    Part A — aggregator sweep overhead on the serving p50: one serving
    Decision answers W waves of K concurrent route_db queries (cache
    cleared per wave, so every wave pays a real millisecond-scale
    batch solve) while a FleetHealthAggregator
    sweeps a 9-node snapshot fleet ON THE SAME event loop, one full
    sweep (9 captures + cross-node merge + signal evaluation) per
    64-query wave — orders of magnitude more often than the production
    15s cadence, so the measured contention is an upper bound.
    Acceptance: p50 inflation <= 2%.

    Part B — chaos detection latency: per fault family, seeded 9-node
    SimClock emulations measure fault-injection -> first-alert latency
    in virtual ms at a 500ms sweep cadence (plus a replay determinism
    check on the alert JSONL).  Emits one JSON line."""
    import asyncio
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    from openr_tpu.ops.platform_env import (
        enable_persistent_compile_cache,
        fallback_to_cpu_if_unreachable,
        honor_cpu_platform_request,
    )

    honor_cpu_platform_request()
    fallback_to_cpu_if_unreachable()
    enable_persistent_compile_cache()

    from openr_tpu.common.runtime import WallClock
    from openr_tpu.config import DecisionConfig, ServingConfig
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.decision import Decision
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import (
        build_adj_dbs,
        random_connected_edges,
    )
    from openr_tpu.health import AlertSink, FleetHealthAggregator
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.monitor.metrics import MetricsSnapshot
    from openr_tpu.serving.service import QueryService
    from openr_tpu.types import PrefixEntry

    detection_seeds = (
        HEALTH_SEEDS if seed is None else (seed, seed + 4, seed + 6)
    )
    n_nodes, n_links, seed = 256, 512, (11 if seed is None else seed)
    waves, clients = 20, 64
    edges = random_connected_edges(n_nodes, n_links, seed=seed)
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(n_nodes):
        ps.update_prefix(
            f"node{i}", "0", PrefixEntry(f"10.{i // 256}.{i % 256}.0/24")
        )
    als = {"0": ls}

    def fresh_decision() -> Decision:
        solver = SpfSolver("node0")
        d = Decision(
            "node0",
            WallClock(),
            DecisionConfig(),
            ReplicateQueue("routes"),
            backend=TpuBackend(solver),
            solver=solver,
        )
        d.area_link_states = als
        d.prefix_state = ps
        d._change_seq = 1
        return d

    async def serving_round(with_health: bool):
        clock = WallClock()
        d = fresh_decision()
        sv = QueryService(
            "node0",
            clock,
            ServingConfig(max_batch=64, max_wait_ms=2),
            d,
            counters=d.counters,
        )
        sv.start()
        agg = None
        if with_health:
            # a 9-snapshot fleet sharing the serving node's live counter
            # surface: every sweep pays 9 captures + the full merge
            def fleet_source():
                return [
                    MetricsSnapshot.capture(
                        counters=d.counters,
                        node_name=f"node{i}",
                        clock=clock,
                    )
                    for i in range(HEALTH_FLEET_NODES)
                ]

            agg = FleetHealthAggregator(
                node_name="bench",
                clock=clock,
                source=fleet_source,
                sink=AlertSink("bench", clock, d.counters),
                counters=d.counters,
            )
        lat = []

        async def sweep_once():
            # rides the SAME event loop as the in-flight clients, so
            # the full capture+merge cost contends with serving exactly
            # like the HealthMonitor fiber does in production
            agg.sweep()

        async def client(i: int):
            t1 = time.perf_counter()
            await sv.submit(
                "route_db",
                {"node": f"node{i % clients}"},
                client_id=f"client{i}",
            )
            lat.append((time.perf_counter() - t1) * 1000.0)

        # warm-up wave (compile + first batch solve) excluded
        await asyncio.gather(*[client(i) for i in range(clients)])
        lat.clear()
        for _w in range(waves):
            # cold wave: every wave re-pays the batch solve, so the
            # p50 is a real millisecond-scale serving latency and the
            # sweep's contention is measured against it, not against
            # sub-microsecond cache hits
            sv.cache.clear()
            tasks = [client(i) for i in range(clients)]
            if agg is not None:
                tasks.append(sweep_once())  # one sweep per 64 queries
            await asyncio.gather(*tasks)
        sweeps = agg.num_sweeps if agg is not None else 0
        await sv.stop()
        lat.sort()
        return lat, sweeps

    def pct(lat, q):
        return lat[min(len(lat) - 1, int(len(lat) * q))]

    loop = asyncio.new_event_loop()
    try:
        lat_off, _ = loop.run_until_complete(serving_round(False))
        lat_on, sweeps = loop.run_until_complete(serving_round(True))
    finally:
        loop.close()
    p50_off, p50_on = pct(lat_off, 0.50), pct(lat_on, 0.50)
    overhead_pct = (p50_on - p50_off) / p50_off * 100.0

    det = _health_detection_sweep(seeds=detection_seeds)

    doc = {
        "metric": "health_sweep_overhead_pct_serving_p50",
        "value": round(overhead_pct, 2),
        "unit": "pct",
        "detail": {
            "serving_p50_ms_health_off": round(p50_off, 4),
            "serving_p50_ms_health_on": round(p50_on, 4),
            "serving_p99_ms_health_off": round(pct(lat_off, 0.99), 4),
            "serving_p99_ms_health_on": round(pct(lat_on, 0.99), 4),
            "sweeps_during_run": sweeps,
            "queries_per_sweep": clients,
            "fleet_nodes": HEALTH_FLEET_NODES,
            "waves": waves,
            "clients": clients,
            "detection": det["families"],
            "detection_sweep_interval_ms": det["sweep_interval_ms"],
            "deterministic_replay": det["replay_identical"],
            "world": {
                "nodes": n_nodes,
                "links": n_links,
                "prefixes": n_nodes,
                "topology": "random_connected",
                "seed": seed,
            },
            "mode": (
                "part A: wall-clock serving rounds with one full fleet "
                "sweep (9 captures + merge + evaluation) per 64-query "
                "wave on the shared event loop (far above the prod 15s "
                "cadence); part B: seeded 9-node grid SimClock "
                "emulations per fault family, detection in virtual ms"
            ),
            "env": env_stamp(),
        },
    }
    validate_health_bench(doc)
    print(json.dumps(doc))


WARMSTART_GENERATIONS = 24
WARMSTART_PARITY_EVERY = 8
WARMSTART_SWEEP_WARM = 2048
WARMSTART_SWEEP_COLD = 256
#: BENCH_SUITE_p50_r05.json grid4096 p50 publication→FIB — the round-5
#: cold-path baseline the warm rebuild must beat
WARMSTART_COLD_P50_REFERENCE_MS = 127.172


def validate_warmstart_bench(doc: dict) -> None:
    """Schema contract for BENCH_WARMSTART_r*.json — shared by the bench
    emitter and the tier-1 smoke test (tests/test_warmstart_bench_schema).

    The headline value is the warm generation-delta rebuild p50
    (publication→FIB equivalent: build + RouteDb diff) on grid4096,
    which must beat BOTH the in-run cold rebuild p50 and the round-5
    127ms reference.  The sweep block pins device warm-vs-cold
    incrementality (warm must win) and records the native C++ warm
    baseline; the device-beats-native gate applies whenever a real
    accelerator is attached (on a cpu-platform run the 'device' kernel
    IS host XLA, so that comparison measures compilers, not the
    architecture — the artifact records it honestly instead of gating)."""
    assert doc["metric"] == (
        "warmstart_rebuild_p50_publication_to_fib_ms_grid4096"
    )
    assert doc["unit"] == "ms"
    assert 0 < doc["value"] < WARMSTART_COLD_P50_REFERENCE_MS
    d = doc["detail"]
    rb = d["rebuild"]
    assert rb["warm_p50_ms"] == doc["value"]
    assert rb["warm_p50_ms"] < rb["cold_p50_ms"]
    assert rb["warm_p95_ms"] >= rb["warm_p50_ms"]
    assert rb["cold_p50_ms"] > 0
    assert rb["generations"] >= 16
    # every generation in the sweep is a pure perturbation: the warm
    # path must take ALL of them (hit ratio 1.0), with the selective
    # patch engaged and the counters recorded for the operator surface
    assert rb["warm_hits"] == rb["generations"]
    assert rb["warm_selective_builds"] == rb["generations"]
    assert rb["cold_fallbacks"] == 0
    assert rb["warm_purges"] == 0
    assert rb["encode_patches"] >= 1
    assert rb["parity_checks"] >= 2
    assert rb["parity_ok"] is True
    assert rb["reference_cold_p50_ms_r05"] == WARMSTART_COLD_P50_REFERENCE_MS
    assert rb["speedup_vs_cold"] > 1.0
    sw = d["sweep"]
    assert sw["device_warm_solves_per_sec"] > 0
    assert sw["device_cold_solves_per_sec"] > 0
    assert sw["native_warm_solves_per_sec"] > 0
    assert (
        sw["device_warm_solves_per_sec"] > sw["device_cold_solves_per_sec"]
    ), "warm-start must beat the cold kernel on the same sweep"
    assert sw["warm_solves"] >= 1024 and sw["cold_solves"] >= 128
    if d["env"]["platform"] != "cpu":
        assert (
            sw["device_warm_solves_per_sec"]
            > sw["native_warm_solves_per_sec"]
        ), "an attached accelerator must beat the native warm sweep"
    for key in ("world", "env", "mode"):
        assert key in d, key
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"
    assert d["env"]["device_count"] >= 1


def warmstart_main(seed: Optional[int] = None) -> None:
    """Warm-start benchmark (BENCH_WARMSTART_r*): the ISSUE-9
    generation-delta rebuild path on grid4096.

    Part A — rebuild p50: one TpuBackend with the warm context enabled
    and one with it disabled replay the SAME seeded link-metric
    perturbation sweep (one random link flips its metric per
    generation).  Each generation is measured publication→FIB
    equivalent: ``build_route_db(force_full=True, warm_delta=True)``
    plus the RouteDb diff Decision would publish (O(changed) for the
    warm-selective path, full for cold).  Every WARMSTART_PARITY_EVERY
    generations the warm RIB is asserted equal to the cold device build
    AND the scalar oracle.

    Part B — sweep solves/s: the single-link-failure repair sweep
    (ops/repair.RepairSweep, depth-sorted chunks) vs the cold
    batch-minor kernel on the same grid4096 world, plus the native C++
    warm-start sweep (spf_warm_sweep) as the cross-engine baseline."""
    from openr_tpu.ops.platform_env import (
        enable_persistent_compile_cache,
        fallback_to_cpu_if_unreachable,
        honor_cpu_platform_request,
    )

    honor_cpu_platform_request()
    fallback_to_cpu_if_unreachable()
    enable_persistent_compile_cache()

    import jax
    import jax.numpy as jnp

    from openr_tpu.common.runtime import CounterMap, WallClock
    from openr_tpu.config import ParallelConfig, ResilienceConfig
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
    from openr_tpu.types import PrefixEntry

    seed = 7 if seed is None else seed
    side = 64  # grid4096: the ROADMAP's canonical scale point
    edges = grid_edges(side)
    adj_dbs = build_adj_dbs(edges)
    ls = LinkState("0", "node0")
    for db in adj_dbs.values():
        ls.update_adjacency_database(db)
    n_nodes = side * side
    ps = PrefixState()
    for i in range(n_nodes):
        ps.update_prefix(
            f"node{i}",
            "0",
            PrefixEntry(f"10.{(i >> 8) & 0xFF}.{i & 0xFF}.0/24"),
        )
    als = {"0": ls}
    rng = np.random.default_rng(seed)

    def make_backend(warm: bool) -> TpuBackend:
        return TpuBackend(
            SpfSolver("node0"),
            min_device_prefixes=0,
            clock=WallClock(),
            counters=CounterMap(),
            resilience=ResilienceConfig(enabled=False),
            parallel=ParallelConfig(max_devices=1, min_shard_rows=0),
            warm_rebuild=warm,
        )

    def norm_db(db):
        return {
            p: (
                sorted(
                    (nh.neighbor_node_name, nh.metric) for nh in e.nexthops
                ),
                float(e.igp_cost),
            )
            for p, e in db.unicast_routes.items()
        }

    # ---- part A: the generation sweep --------------------------------
    warm_be = make_backend(True)
    cold_be = make_backend(False)
    prev_warm = warm_be.build_route_db(als, ps, force_full=True)
    prev_cold = cold_be.build_route_db(als, ps, force_full=True)
    # one unmeasured perturbation warms every jit shape both sides use
    node_names = sorted(adj_dbs)

    def perturb(step: int) -> None:
        victim = node_names[int(rng.integers(len(node_names)))]
        db = adj_dbs[victim]
        a = db.adjacencies[int(rng.integers(len(db.adjacencies)))]
        a.metric = 1 + (a.metric % 3)  # cycles 1→2→3→1: always a change
        ls.update_adjacency_database(db)

    # unmeasured warm-up perturbations: compile the warm kernels' shape
    # buckets (sub-edge + gathered-selection) before the timed window
    for step in range(-4, 0):
        perturb(step)
        warm_be.build_route_db(
            als, ps, changed_prefixes=set(), force_full=True,
            warm_delta=True,
        )
        warm_be.take_last_changed_prefixes()
        cold_be.build_route_db(
            als, ps, changed_prefixes=set(), force_full=True
        )
    w0, s0 = warm_be.num_warm_builds, warm_be.num_warm_selective_builds
    f0, p0 = warm_be.num_warm_cold_fallbacks, warm_be.num_warm_purges
    e0 = warm_be.num_encode_patches
    warm_lat, cold_lat = [], []
    parity_checks = 0
    parity_ok = True
    depths, rounds = [], []
    for gen in range(WARMSTART_GENERATIONS):
        perturb(gen)
        t0 = time.perf_counter()
        db_w = warm_be.build_route_db(
            als,
            ps,
            changed_prefixes=set(),
            force_full=True,
            warm_delta=True,
        )
        changed = warm_be.take_last_changed_prefixes()
        if changed is not None:
            update = prev_warm.calculate_update_for(db_w, changed)
        else:
            update = prev_warm.calculate_update(db_w)
        warm_lat.append((time.perf_counter() - t0) * 1000.0)
        prev_warm = db_w
        depths.append(warm_be.warm_last_est_depth)
        rounds.append(warm_be.warm_last_rounds)
        t0 = time.perf_counter()
        db_c = cold_be.build_route_db(
            als, ps, changed_prefixes=set(), force_full=True
        )
        cold_update = prev_cold.calculate_update(db_c)
        cold_lat.append((time.perf_counter() - t0) * 1000.0)
        prev_cold = db_c
        # the two engines must agree on WHAT changed, not just the state
        assert set(update.unicast_routes_to_update) <= set(
            db_w.unicast_routes
        )
        if gen % WARMSTART_PARITY_EVERY == 0:
            parity_checks += 1
            scalar = SpfSolver("node0").build_route_db(als, ps)
            parity_ok = parity_ok and (
                norm_db(db_w) == norm_db(db_c) == norm_db(scalar)
            )
        print(
            f"# gen {gen}: warm {warm_lat[-1]:.1f}ms "
            f"(depth {depths[-1]}, rounds {rounds[-1]}, "
            f"changed {len(changed) if changed is not None else 'all'}) "
            f"cold {cold_lat[-1]:.1f}ms",
            file=sys.stderr,
        )

    def pct(lat, q):
        srt = sorted(lat)
        return srt[min(len(srt) - 1, int(len(srt) * q))]

    warm_p50, cold_p50 = pct(warm_lat, 0.5), pct(cold_lat, 0.5)

    # ---- part B: the repair-sweep comparison -------------------------
    from openr_tpu.ops.csr import encode_link_state
    from openr_tpu.ops.repair import sort_by_depth
    from openr_tpu.ops.spf import sweep_spf_link_failures
    from openr_tpu.ops.whatif import LinkFailureSweep

    topo = encode_link_state(ls)
    eng = LinkFailureSweep(topo, "node0")
    eng.base_solve()
    plan = eng.plan()
    rs = eng.repair_sweep()
    g = rs.batch_granularity
    fails = rng.integers(
        0, len(topo.links), size=WARMSTART_SWEEP_WARM
    ).astype(np.int32)
    sfails, _ = sort_by_depth(plan, fails)
    chunk = 1024

    def warm_sweep_once():
        outs = []
        for off in range(0, len(sfails), chunk):
            c = sfails[off : off + chunk]
            if len(c) % g:
                c = np.concatenate(
                    [c, np.full(g - len(c) % g, -1, np.int32)]
                )
            outs.append(rs.solve(c))
        return outs

    jax.block_until_ready(warm_sweep_once())  # compile warm-up
    t0 = time.perf_counter()
    jax.block_until_ready(warm_sweep_once())
    device_warm_sps = WARMSTART_SWEEP_WARM / (time.perf_counter() - t0)

    cold_args = (
        jnp.asarray(topo.src),
        jnp.asarray(topo.dst),
        jnp.asarray(topo.w),
        jnp.asarray(topo.edge_ok),
        jnp.asarray(topo.link_index),
    )
    ovl = jnp.asarray(topo.overloaded)
    root = jnp.int32(topo.node_id("node0"))
    cold_fails = fails[:WARMSTART_SWEEP_COLD]

    def cold_sweep_once():
        return sweep_spf_link_failures(
            *cold_args,
            jnp.asarray(cold_fails),
            ovl,
            root,
            max_degree=topo.max_out_degree(),
            packed=False,
        )

    jax.block_until_ready(cold_sweep_once())
    t0 = time.perf_counter()
    jax.block_until_ready(cold_sweep_once())
    device_cold_sps = WARMSTART_SWEEP_COLD / (time.perf_counter() - t0)

    from openr_tpu.ops.native_spf import NativeSpf

    native = NativeSpf(topo, "node0")
    native.warm_prepare()
    native.warm_sweep(fails[:32])
    t0 = time.perf_counter()
    native.warm_sweep(fails)
    native_warm_sps = WARMSTART_SWEEP_WARM / (time.perf_counter() - t0)

    env = env_stamp()
    doc = {
        "metric": "warmstart_rebuild_p50_publication_to_fib_ms_grid4096",
        "value": round(warm_p50, 3),
        "unit": "ms",
        "vs_baseline": round(cold_p50 / warm_p50, 2),
        "detail": {
            "rebuild": {
                "warm_p50_ms": round(warm_p50, 3),
                "warm_p95_ms": round(pct(warm_lat, 0.95), 3),
                "warm_max_ms": round(max(warm_lat), 3),
                "cold_p50_ms": round(cold_p50, 3),
                "cold_p95_ms": round(pct(cold_lat, 0.95), 3),
                "speedup_vs_cold": round(cold_p50 / warm_p50, 2),
                "generations": WARMSTART_GENERATIONS,
                "warm_hits": warm_be.num_warm_builds - w0,
                "warm_selective_builds": (
                    warm_be.num_warm_selective_builds - s0
                ),
                "cold_fallbacks": warm_be.num_warm_cold_fallbacks - f0,
                "warm_purges": warm_be.num_warm_purges - p0,
                "encode_patches": warm_be.num_encode_patches - e0,
                "est_depth_max": max(depths),
                "warm_rounds_max": max(r for pair in rounds for r in pair),
                "parity_checks": parity_checks,
                "parity_ok": parity_ok,
                "reference_cold_p50_ms_r05": (
                    WARMSTART_COLD_P50_REFERENCE_MS
                ),
                "reference_note": (
                    "BENCH_SUITE_p50_r05.json grid4096 "
                    "p50_publication_to_fib_ms (TPU v5e capture, "
                    "2026-07-30); the in-run cold_p50_ms is the "
                    "same-host apples-to-apples denominator"
                ),
            },
            "sweep": {
                "device_warm_solves_per_sec": round(device_warm_sps, 1),
                "device_cold_solves_per_sec": round(device_cold_sps, 1),
                "native_warm_solves_per_sec": round(native_warm_sps, 1),
                "warm_vs_cold": round(
                    device_warm_sps / device_cold_sps, 2
                ),
                "warm_vs_native": round(
                    device_warm_sps / native_warm_sps, 3
                ),
                "warm_solves": WARMSTART_SWEEP_WARM,
                "cold_solves": WARMSTART_SWEEP_COLD,
                "native_reference_note": (
                    "BENCH_r04 native warm-start was ~420k solves/s on "
                    "the 1024-node WAN world; this sweep re-measures "
                    "BOTH engines on grid4096 in THIS environment.  On "
                    "platform=cpu the device kernel is host XLA sharing "
                    "the native baseline's silicon, so beating native "
                    "is only gated when a real accelerator is attached "
                    "(see validate_warmstart_bench)."
                ),
            },
            "world": {
                "nodes": n_nodes,
                "links": len(topo.links),
                "prefixes": n_nodes,
                "topology": f"grid{side}x{side}",
                "seed": seed,
            },
            "mode": (
                "emulate (in-process LSDB, WallClock backends; part A "
                "measures build_route_db(force_full, warm_delta) + the "
                "RouteDb diff Decision publishes, one random link-metric "
                "perturbation per generation; part B sweeps single-link "
                "failures through the repair kernel vs the cold kernel "
                "vs native C++ warm-start)"
            ),
            "env": env,
        },
    }
    validate_warmstart_bench(doc)
    print(json.dumps(doc))


#: topology classes the full --suite mode sweeps (the multi-area WAN
#: variant is exercised through per-area LSDB unit tests, not the
#: single-area protocol emulation)
SUITE_CLASSES = ("grid", "fattree_multipod", "wan_hierarchy")
SUITE_FULL_SCALE = 1024
SUITE_MIN_FULL_NODES = 1000
SUITE_SMOKE_SCALE = 256
SUITE_FLAPS = 6
SUITE_DRAINS = 2
SUITE_ANCHORS = 8
SUITE_SEED = 7


def validate_trajectory_bench(doc: dict) -> None:
    """Schema contract for BENCH_TRAJECTORY_r*.json — shared by the
    suite emitter, the tier-1 artifact gate, and the benchtrack
    manifest.  The headline value is the WORST per-class p50
    publication→FIB over the required topology classes at full scale;
    each class block must carry the 1k+-node floor, ordered
    percentiles, the warm-hit ratio, the per-class SLO verdict, full
    pipeline-phase shares, and the zero-unexpected-alerts assertion;
    the smoke block pins the tier-1 replay-determinism contract."""
    from openr_tpu.emulation.topology import TOPOLOGY_CLASSES

    assert doc["metric"] == "suite_worst_class_p50_publication_to_fib_ms"
    assert doc["unit"] == "ms_p50_virtual"
    d = doc["detail"]
    classes = d["classes"]
    assert set(SUITE_CLASSES) <= set(classes), (
        "the required topology classes must all be present"
    )
    for name, row in classes.items():
        assert name in TOPOLOGY_CLASSES, name
        assert row["nodes"] >= SUITE_MIN_FULL_NODES, (
            f"{name}: full-scale classes must be >= 1k nodes"
        )
        assert row["links"] > row["nodes"] * 0.9, name
        conv = row["convergence"]
        assert conv["samples"] > 0, name
        assert (
            0
            < conv["p50_ms"]
            <= conv["p95_ms"]
            <= conv["p99_ms"]
            <= conv["max_ms"]
        ), name
        w = row["warm"]
        assert w["hits"] >= 1, f"{name}: the flap sweep must warm-start"
        assert 0.0 <= w["hit_ratio"] <= 1.0, name
        slo = row["slo"]
        assert slo["convergence_slo_ms"] > 0, name
        assert slo["p99_within_slo"] is (
            conv["p99_ms"] <= slo["convergence_slo_ms"]
        ), name
        assert slo["p99_within_slo"], (
            f"{name}: p99 {conv['p99_ms']}ms blew the per-class SLO "
            f"{slo['convergence_slo_ms']}ms"
        )
        shares = row["pipeline_phase_share_pct"]
        assert shares, f"{name}: observer pipeline shares missing"
        assert abs(sum(shares.values()) - 100.0) < 1.0, name
        alerts = row["alerts"]
        assert alerts["unexpected"] == 0, (
            f"{name}: unexpected health alerts fired: {alerts}"
        )
        assert row["flaps"] >= 4 and row["drains"] >= 1, name
        assert row["observer"], name
    worst = max(
        classes[c]["convergence"]["p50_ms"] for c in SUITE_CLASSES
    )
    assert doc["value"] == worst
    smoke = d["smoke"]
    assert smoke["nodes"] <= SUITE_SMOKE_SCALE
    assert smoke["convergence"]["samples"] > 0
    assert d["deterministic_replay"] is True
    for key in ("seed", "mode", "env"):
        assert key in d, key
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"


def _class_phase_shares(edges, root: str, prefixes: int = 64) -> dict:
    """Wall-clock pipeline-phase shares for one topology class: one
    cold full device rebuild plus one warm perturbation tick of the
    class LSDB through a WallClock-probed TpuBackend.

    The emulation observer's probe rides the SimClock, where a
    synchronous build spans ZERO virtual ms — phase *time shares* are a
    wall-clock concept, so they come from this shadow build over the
    identical topology (compile excluded; shares recorded, absolute ms
    deliberately not: they are environment-bound)."""
    from openr_tpu.common.runtime import CounterMap, WallClock
    from openr_tpu.config import ParallelConfig, ResilienceConfig
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import build_adj_dbs, topology_nodes
    from openr_tpu.tracing import pipeline
    from openr_tpu.types import PrefixEntry

    adj_dbs = build_adj_dbs(edges)
    ls = LinkState("0", root)
    for db in adj_dbs.values():
        ls.update_adjacency_database(db)
    names = topology_nodes(edges)
    ps = PrefixState()
    step = max(1, len(names) // prefixes)
    for i, n in enumerate(names[::step][:prefixes]):
        ps.update_prefix(
            n, "0", PrefixEntry(f"10.{220 + i // 256}.{i % 256}.0/24")
        )
    als = {"0": ls}
    counters = CounterMap()
    backend = TpuBackend(
        SpfSolver(root),
        min_device_prefixes=0,
        clock=WallClock(),
        counters=counters,
        resilience=ResilienceConfig(enabled=False),
        parallel=ParallelConfig(max_devices=1, min_shard_rows=0),
        warm_rebuild=True,
    )
    backend.build_route_db(als, ps, force_full=True)  # compile, unmeasured

    def totals():
        out = {}
        for phase in pipeline.PHASES:
            h = counters.histogram(pipeline.hist_key(phase))
            if h is not None:
                out[phase] = h.total
        return out

    t0 = totals()
    flip = adj_dbs[root].adjacencies[0]
    flip.metric += 1
    ls.update_adjacency_database(adj_dbs[root])
    backend.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True
    )  # the cold lifecycle
    flip.metric += 1
    ls.update_adjacency_database(adj_dbs[root])
    backend.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True, warm_delta=True
    )  # the warm generation-delta tick
    t1 = totals()
    deltas = {
        k: t1.get(k, 0.0) - t0.get(k, 0.0)
        for k in t1
        if t1.get(k, 0.0) - t0.get(k, 0.0) > 0.0
    }
    attributed = sum(deltas.values())
    if not attributed:
        return {}
    return {
        k: round(v / attributed * 100.0, 2)
        for k, v in sorted(deltas.items())
    }


def suite_sweep_class(
    cls_name: str,
    scale: int,
    seed: int,
    flaps: int = SUITE_FLAPS,
    drains: int = SUITE_DRAINS,
    phase_shares: bool = True,
):
    """One topology class's seeded chaos flap/drain sweep through the
    protocol emulation under SimClock.

    Shape: the whole class-scale fleet runs complete OpenrNodes on the
    scalar decision path; ONE observer node (the sorted-first name)
    runs the device backend with warm rebuild and the fleet-health
    aggregator with the class's per-topology SLO catalog — a thousand
    jitted backends in one process would measure the harness, not the
    system, while one observer yields the warm-hit / pipeline-phase /
    alert surfaces the trajectory records.  ``SUITE_ANCHORS`` anchor
    prefixes (not full-mesh loopbacks) keep the route plane
    proportional to the control-plane story being measured.

    Returns ``(detail, fingerprint)``: the per-class artifact block and
    the replay-comparable bytes (alert JSONL + chaos counter dump +
    convergence histogram buckets) — two runs from one seed must match
    byte for byte."""
    import asyncio
    import random as _random
    import zlib

    from openr_tpu.chaos import ChaosController, FaultPlan
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import SloSpecConfig
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import (
        TOPOLOGY_CLASSES,
        topology_nodes,
    )
    from openr_tpu.health.slo import slos_for_topology_class
    from openr_tpu.types import PrefixEntry

    row = TOPOLOGY_CLASSES[cls_name]
    edges = row.build(scale, seed)
    names = topology_nodes(edges)
    observer = names[0]
    rng = _random.Random(zlib.crc32(cls_name.encode()) ^ (seed * 2654435761))
    anchors = sorted(rng.sample(names, min(SUITE_ANCHORS, len(names))))
    anchor_prefix = {
        a: f"10.210.{i}.0/24" for i, a in enumerate(anchors)
    }
    slo_specs = slos_for_topology_class(cls_name)

    def overrides(cfg):
        is_obs = cfg.node_name == observer
        cfg.tpu_compute_config.enable_tpu_spf = is_obs
        if is_obs:
            cfg.tpu_compute_config.min_device_prefixes = 0
        hc = cfg.health_config
        hc.enabled = is_obs
        hc.sweep_interval_s = 5.0
        hc.slos = [
            SloSpecConfig(
                name=s.name,
                metric=s.metric,
                kind=s.kind,
                percentile=s.percentile,
                threshold=s.threshold,
                objective=s.objective,
                fast_window_s=s.fast_window_s,
                slow_window_s=s.slow_window_s,
                burn_threshold=s.burn_threshold,
            )
            for s in slo_specs
        ]
        cfg.tracing_config.flight_recorder = is_obs

    async def run():
        clock = SimClock()
        net = EmulatedNetwork(
            clock, use_tpu_backend=None, config_overrides=overrides
        )
        net.build(edges)
        net.start(advertise_loopbacks=False)
        for a in anchors:
            net.nodes[a].advertise_prefixes([PrefixEntry(anchor_prefix[a])])
        all_prefixes = set(anchor_prefix.values())

        def anchors_routed():
            for name, node in net.nodes.items():
                want = all_prefixes - {anchor_prefix.get(name)}
                if want - set(net.fib_routes(name)):
                    return False
            return True

        converged = False
        for _ in range(30):
            await clock.run_for(4.0)
            if anchors_routed():
                converged = True
                break
        assert converged, f"{cls_name}@{scale}: anchors never converged"

        # baseline reset: only chaos-driven convergence is scored.  The
        # incarnation stamp survives the wipe (a reset start_ms would
        # read as a crash to the health plane's latch).
        for node in net.nodes.values():
            start_ms = node.counters.get("node.start_ms")
            node.counters.clear()
            node.counters.set("node.start_ms", start_ms)
        obs = net.nodes[observer]
        be = obs.decision.backend
        w0 = be.num_warm_builds
        s0 = be.num_warm_selective_builds
        f0 = be.num_warm_cold_fallbacks
        p0 = be.num_warm_purges
        t_mark_ms = clock.now_ms()

        links = sorted({tuple(sorted((a, b))) for a, b, _m in edges})
        flap_links = rng.sample(links, min(flaps, len(links)))
        plan = FaultPlan()
        t = 2.0
        for a, b in flap_links:
            plan.link_down(a, b, at=t, duration=4.0)
            t += 8.0
        controller = ChaosController(net, plan, seed=seed)
        controller.start()
        drain_pool = [
            n for n in names if n != observer and n not in anchors
        ]
        drain_nodes = rng.sample(drain_pool, min(drains, len(drain_pool)))
        step_s = 2.0
        steps = int((plan.horizon_s() + 4.0) / step_s) + 1
        # soft-drain flips ride the flap window: drain i raises its
        # node metric at step 2+3i and clears it three steps later —
        # both edges are pure perturbation ticks for the warm path
        drain_sched = {}
        for i, dn in enumerate(drain_nodes):
            on = 2 + 3 * i
            drain_sched.setdefault(on, []).append((dn, 100))
            drain_sched.setdefault(on + 3, []).append((dn, 0))
        for step in range(steps):
            for dn, inc in drain_sched.get(step, ()):
                net.nodes[dn].link_monitor.set_node_metric_increment(inc)
            await clock.run_for(step_s)
        for dn in drain_nodes:
            net.nodes[dn].link_monitor.set_node_metric_increment(0)
        await clock.run_for(12.0)
        assert anchors_routed(), (
            f"{cls_name}@{scale}: anchors lost after the sweep healed"
        )

        conv = net.merged_histogram("convergence.event_to_fib_ms")
        assert conv is not None and conv.count > 0, (
            f"{cls_name}@{scale}: no convergence samples in the window"
        )
        pct = conv.percentiles()

        warm_hits = be.num_warm_builds - w0
        fallbacks = be.num_warm_cold_fallbacks - f0

        health = obs.health
        fired_after_mark = []
        if health is not None:
            for line in health.alert_log():
                e = json.loads(line)
                if e["event"] == "fired" and e["ts_ms"] >= t_mark_ms:
                    fired_after_mark.append(e["name"])
        # a flap/drain sweep on a path-redundant class must fire NO
        # alerts: no partitions, no corruption, no crashes, and the
        # per-class convergence SLO holds
        unexpected = sorted(fired_after_mark)

        detail = {
            "topology_class": cls_name,
            "scale": scale,
            "nodes": len(names),
            "links": len(links),
            "seed": seed,
            "observer": observer,
            "anchors": len(anchors),
            "flaps": len(flap_links),
            "drains": len(drain_nodes),
            "virtual_s": round(clock.now(), 1),
            "convergence": {
                "p50_ms": round(pct["p50"], 2),
                "p95_ms": round(pct["p95"], 2),
                "p99_ms": round(pct["p99"], 2),
                "max_ms": round(conv.vmax, 2),
                "samples": conv.count,
            },
            "warm": {
                "hits": warm_hits,
                "selective_builds": be.num_warm_selective_builds - s0,
                "cold_fallbacks": fallbacks,
                "purges": be.num_warm_purges - p0,
                "hit_ratio": round(
                    warm_hits / max(1, warm_hits + fallbacks), 3
                ),
            },
            "alerts": {
                "fired": len(fired_after_mark),
                "unexpected": len(unexpected),
                "unexpected_names": unexpected,
                "health_sweeps": (
                    health.num_sweeps if health is not None else 0
                ),
            },
            "slo": {
                "convergence_slo_ms": row.convergence_slo_ms,
                "p99_within_slo": (
                    round(pct["p99"], 2) <= row.convergence_slo_ms
                ),
            },
        }
        fingerprint = b"\n".join(
            [
                health.sink.log_bytes() if health is not None else b"",
                json.dumps(
                    controller.counter_dump(), sort_keys=True
                ).encode(),
                json.dumps(
                    sorted(conv.bucket_items()), sort_keys=True
                ).encode(),
            ]
        )
        await controller.stop()
        await net.stop()
        return detail, fingerprint

    loop = asyncio.new_event_loop()
    try:
        detail, fingerprint = loop.run_until_complete(run())
    finally:
        loop.close()
    # wall-clock phase shares ride OUTSIDE the deterministic emulation
    # (and outside the fingerprint): shares are a wall-time concept
    detail["pipeline_phase_share_pct"] = (
        _class_phase_shares(edges, observer) if phase_shares else {}
    )
    return detail, fingerprint


def suite_main(seed: Optional[int] = None) -> None:
    """Trajectory suite benchmark (BENCH_TRAJECTORY_r*): per topology
    class at full scale (1k+ nodes), a seeded chaos flap/drain sweep
    through the SimClock protocol emulation, harvesting the
    publication→FIB percentile trajectory, observer warm-hit ratio,
    pipeline phase shares, and the zero-unexpected-alerts assertion;
    plus the 256-node smoke replayed twice to pin byte-identical
    determinism (the same contract tier-1 re-proves live).  Emits one
    JSON line; `python -m openr_tpu.benchtrack` reads the result into
    the cross-round trajectory."""
    seed = SUITE_SEED if seed is None else seed
    classes = {}
    for cls in SUITE_CLASSES:
        t0 = time.time()
        detail, _fp = suite_sweep_class(cls, SUITE_FULL_SCALE, seed)
        detail["wall_s"] = round(time.time() - t0, 1)
        classes[cls] = detail
        print(
            f"# {cls}@{detail['nodes']}: p50 "
            f"{detail['convergence']['p50_ms']}ms p99 "
            f"{detail['convergence']['p99_ms']}ms warm-hit "
            f"{detail['warm']['hit_ratio']} "
            f"({detail['wall_s']}s wall)",
            file=sys.stderr,
        )
    d1, fp1 = suite_sweep_class(
        "grid", SUITE_SMOKE_SCALE, seed, phase_shares=False
    )
    _d2, fp2 = suite_sweep_class(
        "grid", SUITE_SMOKE_SCALE, seed, phase_shares=False
    )
    deterministic = fp1 == fp2
    worst = max(
        classes[c]["convergence"]["p50_ms"] for c in SUITE_CLASSES
    )
    doc = {
        "metric": "suite_worst_class_p50_publication_to_fib_ms",
        "value": worst,
        "unit": "ms_p50_virtual",
        "detail": {
            "classes": classes,
            "smoke": {
                "topology_class": "grid",
                "scale": SUITE_SMOKE_SCALE,
                "nodes": d1["nodes"],
                "convergence": d1["convergence"],
            },
            "deterministic_replay": deterministic,
            "seed": seed,
            "mode": (
                "emulate (SimClock, full OpenrNodes; scalar fleet + one "
                "device-backend observer with warm rebuild and the "
                "per-class SLO catalog; anchor prefixes, seeded "
                "link-flap + soft-drain chaos; virtual-ms percentiles, "
                "deterministic across hosts)"
            ),
            "env": env_stamp(),
        },
    }
    validate_trajectory_bench(doc)
    print(json.dumps(doc))


# ---------------------------------------------------------------------------
# rolling-restart survival (ISSUE 12): BENCH_ROLLING_r*
# ---------------------------------------------------------------------------

ROLLING_CLASS = "grid"
ROLLING_SCALE = 64
ROLLING_SMOKE_SCALE = 36
ROLLING_SEED = 11
ROLLING_DOWN_S = 5.0
ROLLING_SETTLE_S = 6.0


def validate_rolling_bench(doc: dict) -> None:
    """Schema contract for BENCH_ROLLING_r*.json — shared by the bench
    emitter, the tier-1 artifact gate and the benchtrack manifest.  The
    headline is the STRUCTURAL warm-hit ratio over a rolling-restart
    sweep (every non-observer node bounced exactly once through the
    supervisor's storm-guarded queue): before the slot-stable encode it
    was 0 by construction.  The publication→FIB percentiles must hold
    the per-class SLO for the whole upgrade, the health plane must stay
    silent, and the seeded smoke must replay byte-identically."""
    assert doc["metric"] == "rolling_restart_structural_warm_hit_ratio"
    assert doc["unit"] == "ratio"
    d = doc["detail"]
    assert d["topology_class"] == ROLLING_CLASS
    sweep = d["sweep"]
    # every node except the measurement observer bounces exactly once,
    # and the restart-storm guard keeps the fleet from going down at
    # once (default cap: 1 in-flight restart)
    assert sweep["nodes_bounced"] == d["nodes"] - 1
    assert sweep["restarts"] == sweep["nodes_bounced"]
    assert sweep["max_concurrent_observed"] == 1
    assert sweep["crashes"] == 0, "deliberate restarts must not latch"
    w = d["warm"]
    assert 0.0 <= w["structural_hit_ratio"] <= 1.0
    assert doc["value"] == w["structural_hit_ratio"]
    # each bounce produces at least one structural tick at the observer
    # (leave + rejoin, possibly debounce-coalesced)
    assert w["structural_hits"] >= sweep["nodes_bounced"]
    assert w["slot_patches"] >= w["structural_hits"]
    conv = d["convergence"]
    assert conv["samples"] > 0
    assert (
        0
        < conv["p50_ms"]
        <= conv["p95_ms"]
        <= conv["p99_ms"]
        <= conv["max_ms"]
    )
    slo = d["slo"]
    assert slo["convergence_slo_ms"] > 0
    assert slo["p99_within_slo"] is (
        conv["p99_ms"] <= slo["convergence_slo_ms"]
    )
    assert slo["p99_within_slo"], (
        f"p99 {conv['p99_ms']}ms blew the per-class SLO "
        f"{slo['convergence_slo_ms']}ms mid-upgrade"
    )
    alerts = d["alerts"]
    assert alerts["unexpected"] == 0, (
        f"unexpected health alerts fired during the upgrade: {alerts}"
    )
    assert d["serving"]["queries"] > 0, "the sweep must run under load"
    assert d["smoke"]["nodes"] <= ROLLING_SMOKE_SCALE
    assert d["deterministic_replay"] is True
    for key in ("seed", "mode", "env"):
        assert key in d, key
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"


def rolling_sweep_world(
    scale: int,
    seed: int,
    down_s: float = ROLLING_DOWN_S,
    settle_s: float = ROLLING_SETTLE_S,
):
    """One rolling-restart survival round through the SimClock protocol
    emulation: boot a grid-class fleet (scalar decision path + ONE
    device-backend observer carrying warm rebuild, the health plane and
    the per-class SLO catalog — the suite's shape), converge, then
    bounce every non-observer node exactly once via the supervisor's
    storm-guarded deliberate-restart queue, with a down window past the
    Spark hold timer (neighbors must really observe the leave) and a
    serving-query load riding the observer throughout.

    Returns ``(detail, fingerprint)`` — fingerprint covers the bounce
    log, the supervisor restart log, the health alert JSONL and the
    convergence histogram buckets: two runs from one seed must match
    byte for byte."""
    import asyncio
    import random as _random
    import zlib

    from openr_tpu.chaos import RollingRestartSweep, Supervisor
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import SloSpecConfig
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import (
        TOPOLOGY_CLASSES,
        topology_nodes,
    )
    from openr_tpu.health.slo import slos_for_topology_class
    from openr_tpu.types import PrefixEntry

    row = TOPOLOGY_CLASSES[ROLLING_CLASS]
    edges = row.build(scale, seed)
    names = topology_nodes(edges)
    observer = names[0]
    rng = _random.Random(
        zlib.crc32(b"rolling") ^ (seed * 2654435761)
    )
    anchors = sorted(rng.sample(names, min(SUITE_ANCHORS, len(names))))
    anchor_prefix = {a: f"10.212.{i}.0/24" for i, a in enumerate(anchors)}
    slo_specs = slos_for_topology_class(ROLLING_CLASS)

    def overrides(cfg):
        is_obs = cfg.node_name == observer
        cfg.tpu_compute_config.enable_tpu_spf = is_obs
        if is_obs:
            cfg.tpu_compute_config.min_device_prefixes = 0
        hc = cfg.health_config
        hc.enabled = is_obs
        hc.sweep_interval_s = 5.0
        hc.slos = [
            SloSpecConfig(
                name=s.name,
                metric=s.metric,
                kind=s.kind,
                percentile=s.percentile,
                threshold=s.threshold,
                objective=s.objective,
                fast_window_s=s.fast_window_s,
                slow_window_s=s.slow_window_s,
                burn_threshold=s.burn_threshold,
            )
            for s in slo_specs
        ]

    async def run():
        clock = SimClock()
        net = EmulatedNetwork(
            clock, use_tpu_backend=None, config_overrides=overrides
        )
        net.build(edges)
        net.start(advertise_loopbacks=False)
        for a in anchors:
            net.nodes[a].advertise_prefixes([PrefixEntry(anchor_prefix[a])])
        all_prefixes = set(anchor_prefix.values())

        def anchors_routed():
            for name, node in net.nodes.items():
                want = all_prefixes - {anchor_prefix.get(name)}
                if want - set(net.fib_routes(name)):
                    return False
            return True

        converged = False
        for _ in range(30):
            await clock.run_for(4.0)
            if anchors_routed():
                converged = True
                break
        assert converged, f"rolling@{scale}: anchors never converged"

        # baseline reset: only sweep-driven convergence is scored; the
        # incarnation stamp survives (a reset start_ms would read as a
        # crash to the health plane's latch)
        for node in net.nodes.values():
            start_ms = node.counters.get("node.start_ms")
            node.counters.clear()
            node.counters.set("node.start_ms", start_ms)
        obs = net.nodes[observer]
        be = obs.decision.backend
        sh0 = dict(be._warm_class_builds)
        sf0 = dict(be._warm_class_fallbacks)
        slot0 = be.num_encode_slot_patches
        purge0 = be.num_warm_purges
        t_mark_ms = clock.now_ms()

        supervisor = Supervisor(clock)

        async def restart_and_readvertise(name):
            # a production daemon re-reads its configured prefixes at
            # boot; the anchor advertisements are harness-owned config,
            # so the harness restores them on the replacement node
            node = await net.restart_node(name)
            if name in anchor_prefix:
                node.advertise_prefixes(
                    [PrefixEntry(anchor_prefix[name])]
                )
            return node

        sweep = RollingRestartSweep(
            net,
            supervisor,
            seed=seed,
            down_s=down_s,
            settle_s=settle_s,
            skip=(observer,),
            restart_fn=restart_and_readvertise,
        )
        serving_stats = {"queries": 0, "errors": 0}
        serving_alive = [True]

        async def serving_load():
            # "under serving load": a route_db query per tick against
            # the observer's serving plane, vantage rotating over the
            # anchors — rides the device fleet engine while the sweep
            # churns under it
            i = 0
            while serving_alive[0]:
                target = anchors[i % len(anchors)]
                try:
                    await obs.serving.submit(
                        "route_db", {"node": target}, client_id="bench"
                    )
                    serving_stats["queries"] += 1
                except Exception:  # noqa: BLE001 - shed/quota under churn
                    serving_stats["errors"] += 1
                i += 1
                await clock.sleep(3.0)

        load_task = asyncio.ensure_future(serving_load())
        sweep_task = asyncio.ensure_future(sweep.run())
        while not sweep_task.done():
            await clock.run_for(2.0)
        sweep_task.result()
        settled = False
        for _ in range(20):
            await clock.run_for(4.0)
            if anchors_routed():
                settled = True
                break
        serving_alive[0] = False
        await clock.run_for(4.0)
        load_task.cancel()
        assert settled, (
            f"rolling@{scale}: anchors lost after the upgrade completed"
        )

        # publication→FIB at the STABLE vantage (the observer): a
        # freshly reborn node's full sync re-delivers keys whose
        # embedded trace contexts join their ORIGINAL origin events
        # (PR-3 semantics), so its convergence samples measure key age,
        # not propagation — the upgrade's latency story is what the
        # surviving vantage experienced while the fleet churned under
        # it
        conv = obs.counters.histogram("convergence.event_to_fib_ms")
        assert conv is not None and conv.count > 0
        pct = conv.percentiles()

        s_hits = be._warm_class_builds["structural"] - sh0["structural"]
        s_fb = (
            be._warm_class_fallbacks["structural"] - sf0["structural"]
        )
        p_hits = (
            be._warm_class_builds["perturbation"] - sh0["perturbation"]
        )

        health = obs.health
        fired_after_mark = []
        if health is not None:
            for line in health.alert_log():
                e = json.loads(line)
                if e["event"] == "fired" and e["ts_ms"] >= t_mark_ms:
                    fired_after_mark.append(e["name"])
        unexpected = sorted(fired_after_mark)

        detail = {
            "topology_class": ROLLING_CLASS,
            "scale": scale,
            "nodes": len(names),
            "links": len({tuple(sorted((a, b))) for a, b, _m in edges}),
            "seed": seed,
            "observer": observer,
            "anchors": len(anchors),
            "virtual_s": round(clock.now(), 1),
            "sweep": {
                "nodes_bounced": sweep.num_bounced,
                "down_s": down_s,
                "settle_s": settle_s,
                "restarts": supervisor.num_restarts,
                "requested": supervisor.num_requested_restarts,
                "crashes": supervisor.num_crashes,
                "max_concurrent_observed": (
                    supervisor.max_observed_concurrency
                ),
            },
            "warm": {
                "structural_hits": s_hits,
                "structural_fallbacks": s_fb,
                "structural_hit_ratio": round(
                    s_hits / max(1, s_hits + s_fb), 3
                ),
                "perturbation_hits": p_hits,
                "slot_patches": be.num_encode_slot_patches - slot0,
                "slot_declines": dict(be._slot_decline_reasons),
                "purges": be.num_warm_purges - purge0,
            },
            "convergence": {
                "vantage": observer,
                "p50_ms": round(pct["p50"], 2),
                "p95_ms": round(pct["p95"], 2),
                "p99_ms": round(pct["p99"], 2),
                "max_ms": round(conv.vmax, 2),
                "samples": conv.count,
            },
            "slo": {
                "convergence_slo_ms": row.convergence_slo_ms,
                "p99_within_slo": (
                    round(pct["p99"], 2) <= row.convergence_slo_ms
                ),
            },
            "alerts": {
                "fired": len(fired_after_mark),
                "unexpected": len(unexpected),
                "unexpected_names": unexpected,
                "health_sweeps": (
                    health.num_sweeps if health is not None else 0
                ),
            },
            "serving": dict(serving_stats),
        }
        fingerprint = b"\n".join(
            [
                sweep.fingerprint(),
                health.sink.log_bytes() if health is not None else b"",
                json.dumps(
                    sorted(conv.bucket_items()), sort_keys=True
                ).encode(),
            ]
        )
        await net.stop()
        return detail, fingerprint

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(run())
    finally:
        loop.close()


def rolling_main(seed: Optional[int] = None) -> None:
    """Rolling-restart survival benchmark (BENCH_ROLLING_r*): bounce
    every non-observer node of a grid-class fleet exactly once through
    the supervisor's storm-guarded queue, under serving load, and prove
    the system never goes cold — structural warm-hit ratio as the
    headline (0 before the slot-stable encode), publication→FIB p99
    held within the per-class SLO for the entire upgrade, zero health
    alerts, and the seeded smoke replayed twice for byte-identical
    determinism.  Emits one JSON line."""
    seed = ROLLING_SEED if seed is None else seed
    t0 = time.time()
    detail, _fp = rolling_sweep_world(ROLLING_SCALE, seed)
    detail["wall_s"] = round(time.time() - t0, 1)
    print(
        f"# rolling grid@{detail['nodes']}: bounced "
        f"{detail['sweep']['nodes_bounced']} structural warm-hit "
        f"{detail['warm']['structural_hit_ratio']} p99 "
        f"{detail['convergence']['p99_ms']}ms ({detail['wall_s']}s wall)",
        file=sys.stderr,
    )
    d1, fp1 = rolling_sweep_world(ROLLING_SMOKE_SCALE, seed)
    _d2, fp2 = rolling_sweep_world(ROLLING_SMOKE_SCALE, seed)
    doc = {
        "metric": "rolling_restart_structural_warm_hit_ratio",
        "value": detail["warm"]["structural_hit_ratio"],
        "unit": "ratio",
        "detail": {
            **detail,
            "smoke": {
                "scale": ROLLING_SMOKE_SCALE,
                "nodes": d1["nodes"],
                "nodes_bounced": d1["sweep"]["nodes_bounced"],
                "structural_hit_ratio": (
                    d1["warm"]["structural_hit_ratio"]
                ),
                "convergence": d1["convergence"],
            },
            "deterministic_replay": fp1 == fp2,
            "mode": (
                "emulate (SimClock, full OpenrNodes; scalar fleet + one "
                "device-backend observer with warm rebuild, health plane "
                "and per-class SLOs; every non-observer node bounced "
                "once via the supervisor's storm-guarded queue, down "
                "window past the Spark hold timer, serving load riding "
                "the observer; virtual-ms percentiles.  Class params "
                "derive from --scale: the 1k-node rerun of this sweep "
                "is owed on faster iron — wall cost scales ~N^2 in the "
                "in-process emulation)"
            ),
            "env": env_stamp(),
        },
    }
    validate_rolling_bench(doc)
    print(json.dumps(doc))


# ===========================================================================
# --streaming: snapshot+delta fan-out at 10k+ subscribers (ISSUE 13)
# ===========================================================================

STREAMING_SEED = 11
STREAMING_SUBS = 10_000
STREAMING_CHURN_PER_TICK = 64
STREAMING_TICKS = 24
STREAMING_SMOKE_SUBS = 64
STREAMING_SMOKE_TICKS = 12
#: pull-mode cohort left undrained until the end: their 16-deep queues
#: overflow over the tick run, proving shed_oldest-to-resync escalation
STREAMING_OVERFLOW_COHORT = 32


def validate_streaming_bench(doc: dict) -> None:
    """Schema contract for BENCH_STREAMING_r*.json — shared by the
    bench emitter, the tier-1 artifact gate and the benchtrack
    manifest.  The headline is wall-clock fan-out throughput (delivered
    emissions/s) over a 10k+ subscriber churn sweep under seeded chaos
    (partition/heal mid-sweep); generation correctness is gated hard:
    zero monotone-invariant violations, the stalled subscriber's single
    merged delta reproducing the live db, no pre-partition generation
    ever emitted, zero unexpected alerts, byte-identical seeded
    replays."""
    assert doc["metric"] == "streaming_fanout_emissions_per_sec"
    assert doc["unit"] == "emissions/s"
    d = doc["detail"]
    subs = d["subscribers"]
    assert subs["peak"] >= 10_000, "the sweep must reach 10k+ subscribers"
    assert subs["churned"] > 0
    fan = d["fanout"]
    assert fan["emissions"] > 0 and fan["wall_s"] > 0
    assert doc["value"] == fan["emissions_per_sec"] > 0
    # the emissions/s regression guard (ISSUE-14 satellite): the
    # shared-wire-encode fan-out loop must never regress to an
    # order-of-magnitude-slower per-subscriber rebuild path.  An
    # absolute floor (r01 measured ~69k/s on this class of host; the
    # benchtrack ratchet holds the fine-grained line)
    assert fan["emissions_per_sec"] >= 5_000, (
        "fan-out throughput collapsed an order of magnitude"
    )
    if "shared_encode" in fan:
        # emitted from the shared-wire-encode era on: the delta body
        # must be rendered once per feed entry, shared across the
        # subscriber fan-out
        se = fan["shared_encode"]
        assert se["shared_payloads"] > se["rendered_payloads"] > 0
    assert fan["deltas"] > 0 and fan["snapshots"] > 0
    st = d["staleness_ms"]
    assert st["samples"] > 0
    assert 0 <= st["p50"] <= st["p95"] <= st["p99"] <= st["max"]
    rs = d["resyncs"]
    assert rs["count"] >= 1, "the overflow cohort must have resynced"
    assert rs["overflow_cohort_resynced"] >= 1
    assert 0.0 <= rs["rate"] <= 1.0
    assert rs["shed_deltas"] >= 1
    md = d["merged_delta"]
    assert md["skipped_generations"] >= 3
    assert md["emissions"] == 1, "one merged delta, never a replay of N"
    assert md["kind_ok"] is True, "the merged window must be ONE delta"
    assert md["parity"] is True
    part = d["partition"]
    assert part["post_heal_emissions"] > 0
    assert part["pre_partition_generation_emissions"] == 0
    assert d["invariant_violations"] == 0
    assert d["alerts"]["unexpected"] == 0, d["alerts"]
    assert d["smoke"]["subscribers"] == STREAMING_SMOKE_SUBS
    assert d["deterministic_replay"] is True
    for key in ("seed", "mode", "env"):
        assert key in d, key
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"


def streaming_fanout_world(n_subs: int, seed: int, ticks: int):
    """One watch-plane fan-out round through the SimClock protocol
    emulation: a 9-node grid converges, node0's StreamingService takes
    ``n_subs`` push subscribers (vantages rotating over the other 8
    nodes, a quarter of them prefix-filtered) plus a pull-mode overflow
    cohort and one deliberately stalled probe, then a seeded churn
    sweep drives ``ticks`` generations (prefix churn + a mid-sweep
    partition/heal of node8) while subscribers attach/detach each tick.

    Returns ``(detail, fingerprint)`` — the fingerprint covers the
    probe subscribers' full emission logs and every node's alert JSONL:
    two runs from one seed must match byte for byte."""
    import asyncio
    import random as _random
    import zlib

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import grid_edges
    from openr_tpu.serving import apply_emission
    from openr_tpu.types import PrefixEntry

    rng = _random.Random(zlib.crc32(b"streaming") ^ (seed * 2654435761))

    def overrides(cfg):
        s = cfg.serving_config
        s.stream_publish_min_ms = 5
        s.stream_publish_max_ms = 20
        # shallow queues so the never-drained overflow cohort provably
        # escalates to resync within the tick budget
        s.stream_queue_depth = 8
        s.quota_tokens = 50
        s.quota_refill_per_s = 100.0
        # pull-mode cohorts are drained at the END of the sweep; the
        # stall detacher must not reap them mid-measurement
        s.stream_stall_detach_s = 300.0

    def canon_rows(rows) -> str:
        return json.dumps(
            {"|".join(map(str, k)): v for k, v in rows.items()},
            sort_keys=True,
            default=str,
        )

    async def run():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=overrides)
        net.build(grid_edges(3))
        net.start()
        for _ in range(10):
            await clock.run_for(4.0)
            if net.converged_full_mesh()[0]:
                break
        ok, why = net.converged_full_mesh()
        assert ok, why

        n0 = net.nodes["node0"]
        st = n0.streaming
        vantages = [f"node{i}" for i in range(1, 9)]

        delivered = [0]
        monotone_regressions = [0]
        pre_partition_emissions = [0]
        post_heal_emissions = [0]
        partition_seq = [None]
        healed_at_emission = [None]

        def make_deliver(record: Optional[list] = None):
            state = {"last": -1}

            def deliver(e):
                delivered[0] += 1
                if e["seq"] < state["last"]:
                    monotone_regressions[0] += 1
                state["last"] = e["seq"]
                if (
                    partition_seq[0] is not None
                    and e["seq"] <= partition_seq[0]
                ):
                    pre_partition_emissions[0] += 1
                if healed_at_emission[0] is not None:
                    post_heal_emissions[0] += 1
                if record is not None:
                    record.append(e)

            return deliver

        live: list = []  # (sub_id, client) attach order, churn pool
        attached_total = 0

        def attach_one(i: int, record: Optional[list] = None):
            nonlocal attached_total
            filters = ("10.220.",) if i % 4 == 0 else ()
            sid = st.subscribe(
                "route_db",
                {"node": vantages[i % len(vantages)]},
                client_id=f"w{i}",
                prefix_filters=filters,
                deliver=make_deliver(record),
            )
            live.append((sid, f"w{i}"))
            attached_total += 1
            return sid

        # probe subscribers: full emission logs (the determinism
        # fingerprint) + applied-state parity at the end
        probe_logs = [[] for _ in range(4)]
        probe_ids = [
            attach_one(i, record=probe_logs[i]) for i in range(4)
        ]
        for i in range(4, n_subs):
            attach_one(i)
        # pull-mode cohorts: the overflow cohort never polls until the
        # end; the stalled probe polls exactly once after skipping >= 3
        # generations
        overflow_ids = [
            st.subscribe(
                "route_db",
                {"node": vantages[i % len(vantages)]},
                client_id=f"ov{i}",
            )
            for i in range(STREAMING_OVERFLOW_COHORT)
        ]
        stalled_id = st.subscribe(
            "route_db", {"node": "node3"}, client_id="stalled"
        )

        async def poll1(sid, hold=0.1):
            # SimClock discipline: the poll must park on a task while
            # run_for advances virtual time
            t = asyncio.ensure_future(st.next_emission(sid, hold_s=hold))
            await clock.run_for(max(hold * 4, 0.5))
            return t.result()

        stalled_snap = await poll1(stalled_id)
        assert stalled_snap["type"] == "snapshot"
        stalled_state = apply_emission({}, stalled_snap)
        stalled_cursor = stalled_snap["seq"]
        # prime the overflow cohort's cursors (first contact = the
        # subscribe snapshot); they never drain again until the end
        for sid in overflow_ids:
            e = await poll1(sid)
            assert e["type"] == "snapshot"
        merged_stats = {}

        peak = len(st._subs)
        churned = 0
        side_a = [f"node{i}" for i in range(8)]
        t0 = time.time()
        for tick in range(ticks):
            n0.advertise_prefixes([PrefixEntry(f"10.220.{tick}.0/24")])
            await clock.run_for(1.0)
            if tick == ticks // 3:
                # mid-sweep partition: node8's hold-timer leave is a
                # structural (full-window) generation at node0
                partition_seq[0] = n0.decision.generation_key()[0]
                net.partition(side_a, ["node8"])
                await clock.run_for(4.0)
            if tick == (2 * ticks) // 3:
                net.heal_partition(side_a, ["node8"])
                await clock.run_for(8.0)
                healed_at_emission[0] = delivered[0]
            if tick == 5:
                # the stalled probe drains once mid-sweep, BEFORE its
                # queue overflows: >= 3 skipped generations must fold
                # into exactly ONE merged delta reproducing live
                skipped = (
                    n0.decision.generation_key()[0] - stalled_cursor
                )
                merged = await poll1(stalled_id)
                emitted = 0
                if merged is not None:
                    emitted = 1
                    stalled_state = apply_emission(stalled_state, merged)
                more = await poll1(stalled_id)
                _g, live_db = n0.serving.snapshot_for(
                    "route_db", {"node": "node3"}
                )
                want = {
                    ("u", r["dest"]): r
                    for r in live_db["unicast_routes"]
                }
                want.update(
                    {
                        ("m", r["top_label"]): r
                        for r in live_db["mpls_routes"]
                    }
                )
                merged_stats = {
                    "skipped_generations": skipped,
                    "emissions": emitted,
                    "kind_ok": (
                        merged is not None
                        and merged["type"] == "delta"
                        and merged["merged_generations"] >= 3
                        and more is None
                    ),
                    "parity": (
                        canon_rows(stalled_state) == canon_rows(want)
                    ),
                }
            # subscriber churn: seeded detach + fresh attach
            for _ in range(min(STREAMING_CHURN_PER_TICK, len(live) - 8)):
                idx = rng.randrange(4, len(live))  # never the probes
                sid, _client = live.pop(idx)
                st.unsubscribe(sid)
                churned += 1
            for j in range(STREAMING_CHURN_PER_TICK):
                attach_one(attached_total)
            peak = max(peak, len(st._subs))
        await clock.run_for(4.0)
        wall_s = time.time() - t0

        # the overflow cohort: shallow queues over `ticks` generations
        # must have escalated to snapshot resync
        overflow_resyncs = 0
        for sid in overflow_ids:
            e = await poll1(sid)
            if e is not None and e["type"] == "snapshot" and e[
                "reason"
            ].startswith("resync"):
                overflow_resyncs += 1

        # probe parity: every probe's applied state matches live
        probe_parity = True
        for i, log in enumerate(probe_logs):
            state: dict = {}
            for e in log:
                state = apply_emission(state, e)
            _g, db = n0.serving.snapshot_for(
                "route_db", {"node": vantages[i % len(vantages)]}
            )
            wrows = {("u", r["dest"]): r for r in db["unicast_routes"]}
            wrows.update(
                {("m", r["top_label"]): r for r in db["mpls_routes"]}
            )
            if probe_ids[i] in st._subs and st._subs[
                probe_ids[i]
            ].prefix_filters:
                wrows = {
                    k: v
                    for k, v in wrows.items()
                    if k[0] != "u" or k[1].startswith("10.220.")
                }
            if canon_rows(state) != canon_rows(wrows):
                probe_parity = False

        c = n0.counters
        stale_h = c.histogram("streaming.staleness_ms")
        pct = stale_h.percentiles() if stale_h is not None else {}
        emissions = int(c.get("streaming.emissions"))
        resyncs = int(c.get("streaming.resyncs"))
        fired = []
        for _name, node in sorted(net.nodes.items()):
            if node.health is not None:
                for line in node.health.alert_log():
                    e = json.loads(line)
                    if e["event"] == "fired":
                        fired.append(e["name"])

        detail = {
            "nodes": 9,
            "seed": seed,
            "ticks": ticks,
            "virtual_s": round(clock.now(), 1),
            "subscribers": {
                "peak": peak,
                "attached_total": attached_total
                + STREAMING_OVERFLOW_COHORT
                + 1,
                "churned": churned,
                "final": len(st._subs),
                "quota_clients_final": len(n0.serving._quotas),
            },
            "feeds": len(st._feeds),
            "fanout": {
                "emissions": emissions,
                "delivered": delivered[0],
                "wall_s": round(wall_s, 3),
                "emissions_per_sec": round(delivered[0] / wall_s, 1),
                "deltas": int(c.get("streaming.deltas")),
                "snapshots": int(c.get("streaming.snapshots")),
                "coalesced": int(
                    c.get("streaming.coalesced_emissions")
                ),
                # shared-wire-encode evidence (ISSUE-14 satellite):
                # delta bodies rendered once per feed entry, shared by
                # reference across the unfiltered subscriber fan-out
                "shared_encode": {
                    "rendered_payloads": int(
                        c.get("streaming.rendered_payloads")
                    ),
                    "shared_payloads": int(
                        c.get("streaming.shared_payloads")
                    ),
                },
            },
            "staleness_ms": {
                "p50": round(pct.get("p50", 0.0), 3),
                "p95": round(pct.get("p95", 0.0), 3),
                "p99": round(pct.get("p99", 0.0), 3),
                "max": round(stale_h.vmax if stale_h else 0.0, 3),
                "samples": stale_h.count if stale_h else 0,
            },
            "resyncs": {
                "count": resyncs,
                "rate": round(resyncs / max(1, emissions), 5),
                "shed_deltas": int(c.get("streaming.shed_deltas")),
                "overflow_cohort_resynced": overflow_resyncs,
            },
            "merged_delta": {
                **merged_stats,
                "parity": merged_stats.get("parity", False)
                and probe_parity,
            },
            "partition": {
                "partition_seq": partition_seq[0],
                "pre_partition_generation_emissions": (
                    pre_partition_emissions[0]
                ),
                "post_heal_emissions": (
                    delivered[0] - (healed_at_emission[0] or 0)
                ),
                "monotone_regressions": monotone_regressions[0],
            },
            "invariant_violations": int(
                c.get("streaming.invariant_violations")
            ),
            "alerts": {
                "fired": len(fired),
                "unexpected": len(fired),
                "unexpected_names": sorted(fired),
            },
        }
        fingerprint = b"\n".join(
            [
                json.dumps(
                    [
                        [
                            json.dumps(e, sort_keys=True, default=str)
                            for e in log
                        ]
                        for log in probe_logs
                    ]
                ).encode(),
                *(
                    log
                    for _n, log in sorted(
                        net.health_alert_logs().items()
                    )
                ),
            ]
        )
        await net.stop()
        return detail, fingerprint

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(run())
    finally:
        loop.close()


def streaming_main(seed: Optional[int] = None) -> None:
    """Watch-plane fan-out benchmark (BENCH_STREAMING_r*): 10k+ push
    subscribers with per-tick churn on one node's StreamingService,
    under a seeded chaos sweep (mid-sweep partition/heal of node8), with
    generation correctness gated hard — see validate_streaming_bench.
    Emits one JSON line."""
    seed = STREAMING_SEED if seed is None else seed
    t0 = time.time()
    detail, _fp = streaming_fanout_world(
        STREAMING_SUBS, seed, STREAMING_TICKS
    )
    detail["wall_s"] = round(time.time() - t0, 1)
    print(
        f"# streaming fan-out: {detail['subscribers']['peak']} subs peak "
        f"{detail['fanout']['emissions_per_sec']} emissions/s "
        f"p99 staleness {detail['staleness_ms']['p99']}ms virtual "
        f"resync rate {detail['resyncs']['rate']} "
        f"({detail['wall_s']}s wall)",
        file=sys.stderr,
    )
    d1, fp1 = streaming_fanout_world(
        STREAMING_SMOKE_SUBS, seed, STREAMING_SMOKE_TICKS
    )
    _d2, fp2 = streaming_fanout_world(
        STREAMING_SMOKE_SUBS, seed, STREAMING_SMOKE_TICKS
    )
    doc = {
        "metric": "streaming_fanout_emissions_per_sec",
        "value": detail["fanout"]["emissions_per_sec"],
        "unit": "emissions/s",
        "detail": {
            **detail,
            "smoke": {
                "subscribers": STREAMING_SMOKE_SUBS,
                "ticks": STREAMING_SMOKE_TICKS,
                "emissions": d1["fanout"]["emissions"],
                "resyncs": d1["resyncs"]["count"],
            },
            "deterministic_replay": fp1 == fp2,
            "mode": (
                "emulate (SimClock, 9-node grid, full OpenrNodes; "
                "scalar decision path; 10k+ push subscribers with "
                "seeded per-tick churn on node0's StreamingService, "
                "pull-mode overflow cohort + one stalled probe; "
                "mid-sweep partition/heal of node8; staleness in "
                "virtual ms, fan-out throughput in wall seconds)"
            ),
            "env": env_stamp(),
        },
    }
    validate_streaming_bench(doc)
    print(json.dumps(doc))


SWEEP_SEED = 7
SWEEP_GRID_SIDE = 64  # 4096 nodes, 8064 links: the grid4096 class
SWEEP_SHARD = 1024
SWEEP_COMBOS_PER_WORLD = 512
SWEEP_RESUME_KILL_AFTER = 3


def validate_sweep_bench(doc: dict) -> None:
    """Schema contract for BENCH_SWEEP_r*.json — shared by the bench
    emitter, the tier-1 artifact gate and the benchtrack manifest.

    The ISSUE-14 acceptance: 100k+ scenarios on a grid4096-class
    topology end to end in ONE round, per-phase pipeline attribution
    proving the sweep is DEVICE-bound (not decode- or spill-bound),
    spill-file row count + peak host-resident rows recorded
    in-artifact, and a kill-after-shard-K resume reproducing the
    uninterrupted ranked summary byte for byte."""
    from openr_tpu.tracing.pipeline import (
        DECODE,
        DEVICE_PHASES,
        HOST_PHASES,
        STREAM_DRAIN,
        SWEEP_REDUCE,
        SWEEP_SHARD_SOLVE,
    )

    assert doc["metric"] == "sweep_scenarios_per_sec_grid4096"
    assert doc["unit"] == "scenarios/s"
    d = doc["detail"]
    assert d["world"]["nodes"] == SWEEP_GRID_SIDE * SWEEP_GRID_SIDE
    sc = d["scenarios"]
    assert sc["total"] >= 100_000, "the acceptance floor is 100k+"
    assert sc["singles"] > 0 and sc["worlds"] >= 2
    assert sc["device_solves"] > 0
    sh = d["shards"]
    assert sh["completed"] == sh["total"] >= 2
    assert sh["scenarios_per_shard"] >= 1
    th = d["throughput"]
    assert doc["value"] == th["scenarios_per_sec"] > 0
    assert th["wall_s"] > 0
    sp = d["spill"]
    assert sp["rows"] == sc["total"], "every scenario spills exactly once"
    assert sp["segments_sealed"] >= 1 and sp["bytes"] > 0
    # the never-host-resident claim: peak rows in host memory bounded
    # by ONE shard, never the sweep
    assert 0 < sp["peak_host_rows"] <= sh["scenarios_per_shard"]
    att = d["attribution"]
    phases = att["phases_ms"]
    assert phases.get(SWEEP_SHARD_SOLVE, 0.0) > 0.0
    assert phases.get(STREAM_DRAIN, 0.0) > 0.0
    assert phases.get(SWEEP_REDUCE, 0.0) > 0.0
    assert phases.get(DECODE, 0.0) > 0.0
    host = sum(phases.get(p, 0.0) for p in HOST_PHASES)
    device = sum(phases.get(p, 0.0) for p in DEVICE_PHASES)
    assert att["device_share_pct"] == round(
        device / max(host + device, 1e-9) * 100.0, 2
    )
    assert att["device_bound"] is True
    assert att["device_share_pct"] > 50.0, (
        "the sweep must be device-bound"
    )
    for p, bound in ((DECODE, 25.0), (SWEEP_REDUCE, 25.0)):
        share = phases.get(p, 0.0) / max(host + device, 1e-9) * 100.0
        assert share < bound, f"{p} share {share:.1f}% — not device-bound"
    assert 0.0 <= att["gap_pct"] <= 30.0, (
        "un-attributed wall beyond the loop-overhead allowance"
    )
    pc = d["plan_cache"]
    assert pc["hits"] >= 1, (
        "world engine replicas must HIT the content-hash plan cache"
    )
    assert pc["size"] <= pc["cap"]
    rs = d["resume"]
    assert rs["proof_scenarios"] >= 8_000
    assert rs["killed_after_shards"] >= 1
    assert rs["resumed_shards"] == rs["killed_after_shards"]
    assert rs["checkpoint_verified"] is True
    assert rs["summary_byte_identical"] is True
    rk = d["ranked"]
    assert rk["criticality_rows"] >= 1
    assert rk["worst_case"] is not None
    for key in ("seed", "mode", "env"):
        assert key in d, key
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"
    assert d["env"]["device_count"] >= 8


def _sweep_bench_world(n_side: int):
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
    from openr_tpu.types import PrefixEntry

    ls = LinkState("0")
    for db in build_adj_dbs(grid_edges(n_side)).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(n_side * n_side):
        ps.update_prefix(
            f"node{i}", "0",
            PrefixEntry(f"10.{i // 256}.{i % 256}.0/24"),
        )
    return {"0": ls}, ps


def sweep_main(seed: Optional[int] = None) -> None:
    """Capacity-planning sweep benchmark (BENCH_SWEEP_r*): 100k+
    scenarios (single-link failures x drain states x metric
    perturbations + bounded 2-node-domain combos) on the grid4096
    class, sharded as committed per-device dispatches over an 8-chip
    DevicePool, spilled + checkpointed + rank-reduced end to end; plus
    the kill-after-shard-K resume proof on a single-world sub-sweep.
    Emits one JSON line."""
    import os
    import shutil
    import tempfile

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    seed = SWEEP_SEED if seed is None else seed

    from openr_tpu.common.runtime import CounterMap, WallClock
    from openr_tpu.ops import repair
    from openr_tpu.parallel.mesh import DevicePool
    from openr_tpu.sweep import ScenarioSpec, SweepExecutor, SweepInputs
    from openr_tpu.sweep.spill import CheckpointManifest, SpillReader
    from openr_tpu.tracing import pipeline
    from openr_tpu.tracing.pipeline import PipelineProbe

    als, ps = _sweep_bench_world(SWEEP_GRID_SIDE)
    clock = WallClock()
    counters = CounterMap()
    probe = PipelineProbe(clock, counters)
    pool = DevicePool()

    def inputs():
        return SweepInputs(
            area_link_states=als,
            prefix_state=ps,
            change_seq=1,
            root="node0",
            pool=pool,
            probe=probe,
        )

    def phase_totals() -> dict:
        out = {}
        for phase in pipeline.PHASES:
            h = counters.histogram(pipeline.hist_key(phase))
            if h is not None:
                out[phase] = h.total
        return out

    def make_ex(spill_dir):
        return SweepExecutor(
            inputs,
            spill_dir,
            clock=clock,
            counters=counters,
            shard_scenarios=SWEEP_SHARD,
            inflight=2,
        )

    # the headline grammar: 12 worlds x 8064 single-link failures +
    # 512 seeded 2-node-domain combos per world = 102,912 scenarios
    spec = ScenarioSpec(
        drain_node_sets=(
            (),
            ("node2080",),            # center drain
            ("node1032",),            # off-center drain
            ("node1032", "node2080"),  # double maintenance window
        ),
        metric_perturbations=(
            (r"node1[0-9]{3}", 2.0),  # mid-band cost-up
            (r"node2[0-9]{3}", 8.0),  # deep cost-out
        ),
        combo_k=2,
        max_combo_scenarios=SWEEP_COMBOS_PER_WORLD,
        combo_seed=seed,
    )
    tmp = tempfile.mkdtemp(prefix="openr_sweep_bench.")
    try:
        ex = make_ex(os.path.join(tmp, "headline"))
        t0 = time.time()
        rep = ex.prepare(spec)
        prepare_s = time.time() - t0
        print(
            f"# sweep: {rep['scenarios']} scenarios in {rep['shards']} "
            f"shards over {pool.num_healthy} devices "
            f"(enumerate {prepare_s:.1f}s)",
            file=sys.stderr,
        )
        p0 = phase_totals()
        t0 = time.time()
        ex.run()
        wall_s = time.time() - t0
        p1 = phase_totals()
        phases_ms = {
            k: round(p1.get(k, 0.0) - p0.get(k, 0.0), 3)
            for k in pipeline.PHASES
            if p1.get(k, 0.0) - p0.get(k, 0.0) > 0.0
        }
        host = sum(
            phases_ms.get(p, 0.0) for p in pipeline.HOST_PHASES
        )
        device = sum(
            phases_ms.get(p, 0.0) for p in pipeline.DEVICE_PHASES
        )
        attributed = host + device
        status = ex.status()
        summary = ex.summary()
        plan_gauges = repair.plan_cache_gauges()
        per_device = [int(n) for n in pool.num_dispatches]
        print(
            f"# sweep: {status['scenarios_completed']} scenarios in "
            f"{wall_s:.1f}s ({status['scenarios_completed'] / wall_s:.0f}"
            f"/s), {status['device_solves']} device solves, "
            f"device share "
            f"{device / max(attributed, 1e-9) * 100.0:.1f}%",
            file=sys.stderr,
        )

        # ---- the resume proof: kill after shard K, resume, compare --
        proof_spec = ScenarioSpec()  # identity world, 8064 singles
        exf = make_ex(os.path.join(tmp, "proof_full"))
        exf.prepare(proof_spec)
        exf.run()
        exk = make_ex(os.path.join(tmp, "proof_kill"))
        exk.prepare(proof_spec)
        exk.run(stop_after_shards=SWEEP_RESUME_KILL_AFTER)
        killed = len(exk.completed)
        exr = make_ex(os.path.join(tmp, "proof_kill"))
        rrep = exr.prepare(proof_spec)
        # checkpoint verification: the manifest's committed shards are
        # exactly what the kill left, and the spill holds their rows
        cp = CheckpointManifest(os.path.join(tmp, "proof_kill"))
        committed = cp.completed_shards()
        replayed = sum(
            1
            for _ in SpillReader(os.path.join(tmp, "proof_kill")).rows(
                shard_filter=set(committed)
            )
        )
        checkpoint_verified = (
            sorted(committed) == sorted(range(killed))
            and replayed == sum(m["rows"] for m in committed.values())
        )
        exr.run()
        resume = {
            "proof_scenarios": len(exf.scenarios),
            "killed_after_shards": killed,
            "resumed_shards": rrep["resumed_shards"],
            "checkpoint_verified": checkpoint_verified,
            "summary_byte_identical": (
                exr.summary()["summary_digest"]
                == exf.summary()["summary_digest"]
            ),
        }
        print(
            f"# sweep resume proof: killed after {killed} shards, "
            f"resumed {rrep['resumed_shards']}, byte-identical "
            f"{resume['summary_byte_identical']}",
            file=sys.stderr,
        )
        ranked = summary["summary"]
        doc = {
            "metric": "sweep_scenarios_per_sec_grid4096",
            "value": round(status["scenarios_completed"] / wall_s, 1),
            "unit": "scenarios/s",
            "detail": {
                "world": {
                    "topology": f"grid{SWEEP_GRID_SIDE}x{SWEEP_GRID_SIDE}",
                    "nodes": SWEEP_GRID_SIDE * SWEEP_GRID_SIDE,
                    "links": 2
                    * SWEEP_GRID_SIDE
                    * (SWEEP_GRID_SIDE - 1),
                    "prefixes": SWEEP_GRID_SIDE * SWEEP_GRID_SIDE,
                    "vantage": "node0",
                },
                "scenarios": {
                    "total": status["scenarios_completed"],
                    "singles": 12 * 2 * SWEEP_GRID_SIDE
                    * (SWEEP_GRID_SIDE - 1),
                    "combos": status["scenarios_completed"]
                    - 12 * 2 * SWEEP_GRID_SIDE * (SWEEP_GRID_SIDE - 1),
                    "worlds": 12,
                    "device_solves": status["device_solves"],
                    "alias_rows": ranked["alias_rows"],
                    "zero_delta": ranked["zero_delta"],
                },
                "shards": {
                    "total": status["shards_total"],
                    "completed": status["shards_completed"],
                    "scenarios_per_shard": SWEEP_SHARD,
                    "repacked": status["repacked_shards"],
                    "per_device_dispatches": per_device,
                },
                "throughput": {
                    "scenarios_per_sec": round(
                        status["scenarios_completed"] / wall_s, 1
                    ),
                    "device_solves_per_sec": round(
                        status["device_solves"] / wall_s, 1
                    ),
                    "wall_s": round(wall_s, 1),
                    "prepare_s": round(prepare_s, 1),
                },
                "spill": status["spill"],
                "attribution": {
                    "phases_ms": phases_ms,
                    "attributed_ms": round(attributed, 1),
                    "host_ms": round(host, 1),
                    "device_ms": round(device, 1),
                    "device_share_pct": round(
                        device / max(attributed, 1e-9) * 100.0, 2
                    ),
                    "device_bound": device
                    / max(attributed, 1e-9)
                    > 0.5,
                    "gap_pct": round(
                        max(
                            (wall_s * 1000.0 - attributed)
                            / (wall_s * 1000.0)
                            * 100.0,
                            0.0,
                        ),
                        2,
                    ),
                },
                "plan_cache": {
                    "hits": int(plan_gauges["plan_cache.hits"]),
                    "misses": int(plan_gauges["plan_cache.misses"]),
                    "evictions": int(
                        plan_gauges["plan_cache.evictions"]
                    ),
                    "size": int(plan_gauges["plan_cache.size"]),
                    "cap": int(plan_gauges["plan_cache.cap"]),
                },
                "resume": resume,
                "ranked": {
                    "criticality_rows": len(ranked["criticality"]),
                    "top_links": ranked["criticality"][:5],
                    "worst_case": ranked["worst_case"],
                    "spof_count": len(ranked["spof_links"]),
                    "summary_digest": summary["summary_digest"],
                },
                "seed": seed,
                "mode": (
                    "standalone executor (WallClock) over a synthetic "
                    "grid4096 LSDB; 8 forced host devices (virtual "
                    "chips share physical cores — per-device scaling "
                    "is structural, the throughput is the one-host "
                    "number); warm-repair solve + on-device selection "
                    "per shard, streamed FIFO drains"
                ),
                "env": env_stamp(),
            },
        }
        validate_sweep_bench(doc)
        print(json.dumps(doc))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


FRR_GRID_SIDE = 64
FRR_MAX_LINKS = 128
FRR_FLAPS = 24
#: the checked-in BENCH_WARMSTART_r01 warm generation-delta rebuild p50
#: (publication→FIB equivalent) on the same grid4096 world — the
#: protection tier's 10x acceptance floor is judged against this
#: warm-path reference (rebuilding is the thing the table replaces)
FRR_WARM_REFERENCE_P50_MS = 79.314
FRR_SPEEDUP_FLOOR = 10.0


def validate_frr_bench(doc: dict) -> None:
    """Schema contract for BENCH_FRR_r*.json — shared by the bench
    emitter, the tier-1 artifact gate and the benchtrack manifest.

    The ISSUE-16 acceptance: on grid4096 with a 128-link minted
    protection table, the publication→FIB p99 of a PROTECTED
    single-link flap (kv ingest → classify → generation-exact lookup →
    materialize → publish → FIB program, real Decision + Fib actors on
    the wall clock) must sit >= 10x below the 79.3ms warm-rebuild p50
    reference; every applied patch carries scalar-oracle RIB parity
    after its confirming warm solve (zero mismatches); stale-table and
    unminted-link fallbacks are exercised and counted in-artifact; a
    mint killed after shard K resumes to the byte-identical table
    hash."""
    assert doc["metric"] == (
        "frr_protected_flap_publication_to_fib_p99_ms_grid4096"
    )
    assert doc["unit"] == "ms"
    d = doc["detail"]
    ap = d["apply"]
    assert doc["value"] == ap["p99_ms"]
    assert 0 < ap["p50_ms"] <= ap["p95_ms"] <= ap["p99_ms"] <= ap["max_ms"]
    assert ap["flaps"] >= 16
    assert len(ap["samples_ms"]) == ap["flaps"]
    # every measured flap applied from the table, was confirmed by the
    # warm authority, and reached the FIB as an frr-stamped patch
    assert ap["applied"] == ap["flaps"]
    assert ap["fib_patches_applied"] == ap["flaps"]
    assert ap["confirms"] == ap["flaps"]
    assert ap["mismatches"] == 0
    assert ap["scalar_parity"] is True
    assert ap["parity_checks"] == ap["flaps"]
    wm = d["warm"]
    assert wm["samples"] >= 16
    assert 0 < wm["p50_ms"] <= wm["p99_ms"]
    assert wm["reference_p50_ms_r01"] == FRR_WARM_REFERENCE_P50_MS
    sp = d["speedup"]
    assert sp["floor"] == FRR_SPEEDUP_FLOOR
    assert sp["vs_reference_warm_p50"] == round(
        FRR_WARM_REFERENCE_P50_MS / ap["p99_ms"], 2
    )
    assert sp["vs_reference_warm_p50"] >= FRR_SPEEDUP_FLOOR, (
        "protected convergence must be a lookup: p99 >= 10x under the "
        "warm-rebuild reference"
    )
    fb = d["fallbacks"]
    assert fb["stale"] >= 1, "stale-table fallback must be exercised"
    assert fb["miss"] >= 1, "unminted-link fallback must be exercised"
    assert fb["total"] >= fb["stale"] + fb["miss"]
    mi = d["mint"]
    assert mi["patches"] == mi["max_links"] == FRR_MAX_LINKS
    assert mi["eligible"] >= 1
    assert mi["mints"] >= ap["flaps"]
    assert mi["cold_wall_ms"] > 0 and mi["warm_wall_p50_ms"] > 0
    assert 0 < mi["coverage_pct"] < 100.0
    rs = d["resume"]
    assert rs["killed_after_shards"] >= 1
    assert rs["resumed"] is True
    assert rs["table_hash_byte_identical"] is True
    assert d["world"]["nodes"] == FRR_GRID_SIDE * FRR_GRID_SIDE
    for key in ("seed", "mode", "env"):
        assert key in d, key
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"
    assert d["env"]["device_count"] >= 1


def frr_main(seed: Optional[int] = None) -> None:
    """Fast-reroute protection-tier benchmark (BENCH_FRR_r*): failure
    convergence as a lookup, on grid4096 with REAL actors.

    One Decision (TPU backend) and one Fib (instrumented in-memory
    agent) run on the wall clock, fed delta kv publications exactly the
    way a flood would deliver them.  A 128-link protection table is
    minted from the live generation before every measured flap; the
    headline sample is t(kv publication push) → t(the frr patch's
    routes hit the FibAgent), covering ingest, down-classification, the
    generation-exact table lookup, patch materialization, the
    INCREMENTAL publish and the Fib actor's program step.  The same
    flap set replays with the tier detached for the in-run warm-path
    comparison (debounce + generation-delta rebuild + publish).  Every
    applied patch is confirmed by the warm solve and checked against
    the scalar oracle; stale-table and unminted-link refusals are
    driven on purpose so the fallback ledger is populated; a mint
    killed after one shard proves byte-identical resume."""
    import asyncio
    import copy
    import gc
    import os
    import random as _random
    import shutil
    import tempfile

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    from openr_tpu.ops.platform_env import (
        enable_persistent_compile_cache,
        fallback_to_cpu_if_unreachable,
        honor_cpu_platform_request,
    )

    honor_cpu_platform_request()
    fallback_to_cpu_if_unreachable()
    enable_persistent_compile_cache()

    from openr_tpu.common.runtime import CounterMap, WallClock
    from openr_tpu.config import DecisionConfig, FibConfig, ProtectionConfig
    from openr_tpu.decision.backend import ScalarBackend, TpuBackend
    from openr_tpu.decision.decision import Decision
    from openr_tpu.decision.rib import route_db_summary
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
    from openr_tpu.fib.fib import Fib, MockFibAgent
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.protection import ProtectionBuilder, ProtectionService, ProtectionStore
    from openr_tpu.sweep import SweepInputs
    from openr_tpu.types import (
        InitializationEvent,
        PrefixDatabase,
        PrefixEntry,
        PrefixMetrics,
        Publication,
        Value,
        prefix_key,
    )

    seed = 7 if seed is None else seed
    side = FRR_GRID_SIDE
    n_nodes = side * side
    # seeded heterogeneous link costs: a unit-metric grid is pathological
    # ECMP — from a corner vantage every destination keeps the same two
    # nexthops across ANY interior-link failure, so most patches would be
    # empty.  Random WAN-style costs make shortest paths (mostly) unique,
    # so a protected flap actually reroutes a subtree.
    _mrng = _random.Random(seed * 7919 + 1)
    edges = [
        (a, b, 1 + _mrng.randrange(15)) for a, b, _m in grid_edges(side)
    ]
    base_dbs = build_adj_dbs(edges)
    versions = {node: 1 for node in base_dbs}

    def adj_value(node, without=None):
        db = copy.deepcopy(base_dbs[node])
        if without is not None:
            db.adjacencies = [
                a for a in db.adjacencies if a.other_node_name != without
            ]
        return Value(
            version=versions[node],
            originator_id=node,
            value=json.dumps(db.to_wire()).encode(),
        )

    def link_pub(a, b, down):
        """The delta publication a flood delivers for one link event:
        just the two endpoints' re-encoded adjacency DBs."""
        versions[a] += 1
        versions[b] += 1
        return Publication(
            key_vals={
                f"adj:{a}": adj_value(a, without=b if down else None),
                f"adj:{b}": adj_value(b, without=a if down else None),
            }
        )

    class TimingAgent(MockFibAgent):
        """MockFibAgent that timestamps the first route programming
        after arm() — the measurement endpoint of every flap sample."""

        def __init__(self, c) -> None:
            super().__init__(c)
            self.armed = False
            self.t_program = 0.0
            self.programmed = asyncio.Event()

        def arm(self) -> None:
            self.armed = True
            self.programmed.clear()

        async def add_unicast_routes(self, routes):
            if self.armed:
                self.t_program = time.perf_counter()
                self.armed = False
                self.programmed.set()
            await super().add_unicast_routes(routes)

    prot_dir = tempfile.mkdtemp(prefix="openr_frr_bench.")

    async def bench():
        clock = WallClock()
        solver = SpfSolver("node0")
        out_q = ReplicateQueue("routes")
        kv_q = ReplicateQueue("kv")
        d = Decision(
            "node0",
            clock,
            DecisionConfig(debounce_min_ms=10, debounce_max_ms=250),
            out_q,
            kv_store_updates_reader=kv_q.get_reader(),
            backend=TpuBackend(solver),
            solver=solver,
        )
        d.backend.auto_dispatch_rt_ms = 0.0
        agent = TimingAgent(clock)
        fib = Fib(
            "node0",
            clock,
            FibConfig(route_delete_delay_ms=50),
            agent,
            out_q.get_reader(),
            counters=d.counters,
        )
        d.start()
        fib.start()
        d.on_initialization_event(InitializationEvent.KVSTORE_SYNCED)
        kv_q.push(
            Publication(
                key_vals={f"adj:{n}": adj_value(n) for n in base_dbs}
            )
        )
        prefix_kvs = {}
        for i in range(1, n_nodes):
            node = f"node{i}"
            prefix = f"10.{(i >> 8) & 0xFF}.{i & 0xFF}.0/24"
            pdb = PrefixDatabase(
                this_node_name=node,
                prefix_entries=[
                    PrefixEntry(
                        prefix,
                        metrics=PrefixMetrics(path_preference=1000),
                    )
                ],
            )
            prefix_kvs[prefix_key(node, prefix)] = Value(
                version=1,
                originator_id=node,
                value=json.dumps(pdb.to_wire()).encode(),
            )
        kv_q.push(Publication(key_vals=prefix_kvs))

        async def wait_for(pred, what, timeout_s=120.0):
            deadline = time.perf_counter() + timeout_s
            while not pred():
                if time.perf_counter() > deadline:
                    raise AssertionError(f"timed out waiting for {what}")
                await asyncio.sleep(0.002)

        await wait_for(
            lambda: d._first_build_done and agent.num_sync >= 1,
            "first build + FULL_SYNC",
        )

        async def push_and_settle(pubs, what):
            s = d._change_seq
            for p in pubs:
                kv_q.push(p)
            await wait_for(
                lambda: d._change_seq >= s + len(pubs)
                and d.rebuild_settled(),
                what,
            )

        svc = ProtectionService(
            "node0",
            clock,
            ProtectionConfig(
                enabled=True,
                store_dir=os.path.join(prot_dir, "store"),
                shard_scenarios=64,
                max_links=FRR_MAX_LINKS,
            ),
            d,
            counters=d.counters,
        )
        d.protection = svc
        d.add_generation_listener(svc._on_generation, priority=20)

        # -- mint the table (cold: includes sweep-kernel compile) -----------
        t0 = time.perf_counter()
        rep = svc.mint_now()
        cold_mint_ms = (time.perf_counter() - t0) * 1000.0
        assert rep["patches"] == FRR_MAX_LINKS, rep
        mint_walls = []

        def mint_warm():
            t0 = time.perf_counter()
            svc.mint_now()
            mint_walls.append((time.perf_counter() - t0) * 1000.0)

        minted = [
            tuple(k.split("|"))
            for k in svc.table.store.keys()
            if k.count("|") == 1
        ]
        # measured flaps must carry a real route delta (a flap off the
        # vantage's SPF tree legitimately mints an empty patch — nothing
        # to program, nothing to time), and the vantage keeps its own
        # adjacencies up
        protected = []
        for a, b in minted:
            if "node0" in (a, b):
                continue
            doc = svc.table.store.lookup(f"{a}|{b}")
            if doc and doc.get("eligible") and doc.get("sets"):
                protected.append((a, b))
        assert len(protected) >= FRR_FLAPS + 2, (
            f"only {len(protected)} non-trivial protected links minted"
        )
        rng = _random.Random(seed)
        flap_pairs = rng.sample(protected, FRR_FLAPS)
        spare = [p for p in protected if p not in flap_pairs]

        # -- warm-path comparison: same flaps, tier detached ----------------
        d.protection = None
        warm_ms = []
        for i, (a, b) in enumerate([flap_pairs[0]] + flap_pairs):
            print(f"warm flap {i}: {a}|{b}", file=sys.stderr, flush=True)
            s = d._change_seq
            agent.arm()
            t0 = time.perf_counter()
            kv_q.push(link_pub(a, b, down=True))
            await asyncio.wait_for(agent.programmed.wait(), timeout=60.0)
            if i > 0:  # flap 0 replays unmeasured to absorb compiles
                warm_ms.append((agent.t_program - t0) * 1000.0)
            await wait_for(
                lambda: d._change_seq >= s + 1 and d.rebuild_settled(),
                "warm flap settle",
            )
            await push_and_settle(
                [link_pub(a, b, down=False)], "warm restore"
            )
        d.protection = svc

        # -- fallback ledger: an unminted link misses ----------------------
        mint_warm()
        pairs_all = {tuple(sorted((a, b))) for a, b, _m in edges}
        miss_pair = next(
            p
            for p in sorted(pairs_all - set(minted))
            if "node0" not in p
        )
        await push_and_settle(
            [link_pub(*miss_pair, down=True)], "miss flap"
        )
        await push_and_settle(
            [link_pub(*miss_pair, down=False)], "miss restore"
        )
        assert d.counters.get("protection.fallback.miss") >= 1

        # -- fallback ledger: a second flap hits the now-stale table -------
        mint_warm()
        first, second = spare[0], spare[1]
        # the first flap applies from the table and moves the generation;
        # the second (NO re-mint) finds its previous generation no longer
        # matching the mint — refuse stale, converge warm
        await push_and_settle(
            [link_pub(*first, down=True)], "stale first flap"
        )
        await push_and_settle(
            [link_pub(*second, down=True)], "stale second flap"
        )
        await push_and_settle(
            [
                link_pub(*first, down=False),
                link_pub(*second, down=False),
            ],
            "stale restore",
        )
        assert d.counters.get("protection.fallback.stale") >= 1

        # -- the measured pass ----------------------------------------------
        counter_keys = (
            "decision.frr_applied",
            "decision.frr_mismatches",
            "protection.confirms",
            "fib.frr_patches_applied",
        )
        base = {k: d.counters.get(k) for k in counter_keys}
        frr_ms = []
        parity_checks = 0
        parity_ok = True
        for a, b in flap_pairs:
            mint_warm()  # fresh-generation table for THIS flap
            gc.collect()
            confirms0 = d.counters.get("protection.confirms")
            s = d._change_seq
            agent.arm()
            # a 24-sample p99 is the max sample: keep the collector out
            # of the timed window (it is re-enabled before the confirm)
            gc.disable()
            try:
                t0 = time.perf_counter()
                kv_q.push(link_pub(a, b, down=True))
                await asyncio.wait_for(agent.programmed.wait(), timeout=60.0)
                frr_ms.append((agent.t_program - t0) * 1000.0)
            finally:
                gc.enable()
            print(
                f"frr flap {a}|{b}: {frr_ms[-1]:.3f} ms",
                file=sys.stderr,
                flush=True,
            )
            # the confirming warm solve is the authority — wait for it,
            # then hold the patched RIB against the scalar oracle
            await wait_for(
                lambda: d.counters.get("protection.confirms") > confirms0,
                "confirm",
            )
            await wait_for(
                lambda: d._change_seq >= s + 1 and d.rebuild_settled(),
                "flap settle",
            )
            oracle = ScalarBackend(SpfSolver("node0")).build_route_db(
                d.area_link_states, d.prefix_state
            )
            parity_checks += 1
            parity_ok = parity_ok and (
                route_db_summary(d.route_db) == route_db_summary(oracle)
            )
            await push_and_settle(
                [link_pub(a, b, down=False)], "restore"
            )
        deltas = {k: d.counters.get(k) - base[k] for k in counter_keys}

        # -- kill-after-shard-K resume: byte-identical table hash -----------
        def inputs_fn():
            return SweepInputs(**d.capacity_sweep_inputs())

        def run_builder(sub, kill_after=None, resume=False):
            b = ProtectionBuilder(
                inputs_fn,
                ProtectionStore(os.path.join(prot_dir, sub, "store")),
                d.solver,
                os.path.join(prot_dir, sub, "sweep"),
                counters=CounterMap(),
                shard_scenarios=32,
                max_links=FRR_MAX_LINKS,
            )
            rep = b.prepare(resume=resume)
            steps = 0
            while not b.finished():
                b.step(1)
                steps += 1
                if kill_after is not None and steps >= kill_after:
                    return rep, None
            return rep, b.finalize()

        _, clean = run_builder("clean")
        run_builder("killed", kill_after=1)
        rep_res, fin_res = run_builder("killed", resume=True)
        resume_detail = {
            "killed_after_shards": 1,
            "resumed": bool(rep_res.get("resumed")),
            "resumed_shards": int(rep_res.get("resumed_shards", 0)),
            "table_hash_byte_identical": (
                fin_res["table_hash"] == clean["table_hash"]
            ),
        }

        fallbacks = {
            "total": d.counters.get("protection.fallbacks"),
            "stale": d.counters.get("protection.fallback.stale"),
            "miss": d.counters.get("protection.fallback.miss"),
            "minting": d.counters.get("protection.fallback.minting"),
            "multi_failure": d.counters.get(
                "protection.fallback.multi_failure"
            ),
        }
        table_stats = {
            "patches": svc.table.patches,
            "eligible": svc.table.eligible,
            "mints": svc.table.num_mints,
        }
        await d.stop()
        await fib.stop()
        return (
            frr_ms,
            warm_ms,
            deltas,
            parity_checks,
            parity_ok,
            cold_mint_ms,
            mint_walls,
            fallbacks,
            table_stats,
            resume_detail,
        )

    loop = asyncio.new_event_loop()
    try:
        (
            frr_ms,
            warm_ms,
            deltas,
            parity_checks,
            parity_ok,
            cold_mint_ms,
            mint_walls,
            fallbacks,
            table_stats,
            resume_detail,
        ) = loop.run_until_complete(bench())
    finally:
        pending = asyncio.all_tasks(loop)
        for t in pending:
            t.cancel()
        if pending:
            loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True)
            )
        loop.close()
        shutil.rmtree(prot_dir, ignore_errors=True)

    def pct(xs, q):
        ys = sorted(xs)
        return ys[min(len(ys) - 1, int(round(q / 100.0 * (len(ys) - 1))))]

    p99 = round(pct(frr_ms, 99), 3)
    doc = {
        "metric": "frr_protected_flap_publication_to_fib_p99_ms_grid4096",
        "value": p99,
        "unit": "ms",
        "detail": {
            "world": {
                "nodes": n_nodes,
                "links": len(edges),
                "prefixes": n_nodes - 1,
                "topology": f"grid{side}x{side}",
            },
            "apply": {
                "flaps": len(frr_ms),
                "p50_ms": round(pct(frr_ms, 50), 3),
                "p95_ms": round(pct(frr_ms, 95), 3),
                "p99_ms": p99,
                "max_ms": round(max(frr_ms), 3),
                "samples_ms": [round(x, 3) for x in frr_ms],
                "applied": deltas["decision.frr_applied"],
                "fib_patches_applied": deltas["fib.frr_patches_applied"],
                "confirms": deltas["protection.confirms"],
                "mismatches": deltas["decision.frr_mismatches"],
                "scalar_parity": parity_ok,
                "parity_checks": parity_checks,
            },
            "warm": {
                "samples": len(warm_ms),
                "p50_ms": round(pct(warm_ms, 50), 3),
                "p99_ms": round(pct(warm_ms, 99), 3),
                "reference_p50_ms_r01": FRR_WARM_REFERENCE_P50_MS,
                "note": "same flap set with the protection tier "
                "detached: debounce + generation-delta warm rebuild + "
                "publish + FIB program; reference = BENCH_WARMSTART_r01 "
                "warm_p50_ms on the same grid4096 world",
            },
            "speedup": {
                "floor": FRR_SPEEDUP_FLOOR,
                "vs_reference_warm_p50": round(
                    FRR_WARM_REFERENCE_P50_MS / p99, 2
                ),
                "vs_inrun_warm_p50": round(pct(warm_ms, 50) / p99, 2),
            },
            "fallbacks": fallbacks,
            "mint": {
                "max_links": FRR_MAX_LINKS,
                "patches": table_stats["patches"],
                "eligible": table_stats["eligible"],
                "mints": table_stats["mints"],
                "cold_wall_ms": round(cold_mint_ms, 1),
                "warm_wall_p50_ms": round(pct(mint_walls, 50), 1),
                "coverage_pct": round(
                    FRR_MAX_LINKS / len(edges) * 100.0, 2
                ),
            },
            "resume": resume_detail,
            "seed": seed,
            "mode": (
                "real Decision (TPU backend) + Fib actors on the wall "
                "clock, delta kv publications; seeded heterogeneous "
                "link costs (unit-metric grids are pathological ECMP "
                "— interior flaps would mint empty patches); per-flap "
                "re-mint so every lookup is generation-exact; 8 "
                "forced host devices"
            ),
            "env": env_stamp(),
        },
    }
    try:
        validate_frr_bench(doc)
    except AssertionError:
        # the doc never reaches stdout on a failed gate — surface it on
        # stderr so the failing run is diagnosable from its log alone
        print(json.dumps(doc), file=sys.stderr, flush=True)
        raise
    print(json.dumps(doc))


# ---------------------------------------------------------------------------
# fleet compute fabric bench (--fleet-sweep / --fleet-streaming)
# ---------------------------------------------------------------------------

FLEET_BENCH_NODES = ("fab0", "fab1", "fab2")
FLEET_BENCH_SIDE = 4


def validate_fleet_bench(doc: dict) -> None:
    """Schema contract for BENCH_FLEET_r*.json — shared by the bench
    emitter, the tier-1 artifact gate and the benchtrack manifest.

    The ISSUE-19 acceptance, in-artifact: the 3-node fleet sweep's
    merged summary digest is byte-equal to the single-node run of the
    same scenario set; a mid-sweep node kill re-packs ONLY the victim's
    worlds onto survivors and still converges to the byte-identical
    digest AND fleet manifest; a mid-stream node kill migrates exactly
    the victim's watchers to their hash successors with zero
    monotone-generation invariant violations and no pre-migration
    generation re-emitted; a maintenance drain hands off cleanly (zero
    residual subscribers on the drained daemon); the whole chaos
    schedule replays byte-identically on the virtual clock.

    The ISSUE-20 liveness tier rides the same artifact: an UNANNOUNCED
    kill is concluded from heartbeat silence alone within the TTL
    bound (p50/max over phase-shifted samples), and the sweep still
    merges to the byte-identical digest with zero stream violations;
    an asymmetric partition's stale-epoch pushes are fenced, never
    double-delivered; stale-epoch sweep dispatches are fenced and
    re-packed; a straggling member's worlds re-pack first-committed-
    wins with the digest unchanged; a heartbeating-but-raising member
    is gray-demoted without crashing the coordinator; a flapping
    member is damped with ownership churn bounded to <=2 moves per
    flap cycle.  Every liveness chaos schedule replays
    byte-identically."""
    assert doc["metric"] == "fleet_sweep_merged_scenarios_per_s_3node"
    assert doc["unit"] == "scenarios/s"
    assert doc["value"] > 0
    d = doc["detail"]
    sw = d["sweep"]
    assert sw["nodes"] == len(FLEET_BENCH_NODES)
    assert sw["worlds"] >= 8
    assert sw["scenarios"] >= sw["worlds"]
    assert doc["value"] == sw["merged_scenarios_per_s"]
    assert sw["single_node_digest"]
    assert sw["fleet_digest"] == sw["single_node_digest"]
    assert sw["summary_digest_equal"] is True
    k = sw["kill"]
    assert k["victim"] in FLEET_BENCH_NODES
    assert k["repacked_worlds"] >= 1
    assert k["rounds"] >= 2
    assert k["digest_equal"] is True
    assert k["manifest_byte_identical"] is True
    st = d["streaming"]
    assert st["watchers"] >= 8
    assert st["migrated_watchers"] >= 1
    assert st["invariant_violations"] == 0
    assert st["pre_migration_generation_emissions"] == 0
    assert st["deterministic_replay"] is True
    dr = st["drain"]
    assert dr["migrated_watchers"] >= 1
    assert dr["invariant_violations"] == 0
    assert dr["residual_subscribers"] == 0
    # -- the ISSUE-20 liveness tier: self-hosted membership ------------
    lv = d["liveness"]
    hb = lv["heartbeat"]
    assert 0 < hb["interval_s"] < hb["suspect_after_s"] < hb["ttl_s"]
    det = lv["detection"]
    assert det["samples"] >= 3
    assert 0 < det["p50_s"] <= det["max_s"] <= det["bound_s"]
    uk = lv["unannounced_kill"]
    assert uk["victim"] in FLEET_BENCH_NODES
    assert uk["detection_s"] > 0
    assert uk["suspects_seen"] >= 1
    assert uk["repacked_worlds"] >= 1
    assert uk["digest_equal"] is True
    assert uk["manifest_byte_identical"] is True
    assert uk["invariant_violations"] == 0
    assert uk["pre_migration_generation_emissions"] == 0
    assert uk["deterministic_replay"] is True
    sb = lv["split_brain"]
    assert sb["victim"] in FLEET_BENCH_NODES
    assert sb["fenced_stream_deliveries"] >= 1
    assert sb["invariant_violations"] == 0
    assert sb["double_pushes"] == 0
    assert sb["healed_stale_subscriptions"] == 0
    assert sb["deterministic_replay"] is True
    fe = lv["epoch_fence"]
    assert fe["fenced_worlds"] >= 1
    assert fe["digest_equal"] is True
    assert fe["manifest_byte_identical"] is True
    sg = lv["straggler"]
    assert sg["straggler_repacks"] >= 1
    assert sg["duplicate_completions"] >= 1
    assert sg["digest_equal"] is True
    assert sg["manifest_byte_identical"] is True
    gr = lv["gray_failure"]
    assert gr["victim"] in FLEET_BENCH_NODES
    assert gr["demotions"] >= 1
    assert gr["coordinator_crashes"] == 0
    assert gr["ticket_firing"] is True
    assert gr["digest_equal"] is True
    fl = lv["flap"]
    assert fl["flap_damped"] >= 1
    assert fl["flap_cycles"] >= 2
    assert fl["max_watcher_migrations"] <= 2 * fl["flap_cycles"]
    assert fl["invariant_violations"] == 0
    for key in ("seed", "mode", "env"):
        assert key in d, key
    for key in ("platform", "jax", "device_count"):
        assert key in d["env"], f"env.{key}"
    assert d["env"]["device_count"] >= 1


def _fleet_bench_doc(seed: Optional[int]) -> dict:
    """Measure both fleet halves over one FleetFabric world and build
    the combined BENCH_FLEET document.  Everything runs on the SimClock
    (chaos schedules are replayable); only the headline merge rate is
    wall-clock."""
    import asyncio
    import shutil
    import tempfile

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.emulation.fabric import FleetFabric
    from openr_tpu.sweep import SweepExecutor
    from openr_tpu.sweep.scenario import ScenarioSpec

    seed = 7 if seed is None else int(seed)
    params = {
        "drain_node_sets": [
            [], ["node5"], ["node7"], ["node3"], ["node11"], ["node13"],
        ],
        "metric_perturbations": [{"pattern": "node.*", "factor": 2.0}],
        "combo_k": 2,
        "max_combo_scenarios": 8,
        "combo_seed": seed,
    }
    root = tempfile.mkdtemp(prefix="bench_fleet_")

    def make_fabric(sub: str, **kw) -> "tuple":
        clock = SimClock()
        fab = FleetFabric(
            clock,
            spill_root=f"{root}/{sub}",
            node_names=FLEET_BENCH_NODES,
            n_side=FLEET_BENCH_SIDE,
            sweep_overrides={
                "shard_scenarios": 8, "inter_shard_pause_s": 0.05,
            },
            **kw,
        )
        return clock, fab

    async def drive_sweep(fab, clock, kill=False):
        """Pump one fleet sweep to completion; with ``kill``, crash the
        first member seen with a running sub-sweep (rendezvous decides
        who holds worlds under this grammar, so the victim is picked by
        observation, not by name)."""
        fab.coordinator.prepare(params)
        fab.coordinator.start()
        victim = None
        for _ in range(20000):
            await clock.run_for(0.05)
            st = fab.coordinator.status()
            if kill and victim is None:
                running = [
                    t["node"] for t in st["assignments"]
                    if t["state"] == "running"
                ]
                if running:
                    victim = running[0]
                    await fab.kill_node(victim)
            if fab.coordinator.state != "running":
                break
        assert fab.coordinator.state == "done", fab.coordinator.state
        if kill:
            assert victim is not None, "kill window never opened"
        s = fab.coordinator.summary()
        return (
            s["summary_digest"],
            fab.coordinator.manifest_bytes(),
            fab.coordinator.status(),
            victim,
        )

    async def sweep_half():
        # single-node reference: the same grammar through one executor
        clock, fab = make_fabric("single")
        fab.start()
        await clock.run_for(2.0)
        svc = fab.nodes["fab0"].sweep
        spec = ScenarioSpec.from_params(svc.config, params)
        ex = SweepExecutor(
            svc._inputs, f"{root}/single/ref", clock=clock,
            shard_scenarios=64,
        )
        ex.prepare(spec, resume=False)
        ex.run()
        single_digest = ex.reducer.summary_digest()
        await fab.stop()

        # the clean 3-node fleet run (wall-clocked for the headline)
        clock, fab = make_fabric("clean")
        fab.start()
        await clock.run_for(2.0)
        t0 = time.perf_counter()
        digest, manifest, st, _ = await drive_sweep(fab, clock)
        wall_s = time.perf_counter() - t0
        await fab.stop()

        # the chaos run: kill one member while its sub-sweep runs
        clock, fab = make_fabric("killed")
        fab.start()
        await clock.run_for(2.0)
        kdigest, kmanifest, kst, victim = await drive_sweep(
            fab, clock, kill=True
        )
        await fab.stop()
        return digest, manifest, {
            "nodes": len(FLEET_BENCH_NODES),
            "worlds": st["worlds_total"],
            "scenarios": st["scenarios_total"],
            "merge_wall_ms": round(wall_s * 1000.0, 1),
            "merged_scenarios_per_s": round(
                st["scenarios_total"] / wall_s, 1
            ),
            "single_node_digest": single_digest,
            "fleet_digest": digest,
            "summary_digest_equal": digest == single_digest,
            "kill": {
                "victim": victim,
                "repacked_worlds": kst["repacked_worlds"],
                "rounds": kst["rounds"],
                "digest_equal": kdigest == digest,
                "manifest_byte_identical": kmanifest == manifest,
            },
        }

    async def stream_scenario(sub: str, drain_instead: bool = False):
        clock, fab = make_fabric(sub)
        fab.start()
        await clock.run_for(2.0)
        n_watch = 12
        watchers = [
            fab.router.watch("route_db", {"node": f"node{i}"})
            for i in range(n_watch)
        ]
        await clock.run_for(1.0)
        fab.announce_prefix("node2", "10.99.0.0/24")
        await clock.run_for(2.0)
        placement = {}
        for w in watchers:
            placement.setdefault(w.serving_node, []).append(w)
        victim = max(placement, key=lambda n: len(placement[n]))
        if drain_instead:
            fab.drain_node(victim)
        else:
            await fab.kill_node(victim)
        await clock.run_for(1.0)
        fab.announce_prefix("node0", "10.98.0.0/24")
        await clock.run_for(2.0)
        out = {
            "watchers": n_watch,
            "victim": victim,
            "migrated_watchers": len(placement[victim]),
            "invariant_violations": fab.router.invariant_violations(),
            "pre_migration_generation_emissions": (
                fab.router.pre_migration_re_emissions()
            ),
            "log": b"\x00".join(w.log_bytes() for w in watchers),
        }
        if drain_instead:
            stats = fab.nodes[victim].streaming.stats()
            out["residual_subscribers"] = sum(
                f["subscribers"] for f in stats["feeds"]
            )
        await fab.stop()
        return out

    async def streaming_half():
        a = await stream_scenario("skill_a")
        b = await stream_scenario("skill_b")
        dr = await stream_scenario("sdrain", drain_instead=True)
        return {
            "watchers": a["watchers"],
            "victim": a["victim"],
            "migrated_watchers": a["migrated_watchers"],
            "invariant_violations": a["invariant_violations"],
            "pre_migration_generation_emissions": (
                a["pre_migration_generation_emissions"]
            ),
            "deterministic_replay": (
                a["victim"] == b["victim"] and a["log"] == b["log"]
            ),
            "drain": {
                "victim": dr["victim"],
                "migrated_watchers": dr["migrated_watchers"],
                "invariant_violations": dr["invariant_violations"],
                "residual_subscribers": dr["residual_subscribers"],
            },
        }

    # -- the ISSUE-20 liveness tier: compressed heartbeat timers so the
    #    suspicion machine runs its whole arc inside seconds of virtual
    #    time (the production defaults only stretch the same schedule)
    fast_liveness = {
        "heartbeat_interval_s": 0.1,
        "suspect_after_s": 0.25,
        "heartbeat_ttl_s": 0.5,
        "tick_s": 0.05,
    }

    async def detect_once(sub: str, k: int) -> float:
        """Kill one member UNANNOUNCED at a phase offset off the
        heartbeat grid and time how long heartbeat silence alone takes
        to conclude the death (suspect -> TTL expiry -> down)."""
        clock, fab = make_fabric(
            sub, liveness_overrides=dict(fast_liveness)
        )
        fab.start()
        await clock.run_for(2.0 + 0.013 + 0.037 * k)
        victim = FLEET_BENCH_NODES[k % len(FLEET_BENCH_NODES)]
        await fab.kill_node_unannounced(victim)
        t_kill = clock.now()
        t_detect = None
        for _ in range(400):
            await clock.run_for(0.01)
            if not fab.membership.is_live(victim):
                t_detect = clock.now()
                break
        assert t_detect is not None, "liveness never concluded the kill"
        await fab.stop()
        return round(t_detect - t_kill, 6)

    async def unannounced_scenario(sub: str) -> dict:
        """The detection-tier acceptance: a mid-sweep member killed
        with membership told NOTHING — heartbeat silence re-packs its
        worlds and migrates its watchers, digest/manifest byte-equal."""
        clock, fab = make_fabric(
            sub, liveness_overrides=dict(fast_liveness)
        )
        fab.start()
        await clock.run_for(2.0)
        watchers = [
            fab.router.watch("route_db", {"node": f"node{i}"})
            for i in range(8)
        ]
        await clock.run_for(1.0)
        fab.coordinator.prepare(params)
        fab.coordinator.start()
        victim = t_kill = t_detect = None
        for _ in range(20000):
            await clock.run_for(0.05)
            st = fab.coordinator.status()
            if victim is None:
                running = sorted(
                    t["node"] for t in st["assignments"]
                    if t["state"] == "running"
                )
                if running:
                    victim = running[0]
                    await fab.kill_node_unannounced(victim)
                    t_kill = clock.now()
            elif t_detect is None and not fab.membership.is_live(victim):
                t_detect = clock.now()
                # churn after detection: the migrated watchers must
                # keep applying deltas with the invariants intact
                fab.announce_prefix("node0", "10.95.0.0/24")
            if fab.coordinator.state != "running":
                break
        assert fab.coordinator.state == "done", fab.coordinator.state
        assert victim is not None and t_detect is not None
        await clock.run_for(1.0)
        st = fab.coordinator.status()
        out = {
            "victim": victim,
            "detection_s": round(t_detect - t_kill, 6),
            "suspects_seen": fab.counters.get("fleet.membership.suspect"),
            "repacked_worlds": st["repacked_worlds"],
            "digest": fab.coordinator.summary()["summary_digest"],
            "manifest": fab.coordinator.manifest_bytes(),
            "violations": fab.router.invariant_violations(),
            "re_emissions": fab.router.pre_migration_re_emissions(),
            "log": b"\x00".join(w.log_bytes() for w in watchers),
        }
        await fab.stop()
        return out

    async def split_brain_scenario(sub: str) -> dict:
        """Asymmetric partition: the victim's heartbeats stop REACHING
        the tracker while its services keep pushing — every stale-epoch
        delivery must be fenced, never applied, never doubled."""
        clock, fab = make_fabric(
            sub, liveness_overrides=dict(fast_liveness)
        )
        fab.start()
        await clock.run_for(2.0)
        watchers = [
            fab.router.watch("route_db", {"node": f"node{i}"})
            for i in range(12)
        ]
        await clock.run_for(1.0)
        placement = {}
        for w in watchers:
            placement.setdefault(w.serving_node, []).append(w)
        victim = max(sorted(placement), key=lambda n: len(placement[n]))
        fab.partition_asymmetric(victim)
        await clock.run_for(1.0)
        assert not fab.membership.is_live(victim)
        assert fab.nodes[victim].running  # daemon alive: asymmetric
        # churn: EVERY service pushes, including the stale owner
        fab.announce_prefix("node1", "10.94.0.0/24")
        await clock.run_for(1.0)
        out = {
            "victim": victim,
            "fenced_stream": fab.router.fenced_deliveries(),
            "violations": fab.router.invariant_violations(),
            "re_emissions": fab.router.pre_migration_re_emissions(),
        }
        fab.heal_partition(victim)
        await clock.run_for(1.0)
        out["healed_live"] = fab.membership.is_live(victim)
        out["stale_after_heal"] = (
            fab.router.status()["stale_subscriptions"]
        )
        fab.announce_prefix("node2", "10.93.0.0/24")
        await clock.run_for(1.0)
        out["violations"] = fab.router.invariant_violations()
        out["log"] = b"\x00".join(w.log_bytes() for w in watchers)
        await fab.stop()
        return out

    async def epoch_fence_scenario(sub: str) -> dict:
        """Dispatches stamped under a pre-kill epoch are refused by the
        receivers (counted, returned, never raised) and re-packed at
        the current epoch — the digest contract survives the fence."""
        clock, fab = make_fabric(sub)
        fab.start()
        await clock.run_for(2.0)
        fab.coordinator.prepare(params)
        holder = sorted({t.node for t in fab.coordinator.tasks})[0]
        await fab.kill_node(holder)
        fab.coordinator.start()
        for _ in range(20000):
            await clock.run_for(0.05)
            if fab.coordinator.state != "running":
                break
        assert fab.coordinator.state == "done", fab.coordinator.state
        st = fab.coordinator.status()
        out = {
            "fenced_worlds": st["fenced_worlds"],
            "sweep_fence_rejections": sum(
                f.counters.get("fleet.fenced.sweep_rejected") or 0
                for f in fab.nodes.values()
            ),
            "digest": fab.coordinator.summary()["summary_digest"],
            "manifest": fab.coordinator.manifest_bytes(),
        }
        await fab.stop()
        return out

    async def straggler_scenario(sub: str) -> dict:
        """The busiest member turns slow mid-round; its unfinished
        worlds re-pack past ``straggler_deadline_s`` WITHOUT declaring
        it dead, and merge reconciles first-committed-wins."""
        clock, fab = make_fabric(
            sub,
            # above the busiest member's natural round (~1.2s virtual:
            # half the 384-scenario grammar at 8/shard x 0.05s), below
            # the wedged member's never-finishing round
            coordinator_overrides={"straggler_deadline_s": 2.0},
        )
        fab.start()
        await clock.run_for(2.0)
        fab.coordinator.prepare(params)
        counts = {}
        for t in fab.coordinator.tasks:
            counts[t.node] = counts.get(t.node, 0) + len(t.worlds)
        slow = max(sorted(counts), key=lambda n: counts[n])
        fab.nodes[slow].sweep.config.inter_shard_pause_s = 60.0
        fab.coordinator.start()
        for _ in range(20000):
            await clock.run_for(0.05)
            if fab.coordinator.state != "running":
                break
        assert fab.coordinator.state == "done", fab.coordinator.state
        st = fab.coordinator.status()
        out = {
            "straggler": slow,
            "straggler_repacks": st["straggler_repacks"],
            "repacked_worlds": st["straggler_repacked_worlds"],
            "duplicate_completions": st["duplicate_completions"],
            "digest": fab.coordinator.summary()["summary_digest"],
            "manifest": fab.coordinator.manifest_bytes(),
        }
        await fab.stop()
        return out

    async def gray_scenario(sub: str) -> dict:
        """Gray failure: heartbeats keep flowing while the victim's
        sweep ctrl surface raises on every touch — the breaker + strike
        policy demotes it to drained, the survivors finish."""
        clock, fab = make_fabric(sub)
        fab.start()
        await clock.run_for(2.0)
        fab.coordinator.prepare(params)
        fab.coordinator.start()
        victim = None
        for _ in range(20000):
            await clock.run_for(0.05)
            st = fab.coordinator.status()
            if victim is None:
                running = sorted(
                    t["node"] for t in st["assignments"]
                    if t["state"] == "running"
                )
                if running:
                    victim = running[0]
                    fab.gray_sweep_failure(victim)
            if fab.coordinator.state != "running":
                break
        assert fab.coordinator.state == "done", fab.coordinator.state
        assert victim is not None
        firing = fab.membership.health_firing()
        out = {
            "victim": victim,
            "demotions": fab.counters.get("fleet.gray.demotions"),
            "ctrl_errors": fab.counters.get("fleet.ctrl.errors"),
            "crashes": fab.counters.get("fleet.crash") or 0,
            "drained_still_up": (
                fab.membership.is_up(victim)
                and not fab.membership.is_live(victim)
            ),
            "ticket_firing": "fleet_gray_failure" in firing,
            "digest": fab.coordinator.summary()["summary_digest"],
            "manifest": fab.coordinator.manifest_bytes(),
        }
        await fab.stop()
        return out

    async def flap_scenario(sub: str) -> dict:
        """A member bouncing inside the flap window is DAMPED with an
        exponential hold, bounding ownership churn to <=2 moves per
        flap cycle (one out, one back)."""
        cycles = 2
        clock, fab = make_fabric(
            sub,
            liveness_overrides={
                **fast_liveness,
                "flap_hold_base_s": 1.0,
                "flap_hold_max_s": 4.0,
                "flap_window_s": 30.0,
            },
        )
        fab.start()
        await clock.run_for(2.0)
        watchers = [
            fab.router.watch("route_db", {"node": f"node{i}"})
            for i in range(12)
        ]
        await clock.run_for(1.0)
        placement = {}
        for w in watchers:
            placement.setdefault(w.serving_node, []).append(w)
        victim = max(sorted(placement), key=lambda n: len(placement[n]))
        epoch0 = fab.membership.epoch
        for _ in range(cycles):
            fab.heartbeat_stall(victim)
            await clock.run_for(0.8)  # past the TTL: down
            fab.heal_heartbeat(victim)
            await clock.run_for(0.3)
        # ride out the exponential hold of the damped rejoin, plus the
        # tick that readmits once the hold expires with beats flowing
        await clock.run_for(3.0)
        assert fab.membership.is_live(victim)
        out = {
            "victim": victim,
            "flap_cycles": cycles,
            "flap_damped": fab.counters.get("fleet.flap_damped"),
            "epoch_bumps": fab.membership.epoch - epoch0,
            "max_watcher_migrations": max(
                w.migrations for w in watchers
            ),
            "violations": fab.router.invariant_violations(),
        }
        await fab.stop()
        return out

    async def liveness_half(clean_digest, clean_manifest) -> dict:
        det = [
            await detect_once(f"live_det{k}", k) for k in range(5)
        ]
        det_sorted = sorted(det)
        uk_a = await unannounced_scenario("live_uk_a")
        uk_b = await unannounced_scenario("live_uk_b")
        sb_a = await split_brain_scenario("live_sb_a")
        sb_b = await split_brain_scenario("live_sb_b")
        fe = await epoch_fence_scenario("live_fence")
        sg = await straggler_scenario("live_strag")
        gr = await gray_scenario("live_gray")
        fl = await flap_scenario("live_flap")
        return {
            "heartbeat": {
                "interval_s": fast_liveness["heartbeat_interval_s"],
                "suspect_after_s": fast_liveness["suspect_after_s"],
                "ttl_s": fast_liveness["heartbeat_ttl_s"],
                "tick_s": fast_liveness["tick_s"],
            },
            "detection": {
                "samples": len(det),
                "p50_s": det_sorted[len(det_sorted) // 2],
                "max_s": det_sorted[-1],
                # TTL from the last pre-kill beat + one tracker tick +
                # the harness sampling step
                "bound_s": round(
                    fast_liveness["heartbeat_ttl_s"]
                    + fast_liveness["tick_s"]
                    + 0.02,
                    6,
                ),
            },
            "unannounced_kill": {
                "victim": uk_a["victim"],
                "detection_s": uk_a["detection_s"],
                "suspects_seen": uk_a["suspects_seen"],
                "repacked_worlds": uk_a["repacked_worlds"],
                "digest_equal": uk_a["digest"] == clean_digest,
                "manifest_byte_identical": (
                    uk_a["manifest"] == clean_manifest
                ),
                "invariant_violations": uk_a["violations"],
                "pre_migration_generation_emissions": (
                    uk_a["re_emissions"]
                ),
                "deterministic_replay": (
                    uk_a["victim"] == uk_b["victim"]
                    and uk_a["detection_s"] == uk_b["detection_s"]
                    and uk_a["digest"] == uk_b["digest"]
                    and uk_a["manifest"] == uk_b["manifest"]
                    and uk_a["log"] == uk_b["log"]
                ),
            },
            "split_brain": {
                "victim": sb_a["victim"],
                "fenced_stream_deliveries": sb_a["fenced_stream"],
                "invariant_violations": sb_a["violations"],
                "double_pushes": sb_a["re_emissions"],
                "healed_rejoined": sb_a["healed_live"],
                "healed_stale_subscriptions": sb_a["stale_after_heal"],
                "deterministic_replay": (
                    sb_a["victim"] == sb_b["victim"]
                    and sb_a["log"] == sb_b["log"]
                ),
            },
            "epoch_fence": {
                "fenced_worlds": fe["fenced_worlds"],
                "sweep_fence_rejections": fe["sweep_fence_rejections"],
                "digest_equal": fe["digest"] == clean_digest,
                "manifest_byte_identical": (
                    fe["manifest"] == clean_manifest
                ),
            },
            "straggler": {
                "straggler": sg["straggler"],
                "straggler_repacks": sg["straggler_repacks"],
                "repacked_worlds": sg["repacked_worlds"],
                "duplicate_completions": sg["duplicate_completions"],
                "digest_equal": sg["digest"] == clean_digest,
                "manifest_byte_identical": (
                    sg["manifest"] == clean_manifest
                ),
            },
            "gray_failure": {
                "victim": gr["victim"],
                "demotions": gr["demotions"],
                "ctrl_errors": gr["ctrl_errors"],
                "coordinator_crashes": gr["crashes"],
                "drained_still_up": gr["drained_still_up"],
                "ticket_firing": gr["ticket_firing"],
                "digest_equal": gr["digest"] == clean_digest,
            },
            "flap": {
                "victim": fl["victim"],
                "flap_cycles": fl["flap_cycles"],
                "flap_damped": fl["flap_damped"],
                "epoch_bumps": fl["epoch_bumps"],
                "max_watcher_migrations": fl["max_watcher_migrations"],
                "invariant_violations": fl["violations"],
            },
        }

    try:
        clean_digest, clean_manifest, sweep_detail = asyncio.run(
            sweep_half()
        )
        streaming_detail = asyncio.run(streaming_half())
        liveness_detail = asyncio.run(
            liveness_half(clean_digest, clean_manifest)
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "metric": "fleet_sweep_merged_scenarios_per_s_3node",
        "value": sweep_detail["merged_scenarios_per_s"],
        "unit": "scenarios/s",
        "detail": {
            "sweep": sweep_detail,
            "streaming": streaming_detail,
            "liveness": liveness_detail,
            "seed": seed,
            "mode": (
                "3 fleet members (serving+streaming+sweep) over one "
                "shared scalar decision on a grid16 LSDB, SimClock; "
                "content-derived world assignment (rendezvous over the "
                "scenario-set hash), sub-sweeps merged through the "
                "feed-order-independent reducer; chaos = mid-sweep "
                "member kill + mid-stream kill/drain via the fleet "
                "membership plane, plus the ISSUE-20 liveness tier "
                "(compressed heartbeat timers): unannounced kill, "
                "asymmetric partition, stale-epoch fencing, straggler "
                "re-pack, gray-failure demotion, flap damping"
            ),
            "env": env_stamp(),
        },
    }


def fleet_sweep_main(seed: Optional[int] = None) -> None:
    """Fleet compute-fabric benchmark (BENCH_FLEET_r*), sweep-first
    entry point.  The fabric's two halves share the membership/
    directory core, so either entry point measures BOTH and emits the
    one combined artifact — benching a half alone would skip exactly
    the coupling the acceptance gates (a membership transition must
    re-pack worlds AND migrate watchers off the same event)."""
    doc = _fleet_bench_doc(seed)
    try:
        validate_fleet_bench(doc)
    except AssertionError:
        print(json.dumps(doc), file=sys.stderr, flush=True)
        raise
    print(json.dumps(doc))


def fleet_streaming_main(seed: Optional[int] = None) -> None:
    """Fleet compute-fabric benchmark (BENCH_FLEET_r*), streaming-first
    entry point — same combined measurement as --fleet-sweep (see
    fleet_sweep_main for why the halves are never benched apart)."""
    fleet_sweep_main(seed)


def fleet_liveness_main(seed: Optional[int] = None) -> None:
    """Fleet compute-fabric benchmark (BENCH_FLEET_r*), liveness-first
    entry point — same combined measurement as --fleet-sweep: the
    liveness tier's kill-detection/fencing/straggler/gray/flap
    scenarios share the membership plane the other halves gate, so the
    one artifact carries all three sections."""
    fleet_sweep_main(seed)


def main() -> None:
    t_start = time.time()
    from openr_tpu.ops.platform_env import (
        enable_persistent_compile_cache,
        fallback_to_cpu_if_unreachable,
        honor_cpu_platform_request,
    )

    honor_cpu_platform_request()
    fallback_to_cpu_if_unreachable()
    enable_persistent_compile_cache()
    from openr_tpu.ops.native_spf import NativeSpf
    from openr_tpu.ops.whatif import LinkFailureSweep

    import jax

    # ---- the 1024-node WAN + 10,240 perturbations ------------------------
    n_nodes = 1024
    total = 10_240
    ls, topo, cands = build_headline_world(n_nodes)
    rng = np.random.default_rng(0)
    fails = rng.integers(0, len(topo.links), size=total).astype(np.int32)

    # ---- native C++ single-threaded baselines (median of N + spread) -----
    native = NativeSpf(topo, "node0")
    native.sweep(fails[:32])  # warm caches
    naive_times = []
    for _ in range(NATIVE_REPS):
        t0 = time.perf_counter()
        native.sweep(fails)
        naive_times.append(time.perf_counter() - t0)
    native_naive_s = statistics.median(naive_times)
    native_sps = total / native_naive_s
    uniq = np.unique(fails)
    dedup_times = []
    for _ in range(NATIVE_REPS):
        t0 = time.perf_counter()
        native.sweep(uniq)
        dedup_times.append(time.perf_counter() - t0)
    native_dedup_sps = total / statistics.median(dedup_times)
    # native warm-start: same incremental-repair trick as the device
    native.warm_prepare()
    native.warm_sweep(fails[:32])
    warm_times = []
    for _ in range(NATIVE_REPS):
        t0 = time.perf_counter()
        native.warm_sweep(fails)
        warm_times.append(time.perf_counter() - t0)
    native_warm_sps = total / statistics.median(warm_times)

    # ---- native ENGINE end to end: the operator alternative --------------
    # C++ warm-start sweep per unique on-DAG failure + numpy selection +
    # diff vs the base route table — exactly what the Decision what-if
    # API runs when it picks the native engine, with the same dedup and
    # off-DAG-alias courtesies the device pipeline gets (an off-DAG
    # failure provably changes no routes).  This is the most demanding
    # apples-to-apples denominator: same algorithm, same output.
    from openr_tpu.ops.np_select import select_routes_numpy
    from openr_tpu.ops.whatif import root_lane_count

    sel_args_np = (
        cands.cand_node,
        cands.cand_ok,
        cands.drain_metric,
        cands.path_pref,
        cands.source_pref,
        cands.distance,
        cands.min_nexthop,
    )
    soft_np = np.zeros(topo.padded_nodes, np.int32)
    root_np = topo.node_id("node0")
    D_eng = root_lane_count(topo, root_np)  # == LinkFailureSweep.D
    uniq_on = uniq[native.link_on_dag[uniq].astype(bool)]
    bdist_n, bmask_n = native.warm_base
    blanes_n = native.lanes_dense(D_eng, mask=bmask_n)
    bvalid, bmetric, bnh, _, _ = select_routes_numpy(
        *sel_args_np, bdist_n, blanes_n, topo.overloaded, soft_np, root_np
    )
    native_e2e_times = []
    native_route_deltas = 0
    for _ in range(NATIVE_REPS):
        t0 = time.perf_counter()
        native_route_deltas = 0
        for link in uniq_on:
            native.warm_sweep(
                np.asarray([link], np.int32), keep_last=True
            )
            lanes = native.lanes_dense(D_eng)
            v, m, nh, _n, _u = select_routes_numpy(
                *sel_args_np, native.dist, lanes,
                topo.overloaded, soft_np, root_np,
            )
            changed = (v != bvalid) | (
                v & bvalid & (
                    (m != bmetric) | (nh != bnh).any(axis=1)
                )
            )
            native_route_deltas += int(changed.sum())
        native_e2e_times.append(time.perf_counter() - t0)
    native_e2e_sps = total / statistics.median(native_e2e_times)

    # ---- pure-Python oracle (round-1's flattering denominator) -----------
    ls.run_spf("node0", links_to_ignore=frozenset([topo.links[0]]))
    best = float("inf")
    for rep in range(3):
        t0 = time.perf_counter()
        for i in range(8):
            link = topo.links[int(fails[rep * 8 + i])]
            ls.run_spf("node0", links_to_ignore=frozenset([link]))
        best = min(best, (time.perf_counter() - t0) / 8)
    python_sps = 1.0 / best

    # ---- device: engine setup (base solve + repair plan) -----------------
    import jax.numpy as jnp

    from openr_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()  # all local devices (1 on the bench chip) —
    # the SAME shard_map path dryrun_multichip runs on 8
    eng = LinkFailureSweep(topo, "node0", mesh=mesh)
    t0 = time.perf_counter()
    eng.base_solve()
    base_solve_ms = (time.perf_counter() - t0) * 1000
    t0 = time.perf_counter()
    eng.plan()
    plan_build_ms = (time.perf_counter() - t0) * 1000
    rs = eng.repair_sweep()

    # measure the tunnel/dispatch sync cost once, for the detail split
    (jnp.zeros(8) + 1).block_until_ready()
    t0 = time.perf_counter()
    (jnp.zeros(8) + 1).block_until_ready()
    sync_ms = (time.perf_counter() - t0) * 1000

    # ---- device raw: every snapshot solved via the repair kernel ---------
    from openr_tpu.ops.repair import sort_by_depth

    chunk = 4096
    g = eng.batch_granularity
    sfails, _ = sort_by_depth(eng.plan(), fails)

    def raw_sweep(fl):
        outs = []
        for off in range(0, total, chunk):
            c = fl[off : off + chunk]
            if len(c) % g:
                c = np.concatenate(
                    [c, np.full(g - len(c) % g, -1, np.int32)]
                )
            outs.append(rs.solve(c))
        return outs

    outs = raw_sweep(sfails)
    # jit warm-up, excluded from the timer — including the per-rep
    # reduction kernels the barrier below uses (their first-call
    # compiles would otherwise land inside the timed region)
    jax.block_until_ready(
        [jax.tree.map(lambda a: a.sum(), o) for o in outs]
    )
    t0 = time.perf_counter()
    rep_sums = []
    for _ in range(DEVICE_REPS):
        outs = raw_sweep(sfails)
        # per-rep scalar reductions: their readiness implies every chunk
        # of the rep completed (a last-buffer-only barrier once reported
        # a nonsense 9.9M solves/s when the experimental axon runtime
        # signaled a later buffer early), without keeping all reps'
        # full-size outputs live on device inside the timed region
        rep_sums.append(
            [jax.tree.map(lambda a: a.sum(), o) for o in outs]
        )
    jax.block_until_ready(rep_sums)
    device_raw_sps = DEVICE_REPS * total / (time.perf_counter() - t0)
    raw_rounds = [
        (int(np.max(o[2])), int(np.max(o[3]))) for o in outs
    ]  # per-device maxima under the sharded kernel

    # ---- device cold kernel (round-2's raw path, for transparency) -------
    from openr_tpu.ops.spf import sweep_spf_link_failures

    D_cold = topo.max_out_degree()
    cold_args = (
        jnp.asarray(topo.src),
        jnp.asarray(topo.dst),
        jnp.asarray(topo.w),
        jnp.asarray(topo.edge_ok),
        jnp.asarray(topo.link_index),
    )
    ovl = jnp.asarray(topo.overloaded)
    root = jnp.int32(topo.node_id("node0"))

    def cold_sweep():
        last = None
        for off in range(0, total, 2048):
            f = jnp.asarray(fails[off : off + 2048])
            d, nh = sweep_spf_link_failures(
                *cold_args, f, ovl, root, max_degree=D_cold, packed=True
            )
            last = d
        return last

    cold_sweep().block_until_ready()
    t0 = time.perf_counter()
    last = None
    for _ in range(DEVICE_REPS):
        last = cold_sweep()
    last.block_until_ready()
    device_cold_sps = DEVICE_REPS * total / (time.perf_counter() - t0)

    # ---- device: SPF-tables-only engine throughput (detail line) ---------
    res = eng.run(fails, fetch=False)
    res.block()  # warm-up (compiles the bucket shapes)
    t0 = time.perf_counter()
    results = [eng.run(fails, fetch=False) for _ in range(DEVICE_REPS)]
    results[-1].block()
    engine_sps = DEVICE_REPS * total / (time.perf_counter() - t0)
    # single-shot latency (what one cold rebuild tick would see)
    t0 = time.perf_counter()
    single = eng.run(fails, fetch=False)
    single.block()
    engine_latency_ms = (time.perf_counter() - t0) * 1000

    # ---- THE HEADLINE: sweep -> route deltas, end to end -----------------
    # (ops/sweep_select.py): 1024 loopback prefixes selected against every
    # snapshot ON DEVICE, diffed vs the base route table on device, only
    # changed route rows cross the tunnel; every chunk's selection kernel
    # is dispatched before the first blocking fetch so selection of chunk
    # k overlaps SPF of chunk k+1
    from openr_tpu.ops.sweep_select import SweepRouteSelector

    sel = SweepRouteSelector(
        topo,
        "node0",
        cands,
        max_degree=eng.D,
        mesh=mesh,
    )
    deltas = sel.run(single)  # warm-up (compiles chunk + compact shapes)
    # single-shot latency: what ONE operator sweep experiences
    t0 = time.perf_counter()
    deltas = sel.run(eng.run(fails, fetch=False))
    routes_pipeline_ms = (time.perf_counter() - t0) * 1000
    # steady-state throughput: PIPELINE_DEPTH sweeps in flight via
    # sel.start()/finish() — selection+compaction fetches ride
    # copy_to_host_async, so the ~75 ms tunnel round trip overlaps the
    # following sweeps' SPF+selection instead of serializing after them
    # (the continuous-what-if-service shape; device compute per sweep is
    # single-digit ms, so without overlap the tunnel latency IS the
    # pipeline floor)
    # the two end-to-end pipelines must find the IDENTICAL delta count —
    # computed independently (C++ sweep + numpy select vs device repair
    # kernel + on-device select + fused compaction); asserted on the
    # same failure set the native engine ran
    assert int(deltas.num_deltas) == native_route_deltas, (
        deltas.num_deltas,
        native_route_deltas,
    )
    # steady-state reps use FRESH random failure sets each (r4 review
    # weak #5: one reused set flatters caching; the 3-minute soak's
    # honest fresh-sets number now IS the committed headline's shape)
    PIPELINE_DEPTH = 4
    e2e_reps = 12
    rng_reps = np.random.default_rng(20260730)
    # rep 0 re-runs the native engine's failure set so the ASYNC
    # (copy_to_host_async) pipeline path stays correctness-validated
    # against the native delta count, not just the synchronous run
    rep_fails = [fails] + [
        rng_reps.integers(0, len(topo.links), size=total).astype(np.int32)
        for _ in range(e2e_reps - 1)
    ]
    t0 = time.perf_counter()
    pend = []
    finished = []
    for r in range(e2e_reps):
        sw = eng.run(rep_fails[r], fetch=False)
        pend.append(sel.start(sw))
        if len(pend) >= PIPELINE_DEPTH:
            finished.append(pend.pop(0).finish())
    while pend:
        finished.append(pend.pop(0).finish())
    e2e_sps = e2e_reps * total / (time.perf_counter() - t0)
    assert int(finished[0].num_deltas) == native_route_deltas, (
        finished[0].num_deltas,
        native_route_deltas,
    )
    # sanity on every fresh-set rep: a 10k random sweep of this world
    # always changes SOME routes, and can never exceed the full table
    assert all(
        0 < int(d.num_deltas) <= total * n_nodes for d in finished
    ), [int(d.num_deltas) for d in finished]

    # route parity vs native for sample snapshots (base + changed rows)
    for s in (3, 1007, 9000):
        native.solve(failed_link=int(fails[s]))
        valid, metric, lanes = deltas.routes_of(s)
        nd = native.dist[:n_nodes]
        nl = native.lanes_dense(eng.D)[:n_nodes]
        # valid = advertiser reachable with a first-hop set, and not the
        # root's own prefix (skip-if-self)
        exp_valid = (
            np.isfinite(nd)
            & nl.any(axis=1)
            & (np.arange(n_nodes) != topo.node_id("node0"))
        )
        assert np.array_equal(valid, exp_valid), f"route valid parity {s}"
        assert np.array_equal(metric[exp_valid], nd[exp_valid]), (
            f"route metric parity {s}"
        )
        assert np.array_equal(lanes[exp_valid], nl[exp_valid]), (
            f"route lane parity {s}"
        )

    # host fetch of the unique tables (tunnel-bound; reported, not part
    # of the throughput number — the routes pipeline above is what
    # downstream consumes; this line kept for the before/after contrast)
    t0 = time.perf_counter()
    single.materialize()
    fetch_ms = (time.perf_counter() - t0) * 1000

    # ---- parity: device results == native results ------------------------
    for s in (3, 1007, 9000):
        native.solve(failed_link=int(fails[s]))
        finite = np.isfinite(native.dist)
        assert np.array_equal(
            native.dist[finite], single.dist_of(s)[finite]
        ), f"distance parity failure at snapshot {s}"
        assert np.array_equal(
            native.lanes_dense(eng.D)[finite], single.nh_of(s)[finite]
        ), f"lane parity failure at snapshot {s}"

    def spread(ts):
        return {
            "median_s": round(statistics.median(ts), 4),
            "min_s": round(min(ts), 4),
            "max_s": round(max(ts), 4),
            "reps": len(ts),
        }

    print(
        json.dumps(
            {
                "metric": "whatif_routes_end_to_end_per_sec_10k_x_1024node",
                "value": round(e2e_sps, 1),
                "unit": "snapshots/s",
                "vs_baseline": round(e2e_sps / native_sps, 2),
                "detail": {
                    "native_cxx_solves_per_sec": round(native_sps, 1),
                    "native_naive_spread": spread(naive_times),
                    "native_cxx_dedup_effective_per_sec": round(
                        native_dedup_sps, 1
                    ),
                    "native_warmstart_solves_per_sec": round(
                        native_warm_sps, 1
                    ),
                    "native_warm_spread": spread(warm_times),
                    "native_engine_routes_per_sec": round(
                        native_e2e_sps, 1
                    ),
                    "native_engine_spread": spread(native_e2e_times),
                    "native_engine_route_deltas": int(native_route_deltas),
                    "vs_native_engine_e2e": round(
                        e2e_sps / native_e2e_sps, 2
                    ),
                    "python_solves_per_sec": round(python_sps, 1),
                    "device_spf_tables_per_sec": round(engine_sps, 1),
                    "device_raw_solves_per_sec": round(device_raw_sps, 1),
                    "device_cold_solves_per_sec": round(device_cold_sps, 1),
                    "vs_native_spf_tables_only": round(
                        engine_sps / native_sps, 2
                    ),
                    "vs_native_raw_kernel_only": round(
                        device_raw_sps / native_sps, 2
                    ),
                    "vs_native_cold_kernel": round(
                        device_cold_sps / native_sps, 2
                    ),
                    "vs_native_dedup": round(e2e_sps / native_dedup_sps, 2),
                    "vs_native_warmstart": round(
                        e2e_sps / native_warm_sps, 2
                    ),
                    "vs_python": round(e2e_sps / python_sps, 2),
                    "engine_latency_ms": round(engine_latency_ms, 1),
                    "base_solve_ms": round(base_solve_ms, 1),
                    "repair_plan_build_ms": round(plan_build_ms, 1),
                    "routes_pipeline_ms": round(routes_pipeline_ms, 1),
                    "pipeline_depth": PIPELINE_DEPTH,
                    "route_deltas": int(deltas.num_deltas),
                    "route_delta_fetch_bytes": int(deltas.fetch_bytes),
                    "host_fetch_unique_tables_ms": round(fetch_ms, 1),
                    "dispatch_sync_ms": round(sync_ms, 1),
                    "unique_device_solves": int(single.num_device_solves),
                    "on_dag_link_fraction": round(
                        float(eng.on_dag_links().mean()), 3
                    ),
                    "raw_chunk_rounds_dist_lanes": raw_rounds,
                    "batch_total": total,
                    "nodes": n_nodes,
                    "directed_edges": topo.num_edges,
                    "lanes": eng.D,
                    "mesh_devices": int(mesh.devices.size),
                    "devices": [str(d) for d in jax.devices()],
                    "env": env_stamp(),
                    "fresh_failure_sets_per_rep": True,
                    "wall_s": round(time.time() - t_start, 1),
                },
            }
        )
    )


class _Tee:
    """stdout tee for --out: bench modes print exactly one JSON artifact
    line to stdout (progress goes to stderr), so mirroring stdout into
    the artifact file gives every mode shared output-path handling."""

    def __init__(self, *streams) -> None:
        self._streams = streams

    def write(self, data: str) -> int:
        n = 0
        for s in self._streams:
            n = s.write(data)
        return n

    def flush(self) -> None:
        for s in self._streams:
            s.flush()


#: one dispatch table for every bench mode — a new mode registers here
#: (and nowhere else) and inherits the shared env_stamp/--seed/--out
#: handling.  Values: (runner, default_seed_note, help text).  EVERY
#: runner accepts ``seed=None``; None reproduces the mode's historical
#: defaults (noted here), so checked-in artifacts regenerate unchanged
#: when --seed is omitted.
BENCH_MODES = {
    "convergence": (convergence_main, "canonical flap order", "9-node flap convergence percentiles (virtual time)"),
    "serving": (serving_main, "world 11", "micro-batched serving plane vs unbatched scalar"),
    "multichip-serving": (multichip_serving_main, "world 11", "fleet serving over a 1/2/4/8-chip DevicePool"),
    "pipeline": (pipeline_main, "flip victim node0", "phase-level attribution of the grid4096 rebuild"),
    "resilience": (resilience_main, "world 11, SDC scenario 7", "shadow-verification overhead + seeded SDC scenario"),
    "health": (health_main, "world 11, detection (7,11,13)", "fleet health sweep overhead + detection latency"),
    "warm-start": (warmstart_main, "perturbations 7", "generation-delta warm rebuild vs cold + native warm sweep"),
    "suite": (suite_main, "sweeps 7", "topology-class trajectory: seeded chaos sweeps at 1k+ nodes per class"),
    "rolling": (rolling_main, "sweep 11", "rolling-restart survival: every node bounced once, structural warm-hit + SLO hold"),
    "streaming": (streaming_main, "sweep 11", "watch-plane fan-out: 10k+ subscriber churn under chaos, snapshot+delta generation correctness"),
    "sweep": (sweep_main, "grammar 7", "capacity-planning sweep: 100k+ scenarios on grid4096, sharded/spilled/resumable, ranked risk summary"),
    "frr": (frr_main, "flap sample 7", "fast-reroute protection tier: protected-flap publication→FIB percentiles vs the warm path on grid4096"),
    "fleet-sweep": (fleet_sweep_main, "grammar 7", "fleet fabric: 3-node sharded sweep digest parity + mid-sweep kill repack (emits the combined fleet artifact)"),
    "fleet-streaming": (fleet_streaming_main, "grammar 7", "fleet fabric: consistent-hash watcher migration under kill/drain (emits the combined fleet artifact)"),
    "fleet-liveness": (fleet_liveness_main, "grammar 7", "fleet liveness: heartbeat kill-detection latency, epoch fencing, straggler/gray digest parity, flap damping (emits the combined fleet artifact)"),
}


def _cli(argv) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="bench.py",
        description=(
            "openr-tpu benchmark suite.  With no mode flag, runs the "
            "headline 10k x 1024-node what-if sweep."
        ),
    )
    group = parser.add_mutually_exclusive_group()
    for name, (_fn, _seed_note, help_text) in BENCH_MODES.items():
        group.add_argument(
            f"--{name}",
            dest=name.replace("-", "_"),
            action="store_true",
            help=help_text,
        )
    group.add_argument(
        "--list-modes",
        action="store_true",
        help="list every bench mode with its default-seed behavior",
    )
    parser.add_argument(
        "--out",
        metavar="PATH",
        default=None,
        help="also write the emitted JSON line(s) to PATH",
    )
    parser.add_argument(
        "--seed",
        type=int,
        default=None,
        help=(
            "world/perturbation seed (every mode takes one; omitted = "
            "the mode's historical default, so checked-in artifacts "
            "regenerate unchanged)"
        ),
    )
    args = parser.parse_args(argv)
    if args.list_modes:
        width = max(len(n) for n in BENCH_MODES)
        for name, (_fn, seed_note, help_text) in BENCH_MODES.items():
            print(
                f"--{name:<{width}}  {help_text}  "
                f"[default seed: {seed_note}]"
            )
        return 0
    runner = main
    for name, (fn, _seed_note, _help) in BENCH_MODES.items():
        if getattr(args, name.replace("-", "_")):
            runner = lambda fn=fn, s=args.seed: fn(seed=s)  # noqa: E731
            break
    if args.out:
        with open(args.out, "w") as f:
            real = sys.stdout
            sys.stdout = _Tee(real, f)
            try:
                return runner() or 0
            finally:
                sys.stdout = real
    return runner() or 0


if __name__ == "__main__":
    sys.exit(_cli(sys.argv[1:]))
