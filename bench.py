#!/usr/bin/env python
"""Headline benchmark: batched SPF what-if sweep vs single-threaded scalar.

Config (BASELINE.md north star): 10k single-link-failure perturbations of a
1024-node WAN LSDB, full SPF (distances + all-shortest-paths nexthop sets)
from one vantage root per snapshot.  The baseline is this repo's own scalar
Dijkstra (the reference publishes no absolute numbers — BASELINE.md),
measured in-process on one core exactly as the reference's single-threaded
SpfSolver would run.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import sys
import time

import numpy as np


def main() -> None:
    t_start = time.time()
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.emulation.topology import build_adj_dbs, random_connected_edges
    from openr_tpu.ops.csr import encode_link_state
    from openr_tpu.ops.spf import batched_spf_link_failures

    import jax
    import jax.numpy as jnp

    # ---- build the 1024-node WAN ----------------------------------------
    n_nodes = 1024
    edges = random_connected_edges(n_nodes, 2048, seed=7)
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    topo = encode_link_state(ls)
    D = topo.max_out_degree()

    # ---- scalar baseline: same solve, heap Dijkstra, one thread ---------
    # (distances + nexthop sets, identical semantics; see decision/link_state)
    # one warm-up to stabilize allocator/caches, then best-of-3 batches of 8
    ls.run_spf("node0", links_to_ignore=frozenset([topo.links[0]]))
    best = float("inf")
    for rep in range(3):
        t0 = time.perf_counter()
        for i in range(8):
            link = topo.links[(rep * 8 + i) % len(topo.links)]
            ls.run_spf("node0", links_to_ignore=frozenset([link]))
        best = min(best, (time.perf_counter() - t0) / 8)
    scalar_s_per_solve = best

    # ---- batched device sweep -------------------------------------------
    total = 10_240
    chunk = 2_048
    rng = np.random.default_rng(0)
    fails = rng.integers(0, len(topo.links), size=total).astype(np.int32)

    src = jnp.asarray(topo.src)
    dst = jnp.asarray(topo.dst)
    w = jnp.asarray(topo.w)
    edge_ok = jnp.asarray(topo.edge_ok)
    link_index = jnp.asarray(topo.link_index)
    ovl = jnp.tile(jnp.asarray(topo.overloaded), (chunk, 1))
    roots = jnp.zeros(chunk, jnp.int32)

    # warm the jit cache (compile excluded from the steady-state number,
    # included in wall_s below for transparency)
    d, _ = batched_spf_link_failures(
        src, dst, w, edge_ok, link_index, jnp.asarray(fails[:chunk]), ovl,
        roots, max_degree=D,
    )
    d.block_until_ready()

    t0 = time.perf_counter()
    last = None
    for off in range(0, total, chunk):
        f = jnp.asarray(fails[off : off + chunk])
        dist, nh = batched_spf_link_failures(
            src, dst, w, edge_ok, link_index, f, ovl, roots, max_degree=D
        )
        last = dist
    last.block_until_ready()
    batch_elapsed = time.perf_counter() - t0

    solves_per_sec = total / batch_elapsed
    scalar_solves_per_sec = 1.0 / scalar_s_per_solve
    speedup = solves_per_sec / scalar_solves_per_sec

    # sanity: one snapshot (from the warm-up run, same first chunk) must
    # match the scalar result
    b_check = 3
    res = ls.run_spf(
        "node0", links_to_ignore=frozenset([topo.links[int(fails[b_check])]])
    )
    kd = np.asarray(d)[b_check]
    for node, r in res.items():
        assert kd[topo.node_id(node)] == r.metric, f"parity failure at {node}"

    print(
        json.dumps(
            {
                "metric": "spf_solves_per_sec_10k_x_1024node_whatif",
                "value": round(solves_per_sec, 1),
                "unit": "solves/s",
                "vs_baseline": round(speedup, 2),
                "detail": {
                    "scalar_solves_per_sec": round(scalar_solves_per_sec, 1),
                    "batch_total": total,
                    "batch_chunk": chunk,
                    "nodes": n_nodes,
                    "directed_edges": topo.num_edges,
                    "max_degree": D,
                    "devices": [str(d) for d in jax.devices()],
                    "wall_s": round(time.time() - t_start, 1),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
