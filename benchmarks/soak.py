"""Continuous what-if-service soak: fresh random failure sets, forever.

The steady-state headline in ``bench.py`` runs 12 pipelined sweeps;
this harness runs the SAME pipeline (LinkFailureSweep +
SweepRouteSelector, depth-4 in-flight, fresh random failure set per
sweep) for ``--seconds`` wall-clock and reports windowed throughput —
the continuous-service shape an operator deployment actually runs.
Writes ``SOAK.json`` (override with ``--json``) so the number the
README quotes is pinned by an in-tree artifact (r4 review weak #5 /
next-step #6; the reference's equivalent discipline is
benchmarks-in-tree, openr/decision/tests/DecisionBenchmark.cpp).

Usage:  python -m benchmarks.soak --seconds 180 [--json SOAK.json]
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seconds", type=float, default=180.0)
    ap.add_argument("--nodes", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=10_240)
    ap.add_argument("--json", default="SOAK.json")
    ap.add_argument("--window", type=int, default=10,
                    help="sweeps per throughput window")
    args = ap.parse_args()

    from openr_tpu.ops.platform_env import (
        enable_persistent_compile_cache,
        fallback_to_cpu_if_unreachable,
        honor_cpu_platform_request,
    )

    honor_cpu_platform_request()
    fallback_to_cpu_if_unreachable()
    enable_persistent_compile_cache()

    import jax

    from bench import build_headline_world, env_stamp
    from openr_tpu.ops.sweep_select import SweepRouteSelector
    from openr_tpu.ops.whatif import LinkFailureSweep
    from openr_tpu.parallel.mesh import make_mesh

    # the SHARED headline world (bench.build_headline_world) — the soak
    # must measure the same workload the headline quotes, or graph
    # density changes the on-DAG fraction / dedup economics and the
    # comparison stops being apples-to-apples (r5 review)
    _ls, topo, cands = build_headline_world(args.nodes)
    L = len(topo.links)
    mesh = make_mesh()
    eng = LinkFailureSweep(topo, "node0", mesh=mesh)
    sel = SweepRouteSelector(
        topo, "node0", cands, max_degree=eng.D, mesh=mesh
    )
    rng = np.random.default_rng(0xC0FFEE)

    def fresh():
        return rng.integers(0, L, size=args.batch).astype(np.int32)

    # warm-up: compile every shape on the pipeline path
    sel.run(eng.run(fresh(), fetch=False))

    DEPTH = 4
    pend = []
    sweeps = 0
    deltas_total = 0
    window_t0 = time.perf_counter()
    window_sweeps = 0
    windows = []
    deadline = time.perf_counter() + args.seconds
    t_start = time.perf_counter()
    while time.perf_counter() < deadline or pend:
        if time.perf_counter() < deadline:
            pend.append(sel.start(eng.run(fresh(), fetch=False)))
        if len(pend) >= DEPTH or (
            pend and time.perf_counter() >= deadline
        ):
            d = pend.pop(0).finish()
            sweeps += 1
            window_sweeps += 1
            nd = int(d.num_deltas)
            # same correctness bound as bench.py's fresh-set reps.
            # Upper bound always holds; the >0 lower bound only at the
            # default batch scale (a tiny --batch can legitimately draw
            # all-off-DAG failure sets that change nothing)
            assert 0 <= nd <= args.batch * args.nodes, nd
            if args.batch >= 1024:
                assert nd > 0, "large fresh sweep changed no routes"

            deltas_total += nd
            if window_sweeps == args.window:
                dt = time.perf_counter() - window_t0
                windows.append(args.window * args.batch / dt)
                window_t0 = time.perf_counter()
                window_sweeps = 0
    wall = time.perf_counter() - t_start
    sps = sweeps * args.batch / wall
    result = {
        "metric": "soak_whatif_snapshots_per_sec",
        "value": round(sps, 1),
        "unit": "snapshots/s",
        "detail": {
            "seconds": round(wall, 1),
            "sweeps": sweeps,
            "snapshots": sweeps * args.batch,
            "route_deltas_decoded": deltas_total,
            "windows": len(windows),
            "window_sps_p50": round(statistics.median(windows), 1)
            if windows
            else None,
            "window_sps_min": round(min(windows), 1) if windows else None,
            "window_sps_max": round(max(windows), 1) if windows else None,
            "fresh_failure_sets_per_sweep": True,
            "pipeline_depth": DEPTH,
            "nodes": args.nodes,
            "batch": args.batch,
            "devices": [str(d) for d in jax.devices()],
            "env": env_stamp(),
        },
    }
    with open(args.json, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
