"""Benchmark suite — ports of the reference's folly::Benchmark harnesses.

Reference parity (SURVEY §6 / BASELINE.md):
  * DecisionBenchmark (openr/decision/tests/DecisionBenchmark.cpp:20-80):
    grid initial route build, adjacency-update reconvergence, prefix
    updates — topology generators from RoutingBenchmarkUtils.cpp
    (grid :251, 3-tier fabric :422) live in openr_tpu.emulation.topology
  * KvStoreBenchmarkTest.cpp:676: key persist/update at 100/1k/10k keys
  * KvStoreConvergenceBenchmark.cpp:146: multi-store flood convergence
  * FibBenchmark.cpp: route-programming throughput
  * PrefixManagerBenchmarkTest.cpp: advertise throughput
  * MessagingBenchmark.cpp: queue throughput

Run:  python -m benchmarks.suite [--full] [--json PATH]
Each result prints as one JSON line {"metric", "value", "unit", ...};
the aggregate is written to --json (default BENCH_SUITE.json).

The decision benches run BOTH backends (scalar oracle and the TPU/JAX
batched kernel) so the speedup is measured, not assumed.
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Callable, Dict, List


def _best_of(fn: Callable[[], None], repeats: int = 3) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _result(metric: str, value: float, unit: str, **detail) -> Dict:
    out = {"metric": metric, "value": round(value, 3), "unit": unit}
    if detail:
        out["detail"] = detail
    print(json.dumps(out), flush=True)
    return out


# ---------------------------------------------------------------------------
# Decision (DecisionBenchmark.cpp)
# ---------------------------------------------------------------------------

def _build_decision_problem(edges, prefixes_per_node: int, area: str = "0"):
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.emulation.topology import build_adj_dbs
    from openr_tpu.types import PrefixEntry

    ls = LinkState(area)
    dbs = build_adj_dbs(edges)
    for db in dbs.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i, node in enumerate(sorted(dbs)):
        for p in range(prefixes_per_node):
            ps.update_prefix(
                node, area, PrefixEntry(prefix=f"10.{(i >> 8) & 255}.{i & 255}.{p}/32")
            )
    return ls, ps, sorted(dbs)


def _make_backends(root: str):
    from openr_tpu.decision.backend import ScalarBackend, TpuBackend
    from openr_tpu.decision.spf_solver import SpfSolver

    return {
        "scalar": ScalarBackend(SpfSolver(root)),
        "tpu": TpuBackend(SpfSolver(root)),
    }


def bench_decision_initial(results: List[Dict], full: bool) -> None:
    """BM_DecisionGridInitialUpdate: cold full route build on grids."""
    from openr_tpu.emulation.topology import fabric_edges, grid_edges

    cases = [("grid", grid_edges(4), 10), ("grid", grid_edges(8), 10)]
    if full:
        cases.append(("grid", grid_edges(16), 10))
    cases.append(
        ("fabric", fabric_edges(num_pods=4, rsws_per_pod=8, fsws_per_pod=4,
                                num_ssws=8), 10)
    )
    for kind, edges, ppn in cases:
        ls, ps, nodes = _build_decision_problem(edges, ppn)
        n = len(nodes)
        timings = {}
        for name, backend in _make_backends(nodes[0]).items():
            backend.build_route_db({"0": ls}, ps)  # warm (jit compile)

            def cold_build(b=backend):
                # cold = no memoized SPF and no cached topology encoding:
                # that's what "initial update" measures in the reference
                ls.clear_spf_memoization()
                if hasattr(b, "_enc_cache"):
                    b._enc_cache = {}
                b.build_route_db({"0": ls}, ps)

            timings[name] = _best_of(cold_build)
            results.append(
                _result(
                    f"decision_initial_{kind}{n}_{name}",
                    timings[name] * 1000,
                    "ms",
                    nodes=n,
                    prefixes=n * ppn,
                )
            )
        if timings["scalar"] and timings["tpu"]:
            _result(
                f"decision_initial_{kind}{n}_speedup",
                timings["scalar"] / timings["tpu"],
                "x",
            )


def bench_decision_adj_update(results: List[Dict], full: bool) -> None:
    """BM_DecisionGridAdjUpdates: reconvergence after one metric change."""
    from openr_tpu.emulation.topology import build_adj_dbs, grid_edges

    side = 16 if full else 8
    edges = grid_edges(side)
    ls, ps, nodes = _build_decision_problem(edges, 10)
    dbs = build_adj_dbs(edges)
    flip_node = nodes[1]
    for name, backend in _make_backends(nodes[0]).items():
        backend.build_route_db({"0": ls}, ps)  # steady state
        toggle = [0]

        def one_update(b=backend):
            toggle[0] ^= 1
            db = dbs[flip_node]
            for adj in db.adjacencies:
                adj.metric = 10 if toggle[0] else 1
            ls.update_adjacency_database(db)
            b.build_route_db({"0": ls}, ps)

        dt = _best_of(one_update, repeats=5)
        results.append(
            _result(
                f"decision_adj_update_grid{side * side}_{name}",
                dt * 1000,
                "ms",
                nodes=side * side,
            )
        )


def bench_decision_prefix_update(results: List[Dict], full: bool) -> None:
    """BM_DecisionGridPrefixUpdates: prefix churn on a fixed topology."""
    from openr_tpu.emulation.topology import grid_edges
    from openr_tpu.types import PrefixEntry, PrefixMetrics

    batch = 1000 if full else 100
    # fresh, identical problem per backend (churn must not accumulate
    # across backends/repeats), with names driven by the backend registry
    first = _build_decision_problem(grid_edges(10), 10)
    names = list(_make_backends(first[2][0]))
    problems = {names[0]: first}
    for name in names[1:]:
        problems[name] = _build_decision_problem(grid_edges(10), 10)
    for name, (ls, ps, nodes) in problems.items():
        backend = _make_backends(nodes[0])[name]
        backend.build_route_db({"0": ls}, ps)
        toggle = [0]

        def churn(b=backend, ls=ls, ps=ps, nodes=nodes):
            # overwrite the SAME prefix set with alternating payloads:
            # steady-state update churn, constant workload per repeat
            toggle[0] ^= 1
            for i in range(batch):
                ps.update_prefix(
                    nodes[i % len(nodes)],
                    "0",
                    PrefixEntry(
                        prefix=f"172.16.{i >> 8}.{i & 255}/32",
                        metrics=PrefixMetrics(path_preference=toggle[0]),
                    ),
                )
            b.build_route_db({"0": ls}, ps)

        churn()  # populate the churn set once before timing
        dt = _best_of(churn, repeats=3)
        results.append(
            _result(
                f"decision_prefix_update_{batch}_{name}", dt * 1000, "ms",
                nodes=100, prefixes_churned=batch,
            )
        )


def bench_parity_device_coverage(results: List[Dict], full: bool) -> None:
    """BASELINE parity configs: every one must run the device path with
    ZERO scalar fallbacks (num_scalar_builds == 0), and match the scalar
    oracle.  The 5th config (10k what-if sweep) is bench.py's headline."""
    from openr_tpu.decision.backend import ScalarBackend, TpuBackend
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.decision.rib_policy import (
        RibPolicy,
        RibPolicyStatement,
        RibRouteActionWeight,
    )
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import (
        build_adj_dbs,
        fabric_edges,
        grid_edges,
        ring_edges,
    )
    from openr_tpu.types import (
        PrefixEntry,
        PrefixForwardingAlgorithm,
        PrefixForwardingType,
    )

    def mk_ls(edges, area="0", **kw):
        ls = LinkState(area)
        for db in build_adj_dbs(edges, area=area, **kw).values():
            ls.update_adjacency_database(db)
        return ls

    def cfg_grid16():
        als = {"0": mk_ls(grid_edges(4))}
        ps = PrefixState()
        for i in range(16):
            ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.0.{i}.0/24"))
        return "grid16_shortest_distance", als, ps, "node0", {}

    def cfg_ksp2_fabric():
        edges = fabric_edges(num_pods=3, rsws_per_pod=4, fsws_per_pod=2,
                             num_ssws=4)
        als = {"0": mk_ls(edges)}
        ps = PrefixState()
        rsws = sorted(n for e in edges for n in e[:2] if n.startswith("rsw"))
        for i, n in enumerate(dict.fromkeys(rsws)):
            ps.update_prefix(n, "0", PrefixEntry(
                f"10.{i}.0.0/24",
                forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP))
        return "ksp2_fabric", als, ps, "rsw0_0", {}

    def cfg_multiarea_ribpolicy():
        als = {
            "1": mk_ls(grid_edges(3), "1"),
            "2": mk_ls(ring_edges(6, prefix="b") + [("b0", "node0", 1)], "2"),
        }
        ps = PrefixState()
        ps.update_prefix("node8", "1", PrefixEntry("10.0.0.0/24"))
        ps.update_prefix("b3", "2", PrefixEntry("10.0.0.0/24"))
        ps.update_prefix("b4", "2", PrefixEntry("10.1.0.0/24"))
        policy = RibPolicy(
            statements=[RibPolicyStatement(
                name="prefer-area1",
                prefixes=["10.0.0.0/24"],
                action=RibRouteActionWeight(
                    default_weight=1, area_to_weight={"1": 2}),
            )],
            valid_until=300.0,
        )
        return "multiarea_ribpolicy", als, ps, "node0", {"policy": policy}

    def cfg_sr_mpls():
        edges = fabric_edges(num_pods=2, rsws_per_pod=3, fsws_per_pod=2,
                             num_ssws=2)
        nodes = sorted({n for e in edges for n in e[:2]})
        labels = {n: 100 + i for i, n in enumerate(nodes)}
        als = {"0": mk_ls(edges, node_labels=labels)}
        ps = PrefixState()
        ps.update_prefix("rsw1_2", "0", PrefixEntry(
            "2001:db8::/64",
            forwarding_type=PrefixForwardingType.SR_MPLS,
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP))
        return (
            "sr_mpls_labels", als, ps, "rsw0_0",
            {"solver_kwargs": {"enable_node_segment_label": True}},
        )

    all_on_device = True
    for cfg in (cfg_grid16, cfg_ksp2_fabric, cfg_multiarea_ribpolicy,
                cfg_sr_mpls):
        name, als, ps, me, extra = cfg()
        skw = extra.get("solver_kwargs", {})
        backend = TpuBackend(SpfSolver(me, **skw))
        db = backend.build_route_db(als, ps)
        ref = ScalarBackend(SpfSolver(me, **skw)).build_route_db(als, ps)
        policy = extra.get("policy")
        if policy is not None:
            clock = SimClock()
            for d in (db, ref):
                assert policy.apply_policy(d, clock) > 0
        from openr_tpu.decision.rib import route_db_summary

        match = route_db_summary(db) == route_db_summary(ref)
        on_device = backend.num_scalar_builds == 0 and match
        all_on_device &= on_device
        results.append(_result(
            f"parity_{name}_on_device", 1.0 if on_device else 0.0, "bool",
            scalar_builds=backend.num_scalar_builds,
            device_builds=backend.num_device_builds,
            matches_oracle=match,
        ))
    results.append(_result(
        "parity_configs_device_coverage", 1.0 if all_on_device else 0.0,
        "fraction"))


# ---------------------------------------------------------------------------
# KvStore (KvStoreBenchmarkTest.cpp, KvStoreConvergenceBenchmark.cpp)
# ---------------------------------------------------------------------------

def bench_kvstore_persist(results: List[Dict], full: bool) -> None:
    import asyncio

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import KvStoreConfig
    from openr_tpu.kvstore.kv_store import KvStore
    from openr_tpu.kvstore.transport import InProcessTransport
    from openr_tpu.messaging.queue import ReplicateQueue

    sizes = [100, 1000, 10_000] if full else [100, 1000]
    for n in sizes:
        async def run(n=n):
            clock = SimClock()
            store = KvStore(
                node_name="b0",
                clock=clock,
                config=KvStoreConfig(),
                areas=["0"],
                transport=InProcessTransport(clock),
                publications_queue=ReplicateQueue("pubs"),
            )
            db = store.areas["0"]
            payload = b"x" * 128
            t0 = time.perf_counter()
            for i in range(n):
                db.persist_self_originated_key(f"prefix:b0:k{i}", payload)
            dt = time.perf_counter() - t0
            # update pass: same keys, new values (version bump path)
            t0 = time.perf_counter()
            for i in range(n):
                db.persist_self_originated_key(f"prefix:b0:k{i}", payload + b"y")
            dt_update = time.perf_counter() - t0
            await store.stop()
            return dt, dt_update

        dt, dt_update = asyncio.run(run())
        results.append(
            _result(f"kvstore_persist_{n}", n / dt, "keys/s")
        )
        results.append(
            _result(f"kvstore_update_{n}", n / dt_update, "keys/s")
        )


def bench_kvstore_flood_convergence(results: List[Dict], full: bool) -> None:
    """N stores in a line; one key injected at the head; time until every
    store holds it (virtual time = protocol latency, wall time = compute)."""
    import asyncio

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import KvStoreConfig
    from openr_tpu.kvstore.kv_store import KvStore
    from openr_tpu.kvstore.transport import InProcessTransport
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.types import PeerSpec

    n = 64 if full else 16

    async def run():
        clock = SimClock()
        transport = InProcessTransport(clock, latency_s=0.001)
        stores = []
        for i in range(n):
            store = KvStore(
                node_name=f"s{i}",
                clock=clock,
                config=KvStoreConfig(),
                areas=["0"],
                transport=transport,
                publications_queue=ReplicateQueue(f"pubs{i}"),
            )
            transport.register(f"s{i}", store)
            stores.append(store)
            store.start()
        for i, store in enumerate(stores):
            peers = {}
            if i > 0:
                peers[f"s{i - 1}"] = PeerSpec()
            if i < n - 1:
                peers[f"s{i + 1}"] = PeerSpec()
            store.areas["0"].add_peers(peers)
        await clock.run_for(5.0)

        t_wall = time.perf_counter()
        t_virtual = clock.now()
        stores[0].areas["0"].persist_self_originated_key("prefix:s0:x", b"v")
        while not all("prefix:s0:x" in s.areas["0"].key_vals for s in stores):
            await clock.run_for(0.05)
            if clock.now() - t_virtual > 60:
                raise RuntimeError("flood did not converge")
        wall = time.perf_counter() - t_wall
        virtual = clock.now() - t_virtual
        for store in stores:
            await store.stop()
        return wall, virtual

    wall, virtual = asyncio.run(run())
    results.append(
        _result(
            f"kvstore_flood_convergence_{n}",
            virtual * 1000,
            "virtual_ms",
            wall_ms=round(wall * 1000, 1),
            stores=n,
        )
    )


# ---------------------------------------------------------------------------
# Fib (FibBenchmark.cpp)
# ---------------------------------------------------------------------------

def bench_fib_programming(results: List[Dict], full: bool) -> None:
    import asyncio

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import FibConfig
    from openr_tpu.decision.rib import (
        DecisionRouteUpdate,
        DecisionRouteUpdateType,
        RibUnicastEntry,
    )
    from openr_tpu.fib.fib import Fib, MockFibAgent
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.types import NextHop

    n = 10_000 if full else 2_000

    async def run():
        clock = SimClock()
        agent = MockFibAgent(clock)
        q = ReplicateQueue("routes")
        fib = Fib(
            node_name="b0",
            clock=clock,
            config=FibConfig(),
            agent=agent,
            route_updates_reader=q.get_reader(),
        )
        fib.start()
        routes = {
            f"10.{(i >> 8) & 255}.{i & 255}.0/24": RibUnicastEntry(
                prefix=f"10.{(i >> 8) & 255}.{i & 255}.0/24",
                nexthops=[NextHop(address="fe80::1", if_name="eth0")],
            )
            for i in range(n)
        }
        t0 = time.perf_counter()
        q.push(
            DecisionRouteUpdate(
                type=DecisionRouteUpdateType.FULL_SYNC,
                unicast_routes_to_update=routes,
            )
        )
        while len(agent.unicast) < n:
            await clock.run_for(0.05)
        dt = time.perf_counter() - t0
        await fib.stop()
        return dt

    dt = asyncio.run(run())
    results.append(_result(f"fib_program_{n}", n / dt, "routes/s"))


# ---------------------------------------------------------------------------
# PrefixManager (PrefixManagerBenchmarkTest.cpp)
# ---------------------------------------------------------------------------

def bench_prefix_manager_advertise(results: List[Dict], full: bool) -> None:
    import asyncio

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.prefix_manager.prefix_manager import PrefixManager
    from openr_tpu.types import (
        PrefixEntry,
        PrefixEvent,
        PrefixEventType,
    )

    n = 10_000 if full else 2_000

    async def run():
        clock = SimClock()
        kv_q = ReplicateQueue("kvreq")
        kv_r = kv_q.get_reader()
        prefix_q = ReplicateQueue("prefixEvents")
        pm = PrefixManager(
            node_name="b0",
            clock=clock,
            kv_request_queue=kv_q,
            prefix_updates_reader=prefix_q.get_reader(),
        )
        pm.start()
        await clock.run_for(0.1)
        while kv_r.try_get() is not None:
            pass
        entries = [
            PrefixEntry(prefix=f"10.{(i >> 8) & 255}.{i & 255}.0/24")
            for i in range(n)
        ]
        t0 = time.perf_counter()
        prefix_q.push(
            PrefixEvent(
                event_type=PrefixEventType.ADD_PREFIXES, prefixes=entries
            )
        )
        seen = 0
        while seen < n:
            await clock.run_for(0.05)
            while kv_r.try_get() is not None:
                seen += 1
        dt = time.perf_counter() - t0
        await pm.stop()
        return dt

    dt = asyncio.run(run())
    results.append(_result(f"prefix_manager_advertise_{n}", n / dt, "prefixes/s"))


# ---------------------------------------------------------------------------
# Messaging (MessagingBenchmark.cpp)
# ---------------------------------------------------------------------------

def bench_messaging(results: List[Dict], full: bool) -> None:
    import asyncio

    from openr_tpu.messaging.queue import ReplicateQueue

    n = 200_000 if full else 50_000
    readers = 4

    async def run():
        q = ReplicateQueue("bench")
        rs = [q.get_reader() for _ in range(readers)]
        t0 = time.perf_counter()

        async def drain(r):
            for _ in range(n):
                await r.get()

        tasks = [asyncio.ensure_future(drain(r)) for r in rs]
        for i in range(n):
            q.push(i)
            if i % 4096 == 0:
                await asyncio.sleep(0)  # let readers drain; bounds memory
        await asyncio.gather(*tasks)
        return time.perf_counter() - t0

    dt = asyncio.run(run())
    results.append(
        _result(
            "messaging_replicate_throughput",
            n * readers / dt,
            "deliveries/s",
            items=n,
            readers=readers,
        )
    )


ALL_BENCHES = [
    bench_decision_initial,
    bench_decision_adj_update,
    bench_decision_prefix_update,
    bench_parity_device_coverage,
    bench_kvstore_persist,
    bench_kvstore_flood_convergence,
    bench_fib_programming,
    bench_prefix_manager_advertise,
    bench_messaging,
]


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--full", action="store_true",
                   help="reference-scale sizes (slower)")
    p.add_argument("--json", default="BENCH_SUITE.json")
    p.add_argument("--only", default="",
                   help="substring filter on bench function names")
    args = p.parse_args()
    results: List[Dict] = []
    t0 = time.time()
    for bench in ALL_BENCHES:
        if args.only and args.only not in bench.__name__:
            continue
        bench(results, args.full)
    with open(args.json, "w") as f:
        json.dump(
            {"results": results, "wall_s": round(time.time() - t0, 1)},
            f,
            indent=2,
        )
    print(f"# {len(results)} results -> {args.json}", flush=True)


if __name__ == "__main__":
    main()
