"""Benchmark suite — ports of the reference's folly::Benchmark harnesses.

Reference parity (SURVEY §6 / BASELINE.md):
  * DecisionBenchmark (openr/decision/tests/DecisionBenchmark.cpp:20-80):
    grid initial route build, adjacency-update reconvergence, prefix
    updates — topology generators from RoutingBenchmarkUtils.cpp
    (grid :251, 3-tier fabric :422) live in openr_tpu.emulation.topology
  * KvStoreBenchmarkTest.cpp:676: key persist/update at 100/1k/10k keys
  * KvStoreConvergenceBenchmark.cpp:146: multi-store flood convergence
  * FibBenchmark.cpp: route-programming throughput
  * PrefixManagerBenchmarkTest.cpp: advertise throughput
  * MessagingBenchmark.cpp: queue throughput

Run:  python -m benchmarks.suite [--full] [--json PATH]
Each result prints as one JSON line {"metric", "value", "unit", ...};
the aggregate is written to --json (default BENCH_SUITE.json).

The decision benches run BOTH backends (scalar oracle and the TPU/JAX
batched kernel) so the speedup is measured, not assumed.
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Callable, Dict, List


def _best_of(fn: Callable[[], None], repeats: int = 3) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _result(metric: str, value: float, unit: str, **detail) -> Dict:
    out = {"metric": metric, "value": round(value, 3), "unit": unit}
    if detail:
        out["detail"] = detail
    print(json.dumps(out), flush=True)
    return out


# ---------------------------------------------------------------------------
# Decision (DecisionBenchmark.cpp)
# ---------------------------------------------------------------------------

def _build_decision_problem(edges, prefixes_per_node: int, area: str = "0"):
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.emulation.topology import build_adj_dbs
    from openr_tpu.types import PrefixEntry

    ls = LinkState(area)
    dbs = build_adj_dbs(edges)
    for db in dbs.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i, node in enumerate(sorted(dbs)):
        for p in range(prefixes_per_node):
            # globally-unique /32 per (node, p) across a 24-bit space
            idx = i * prefixes_per_node + p
            ps.update_prefix(
                node,
                area,
                PrefixEntry(
                    prefix=f"10.{(idx >> 16) & 255}.{(idx >> 8) & 255}"
                    f".{idx & 255}/32"
                ),
            )
    return ls, ps, sorted(dbs)


def _make_backends(root: str):
    from openr_tpu.decision.backend import ScalarBackend, TpuBackend
    from openr_tpu.decision.spf_solver import SpfSolver

    return {
        "scalar": ScalarBackend(SpfSolver(root)),
        "tpu": TpuBackend(SpfSolver(root)),
    }


def bench_decision_initial(results: List[Dict], full: bool) -> None:
    """BM_DecisionGridInitialUpdate: cold full route build on grids and
    3-tier fabrics at reference scales (DecisionBenchmark.cpp:20-35 runs
    grids of 10/100/1000/10000 nodes; RoutingBenchmarkUtils.cpp:251,422).
    Every config measures BOTH backends (repeats shrink as scale grows:
    the 10,000-node scalar pass runs once); absent rows mean 'not
    measured', never 'assumed'."""
    from openr_tpu.emulation.topology import fabric_edges, grid_edges

    # (kind, edges, prefixes/node, backends, repeats)
    cases = [
        ("grid", grid_edges(4), 10, ("scalar", "tpu"), 3),
        ("grid", grid_edges(8), 10, ("scalar", "tpu"), 3),
        (
            "fabric",
            fabric_edges(num_pods=4, rsws_per_pod=8, fsws_per_pod=4,
                         num_ssws=8),
            10,
            ("scalar", "tpu"),
            3,
        ),
    ]
    if full:
        cases += [
            ("grid", grid_edges(16), 10, ("scalar", "tpu"), 3),
            # 1024-node grid — reference's 1000-node row
            ("grid", grid_edges(32), 10, ("scalar", "tpu"), 2),
            # 256 nodes x 100 prefixes/node
            ("grid", grid_edges(16), 100, ("scalar", "tpu"), 2),
            # 100 nodes x 1000 prefixes/node (BM prefix-density row)
            ("grid", grid_edges(10), 1000, ("scalar", "tpu"), 1),
            # ~1000-node 3-tier fabric
            (
                "fabric",
                fabric_edges(num_pods=12, rsws_per_pod=64, fsws_per_pod=8,
                             num_ssws=96),
                10,
                ("scalar", "tpu"),
                1,
            ),
            # 10,000-node grid — the reference's largest config; scalar
            # runs once (a single from-scratch pass is ~half a minute)
            ("grid", grid_edges(100), 10, ("scalar", "tpu"), 1),
        ]
    for kind, edges, ppn, backends, repeats in cases:
        ls, ps, nodes = _build_decision_problem(edges, ppn)
        n = len(nodes)
        timings = {}
        for name, backend in _make_backends(nodes[0]).items():
            if name not in backends:
                continue
            if name != "scalar":
                backend.build_route_db({"0": ls}, ps)  # warm (jit compile)

            def cold_build(b=backend):
                # cold = no memoized SPF and no cached topology encoding:
                # that's what "initial update" measures in the reference
                ls.clear_spf_memoization()
                if hasattr(b, "_enc_cache"):
                    b._enc_cache = {}
                b.build_route_db({"0": ls}, ps)

            timings[name] = _best_of(cold_build, repeats=repeats)
            results.append(
                _result(
                    f"decision_initial_{kind}{n}_ppn{ppn}_{name}",
                    timings[name] * 1000,
                    "ms",
                    nodes=n,
                    prefixes=n * ppn,
                )
            )
        if timings.get("scalar") and timings.get("tpu"):
            results.append(
                _result(
                    f"decision_initial_{kind}{n}_ppn{ppn}_speedup",
                    timings["scalar"] / timings["tpu"],
                    "x",
                )
            )
        # what the DAEMON default (auto cutover) would pick at this
        # scale: the backend probes the dispatch round trip and chooses
        # scalar when the device can't amortize it (the rows above force
        # each path to keep measuring both)
        from openr_tpu.decision.backend import TpuBackend
        from openr_tpu.decision.spf_solver import SpfSolver

        auto = TpuBackend(SpfSolver(nodes[0]), min_device_prefixes=None)
        choice = (
            "device" if auto._device_worth_it({"0": ls}, ps) else "scalar"
        )
        results.append(
            _result(
                f"decision_initial_{kind}{n}_ppn{ppn}_auto_choice",
                1.0 if choice == "device" else 0.0,
                choice,
                nodes=n,
                prefixes=n * ppn,
                dispatch_rt_ms=round(auto.auto_dispatch_rt_ms, 2),
            )
        )


def bench_decision_adj_update(results: List[Dict], full: bool) -> None:
    """BM_DecisionGridAdjUpdates: reconvergence after one metric change."""
    from openr_tpu.emulation.topology import build_adj_dbs, grid_edges

    side = 16 if full else 8
    edges = grid_edges(side)
    ls, ps, nodes = _build_decision_problem(edges, 10)
    dbs = build_adj_dbs(edges)
    flip_node = nodes[1]
    for name, backend in _make_backends(nodes[0]).items():
        backend.build_route_db({"0": ls}, ps)  # steady state
        toggle = [0]

        def one_update(b=backend):
            toggle[0] ^= 1
            db = dbs[flip_node]
            for adj in db.adjacencies:
                adj.metric = 10 if toggle[0] else 1
            ls.update_adjacency_database(db)
            # exactly what Decision passes on a topology-only delta:
            # force_full (SPF changed) with an empty prefix-churn set, so
            # backends keep their candidate tables instead of re-reading
            # the whole PrefixState
            b.build_route_db(
                {"0": ls}, ps, changed_prefixes=set(), force_full=True
            )

        dt = _best_of(one_update, repeats=5)
        results.append(
            _result(
                f"decision_adj_update_grid{side * side}_{name}",
                dt * 1000,
                "ms",
                nodes=side * side,
            )
        )


def bench_decision_prefix_update(results: List[Dict], full: bool) -> None:
    """BM_DecisionGridPrefixUpdates: prefix churn on a fixed topology —
    measured BOTH as a full rebuild (the reference's only mode) and as a
    per-prefix incremental rebuild (Decision.cpp:908-952 parity path).
    The incremental row must stay ~flat as TOTAL prefixes grow; that is
    the sub-linearity VERDICT r2 item 4 demands."""
    from openr_tpu.emulation.topology import grid_edges
    from openr_tpu.types import PrefixEntry, PrefixMetrics

    batch = 1000 if full else 100
    ppn_cases = [10, 1000] if full else [10]
    for ppn in ppn_cases:
        # fresh, identical problem per backend (churn must not accumulate
        # across backends/repeats), with names from the backend registry
        first = _build_decision_problem(grid_edges(10), ppn)
        names = list(_make_backends(first[2][0]))
        problems = {names[0]: first}
        for name in names[1:]:
            problems[name] = _build_decision_problem(grid_edges(10), ppn)
        for name, (ls, ps, nodes) in problems.items():
            backend = _make_backends(nodes[0])[name]
            backend.build_route_db({"0": ls}, ps)
            toggle = [0]

            def churn_prefixes(ps=ps, nodes=nodes):
                # overwrite the SAME prefix set with alternating payloads:
                # steady-state update churn, constant workload per repeat
                toggle[0] ^= 1
                changed = set()
                for i in range(batch):
                    changed |= ps.update_prefix(
                        nodes[i % len(nodes)],
                        "0",
                        PrefixEntry(
                            prefix=f"172.16.{i >> 8}.{i & 255}/32",
                            metrics=PrefixMetrics(path_preference=toggle[0]),
                        ),
                    )
                return changed

            def full_rebuild(b=backend, ls=ls, ps=ps):
                churn_prefixes()
                b.build_route_db({"0": ls}, ps, force_full=True)

            def incremental(b=backend, ls=ls, ps=ps):
                changed = churn_prefixes()
                b.build_route_db({"0": ls}, ps, changed_prefixes=changed)

            total = len(ps.prefixes()) + batch
            churn_prefixes()  # populate the churn set once before timing
            backend.build_route_db({"0": ls}, ps, force_full=True)
            dt = _best_of(full_rebuild, repeats=3 if ppn <= 10 else 1)
            results.append(
                _result(
                    f"decision_prefix_update_full_{batch}of{total}_{name}",
                    dt * 1000,
                    "ms",
                    nodes=100,
                    prefixes_churned=batch,
                    prefixes_total=total,
                )
            )
            dt = _best_of(incremental, repeats=3)
            results.append(
                _result(
                    f"decision_prefix_update_inc_{batch}of{total}_{name}",
                    dt * 1000,
                    "ms",
                    nodes=100,
                    prefixes_churned=batch,
                    prefixes_total=total,
                )
            )


def bench_parity_device_coverage(results: List[Dict], full: bool) -> None:
    """BASELINE parity configs: every one must run the device path with
    ZERO scalar fallbacks (num_scalar_builds == 0), and match the scalar
    oracle.  The 5th config (10k what-if sweep) is bench.py's headline."""
    from openr_tpu.decision.backend import ScalarBackend, TpuBackend
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.decision.rib_policy import (
        RibPolicy,
        RibPolicyStatement,
        RibRouteActionWeight,
    )
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import (
        build_adj_dbs,
        fabric_edges,
        grid_edges,
        ring_edges,
    )
    from openr_tpu.types import (
        PrefixEntry,
        PrefixForwardingAlgorithm,
        PrefixForwardingType,
    )

    def mk_ls(edges, area="0", **kw):
        ls = LinkState(area)
        for db in build_adj_dbs(edges, area=area, **kw).values():
            ls.update_adjacency_database(db)
        return ls

    def cfg_grid16():
        als = {"0": mk_ls(grid_edges(4))}
        ps = PrefixState()
        for i in range(16):
            ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.0.{i}.0/24"))
        return "grid16_shortest_distance", als, ps, "node0", {}

    def cfg_ksp2_fabric():
        edges = fabric_edges(num_pods=3, rsws_per_pod=4, fsws_per_pod=2,
                             num_ssws=4)
        als = {"0": mk_ls(edges)}
        ps = PrefixState()
        rsws = sorted(n for e in edges for n in e[:2] if n.startswith("rsw"))
        for i, n in enumerate(dict.fromkeys(rsws)):
            ps.update_prefix(n, "0", PrefixEntry(
                f"10.{i}.0.0/24",
                forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP))
        return "ksp2_fabric", als, ps, "rsw0_0", {}

    def cfg_multiarea_ribpolicy():
        als = {
            "1": mk_ls(grid_edges(3), "1"),
            "2": mk_ls(ring_edges(6, prefix="b") + [("b0", "node0", 1)], "2"),
        }
        ps = PrefixState()
        ps.update_prefix("node8", "1", PrefixEntry("10.0.0.0/24"))
        ps.update_prefix("b3", "2", PrefixEntry("10.0.0.0/24"))
        ps.update_prefix("b4", "2", PrefixEntry("10.1.0.0/24"))
        policy = RibPolicy(
            statements=[RibPolicyStatement(
                name="prefer-area1",
                prefixes=["10.0.0.0/24"],
                action=RibRouteActionWeight(
                    default_weight=1, area_to_weight={"1": 2}),
            )],
            valid_until=300.0,
        )
        return "multiarea_ribpolicy", als, ps, "node0", {"policy": policy}

    def cfg_sr_mpls():
        edges = fabric_edges(num_pods=2, rsws_per_pod=3, fsws_per_pod=2,
                             num_ssws=2)
        nodes = sorted({n for e in edges for n in e[:2]})
        labels = {n: 100 + i for i, n in enumerate(nodes)}
        als = {"0": mk_ls(edges, node_labels=labels)}
        ps = PrefixState()
        ps.update_prefix("rsw1_2", "0", PrefixEntry(
            "2001:db8::/64",
            forwarding_type=PrefixForwardingType.SR_MPLS,
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP))
        return (
            "sr_mpls_labels", als, ps, "rsw0_0",
            {"solver_kwargs": {"enable_node_segment_label": True}},
        )

    all_on_device = True
    for cfg in (cfg_grid16, cfg_ksp2_fabric, cfg_multiarea_ribpolicy,
                cfg_sr_mpls):
        name, als, ps, me, extra = cfg()
        skw = extra.get("solver_kwargs", {})
        backend = TpuBackend(SpfSolver(me, **skw))
        db = backend.build_route_db(als, ps)
        ref = ScalarBackend(SpfSolver(me, **skw)).build_route_db(als, ps)
        policy = extra.get("policy")
        if policy is not None:
            clock = SimClock()
            for d in (db, ref):
                assert policy.apply_policy(d, clock) > 0
        from openr_tpu.decision.rib import route_db_summary

        match = route_db_summary(db) == route_db_summary(ref)
        on_device = backend.num_scalar_builds == 0 and match
        all_on_device &= on_device
        results.append(_result(
            f"parity_{name}_on_device", 1.0 if on_device else 0.0, "bool",
            scalar_builds=backend.num_scalar_builds,
            device_builds=backend.num_device_builds,
            matches_oracle=match,
        ))
    results.append(_result(
        "parity_configs_device_coverage", 1.0 if all_on_device else 0.0,
        "fraction"))


def bench_fleet_rib(results: List[Dict], full: bool) -> None:
    """Network-wide RIB: every node's route table from one batched device
    solve (ops/fleet_tables.py) vs sequential scalar per-vantage passes (the
    reference's only mode, Decision.cpp:342 per getRouteDbComputed call).
    The scalar side measures a sample of roots and reports the measured
    per-root cost; 'scalar_projected_s' = per_root x V is labeled as a
    projection, not a measurement."""
    from openr_tpu.decision.fleet import FleetRibEngine
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import (
        build_adj_dbs,
        grid_edges,
        random_connected_edges,
    )
    from openr_tpu.types import PrefixEntry

    edges = (
        random_connected_edges(1024, 2048, seed=7) if full else grid_edges(16)
    )
    ls = LinkState("0")
    dbs = build_adj_dbs(edges)
    for db in dbs.values():
        ls.update_adjacency_database(db)
    nodes = sorted(dbs)
    V = len(nodes)
    ps = PrefixState()
    for i, node in enumerate(nodes):
        ps.update_prefix(
            node, "0", PrefixEntry(f"10.{(i >> 8) & 255}.{i & 255}.0/24")
        )
    als = {"0": ls}

    eng = FleetRibEngine(SpfSolver(nodes[0]))
    assert eng.eligible(als, ps, change_seq=0)
    eng.compute_for_node(nodes[0], als, ps, change_seq=0)  # warm/compile
    t0 = time.perf_counter()
    # change_seq bump = cache miss: measures a full re-solve
    eng.compute_for_node(nodes[0], als, ps, change_seq=1)
    batch_s = time.perf_counter() - t0
    # decoding EVERY root's RouteDb from the cached tables (decode is
    # per-request in production; this measures the full-fleet cost the
    # batch number doesn't include)
    t0 = time.perf_counter()
    for node in nodes:
        eng.compute_for_node(node, als, ps, change_seq=1)
    decode_all_s = time.perf_counter() - t0

    # scalar: at --full, ONE measured full fleet (the honest denominator
    # for the headline speedup); quick mode keeps the 8-root sample and
    # labels the result a projection
    if full:
        t0 = time.perf_counter()
        for node in nodes:
            SpfSolver(node).build_route_db(als, ps)
        scalar_full_s = time.perf_counter() - t0
        per_root_s = scalar_full_s / V
    else:
        sample = nodes[:: max(1, V // 8)][:8]
        t0 = time.perf_counter()
        for node in sample:
            SpfSolver(node).build_route_db(als, ps)
        per_root_s = (time.perf_counter() - t0) / len(sample)
        scalar_full_s = None

    detail = dict(
        batch_s=round(batch_s, 3),
        decode_all_ms=round(decode_all_s * 1000, 1),
        scalar_per_root_ms=round(per_root_s * 1000, 2),
        nodes=V,
    )
    if scalar_full_s is not None:
        detail["scalar_measured_s"] = round(scalar_full_s, 1)
        detail["measured_speedup"] = round(scalar_full_s / batch_s, 1)
        # end-to-end: batch solve + decoding every root, vs the measured
        # full scalar fleet (which also materializes every RouteDb)
        detail["measured_speedup_incl_decode_all"] = round(
            scalar_full_s / (batch_s + decode_all_s), 1
        )
    else:
        detail["scalar_projected_s"] = round(per_root_s * V, 1)
        detail["projected_speedup"] = round(per_root_s * V / batch_s, 1)
        detail["scalar_sample_roots"] = 8
    results.append(
        _result(
            f"fleet_rib_all_roots_{V}",
            V / batch_s,
            "vantage_ribs/s",
            **detail,
        )
    )


def bench_p50_convergence(results: List[Dict], full: bool) -> None:
    """North-star metric 2 (BASELINE.md): p50 publication→FIB-programmed
    convergence on the device path.  Drives the REAL Decision + Fib actors
    (debounce, queues, route-delta diff, FIB programming) on a SimClock:
    virtual time costs nothing, so the measured wall-clock IS the compute
    latency the 10-250ms debounce budget (OpenrConfig.thrift:105-108) must
    absorb.  Steady state is a 4096-node grid (--full; 256 quick) with one
    loopback per node plus prefix density; each sample advertises a batch
    of 10 prefixes in one publication and waits until the mock FIB agent
    holds them."""
    import asyncio
    import json as _json
    import statistics

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import DecisionConfig, FibConfig
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.decision import Decision
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
    from openr_tpu.fib.fib import Fib, MockFibAgent
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.types import (
        InitializationEvent,
        PrefixDatabase,
        PrefixEntry,
        PrefixMetrics,
        Publication,
        Value,
        prefix_key,
    )

    side = 64 if full else 16
    ppn = 100 if full else 10  # density beyond the per-node loopback
    samples = 20 if full else 8
    batch = 10

    async def run():
        clock = SimClock()
        solver = SpfSolver("node0")
        backend = TpuBackend(solver)
        routes_q = ReplicateQueue("routes")
        kv_q = ReplicateQueue("kv")
        agent = MockFibAgent(clock)
        decision = Decision(
            "node0",
            clock,
            DecisionConfig(debounce_min_ms=10, debounce_max_ms=250),
            routes_q,
            kv_store_updates_reader=kv_q.get_reader(),
            backend=backend,
            solver=solver,
        )
        fib = Fib(
            node_name="node0",
            clock=clock,
            config=FibConfig(),
            agent=agent,
            route_updates_reader=routes_q.get_reader(),
        )
        decision.start()
        fib.start()
        decision.on_initialization_event(InitializationEvent.KVSTORE_SYNCED)

        edges = grid_edges(side)
        dbs = build_adj_dbs(edges)
        n = len(dbs)

        def val(node, obj):
            return Value(
                version=1,
                originator_id=node,
                value=_json.dumps(obj.to_wire()).encode(),
            )

        kv_q.push(
            Publication(
                key_vals={f"adj:{node}": val(node, db) for node, db in dbs.items()}
            )
        )
        # one loopback + (ppn-1) density prefixes per node, pushed in
        # node-sized publications (not timed; builds the steady state)
        for i, node in enumerate(sorted(dbs)):
            kvs = {}
            for p in range(ppn):
                pfx = f"10.{(i >> 8) & 255}.{i & 255}.{p}/32"
                pdb = PrefixDatabase(
                    this_node_name=node, prefix_entries=[PrefixEntry(pfx)]
                )
                kvs[prefix_key(node, pfx)] = val(node, pdb)
            kv_q.push(Publication(key_vals=kvs))

        t0 = time.perf_counter()
        while not decision._first_build_done or len(agent.unicast) < (n - 1) * ppn:
            await clock.run_for(0.05)
            if time.perf_counter() - t0 > 1800:
                raise RuntimeError(
                    f"initial build stalled: {len(agent.unicast)} routes"
                )
        initial_ms = (time.perf_counter() - t0) * 1000

        lat_ms = []
        all_nodes = sorted(dbs)
        for s in range(-1, samples):  # s == -1: untimed jit-compile warmup
            # never the local node: its own advertisements are skip-if-self
            # and would produce no FIB route to wait for
            node = all_nodes[1 + (s * 37) % (n - 1)]
            kvs = {}
            want = []
            for b in range(batch):
                pfx = f"172.20.{s & 255}.{b}/32"
                pdb = PrefixDatabase(
                    this_node_name=node,
                    prefix_entries=[
                        PrefixEntry(
                            pfx, metrics=PrefixMetrics(path_preference=1000)
                        )
                    ],
                )
                kvs[prefix_key(node, pfx)] = val(node, pdb)
                want.append(pfx)
            t0 = time.perf_counter()
            kv_q.push(Publication(key_vals=kvs))
            while not all(p in agent.unicast for p in want):
                await clock.run_for(0.02)
                if time.perf_counter() - t0 > 300:
                    raise RuntimeError("churn sample stalled")
            if s >= 0:
                lat_ms.append((time.perf_counter() - t0) * 1000)
        await decision.stop()
        await fib.stop()
        return initial_ms, lat_ms, backend

    initial_ms, lat_ms, backend = asyncio.run(run())
    lat_sorted = sorted(lat_ms)
    p50 = statistics.median(lat_sorted)
    p95 = lat_sorted[max(0, int(round(0.95 * len(lat_sorted))) - 1)]
    results.append(
        _result(
            f"p50_publication_to_fib_ms_grid{side * side}",
            p50,
            "ms",
            p95_ms=round(p95, 1),
            samples=len(lat_ms),
            batch_per_sample=batch,
            nodes=side * side,
            total_prefixes=side * side * ppn,
            initial_full_build_ms=round(initial_ms, 1),
            incremental_builds=backend.num_incremental_builds,
            within_debounce_budget=bool(p50 <= 250.0),
        )
    )


# ---------------------------------------------------------------------------
# KvStore (KvStoreBenchmarkTest.cpp, KvStoreConvergenceBenchmark.cpp)
# ---------------------------------------------------------------------------

def bench_kvstore_persist(results: List[Dict], full: bool) -> None:
    import asyncio

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import KvStoreConfig
    from openr_tpu.kvstore.kv_store import KvStore
    from openr_tpu.kvstore.transport import InProcessTransport
    from openr_tpu.messaging.queue import ReplicateQueue

    sizes = [100, 1000, 10_000] if full else [100, 1000]
    for n in sizes:
        async def run(n=n):
            clock = SimClock()
            store = KvStore(
                node_name="b0",
                clock=clock,
                config=KvStoreConfig(),
                areas=["0"],
                transport=InProcessTransport(clock),
                publications_queue=ReplicateQueue("pubs"),
            )
            db = store.areas["0"]
            payload = b"x" * 128
            t0 = time.perf_counter()
            for i in range(n):
                db.persist_self_originated_key(f"prefix:b0:k{i}", payload)
            dt = time.perf_counter() - t0
            # update pass: same keys, new values (version bump path)
            t0 = time.perf_counter()
            for i in range(n):
                db.persist_self_originated_key(f"prefix:b0:k{i}", payload + b"y")
            dt_update = time.perf_counter() - t0
            await store.stop()
            return dt, dt_update

        dt, dt_update = asyncio.run(run())
        results.append(
            _result(f"kvstore_persist_{n}", n / dt, "keys/s")
        )
        results.append(
            _result(f"kvstore_update_{n}", n / dt_update, "keys/s")
        )


def bench_kvstore_flood_convergence(results: List[Dict], full: bool) -> None:
    """N stores in a line; one key injected at the head; time until every
    store holds it (virtual time = protocol latency, wall time = compute)."""
    import asyncio

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import KvStoreConfig
    from openr_tpu.kvstore.kv_store import KvStore
    from openr_tpu.kvstore.transport import InProcessTransport
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.types import PeerSpec

    n = 64 if full else 16

    async def run():
        clock = SimClock()
        transport = InProcessTransport(clock, latency_s=0.001)
        stores = []
        for i in range(n):
            store = KvStore(
                node_name=f"s{i}",
                clock=clock,
                config=KvStoreConfig(),
                areas=["0"],
                transport=transport,
                publications_queue=ReplicateQueue(f"pubs{i}"),
            )
            transport.register(f"s{i}", store)
            stores.append(store)
            store.start()
        for i, store in enumerate(stores):
            peers = {}
            if i > 0:
                peers[f"s{i - 1}"] = PeerSpec()
            if i < n - 1:
                peers[f"s{i + 1}"] = PeerSpec()
            store.areas["0"].add_peers(peers)
        await clock.run_for(5.0)

        t_wall = time.perf_counter()
        t_virtual = clock.now()
        stores[0].areas["0"].persist_self_originated_key("prefix:s0:x", b"v")
        while not all("prefix:s0:x" in s.areas["0"].key_vals for s in stores):
            await clock.run_for(0.05)
            if clock.now() - t_virtual > 60:
                raise RuntimeError("flood did not converge")
        wall = time.perf_counter() - t_wall
        virtual = clock.now() - t_virtual
        for store in stores:
            await store.stop()
        return wall, virtual

    wall, virtual = asyncio.run(run())
    results.append(
        _result(
            f"kvstore_flood_convergence_{n}",
            virtual * 1000,
            "virtual_ms",
            wall_ms=round(wall * 1000, 1),
            stores=n,
        )
    )


# ---------------------------------------------------------------------------
# Fib (FibBenchmark.cpp)
# ---------------------------------------------------------------------------

def bench_fib_programming(results: List[Dict], full: bool) -> None:
    import asyncio

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import FibConfig
    from openr_tpu.decision.rib import (
        DecisionRouteUpdate,
        DecisionRouteUpdateType,
        RibUnicastEntry,
    )
    from openr_tpu.fib.fib import Fib, MockFibAgent
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.types import NextHop

    n = 10_000 if full else 2_000

    async def run():
        clock = SimClock()
        agent = MockFibAgent(clock)
        q = ReplicateQueue("routes")
        fib = Fib(
            node_name="b0",
            clock=clock,
            config=FibConfig(),
            agent=agent,
            route_updates_reader=q.get_reader(),
        )
        fib.start()
        routes = {
            f"10.{(i >> 8) & 255}.{i & 255}.0/24": RibUnicastEntry(
                prefix=f"10.{(i >> 8) & 255}.{i & 255}.0/24",
                nexthops=[NextHop(address="fe80::1", if_name="eth0")],
            )
            for i in range(n)
        }
        t0 = time.perf_counter()
        q.push(
            DecisionRouteUpdate(
                type=DecisionRouteUpdateType.FULL_SYNC,
                unicast_routes_to_update=routes,
            )
        )
        while len(agent.unicast) < n:
            await clock.run_for(0.05)
        dt = time.perf_counter() - t0
        await fib.stop()
        return dt

    dt = asyncio.run(run())
    results.append(_result(f"fib_program_{n}", n / dt, "routes/s"))


# ---------------------------------------------------------------------------
# PrefixManager (PrefixManagerBenchmarkTest.cpp)
# ---------------------------------------------------------------------------

def bench_prefix_manager_advertise(results: List[Dict], full: bool) -> None:
    import asyncio

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.prefix_manager.prefix_manager import PrefixManager
    from openr_tpu.types import (
        PrefixEntry,
        PrefixEvent,
        PrefixEventType,
    )

    n = 10_000 if full else 2_000

    async def run():
        clock = SimClock()
        kv_q = ReplicateQueue("kvreq")
        kv_r = kv_q.get_reader()
        prefix_q = ReplicateQueue("prefixEvents")
        pm = PrefixManager(
            node_name="b0",
            clock=clock,
            kv_request_queue=kv_q,
            prefix_updates_reader=prefix_q.get_reader(),
        )
        pm.start()
        await clock.run_for(0.1)
        while kv_r.try_get() is not None:
            pass
        entries = [
            PrefixEntry(prefix=f"10.{(i >> 8) & 255}.{i & 255}.0/24")
            for i in range(n)
        ]
        t0 = time.perf_counter()
        prefix_q.push(
            PrefixEvent(
                event_type=PrefixEventType.ADD_PREFIXES, prefixes=entries
            )
        )
        seen = 0
        while seen < n:
            await clock.run_for(0.05)
            while kv_r.try_get() is not None:
                seen += 1
        dt = time.perf_counter() - t0
        await pm.stop()
        return dt

    dt = asyncio.run(run())
    results.append(_result(f"prefix_manager_advertise_{n}", n / dt, "prefixes/s"))


# ---------------------------------------------------------------------------
# Messaging (MessagingBenchmark.cpp)
# ---------------------------------------------------------------------------

def bench_messaging(results: List[Dict], full: bool) -> None:
    import asyncio

    from openr_tpu.messaging.queue import ReplicateQueue

    n = 200_000 if full else 50_000
    readers = 4

    async def run():
        q = ReplicateQueue("bench")
        rs = [q.get_reader() for _ in range(readers)]
        t0 = time.perf_counter()

        async def drain(r):
            for _ in range(n):
                await r.get()

        tasks = [asyncio.ensure_future(drain(r)) for r in rs]
        for i in range(n):
            q.push(i)
            if i % 4096 == 0:
                await asyncio.sleep(0)  # let readers drain; bounds memory
        await asyncio.gather(*tasks)
        return time.perf_counter() - t0

    dt = asyncio.run(run())
    results.append(
        _result(
            "messaging_replicate_throughput",
            n * readers / dt,
            "deliveries/s",
            items=n,
            readers=readers,
        )
    )


def bench_whatif_double_failures(results: List[Dict], full: bool) -> None:
    """Exhaustive DOUBLE-failure analysis: every unordered pair of
    links failed simultaneously (the maintenance-window question "is
    there any second failure that partitions us?").  Pairs scale as
    L^2/2 — the batch shape the set-repair kernel exists for; the
    native baseline is the same exhaustive loop over
    spf_scalar_solve_set (sampled, then extrapolated, when full=False).
    """
    import itertools

    import numpy as np

    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.emulation.topology import (
        build_adj_dbs,
        random_connected_edges,
    )
    from openr_tpu.ops.csr import encode_link_state
    from openr_tpu.ops.native_spf import NativeSpf
    from openr_tpu.ops.whatif import LinkFailureSweep

    # pairs scale as L^2/2, with L = (nodes-1) tree edges + extra
    # chords: 128 nodes + 128 chords -> L=255 -> ~32k solves (CPU
    # smoke); --full 256+256 -> L=511 -> ~130k (a device-scale batch)
    n_nodes, extra = (128, 128) if not full else (256, 256)
    edges = random_connected_edges(n_nodes, extra, seed=21)
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    topo = encode_link_state(ls)
    L = len(topo.links)
    pairs = list(itertools.combinations(range(L), 2))

    eng = LinkFailureSweep(topo, "node0")
    eng.base_solve()
    sets_mat = np.asarray(pairs, np.int32)
    res = eng.run_sets(sets_mat, fetch=False)  # warm-up compile
    res.block()
    t0 = time.perf_counter()
    res = eng.run_sets(sets_mat, fetch=False)
    res.block()
    device_s = time.perf_counter() - t0
    # partition scan: pairs whose failure disconnects some node — one
    # bool per UNIQUE solve row, then mapped through snap_row.  Only the
    # dist chunks are fetched (one overlapped device_get); materialize()
    # would also pull + bit-unpack the nh tables this scan never reads.
    import jax

    from openr_tpu.ops.consts import BIG

    U = 1 + res.num_device_solves
    row_partitions = np.zeros(U, bool)  # base row: connected graph
    dists_h = jax.device_get([c[2] for c in res.chunks or []])
    for (off, n, _dd, _nd), dist_h in zip(res.chunks or [], dists_h):
        row_partitions[1 + off : 1 + off + n] = (
            dist_h[: topo.num_nodes, :n] >= BIG
        ).any(axis=0)
    n_partitioning = int(row_partitions[res.snap_row].sum())

    nat = NativeSpf(topo, "node0")
    sample = pairs if full else pairs[:: max(1, len(pairs) // 2000)]
    t0 = time.perf_counter()
    for pr in sample:
        nat.solve_set(list(pr))
    native_s_sample = time.perf_counter() - t0
    native_s = native_s_sample * (len(pairs) / len(sample))

    results.append(
        _result(
            f"whatif_double_failures_L{L}",
            len(pairs) / device_s,
            "pairs/s",
            pairs=len(pairs),
            device_s=round(device_s, 3),
            native_set_solver_s=round(native_s, 3),
            native_sampled=not full,
            speedup=round(native_s / device_s, 1),
            partitioning_pairs=n_partitioning,
            nodes=n_nodes,
        )
    )


ALL_BENCHES = [
    bench_decision_initial,
    bench_decision_adj_update,
    bench_decision_prefix_update,
    bench_parity_device_coverage,
    bench_fleet_rib,
    bench_p50_convergence,
    bench_whatif_double_failures,
    bench_kvstore_persist,
    bench_kvstore_flood_convergence,
    bench_fib_programming,
    bench_prefix_manager_advertise,
    bench_messaging,
]


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--full", action="store_true",
                   help="reference-scale sizes (slower)")
    p.add_argument("--json", default="BENCH_SUITE.json")
    p.add_argument("--only", default="",
                   help="substring filter on bench function names")
    args = p.parse_args()
    # an explicit CPU request must win BEFORE the first jax import: a
    # site hook may force-select a tunneled accelerator whose remote
    # init blocks indefinitely (a CPU smoke run would hang forever)
    from openr_tpu.ops.platform_env import (
        enable_persistent_compile_cache,
        fallback_to_cpu_if_unreachable,
        honor_cpu_platform_request,
    )

    honor_cpu_platform_request()
    fallback_to_cpu_if_unreachable()
    enable_persistent_compile_cache()
    results: List[Dict] = []
    t0 = time.time()
    for bench in ALL_BENCHES:
        if args.only and args.only not in bench.__name__:
            continue
        bench(results, args.full)
    import jax

    from bench import env_stamp

    with open(args.json, "w") as f:
        json.dump(
            {
                # the platform stamp keeps CPU smoke runs from being
                # mistaken for device measurements
                "devices": [str(d) for d in jax.devices()],
                "env": env_stamp(),
                "full": args.full,
                "results": results,
                "wall_s": round(time.time() - t0, 1),
            },
            f,
            indent=2,
        )
    print(f"# {len(results)} results -> {args.json}", flush=True)


if __name__ == "__main__":
    main()
