"""Tier-1 chaos smoke: three small seeded scenarios, one per recovery
mechanism — partition+heal (KvStore re-sync), fib-agent burst (retry with
backoff + exported counters), actor crash (supervisor restart).  Long
randomized sweeps live in test_chaos_sweep.py behind -m slow.
"""

import asyncio

import pytest

from openr_tpu.chaos import ChaosController, FaultPlan, InvariantChecker, Supervisor
from openr_tpu.common.runtime import SimClock
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import line_edges, ring_edges

CONVERGE_S = 12.0


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def fast_watchdog(cfg):
    cfg.watchdog_config.interval_s = 1.0


@pytest.mark.chaos
def test_partition_and_heal_reconverges():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(ring_edges(4))
        net.start()
        checker = InvariantChecker(net)
        plan = FaultPlan().partition(
            ("node0",), ("node1", "node2", "node3"), at=0.0, duration=10.0
        )
        controller = ChaosController(net, plan, seed=11)
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why
        controller.start()
        await clock.run_for(5.0)
        checker.sample()
        # during the partition the majority component stays consistent
        checker.check_lsdb_converged(nodes=("node1", "node2", "node3"))
        # the isolated node lost its adjacencies: no route out, and no
        # stale blackholed routes either
        await clock.run_for(5.0)  # heal fires at t=10
        await clock.run_for(15.0)  # reconverge
        checker.check_all()
        assert controller.done
        dump = controller.counter_dump()
        assert dump["chaos.injects"] == 1 and dump["chaos.heals"] == 1
        await controller.stop()
        await net.stop()

    run(main())


@pytest.mark.chaos
def test_fib_agent_burst_retries_with_backoff():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(line_edges(3))
        net.start()
        await clock.run_for(CONVERGE_S)
        node1 = net.nodes["node1"]
        plan = FaultPlan().fib_burst("node1", at=0.0, duration=6.0)
        controller = ChaosController(net, plan, seed=5)
        controller.start()
        await clock.run_for(1.0)
        # poke a route change while the agent is failing: programming
        # fails, Fib goes dirty, backoff engages (the withdrawal reaches
        # the agent via the 1s-delayed delete, so give it ~3.5s)
        net.fail_link("node1", "node2")
        await clock.run_for(3.5)
        assert node1.counters.get("fib.programming_failures") > 0
        assert node1.fib.retry_state()["fib.dirty"] == 1.0
        await clock.run_for(12.0)  # burst heals at t=6; retries drain
        assert node1.fib.retry_state()["fib.dirty"] == 0.0
        assert node1.fib.num_retries > 0
        # retry/backoff state is exported through the Monitor provider
        # sweep into the node's counters (ctrl getCounters surface)
        node1.monitor.sample_system_metrics()
        assert node1.counters.get("fib.retries") == node1.fib.num_retries
        assert "fib.backoff_ms" in node1.counters.dump("fib.")
        # desired == programmed after recovery (node2 unreachable now,
        # but nothing stale/blackholed is left programmed)
        InvariantChecker(net).check_no_blackholes()
        await controller.stop()
        await net.stop()

    run(main())


@pytest.mark.chaos
def test_partition_heal_traces_close_end_to_end():
    """Tracing under faults: after a partition heals, the re-discovery
    event still produces a COMPLETE trace (origin span → fib.ack, no
    open spans left in its tree) and `trace.dropped_spans` stays bounded
    — chaos must not leak open spans."""

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(ring_edges(4))
        net.start()
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why
        plan = FaultPlan().partition(
            ("node0",), ("node1", "node2", "node3"), at=0.0, duration=8.0
        )
        controller = ChaosController(net, plan, seed=17)
        controller.start()
        await clock.run_for(8.0)  # partition holds; spark holds expire
        heal_mark = len(net.all_spans())
        await clock.run_for(20.0)  # heal fired at t=8; reconverge
        ok, why = net.converged_full_mesh()
        assert ok, why
        # spans recorded AFTER the heal: the rediscovered adjacency must
        # close end-to-end (spark origin on one side, fib.ack on nodes
        # across the former partition boundary)
        post_heal = net.all_spans()[heal_mark:]
        acks = [s for s in post_heal if s.name == "fib.ack"]
        assert acks, "no fib.ack span after heal"
        healed = [
            s
            for s in acks
            if s.attrs.get("origin_node") == "node0" and s.node != "node0"
        ]
        assert healed, "healed event's trace never closed on the far side"
        tid = healed[0].trace_id
        tree = net.all_spans(trace_id=tid)
        assert {s.node for s in tree} >= {"node0", healed[0].node}
        assert all(s.end_ms is not None for s in tree)
        assert any(s.name.startswith("spark.") for s in tree)
        # drops stay bounded through the fault (no open-span leak): the
        # partition orphans at most the in-flight rebuilds of that tick
        for name, node in net.nodes.items():
            assert node.tracer.num_dropped == 0, (
                name,
                node.tracer.stats(),
            )
        # and the convergence histogram kept observing through the chaos
        merged = net.merged_histogram("convergence.event_to_fib_ms")
        assert merged is not None and merged.count > 0
        await controller.stop()
        await net.stop()

    run(main())


@pytest.mark.chaos
def test_actor_crash_restarts_without_systemexit():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=fast_watchdog)
        net.build(line_edges(2))
        net.start()
        supervisor = Supervisor(clock, initial_backoff_s=0.25, max_backoff_s=2.0)
        supervisor.start()
        for name, node in net.nodes.items():
            supervisor.supervise(name, node, net.restart_node)
        await clock.run_for(CONVERGE_S)
        old = net.nodes["node0"]
        plan = FaultPlan().actor_kill("node0", "fib", at=0.0)
        controller = ChaosController(net, plan, seed=3)
        controller.start()
        # watchdog sweep (1s) notices the dead fiber -> supervisor restart
        await clock.run_for(20.0)
        assert supervisor.num_crashes >= 1
        assert supervisor.num_restarts == 1
        assert net.nodes["node0"] is not old
        assert net.nodes["node0"].initialized
        ok, why = net.converged_full_mesh()
        assert ok, why
        InvariantChecker(net).check_all()
        await supervisor.stop()
        await controller.stop()
        await net.stop()

    run(main())
