"""KvStore eventual-consistency property tests — randomized schedules.

SURVEY §7 hard-part 5 / VERDICT r2 item 6: the reference's merge rules
(KvStoreUtil.cpp:391 mergeKeyValues, :470 compareValues) must make every
interleaving of merge/flood/full-sync/TTL-expiry/failure events converge
to ONE map on every store.  Each schedule here runs REAL KvStore actors
over the in-process transport (real peer FSM, 3-way sync, flooding,
backoff, TTL countdown) on a virtual clock:

  * 3-5 stores on a random connected topology (spanning tree + chords)
  * peers wired in random order at random times
  * conflicting writes: overlapping keys injected via set_key_vals with
    random (version, originator, value, ttl_version), plus per-store
    self-originated keys (whose owners must win back override attempts)
  * link failures: random (src, dst) call-blackholes opened and healed
  * peer flaps: del_peers + re-add
  * TTL: short-lived injected keys must expire EVERYWHERE; long-lived
    keys must survive

After the schedule, everything heals and the network settles in virtual
time; every store must hold the identical (version, originator, value,
ttl_version) map, with every short-TTL key gone.  100+ seeds run in CI
(virtual time makes each schedule ~wall-milliseconds).
"""

import asyncio
import random

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import KvStoreConfig
from openr_tpu.kvstore.kv_store import KvStore
from openr_tpu.kvstore.transport import InProcessTransport
from openr_tpu.messaging.queue import ReplicateQueue

AREA = "0"
SHORT_TTL_MS = 3_000
LONG_TTL_MS = 3_600_000


def snapshot(store: KvStore):
    return {
        k: (v.version, v.originator_id, v.value, v.ttl_version)
        for k, v in store.areas[AREA].key_vals.items()
    }


def random_connected_edges(rng: random.Random, n: int):
    """Random spanning tree + up to n extra chords."""
    edges = set()
    order = list(range(n))
    rng.shuffle(order)
    for i in range(1, n):
        a = order[i]
        b = order[rng.randrange(i)]
        edges.add((min(a, b), max(a, b)))
    for _ in range(rng.randrange(n + 1)):
        a, b = rng.randrange(n), rng.randrange(n)
        if a != b:
            edges.add((min(a, b), max(a, b)))
    return sorted(edges)


async def run_schedule(seed: int) -> None:
    rng = random.Random(seed)
    clock = SimClock()
    transport = InProcessTransport(
        clock, latency_s=rng.choice([0.0, 0.001, 0.01])
    )
    n = rng.randint(3, 5)
    names = [f"s{i}" for i in range(n)]
    cfg = KvStoreConfig(
        key_ttl_ms=LONG_TTL_MS, self_originated_key_ttl_ms=LONG_TTL_MS
    )
    stores = []
    for name in names:
        store = KvStore(
            node_name=name,
            clock=clock,
            config=cfg,
            areas=[AREA],
            transport=transport,
            publications_queue=ReplicateQueue(f"{name}.pubs"),
        )
        transport.register(name, store)
        stores.append(store)
        store.start()

    edges = random_connected_edges(rng, n)
    peer_specs = {i: {} for i in range(n)}
    for a, b in edges:
        peer_specs[a][names[b]] = None
        peer_specs[b][names[a]] = None

    from openr_tpu.types import PeerSpec, Value

    # wire peers in random order, possibly interleaved with early writes
    wiring = [(i, peer) for i in range(n) for peer in peer_specs[i]]
    rng.shuffle(wiring)

    failed_pairs = set()
    short_ttl_keys = set()
    #: (owner_name, key) pairs actually persisted — only these are
    #: defended by their owner's _guard_self_originated
    self_originated = set()

    def inject_write(step: int) -> None:
        store = rng.choice(stores)
        kind = rng.random()
        if kind < 0.45:
            # conflicting plain key: overlapping names, random attributes
            key = f"conf:k{rng.randrange(8)}"
            val = Value(
                version=rng.randint(1, 6),
                originator_id=f"s{rng.randrange(n)}",
                value=bytes([rng.randrange(256)]) * rng.randint(1, 3),
                ttl=LONG_TTL_MS,
                ttl_version=rng.randrange(3),
            )
            store.set_key_vals(AREA, {key: val})
        elif kind < 0.6:
            # short-TTL key: must be gone everywhere at the end
            key = f"ttl:k{step}"
            short_ttl_keys.add(key)
            store.set_key_vals(
                AREA,
                {
                    key: Value(
                        version=1,
                        originator_id=store.node_name,
                        value=b"dying",
                        ttl=SHORT_TTL_MS,
                    )
                },
            )
        elif kind < 0.8:
            # self-originated persist (owner refreshes + defends it)
            key = f"prefix:{store.node_name}:p{rng.randrange(3)}"
            store.areas[AREA].persist_self_originated_key(
                key, bytes([rng.randrange(256)])
            )
            self_originated.add((store.node_name, key))
        else:
            # override attack on someone's self-originated key: the owner
            # must win it back with a higher version
            victim = rng.choice(stores)
            store.set_key_vals(
                AREA,
                {
                    f"prefix:{victim.node_name}:p0": Value(
                        version=rng.randint(1, 20),
                        originator_id=store.node_name,
                        value=b"squat",
                        ttl=LONG_TTL_MS,
                    )
                },
            )

    def flip_failure() -> None:
        if failed_pairs and rng.random() < 0.5:
            pair = rng.choice(sorted(failed_pairs))
            failed_pairs.discard(pair)
            transport.heal(*pair)
        else:
            a, b = rng.sample(range(n), 2)
            failed_pairs.add((names[a], names[b]))
            transport.fail(names[a], names[b])

    def flap_peer() -> None:
        a, b = rng.choice(edges)
        stores[a].areas[AREA].del_peers([names[b]])
        stores[a].areas[AREA].add_peers({names[b]: PeerSpec()})

    # schedule: wiring + ~25 events interleaved in virtual time
    events = [("wire", w) for w in wiring]
    for step in range(25):
        r = rng.random()
        if r < 0.6:
            events.append(("write", step))
        elif r < 0.85:
            events.append(("fail", step))
        else:
            events.append(("flap", step))
    rng.shuffle(events)

    for ev, arg in events:
        await clock.run_for(rng.random() * 2.0)
        if ev == "wire":
            i, peer = arg
            stores[i].areas[AREA].add_peers({peer: PeerSpec()})
        elif ev == "write":
            inject_write(arg)
        elif ev == "fail":
            flip_failure()
        else:
            flap_peer()

    # heal everything and settle: past the max sync backoff (256s,
    # Constants.h / constants.py KVSTORE_SYNC_MAX_BACKOFF_S — a peer that
    # failed repeatedly retries that late) and every short TTL
    for pair in sorted(failed_pairs):
        transport.heal(*pair)
    await clock.run_for(600.0)

    try:
        base = snapshot(stores[0])
        for store in stores[1:]:
            assert snapshot(store) == base, (
                f"seed {seed}: stores diverged"
            )
        for key in short_ttl_keys:
            assert key not in base, f"seed {seed}: {key} survived its TTL"
        # owners won back the self-originated keys they actually persisted
        # (a squat on a never-persisted key name has no defender and
        # legitimately sticks)
        for owner, key in self_originated:
            assert key in base, f"seed {seed}: {key} missing"
            assert base[key][1] == owner, (
                f"seed {seed}: {key} owned by {base[key][1]}, not {owner}"
            )
    finally:
        for store in stores:
            await store.stop()


@pytest.mark.parametrize("chunk", range(4))
def test_randomized_schedules(chunk):
    """100 seeded schedules (25 per chunk for parallelism/granularity)."""

    async def main():
        for seed in range(chunk * 25, (chunk + 1) * 25):
            await run_schedule(seed)

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(main())
    finally:
        loop.close()
