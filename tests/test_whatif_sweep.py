"""Transposed sweep kernels + what-if engine exactness tests.

The engine's optimizations (base aliasing, off-DAG skip, dedup) must be
invisible: every snapshot's results identical to an independent full
solve (and to the Python oracle)."""

import numpy as np
import pytest

from openr_tpu.decision.link_state import LinkState
from openr_tpu.emulation.topology import (
    build_adj_dbs,
    grid_edges,
    random_connected_edges,
)
from openr_tpu.ops.csr import encode_link_state
from openr_tpu.ops.whatif import LinkFailureSweep


def make_topo(edges, **kwargs):
    ls = LinkState("0")
    for db in build_adj_dbs(edges, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls, encode_link_state(ls)


def test_transposed_kernels_match_batch_leading():
    import jax.numpy as jnp

    from openr_tpu.ops.spf import (
        batched_spf_link_failures,
        sweep_spf_link_failures,
    )

    ls, topo = make_topo(random_connected_edges(32, 40, seed=9))
    D = topo.max_out_degree()
    fails = np.array([-1, 0, 3, 7, 11, 3], np.int32)
    B = len(fails)
    d_ref, nh_ref = batched_spf_link_failures(
        jnp.asarray(topo.src),
        jnp.asarray(topo.dst),
        jnp.asarray(topo.w),
        jnp.asarray(topo.edge_ok),
        jnp.asarray(topo.link_index),
        jnp.asarray(fails),
        jnp.tile(jnp.asarray(topo.overloaded), (B, 1)),
        jnp.zeros(B, jnp.int32),
        max_degree=D,
    )
    d_t, nh_t = sweep_spf_link_failures(
        jnp.asarray(topo.src),
        jnp.asarray(topo.dst),
        jnp.asarray(topo.w),
        jnp.asarray(topo.edge_ok),
        jnp.asarray(topo.link_index),
        jnp.asarray(fails),
        jnp.asarray(topo.overloaded),
        jnp.int32(0),
        max_degree=D,
    )
    assert np.array_equal(np.asarray(d_t).T, np.asarray(d_ref))
    assert np.array_equal(
        np.moveaxis(np.asarray(nh_t), 1, 0), np.asarray(nh_ref)
    )


def test_packed_lanes_match_dense():
    import jax.numpy as jnp

    from openr_tpu.ops.spf import (
        spf_distances_sweep,
        spf_lanes_sweep,
        spf_lanes_sweep_packed,
        unpack_lanes,
    )

    ls, topo = make_topo(random_connected_edges(40, 60, seed=15))
    D = topo.max_out_degree()
    fails = np.array([-1, 2, 9, 17], np.int32)
    en = jnp.asarray(
        topo.edge_ok[:, None] & (topo.link_index[:, None] != fails[None, :])
    )
    args = (
        jnp.asarray(topo.src),
        jnp.asarray(topo.dst),
        jnp.asarray(topo.w),
        en,
        jnp.asarray(topo.overloaded),
        jnp.int32(0),
    )
    dist = spf_distances_sweep(*args)
    dense = np.asarray(spf_lanes_sweep(*args, dist, D))
    packed = np.asarray(spf_lanes_sweep_packed(*args, dist, D))
    # segment_max yields int8-min (-128) for empty segments (unreachable
    # or padding nodes); consumers only test lane > 0, so compare that
    assert np.array_equal(unpack_lanes(packed, D), (dense > 0).astype(np.int8))


@pytest.mark.parametrize("seed", [21, 22])
def test_sweep_engine_matches_python_oracle(seed):
    edges = random_connected_edges(48, 60, seed=seed)
    ls, topo = make_topo(edges)
    eng = LinkFailureSweep(topo, "node0")
    rng = np.random.default_rng(seed)
    fails = rng.integers(0, len(topo.links), size=40).astype(np.int32)
    res = eng.run(fails)
    assert res.num_snapshots == 40
    # dedup + off-DAG skip must have collapsed the solve count
    assert res.num_device_solves < len(np.unique(fails))
    for s in (0, 7, 13, 39):
        ref = ls.run_spf(
            "node0", links_to_ignore=frozenset([topo.links[int(fails[s])]])
        )
        dist = res.dist_of(s)
        for node, r in ref.items():
            assert dist[topo.node_id(node)] == np.float32(r.metric), (s, node)
        reached = {topo.node_id(n) for n in ref}
        for v in range(topo.num_nodes):
            if v not in reached:
                assert dist[v] >= 3.0e38


def test_off_dag_failure_aliases_base_and_is_correct():
    # weighted random graph: a uniform grid has every link on some
    # shortest path, so off-DAG links only exist with varied metrics
    ls, topo = make_topo(random_connected_edges(32, 48, seed=31))
    eng = LinkFailureSweep(topo, "node0")
    on_dag = eng.on_dag_links()
    assert (~on_dag).any(), "expected at least one off-DAG link"
    off = int(np.nonzero(~on_dag)[0][0])
    res = eng.run(np.array([off], np.int32))
    assert res.num_device_solves == 0  # aliased to base
    assert res.snap_row[0] == 0
    # and the claim itself: removing that link really changes nothing
    ref = ls.run_spf(
        "node0", links_to_ignore=frozenset([topo.links[off]])
    )
    for node, r in ref.items():
        assert res.dist_of(0)[topo.node_id(node)] == np.float32(r.metric)


def test_sweep_engine_lane_parity_with_native():
    from openr_tpu.ops.native_spf import NativeSpf

    ls, topo = make_topo(random_connected_edges(40, 50, seed=23))
    eng = LinkFailureSweep(topo, "node0")
    native = NativeSpf(topo, "node0")
    fails = np.array([0, 5, 9], np.int32)
    res = eng.run(fails)
    D = eng.D
    for s, fl in enumerate(fails):
        native.solve(failed_link=int(fl))
        finite = np.isfinite(native.dist)
        dist = res.dist_of(s)
        assert np.array_equal(native.dist[finite], dist[finite])
        assert np.array_equal(
            native.lanes_dense(D)[finite], res.nh_of(s)[finite]
        )


def test_sweep_with_overloaded_nodes():
    ls, topo = make_topo(grid_edges(4), overloaded=["node5"])
    eng = LinkFailureSweep(topo, "node0")
    fails = np.arange(len(topo.links), dtype=np.int32)
    res = eng.run(fails)
    for s in range(0, len(fails), 5):
        ref = ls.run_spf(
            "node0", links_to_ignore=frozenset([topo.links[s]])
        )
        dist = res.dist_of(s)
        for node, r in ref.items():
            assert dist[topo.node_id(node)] == np.float32(r.metric)


class TestWarmBaseAcrossGenerations:
    """Cross-generation warm base solve (ops.repair.warm_base_from_
    previous): after LSDB churn the new engine's base must be BIT-EXACT
    vs a cold solve — removals, weight increases/decreases, and link
    additions all covered."""

    def _engines(self, edges_old, edges_new):
        ls_old, topo_old = make_topo(edges_old)
        ls_new, topo_new = make_topo(edges_new)
        old = LinkFailureSweep(topo_old, "node0")
        old.base_solve()
        warm = LinkFailureSweep(topo_new, "node0")
        assert warm.seed_base_from(old), "seed should apply"
        cold = LinkFailureSweep(topo_new, "node0")
        return warm, cold

    def _check(self, edges_old, edges_new):
        warm, cold = self._engines(edges_old, edges_new)
        wd, wn = warm.base_solve()
        assert warm.base_was_warm
        cd, cn = cold.base_solve()
        assert np.array_equal(wd, cd)
        assert np.array_equal(wn, cn)

    def test_link_removal(self):
        edges = grid_edges(6)
        # drop two interior links (every node keeps at least one link,
        # so the symbol tables stay identical across generations)
        self._check(edges, edges[:20] + edges[22:])

    def test_weight_increase_and_decrease(self):
        base = [(a, b, 10) for (a, b, _w) in grid_edges(6)]
        bumped = [
            (a, b, 40 if i == 3 else (1 if i == 5 else w))
            for i, (a, b, w) in enumerate(base)
        ]
        self._check(base, bumped)

    def test_link_addition(self):
        edges = grid_edges(6)
        extra = edges + [("node0", "node35", 3)]
        self._check(edges, extra)

    def test_mixed_churn_sweep_still_exact(self):
        """After a warm-seeded base, the repair sweep on the NEW
        topology must still match the python oracle."""
        edges = grid_edges(5)
        churned = edges[:10] + edges[11:]
        warm, _ = self._engines(edges, churned)
        ls_new, topo_new = make_topo(churned)
        L = len(topo_new.links)
        fails = np.arange(L, dtype=np.int32)
        res = warm.run(fails, fetch=True)
        for li in range(0, L, 5):
            ref = ls_new.run_spf(
                "node0", links_to_ignore=frozenset([topo_new.links[li]])
            )
            d = res.dist_of(li)
            for node, r in ref.items():
                assert d[topo_new.node_id(node)] == r.metric, (li, node)

    def test_node_set_change_falls_back_cold(self):
        ls_old, topo_old = make_topo(grid_edges(6))
        ls_new, topo_new = make_topo(grid_edges(5))
        old = LinkFailureSweep(topo_old, "node0")
        old.base_solve()
        warm = LinkFailureSweep(topo_new, "node0")
        assert not warm.seed_base_from(old)
        d, _ = warm.base_solve()
        assert not warm.base_was_warm
        ref = ls_new.run_spf("node0")
        for node, r in ref.items():
            assert d[topo_new.node_id(node)] == r.metric


def test_native_base_solve_bit_matches_device_base(monkeypatch):
    """The engine seeds its base solve from the native C++ Dijkstra
    (~1 ms) instead of the cold device kernel (~2.4 s compile+solve on a
    tunneled chip — the old first-what-if-after-restart latency).  The
    two bases must be bit-identical, and sweeps from either base must
    produce identical route tables."""
    _, topo = make_topo(random_connected_edges(48, 96, seed=13))
    eng_native = LinkFailureSweep(topo, "node0")
    base_n = eng_native.base_solve()
    assert eng_native.base_source == "native"

    # force the device path by making the native import fail
    import openr_tpu.ops.native_spf as native_mod

    class Boom:
        def __init__(self, *a, **k):
            raise RuntimeError("forced device path")

    monkeypatch.setattr(native_mod, "NativeSpf", Boom)
    eng_device = LinkFailureSweep(topo, "node0")
    base_d = eng_device.base_solve()
    assert eng_device.base_source == "device"

    assert np.array_equal(base_n[0], base_d[0])  # dist bit parity
    assert np.array_equal(base_n[1], base_d[1])  # lane bit parity

    fails = np.arange(min(48, len(topo.links)), dtype=np.int32)
    r_n = eng_native.run(fails)
    r_d = eng_device.run(fails)
    assert np.array_equal(r_n.snap_row, r_d.snap_row)
    for s in range(0, len(fails), 7):
        assert np.array_equal(r_n.dist_of(s), r_d.dist_of(s))
        assert np.array_equal(r_n.nh_of(s), r_d.nh_of(s))
