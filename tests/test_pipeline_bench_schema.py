"""Tier-1 smoke: the checked-in BENCH_PIPELINE artifact obeys the
schema the bench emits (shared validator — bench.validate_pipeline_bench)
and holds the ISSUE-7 acceptance shape: per-phase ms summing to within
10% of the measured grid4096 full-rebuild wall time (no unattributed
gap), per-chip busy fractions recorded at 1 and 8 forced host devices,
and fleet/what-if rounds attributed over the 8-chip pool.

The validator lives in bench.py so the emitter and this gate can never
drift apart; regenerate the artifact with `python bench.py --pipeline`.
"""

import json
import pathlib

import pytest

import bench

pytestmark = [pytest.mark.multichip]

ARTIFACT = pathlib.Path(__file__).resolve().parent.parent / (
    "BENCH_PIPELINE_r01.json"
)


def test_artifact_exists_and_matches_schema():
    doc = json.loads(ARTIFACT.read_text())
    bench.validate_pipeline_bench(doc)


def test_gap_bound_is_the_acceptance_bound():
    """The headline IS the acceptance criterion: the per-phase table
    explains >= 90% of the end-to-end rebuild wall on grid4096."""
    doc = json.loads(ARTIFACT.read_text())
    assert abs(doc["value"]) <= bench.PIPELINE_GAP_BOUND_PCT
    for r in doc["detail"]["rebuild_rounds"]:
        assert abs(r["gap_pct"]) <= bench.PIPELINE_GAP_BOUND_PCT


def test_per_chip_busy_fractions_at_1_and_8_devices():
    doc = json.loads(ARTIFACT.read_text())
    rounds = {r["devices"]: r for r in doc["detail"]["rebuild_rounds"]}
    assert set(rounds) == set(bench.PIPELINE_DEVICES)
    assert list(rounds[1]["per_chip_busy"]) == ["dev0"]
    assert len(rounds[8]["per_chip_busy"]) == 8
    # an 8-way sharded rebuild must actually occupy every chip
    for row in rounds[8]["per_chip_busy"].values():
        assert row["busy_fraction"] > 0.0


def test_host_vs_device_share_recorded():
    doc = json.loads(ARTIFACT.read_text())
    for r in doc["detail"]["rebuild_rounds"]:
        assert 0.0 < r["host_share_pct"] < 100.0
        assert r["host_ms"] > 0 and r["device_ms"] > 0


def test_environment_triple_is_recorded():
    doc = json.loads(ARTIFACT.read_text())
    env = doc["detail"]["env"]
    assert env["platform"]
    assert env["jax"]
    assert env["device_count"] >= 8


def test_validator_rejects_malformed_doc():
    doc = json.loads(ARTIFACT.read_text())
    doc["detail"]["rebuild_rounds"][0]["gap_pct"] = 55.0
    with pytest.raises(AssertionError):
        bench.validate_pipeline_bench(doc)
