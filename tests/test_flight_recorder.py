"""Flight recorder (ISSUE 7 tentpole): the bounded post-mortem ring,
its three auto-dump triggers (chip quarantine via the governor hook,
watchdog crash, InvariantChecker breach), deterministic dump bytes, and
the seeded-chaos acceptance — a ``tpu_corrupt(device_index=k)`` run
auto-produces a dump holding chip k's quarantine span tree,
byte-identical across two replays of one seed."""

import asyncio
import json

import pytest

from openr_tpu.common.runtime import CounterMap, SimClock
from openr_tpu.config import ParallelConfig, ResilienceConfig
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.tracing import FlightRecorder, Tracer


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def make_recorder(clock=None, counters=None, out_dir=""):
    clock = clock or SimClock()
    counters = counters if counters is not None else CounterMap()
    tracer = Tracer("node0", clock=clock, counters=counters)
    rec = FlightRecorder(
        "node0", clock, tracer, counters,
        out_dir=out_dir,
        queue_stats_fn=lambda: {"messaging.queue.routes.depth": 2.0},
        generation_fn=lambda: [5],
    )
    return rec, tracer, counters, clock


# ---------------------------------------------------------------------------
# the ring + dump mechanics
# ---------------------------------------------------------------------------


def test_frames_record_counter_deltas_and_watermarks():
    rec, _tracer, counters, _clock = make_recorder()
    counters.bump("decision.route_build_runs", 2)
    rec.record_frame("sweep")
    counters.bump("decision.route_build_runs")
    counters.set("process.memory.rss", 9.0)  # wall-clock noise: excluded
    rec.record_frame("sweep")
    frames = list(rec._frames)
    assert frames[0]["counter_deltas"] == {"decision.route_build_runs": 2.0}
    assert frames[1]["counter_deltas"] == {"decision.route_build_runs": 1.0}
    assert frames[1]["queue_watermarks"] == {
        "messaging.queue.routes.depth": 2.0
    }


def test_dump_is_self_contained_and_written_to_disk(tmp_path):
    rec, tracer, counters, clock = make_recorder(out_dir=str(tmp_path))
    span = tracer.start_span("decision.rebuild", module="decision")
    tracer.end_span(span)
    counters.bump("decision.route_build_runs")
    payload = rec.dump("unit_test", extra={"device": 3})
    doc = json.loads(payload.decode())
    assert doc["kind"] == "openr_tpu_flight_recorder_dump"
    assert doc["reason"] == "unit_test" and doc["extra"]["device"] == 3
    names = [e["name"] for e in doc["chrome_trace"] if e.get("ph") == "X"]
    assert "decision.rebuild" in names
    assert doc["snapshot"]["counters"]["decision.route_build_runs"] == 1.0
    assert doc["frames"][-1]["label"] == "dump:unit_test"
    assert rec.last_dump == payload and rec.num_dumps == 1
    files = list(tmp_path.glob("flight_node0_*_unit_test.json"))
    assert len(files) == 1 and files[0].read_bytes() == payload
    assert rec.last_dump_doc()["reason"] == "unit_test"


def test_dump_strips_volatile_span_attrs_and_process_counters():
    rec, tracer, counters, _clock = make_recorder()
    span = tracer.start_span(
        "decision.spf_kernel", module="decision", compiled=True, device=1
    )
    tracer.end_span(span, healed=True)
    counters.set("process.cpu.pct", 55.0)
    doc = json.loads(rec.dump("x").decode())
    ev = [e for e in doc["chrome_trace"] if e.get("ph") == "X"][0]
    assert "compiled" not in ev["args"] and "healed" not in ev["args"]
    assert ev["args"]["device"] == 1  # chip attribution survives
    assert not any(
        k.startswith("process.") for k in doc["snapshot"]["counters"]
    )


def test_dump_bytes_deterministic_for_identical_state():
    def one():
        rec, tracer, counters, clock = make_recorder()
        s = tracer.start_span("resilience.probe", module="resilience",
                              device=2)
        tracer.end_span(s, passed=False)
        counters.bump("resilience.backend.chip_quarantines")
        return rec.dump("quarantine_dev2")

    assert one() == one()


def test_dump_ring_is_bounded():
    rec, _tracer, _counters, _clock = make_recorder()
    for i in range(12):
        rec.dump(f"r{i}")
    assert rec.num_dumps == 12 and len(rec.dumps) == 8  # max_dumps


def test_simultaneous_triggers_coalesce_into_one_dump():
    """ISSUE 8 satellite: two listeners firing in one Monitor sweep (a
    chip quarantine whose fallout also breaches an invariant) describe
    ONE incident window — the second trigger is coalesced, counted,
    and its reason recorded, instead of double-dumping the ring."""
    rec, tracer, counters, clock = make_recorder()
    span = tracer.start_span("resilience.shadow_check", module="resilience")
    tracer.end_span(span, passed=False)
    # same SimClock instant = same sweep: quarantine then breach
    rec.on_quarantine({"device": 3, "reason": "shadow:prefixes"})
    rec.on_invariant_breach("node0: FIB desired/programmed mismatch")
    assert rec.num_dumps == 1
    assert rec.last_reason == "quarantine_dev3"
    assert rec.num_suppressed == 1
    assert rec.suppressed_reasons == ["invariant_breach"]
    assert counters.get("trace.flight_dumps_suppressed") == 1.0
    assert rec.stats()["trace.flight_dumps_suppressed"] == 1.0
    # past the dedupe window a fresh trigger dumps again
    async def advance():
        await clock.run_for(1.0)

    run(advance())
    rec.on_watchdog_crash("Module decision fiber died")
    assert rec.num_dumps == 2 and rec.last_reason == "watchdog_crash"
    assert rec.suppressed_reasons == []


def test_explicit_dump_calls_are_never_suppressed():
    """The operator/ctrl/chaos-harness dump() path stays unconditional
    — only the automatic trigger hooks dedupe."""
    rec, _tracer, _counters, _clock = make_recorder()
    rec.dump("a")
    rec.dump("b")
    assert rec.num_dumps == 2 and rec.num_suppressed == 0


# ---------------------------------------------------------------------------
# trigger hooks
# ---------------------------------------------------------------------------


def test_governor_quarantine_hook_fires_a_chip_dump():
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.emulation.topology import build_adj_dbs, ring_edges
    from openr_tpu.types import PrefixEntry

    clock = SimClock()
    counters = CounterMap()
    tracer = Tracer("node0", clock=clock, counters=counters)
    backend = TpuBackend(
        SpfSolver("node0"),
        clock=clock,
        counters=counters,
        tracer=tracer,
        resilience=ResilienceConfig(shadow_sample_every=1, jitter_pct=0.0),
        parallel=ParallelConfig(min_shard_rows=0),
    )
    rec = FlightRecorder("node0", clock, tracer, counters)
    backend.governor.add_quarantine_listener(rec.on_quarantine)

    ls = LinkState("0")
    for db in build_adj_dbs(ring_edges(12)).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(12):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.7.{i}.0/24"))
    als = {"0": ls}
    backend.build_route_db(als, ps)
    assert rec.num_dumps == 0
    backend.inject_silent_corruption(True, device_index=3)
    backend.build_route_db(als, ps, force_full=True)
    assert backend.governor.num_chip_quarantines == 1
    assert rec.num_dumps == 1 and rec.last_reason == "quarantine_dev3"
    doc = rec.last_dump_doc()
    assert doc["extra"]["device"] == 3
    assert doc["extra"]["reason"].startswith("shadow:")
    # the quarantine span tree is inside: the failed shadow check span
    shadow = [
        e for e in doc["chrome_trace"]
        if e.get("ph") == "X" and e["name"] == "resilience.shadow_check"
    ]
    assert shadow and shadow[-1]["args"]["passed"] is False


def test_watchdog_crash_dumps_before_the_crash_sink():
    from openr_tpu.watchdog.watchdog import Watchdog

    rec, _tracer, counters, clock = make_recorder()
    order = []
    rec_dump = rec.on_watchdog_crash

    def spy_dump(reason):
        order.append("dump")
        rec_dump(reason)

    wd = Watchdog(
        "node0", clock, counters,
        fire_crash=lambda reason: order.append("crash"),
    )
    wd.add_crash_listener(spy_dump)
    wd._crash("Module decision fiber died")
    assert order == ["dump", "crash"]
    assert rec.last_reason == "watchdog_crash"
    assert rec.last_dump_doc()["extra"]["crash_reason"] == (
        "Module decision fiber died"
    )


def test_invariant_breach_dumps_every_recorded_node():
    from openr_tpu.chaos.invariants import InvariantChecker, InvariantViolation

    rec, _tracer, _counters, _clock = make_recorder()

    class Node:
        def __init__(self, recorder):
            self.flight_recorder = recorder

    class Net:
        nodes = {"node0": Node(rec), "node1": Node(None)}

        @staticmethod
        def converged_full_mesh():
            return False, "node0 missing route to node1"

    checker = InvariantChecker(Net())
    with pytest.raises(InvariantViolation, match="full-mesh"):
        checker.check_full_mesh()
    assert checker.num_breach_dumps == 1
    assert rec.last_reason == "invariant_breach"
    assert "missing route" in rec.last_dump_doc()["extra"]["violation"]


def test_breach_dump_can_be_disabled():
    from openr_tpu.chaos.invariants import InvariantChecker, InvariantViolation

    rec, _tracer, _counters, _clock = make_recorder()

    class Node:
        flight_recorder = rec

    class Net:
        nodes = {"node0": Node()}

        @staticmethod
        def converged_full_mesh():
            return False, "x"

    checker = InvariantChecker(Net(), auto_dump=False)
    with pytest.raises(InvariantViolation):
        checker.check_full_mesh()
    assert rec.num_dumps == 0


# ---------------------------------------------------------------------------
# seeded chaos acceptance: per-chip tpu_corrupt auto-dump, byte-identical
# across two replays of the same seed
# ---------------------------------------------------------------------------

VICTIM = "node4"
BAD_CHIP = 3


def _overrides(cfg):
    cfg.tpu_compute_config.min_device_prefixes = 0
    cfg.parallel_config = ParallelConfig(min_shard_rows=0)
    cfg.resilience_config = ResilienceConfig(
        shadow_sample_every=2,
        failure_threshold=2,
        probe_backoff_initial_s=0.5,
        probe_backoff_max_s=4.0,
        jitter_pct=0.1,
        seed=7,
    )


async def _corrupt_until_quarantine_dump():
    from openr_tpu.chaos import ChaosController, FaultPlan, InvariantChecker
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import grid_edges
    from openr_tpu.types import PrefixEntry

    clock = SimClock()
    net = EmulatedNetwork(
        clock, use_tpu_backend=True, config_overrides=_overrides
    )
    net.build(grid_edges(3))
    net.start()
    checker = InvariantChecker(net)
    plan = FaultPlan().tpu_corrupt(
        VICTIM, at=2.0, duration=14.0, device_index=BAD_CHIP
    )
    controller = ChaosController(net, plan, seed=7)
    await clock.run_for(18.0)
    ok, why = net.converged_full_mesh()
    assert ok, why
    victim = net.nodes[VICTIM]
    assert victim.flight_recorder is not None
    # widen the candidate table so every chip's shard holds real rows
    net.nodes["node0"].advertise_prefixes(
        [PrefixEntry(f"10.99.{i}.0/24") for i in range(9)]
    )
    await clock.run_for(3.0)
    controller.start()
    await clock.run_for(3.0)  # corruption live on chip 3
    gov = victim.decision.backend.governor
    for a, b in [("node0", "node1"), ("node1", "node2")]:
        net.fail_link(a, b)
        await clock.run_for(2.0)
        checker.sample()
        if gov.num_shadow_mismatches:
            break
    assert gov.num_chip_quarantines >= 1
    dumps = net.flight_dumps()
    payload = dumps[VICTIM]
    assert payload is not None, "quarantine did not auto-dump"
    # other nodes saw no quarantine: no dump fired there
    assert dumps["node0"] is None
    await controller.stop()
    await net.stop()
    return payload


@pytest.mark.chaos
@pytest.mark.multichip
def test_chip_quarantine_auto_dump_is_seed_deterministic():
    a = run(_corrupt_until_quarantine_dump())
    b = run(_corrupt_until_quarantine_dump())
    assert a == b, "same seed must produce byte-identical dumps"
    doc = json.loads(a.decode())
    assert doc["node"] == VICTIM
    assert doc["reason"] == f"quarantine_dev{BAD_CHIP}"
    assert doc["extra"]["device"] == BAD_CHIP
    # the quarantine span tree for chip k: the failed shadow check with
    # its decision.spf_kernel children carrying the chip's device attr
    events = [e for e in doc["chrome_trace"] if e.get("ph") == "X"]
    shadow = [e for e in events if e["name"] == "resilience.shadow_check"]
    assert shadow and shadow[-1]["args"]["passed"] is False
    tree_id = shadow[-1]["args"]["trace_id"]
    kernels = [
        e for e in events
        if e["name"] == "decision.spf_kernel"
        and e["args"].get("device") == BAD_CHIP
    ]
    assert kernels, "chip k's kernel dispatches missing from the dump"
    assert tree_id, "shadow check span lost its trace id"
    # counters in the dump agree with the quarantine the dump explains
    snap = doc["snapshot"]["counters"]
    assert snap["resilience.backend.chip_quarantines"] >= 1.0
