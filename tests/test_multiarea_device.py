"""Multi-area device-path differential tests.

Areas are a batch dim for SPF on device (Decision.cpp:762-773); selection
is global across areas; the cross-area min-metric nexthop merge
(SpfSolver.cpp:276-302) happens during host lane decode.  TpuBackend must
match ScalarBackend bit-for-bit on every multi-area config.
"""

import pytest

from openr_tpu.decision.backend import ScalarBackend, TpuBackend
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import (
    build_adj_dbs,
    fabric_edges,
    grid_edges,
    line_edges,
    random_connected_edges,
    ring_edges,
)
from openr_tpu.types import (
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixMetrics,
    RouteComputationRules,
)

KSP2 = PrefixForwardingAlgorithm.KSP2_ED_ECMP


def make_ls(edges, area, me="", **kwargs) -> LinkState:
    ls = LinkState(area, me)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def _nh_view(entry):
    return sorted(
        (
            nh.neighbor_node_name,
            nh.if_name,
            nh.metric,
            nh.area,
            None
            if nh.mpls_action is None
            else (nh.mpls_action.action, nh.mpls_action.push_labels),
        )
        for nh in entry.nexthops
    )


def _db_view(db):
    if db is None:
        return None
    return {
        p: (
            round(e.igp_cost, 1),
            e.best_area,
            e.best_prefix_entry.metrics.drain_metric,
            _nh_view(e),
        )
        for p, e in db.unicast_routes.items()
    }


def assert_match(mk_areas, ps, me, expect_device=True, **solver_kwargs):
    """mk_areas: zero-arg factory returning fresh {area: LinkState}."""
    scalar = ScalarBackend(SpfSolver(me, **solver_kwargs)).build_route_db(
        mk_areas(), ps
    )
    backend = TpuBackend(SpfSolver(me, **solver_kwargs))
    tpu = backend.build_route_db(mk_areas(), ps)
    assert _db_view(tpu) == _db_view(scalar)
    if expect_device:
        assert backend.num_scalar_builds == 0
        assert backend.num_device_builds == 1
    return backend, tpu


def two_area_factory(me="b0"):
    """Area 1: line a0-a1-b0; area 2: ring b0-b1-b2-b3; b0 borders both."""

    def mk():
        return {
            "1": make_ls(
                [("a0", "a1", 1), ("a1", "b0", 1)], "1", me=me
            ),
            "2": make_ls(ring_edges(4, prefix="b"), "2", me=me),
        }

    return mk


def test_two_areas_basic_differential():
    ps = PrefixState()
    ps.update_prefix("a0", "1", PrefixEntry("10.0.0.0/24"))
    ps.update_prefix("b2", "2", PrefixEntry("10.1.0.0/24"))
    ps.update_prefix("b1", "2", PrefixEntry("2001:db8::/64"))
    backend, tpu = assert_match(two_area_factory(), ps, me="b0")
    assert "10.0.0.0/24" in tpu.unicast_routes
    assert tpu.unicast_routes["10.0.0.0/24"].best_area == "1"


def test_cross_area_same_prefix_min_metric_merge():
    # the same prefix advertised in both areas: winner set spans areas and
    # nexthops merge at the min IGP metric (SpfSolver.cpp:276-302)
    ps = PrefixState()
    ps.update_prefix("a1", "1", PrefixEntry("10.0.0.0/24"))
    ps.update_prefix("b1", "2", PrefixEntry("10.0.0.0/24"))
    backend, tpu = assert_match(two_area_factory(), ps, me="b0")
    route = tpu.unicast_routes["10.0.0.0/24"]
    assert route.igp_cost == 1.0


def test_cross_area_equal_metric_union():
    # equal distance in both areas -> union of both areas' nexthops
    def mk():
        return {
            "1": make_ls(line_edges(2, prefix="x"), "1", me="x0"),
            "2": make_ls(line_edges(2, prefix="y"), "2", me="x0"),
        }

    # me = x0 is only in area 1; put it in area 2 too via a shared node
    def mk2():
        return {
            "1": make_ls([("me", "p", 1)], "1", me="me"),
            "2": make_ls([("me", "q", 1)], "2", me="me"),
        }

    ps = PrefixState()
    ps.update_prefix("p", "1", PrefixEntry("10.0.0.0/24"))
    ps.update_prefix("q", "2", PrefixEntry("10.0.0.0/24"))
    backend, tpu = assert_match(mk2, ps, me="me")
    route = tpu.unicast_routes["10.0.0.0/24"]
    assert {nh.area for nh in route.nexthops} == {"1", "2"}


def test_per_area_shortest_distance_algorithm():
    ps = PrefixState()
    # different distance metrics: PER_AREA keeps each area's min
    ps.update_prefix(
        "a0", "1", PrefixEntry("10.0.0.0/24", metrics=PrefixMetrics(distance=5))
    )
    ps.update_prefix(
        "a1", "1", PrefixEntry("10.0.0.0/24", metrics=PrefixMetrics(distance=3))
    )
    ps.update_prefix(
        "b2", "2", PrefixEntry("10.0.0.0/24", metrics=PrefixMetrics(distance=9))
    )
    assert_match(
        two_area_factory(),
        ps,
        me="b0",
        route_selection_algorithm=(
            RouteComputationRules.PER_AREA_SHORTEST_DISTANCE
        ),
    )


def test_me_absent_from_one_area():
    # I'm only in area 1; area 2 prefixes are unreachable for me
    def mk():
        return {
            "1": make_ls(line_edges(3), "1", me="node0"),
            "2": make_ls(ring_edges(3, prefix="z"), "2", me="node0"),
        }

    ps = PrefixState()
    ps.update_prefix("node2", "1", PrefixEntry("10.0.0.0/24"))
    ps.update_prefix("z1", "2", PrefixEntry("10.1.0.0/24"))
    backend, tpu = assert_match(mk, ps, me="node0")
    assert "10.0.0.0/24" in tpu.unicast_routes
    assert "10.1.0.0/24" not in tpu.unicast_routes


def test_self_advertisement_in_isolated_area_suppresses_route():
    """I advertise the prefix in an area where I have no adjacencies: the
    self-advertisement still wins selection (metric 0 to myself) and
    suppresses programming — scalar get_spf_result semantics preserved by
    interning me into every area's symbol table."""

    def mk():
        return {
            "1": make_ls(line_edges(3), "1", me="node0"),
            # area 2 graph doesn't contain node0 at all
            "2": make_ls([("w0", "w1", 1)], "2", me="node0"),
        }

    ps = PrefixState()
    ps.update_prefix("node2", "1", PrefixEntry("10.0.0.0/24"))
    ps.update_prefix("node0", "2", PrefixEntry("10.0.0.0/24"))  # self, area 2
    backend, tpu = assert_match(mk, ps, me="node0")
    assert "10.0.0.0/24" not in tpu.unicast_routes


def test_multiarea_with_drains():
    def mk():
        return {
            "1": make_ls(
                grid_edges(3), "1", me="node0", overloaded=["node4"]
            ),
            "2": make_ls(
                ring_edges(4, prefix="b"),
                "2",
                me="node0",
                soft_drained={"b2": 50},
            ),
        }

    # node0 must exist in area 2's graph for multi-area to be interesting
    def mk2():
        areas = mk()
        ls2 = LinkState("2", "node0")
        for db in build_adj_dbs(
            ring_edges(4, prefix="b") + [("b0", "node0", 1)],
            area="2",
            soft_drained={"b2": 50},
        ).values():
            ls2.update_adjacency_database(db)
        areas["2"] = ls2
        return areas

    ps = PrefixState()
    ps.update_prefix("node4", "1", PrefixEntry("10.0.0.0/24"))  # hard-drained
    ps.update_prefix("b2", "2", PrefixEntry("10.0.0.0/24"))  # soft-drained
    ps.update_prefix("node8", "1", PrefixEntry("10.1.0.0/24"))
    ps.update_prefix("b1", "2", PrefixEntry("10.1.0.0/24"))
    assert_match(mk2, ps, me="node0")


def test_multiarea_ksp2():
    def mk():
        return {
            "1": make_ls(
                fabric_edges(num_pods=2, rsws_per_pod=2, fsws_per_pod=2),
                "1",
                me="rsw0_0",
            ),
            "2": make_ls(
                grid_edges(3, prefix="g") + [("g0", "rsw0_0", 1)],
                "2",
                me="rsw0_0",
            ),
        }

    ps = PrefixState()
    ps.update_prefix(
        "rsw1_1", "1", PrefixEntry("10.0.0.0/24", forwarding_algorithm=KSP2)
    )
    ps.update_prefix(
        "g8", "2", PrefixEntry("10.0.0.0/24", forwarding_algorithm=KSP2)
    )
    ps.update_prefix(
        "g4", "2", PrefixEntry("10.1.0.0/24", forwarding_algorithm=KSP2)
    )
    backend, tpu = assert_match(mk, ps, me="rsw0_0")
    assert backend.num_scalar_builds == 0


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_multiarea_random_topologies(seed):
    def mk():
        e1 = random_connected_edges(12, 8, seed=seed, prefix="a")
        e2 = random_connected_edges(10, 6, seed=seed + 100, prefix="c")
        # splice me into both areas
        e1.append(("a0", "me", 1))
        e2.append(("c0", "me", 2))
        return {
            "1": make_ls(e1, "1", me="me"),
            "2": make_ls(e2, "2", me="me"),
        }

    ps = PrefixState()
    ps.update_prefix("a5", "1", PrefixEntry("10.0.0.0/24"))
    ps.update_prefix("c5", "2", PrefixEntry("10.0.0.0/24"))
    ps.update_prefix("a7", "1", PrefixEntry("10.1.0.0/24"))
    ps.update_prefix(
        "c3", "2", PrefixEntry("10.2.0.0/24", min_nexthop=1)
    )
    ps.update_prefix(
        "a3",
        "1",
        PrefixEntry("10.3.0.0/24", metrics=PrefixMetrics(path_preference=900)),
    )
    ps.update_prefix(
        "c7",
        "2",
        PrefixEntry("10.3.0.0/24", metrics=PrefixMetrics(path_preference=800)),
    )
    assert_match(mk, ps, me="me")


def test_border_node_does_not_drag_in_unadvertised_area():
    """A winner node whose NAME resolves in a second area's graph must not
    pull that area into the nexthop merge: the scalar chain only iterates
    areas_with_best (areas containing a winner ADVERTISEMENT,
    SpfSolver.cpp:276-283).  Regression test for the device kernel's
    area_has_winner mask."""

    def mk():
        # border node X is in both graphs; in area 1 it's far (5), in
        # area 2 it's adjacent (1).  X advertises ONLY in area 1.
        e1 = [("me", "a1", 1), ("a1", "a2", 1), ("a2", "a3", 1),
              ("a3", "a4", 1), ("a4", "X", 1)]
        e2 = [("me", "X", 1), ("X", "z1", 1)]
        return {
            "1": make_ls(e1, "1", me="me"),
            "2": make_ls(e2, "2", me="me"),
        }

    ps = PrefixState()
    ps.update_prefix("X", "1", PrefixEntry("10.0.0.0/24"))
    backend, tpu = assert_match(mk, ps, me="me")
    route = tpu.unicast_routes["10.0.0.0/24"]
    # must route the long way through area 1, not shortcut via area 2
    assert route.igp_cost == 5.0
    assert {nh.area for nh in route.nexthops} == {"1"}


def test_three_areas():
    def mk():
        return {
            "1": make_ls([("me", "a1", 1), ("a1", "a2", 1)], "1", me="me"),
            "2": make_ls([("me", "b1", 2), ("b1", "b2", 1)], "2", me="me"),
            "3": make_ls([("me", "c1", 3)], "3", me="me"),
        }

    ps = PrefixState()
    for n, a in (("a2", "1"), ("b2", "2"), ("c1", "3")):
        ps.update_prefix(n, a, PrefixEntry("10.0.0.0/24"))
        ps.update_prefix(n, a, PrefixEntry(f"10.{a}.0.0/24"))
    backend, tpu = assert_match(mk, ps, me="me")
    # anycast winner: igp 2 via area 1 (a2) beats area 2 (3) and area 3 (3)
    assert tpu.unicast_routes["10.0.0.0/24"].igp_cost == 2.0
