"""Tier-1 smoke: the checked-in BENCH_HEALTH artifact obeys the schema
the bench emits (shared validator — bench.validate_health_bench) and
holds the ISSUE-8 acceptance shape: aggregator sweep overhead on the
serving p50 bounded <= 2%, and the fault-injection -> alert
detection-latency distribution recorded per fault family over the
seeded 9-node sweep (every injection detected, replay deterministic).

The validator lives in bench.py so the emitter and this gate can never
drift apart; regenerate the artifact with `python bench.py --health`.
"""

import json
import pathlib

import pytest

import bench

pytestmark = [pytest.mark.health]

ARTIFACT = pathlib.Path(__file__).resolve().parent.parent / (
    "BENCH_HEALTH_r01.json"
)


def test_artifact_exists_and_matches_schema():
    doc = json.loads(ARTIFACT.read_text())
    bench.validate_health_bench(doc)


def test_overhead_bound_is_the_acceptance_bound():
    doc = json.loads(ARTIFACT.read_text())
    assert doc["value"] <= bench.HEALTH_OVERHEAD_BOUND_PCT


def test_detection_covers_every_fault_family():
    doc = json.loads(ARTIFACT.read_text())
    det = doc["detail"]["detection"]
    assert set(det) == set(bench.HEALTH_FAULT_FAMILIES)
    # each family detected on every seed, with its registered alert
    from openr_tpu.health.alerts import ALERTS

    for family, row in det.items():
        assert row["detected"] == row["samples"]
        assert row["alert"] in ALERTS
        assert row["p50_ms"] >= 0.0


def test_replay_determinism_recorded():
    doc = json.loads(ARTIFACT.read_text())
    assert doc["detail"]["deterministic_replay"] is True


def test_environment_triple_is_recorded():
    doc = json.loads(ARTIFACT.read_text())
    env = doc["detail"]["env"]
    assert env["platform"] and env["jax"]
    assert env["device_count"] >= 1


def test_validator_rejects_malformed_doc():
    doc = json.loads(ARTIFACT.read_text())
    doc["value"] = 55.0
    with pytest.raises(AssertionError):
        bench.validate_health_bench(doc)
    doc = json.loads(ARTIFACT.read_text())
    del doc["detail"]["detection"]["partition"]
    with pytest.raises(AssertionError):
        bench.validate_health_bench(doc)
