"""Test harness: force an 8-device virtual CPU mesh before JAX import so
multi-chip sharding paths are exercised without TPU hardware."""

import os

# Force CPU regardless of harness-provided platform (a real-TPU session may
# preset JAX_PLATFORMS or register a TPU plugin that overrides it via
# jax.config): tests exercise the 8-device sharded code paths on a virtual
# host mesh.  Set OPENR_TPU_TEST_PLATFORM to override.
_platform = os.environ.get("OPENR_TPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

if _platform == "cpu":
    import jax

    # a site hook may have force-selected an accelerator platform already
    jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test, excluded from tier-1 (-m 'not slow')"
    )
    config.addinivalue_line(
        "markers", "chaos: fault-injection test (openr_tpu.chaos)"
    )
    config.addinivalue_line(
        "markers", "serving: query-serving-plane test (openr_tpu.serving)"
    )
    config.addinivalue_line(
        "markers",
        "multichip: multi-device pool/mesh test (openr_tpu.parallel)",
    )
    config.addinivalue_line(
        "markers",
        "health: fleet-health-plane test (openr_tpu.health)",
    )
    config.addinivalue_line(
        "markers",
        "streaming: watch-plane test (openr_tpu.serving.streaming)",
    )
    config.addinivalue_line(
        "markers",
        "sweep: capacity-planning sweep test (openr_tpu.sweep)",
    )
    config.addinivalue_line(
        "markers",
        "protection: fast-reroute protection-tier test "
        "(openr_tpu.protection)",
    )
    config.addinivalue_line(
        "markers",
        "fleet: fleet-compute-fabric test (openr_tpu.fleet)",
    )


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """One skip-reason summary line per run: silent version-gated skips
    (e.g. the 7 ``jax.shard_map`` tests) used to vanish into the bare
    skip count — this line makes a jax upgrade that un-skips them (or a
    regression that skips more) visible in CI logs."""
    skipped = terminalreporter.stats.get("skipped", [])
    if not skipped:
        return
    reasons = {}
    for rep in skipped:
        reason = rep.longrepr[2] if isinstance(rep.longrepr, tuple) else str(
            rep.longrepr
        )
        reason = reason.removeprefix("Skipped: ")
        reasons[reason] = reasons.get(reason, 0) + 1
    summary = "; ".join(
        f"{n}x {reason!r}"
        for reason, n in sorted(reasons.items(), key=lambda kv: (-kv[1], kv[0]))
    )
    terminalreporter.write_line(f"skip reasons: {summary}")


@pytest.fixture
def sim_loop():
    """Fresh event loop + SimClock per test."""
    from openr_tpu.common.runtime import SimClock

    loop = asyncio.new_event_loop()
    clock = SimClock()
    try:
        yield loop, clock
    finally:
        loop.close()
