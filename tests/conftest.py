"""Test harness: force an 8-device virtual CPU mesh before JAX import so
multi-chip sharding paths are exercised without TPU hardware."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import asyncio  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def sim_loop():
    """Fresh event loop + SimClock per test."""
    from openr_tpu.common.runtime import SimClock

    loop = asyncio.new_event_loop()
    clock = SimClock()
    try:
        yield loop, clock
    finally:
        loop.close()
