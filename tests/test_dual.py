"""DUAL flood-optimization tests — SPT formation, failure reconvergence,
multi-root arbitration (the reference's kvstore/tests/DualTest.cpp
scenarios), plus KvStore integration showing reduced flood fan-out."""

import asyncio
from collections import deque

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import KvStoreConfig
from openr_tpu.kvstore.dual import (
    INF,
    DualEvent,
    DualMessages,
    DualNode,
    DualState,
    DualStateMachine,
)

from test_kvstore import Net, mkval, run


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------


def test_state_machine_passive_transitions():
    sm = DualStateMachine()
    # FC held: stay passive no matter the event
    sm.process_event(DualEvent.OTHERS, fc=True)
    assert sm.state == DualState.PASSIVE
    # FC broken by a local event -> ACTIVE1
    sm.process_event(DualEvent.INCREASE_D, fc=False)
    assert sm.state == DualState.ACTIVE1
    # distance increased while active local-origin -> ACTIVE0
    sm.process_event(DualEvent.INCREASE_D)
    assert sm.state == DualState.ACTIVE0
    # last reply w/o FC -> ACTIVE2; then last reply w/ FC -> PASSIVE
    sm.process_event(DualEvent.LAST_REPLY, fc=False)
    assert sm.state == DualState.ACTIVE2
    sm.process_event(DualEvent.LAST_REPLY, fc=True)
    assert sm.state == DualState.PASSIVE
    # FC broken by successor's query -> ACTIVE3
    sm.process_event(DualEvent.QUERY_FROM_SUCCESSOR, fc=False)
    assert sm.state == DualState.ACTIVE3
    sm.process_event(DualEvent.INCREASE_D)
    assert sm.state == DualState.ACTIVE2


# ---------------------------------------------------------------------------
# pure-library harness: synchronous message router
# ---------------------------------------------------------------------------


class Fabric:
    """N DualNodes exchanging messages through a FIFO pump (stands in for
    the wire; delivery order is deterministic)."""

    def __init__(self, names, roots=()):
        self.pending = deque()  # (dst, DualMessages)
        self.nodes = {n: _FabricNode(self, n, n in roots) for n in names}
        self.links = set()

    def link_up(self, a, b, cost=1):
        self.links.add(frozenset((a, b)))
        self.nodes[a].peer_up(b, cost)
        self.nodes[b].peer_up(a, cost)
        self.pump()

    def link_down(self, a, b):
        self.links.discard(frozenset((a, b)))
        self.nodes[a].peer_down(b)
        self.nodes[b].peer_down(a)
        self.pump()

    def pump(self, limit=100_000):
        n = 0
        while self.pending:
            dst, msgs = self.pending.popleft()
            # drop traffic on dead links (message crossed a link-down)
            if frozenset((dst, msgs.src_id)) not in self.links:
                continue
            self.nodes[dst].process_dual_messages(msgs)
            n += 1
            assert n < limit, "dual message storm"
        return n

    def assert_spt(self, root):
        """Every node's parent chain must reach `root` loop-free with
        hop-count distance, and parent/child sets must agree."""
        for name, node in self.nodes.items():
            info = node.duals[root].info
            assert info.sm.state == DualState.PASSIVE, (name, str(info))
            seen = [name]
            cur = name
            while cur != root:
                nh = self.nodes[cur].duals[root].info.nexthop
                assert nh is not None and nh not in seen, f"loop at {seen}"
                # parent must list cur as its child
                assert cur in self.nodes[nh].duals[root].children(), (
                    f"{nh} missing child {cur}"
                )
                seen.append(nh)
                cur = nh
            assert info.distance == len(seen) - 1 or name == root


class _FabricNode(DualNode):
    def __init__(self, fabric, name, is_root):
        self.fabric = fabric
        super().__init__(name, is_root=is_root)

    def send_dual_messages(self, neighbor, msgs):
        self.fabric.pending.append((neighbor, msgs))
        return True

    def process_nexthop_change(self, root_id, old_nh, new_nh):
        # mirror KvStore's flood-topo-set: maintain child sets on parents
        if old_nh is not None and old_nh != self.node_id:
            self.fabric.nodes[old_nh].duals[root_id].remove_child(self.node_id)
        if new_nh is not None and new_nh != self.node_id:
            self.fabric.nodes[new_nh].duals[root_id].add_child(self.node_id)


def test_line_topology_forms_spt():
    f = Fabric(["a", "b", "c"], roots=["a"])
    f.link_up("a", "b")
    f.link_up("b", "c")
    f.assert_spt("a")
    assert f.nodes["b"].duals["a"].info.nexthop == "a"
    assert f.nodes["c"].duals["a"].info.nexthop == "b"
    assert f.nodes["c"].duals["a"].info.distance == 2
    # flooding neighbor sets = tree edges
    assert f.nodes["a"].get_spt_peers("a") == {"b"}
    assert f.nodes["b"].get_spt_peers("a") == {"a", "c"}
    assert f.nodes["c"].get_spt_peers("a") == {"b"}


def test_ring_reconverges_after_link_failure():
    f = Fabric(["r", "x", "y", "z"], roots=["r"])
    f.link_up("r", "x")
    f.link_up("x", "y")
    f.link_up("y", "z")
    f.link_up("z", "r")
    f.assert_spt("r")
    # cut the link carrying x (or z); tree must reform the other way round
    assert f.nodes["x"].duals["r"].info.nexthop == "r"
    f.link_down("r", "x")
    f.assert_spt("r")
    assert f.nodes["x"].duals["r"].info.nexthop == "y"
    assert f.nodes["x"].duals["r"].info.distance == 3


def test_grid_converges_and_survives_node_isolation():
    # 3x3 grid, root at a corner
    names = [f"n{i}{j}" for i in range(3) for j in range(3)]
    f = Fabric(names, roots=["n00"])
    for i in range(3):
        for j in range(3):
            if i + 1 < 3:
                f.link_up(f"n{i}{j}", f"n{i + 1}{j}")
            if j + 1 < 3:
                f.link_up(f"n{i}{j}", f"n{i}{j + 1}")
    f.assert_spt("n00")
    assert f.nodes["n22"].duals["n00"].info.distance == 4
    # isolate the center node; everyone else must still have a route
    for nbr in ("n01", "n10", "n12", "n21"):
        f.link_down("n11", nbr)
    for name, node in f.nodes.items():
        if name in ("n11",):
            assert not node.duals["n00"].has_valid_route()
        else:
            assert node.duals["n00"].has_valid_route(), name


def test_multi_root_arbitration_and_failover():
    # two roots: smallest id (r1) wins; when r1 dies, r2's tree takes over
    f = Fabric(["r1", "r2", "m"], roots=["r1", "r2"])
    f.link_up("r1", "m")
    f.link_up("m", "r2")
    assert f.nodes["m"].get_spt_root_id() == "r1"
    # r2 is an ordinary node in r1's tree, hanging off m
    assert f.nodes["m"].get_spt_peers("r1") == {"r1", "r2"}
    f.link_down("r1", "m")
    assert f.nodes["m"].get_spt_root_id() == "r2"
    assert f.nodes["m"].get_spt_peers("r2") == {"r2"}


def test_distance_infinity_when_root_unreachable():
    f = Fabric(["r", "a"], roots=["r"])
    f.link_up("r", "a")
    assert f.nodes["a"].duals["r"].info.distance == 1
    f.link_down("r", "a")
    assert f.nodes["a"].duals["r"].info.distance == INF
    assert not f.nodes["a"].duals["r"].has_valid_route()


# ---------------------------------------------------------------------------
# KvStore integration
# ---------------------------------------------------------------------------


def _dual_cfg(root=False):
    return KvStoreConfig(enable_flood_optimization=True, is_flood_root=root)


def test_kvstore_flood_topology_reduces_fanout():
    async def main():
        clock = SimClock()
        names = ["a", "b", "c", "d"]
        cfg = {n: _dual_cfg(root=(n == "a")) for n in names}
        net = Net(names, clock, config=cfg)
        # full mesh: 6 physical links, SPT will use 3
        for i, x in enumerate(names):
            for y in names[i + 1 :]:
                net.peer(x, y)
        await clock.run_for(15.0)
        topo = net.stores["b"].get_flood_topo("0")
        assert topo["a"]["is_chosen"]
        assert topo["a"]["nexthop"] == "a"
        # all non-root nodes hang directly off the root in a full mesh
        root_topo = net.stores["a"].get_flood_topo("0")
        assert set(root_topo["a"]["children"]) == {"b", "c", "d"}
        calls_before = net.transport.num_calls
        net.stores["a"].set_key_vals("0", {"k": mkval(1, "a", b"v")})
        await clock.run_for(5.0)
        for n in names:
            assert net.stores[n].dump_all("0")["k"].value == b"v", n
        spt_calls = net.transport.num_calls - calls_before
        # root floods to its 3 children only: no b<->c<->d cross-traffic
        assert spt_calls <= 4, spt_calls
        await net.stop()

    run(main())


def test_kvstore_flood_falls_back_without_spt():
    async def main():
        clock = SimClock()
        # flood optimization on but NO root configured anywhere: stores
        # must fall back to flooding every peer
        names = ["a", "b", "c"]
        cfg = {n: _dual_cfg(root=False) for n in names}
        net = Net(names, clock, config=cfg)
        net.peer("a", "b")
        net.peer("b", "c")
        await clock.run_for(10.0)
        net.stores["a"].set_key_vals("0", {"k": mkval(1, "a", b"v")})
        await clock.run_for(5.0)
        assert net.stores["c"].dump_all("0")["k"].value == b"v"
        await net.stop()

    run(main())


def test_kvstore_mixed_capability_network_not_partitioned():
    async def main():
        clock = SimClock()
        # a (root, dual) - b (dual) - c (NO flood optimization):
        # after the a-b SPT converges, b must STILL full-flood to c
        names = ["a", "b", "c"]
        cfg = {
            "a": _dual_cfg(root=True),
            "b": _dual_cfg(),
            "c": KvStoreConfig(),  # legacy peer
        }
        net = Net(names, clock, config=cfg)
        net.peer("a", "b")
        net.peer("b", "c")
        await clock.run_for(15.0)
        topo = net.stores["b"].get_flood_topo("0")
        assert topo["a"]["is_chosen"]  # SPT converged between a and b
        net.stores["a"].set_key_vals("0", {"k": mkval(1, "a", b"v")})
        await clock.run_for(5.0)
        assert net.stores["c"].dump_all("0").get("k") is not None
        assert net.stores["c"].dump_all("0")["k"].value == b"v"
        # and the reverse direction: c's update reaches a through b
        net.stores["c"].set_key_vals("0", {"k2": mkval(1, "c", b"w")})
        await clock.run_for(5.0)
        assert net.stores["a"].dump_all("0")["k2"].value == b"w"
        await net.stop()

    run(main())


def test_kvstore_spt_survives_peer_loss():
    async def main():
        clock = SimClock()
        names = ["a", "b", "c"]
        cfg = {n: _dual_cfg(root=(n == "a")) for n in names}
        net = Net(names, clock, config=cfg)
        # triangle: a-b, b-c, c-a
        net.peer("a", "b")
        net.peer("b", "c")
        net.peer("c", "a")
        await clock.run_for(15.0)
        # b's parent is a (direct link); kill the a<->b peering
        from openr_tpu.types import PeerEvent

        net.transport.fail("a", "b")
        net.transport.fail("b", "a")
        net.peer_qs["a"].push(PeerEvent(area="0", peers_to_del=["b"]))
        net.peer_qs["b"].push(PeerEvent(area="0", peers_to_del=["a"]))
        await clock.run_for(10.0)
        topo = net.stores["b"].get_flood_topo("0")
        assert topo["a"]["nexthop"] == "c"  # rerouted through c
        net.stores["a"].set_key_vals("0", {"k2": mkval(1, "a", b"w")})
        await clock.run_for(5.0)
        assert net.stores["b"].dump_all("0")["k2"].value == b"w"
        await net.stop()

    run(main())
