"""Pipelined host/device rebuild (ISSUE 11 tentpole): dense in-edge
SPF kernel bit-parity, the streamed double-buffered shard dispatcher
(out-of-order completion reassembly, mid-stream chip quarantine
re-pack, in-flight slot ledger, honest per-chip busy accounting), and
the on-device delta-extraction path (full-build delta decode vs the
host full decode it replaces, fleet generation delta)."""

import numpy as np
import pytest

from openr_tpu.common.runtime import CounterMap, WallClock
from openr_tpu.config import ParallelConfig, ResilienceConfig
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, grid_edges, ring_edges
from openr_tpu.tracing import pipeline
from openr_tpu.types import PrefixEntry

pytestmark = pytest.mark.multichip


def make_world(side=8, area="0"):
    adj = build_adj_dbs(grid_edges(side), area=area)
    ls = LinkState(area)
    for db in adj.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(side * side):
        ps.update_prefix(
            f"node{i}", area, PrefixEntry(f"10.{(i >> 8) & 255}.{i & 255}.0/24")
        )
    return adj, {area: ls}, ps


def make_backend(ndev=8, resilience_enabled=False, **kw):
    from openr_tpu.decision.backend import TpuBackend

    return TpuBackend(
        SpfSolver("node0"),
        min_device_prefixes=0,
        clock=WallClock(),
        counters=CounterMap(),
        resilience=ResilienceConfig(enabled=resilience_enabled),
        parallel=ParallelConfig(max_devices=ndev, min_shard_rows=0),
        **kw,
    )


def assert_db_equal(a, b):
    assert a.unicast_routes.keys() == b.unicast_routes.keys()
    for p, e in b.unicast_routes.items():
        d = a.unicast_routes[p]
        assert d.nexthops == e.nexthops, p
        assert d.igp_cost == e.igp_cost, p


# ---------------------------------------------------------------------------
# dense in-edge kernels: bit-parity with the segment-reduction twins
# ---------------------------------------------------------------------------


def _table_pair(enc):
    import jax.numpy as jnp

    from openr_tpu.decision.backend import DEGREE_BUCKETS
    from openr_tpu.ops.csr import bucket_for
    from openr_tpu.ops.route_select import (
        multi_area_spf_tables,
        multi_area_spf_tables_dense,
    )

    D = bucket_for(max(enc.max_out_degree(), 1), DEGREE_BUCKETS)
    seg = multi_area_spf_tables(
        jnp.asarray(enc.src),
        jnp.asarray(enc.dst),
        jnp.asarray(enc.w),
        jnp.asarray(enc.edge_ok),
        jnp.asarray(enc.overloaded),
        jnp.asarray(enc.roots),
        max_degree=D,
    )
    dense = multi_area_spf_tables_dense(
        jnp.asarray(enc.in_src),
        jnp.asarray(enc.in_w),
        jnp.asarray(enc.in_ok),
        jnp.asarray(enc.in_rank),
        jnp.asarray(enc.in_has),
        jnp.asarray(enc.overloaded),
        jnp.asarray(enc.roots),
        max_degree=D,
    )
    return seg, dense


def test_dense_spf_bit_parity_multiarea_with_drains():
    """The dense gather kernels reach the segment kernels' fixed points
    BIT-IDENTICALLY (incl. the int8-min fill on absent-dst lane rows),
    across a multi-area LSDB with asymmetric metrics, a hard-drained
    node and a soft-drained node."""
    from openr_tpu.ops.csr import encode_multi_area

    rng = np.random.default_rng(7)
    adjA = build_adj_dbs(ring_edges(12), area="A")
    lsA = LinkState("A")
    for db in adjA.values():
        for a in db.adjacencies:
            a.metric = int(rng.integers(1, 9))
        lsA.update_adjacency_database(db)
    lsA._update_node_overloaded("node3", True)
    lsA._node_metric_increments["node7"] = 50
    adjB = build_adj_dbs(grid_edges(5), area="B")
    lsB = LinkState("B")
    for db in adjB.values():
        lsB.update_adjacency_database(db)
    als = {"A": lsA, "B": lsB}
    enc = encode_multi_area(als, "node2")
    assert enc.has_dense
    (d1, n1), (d2, n2) = _table_pair(enc)
    assert np.array_equal(np.asarray(d1), np.asarray(d2))
    assert np.array_equal(np.asarray(n1), np.asarray(n2))


def test_dense_parity_survives_encode_patch():
    """The O(links) patch path refreshes the dense weight/validity
    planes through the shared slot layout; parity holds after a metric
    perturbation AND the layout arrays stay identity-shared."""
    from openr_tpu.ops.csr import encode_multi_area, patch_encoded_multi_area

    adj, als, _ps = make_world(6)
    enc = encode_multi_area(als, "node0")
    flip = adj["node8"]
    for a in flip.adjacencies:
        a.metric = 4
    als["0"].update_adjacency_database(flip)
    patched = patch_encoded_multi_area(enc, als, "node0")
    assert patched is not None and patched.has_dense
    assert patched.in_src is enc.in_src
    assert patched.in_rank is enc.in_rank
    assert patched.in_has is enc.in_has
    (d1, n1), (d2, n2) = _table_pair(patched)
    assert np.array_equal(np.asarray(d1), np.asarray(d2))
    assert np.array_equal(np.asarray(n1), np.asarray(n2))


def test_in_edge_matrix_layout_and_ranks():
    """Slot/rank construction against the segment kernels' reference
    semantics: rank == index among same-src edges in edge order, every
    real edge (down links included) owns exactly one slot, pads carry
    in_ok=False."""
    from openr_tpu.ops.csr import build_in_edge_matrix

    # hand-built dst-sorted edge list with a down link and a parallel
    # pair; V=4 padded to 6, E padded to 12
    src = np.array([1, 2, 0, 0, 3, 0, 1, 5, 5, 5, 5, 5], np.int32)
    dst = np.array([0, 0, 1, 1, 1, 2, 3, 5, 5, 5, 5, 5], np.int32)
    w = np.array([1, 2, 1, 3, 9, 4, 2, 0, 0, 0, 0, 0], np.float32)
    ok = np.array(
        [1, 1, 1, 1, 0, 1, 1, 0, 0, 0, 0, 0], bool
    )  # edge 4 is a down link
    link_index = np.array(
        [0, 1, 0, 2, 3, 4, 5, -1, -1, -1, -1, -1], np.int32
    )
    out = build_in_edge_matrix(src, dst, w, ok, link_index, 6)
    assert out is not None
    in_src, in_w, in_ok, in_rank, in_edge_pos, in_has = out
    # node1 has three in-slots (two parallel from node0, one down from 3)
    assert sorted(in_src[1][in_w[1] < np.inf].tolist()) == [0, 0, 3]
    assert sorted(in_src[1][in_ok[1]].tolist()) == [0, 0]
    assert (in_ok[1].sum()) == 2  # the down link's slot is not ok
    # ranks: edges 2,3 are node0's out-edges in order -> ranks 0,1;
    # edge 5 is node0's third out-edge -> rank 2
    flat = in_edge_pos
    assert in_rank.flat[flat[2]] == 0
    assert in_rank.flat[flat[3]] == 1
    assert in_rank.flat[flat[5]] == 2
    # every real edge owns a distinct slot; pads own none
    real = flat[link_index >= 0]
    assert len(set(real.tolist())) == 7 and (flat[link_index < 0] == -1).all()
    # in_has covers every dst present in the padded list (pads point at 5)
    assert in_has[[0, 1, 2, 3, 5]].all() and not in_has[4]


def test_dense_declines_past_in_degree_bucket_and_backend_falls_back():
    """A hub with more in-edges than the largest IN_DEGREE_BUCKET
    declines the dense layout; the backend transparently solves via the
    segment kernels and still matches the scalar oracle."""
    from openr_tpu.ops.csr import IN_DEGREE_BUCKETS, encode_multi_area

    n_leaves = IN_DEGREE_BUCKETS[-1] + 1
    edges = [("hub", f"leaf{i}", 1) for i in range(n_leaves)]
    adj = build_adj_dbs(edges)
    ls = LinkState("0")
    for db in adj.values():
        ls.update_adjacency_database(db)
    als = {"0": ls}
    enc = encode_multi_area(als, "hub")
    assert not enc.has_dense
    ps = PrefixState()
    for i in range(0, 64):
        ps.update_prefix(f"leaf{i}", "0", PrefixEntry(f"10.3.{i}.0/24"))
    from openr_tpu.decision.backend import TpuBackend

    backend = TpuBackend(
        SpfSolver("hub"),
        min_device_prefixes=0,
        resilience=ResilienceConfig(enabled=False),
        parallel=ParallelConfig(max_devices=1),
    )
    db = backend.build_route_db(als, ps, force_full=True)
    sc = SpfSolver("hub").build_route_db(als, ps)
    assert_db_equal(db, sc)


# ---------------------------------------------------------------------------
# the streamed dispatcher
# ---------------------------------------------------------------------------


def test_streamed_full_build_matches_oracle_and_records_stream_phases():
    _adj, als, ps = make_world()
    for ndev in (1, 8):
        b = make_backend(ndev)
        db = b.build_route_db(als, ps, force_full=True)
        assert_db_equal(db, SpfSolver("node0").build_route_db(als, ps))
        assert b.num_stream_builds == 1
        h = b.probe.counters.histogram(
            pipeline.hist_key(pipeline.STREAM_DRAIN)
        )
        assert h is not None and h.count == (1 if ndev == 1 else 8)
        # the in-flight ledger closed the loop: nothing left in flight,
        # and the high watermark proves dispatches actually overlapped
        assert all(n == 0 for n in b.pool.num_inflight)
        assert max(b.pool.max_inflight) >= 1


def test_out_of_order_completion_reassembles_row_order():
    """Shard reassembly must be row-order-correct when chips finish in
    ARBITRARY order: force last-in-first-out and seeded-random drain
    orders through the completion-pick seam and demand bit-parity with
    the scalar oracle either way."""
    _adj, als, ps = make_world()
    oracle = SpfSolver("node0").build_route_db(als, ps)
    rng = np.random.default_rng(11)
    for pick in (
        lambda pending: len(pending) - 1,  # strict LIFO
        lambda pending: int(rng.integers(len(pending))),  # arbitrary
    ):
        b = make_backend(8)
        b._stream_pick = pick
        db = b.build_route_db(als, ps, force_full=True)
        assert_db_equal(db, oracle)
        assert len({d for d, _lo, _hi in b._attr_plan}) > 1


def test_mid_stream_chip_failure_repacks_onto_survivors():
    """A shard failing at drain time quarantines ITS chip, re-packs
    exactly its row range onto the lead survivor and resumes — no rows
    dropped, none duplicated, and the next build's plan excludes the
    quarantined chip."""
    _adj, als, ps = make_world()
    b = make_backend(8, resilience_enabled=True)
    fired = []

    def fault(dev_index):
        if dev_index == 3 and not fired:
            fired.append(dev_index)
            raise RuntimeError("injected mid-stream chip failure")

    b._stream_fault = fault
    db = b.build_route_db(als, ps, force_full=True)
    assert fired == [3]
    assert b.num_stream_repacks == 1
    assert_db_equal(db, SpfSolver("node0").build_route_db(als, ps))
    assert not b.pool.is_healthy(3)
    # re-packed build is unattributable by design (rows moved off plan)
    assert b._attr_table is None
    b._stream_fault = None
    db2 = b.build_route_db(als, ps, force_full=True)
    assert_db_equal(db2, SpfSolver("node0").build_route_db(als, ps))
    assert 3 not in {d for d, _lo, _hi in (b._attr_plan or ())}


def test_mid_stream_failure_without_governor_falls_back_scalar():
    """Legacy resilience-disabled semantics preserved: a drain failure
    with no governor propagates and... the build still answers (scalar
    fallback), it just cannot re-pack."""
    _adj, als, ps = make_world()
    b = make_backend(8, resilience_enabled=False)

    def fault(dev_index):
        if dev_index == 2:
            raise RuntimeError("boom")

    b._stream_fault = fault
    with pytest.raises(RuntimeError):
        b.build_route_db(als, ps, force_full=True)


def test_stream_busy_accounting_charges_completing_chip_only():
    """The honest-utilization satellite: per-chip busy time under the
    streamed dispatcher sums to (at most) the attributed device-side
    phase time — the old barrier charged the whole device_get window to
    EVERY in-flight chip, overcounting by up to the chip count."""
    _adj, als, ps = make_world()
    b = make_backend(8)
    b.build_route_db(als, ps, force_full=True)
    counters = b.probe.counters
    attributed = 0.0
    for phase in pipeline.PHASES:
        h = counters.histogram(pipeline.hist_key(phase))
        if h is not None:
            attributed += h.total
    busy = sum(b.probe.busy_snapshot().values())
    assert busy <= attributed * 1.05 + 1e-6


# ---------------------------------------------------------------------------
# on-device delta extraction (cold/full builds)
# ---------------------------------------------------------------------------


def test_full_build_delta_decode_bit_parity_and_object_identity():
    """The cold-path generation delta: consecutive force_full builds
    with exact (empty) churn patch through unchanged rows
    object-identically, fetch only changed rows, report
    take_last_changed_prefixes, and stay bit-parity with both the host
    full decode they replace and the scalar oracle."""
    adj, als, ps = make_world()
    for ndev in (1, 8):
        b = make_backend(ndev)
        db0 = b.build_route_db(als, ps, changed_prefixes=set(), force_full=True)
        assert b.take_last_changed_prefixes() is None
        flip = adj["node63"]
        for a in flip.adjacencies:
            a.metric = 5
        als["0"].update_adjacency_database(flip)
        db1 = b.build_route_db(als, ps, changed_prefixes=set(), force_full=True)
        assert b.num_delta_builds == 1
        assert b.num_delta_rows_fetched >= 1
        assert b.num_delta_rows_skipped > 0
        changed = b.take_last_changed_prefixes()
        assert changed is not None and changed
        # host full decode it replaces: a fresh backend, full fetch
        fresh = make_backend(ndev)
        ref = fresh.build_route_db(als, ps, force_full=True)
        assert_db_equal(db1, ref)
        assert_db_equal(db1, SpfSolver("node0").build_route_db(als, ps))
        # unchanged prefixes patch through OBJECT-IDENTICALLY
        same = sum(
            1
            for p in db1.unicast_routes
            if db0.unicast_routes.get(p) is db1.unicast_routes[p]
        )
        assert same == len(db1.unicast_routes) - len(
            changed & set(db1.unicast_routes)
        )
        # device_select recorded the compacted gather
        h = b.probe.counters.histogram(
            pipeline.hist_key(pipeline.DEVICE_SELECT)
        )
        assert h is not None and h.count >= 1
        # restore for the next loop iteration
        for a in flip.adjacencies:
            a.metric = 1
        als["0"].update_adjacency_database(flip)


def test_delta_decode_handles_prefix_churn_and_deletion():
    """Churn rows are decoded even when the device reports their
    selection outputs unchanged (entry content the candidate columns
    don't encode), and deletions patch out of the db."""
    _adj, als, ps = make_world()
    b = make_backend(8)
    b.build_route_db(als, ps, changed_prefixes=set(), force_full=True)
    # delete one prefix, add another
    ps.delete_prefix("node5", "0", "10.0.5.0/24")
    ps.update_prefix("node9", "0", PrefixEntry("10.99.0.0/24"))
    changed = {"10.0.5.0/24", "10.99.0.0/24"}
    db = b.build_route_db(als, ps, changed_prefixes=changed, force_full=True)
    assert_db_equal(db, SpfSolver("node0").build_route_db(als, ps))
    assert "10.0.5.0/24" not in db.unicast_routes
    assert "10.99.0.0/24" in db.unicast_routes


def test_delta_declines_after_purge_and_on_static_change():
    """Purge semantics: corruption injection drops the delta base (the
    next full build fetches everything), and a static-route change
    declines the patch path."""
    _adj, als, ps = make_world()
    b = make_backend(8)
    b.build_route_db(als, ps, changed_prefixes=set(), force_full=True)
    assert b._prev_sel is not None
    b.inject_silent_corruption(True)
    assert b._prev_sel is None
    b.inject_silent_corruption(False)
    b.build_route_db(als, ps, changed_prefixes=set(), force_full=True)
    assert b.num_delta_builds == 0
    # static-route change between builds: delta declines
    from openr_tpu.decision.rib import RibUnicastEntry
    from openr_tpu.types import NextHop

    sr = {
        "10.200.0.0/24": RibUnicastEntry(
            prefix="10.200.0.0/24",
            nexthops=frozenset(
                {
                    NextHop(
                        address="fe80::1", if_name="eth0", metric=1
                    )
                }
            ),
            best_prefix_entry=PrefixEntry("10.200.0.0/24"),
            best_area="0",
            igp_cost=1,
        )
    }
    b.solver.update_static_unicast_routes(sr, [])
    db = b.build_route_db(als, ps, changed_prefixes=set(), force_full=True)
    assert b.num_delta_builds == 0
    assert "10.200.0.0/24" in db.unicast_routes


# ---------------------------------------------------------------------------
# fleet generation delta + engine streams
# ---------------------------------------------------------------------------


def test_fleet_generation_delta_parity():
    """The fleet engine's on-device generation delta: a perturbed
    generation re-solves on device but fetches only changed roots'
    rows; summaries and per-node RouteDbs match a fresh engine's full
    fetch."""
    from openr_tpu.decision.fleet import FleetRibEngine
    from openr_tpu.parallel.mesh import DevicePool

    adj, als, ps = make_world(6)
    pool = DevicePool()
    eng = FleetRibEngine(SpfSolver("node0"), pool=pool)
    eng.fleet_summary(als, ps, 1)
    flip = adj["node35"]
    for a in flip.adjacencies:
        a.metric = 7
    als["0"].update_adjacency_database(flip)
    s2 = eng.fleet_summary(als, ps, 2)
    assert eng.num_delta_solves == 1
    assert eng.num_delta_roots_fetched >= 1
    fresh = FleetRibEngine(SpfSolver("node0"), pool=pool)
    assert s2 == fresh.fleet_summary(als, ps, 2)
    db_a = eng.compute_for_node("node17", als, ps, 2)
    db_b = fresh.compute_for_node("node17", als, ps, 2)
    assert_db_equal(db_a, db_b)


def test_fleet_delta_declines_on_membership_change():
    """A node joining the prefix table (row map shifts on full_sync)
    must decline the delta and re-fetch everything."""
    from openr_tpu.decision.fleet import FleetRibEngine
    from openr_tpu.parallel.mesh import DevicePool

    _adj, als, ps = make_world(6)
    eng = FleetRibEngine(SpfSolver("node0"), pool=DevicePool())
    eng.fleet_summary(als, ps, 1)
    ps.update_prefix("node1", "0", PrefixEntry("10.123.0.0/24"))
    s = eng.fleet_summary(als, ps, 2)
    assert eng.num_delta_solves == 0
    fresh = FleetRibEngine(SpfSolver("node0"), pool=DevicePool())
    assert s == fresh.fleet_summary(als, ps, 2)


def test_whatif_pool_stream_matches_single_device():
    """The what-if engine's streamed per-shard drain is bit-identical
    to the single-device path."""
    from openr_tpu.decision.whatif_api import MultiAreaWhatIfEngine
    from openr_tpu.parallel.mesh import DevicePool

    _adj, als, ps = make_world(6)
    failures = [(f"node{i}", f"node{i + 1}") for i in range(0, 10) if (i + 1) % 6]
    pooled = MultiAreaWhatIfEngine(SpfSolver("node0"), pool=DevicePool())
    single = MultiAreaWhatIfEngine(SpfSolver("node0"))
    r1 = pooled.run(failures, als, ps, 1)
    r2 = single.run(failures, als, ps, 1)
    assert r1 == r2
    assert pooled.num_pool_dispatches >= 2


def test_survivor_mesh_collective_repacks_on_quarantine():
    """PR-6 remnant: engines given BOTH a mesh and a pool re-derive the
    collective mesh from DevicePool.survivor_mesh() when a chip
    quarantines mid-run, and results stay bit-identical."""
    from openr_tpu.parallel.mesh import DevicePool, shard_map_supported

    if not shard_map_supported():
        # version-gated: this jax predates the stable jax.shard_map the
        # collective engines are written against
        pytest.skip("this jax has no stable jax.shard_map")
    from openr_tpu.decision.fleet import FleetRibEngine

    _adj, als, ps = make_world(6)
    pool = DevicePool()
    eng = FleetRibEngine(
        SpfSolver("node0"), mesh=pool.survivor_mesh(), pool=pool
    )
    s1 = eng.fleet_summary(als, ps, 1)
    pool.quarantine_device(3)
    try:
        s2 = eng.fleet_summary(als, ps, 2)
        assert eng.mesh is not None
        assert eng.mesh.devices.size == pool.num_healthy
        fresh = FleetRibEngine(SpfSolver("node0"))
        assert s2 == fresh.fleet_summary(als, ps, 2)
        assert s1 == s2  # topology unchanged; only the mesh re-packed
    finally:
        pool.restore_device(3)


def test_active_mesh_rederives_on_health_transitions():
    """The mesh wiring itself (works regardless of shard_map support):
    health transitions re-derive, restores re-admit, and engines
    without a pool keep their pinned mesh."""
    from openr_tpu.decision.fleet import FleetRibEngine
    from openr_tpu.parallel.mesh import DevicePool, shard_map_supported

    pool = DevicePool()
    eng = FleetRibEngine(SpfSolver("node0"), mesh=object(), pool=pool)
    m0 = eng._active_mesh()
    if shard_map_supported():
        assert m0 is not None and m0.devices.size == 8
    else:
        assert m0 is None  # survivor_mesh is version-gated
    pool.quarantine_device(2)
    m1 = eng._active_mesh()
    if shard_map_supported():
        assert m1.devices.size == 7
    pool.restore_device(2)
    m2 = eng._active_mesh()
    if shard_map_supported():
        assert m2.devices.size == 8
    # no pool: the constructor's mesh is pinned
    pinned = object()
    eng2 = FleetRibEngine(SpfSolver("node0"), mesh=pinned)
    assert eng2._active_mesh() is pinned
