"""Schedule-perturbation race detector (ISSUE 17 tentpole, dynamic half).

Three layers:

* a deliberately order-dependent toy actor that BOTH halves of the race
  tier must catch — the static ``await-atomicity`` pass on its source,
  and the dynamic sweep as a digest divergence minimized to a seed and a
  first diverging actor turn;
* unit coverage of the machinery: divergence minimization, turn-log
  lookup, perturbed-run replay determinism, and the SimClock dispatch
  classes (prologue / mutator / observer) the detector relies on;
* the acceptance gate: the 9-node chaos world (the same topology, fault
  plan, and supervisor wiring as ``test_chaos_recovery``) must produce
  byte-identical replay digests under perturbed schedules — 3 seeds in
  tier-1, the full K=32 sweep under ``-m slow``.
"""

import asyncio

import pytest

from openr_tpu.analysis import analyze_source
from openr_tpu.chaos import (
    ChaosController,
    FaultPlan,
    InvariantChecker,
    SchedulePerturber,
    ScheduleRun,
    Supervisor,
    collect_replay_digests,
    first_divergence,
    run_schedules,
    run_world,
)
from openr_tpu.chaos.schedule import _canon, _line_time
from openr_tpu.common.runtime import SimClock
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import grid_edges

# ---------------------------------------------------------------------------
# the toy order-dependent actor — one source, caught by both halves
# ---------------------------------------------------------------------------

TOY_SOURCE = '''\
from openr_tpu.common.runtime import Actor


class LeaseActor(Actor):
    """Deliberately order-dependent toy: check-then-act on actor state
    straddles a suspension point with no re-validation."""

    def __init__(self, clock):
        super().__init__("lease", clock)
        self.owner = None

    async def claim(self, who, delay_s):
        if self.owner is None:
            await self.clock.sleep(delay_s)
            self.owner = who
'''

_ns: dict = {}
exec(compile(TOY_SOURCE, "toy_lease.py", "exec"), _ns)
LeaseActor = _ns["LeaseActor"]

#: a perturbation seed whose first same-instant shuffle swaps the two
#: claim fibers (pinned: the perturber's RNG stream is deterministic)
FLIP_SEED = 1


async def toy_world(clock):
    """Two fibers race LeaseActor.claim at the same virtual instant; the
    winner depends on same-instant wakeup order — the bug the detector
    must surface as a digest divergence."""
    actor = LeaseActor(clock)
    loop = asyncio.get_event_loop()
    tasks = [
        loop.create_task(actor.claim(who, 1.0), name=f"claim.{who}")
        for who in ("alpha", "beta")
    ]
    await clock.run_until(2.0)
    await asyncio.gather(*tasks)
    return {"toy/owner": _canon({"owner": actor.owner, "t": 1.0})}


def test_toy_race_caught_statically():
    findings = analyze_source(TOY_SOURCE, rel="toy_lease.py")
    assert [(f.rule, f.line) for f in findings] == [("await-atomicity", 15)]


def test_toy_race_caught_dynamically_with_minimized_report():
    sweep = run_schedules(toy_world, [FLIP_SEED])
    assert not sweep.identical
    (report,) = sweep.divergences
    assert report.seed == FLIP_SEED
    assert report.artifact == "toy/owner"
    assert report.line_index == 0
    assert "beta" in report.baseline_line
    assert "alpha" in report.perturbed_line
    # minimized to the first diverging actor turn of the perturbed run
    assert report.turn is not None
    t, label = report.turn
    assert t == 1.0
    assert label.startswith("claim.")
    text = report.render()
    assert f"seed={FLIP_SEED}" in text
    assert "first diverging actor turn" in text
    assert "replay: rerun" in text


def test_perturbed_run_replays_deterministically():
    """The divergence-replay contract: a perturbed schedule is itself a
    pure function of its seed — rerunning reproduces digests AND the
    turn log byte-for-byte, so every report is debuggable, not a flake."""
    a = run_world(toy_world, FLIP_SEED)
    b = run_world(toy_world, FLIP_SEED)
    assert a.digests == b.digests
    assert a.turns == b.turns
    assert a.turns, "perturbed run must record its actor-turn log"


# ---------------------------------------------------------------------------
# divergence minimization units
# ---------------------------------------------------------------------------


def test_first_divergence_none_when_identical():
    run = ScheduleRun(seed=None, digests={"x": b"same"})
    assert first_divergence(run, ScheduleRun(seed=3, digests={"x": b"same"})) is None


def test_first_divergence_minimizes_to_line_and_turn():
    baseline = ScheduleRun(
        seed=None,
        digests={"alerts/n0": b'{"ts_ms":1000,"kind":"a"}\n{"ts_ms":30000,"kind":"b"}'},
    )
    perturbed = ScheduleRun(
        seed=9,
        digests={"alerts/n0": b'{"ts_ms":1000,"kind":"a"}\n{"ts_ms":30000,"kind":"c"}'},
    )
    probe = SchedulePerturber(9)
    probe.turns = [(0.5, "boot"), (29.75, "health.sweeps"), (31.0, "late")]
    report = first_divergence(baseline, perturbed, probe)
    assert report is not None
    assert report.artifact == "alerts/n0"
    assert report.line_index == 1
    # ts_ms is milliseconds: 30000 -> t=30.0, whose nearest dispatched
    # turn at-or-before is the health sweep, not the later wakeup
    assert report.turn == (29.75, "health.sweeps")


def test_first_divergence_reports_earliest_artifact_by_name():
    baseline = ScheduleRun(seed=None, digests={"a": b"1", "z": b"1"})
    perturbed = ScheduleRun(seed=2, digests={"a": b"1", "z": b"2"})
    report = first_divergence(baseline, perturbed)
    assert report.artifact == "z"
    assert report.baseline_line == "1"
    assert report.perturbed_line == "2"


def test_line_time_parses_ms_and_s_spellings():
    assert _line_time('{"ts_ms": 30000}') == 30.0
    assert _line_time('{"t": 1.5}') == 1.5
    assert _line_time("wakeup t=2.25 fiber=x") == 2.25
    assert _line_time("no timestamp here") is None


def test_nearest_turn_bisects_turn_log():
    p = SchedulePerturber(0)
    assert p.nearest_turn(1.0) is None
    p.turns = [(1.0, "a"), (2.0, "b"), (2.0, "c"), (5.0, "d")]
    assert p.nearest_turn(0.5) == (1.0, "a")  # before first: clamp to it
    assert p.nearest_turn(2.0) == (2.0, "c")  # last turn AT the instant
    assert p.nearest_turn(9.0) == (5.0, "d")


# ---------------------------------------------------------------------------
# SimClock dispatch classes — the ordering contract the detector perturbs
# ---------------------------------------------------------------------------


def _dispatch_order(seed, marks=()):
    """Run four same-instant fibers, returning their dispatch order."""

    async def world(clock):
        for kind, label in marks:
            getattr(clock, f"mark_{kind}")(label)
        order = []

        async def fiber(name):
            await clock.sleep(1.0)
            order.append(name)

        loop = asyncio.get_event_loop()
        tasks = [
            loop.create_task(fiber(n), name=n)
            for n in ("m1", "obs", "env", "m2")
        ]
        await clock.run_until(2.0)
        await asyncio.gather(*tasks)
        return {"order": _canon(order)}

    return run_world(world, seed).digests["order"]


def test_canonical_dispatch_is_registration_order_without_marks():
    assert _dispatch_order(None) == _canon(["m1", "obs", "env", "m2"])


@pytest.mark.parametrize("seed", [None, 1, 2, 3, 4, 5])
def test_prologue_first_observer_last_on_every_schedule(seed):
    """mark_prologue fibers run before, and mark_observer fibers after,
    every same-instant mutator — on the canonical schedule and under any
    perturbation seed (only the mutator order is ever permuted)."""
    marks = (("prologue", "env"), ("observer", "obs"))
    order = _dispatch_order(seed, marks)
    decoded = order.decode()
    assert decoded.index("env") < decoded.index("m1")
    assert decoded.index("env") < decoded.index("m2")
    assert decoded.index("obs") > decoded.index("m1")
    assert decoded.index("obs") > decoded.index("m2")


# ---------------------------------------------------------------------------
# order-independence regressions for fixes the detector surfaced
# ---------------------------------------------------------------------------


def test_tracer_ids_are_content_derived_not_mint_ordered():
    """Regression (found by the perturbation sweep): trace/span ids came
    from a node-global counter, so the same spans minted in a different
    interleaving got different ids — and the ids are embedded in kvstore
    values.  Ids must be a function of content, not of mint order."""
    from openr_tpu.tracing.tracer import Tracer

    def mint(order):
        clock = SimClock()
        tracer = Tracer("n0", clock)
        ctxs = {}
        for name in order:
            ctxs[name] = tracer.start_trace(name, attrs={"k": name})
        return {name: ctx.trace_id for name, ctx in ctxs.items()}

    assert mint(["adj", "prefix"]) == mint(["prefix", "adj"])


def test_spark_loss_coin_is_content_pure():
    """Regression (found by the perturbation sweep): the loss decision
    drew from a stateful RNG in SEND order, so permuting same-tick sends
    flipped which hello got dropped.  The coin must be a pure function
    of (salt, src, dst, time, payload) — same packet, same verdict, in
    any order."""
    from openr_tpu.spark.io_provider import MockIoProvider

    io = MockIoProvider(SimClock())
    io.seed_loss_rng(7)
    c1 = io._loss_coin("n0", "n1", {"seq": 0})
    io._loss_coin("n1", "n2", {"seq": 1})  # interleave another draw
    c2 = io._loss_coin("n0", "n1", {"seq": 0})
    assert c1 == c2, "same packet must draw the same coin every time"
    assert 0.0 <= c1 < 1.0
    # a different seed moves the coin (the salt participates)
    io2 = MockIoProvider(SimClock())
    io2.seed_loss_rng(8)
    assert io2._loss_coin("n0", "n1", {"seq": 0}) != c1


# ---------------------------------------------------------------------------
# the acceptance gate: 9-node chaos world, byte-identical across schedules
# ---------------------------------------------------------------------------

SEED = 7
LEFT = ("node0", "node3", "node6")
RIGHT = ("node1", "node2", "node4", "node5", "node7", "node8")


def _chaos_overrides(cfg):
    cfg.watchdog_config.interval_s = 1.0


def _build_plan():
    plan = FaultPlan()
    plan.partition(LEFT, RIGHT, at=2.0, duration=12.0)
    plan.spark_loss("node1", "node2", prob=0.5, at=3.0, duration=8.0)
    plan.kv_rpc_latency("node1", "node4", extra_s=0.2, at=2.0, duration=10.0)
    plan.fib_burst("node4", at=4.0, duration=6.0)
    plan.actor_kill("node4", "decision", at=6.0)
    return plan


async def chaos_world(clock):
    """The 9-node chaos acceptance world (mirrors test_chaos_recovery):
    converge, run the full fault plan under supervision, heal, then
    collect every replay-sensitive digest."""
    net = EmulatedNetwork(clock, config_overrides=_chaos_overrides)
    net.build(grid_edges(3))
    net.start()
    supervisor = Supervisor(clock, initial_backoff_s=0.25, max_backoff_s=5.0)
    supervisor.start()
    for name, node in net.nodes.items():
        supervisor.supervise(name, node, net.restart_node)
    controller = ChaosController(net, _build_plan(), seed=SEED)
    await clock.run_for(18.0)
    ok, why = net.converged_full_mesh()
    assert ok, why
    controller.start()
    for _ in range(8):
        await clock.run_for(2.5)
    await clock.run_for(30.0)
    checker = InvariantChecker(net)
    checker.check_all()
    digests = collect_replay_digests(net)
    digests["chaos/counters"] = _canon(controller.counter_dump())
    await supervisor.stop()
    await controller.stop()
    await net.stop()
    return digests


def _assert_stable(seeds):
    sweep = run_schedules(chaos_world, seeds)
    assert sweep.identical, "\n" + sweep.render()
    # the digests are substantive, not vacuously empty
    assert any(
        name.startswith("kvstore/") and digest
        for name, digest in sweep.baseline.digests.items()
    )
    for run in sweep.runs:
        assert run.turns, "perturbed runs must log actor turns"


@pytest.mark.chaos
def test_chaos_world_digests_stable_under_3_schedules():
    _assert_stable([1, 2, 3])


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_world_digests_stable_under_32_schedules():
    _assert_stable(list(range(1, 33)))
