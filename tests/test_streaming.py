"""StreamingService — the watch plane's generation-correctness contract.

Covers (ISSUE 13):
* snapshot-then-delta: one cached generation-stamped snapshot, then
  per-generation deltas whose application reproduces the live route-db
  byte-identically;
* generation-correct coalescing: a stalled subscriber skipping >= 3
  generations receives exactly ONE merged delta (per-prefix last-writer-
  wins, deletions preserved) that still reproduces the live db;
* shed_oldest-to-resync escalation at the bounded queue, monotone-
  generation invariant enforcement at emission;
* breaker-protected push transports, stall detach, prefix filters,
  long-poll heartbeat;
* satellite fixes: generation-listener ordering (cache purge before
  snapshot-minting listeners), ResultCache's O(purged) generation
  index, config-tunable quota-table bound + eager disconnect prune.
"""

import asyncio
import json

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import ServingConfig
from openr_tpu.decision.backend import ScalarBackend
from openr_tpu.serving import (
    QueryService,
    ResultCache,
    ServingQuotaError,
    StreamingInvariantError,
    StreamingService,
    StreamingUnknownSubscriberError,
    apply_emission,
)
from openr_tpu.types import PrefixEntry

from tests.test_serving import build_decision, make_serving, run

pytestmark = [pytest.mark.serving, pytest.mark.streaming]


def make_streaming(clock, d, sv, **overrides):
    return StreamingService(
        "node0", clock, sv.config, d, sv, counters=d.counters
    )


def world(clock, **serving_overrides):
    d, edges = build_decision(clock, backend_cls=ScalarBackend)
    sv = make_serving(clock, d, **serving_overrides)
    st = make_streaming(clock, d, sv)
    sv.start()
    st.start()
    return d, sv, st


def bump_prefix(d, prefix, node="node5", withdraw=False):
    """One prefix-only generation bump (the LSDB-churn delta class)."""
    if withdraw:
        changed = d.prefix_state.delete_prefix(node, "0", prefix)
        d._pending_prefix_changes |= changed or {prefix}
    else:
        d.prefix_state.update_prefix(node, "0", PrefixEntry(prefix))
        d._pending_prefix_changes.add(prefix)
    d._bump_generation()


def live_rows(sv, vantage="node3"):
    _gen, res = sv.snapshot_for("route_db", {"node": vantage})
    rows = {("u", r["dest"]): r for r in res["unicast_routes"]}
    rows.update({("m", r["top_label"]): r for r in res["mpls_routes"]})
    return rows


async def poll(clock, st, sub, duration=1.0, hold=None):
    """One long-poll round; pass a short `hold` when a None heartbeat
    is the expected outcome (the default hold outlives the test)."""
    t = asyncio.ensure_future(st.next_emission(sub, hold_s=hold))
    await clock.run_for(duration)
    return t.result()


def canon(rows):
    """Byte-comparable form of a client row map (tuple keys joined)."""
    return json.dumps(
        {"|".join(map(str, k)): v for k, v in rows.items()},
        sort_keys=True,
        default=str,
    )


# ---------------------------------------------------------------------------
# snapshot + per-generation deltas
# ---------------------------------------------------------------------------


def test_snapshot_then_deltas_reproduce_live_db():
    async def main():
        clock = SimClock()
        d, sv, st = world(clock)
        sub = st.subscribe("route_db", {"node": "node3"}, client_id="c1")
        snap = await poll(clock, st, sub)
        assert snap["type"] == "snapshot" and snap["reason"] == "subscribe"
        assert snap["seq"] == d.generation_key()[0]
        assert snap["generation"] == list(d.generation_key())
        state = apply_emission({}, snap)
        assert canon(state) == canon(live_rows(sv))
        # a second subscriber's snapshot is a cache HIT (one solve per
        # generation no matter how many watchers)
        misses_before = d.counters.get("serving.cache.misses")
        sub2 = st.subscribe("route_db", {"node": "node3"}, client_id="c2")
        snap2 = await poll(clock, st, sub2)
        assert snap2["route_db"] == snap["route_db"]
        assert d.counters.get("serving.cache.misses") == misses_before
        # three generations, polled promptly: three distinct deltas
        for i in range(3):
            bump_prefix(d, f"10.200.{i}.0/24")
            delta = await poll(clock, st, sub)
            assert delta["type"] == "delta"
            assert delta["merged_generations"] == 1
            assert delta["unicast_updated"][0]["dest"] == f"10.200.{i}.0/24"
            state = apply_emission(state, delta)
        assert canon(state) == canon(live_rows(sv))
        assert st.num_invariant_violations == 0

    run(main())


def test_stalled_subscriber_gets_exactly_one_merged_delta():
    """THE acceptance bar: a subscriber skipping >= 3 generations gets
    ONE merged delta — last-writer-wins per prefix, deletions preserved
    both ways — whose application reproduces the live db."""

    async def main():
        clock = SimClock()
        d, sv, st = world(clock)
        sub = st.subscribe("route_db", {"node": "node3"}, client_id="c1")
        state = apply_emission({}, await poll(clock, st, sub))

        # 5 generations while the subscriber stalls:
        #   A: added then REMOVED       -> must arrive as a deletion
        #   B: removed then RE-ADDED    -> must arrive as an update
        #   C: plain add                -> update
        bump_prefix(d, "10.201.0.0/24")  # A add
        await clock.run_for(0.5)
        bump_prefix(d, "10.202.0.0/24")  # B add
        await clock.run_for(0.5)
        bump_prefix(d, "10.202.0.0/24", withdraw=True)  # B remove
        await clock.run_for(0.5)
        bump_prefix(d, "10.202.0.0/24")  # B re-add
        await clock.run_for(0.5)
        bump_prefix(d, "10.201.0.0/24", withdraw=True)  # A remove
        await clock.run_for(0.5)
        bump_prefix(d, "10.203.0.0/24")  # C add
        await clock.run_for(0.5)

        cursor_before = st._subs[sub].cursor_seq
        assert st._subs[sub].queue, "deltas queued while stalled"
        delta = await poll(clock, st, sub)
        assert delta["type"] == "delta"
        assert delta["merged_generations"] >= 3
        assert delta["from_seq"] == cursor_before
        assert delta["seq"] > delta["from_seq"]
        assert "10.201.0.0/24" in delta["unicast_removed"]
        updated = {r["dest"] for r in delta["unicast_updated"]}
        assert {"10.202.0.0/24", "10.203.0.0/24"} <= updated
        assert "10.201.0.0/24" not in updated
        state = apply_emission(state, delta)
        assert canon(state) == canon(live_rows(sv))
        # exactly ONE emission covered the window: nothing else queued
        assert not st._subs[sub].queue
        assert await poll(clock, st, sub, 0.5, hold=0.2) is None  # heartbeat
        assert st.num_invariant_violations == 0

    run(main())


def test_queue_overflow_sheds_oldest_and_escalates_to_resync():
    async def main():
        clock = SimClock()
        d, sv, st = world(clock)
        sv.config.stream_queue_depth = 2  # shared config object
        sub = st.subscribe("route_db", {"node": "node3"}, client_id="c1")
        state = apply_emission({}, await poll(clock, st, sub))
        for i in range(5):
            bump_prefix(d, f"10.204.{i}.0/24")
            await clock.run_for(0.5)
        assert st.num_shed >= 1
        assert d.counters.get("streaming.shed_deltas") >= 1
        emission = await poll(clock, st, sub)
        assert emission["type"] == "snapshot"
        assert emission["reason"] == "resync:queue_overflow"
        state = apply_emission(state, emission)
        assert canon(state) == canon(live_rows(sv))
        assert st.num_resyncs == 1
        # after the resync the subscriber is back on the delta path
        bump_prefix(d, "10.205.0.0/24")
        nxt = await poll(clock, st, sub)
        assert nxt["type"] == "delta"
        assert st.num_invariant_violations == 0

    run(main())


def test_monotone_generation_invariant_enforced_at_emission():
    async def main():
        clock = SimClock()
        d, sv, st = world(clock)
        sub = st.subscribe("route_db", {"node": "node3"}, client_id="c1")
        await poll(clock, st, sub)
        bump_prefix(d, "10.206.0.0/24")
        await clock.run_for(0.5)
        # sabotage: pretend the subscriber already saw a FUTURE
        # generation — the emission must refuse, not deliver stale
        st._subs[sub].cursor_seq = d.generation_key()[0] + 100
        with pytest.raises(StreamingInvariantError):
            st._next_emission_now(st._subs[sub])
        assert st.num_invariant_violations == 1
        assert d.counters.get("streaming.invariant_violations") == 1

    run(main())


# ---------------------------------------------------------------------------
# satellite: generation-listener ordering (purge before publish)
# ---------------------------------------------------------------------------


def test_generation_listeners_fire_in_stable_priority_order():
    clock = SimClock()
    d, _edges = build_decision(clock, backend_cls=ScalarBackend)
    order = []
    d.add_generation_listener(lambda s: order.append("late"), priority=10)
    d.add_generation_listener(lambda s: order.append("purge_a"))
    d.add_generation_listener(lambda s: order.append("purge_b"))
    d._bump_generation()
    # priority wins; equal priorities keep REGISTRATION order (stable)
    assert order == ["purge_a", "purge_b", "late"]


def test_query_service_purge_registers_before_streaming_publish():
    """The wiring contract: QueryService's cache purge (priority 0)
    always precedes StreamingService's publish scheduler (priority 10)
    regardless of construction order quirks — a snapshot minted from
    the fresh generation can never be raced by the purge."""
    clock = SimClock()
    d, _edges = build_decision(clock, backend_cls=ScalarBackend)
    sv = make_serving(clock, d)
    st = make_streaming(clock, d, sv)
    owners = [
        type(fn.__self__).__name__
        for _prio, _order, fn in d._generation_listeners
        if hasattr(fn, "__self__")
    ]
    assert owners.index("QueryService") < owners.index("StreamingService")
    # and functionally: on a bump, the purge runs before the streaming
    # listener observes the bump (the cache holds no superseded entry
    # by the time the publish window is scheduled)
    sv.cache.put(("old",), ("q",), {"stale": True})
    seen = []
    d.add_generation_listener(
        lambda s: seen.append(len(sv.cache)), priority=10
    )
    bump_prefix(d, "10.207.0.0/24")
    assert seen == [0], "purge must precede later-priority listeners"
    assert st._dirty


# ---------------------------------------------------------------------------
# satellite: ResultCache generation index
# ---------------------------------------------------------------------------


def test_cache_invalidation_retains_live_generation_entries():
    c = ResultCache(max_entries=16)
    for i in range(4):
        c.put(("gen_a",), ("q", i), i)
    for i in range(3):
        c.put(("gen_b",), ("q", i), 100 + i)
    c.invalidate_generation(("gen_b",))
    assert c.invalidations == 4
    assert len(c) == 3
    for i in range(3):
        hit, got = c.get(("gen_b",), ("q", i))
        assert hit and got == 100 + i
    hit, _ = c.get(("gen_a",), ("q", 0))
    assert not hit
    # the index follows LRU evictions: no stale index entry may dangle
    small = ResultCache(max_entries=2)
    small.put(("g1",), ("a",), 1)
    small.put(("g1",), ("b",), 2)
    small.put(("g2",), ("c",), 3)  # evicts ("g1", "a")
    assert small.evictions == 1
    small.invalidate_generation(("g2",))  # must not KeyError on ("g1","a")
    assert small.invalidations == 1 and len(small) == 1
    # full purge (None) clears the index too
    small.invalidate_generation(None)
    assert len(small) == 0
    small.put(("g3",), ("d",), 4)
    assert len(small) == 1


# ---------------------------------------------------------------------------
# satellite: quota-table bound is config-tunable + eager disconnect prune
# ---------------------------------------------------------------------------


def test_quota_bucket_pruned_eagerly_on_unsubscribe():
    async def main():
        clock = SimClock()
        d, sv, st = world(clock, quota_tokens=5, quota_refill_per_s=1.0)
        sub = st.subscribe("route_db", {"node": "node3"}, client_id="gone")
        assert "gone" in sv._quotas
        await clock.run_for(10.0)  # bucket fully refills
        st.unsubscribe(sub)
        assert "gone" not in sv._quotas, "refilled bucket must prune"
        # a part-spent bucket survives disconnect (dropping it would
        # refund the spend to a reconnecting client)
        sub2 = st.subscribe("route_db", {"node": "node3"}, client_id="busy")
        st.unsubscribe(sub2)
        assert "busy" in sv._quotas

    run(main())


def test_quota_client_table_bound_is_config_tunable():
    async def main():
        clock = SimClock()
        d, edges = build_decision(clock, backend_cls=ScalarBackend)
        sv = make_serving(
            clock, d, quota_tokens=100, max_quota_clients=3
        )
        assert sv.config.max_quota_clients == 3
        for i in range(4):
            sv.check_quota(f"client{i}")
        assert len(sv._quotas) == 4
        await clock.run_for(5.0)  # everyone refills
        # the NEXT admission crosses the (tunable) threshold and prunes
        # every refilled bucket except the caller's
        sv.check_quota("client_new")
        assert set(sv._quotas) == {"client_new"}

    run(main())


# ---------------------------------------------------------------------------
# prefix filters, long-poll heartbeat, stall detach, push breaker
# ---------------------------------------------------------------------------


def test_prefix_filters_scope_snapshot_and_deltas():
    async def main():
        clock = SimClock()
        d, sv, st = world(clock)
        sub = st.subscribe(
            "route_db",
            {"node": "node3"},
            client_id="c1",
            prefix_filters=("10.210.",),
        )
        snap = await poll(clock, st, sub)
        assert snap["route_db"]["unicast_routes"] == []
        bump_prefix(d, "10.210.7.0/24")
        delta = await poll(clock, st, sub)
        assert [r["dest"] for r in delta["unicast_updated"]] == [
            "10.210.7.0/24"
        ]
        # a non-matching change produces NO emission (heartbeat instead)
        bump_prefix(d, "10.211.0.0/24")
        assert await poll(clock, st, sub, 1.0, hold=0.5) is None
        assert d.counters.get("streaming.filtered_empty") >= 1

    run(main())


def test_long_poll_parks_and_wakes_on_bump():
    async def main():
        clock = SimClock()
        d, sv, st = world(clock)
        sub = st.subscribe("route_db", {"node": "node3"}, client_id="c1")
        await poll(clock, st, sub)
        # park with nothing pending; a bump mid-hold wakes the poll
        t = asyncio.ensure_future(st.next_emission(sub, hold_s=30.0))
        await clock.run_for(2.0)
        assert not t.done()
        bump_prefix(d, "10.212.0.0/24")
        await clock.run_for(1.0)
        assert t.done() and t.result()["type"] == "delta"
        # and an idle hold expires to the None heartbeat
        t2 = asyncio.ensure_future(st.next_emission(sub, hold_s=3.0))
        await clock.run_for(4.0)
        assert t2.result() is None

    run(main())


def test_stalled_subscriber_detaches_after_window():
    async def main():
        clock = SimClock()
        d, sv, st = world(clock, quota_tokens=50)
        sv.config.stream_stall_detach_s = 5.0
        sub = st.subscribe("route_db", {"node": "node3"}, client_id="c1")
        await poll(clock, st, sub)
        await clock.run_for(20.0)  # never polls again
        assert st.num_detached_stalled == 1
        assert sub not in st._subs
        assert "c1" not in sv._quotas, "detach prunes the quota bucket"
        with pytest.raises(StreamingUnknownSubscriberError):
            await st.next_emission(sub)
        # a parked long-poll counts as LIVE: it must not detach
        sub2 = st.subscribe("route_db", {"node": "node3"}, client_id="c2")
        await poll(clock, st, sub2)
        t = asyncio.ensure_future(st.next_emission(sub2, hold_s=60.0))
        await clock.run_for(20.0)
        assert sub2 in st._subs
        t.cancel()

    run(main())


def test_push_transport_breaker_trips_and_resyncs_on_heal():
    async def main():
        clock = SimClock()
        d, sv, st = world(clock)
        delivered = []
        healthy = [True]

        def deliver(emission):
            if not healthy[0]:
                raise ConnectionError("transport down")
            delivered.append(emission)

        sub = st.subscribe(
            "route_db", {"node": "node3"}, client_id="c1", deliver=deliver
        )
        assert delivered and delivered[0]["type"] == "snapshot"
        state = apply_emission({}, delivered[0])
        bump_prefix(d, "10.213.0.0/24")
        await clock.run_for(0.5)
        assert delivered[-1]["type"] == "delta"
        state = apply_emission(state, delivered[-1])

        # transport starts throwing: breaker trips, deliveries stop
        healthy[0] = False
        n_before = len(delivered)
        for i in range(4):
            bump_prefix(d, f"10.214.{i}.0/24")
            await clock.run_for(0.5)
        assert len(delivered) == n_before
        assert d.counters.get("streaming.push_failures") >= 1
        breaker = st._subs[sub].breaker
        assert breaker.state != "closed"

        # heal; wait out the jittered hold, then pump the probe through
        healthy[0] = True
        await clock.run_for(40.0)
        st.pump()
        # the lost window arrives as a RESYNC snapshot, never a gap
        assert delivered[-1]["type"] == "snapshot"
        assert delivered[-1]["reason"].startswith("resync:")
        state = apply_emission(state, delivered[-1])
        assert canon(state) == canon(live_rows(sv))
        assert breaker.state == "closed"
        assert st.num_invariant_violations == 0

    run(main())


def test_subscriber_bound_and_quota_admission():
    async def main():
        clock = SimClock()
        d, sv, st = world(clock, quota_tokens=2, quota_refill_per_s=0.1)
        sv.config.stream_max_subscribers = 2
        st.subscribe("route_db", {"node": "node1"}, client_id="a")
        st.subscribe("route_db", {"node": "node2"}, client_id="b")
        from openr_tpu.serving import ServingRejectedError

        with pytest.raises(ServingRejectedError):
            st.subscribe("route_db", {"node": "node3"}, client_id="c")
        assert d.counters.get("streaming.rejected_subscribers") == 1
        # polls charge the SAME bucket the query plane uses
        sv.config.stream_max_subscribers = 10
        s = st.subscribe("route_db", {"node": "node3"}, client_id="q")
        await poll(clock, st, s)  # token 2 of 2 (subscribe took one)
        with pytest.raises(ServingQuotaError):
            await st.next_emission(s)

    run(main())


def test_whatif_feed_snapshots_and_is_quiet_without_changes():
    async def main():
        clock = SimClock()
        d, sv, st = world(clock)
        pairs = [["node0", "node1"]]
        sub = st.subscribe(
            "whatif", {"link_failures": pairs}, client_id="c1"
        )
        snap = await poll(clock, st, sub)
        assert snap["type"] == "snapshot" and "scenario" in snap
        # a prefix bump that doesn't change the scenario answer is
        # filtered at the diff: heartbeat, not a spurious delta
        d._bump_generation()
        assert await poll(clock, st, sub, 1.0, hold=0.5) is None

    run(main())
