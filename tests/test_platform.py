"""Platform layer tests: NetlinkFibHandler over the mock kernel, the
FibService TCP server + RemoteFibAgent, and Fib programming end-to-end
through the platform agent.

Reference test parity: openr/platform (NetlinkFibHandler) +
openr/fib/tests/FibTest.cpp (Fib against a real local FibService server).
"""

import asyncio

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import FibConfig
from openr_tpu.decision.rib import (
    DecisionRouteUpdate,
    DecisionRouteUpdateType,
    RibUnicastEntry,
)
from openr_tpu.fib.fib import Fib, FibAgentError
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.platform import (
    CLIENT_ID_OPENR,
    FibServiceServer,
    NetlinkFibAgent,
    NetlinkFibHandler,
    RemoteFibAgent,
)
from openr_tpu.platform.nl import (
    MockNetlinkProtocolSocket,
    NetlinkEventsInjector,
)
from openr_tpu.types import (
    MplsAction,
    MplsActionCode,
    MplsRoute,
    NextHop,
    UnicastRoute,
)


def make_handler():
    nl = MockNetlinkProtocolSocket()
    inj = NetlinkEventsInjector(nl)
    inj.set_link(2, "eth0", True)
    inj.set_link(3, "eth1", True)
    return NetlinkFibHandler(nl), nl


def uroute(dest, *hops):
    return UnicastRoute(
        dest=dest,
        next_hops=[NextHop(address=a, if_name=i) for a, i in hops],
    )


class TestNetlinkFibHandler:
    def test_unicast_add_delete_programs_kernel(self):
        async def run():
            handler, nl = make_handler()
            await handler.add_unicast_routes(
                CLIENT_ID_OPENR,
                [
                    uroute("10.1.0.0/24", ("fe80::1", "eth0")),
                    uroute("10.2.0.0/24", ("fe80::1", "eth0"), ("fe80::2", "eth1")),
                ],
            )
            kernel = await handler.get_kernel_routes()
            assert {r.prefix for r in kernel} == {"10.1.0.0/24", "10.2.0.0/24"}
            multi = next(r for r in kernel if r.prefix == "10.2.0.0/24")
            assert {nh.if_index for nh in multi.nexthops} == {2, 3}
            table = await handler.get_route_table_by_client(CLIENT_ID_OPENR)
            assert len(table) == 2

            await handler.delete_unicast_routes(CLIENT_ID_OPENR, ["10.1.0.0/24"])
            assert len(await handler.get_kernel_routes()) == 1
            # deleting a never-programmed prefix is tolerated
            await handler.delete_unicast_routes(CLIENT_ID_OPENR, ["10.99.0.0/16"])

        asyncio.run(run())

    def test_unknown_interface_raises(self):
        async def run():
            handler, _ = make_handler()
            with pytest.raises(FibAgentError):
                await handler.add_unicast_routes(
                    CLIENT_ID_OPENR, [uroute("10.1.0.0/24", ("fe80::1", "wat0"))]
                )

        asyncio.run(run())

    def test_mpls_routes(self):
        async def run():
            handler, _ = make_handler()
            route = MplsRoute(
                top_label=100101,
                next_hops=[
                    NextHop(
                        address="fe80::1",
                        if_name="eth0",
                        mpls_action=MplsAction(
                            action=MplsActionCode.SWAP, swap_label=100201
                        ),
                    )
                ],
            )
            await handler.add_mpls_routes(CLIENT_ID_OPENR, [route])
            kernel = await handler.get_kernel_routes()
            assert kernel[0].label == 100101
            await handler.delete_mpls_routes(CLIENT_ID_OPENR, [100101])
            assert not await handler.get_kernel_routes()

        asyncio.run(run())

    def test_sync_fib_removes_stale(self):
        async def run():
            handler, _ = make_handler()
            await handler.add_unicast_routes(
                CLIENT_ID_OPENR,
                [
                    uroute("10.1.0.0/24", ("fe80::1", "eth0")),
                    uroute("10.2.0.0/24", ("fe80::1", "eth0")),
                ],
            )
            await handler.sync_fib(
                CLIENT_ID_OPENR,
                [
                    uroute("10.2.0.0/24", ("fe80::2", "eth1")),
                    uroute("10.3.0.0/24", ("fe80::1", "eth0")),
                ],
            )
            kernel = await handler.get_kernel_routes()
            assert {r.prefix for r in kernel} == {"10.2.0.0/24", "10.3.0.0/24"}

        asyncio.run(run())

    def test_per_client_tables(self):
        async def run():
            handler, _ = make_handler()
            await handler.add_unicast_routes(
                1, [uroute("10.1.0.0/24", ("fe80::1", "eth0"))]
            )
            await handler.add_unicast_routes(
                2, [uroute("10.2.0.0/24", ("fe80::1", "eth0"))]
            )
            assert len(await handler.get_route_table_by_client(1)) == 1
            assert len(await handler.get_route_table_by_client(2)) == 1
            assert not await handler.get_route_table_by_client(3)

        asyncio.run(run())


class TestFibServiceServer:
    def test_remote_agent_end_to_end(self):
        async def run():
            handler, nl = make_handler()
            server = FibServiceServer(handler)
            await server.start()
            agent = RemoteFibAgent(port=server.port)
            try:
                await agent.add_unicast_routes(
                    [uroute("10.1.0.0/24", ("fe80::1", "eth0"))]
                )
                table = await agent.get_route_table()
                assert table[0].dest == "10.1.0.0/24"
                assert table[0].next_hops[0].address == "fe80::1"
                assert await agent.alive_since() > 0
                await agent.sync_fib(
                    [uroute("10.5.0.0/24", ("fe80::2", "eth1"))], []
                )
                kernel = await handler.get_kernel_routes()
                assert {r.prefix for r in kernel} == {"10.5.0.0/24"}
                # transport error path: agent surface FibAgentError
                await server.stop()
                await agent.close()
                with pytest.raises(FibAgentError):
                    await agent.add_unicast_routes(
                        [uroute("10.6.0.0/24", ("fe80::1", "eth0"))]
                    )
            finally:
                await agent.close()
                await server.stop()

        asyncio.run(run())

    def test_platform_thrift_parity_methods(self):
        """The four remaining FibService methods (Platform.thrift:78-146):
        singular add/delete, getSwitchRunState, sendNeighborDownInfo
        fan-out to registered neighbor listeners."""

        async def run():
            from openr_tpu.platform.fib_service import (
                SWITCH_RUN_STATE_CONFIGURED,
            )

            handler, nl = make_handler()
            down_events = []
            handler.register_neighbor_listener(
                lambda ips, is_up: down_events.append((tuple(ips), is_up))
            )
            server = FibServiceServer(handler)
            await server.start()
            agent = RemoteFibAgent(port=server.port)
            try:
                await agent.add_unicast_route(
                    uroute("10.9.0.0/24", ("fe80::1", "eth0"))
                )
                assert [r.dest for r in await agent.get_route_table()] == [
                    "10.9.0.0/24"
                ]
                await agent.delete_unicast_route("10.9.0.0/24")
                assert not await agent.get_route_table()
                assert (
                    await agent.get_switch_run_state()
                    == SWITCH_RUN_STATE_CONFIGURED
                )
                # a throwing listener must not starve later listeners
                def bad(ips, up):
                    raise RuntimeError("boom")

                handler._neighbor_listeners.insert(0, bad)
                await agent.send_neighbor_down_info(["fe80::9", "fe80::a"])
                assert down_events == [(("fe80::9", "fe80::a"), False)]
                assert (await agent.get_counters())[
                    "fib.neighbor_listener_errors"
                ] == 1
            finally:
                await agent.close()
                await server.stop()

        asyncio.run(run())


class TestFibThroughPlatform:
    def test_fib_programs_via_netlink_agent(self):
        """DecisionRouteUpdate -> Fib -> NetlinkFibAgent -> mock kernel."""

        async def run():
            clock = SimClock()
            handler, nl = make_handler()
            agent = NetlinkFibAgent(handler)
            routes_q = ReplicateQueue("routeUpdates")
            fib = Fib(
                node_name="node1",
                clock=clock,
                config=FibConfig(),
                agent=agent,
                route_updates_reader=routes_q.get_reader(),
            )
            fib.start()
            entry = RibUnicastEntry(
                prefix="10.1.0.0/24",
                nexthops=[NextHop(address="fe80::1", if_name="eth0")],
            )
            routes_q.push(
                DecisionRouteUpdate(
                    type=DecisionRouteUpdateType.FULL_SYNC,
                    unicast_routes_to_update={"10.1.0.0/24": entry},
                )
            )
            await clock.run_for(1.0)
            kernel = await handler.get_kernel_routes()
            assert {r.prefix for r in kernel} == {"10.1.0.0/24"}
            assert fib.synced
            await fib.stop()

        asyncio.run(run())
