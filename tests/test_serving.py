"""Serving plane — micro-batching, content-addressed caching, admission.

The contract under test (docs/Serving.md):

* N concurrent distinct what-if queries against one LSDB generation are
  answered by EXACTLY ONE device batch solve (counter-verified on the
  engine), with per-request answers identical to the unbatched path;
* repeated queries hit the result cache and are served without ANY
  solve; a generation bump (LSDB churn or RibPolicy flip) invalidates;
* identical in-flight queries dedup onto one future;
* the bounded queue sheds (policy-selectable) instead of growing, token
  quotas refuse over-budget clients, and a TPU outage degrades the
  batcher to the scalar/native paths without deadlock.

All timing rides SimClock — every test replays deterministically.
"""

import asyncio

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import DecisionConfig, ServingConfig
from openr_tpu.decision.backend import ScalarBackend, TpuBackend
from openr_tpu.decision.decision import Decision
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib_policy import (
    RibPolicy,
    RibPolicyStatement,
    RibRouteActionWeight,
)
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.serving import (
    QueryService,
    ServingQuotaError,
    ServingRejectedError,
    ServingShedError,
    canonical_query,
)
from openr_tpu.types import PrefixEntry

pytestmark = pytest.mark.serving


def build_decision(clock, backend_cls=TpuBackend, n_side=4):
    edges = grid_edges(n_side)
    dbs = build_adj_dbs(edges)
    ls = LinkState("0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(n_side * n_side):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))
    solver = SpfSolver("node0")
    d = Decision(
        "node0",
        clock,
        DecisionConfig(),
        ReplicateQueue("routes"),
        backend=backend_cls(solver),
        solver=solver,
    )
    d.area_link_states = {"0": ls}
    d.prefix_state = ps
    d._change_seq = 1
    if backend_cls is TpuBackend:
        # deterministic engine choice: a zero dispatch round trip makes
        # the DEVICE what-if engine win the native-vs-device calibration
        d.backend.auto_dispatch_rt_ms = 0.0
    return d, edges


def make_serving(clock, d, **overrides):
    cfg = ServingConfig(**overrides)
    return QueryService(
        "node0", clock, cfg, d, counters=d.counters
    )


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        # tests leave the batcher fiber parked on its arrival event;
        # cancel stragglers so loop.close() is silent
        pending = asyncio.all_tasks(loop)
        for t in pending:
            t.cancel()
        if pending:
            loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True)
            )
        loop.close()


async def settle(clock, duration=0.1):
    await clock.run_for(duration)


def norm_routes(db_wire: dict) -> dict:
    """Route-order-insensitive view of a RouteDatabase wire dict (the
    fleet decode emits prefix-sorted rows, the scalar solver insertion
    order; content must be identical)."""
    import json

    return {
        **db_wire,
        "unicast_routes": sorted(
            db_wire["unicast_routes"],
            key=lambda r: json.dumps(r, sort_keys=True, default=str),
        ),
        "mpls_routes": sorted(
            db_wire["mpls_routes"],
            key=lambda r: json.dumps(r, sort_keys=True, default=str),
        ),
    }


# ---------------------------------------------------------------------------
# micro-batching + dedup + cache
# ---------------------------------------------------------------------------


def test_eight_concurrent_whatif_queries_one_device_batch_solve():
    """THE acceptance bar: >=8 concurrent identical-generation what-if
    queries -> exactly 1 device batch solve, counter-verified, answers
    identical to the unbatched path; a second round is served from the
    cache without any solve."""

    async def main():
        clock = SimClock()
        d, edges = build_decision(clock)
        sv = make_serving(clock, d)
        sv.start()
        pairs = [(a, b) for a, b, _m in edges][:8]
        # the unbatched oracle: one direct engine call per query (run
        # FIRST so its own engine counters don't pollute the assert;
        # use a dedicated Decision so the serving path's engines start
        # cold)
        oracle_d, _ = build_decision(clock)
        oracle = {
            p: oracle_d.get_link_failure_whatif([list(p)]) for p in pairs
        }

        tasks = [
            asyncio.ensure_future(
                sv.submit("whatif", {"link_failures": [p]})
            )
            for p in pairs
        ]
        await settle(clock)
        results = [t.result() for t in tasks]
        engine = d._whatif_engine
        assert engine is not None, "device what-if engine must serve this"
        assert engine.num_sweeps == 1, (
            "8 concurrent queries must coalesce into ONE device sweep"
        )
        assert sv.num_batches == 1
        assert d.counters.get("serving.batches") == 1
        for p, got in zip(pairs, results):
            want = oracle[p]
            assert got["eligible"] and want["eligible"]
            assert got["failures"] == want["failures"], p

        # round 2: pure cache hits — NO additional solve of any kind
        tasks = [
            asyncio.ensure_future(
                sv.submit("whatif", {"link_failures": [p]})
            )
            for p in pairs
        ]
        await settle(clock)
        cached = [t.result() for t in tasks]
        assert cached == results
        assert engine.num_sweeps == 1  # untouched
        assert sv.num_batches == 1  # no new batch either
        assert d.counters.get("serving.cache.hits") == 8

    run(main())


def test_identical_inflight_queries_dedup_onto_one_future():
    async def main():
        clock = SimClock()
        d, edges = build_decision(clock)
        sv = make_serving(clock, d)
        sv.start()
        pair = (edges[0][0], edges[0][1])
        tasks = [
            asyncio.ensure_future(
                sv.submit("whatif", {"link_failures": [pair]})
            )
            for _ in range(4)
        ]
        await settle(clock)
        results = [t.result() for t in tasks]
        assert all(r == results[0] for r in results)
        assert sv.num_dedup_hits == 3
        assert d._whatif_engine.num_sweeps == 1

    run(main())


def test_route_db_batch_rides_one_fleet_solve():
    """A flush of K route_db queries costs ONE fleet batch solve + K
    decodes (the fleet engine's all-roots table), and each answer equals
    the scalar per-vantage oracle."""

    async def main():
        clock = SimClock()
        d, _edges = build_decision(clock)
        sv = make_serving(clock, d)
        sv.start()
        nodes = [f"node{i}" for i in range(8)]
        tasks = [
            asyncio.ensure_future(sv.submit("route_db", {"node": n}))
            for n in nodes
        ]
        await settle(clock)
        results = [t.result() for t in tasks]
        fleet = d._fleet_engine
        assert fleet is not None and fleet.num_batched_solves == 1
        assert fleet.num_decodes == 8
        for n, got in zip(nodes, results):
            oracle = (
                SpfSolver(n)
                .build_route_db(d.area_link_states, d.prefix_state)
                .to_route_database(n)
                .to_wire()
            )
            assert norm_routes(got) == norm_routes(oracle), n

    run(main())


def test_max_batch_flushes_without_waiting_for_timer():
    async def main():
        clock = SimClock()
        d, edges = build_decision(clock)
        sv = make_serving(clock, d, max_batch=4, max_wait_ms=60_000)
        sv.start()
        pairs = [(a, b) for a, b, _m in edges][:4]
        tasks = [
            asyncio.ensure_future(
                sv.submit("whatif", {"link_failures": [p]})
            )
            for p in pairs
        ]
        # virtually no time passes: the full batch must flush on count
        await settle(clock, 0.001)
        assert all(t.done() for t in tasks)
        assert sv.num_batches == 1

    run(main())


# ---------------------------------------------------------------------------
# cache invalidation: generation = (LSDB, RibPolicy)
# ---------------------------------------------------------------------------


def _weight_policy(clock) -> RibPolicy:
    return RibPolicy(
        statements=[
            RibPolicyStatement(
                name="t",
                prefixes=["10.1.0.0/24"],
                action=RibRouteActionWeight(default_weight=3),
            )
        ],
        valid_until=clock.now() + 3600.0,
    )


def test_policy_flip_invalidates_serving_cache_and_fleet_cache():
    """Satellite regression: a RibPolicy set/clear between two
    identical-LSDB queries MUST invalidate the fleet table cache and the
    serving result cache (generation is (LSDB, policy), not LSDB)."""

    async def main():
        clock = SimClock()
        d, _edges = build_decision(clock)
        sv = make_serving(clock, d)
        sv.start()

        async def one_query():
            return await asyncio.ensure_future(
                sv.submit("route_db", {"node": "node3"})
            )

        t = asyncio.ensure_future(one_query())
        await settle(clock)
        t.result()
        fleet = d._fleet_engine
        assert fleet.num_batched_solves == 1
        gen_before = d.generation_key()

        d.set_rib_policy(_weight_policy(clock))
        assert d.generation_key() != gen_before
        # eager invalidation ran (rebuild-path hook)
        assert len(sv.cache) == 0
        assert d.counters.get("serving.cache.generation_invalidations") >= 1

        t = asyncio.ensure_future(one_query())
        await settle(clock)
        t.result()
        # identical LSDB, but the policy flip forced a re-solve
        assert fleet.num_batched_solves == 2
        assert d.counters.get("serving.cache.hits") == 0

        d.clear_rib_policy()
        t = asyncio.ensure_future(one_query())
        await settle(clock)
        t.result()
        assert fleet.num_batched_solves == 3

    run(main())


def test_fleet_cache_policy_flip_regression_direct():
    """The same satellite regression WITHOUT the serving plane: two
    identical-LSDB compute_route_db_for_node calls around a policy flip
    re-solve the fleet tables instead of serving the stale cache."""
    clock = SimClock()
    d, _edges = build_decision(clock)
    d.compute_route_db_for_node("node5")
    assert d._fleet_engine.num_batched_solves == 1
    d.compute_route_db_for_node("node5")
    assert d._fleet_engine.num_batched_solves == 1  # cached
    d.set_rib_policy(_weight_policy(clock))
    d.compute_route_db_for_node("node5")
    assert d._fleet_engine.num_batched_solves == 2  # policy flip re-solved
    d.clear_rib_policy()
    d.compute_route_db_for_node("node5")
    assert d._fleet_engine.num_batched_solves == 3


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_reject_newest_when_queue_full():
    async def main():
        clock = SimClock()
        d, edges = build_decision(clock)
        sv = make_serving(
            clock, d, max_queue_depth=2, max_batch=64, max_wait_ms=50
        )
        sv.start()
        pairs = [(a, b) for a, b, _m in edges][:3]
        t1 = asyncio.ensure_future(
            sv.submit("whatif", {"link_failures": [pairs[0]]})
        )
        t2 = asyncio.ensure_future(
            sv.submit("whatif", {"link_failures": [pairs[1]]})
        )
        t3 = asyncio.ensure_future(
            sv.submit("whatif", {"link_failures": [pairs[2]]})
        )
        await settle(clock, 0.2)
        assert t1.result()["eligible"] and t2.result()["eligible"]
        with pytest.raises(ServingRejectedError):
            t3.result()
        assert sv.num_rejected == 1

    run(main())


def test_shed_oldest_evicts_longest_waiter():
    async def main():
        clock = SimClock()
        d, edges = build_decision(clock)
        sv = make_serving(
            clock, d, max_queue_depth=2, max_batch=64, max_wait_ms=50,
            shed_policy="shed_oldest",
        )
        sv.start()
        pairs = [(a, b) for a, b, _m in edges][:3]
        tasks = [
            asyncio.ensure_future(
                sv.submit("whatif", {"link_failures": [p]})
            )
            for p in pairs
        ]
        await settle(clock, 0.2)
        with pytest.raises(ServingShedError):
            tasks[0].result()  # the OLDEST was shed in the newest's favor
        assert tasks[1].result()["eligible"]
        assert tasks[2].result()["eligible"]
        assert sv.num_shed == 1
        assert d.counters.get("serving.shed") == 1

    run(main())


def test_client_token_quota_refuses_and_refills():
    async def main():
        clock = SimClock()
        d, edges = build_decision(clock)
        sv = make_serving(
            clock, d, quota_tokens=2, quota_refill_per_s=1.0
        )
        sv.start()
        pairs = [(a, b) for a, b, _m in edges]

        async def q(i, client):
            return await sv.submit(
                "whatif", {"link_failures": [pairs[i]]}, client_id=client
            )

        t1 = asyncio.ensure_future(q(0, "alice"))
        t2 = asyncio.ensure_future(q(1, "alice"))
        t3 = asyncio.ensure_future(q(2, "alice"))
        t4 = asyncio.ensure_future(q(3, "bob"))  # separate bucket
        await settle(clock, 0.2)
        assert t1.result()["eligible"] and t2.result()["eligible"]
        with pytest.raises(ServingQuotaError):
            t3.result()
        assert t4.result()["eligible"]
        assert sv.num_quota_rejected == 1
        # tokens refill on the injected clock: 2 virtual seconds -> 2
        await settle(clock, 2.0)
        t5 = asyncio.ensure_future(q(4, "alice"))
        await settle(clock, 0.2)
        assert t5.result()["eligible"]

    run(main())


# ---------------------------------------------------------------------------
# canonicalization
# ---------------------------------------------------------------------------


def test_canonical_query_normalizes_pair_order():
    a = canonical_query(
        "whatif", {"link_failures": [("node1", "node2")]}
    )
    b = canonical_query(
        "whatif", {"link_failures": [("node2", "node1")]}
    )
    assert a == b
    # simultaneous sets ignore listing order entirely
    s1 = canonical_query(
        "whatif",
        {"link_failures": [("a", "b"), ("c", "d")], "simultaneous": True},
    )
    s2 = canonical_query(
        "whatif",
        {"link_failures": [("d", "c"), ("b", "a")], "simultaneous": True},
    )
    assert s1 == s2
    # ...but per-failure queries preserve response row order
    o1 = canonical_query(
        "whatif", {"link_failures": [("a", "b"), ("c", "d")]}
    )
    o2 = canonical_query(
        "whatif", {"link_failures": [("c", "d"), ("a", "b")]}
    )
    assert o1 != o2


def test_trace_spans_chain_enqueue_batch_solve_kernel():
    """A served query renders as serving.enqueue → serving.batch_solve
    → decision.spf_kernel spans in one trace (the Observability.md
    taxonomy), and the queue-wait/batch-size histograms observe."""

    async def main():
        clock = SimClock()
        d, edges = build_decision(clock)
        from openr_tpu.tracing import Tracer

        tracer = Tracer("node0", clock, counters=d.counters)
        sv = QueryService(
            "node0", clock, ServingConfig(), d,
            counters=d.counters, tracer=tracer,
        )
        sv.start()
        pair = (edges[0][0], edges[0][1])
        t = asyncio.ensure_future(
            sv.submit("whatif", {"link_failures": [pair]})
        )
        await settle(clock)
        assert t.result()["eligible"]
        by_name: dict = {}
        for s in tracer.get_spans():
            by_name.setdefault(s.name, []).append(s)
        enq = by_name["serving.enqueue"][0]
        solve = by_name["serving.batch_solve"][0]
        assert solve.parent_id == enq.span_id
        assert solve.trace_id == enq.trace_id
        assert solve.attrs["batch_size"] == 1
        kernels = by_name.get("decision.spf_kernel", [])
        assert any(
            k.parent_id == solve.span_id and k.trace_id == enq.trace_id
            for k in kernels
        ), "kernel dispatches must parent under the batch solve"
        for key in ("serving.queue_wait_ms", "serving.batch_size",
                    "serving.batch_solve_ms"):
            h = d.counters.histogram(key)
            assert h is not None and h.count >= 1, key

    run(main())


def test_disabled_serving_answers_inline():
    """serving_config.enabled=false: no batcher fiber runs, but the
    verbs still answer (inline, unbatched) — flipping the knob never
    strands a client."""

    async def main():
        clock = SimClock()
        d, edges = build_decision(clock)
        sv = make_serving(clock, d, enabled=False)
        # deliberately NOT started: disabled mode must not need the fiber
        pair = (edges[0][0], edges[0][1])
        got = await sv.submit("whatif", {"link_failures": [pair]})
        assert got["eligible"]
        db = await sv.submit("route_db", {"node": "node1"})
        assert db["this_node_name"] == "node1"
        assert sv.num_batches == 0
        # still cached: the second identical query is a hit
        again = await sv.submit("whatif", {"link_failures": [pair]})
        assert again == got
        assert d.counters.get("serving.cache.hits") == 1

    run(main())


def test_scalar_backend_serving_still_works():
    """The serving plane is not a device feature: scalar deployments
    batch/cache/shed the same way over the scalar engines."""

    async def main():
        clock = SimClock()
        d, _edges = build_decision(clock, backend_cls=ScalarBackend)
        sv = make_serving(clock, d)
        sv.start()
        t = asyncio.ensure_future(sv.submit("route_db", {"node": "node2"}))
        await settle(clock)
        got = t.result()
        oracle = (
            SpfSolver("node2")
            .build_route_db(d.area_link_states, d.prefix_state)
            .to_route_database("node2")
            .to_wire()
        )
        assert norm_routes(got) == norm_routes(oracle)

    run(main())
