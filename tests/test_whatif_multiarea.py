"""Multi-area operator what-if parity (VERDICT r3 missing #3).

The bar: for EVERY candidate link failure in a 2-area world — border
links included — the MultiAreaWhatIfEngine's per-failure route deltas
must match the scalar oracle (SpfSolver.build_route_db on the mutated
LSDB, the reference's getDecisionRouteDb semantics, Decision.cpp:342):
same changed-prefix set, same old/new nexthop neighbor sets, same
old/new metrics.
"""

import numpy as np
import pytest

from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.decision.whatif_api import MultiAreaWhatIfEngine
from openr_tpu.emulation.topology import build_adj_dbs, ring_edges
from openr_tpu.types import PrefixEntry, PrefixMetrics


def make_ls(edges, area, me="") -> LinkState:
    ls = LinkState(area, me)
    for db in build_adj_dbs(edges, area=area).values():
        ls.update_adjacency_database(db)
    return ls


AREA_EDGES = {
    "1": [("a0", "a1", 1), ("a1", "b0", 1), ("a0", "b0", 3)],
    "2": ring_edges(4, prefix="b"),
}


def two_area_world(me="b0"):
    return {
        a: make_ls(edges, a, me=me) for a, edges in AREA_EDGES.items()
    }


def make_prefixes() -> PrefixState:
    ps = PrefixState()
    ps.update_prefix("a0", "1", PrefixEntry("10.0.0.0/24"))
    ps.update_prefix("b2", "2", PrefixEntry("10.1.0.0/24"))
    ps.update_prefix("b1", "2", PrefixEntry("2001:db8::/64"))
    # anycast across areas (cross-area min-metric merge under failures)
    ps.update_prefix("a1", "1", PrefixEntry(
        "10.9.0.0/24", metrics=PrefixMetrics(path_preference=700)))
    ps.update_prefix("b3", "2", PrefixEntry(
        "10.9.0.0/24", metrics=PrefixMetrics(path_preference=700)))
    return ps


def oracle_view(me, als, ps):
    """prefix -> (metric, frozenset of nexthop neighbor names)."""
    db = SpfSolver(me).build_route_db(als, ps)
    return {
        p: (
            float(e.igp_cost),
            frozenset(nh.neighbor_node_name for nh in e.nexthops),
        )
        for p, e in db.unicast_routes.items()
    }


def oracle_changes(me, ps, area, n1, n2):
    """The scalar diff for failing link (n1, n2) in `area`."""
    base = oracle_view(me, two_area_world(me), ps)
    mutated = {
        a: make_ls(
            [e for e in edges if not (
                a == area and {e[0], e[1]} == {n1, n2}
            )],
            a,
            me=me,
        )
        for a, edges in AREA_EDGES.items()
    }
    after = oracle_view(me, mutated, ps)
    changes = {}
    for p in set(base) | set(after):
        b, f = base.get(p), after.get(p)
        if b != f:
            changes[p] = (b, f)
    return changes


def api_changes(result, link):
    for f in result["failures"]:
        if f["link"] == list(link):
            assert "error" not in f, f
            return {
                c["prefix"]: (
                    (
                        (c["old_metric"], frozenset(c["old_nexthops"]))
                        if c["old_metric"] is not None
                        else None
                    ),
                    (
                        (c["new_metric"], frozenset(c["new_nexthops"]))
                        if c["new_metric"] is not None
                        else None
                    ),
                )
                for c in f["changes"]
            }
    raise AssertionError(f"no result for {link}")


def all_links():
    return [
        (a, n1, n2) for a, edges in AREA_EDGES.items()
        for (n1, n2, _w) in edges
    ]


def test_every_failure_matches_scalar_oracle():
    me = "b0"
    ps = make_prefixes()
    eng = MultiAreaWhatIfEngine(SpfSolver(me))
    links = all_links()
    result = eng.run(
        [(n1, n2) for (_a, n1, n2) in links],
        two_area_world(me),
        ps,
        change_seq=1,
    )
    assert result["eligible"] and result["vantage"] == me
    for a, n1, n2 in links:
        want = oracle_changes(me, ps, a, n1, n2)
        got = api_changes(result, (n1, n2))
        assert got == want, (a, n1, n2)
    assert eng.num_engine_builds == 1


def test_border_failure_reroutes_cross_area_anycast():
    """Failing the cheap border-adjacent link must reroute area-1
    prefixes onto the expensive backup and shift the cross-area anycast
    merge — a genuinely cross-area delta."""
    me = "b0"  # the border node: participates in both areas
    ps = make_prefixes()
    eng = MultiAreaWhatIfEngine(SpfSolver(me))
    result = eng.run(
        [("a1", "b0")], two_area_world(me), ps, change_seq=1
    )
    want = oracle_changes(me, ps, "1", "a1", "b0")
    got = api_changes(result, ("a1", "b0"))
    assert got == want
    assert want, "expected the border failure to change something"


def test_unknown_and_parallel_links_reported():
    me = "b0"
    ps = make_prefixes()
    eng = MultiAreaWhatIfEngine(SpfSolver(me))
    result = eng.run(
        [("nope", "b0")], two_area_world(me), ps, change_seq=1
    )
    assert result["failures"][0]["error"] == "unknown link"


def test_decision_routes_multiarea_to_device_engine():
    """Decision.get_link_failure_whatif must no longer refuse multi-area
    LSDBs (the r3 single-area guard)."""
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import DecisionConfig
    from openr_tpu.decision.decision import Decision
    from openr_tpu.messaging.queue import ReplicateQueue

    from openr_tpu.decision.backend import TpuBackend

    me = "b0"
    ps = make_prefixes()
    d = Decision(
        me,
        SimClock(),
        DecisionConfig(),
        ReplicateQueue(),
        backend=TpuBackend(SpfSolver(me)),
    )
    d.area_link_states = two_area_world(me)
    d.prefix_state = ps
    d._change_seq = 7
    res = d.get_link_failure_whatif([("a1", "b0"), ("b0", "b1")])
    assert res is not None and res["eligible"]
    assert len(res["failures"]) == 2
    want = oracle_changes(me, ps, "1", "a1", "b0")
    assert api_changes(res, ("a1", "b0")) == want


def test_batch_bucketing_independent_of_query_size():
    """Query size must not change per-failure answers (the batch pads to
    stable jit buckets; pad rows are base snapshots)."""
    me = "b0"
    ps = make_prefixes()
    eng = MultiAreaWhatIfEngine(SpfSolver(me))
    als = two_area_world(me)
    solo = eng.run([("a1", "b0")], als, ps, change_seq=1)
    many = eng.run(
        [("b0", "b1"), ("a1", "b0"), ("b2", "b3")], als, ps, change_seq=1
    )
    assert api_changes(solo, ("a1", "b0")) == api_changes(
        many, ("a1", "b0")
    )
    assert eng.num_engine_builds == 1  # same generation, cached context


# ---- generic-solver fallback (algorithm-complete what-if) ------------------


def _oracle_view_without(me, ps, drop_pairs):
    """Oracle with ALL listed pairs removed from every area at once."""
    mutated = {
        a: make_ls(
            [
                (n1, n2, m)
                for (n1, n2, m) in edges
                if frozenset((n1, n2)) not in drop_pairs
            ],
            a,
            me=me,
        )
        for a, edges in AREA_EDGES.items()
    }
    return oracle_view(me, mutated, ps)


def _apply_changes(base_view, failure):
    got = {p: (m, set(nhs)) for p, (m, nhs) in base_view.items()}
    for ch in failure["changes"]:
        if ch["change"] == "removed":
            got.pop(ch["prefix"], None)
        else:
            got[ch["prefix"]] = (
                ch["new_metric"],
                set(ch["new_nexthops"]),
            )
    return got


def test_generic_fallback_multiarea_simultaneous():
    """Multi-area --simultaneous (the fast engines decline it) must
    answer through the generic solver engine with oracle parity."""
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import DecisionConfig
    from openr_tpu.decision.backend import ScalarBackend
    from openr_tpu.decision.decision import Decision
    from openr_tpu.messaging.queue import ReplicateQueue

    me = "b0"
    ps = make_prefixes()
    d = Decision(
        me,
        SimClock(),
        DecisionConfig(),
        ReplicateQueue(),
        backend=ScalarBackend(SpfSolver(me)),
    )
    d.area_link_states = two_area_world(me)
    d.prefix_state = ps
    d._change_seq = 3
    pairs = [("a1", "b0"), ("b0", "b1")]
    res = d.get_link_failure_whatif(
        [list(p) for p in pairs], simultaneous=True
    )
    assert res is not None and res["eligible"]
    assert res["engine"] == "generic-solver"
    (f,) = res["failures"]

    base = {
        p: (m, set(nhs))
        for p, (m, nhs) in oracle_view(me, two_area_world(me), ps).items()
    }
    want = {
        p: (m, set(nhs))
        for p, (m, nhs) in _oracle_view_without(
            me, ps, {frozenset(p) for p in pairs}
        ).items()
    }
    assert _apply_changes(
        {p: (m, sorted(s)) for p, (m, s) in base.items()}, f
    ) == want


def test_generic_fallback_ksp2_answers():
    """KSP2_ED_ECMP vantages (fleet-ineligible) must still answer
    what-ifs via the generic solver engine, matching the KSP2 oracle."""
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import DecisionConfig
    from openr_tpu.decision.backend import ScalarBackend
    from openr_tpu.decision.decision import Decision
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.types import PrefixForwardingAlgorithm

    me = "b0"
    ps = PrefixState()
    ps.update_prefix(
        "b2",
        "2",
        PrefixEntry(
            "10.1.0.0/24",
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        ),
    )
    solver = SpfSolver(me)
    d = Decision(
        me,
        SimClock(),
        DecisionConfig(),
        ReplicateQueue(),
        backend=ScalarBackend(solver),
        solver=solver,
    )
    d.area_link_states = two_area_world(me)
    d.prefix_state = ps
    d._change_seq = 5
    res = d.get_link_failure_whatif([("b0", "b1")])
    assert res is not None and res["eligible"]
    assert res["engine"] == "generic-solver"
    (f,) = res["failures"]
    # KSP2 oracle diff: full solver with the link removed
    base = oracle_view(me, two_area_world(me), ps)
    want = _oracle_view_without(me, ps, {frozenset(("b0", "b1"))})
    changed = {
        p for p in set(base) | set(want) if base.get(p) != want.get(p)
    }
    assert {c["prefix"] for c in f["changes"]} == changed


def test_multiarea_cross_area_pair_routes_to_generic_engine():
    """A pair whose links span areas (or are parallel) fails as a SET on
    the multi-area device kernel since r5 (per-snapshot multi-link
    masks); previously the query fell back to the generic scalar
    engine.  Whole-bundle semantics and oracle parity are pinned."""
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import DecisionConfig
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.decision import Decision
    from openr_tpu.messaging.queue import ReplicateQueue

    me = "b0"
    ps = make_prefixes()
    # give the a1-b0 pair a SECOND link by advertising it in area 2 too
    area_edges = {
        "1": AREA_EDGES["1"],
        "2": ring_edges(4, prefix="b") + [("a1", "b0", 9)],
    }
    als = {
        a: make_ls(e, a, me=me) for a, e in area_edges.items()
    }
    d = Decision(
        me,
        SimClock(),
        DecisionConfig(),
        ReplicateQueue(),
        backend=TpuBackend(SpfSolver(me)),
    )
    d.area_link_states = als
    d.prefix_state = ps
    d._change_seq = 9
    resp = d.get_link_failure_whatif([("a1", "b0")])
    assert resp is not None and resp["eligible"]
    assert resp["engine"] == "multiarea"
    (f,) = resp["failures"]
    assert f["links_failed"] == 2
    # oracle: remove the pair everywhere
    base = oracle_view(me, als, ps)
    mutated = {
        a: make_ls(
            [
                (n1, n2, m)
                for (n1, n2, m) in e
                if frozenset((n1, n2)) != frozenset(("a1", "b0"))
            ],
            a,
            me=me,
        )
        for a, e in area_edges.items()
    }
    want = oracle_view(me, mutated, ps)
    changed = {
        p for p in set(base) | set(want) if base.get(p) != want.get(p)
    }
    assert {c["prefix"] for c in f["changes"]} == changed


def test_ksp2_vantage_uses_device_build_engine():
    """KSP2_ED_ECMP vantages on a DEVICE deployment answer through the
    device-build what-if engine since r5 (full device builds minus the
    links — tables + device KSP2), not the scalar generic fallback; the
    diff must match the scalar KSP2 oracle exactly."""
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import DecisionConfig
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.decision import Decision
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.types import PrefixForwardingAlgorithm

    me = "b0"
    ps = PrefixState()
    ps.update_prefix(
        "b2",
        "2",
        PrefixEntry(
            "10.1.0.0/24",
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        ),
    )
    ps.update_prefix("a2", "1", PrefixEntry("10.2.0.0/24"))
    solver = SpfSolver(me)
    d = Decision(
        me,
        SimClock(),
        DecisionConfig(),
        ReplicateQueue(),
        backend=TpuBackend(solver),
        solver=solver,
    )
    d.area_link_states = two_area_world(me)
    d.prefix_state = ps
    d._change_seq = 7
    res = d.get_link_failure_whatif([("b0", "b1")])
    assert res is not None and res["eligible"]
    assert res["engine"] == "device-build"
    assert d.counters.get("decision.whatif.engine.device_build") == 1
    (f,) = res["failures"]
    # oracle: scalar full build with the link removed (KSP2 included)
    base = oracle_view(me, two_area_world(me), ps)
    want = _oracle_view_without(me, ps, {frozenset(("b0", "b1"))})
    changed = {
        p for p in set(base) | set(want) if base.get(p) != want.get(p)
    }
    assert {c["prefix"] for c in f["changes"]} == changed
    for c in f["changes"]:
        p = c["prefix"]
        if want.get(p):
            assert sorted(c["new_nexthops"]) == sorted(want[p][1]), c
            assert c["new_metric"] == want[p][0], c

    # simultaneous sets run on the same engine
    res2 = d.get_link_failure_whatif(
        [("b0", "b1"), ("a1", "b0")], simultaneous=True
    )
    assert res2["engine"] == "device-build"
    (f2,) = res2["failures"]
    want2 = _oracle_view_without(
        me, ps, {frozenset(("b0", "b1")), frozenset(("a1", "b0"))}
    )
    changed2 = {
        p
        for p in set(base) | set(want2)
        if base.get(p) != want2.get(p)
    }
    assert {c["prefix"] for c in f2["changes"]} == changed2
