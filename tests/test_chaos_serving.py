"""Chaos × serving: the query plane under faults.

Two acceptance properties (ISSUE 4):

* under a TPU outage (chaos ``tpu_fail``) the micro-batcher degrades to
  the scalar/native compute paths AND the bounded queue sheds instead of
  deadlocking when the bound is hit — every submitted future resolves
  (answer or shed error) in bounded virtual time;
* under partition/heal, results cached under generations from before the
  partition are NEVER served after it: the LSDB change bumps the
  generation, which both purges the cache eagerly and makes the old keys
  unmatchable.
"""

import asyncio

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import ring_edges
from openr_tpu.serving import ServingShedError

from tests.test_serving import build_decision, make_serving, norm_routes

pytestmark = [pytest.mark.chaos, pytest.mark.serving]

CONVERGE_S = 12.0


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        pending = asyncio.all_tasks(loop)
        for t in pending:
            t.cancel()
        if pending:
            loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True)
            )
        loop.close()


def test_tpu_outage_degrades_to_scalar_and_sheds_without_deadlock():
    """tpu_fail during a query storm: the batcher keeps answering on the
    scalar/native paths, the queue bound sheds the overflow, and nothing
    wedges — every future is resolved when virtual time stops."""

    async def main():
        clock = SimClock()
        d, edges = build_decision(clock)
        sv = make_serving(
            clock, d,
            max_queue_depth=4,
            max_batch=4,
            max_wait_ms=5,
            shed_policy="shed_oldest",
        )
        sv.start()
        # the chaos tpu_fail fault flips exactly this flag
        d.backend.inject_device_failure(True)
        assert not d.device_available()

        pairs = [(a, b) for a, b, _m in edges][:12]
        tasks = [
            asyncio.ensure_future(
                sv.submit("whatif", {"link_failures": [p]})
            )
            for p in pairs
        ]
        await clock.run_for(1.0)

        assert all(t.done() for t in tasks), "serving deadlocked"
        answered, shed = [], 0
        for t in tasks:
            exc = t.exception()
            if exc is None:
                answered.append(t.result())
            else:
                assert isinstance(exc, ServingShedError), exc
                shed += 1
        # the queue bound actually bit...
        assert shed >= 1 and sv.num_shed == shed
        # ...and everything that was answered came from a degraded
        # (non-device) engine
        assert answered, "at least the admitted window must be answered"
        for r in answered:
            assert r["eligible"]
            assert r["engine"] in ("native", "generic-solver"), r["engine"]
        assert sv.num_degraded >= 1
        assert d.counters.get("serving.degraded_batches") >= 1

        # outage heals: the device engine serves again (fresh queries —
        # the generation is unchanged, but these pairs were shed, so
        # they were never cached)
        d.backend.inject_device_failure(False)
        shed_pairs = [
            p for p, t in zip(pairs, tasks) if t.exception() is not None
        ]
        tasks2 = [
            asyncio.ensure_future(
                sv.submit("whatif", {"link_failures": [p]})
            )
            for p in shed_pairs[:4]
        ]
        await clock.run_for(1.0)
        for t in tasks2:
            assert t.result()["engine"] == "device"

    run(main())


def test_route_db_queries_survive_outage_via_scalar_fallback():
    """route_db queries during an outage answer through the per-vantage
    scalar solver (no fleet/device solve) and still return exact
    routes."""

    async def main():
        clock = SimClock()
        d, _edges = build_decision(clock)
        sv = make_serving(clock, d)
        sv.start()
        d.backend.inject_device_failure(True)
        t = asyncio.ensure_future(sv.submit("route_db", {"node": "node6"}))
        await clock.run_for(0.5)
        got = t.result()
        from openr_tpu.decision.spf_solver import SpfSolver

        oracle = (
            SpfSolver("node6")
            .build_route_db(d.area_link_states, d.prefix_state)
            .to_route_database("node6")
            .to_wire()
        )
        assert norm_routes(got) == norm_routes(oracle)
        # the fleet (device) engine was never built during the outage
        assert d._fleet_engine is None or (
            d._fleet_engine.num_batched_solves == 0
        )

    run(main())


def test_partition_heal_never_serves_pre_partition_generation():
    """EmulatedNetwork ring: a result cached before a partition must
    never be returned after it — the generation bump purges it and makes
    its key unmatchable; post-heal queries run against the healed
    generation."""

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(ring_edges(4))
        net.start()
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why

        n0 = net.nodes["node0"]
        sv = n0.serving

        async def query():
            task = asyncio.ensure_future(
                sv.submit("route_db", {"node": "node2"})
            )
            await clock.run_for(1.0)
            return task.result()

        gen_pre = n0.decision.generation_key()
        pre = await query()
        assert pre["unicast_routes"], "converged ring must route"
        assert len(sv.cache) == 1
        # cached: an immediate repeat is a hit, no new batch
        batches_before = sv.num_batches
        hit = await query()
        assert hit == pre and sv.num_batches == batches_before

        # partition node0 away; hold timers expire -> its LSDB changes
        net.partition(("node0",), ("node1", "node2", "node3"))
        await clock.run_for(8.0)
        gen_mid = n0.decision.generation_key()
        assert gen_mid != gen_pre, "partition must bump the generation"
        # the rebuild path purged the pre-partition entries eagerly
        assert n0.counters.get("serving.cache.generation_invalidations") > 0
        assert len(sv.cache) == 0

        mid = await query()
        assert mid != pre, (
            "post-partition answer must reflect the partitioned LSDB, "
            "not the pre-partition cache"
        )

        net.heal_partition(("node0",), ("node1", "node2", "node3"))
        await clock.run_for(25.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        gen_post = n0.decision.generation_key()
        assert gen_post not in (gen_pre, gen_mid)
        post = await query()
        # healed topology computes the same CONTENT as before the
        # partition, but through a fresh solve under the new generation
        # (never the old cache entry: its generation can no longer match)
        assert norm_routes(post) == norm_routes(pre)
        for (gen, _q) in list(sv.cache._entries):
            assert gen == gen_post

        # the whole-emulation serving view stayed healthy through the
        # partition: queries were answered, none shed
        stats = net.serving_stats()
        assert stats["node0"]["counters"]["serving.requests"] >= 4
        assert stats["node0"]["counters"].get("serving.shed", 0) == 0

        await net.stop()

    run(main())
