"""Tier-1 smoke: the checked-in BENCH_MULTICHIP_SERVING artifact obeys
the schema the bench emits (shared validator —
bench.validate_multichip_serving_bench) and holds the ISSUE-6
acceptance shape: serving-throughput rounds at 1/2/4/8 host devices
plus a 7-of-8 degraded round in which one chip is quarantined and the
serving plane KEEPS answering on the survivors
(`serving_stayed_available`, `device_failed` false).

The validator lives in bench.py so the emitter and this gate can never
drift apart; regenerate the artifact with
`python bench.py --multichip-serving`.
"""

import json
import pathlib

import pytest

import bench

pytestmark = [pytest.mark.serving, pytest.mark.multichip]

ARTIFACT = pathlib.Path(__file__).resolve().parent.parent / (
    "BENCH_MULTICHIP_SERVING_r01.json"
)


def test_artifact_exists_and_matches_schema():
    doc = json.loads(ARTIFACT.read_text())
    bench.validate_multichip_serving_bench(doc)


def test_degraded_round_kept_serving_on_survivors():
    doc = json.loads(ARTIFACT.read_text())
    deg = doc["detail"]["degraded_7of8"]
    assert deg["healthy_devices"] == 7
    assert deg["serving_stayed_available"] is True
    assert deg["device_failed"] is False
    # the 7-of-8 pool must not collapse to scalar-fallback throughput:
    # within 2x of the full-pool round (generous — virtual host devices
    # share physical cores, so this is a structural bound, not a perf
    # claim)
    r8 = next(r for r in doc["detail"]["rounds"] if r["devices"] == 8)
    assert deg["qps"] >= r8["qps"] / 2.0


def test_environment_triple_is_recorded():
    """The ISSUE-6 satellite: every BENCH artifact pins platform, jax
    version, and device count so perf points are comparable across
    environments."""
    doc = json.loads(ARTIFACT.read_text())
    env = doc["detail"]["env"]
    assert env["platform"]
    assert env["jax"]
    assert env["device_count"] >= 8


def test_validator_rejects_malformed_doc():
    doc = json.loads(ARTIFACT.read_text())
    doc["detail"]["degraded_7of8"]["serving_stayed_available"] = False
    with pytest.raises(AssertionError):
        bench.validate_multichip_serving_bench(doc)
