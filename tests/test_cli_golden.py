"""breeze golden-output fixture tests.

Reference parity: py/openr/cli/tests/<module>/{tests,fixtures}.py — click
CliRunner output compared against committed expected-output fixtures
(helpers.py:9-32).  Here each covered command runs against a real 2-node
emulated network over the TCP ctrl server; output is canonicalized
(volatile fields scrubbed, dict keys and list order sorted) and compared
byte-for-byte against tests/cli_fixtures/<name>.golden.

Regenerate after intentional output changes with:
    OPENR_TPU_REGEN_FIXTURES=1 python -m pytest tests/test_cli_golden.py
"""

import asyncio
import json
import os
import pathlib
import re
import threading

import pytest
from click.testing import CliRunner

from openr_tpu.cli.breeze import breeze
from openr_tpu.common.runtime import WallClock
from openr_tpu.ctrl.server import OpenrCtrlServer
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import line_edges
from openr_tpu.types import adj_key

FIXTURES = pathlib.Path(__file__).parent / "cli_fixtures"
REGEN = bool(os.environ.get("OPENR_TPU_REGEN_FIXTURES"))

#: JSON fields whose values vary run-to-run (clocks, sockets, caches)
VOLATILE_KEYS = {
    "ttl",
    "rtt",
    "rtt_us",
    "timestamp",
    "ts",
    "since",
    "hash",
    "version",
    "ttl_version",
    "perf_events",
    "metric_override",  # None vs absent varies with drain test ordering
    "metric",  # rtt-derived under the wall clock (use_rtt_metric)
    "igp_cost",
    "value",  # serialized adj/prefix blobs embed timestamps + rtt
    "generation",  # streaming emission stamps: change-seq dependent
    "seq",
}


def scrub(obj):
    """Zero volatile fields; sort dict keys and list elements so output
    is run-order independent."""
    if isinstance(obj, dict):
        return {
            k: (0 if k in VOLATILE_KEYS else scrub(v))
            for k, v in sorted(obj.items())
        }
    if isinstance(obj, list):
        return sorted(
            (scrub(v) for v in obj), key=lambda v: json.dumps(v, sort_keys=True)
        )
    return obj


def canonical(output: str) -> str:
    """Canonicalize command output: JSON gets scrubbed+redumped, tables
    get their numeric cells normalized."""
    text = output.strip()
    try:
        obj = json.loads(text)
    except ValueError:
        return re.sub(r"\b\d+\b", "N", text) + "\n"
    return json.dumps(scrub(obj), indent=2, sort_keys=True) + "\n"


def _live_node_fixture(num_nodes: int, use_tpu_backend: bool, ready,
                       edges_fn=None):
    """One background-loop node lifecycle; fixtures below parameterize
    topology size, backend, and the readiness predicate."""
    started = threading.Event()
    stop = None
    result = {}

    def runner():
        nonlocal stop
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        result["loop"] = loop
        stop = asyncio.Event()

        async def main():
            clock = WallClock()
            net = EmulatedNetwork(clock, use_tpu_backend=use_tpu_backend)
            net.build(
                edges_fn() if edges_fn is not None else line_edges(num_nodes)
            )
            net.start()
            server = OpenrCtrlServer(net.nodes["node0"], port=0)
            await server.start()
            result["port"] = server.port
            for _ in range(200):
                if ready(net):
                    break
                await asyncio.sleep(0.1)
            started.set()
            await stop.wait()
            await server.stop()
            await net.stop()

        loop.run_until_complete(main())
        loop.close()

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    assert started.wait(timeout=60), "live node failed to start"
    yield result["port"]
    result["loop"].call_soon_threadsafe(stop.set)
    t.join(timeout=30)


@pytest.fixture(scope="module")
def live_node():
    """2-node wall-clock network + ctrl server on a background loop."""
    yield from _live_node_fixture(
        2,
        False,
        lambda net: adj_key("node1")
        in net.nodes["node0"].kv_store.dump_all("0")
        and net.nodes["node0"].fib.get_route_db(),
    )


@pytest.fixture(scope="module")
def live_tpu_node():
    """3-node line with the TPU decision backend — serves the device
    features (fleet-summary, whatif) the scalar fixture can't."""
    yield from _live_node_fixture(
        3, True, lambda net: len(net.nodes["node0"].fib.get_route_db()) >= 2
    )


@pytest.fixture(scope="module")
def live_fleet_node():
    """9-node grid with the TPU decision backend — the fleet the
    `breeze health` goldens render a rollup of (ISSUE 8 acceptance:
    fleet rollup against a live 9-node emulation)."""
    from openr_tpu.emulation.topology import grid_edges

    yield from _live_node_fixture(
        9,
        True,
        lambda net: len(net.nodes["node0"].fib.get_route_db()) >= 8,
        edges_fn=lambda: grid_edges(3),
    )


def check_golden(name: str, port: int, *args: str) -> None:
    r = CliRunner().invoke(breeze, ["--port", str(port), *args], obj={})
    assert r.exit_code == 0, r.output
    got = canonical(r.output)
    path = FIXTURES / f"{name}.golden"
    if REGEN:
        FIXTURES.mkdir(exist_ok=True)
        path.write_text(got)
        return
    # a missing fixture is a FAILURE, not an auto-bless: silently writing
    # it here would make every first run (and any deleted/renamed/
    # forgotten fixture) vacuously pass while asserting nothing
    assert path.exists(), (
        f"no golden fixture {path}; generate it deliberately with "
        "OPENR_TPU_REGEN_FIXTURES=1 and commit it"
    )
    want = path.read_text()
    assert got == want, (
        f"golden mismatch for {name} ({' '.join(args)}):\n"
        f"--- expected ---\n{want}\n--- got ---\n{got}\n"
        "(regenerate with OPENR_TPU_REGEN_FIXTURES=1 if intentional)"
    )


# one golden per command group (reference: per-module fixtures.py)

def test_golden_openr_version(live_node):
    check_golden("openr_version", live_node, "openr", "version")


def test_golden_lm_links(live_node):
    check_golden("lm_links", live_node, "lm", "links")


def test_golden_lm_drain_state(live_node):
    check_golden("lm_drain_state", live_node, "lm", "drain-state")


def test_golden_decision_routes(live_node):
    check_golden("decision_routes", live_node, "decision", "routes")


def test_golden_decision_route_detail(live_node):
    check_golden(
        "decision_route_detail", live_node, "decision", "route-detail"
    )


def test_golden_decision_adj_filtered(live_node):
    check_golden(
        "decision_adj_filtered",
        live_node,
        "decision",
        "adj-filtered",
        "--node",
        "node1",
    )


def test_golden_fib_routes(live_node):
    check_golden("fib_routes", live_node, "fib", "routes")


def test_golden_fib_mpls(live_node):
    check_golden("fib_mpls", live_node, "fib", "mpls")


def test_golden_kvstore_keys(live_node):
    check_golden("kvstore_keys", live_node, "kvstore", "keys")


def test_golden_kvstore_hashes(live_node):
    check_golden(
        "kvstore_hashes", live_node, "kvstore", "hashes", "--prefix", "adj:"
    )


def test_golden_kvstore_keyvals_filtered(live_node):
    check_golden(
        "kvstore_keyvals_filtered",
        live_node,
        "kvstore",
        "keyvals-filtered",
        "--prefix",
        "adj:",
        "--originator",
        "node1",
    )


def test_golden_dispatcher_filters(live_node):
    check_golden("dispatcher_filters", live_node, "dispatcher", "filters")


def test_golden_spark_neighbors(live_node):
    check_golden("spark_neighbors", live_node, "spark", "neighbors")


def test_golden_prefixmgr_area_view(live_node):
    check_golden(
        "prefixmgr_area_view", live_node, "prefixmgr", "area-view", "0"
    )


def test_golden_received_routes_filtered(live_node):
    check_golden(
        "received_routes_filtered",
        live_node,
        "decision",
        "received-routes-filtered",
        "--originator",
        "node1",
    )


def test_golden_serving_watch(live_node):
    """`breeze serving watch NODE --deltas 0`: the generation-stamped
    snapshot emission (ISSUE 13 — the watch plane's CLI surface)."""
    check_golden(
        "serving_watch", live_node, "serving", "watch", "node1",
        "--deltas", "0",
    )


def test_golden_fleet_summary(live_tpu_node):
    check_golden(
        "fleet_summary", live_tpu_node, "decision", "fleet-summary"
    )


def test_golden_whatif(live_tpu_node):
    """Failing the node1-node2 link from node0's vantage removes the
    route to node2's loopback (no alternative path on a line)."""
    check_golden(
        "decision_whatif",
        live_tpu_node,
        "decision",
        "whatif",
        "node1,node2",
    )


def test_config_store_cycle(live_node):
    """Stateful cycle (not golden: mutates) — set/get/erase round trip."""
    port = live_node

    def run(*args):
        r = CliRunner().invoke(breeze, ["--port", str(port), *args], obj={})
        assert r.exit_code == 0, r.output
        return r.output

    run("config-store", "set", "golden:test", "hello")
    assert json.loads(run("config-store", "get", "golden:test")) == "hello"
    keys = json.loads(run("config-store", "keys"))
    assert "golden:test" in keys
    assert "erased" in run("config-store", "erase", "golden:test")
    r = CliRunner().invoke(
        breeze,
        ["--port", str(port), "config-store", "get", "golden:test"],
        obj={},
    )
    assert r.exit_code != 0  # KeyError surfaces as RPC error


def test_golden_decision_path(live_tpu_node):
    check_golden(
        "decision_path",
        live_tpu_node,
        "decision",
        "path",
        "--src",
        "node0",
        "--dst",
        "node2",
    )


def test_golden_config_show_typed(live_node):
    check_golden("config_show_typed", live_node, "config", "show-typed")


def test_golden_config_dryrun(live_node, tmp_path):
    cfg = tmp_path / "candidate.conf"
    cfg.write_text('{"node_name": "nodeX", "domain": "lab"}')
    check_golden("config_dryrun", live_node, "config", "dryrun", str(cfg))


def test_init_duration(live_node):
    """The duration itself varies run to run; assert the command
    succeeds after convergence and returns a sane millisecond count."""
    r = CliRunner().invoke(
        breeze,
        ["--port", str(live_node), "openr", "init-duration"],
        obj={},
    )
    assert r.exit_code == 0, r.output
    assert 0 <= int(r.output.strip()) < 3_600_000


def test_golden_kvstore_keys_json(live_node):
    check_golden(
        "kvstore_keys_json",
        live_node,
        "kvstore",
        "keys",
        "--json",
        "--prefix",
        "adj:",
    )


def test_golden_kvstore_areas(live_node):
    check_golden("kvstore_areas", live_node, "kvstore", "areas")


def test_golden_kvstore_validate(live_node):
    check_golden("kvstore_validate", live_node, "kvstore", "validate")


def test_kvstore_signature_and_compare(live_node):
    """Signature is stable for identical content; kv-compare against
    OURSELVES must report a match (both stores trivially identical)."""
    r1 = CliRunner().invoke(
        breeze, ["--port", str(live_node), "kvstore", "kv-signature"], obj={}
    )
    r2 = CliRunner().invoke(
        breeze, ["--port", str(live_node), "kvstore", "kv-signature"], obj={}
    )
    assert r1.exit_code == 0 and r2.exit_code == 0
    assert r1.output == r2.output and len(r1.output.strip()) == 64
    rc = CliRunner().invoke(
        breeze,
        [
            "--port",
            str(live_node),
            "kvstore",
            "kv-compare",
            "--peer",
            f"127.0.0.1:{live_node}",
        ],
        obj={},
    )
    assert rc.exit_code == 0, rc.output
    assert rc.output.strip().endswith("stores match")


def test_kvstore_keys_originator_filter(live_node):
    r = CliRunner().invoke(
        breeze,
        [
            "--port",
            str(live_node),
            "kvstore",
            "keys",
            "--json",
            "--originator",
            "node1",
        ],
        obj={},
    )
    assert r.exit_code == 0, r.output
    data = json.loads(r.output)
    assert data and all(
        v["originator_id"] == "node1" for v in data.values()
    )


def test_golden_lm_validate(live_node):
    check_golden("lm_validate", live_node, "lm", "validate")


def test_golden_spark_validate(live_node):
    check_golden("spark_validate", live_node, "spark", "validate")


def test_golden_decision_partial_adj(live_node):
    check_golden(
        "decision_partial_adj", live_node, "decision", "partial-adj"
    )


def test_golden_kvstore_prefixes(live_node):
    check_golden("kvstore_prefixes", live_node, "kvstore", "prefixes")


def test_golden_kvstore_nodes(live_node):
    check_golden("kvstore_nodes", live_node, "kvstore", "nodes")


def test_golden_decision_validate(live_node):
    check_golden("decision_validate", live_node, "decision", "validate")


def test_golden_fib_validate(live_node):
    check_golden("fib_validate", live_node, "fib", "validate")


def test_golden_prefixmgr_validate(live_node):
    check_golden(
        "prefixmgr_validate", live_node, "prefixmgr", "validate"
    )


def test_golden_openr_summary(live_node):
    check_golden("openr_summary", live_node, "openr", "summary")


# round-4 option-depth commands


def test_golden_openr_validate(live_node):
    check_golden("openr_validate", live_node, "openr", "validate")


def test_golden_openr_validate_json(live_node):
    check_golden(
        "openr_validate_json", live_node, "openr", "validate", "--json"
    )


def test_golden_decision_adj_json(live_node):
    check_golden(
        "decision_adj_json", live_node, "decision", "adj", "--json"
    )


def test_golden_decision_routes_all(live_node):
    check_golden(
        "decision_routes_all", live_node, "decision", "routes", "--nodes",
        "all",
    )


def test_golden_spark_neighbors_detail(live_node):
    check_golden(
        "spark_neighbors_detail", live_node, "spark", "neighbors",
        "--detail",
    )


def test_golden_config_prefix_manager(live_node):
    check_golden(
        "config_prefix_manager", live_node, "config", "prefix-manager"
    )


def test_golden_whatif_node(live_tpu_node):
    """node1 failing entirely partitions node0 from node1 AND node2 on
    a line — both loopbacks withdraw (the drain-simulation question)."""
    check_golden(
        "decision_whatif_node",
        live_tpu_node,
        "decision",
        "whatif-node",
        "node1",
    )


# ISSUE 8: fleet health plane goldens against the live 9-node grid


def test_golden_health_status(live_fleet_node):
    """The fleet rollup: all 9 nodes' generation rows, SLO burn lines,
    chip/breaker/queue state, zero active alerts on a healthy fleet."""
    check_golden("health_status", live_fleet_node, "health", "status")


def test_golden_health_alerts(live_fleet_node):
    check_golden("health_alerts", live_fleet_node, "health", "alerts")


def test_golden_health_slo(live_fleet_node):
    check_golden("health_slo", live_fleet_node, "health", "slo")


# ISSUE 10: the benchtrack trajectory render (numbers canonicalized, so
# the golden pins the SHAPE: families, ratcheted metrics, round trail,
# check verdict — not the values, which move with artifact rounds)


def test_golden_monitor_trajectory(live_node):
    check_golden(
        "monitor_trajectory", live_node, "monitor", "trajectory"
    )


# ISSUE 19: the fleet sweep's per-node assignment rows in `breeze sweep
# status` (numbers canonicalized; the golden pins the block SHAPE —
# header line + one row per (node, round) assignment).  The status
# payload is frozen (tests/test_cli.py's FLEET_SWEEP_STATUS): the
# coordinator itself is proven in tests/test_fleet_fabric.py.


@pytest.fixture(scope="module")
def live_fleet_sweep_node():
    from tests.test_cli import FLEET_SWEEP_STATUS

    def ready(net):
        net.nodes["node0"].sweep.attach_fleet(
            lambda: dict(FLEET_SWEEP_STATUS)
        )
        return adj_key("node1") in net.nodes["node0"].kv_store.dump_all(
            "0"
        )

    yield from _live_node_fixture(2, False, ready)


def test_golden_sweep_status_fleet(live_fleet_sweep_node):
    check_golden(
        "sweep_status_fleet", live_fleet_sweep_node, "sweep", "status"
    )
