"""Warm-start generation-delta rebuild (ISSUE 9 tentpole): the host
classifier (plan_generation_delta), the encode patch, the warm device
kernels, the selective-selection patch path, purge semantics, and the
content-hash RepairPlan cache.

The load-bearing property throughout: a warm rebuild's RouteDb is
BIT-IDENTICAL to both the cold device build and the scalar oracle, for
every generation of a seeded churn sweep — the warm start is an
optimization, never an approximation."""

import numpy as np
import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import ParallelConfig, ResilienceConfig
from openr_tpu.decision.backend import TpuBackend
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
from openr_tpu.ops.csr import encode_link_state, patch_encoded_topology
from openr_tpu.ops.repair import (
    build_repair_plan_cached,
    plan_cache_stats,
    plan_generation_delta,
    topology_content_hash,
)
from openr_tpu.types import PrefixEntry


def make_world(side=4, seed_prefix="10.7"):
    edges = grid_edges(side)
    adj = build_adj_dbs(edges)
    ls = LinkState("0", "node0")
    for db in adj.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(side * side):
        ps.update_prefix(
            f"node{i}", "0", PrefixEntry(f"{seed_prefix}.{i}.0/24")
        )
    return adj, ls, ps


def make_backend(warm=True, parallel=None, **res_kw):
    resilience = (
        ResilienceConfig(**res_kw) if res_kw else ResilienceConfig(enabled=False)
    )
    return TpuBackend(
        SpfSolver("node0"),
        clock=SimClock(),
        resilience=resilience,
        parallel=parallel,
        warm_rebuild=warm,
    )


def norm_db(db):
    return {
        p: (
            sorted((nh.neighbor_node_name, nh.metric) for nh in e.nexthops),
            float(e.igp_cost),
        )
        for p, e in db.unicast_routes.items()
    }


def perturb_metric(adj, ls, rng):
    victim = sorted(adj)[int(rng.integers(len(adj)))]
    db = adj[victim]
    a = db.adjacencies[int(rng.integers(len(db.adjacencies)))]
    a.metric = 1 + (a.metric % 3)
    ls.update_adjacency_database(db)


# ---------------------------------------------------------------------------
# host classifier + encode patch units
# ---------------------------------------------------------------------------


def test_plan_generation_delta_metric_perturbation():
    adj, ls, _ps = make_world()
    old_topo = encode_link_state(ls)
    root = old_topo.node_id("node0")
    from openr_tpu.ops.native_spf import NativeSpf

    native = NativeSpf(old_topo, "node0")
    dist, _ = native.solve(failed_link=-1)
    from openr_tpu.ops.consts import BIG

    dist = np.where(np.isfinite(dist), dist, np.float32(BIG)).astype(
        np.float32
    )
    # weaken one on-DAG link: the head's descendants (and only a
    # bounded set) reset
    db = adj["node0"]
    db.adjacencies[0].metric = 9
    ls.update_adjacency_database(db)
    new_topo = encode_link_state(ls)
    delta = plan_generation_delta(old_topo, root, dist, new_topo)
    assert delta is not None
    assert delta.num_perturbed_edges >= 1
    assert 0 < delta.num_reset < old_topo.padded_nodes
    assert delta.est_depth >= 1
    assert not delta.reset[root]


def test_plan_generation_delta_structural_is_none():
    adj, ls, _ps = make_world()
    old_topo = encode_link_state(ls)
    root = old_topo.node_id("node0")
    dist = np.zeros(old_topo.padded_nodes, np.float32)
    ls.delete_adjacency_database("node15")
    new_topo = encode_link_state(ls)
    assert plan_generation_delta(old_topo, root, dist, new_topo) is None


def test_patch_encoded_topology_matches_full_encode():
    adj, ls, _ps = make_world()
    old = encode_link_state(ls)
    db = adj["node5"]
    for a in db.adjacencies:
        a.metric = 4
    ls.update_adjacency_database(db)
    patched = patch_encoded_topology(old, ls)
    full = encode_link_state(ls)
    assert patched is not None
    # layout arrays are SHARED with the previous encoding
    assert patched.src is old.src and patched.link_index is old.link_index
    for field in ("src", "dst", "w", "edge_ok", "overloaded", "soft"):
        assert np.array_equal(
            getattr(patched, field), getattr(full, field)
        ), field
    # structural churn declines
    ls.delete_adjacency_database("node15")
    assert patch_encoded_topology(old, ls) is None


def test_topology_content_hash_tracks_graph_not_churn():
    _adj, ls, _ps = make_world()
    t1 = encode_link_state(ls)
    t2 = encode_link_state(ls)  # distinct object, same content
    assert topology_content_hash(t1) == topology_content_hash(t2)
    assert topology_content_hash(t1, 0) != topology_content_hash(t1, 1)


def test_repair_plan_cache_content_addressed():
    adj, ls, _ps = make_world()
    # make this test's graph content-unique: the memo is module-global
    # and other tests encode the same canonical 4x4 world
    db0 = adj["node10"]
    db0.adjacencies[0].metric = 1777
    ls.update_adjacency_database(db0)
    topo_a = encode_link_state(ls)
    root = topo_a.node_id("node0")
    from openr_tpu.ops.native_spf import NativeSpf
    from openr_tpu.ops.consts import BIG

    native = NativeSpf(topo_a, "node0")
    dist, _ = native.solve(failed_link=-1)
    dist = np.where(np.isfinite(dist), dist, np.float32(BIG)).astype(
        np.float32
    )
    from openr_tpu.ops.whatif import root_lane_count

    D = root_lane_count(topo_a, root)
    nh = native.lanes_dense(D)
    h0, m0 = plan_cache_stats()
    p1 = build_repair_plan_cached(topo_a, root, dist, nh)
    # a re-encode of the UNCHANGED graph (what every Decision change
    # generation does on prefix churn) must hit, returning the same plan
    topo_b = encode_link_state(ls)
    p2 = build_repair_plan_cached(topo_b, root, dist, nh)
    h1, m1 = plan_cache_stats()
    assert p2 is p1
    assert h1 == h0 + 1 and m1 == m0 + 1
    # a real graph change misses
    db = adj["node1"]
    db.adjacencies[0].metric = 7
    ls.update_adjacency_database(db)
    topo_c = encode_link_state(ls)
    native_c = NativeSpf(topo_c, "node0")
    dist_c, _ = native_c.solve(failed_link=-1)
    dist_c = np.where(
        np.isfinite(dist_c), dist_c, np.float32(BIG)
    ).astype(np.float32)
    p3 = build_repair_plan_cached(
        topo_c, root, dist_c, native_c.lanes_dense(D)
    )
    assert p3 is not p1
    _, m2 = plan_cache_stats()
    assert m2 == m1 + 1


# ---------------------------------------------------------------------------
# warm/cold/scalar parity across a seeded churn sweep
# ---------------------------------------------------------------------------


def test_warm_cold_scalar_parity_across_generations():
    adj, ls, ps = make_world()
    als = {"0": ls}
    warm = make_backend(warm=True)
    cold = make_backend(warm=False)
    warm.build_route_db(als, ps, force_full=True)
    cold.build_route_db(als, ps, force_full=True)
    rng = np.random.default_rng(11)
    prev_db = None
    for gen in range(8):
        kind = gen % 4
        if kind == 3:
            # overload flip rides the same warm classification as a
            # link perturbation (transit-enabled edges leave/enter)
            victim = sorted(adj)[int(rng.integers(len(adj)))]
            db = adj[victim]
            db.is_overloaded = not db.is_overloaded
            ls.update_adjacency_database(db)
        else:
            perturb_metric(adj, ls, rng)
        db_w = warm.build_route_db(
            als, ps, changed_prefixes=set(), force_full=True,
            warm_delta=True,
        )
        db_c = cold.build_route_db(
            als, ps, changed_prefixes=set(), force_full=True
        )
        db_s = SpfSolver("node0").build_route_db(als, ps)
        assert norm_db(db_w) == norm_db(db_c) == norm_db(db_s), f"gen {gen}"
        changed = warm.take_last_changed_prefixes()
        if changed is not None and prev_db is not None:
            # the selective patch path's changed-set guarantee: every
            # prefix OUTSIDE it is object-identical to the previous db
            # (the O(changed) publication diff depends on this)
            for p, e in db_w.unicast_routes.items():
                if p not in changed:
                    assert prev_db.unicast_routes[p] is e, (gen, p)
        prev_db = db_w
    assert warm.num_warm_builds == 8
    assert warm.num_warm_selective_builds == 8
    assert warm.num_warm_cold_fallbacks == 0
    snap = warm.counter_snapshot()
    assert snap["decision.backend.warm_hit_ratio"] == 1.0
    assert snap["decision.backend.warm_context_ready"] == 1.0


def test_warm_parity_with_prefix_churn_on_same_tick():
    adj, ls, ps = make_world()
    als = {"0": ls}
    warm = make_backend(warm=True)
    warm.build_route_db(als, ps, force_full=True)
    rng = np.random.default_rng(3)
    perturb_metric(adj, ls, rng)
    churn = "10.99.7.0/24"
    ps.update_prefix("node9", "0", PrefixEntry(churn))
    db_w = warm.build_route_db(
        als, ps, changed_prefixes={churn}, force_full=True, warm_delta=True
    )
    assert norm_db(db_w) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    changed = warm.take_last_changed_prefixes()
    assert changed is not None and churn in changed
    # a prefix withdrawal coinciding with a perturbation patches too
    perturb_metric(adj, ls, rng)
    ps.delete_prefix("node9", "0", churn)
    db_w = warm.build_route_db(
        als, ps, changed_prefixes={churn}, force_full=True, warm_delta=True
    )
    assert churn not in db_w.unicast_routes
    assert norm_db(db_w) == norm_db(SpfSolver("node0").build_route_db(als, ps))


def test_structural_delta_unhinted_stays_cold_with_parity():
    adj, ls, ps = make_world()
    als = {"0": ls}
    warm = make_backend(warm=True)
    warm.build_route_db(als, ps, force_full=True)
    # node removal WITHOUT a delta hint (a static-route change
    # coinciding with the churn, say): the build stays cold — and the
    # slot-patched encoding it runs on must still match the oracle
    ls.delete_adjacency_database("node15")
    db_w = warm.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True, warm_delta=False
    )
    assert norm_db(db_w) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    assert warm.num_warm_builds == 0
    assert warm.num_warm_cold_fallbacks >= 1
    # ISSUE 12: membership churn with a delta hint (even the legacy
    # warm_delta spelling) now WARMS through the slot-stable encode —
    # the backend's own classifier proves layout identity and seeds
    # the tombstoned region, and the result stays bit-parity
    ls.delete_adjacency_database("node14")
    db_w = warm.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True, warm_delta=True
    )
    assert norm_db(db_w) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    assert warm.num_warm_builds == 1
    assert warm.num_encode_slot_patches >= 1


def test_structural_delta_hint_warms_and_splits_counters():
    adj, ls, ps = make_world()
    als = {"0": ls}
    warm = make_backend(warm=True)
    warm.build_route_db(als, ps, force_full=True)
    # leave: Decision classifies structural → the slot patch tombstones
    # the node in place and the warm solve repairs only its region
    ls.delete_adjacency_database("node15")
    db_w = warm.build_route_db(
        als,
        ps,
        changed_prefixes=set(),
        force_full=True,
        structural_delta=True,
    )
    assert norm_db(db_w) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    assert warm._warm_class_builds["structural"] == 1
    assert warm._warm_class_builds["perturbation"] == 0
    # rejoin: the same node re-advertises identical adjacencies — its
    # slot and rows revive, improvements relax from the over-estimate
    ls.update_adjacency_database(adj["node15"])
    for n in ("node11", "node14"):
        ls.update_adjacency_database(adj[n])
    db_w = warm.build_route_db(
        als,
        ps,
        changed_prefixes=set(),
        force_full=True,
        structural_delta=True,
    )
    assert norm_db(db_w) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    assert warm._warm_class_builds["structural"] == 2
    assert warm.num_warm_cold_fallbacks == 0
    snap = warm.counter_snapshot()
    assert snap["decision.backend.warm_hit_ratio.structural"] == 1.0
    assert snap["decision.backend.warm_encode_slot_patches"] >= 2


# ---------------------------------------------------------------------------
# purge semantics
# ---------------------------------------------------------------------------


def test_corruption_injection_purges_warm_context():
    adj, ls, ps = make_world()
    als = {"0": ls}
    warm = make_backend(warm=True)
    warm.build_route_db(als, ps, force_full=True)
    assert warm._warm_ctx is not None
    warm.inject_silent_corruption(True)
    assert warm._warm_ctx is None
    assert warm.num_warm_purges == 1
    assert warm._warm_purge_reasons.get("tpu_corrupt") == 1
    warm.inject_silent_corruption(False)
    # device-scoped injection purges too
    warm.build_route_db(als, ps, force_full=True)
    assert warm._warm_ctx is not None
    warm.inject_silent_corruption(True, device_index=2)
    assert warm._warm_ctx is None
    assert warm.num_warm_purges == 2


def test_purged_context_rebuilds_cold_then_warms_again():
    adj, ls, ps = make_world()
    als = {"0": ls}
    warm = make_backend(warm=True)
    warm.build_route_db(als, ps, force_full=True)
    warm.inject_silent_corruption(True)
    warm.inject_silent_corruption(False)
    rng = np.random.default_rng(2)
    perturb_metric(adj, ls, rng)
    db = warm.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True, warm_delta=True
    )
    assert norm_db(db) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    # the purged context forced this build cold...
    assert warm.num_warm_builds == 0
    assert warm._warm_fallback_reasons.get("no_context") == 1
    # ...and re-established the context: the NEXT perturbation warms
    perturb_metric(adj, ls, rng)
    db = warm.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True, warm_delta=True
    )
    assert norm_db(db) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    assert warm.num_warm_builds == 1


def test_purge_requests_shadow_verification():
    adj, ls, ps = make_world()
    als = {"0": ls}
    backend = TpuBackend(
        SpfSolver("node0"),
        clock=SimClock(),
        resilience=ResilienceConfig(
            shadow_sample_every=1000, jitter_pct=0.0
        ),
        warm_rebuild=True,
    )
    gov = backend.governor
    backend.build_route_db(als, ps, force_full=True)  # first build verified
    checks = gov.num_shadow_checks
    backend.build_route_db(als, ps, force_full=True)
    assert gov.num_shadow_checks == checks  # sampling interval is huge
    backend.inject_silent_corruption(True)
    backend.inject_silent_corruption(False)
    backend.build_route_db(als, ps, force_full=True)
    # the purge made the next device build verification-due
    assert gov.num_shadow_checks == checks + 1


# ---------------------------------------------------------------------------
# multichip: quarantine re-pack purges; warm sweep survives mid-sweep
# quarantine with parity
# ---------------------------------------------------------------------------


@pytest.mark.multichip
def test_warm_sweep_with_midsweep_chip_quarantine_and_repack():
    adj, ls, ps = make_world(side=4)
    als = {"0": ls}
    warm = TpuBackend(
        SpfSolver("node0"),
        clock=SimClock(),
        resilience=ResilienceConfig(jitter_pct=0.0),
        parallel=ParallelConfig(min_shard_rows=0),
        warm_rebuild=True,
    )
    assert warm.pool.size > 1
    warm.build_route_db(als, ps, force_full=True)
    rng = np.random.default_rng(17)
    gov = warm.governor
    for gen in range(6):
        perturb_metric(adj, ls, rng)
        if gen == 3:
            # mid-sweep chip quarantine: the health transition purges
            # the warm context (re-pack makes per-chip residency
            # suspect) and the shard plan re-packs onto survivors
            gov.force_quarantine_device(2, reason="test")
            assert warm._warm_ctx is None
        db_w = warm.build_route_db(
            als, ps, changed_prefixes=set(), force_full=True,
            warm_delta=True,
        )
        db_s = SpfSolver("node0").build_route_db(als, ps)
        assert norm_db(db_w) == norm_db(db_s), f"gen {gen}"
    # warm before the quarantine, cold on the purge tick, warm after
    assert warm.num_warm_builds >= 3
    assert warm._warm_purge_reasons.get("quarantine", 0) >= 1
    assert not warm.pool.is_healthy(2)
    # the replica cache dropped the quarantined chip's residency
    assert 2 not in warm._spf_replicas


# ---------------------------------------------------------------------------
# ksp2 / mpls guards on the selective path
# ---------------------------------------------------------------------------


def test_node_segment_labels_disable_selective_patch_not_warm_tables():
    adj, ls, ps = make_world()
    als = {"0": ls}
    backend = TpuBackend(
        SpfSolver("node0", enable_node_segment_label=True),
        clock=SimClock(),
        resilience=ResilienceConfig(enabled=False),
        warm_rebuild=True,
    )
    backend.build_route_db(als, ps, force_full=True)
    rng = np.random.default_rng(4)
    perturb_metric(adj, ls, rng)
    db_w = backend.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True, warm_delta=True
    )
    # warm SPF tables were used, but the patch path declined (labels
    # must recompute on topology change), so no changed-set guarantee
    assert backend.num_warm_builds == 1
    assert backend.num_warm_selective_builds == 0
    assert backend.take_last_changed_prefixes() is None
    ref = SpfSolver(
        "node0", enable_node_segment_label=True
    ).build_route_db(als, ps)
    assert norm_db(db_w) == norm_db(ref)
    assert {
        k: sorted(str(n) for n in v.nexthops)
        for k, v in db_w.mpls_routes.items()
    } == {
        k: sorted(str(n) for n in v.nexthops)
        for k, v in ref.mpls_routes.items()
    }
