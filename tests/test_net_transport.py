"""Real network-plane tests: KvStore anti-entropy sync over TCP
(TcpKvStoreTransport -> peer ctrl servers) and the UDP multicast
IoProvider.

Reference parity: KvStore peer sessions are thrift clients of the peer's
ctrl service (kvstore/KvStore.h:460-466; multi-store thrift tests in
kvstore/tests/KvStoreThriftTest.cpp); Spark's wire is IPv6 link-local UDP
multicast via IoProvider (spark/IoProvider.cpp:43-88).
"""

import asyncio
import socket as pysocket
import types as pytypes

import pytest

from openr_tpu.common.runtime import WallClock
from openr_tpu.config import KvStoreConfig
from openr_tpu.ctrl.server import OpenrCtrlServer
from openr_tpu.kvstore.kv_store import KvStore
from openr_tpu.kvstore.transport import TcpKvStoreTransport
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import PeerSpec, Value


def make_store(name: str) -> KvStore:
    return KvStore(
        node_name=name,
        clock=WallClock(),
        config=KvStoreConfig(),
        areas=["0"],
        transport=TcpKvStoreTransport(),
        publications_queue=ReplicateQueue(f"{name}.pubs"),
    )


async def serve_store(store: KvStore) -> OpenrCtrlServer:
    node_stub = pytypes.SimpleNamespace(kv_store=store)
    server = OpenrCtrlServer(node_stub, port=0)
    await server.start()
    return server


class TestTcpKvStoreTransport:
    def test_two_stores_full_sync_and_flood(self):
        async def run():
            a, b = make_store("a"), make_store("b")
            a.start()
            b.start()
            sa, sb = await serve_store(a), await serve_store(b)
            try:
                # seed a with a key, then peer them up over TCP
                a.areas["0"].persist_self_originated_key("prefix:a", b"va")
                a.areas["0"].add_peers(
                    {"b": PeerSpec(peer_addr="127.0.0.1", ctrl_port=sb.port)}
                )
                b.areas["0"].add_peers(
                    {"a": PeerSpec(peer_addr="127.0.0.1", ctrl_port=sa.port)}
                )
                for _ in range(100):
                    await asyncio.sleep(0.05)
                    if "prefix:a" in b.areas["0"].key_vals:
                        break
                assert "prefix:a" in b.areas["0"].key_vals

                # now flood: a new key on b must reach a via setKeyVals RPC
                b.areas["0"].persist_self_originated_key("prefix:b", b"vb")
                for _ in range(100):
                    await asyncio.sleep(0.05)
                    if "prefix:b" in a.areas["0"].key_vals:
                        break
                assert "prefix:b" in a.areas["0"].key_vals
            finally:
                await a.stop()
                await b.stop()
                await a.transport.close()
                await b.transport.close()
                await sa.stop()
                await sb.stop()

        asyncio.run(run())


def _link_local_iface() -> str:
    """First interface with an fe80:: address (v6 multicast needs one)."""
    try:
        with open("/proc/net/if_inet6") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 6 and parts[3] == "20":  # link-local scope
                    return parts[5]
    except OSError:
        pass
    return ""


_IFACE = _link_local_iface()


@pytest.mark.skipif(not _IFACE, reason="no v6 link-local interface")
class TestUdpIoProvider:
    def test_same_host_multicast_delivery(self):
        from openr_tpu.spark.io_provider import UdpIoProvider

        async def run():
            recv_b = []

            async def cb_a(if_name, payload, ts):
                pass

            async def cb_b(if_name, payload, ts):
                recv_b.append((if_name, payload))

            pa, pb = UdpIoProvider(port=26626), UdpIoProvider(port=26626)
            pa.register("na", cb_a)
            pb.register("nb", cb_b)
            try:
                pa.add_interface(_IFACE)
                pb.add_interface(_IFACE)
                # both providers are on one host here, so the sender must
                # loop its multicast back for the peer socket to see it
                sock, _ = pa._socks[_IFACE]
                sock.setsockopt(
                    pysocket.IPPROTO_IPV6, pysocket.IPV6_MULTICAST_LOOP, 1
                )
                for attempt in range(40):
                    pa.send("na", _IFACE, {"hello": "spark", "seq": attempt})
                    await asyncio.sleep(0.05)
                    if recv_b:
                        break
                assert recv_b, f"no multicast delivery on {_IFACE}"
                if_name, payload = recv_b[0]
                assert if_name == _IFACE
                assert payload["hello"] == "spark"
            finally:
                pa.unregister("na")
                pb.unregister("nb")

        asyncio.run(run())
