"""Queue semantics tests (reference behavior: openr/messaging/tests)."""

import asyncio

import pytest

from openr_tpu.messaging.queue import QueueClosedError, ReplicateQueue, RWQueue


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_rwqueue_fifo_and_stats():
    async def main():
        q = RWQueue("q")
        q.push(1)
        q.push(2)
        assert q.size() == 2
        assert await q.get() == 1
        assert await q.get() == 2
        assert q.num_writes == 2 and q.num_reads == 2

    run(main())


def test_rwqueue_blocking_get_wakes_on_push():
    async def main():
        q = RWQueue("q")

        async def reader():
            return await q.get()

        t = asyncio.ensure_future(reader())
        await asyncio.sleep(0)
        q.push(42)
        assert await t == 42

    run(main())


def test_rwqueue_close_drains_then_raises():
    async def main():
        q = RWQueue("q")
        q.push(1)
        q.close()
        assert not q.push(2)  # push after close rejected
        assert await q.get() == 1  # drain allowed
        with pytest.raises(QueueClosedError):
            await q.get()

    run(main())


def test_rwqueue_close_wakes_blocked_readers():
    async def main():
        q = RWQueue("q")

        async def reader():
            with pytest.raises(QueueClosedError):
                await q.get()
            return "done"

        t = asyncio.ensure_future(reader())
        await asyncio.sleep(0)
        q.close()
        assert await t == "done"

    run(main())


def test_replicate_queue_fans_out_to_all_readers():
    async def main():
        rq = ReplicateQueue("rq")
        r1 = rq.get_reader()
        r2 = rq.get_reader()
        assert rq.push("x") == 2
        assert await r1.get() == "x"
        assert await r2.get() == "x"
        # late reader does not see earlier items
        r3 = rq.get_reader()
        rq.push("y")
        assert await r3.get() == "y"
        assert await r1.get() == "y"
        assert rq.get_num_writes() == 2

    run(main())


def test_replicate_queue_reader_filter():
    async def main():
        rq = ReplicateQueue("rq")
        evens = rq.get_reader(lambda x: x % 2 == 0)
        alls = rq.get_reader()
        for i in range(5):
            rq.push(i)
        assert evens.try_get() == 0
        assert evens.try_get() == 2
        assert evens.try_get() == 4
        assert evens.try_get() is None
        assert [alls.try_get() for _ in range(5)] == [0, 1, 2, 3, 4]

    run(main())


def test_replicate_queue_close_propagates():
    async def main():
        rq = ReplicateQueue("rq")
        r = rq.get_reader()
        rq.push(1)
        rq.close()
        assert rq.push(2) == 0
        assert await r.get() == 1
        with pytest.raises(QueueClosedError):
            await r.get()
        with pytest.raises(QueueClosedError):
            rq.get_reader()

    run(main())


def test_replicate_queue_max_backlog():
    async def main():
        rq = ReplicateQueue("rq")
        r1 = rq.get_reader()
        r2 = rq.get_reader()
        rq.push(1)
        rq.push(2)
        await r1.get()
        assert rq.max_backlog() == 2  # r2 hasn't drained
        _ = r2
        del r2

    run(main())


def test_cancelled_reader_hands_item_to_next_waiter():
    async def main():
        q = RWQueue("q")
        r1 = asyncio.ensure_future(q.get())
        r2 = asyncio.ensure_future(q.get())
        await asyncio.sleep(0)
        q.push("x")  # delivered to r1's future
        r1.cancel()  # r1 cancelled before resuming: item must go to r2
        await asyncio.sleep(0)
        assert await r2 == "x"
        with pytest.raises(asyncio.CancelledError):
            await r1
        # stats: exactly one successful read
        assert q.num_reads == 1

    run(main())


def test_replicate_close_clears_readers_then_open_fresh():
    async def main():
        rq = ReplicateQueue("rq")
        rq.get_reader()
        rq.get_reader()
        rq.close()
        assert rq.get_num_readers() == 0
        rq.open()
        r = rq.get_reader()
        assert rq.push("a") == 1
        assert await r.get() == "a"

    run(main())
