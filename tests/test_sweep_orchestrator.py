"""Capacity-planning sweep orchestrator (ISSUE 14).

Covers:

* the declarative grammar: content-addressed scenario identity
  (enumeration-order independence), the deterministic bounded
  k-failure-domain draw, config/params override resolution;
* spill + checkpoint: segment rotation, the index, torn-tail
  tolerance, shard-filtered replay;
* the online reducer: feed-order independence of the ranked summary;
* the executor: same seed ⇒ byte-identical ranked summary; kill after
  shard K + resume ⇒ shards 0..K-1 skipped (checkpoint verified) and a
  final summary byte-identical to the uninterrupted run; prefix churn
  mid-sweep rides the content-hash plan cache instead of restarting
  planning; world semantics (single failures on a line withdraw the
  far prefixes; the SPOF list catches them); cancel leaves a
  resumable checkpoint; the multi-area kernel path;
* SweepService lifecycle on the SimClock + the ctrl-verb surface;
* the bounded ``build_repair_plan_cached`` cache: a world-churn sweep
  holds the configured cap, evictions/hits export as
  ``decision.backend.plan_cache.*`` gauges;
* streaming satellites: what-if feeds emit per-scenario-row deltas
  (the shared sweep row differ), and the fan-out loop renders +
  encodes each delta body once per feed entry, sharing it across
  subscribers.
"""

import asyncio
import json

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.sweep import (
    CheckpointManifest,
    ScenarioSpec,
    SpillReader,
    SpillWriter,
    SweepError,
    SweepExecutor,
    SweepInputs,
    SweepReducer,
    SweepService,
    diff_scenario_rows,
    enumerate_scenarios,
    scenario_rows,
    scenario_set_hash,
)
from openr_tpu.sweep.scenario import World, canonical_json
from openr_tpu.types import PrefixEntry

from tests.test_serving import build_decision, run

pytestmark = [pytest.mark.sweep]

PAIRS = [
    ("node0", "node1"),
    ("node1", "node2"),
    ("node2", "node3"),
    ("node0", "node3"),
]


# ---------------------------------------------------------------------------
# grammar
# ---------------------------------------------------------------------------


def test_scenario_hashes_are_enumeration_order_independent():
    spec = ScenarioSpec(
        drain_node_sets=((), ("node2",)),
        metric_perturbations=(("node.*", 4.0),),
        combo_k=2,
        max_combo_scenarios=3,
        combo_seed=9,
    )
    a = enumerate_scenarios(spec, PAIRS)
    b = enumerate_scenarios(spec, list(reversed(PAIRS)))
    assert [s.hash for s in a] == [s.hash for s in b]
    assert scenario_set_hash(spec, a) == scenario_set_hash(spec, b)
    # single failures x 4 worlds + 3 combos x 4 worlds
    assert len(a) == 4 * 4 + 3 * 4
    # scenario content is names, never ids
    assert a[0].content()["failed_links"][0][0].startswith("node")


def test_combo_draw_is_deterministic_bounded_and_seed_sensitive():
    spec = lambda seed: ScenarioSpec(  # noqa: E731
        single_link_failures=False,
        combo_k=2,
        max_combo_scenarios=3,
        combo_seed=seed,
    )
    a = enumerate_scenarios(spec(1), PAIRS)
    b = enumerate_scenarios(spec(1), PAIRS)
    c = enumerate_scenarios(spec(2), PAIRS)
    assert [s.hash for s in a] == [s.hash for s in b]
    assert len(a) == 3
    assert {s.hash for s in a} != {s.hash for s in c}, (
        "a different combo seed must draw a different sample"
    )
    # every combo fails the UNION of its node domains' incident links
    for s in a:
        assert len(s.domains) == 2
        assert all(
            any(n in pair for pair in s.failed_links)
            for n in s.domains
        )
    # exhaustive when the universe fits the bound
    wide = ScenarioSpec(
        single_link_failures=False,
        combo_k=2,
        max_combo_scenarios=100,
        combo_seed=1,
    )
    assert len(enumerate_scenarios(wide, PAIRS)) == 6  # C(4, 2)


def test_spec_from_params_overrides_config_defaults():
    from openr_tpu.config import MetricPerturbationConfig, SweepConfig

    cfg = SweepConfig(
        combo_k=2,
        max_combo_scenarios=7,
        drain_node_sets=[[], ["node9"]],
        metric_perturbations=[
            MetricPerturbationConfig(pattern="x.*", factor=3.0)
        ],
    )
    spec = ScenarioSpec.from_params(cfg, None)
    assert spec.combo_k == 2 and spec.max_combo_scenarios == 7
    assert spec.drain_node_sets == ((), ("node9",))
    assert spec.metric_perturbations == (("x.*", 3.0),)
    spec2 = ScenarioSpec.from_params(
        cfg,
        {
            "combo_k": 0,
            "drain_node_sets": [["a", "b"]],
            "metric_perturbations": [],
        },
    )
    assert spec2.combo_k == 0
    assert spec2.drain_node_sets == (("a", "b"),)
    assert spec2.metric_perturbations == ()


# ---------------------------------------------------------------------------
# spill + checkpoint
# ---------------------------------------------------------------------------


def test_spill_rotation_index_and_filtered_replay(tmp_path):
    d = str(tmp_path)
    w = SpillWriter(d, segment_rows=3)
    rows = [{"shard": i // 2, "hash": f"h{i}", "v": i} for i in range(8)]
    w.spill_rows(rows[:5])
    w.spill_rows(rows[5:])
    w.seal()
    st = w.stats()
    assert st["rows"] == 8 and st["segments_sealed"] == 3
    assert st["peak_host_rows"] == 5
    idx = json.loads((tmp_path / "index.json").read_text())
    assert [s["rows"] for s in idx["segments"]] == [3, 3, 2]
    r = SpillReader(d)
    assert [row["v"] for row in r.rows()] == list(range(8))
    assert [row["v"] for row in r.rows(shard_filter={1})] == [2, 3]


def test_spill_torn_tail_is_filtered_on_replay(tmp_path):
    d = str(tmp_path)
    w = SpillWriter(d, segment_rows=100)
    w.spill_rows([{"shard": 0, "v": 1}])
    # simulate a kill mid-write: a torn half-line at the open tail
    with open(tmp_path / "rows-00000.jsonl", "a") as f:
        f.write('{"shard": 1, "v"')
    got = list(SpillReader(d).rows())
    assert got == [{"shard": 0, "v": 1}]


def test_checkpoint_commit_and_match(tmp_path):
    cp = CheckpointManifest(str(tmp_path))
    assert not cp.matches("abc")
    cp.reset("id", "abc", {"g": 1}, 10)
    cp.commit_shard(0, {"rows": 4, "lo": 0, "hi": 4})
    cp2 = CheckpointManifest(str(tmp_path))
    assert cp2.matches("abc") and not cp2.matches("def")
    assert cp2.completed_shards() == {0: {"rows": 4, "lo": 0, "hi": 4}}


# ---------------------------------------------------------------------------
# reducer
# ---------------------------------------------------------------------------


def _mk_row(i, withdrawn, world="w", failure=(("a", "b"),)):
    return {
        "shard": 0,
        "hash": f"{i:04d}",
        "world": world,
        "failure": [list(p) for p in failure],
        "domains": [],
        "changed": withdrawn + 1,
        "withdrawn": withdrawn,
        "added": 0,
        "max_metric_increase": 0.0,
        "solve": "device",
    }


def test_reducer_summary_is_feed_order_independent():
    rows = [
        _mk_row(i, i % 5, failure=((f"n{i % 3}", f"n{i % 3 + 1}"),))
        for i in range(40)
    ]
    a, b = SweepReducer(top_k=8), SweepReducer(top_k=8)
    a.feed(rows)
    b.feed(list(reversed(rows)))
    assert a.summary_digest() == b.summary_digest()
    s = a.summary()
    assert s["scenarios"] == 40
    assert s["worst_case"]["withdrawn"] == 4
    assert len(s["worst_scenarios"]) == 8
    # single failures with withdrawals are SPOFs
    assert s["spof_links"]


# ---------------------------------------------------------------------------
# the shared scenario row differ (streaming satellite (a) substrate)
# ---------------------------------------------------------------------------


def test_scenario_rows_and_differ_are_per_failure_row():
    res = {
        "eligible": True,
        "vantage": "me",
        "engine": "device",
        "failures": [
            {"link": ["a", "b"], "routes_changed": 1, "changes": []},
            {"link": ["c", "d"], "routes_changed": 0, "changes": []},
        ],
    }
    rows = scenario_rows(res)
    assert ("w", "a|b") in rows and ("w", "c|d") in rows
    assert rows[("wmeta",)] == {
        "eligible": True, "vantage": "me", "engine": "device",
    }
    res2 = json.loads(json.dumps(res))
    res2["failures"][0]["routes_changed"] = 2
    updated, removed = diff_scenario_rows(rows, scenario_rows(res2))
    # ONLY the changed failure's row is in the delta
    assert set(updated) == {("w", "a|b")} and not removed
    res3 = {
        "eligible": True, "vantage": "me", "engine": "device",
        "failures": [res2["failures"][0]],
    }
    updated, removed = diff_scenario_rows(
        scenario_rows(res2), scenario_rows(res3)
    )
    assert removed == {("w", "c|d")} and not updated


# ---------------------------------------------------------------------------
# executor: determinism, resume, churn, worlds
# ---------------------------------------------------------------------------

SPEC = ScenarioSpec(
    drain_node_sets=((), ("node5",)),
    metric_perturbations=(("node1|node2", 3.0),),
    combo_k=2,
    max_combo_scenarios=4,
    combo_seed=3,
)


def make_executor(tmp_path, name, clock=None, d=None, **kw):
    if clock is None:
        clock = SimClock()
    if d is None:
        d, _edges = build_decision(clock)

    def inputs():
        return SweepInputs(**d.capacity_sweep_inputs())

    ex = SweepExecutor(
        inputs,
        str(tmp_path / name),
        clock=clock,
        counters=d.counters,
        shard_scenarios=kw.pop("shard_scenarios", 9),
        **kw,
    )
    return ex, d


def test_same_seed_runs_are_byte_identical(tmp_path):
    ex1, _ = make_executor(tmp_path, "a")
    ex1.prepare(SPEC)
    ex1.run()
    ex2, _ = make_executor(tmp_path, "b")
    ex2.prepare(SPEC)
    ex2.run()
    assert ex1.summary()["summary_digest"] == ex2.summary()["summary_digest"]
    assert canonical_json(ex1.reducer.summary()) == canonical_json(
        ex2.reducer.summary()
    )
    st = ex1.status()
    assert st["scenarios_completed"] == st["scenarios_total"]
    assert st["spill"]["rows"] == st["scenarios_total"]
    assert st["device_solves"] > 0


def test_kill_after_shard_k_resumes_byte_identically(tmp_path):
    K = 3
    full, _ = make_executor(tmp_path, "full")
    full.prepare(SPEC)
    full.run()

    killed, d = make_executor(tmp_path, "killed")
    killed.prepare(SPEC)
    killed.run(stop_after_shards=K)
    assert len(killed.completed) == K

    # checkpoint manifest verified: exactly shards 0..K-1 committed,
    # rows durable in the spill
    cp = CheckpointManifest(str(tmp_path / "killed"))
    committed = cp.completed_shards()
    assert sorted(committed) == list(range(K))
    replayed = list(
        SpillReader(str(tmp_path / "killed")).rows(
            shard_filter=set(committed)
        )
    )
    assert len(replayed) == sum(m["rows"] for m in committed.values())

    resumed, _ = make_executor(tmp_path, "killed", d=d)
    rep = resumed.prepare(SPEC)
    assert rep["resumed_shards"] == K
    resumed.run()
    assert resumed.status()["shards_completed"] == len(resumed.shards)
    assert (
        resumed.summary()["summary_digest"]
        == full.summary()["summary_digest"]
    ), "kill+resume must reproduce the uninterrupted summary bytes"
    # the resumed run never re-ran shards 0..K-1
    assert resumed.resumed_shards == K


def test_world_filter_slices_preserve_grammar_and_hashes():
    """The fleet's slicing knob: a ``world_filter`` sub-spec enumerates
    exactly the listed worlds' scenarios with UNCHANGED per-scenario
    hashes, and the unfiltered spec's content (hence every pre-fleet
    checkpoint hash) is byte-preserved — the field only appears when
    set."""
    import dataclasses

    full = enumerate_scenarios(SPEC, PAIRS)
    worlds = sorted({s.world.key() for s in full})
    assert len(worlds) == 4
    assert "world_filter" not in SPEC.content()
    picked = set(worlds[:2])
    sub = dataclasses.replace(SPEC, world_filter=tuple(sorted(picked)))
    assert "world_filter" in sub.content()
    sliced = enumerate_scenarios(sub, PAIRS)
    assert {s.world.key() for s in sliced} == picked
    assert [s.hash for s in sliced] == [
        s.hash for s in full if s.world.key() in picked
    ]
    # the slices partition the set: no overlap, no loss
    rest = dataclasses.replace(
        SPEC, world_filter=tuple(sorted(set(worlds) - picked))
    )
    assert len(sliced) + len(enumerate_scenarios(rest, PAIRS)) == len(full)


def test_cross_node_merge_digest_invariant_to_split_and_interleaving(
    tmp_path,
):
    """THE fleet sweep law: for EVERY node-count split of the world set
    (content-derived assignment over 1..4 nodes) and EVERY feed
    interleaving of the per-node spill streams, the merged reducer
    digest is byte-equal to the single-node run's."""
    import dataclasses

    from openr_tpu.fleet import assign_worlds

    clock = SimClock()
    d, _edges = build_decision(clock)
    single, _ = make_executor(tmp_path, "single", clock=clock, d=d)
    single.prepare(SPEC)
    single.run()
    want = single.summary()["summary_digest"]
    worlds = sorted(
        {
            s.world.key()
            for s in enumerate_scenarios(
                SPEC, SweepExecutor._all_pairs(single.inputs_fn())
            )
        }
    )
    for n_nodes in (1, 2, 3, 4):
        nodes = tuple(f"n{i}" for i in range(n_nodes))
        assignment = assign_worlds(f"split{n_nodes}", worlds, nodes)
        streams = []
        for node, wks in assignment.items():
            ex, _ = make_executor(
                tmp_path, f"s{n_nodes}.{node}", clock=clock, d=d
            )
            ex.prepare(dataclasses.replace(SPEC, world_filter=wks))
            ex.run()
            streams.append(list(SpillReader(ex.spill_dir).rows()))
        # node order, reversed, and row-level round-robin interleave
        for feed_plan in (
            streams,
            list(reversed(streams)),
            [
                [rows[i]]
                for i in range(max(len(s) for s in streams))
                for rows in streams
                if i < len(rows)
            ],
        ):
            reducer = SweepReducer(top_k=64)
            for chunk in feed_plan:
                reducer.feed(chunk)
            assert reducer.summary_digest() == want, (
                f"split over {n_nodes} nodes diverged"
            )


def test_mismatched_scenario_set_starts_fresh_with_clean_spill(tmp_path):
    ex, d = make_executor(tmp_path, "x")
    ex.prepare(SPEC)
    ex.run(stop_after_shards=1)
    other = ScenarioSpec(drain_node_sets=((), ("node7",)))
    ex2, _ = make_executor(tmp_path, "x", d=d)
    rep = ex2.prepare(other)
    # a different grammar never resumes a foreign checkpoint, and the
    # fresh sweep WIPES the stale spill — old shard-0 rows lingering in
    # the directory would collide with the new sweep's shard ids on a
    # later resume (found live: `breeze sweep run --no-resume` against
    # a node whose default spill dir held an earlier sweep)
    assert rep["resumed_shards"] == 0
    ex2.run(stop_after_shards=2)
    rows = list(SpillReader(str(tmp_path / "x")).rows())
    assert len(rows) == ex2.reducer.scenarios, (
        "the spill must hold ONLY the fresh sweep's rows"
    )
    # and the fresh sweep's kill+resume still round-trips
    ex3, _ = make_executor(tmp_path, "x", d=d)
    rep3 = ex3.prepare(other)
    assert rep3["resumed_shards"] == 2
    ex3.run()
    assert not ex3.pending_shards()
    assert ex3.status()["spill"]["rows"] == len(ex3.scenarios)


def test_prefix_churn_mid_sweep_rides_plan_cache(tmp_path):
    from openr_tpu.ops import repair

    ex, d = make_executor(tmp_path, "churn")
    ex.prepare(SPEC)
    ex.run(stop_after_shards=2)
    h0, m0 = repair.plan_cache_stats()
    # prefix-only churn: the graph is untouched, the generation moves
    d.prefix_state.update_prefix(
        "node7", "0", PrefixEntry("10.77.0.0/24")
    )
    d._change_seq += 1
    ex.run()
    st = ex.status()
    assert st["scenarios_completed"] == st["scenarios_total"]
    assert st["generations_observed"] == 2
    h1, m1 = repair.plan_cache_stats()
    assert h1 > h0, (
        "post-churn engine rebuilds must HIT the content-hash plan "
        "cache (the topology content never moved)"
    )
    assert ex.counters.get("sweep.context_builds") == 2


def line_decision(clock):
    """node0-node1-node2-node3 line: every link is a SPOF from node0."""
    from openr_tpu.decision.decision import Decision
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.config import DecisionConfig
    from openr_tpu.emulation.topology import build_adj_dbs
    from openr_tpu.messaging.queue import ReplicateQueue

    edges = [(f"node{i}", f"node{i + 1}", 1) for i in range(3)]
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(4):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))
    solver = SpfSolver("node0")
    d = Decision(
        "node0",
        clock,
        DecisionConfig(),
        ReplicateQueue("routes"),
        backend=TpuBackend(solver),
        solver=solver,
    )
    d.area_link_states = {"0": ls}
    d.prefix_state = ps
    d._change_seq = 1
    d.backend.auto_dispatch_rt_ms = 0.0
    return d


def test_line_topology_single_failures_rank_as_spofs(tmp_path):
    clock = SimClock()
    d = line_decision(clock)
    ex, _ = make_executor(tmp_path, "line", clock=clock, d=d)
    ex.prepare(ScenarioSpec())
    ex.run()
    s = ex.summary()["summary"]
    # every line link withdraws downstream prefixes from node0's vantage
    assert s["spof_links"] == [
        "node0|node1", "node1|node2", "node2|node3",
    ]
    # criticality ranks the nearest cut (3 prefixes lost) first
    top = s["criticality"][0]
    assert top["link"] == ["node0", "node1"]
    assert top["worst_withdrawn"] == 3
    assert s["worst_case"]["withdrawn"] == 3
    # spilled rows carry the per-scenario detail
    rows = list(SpillReader(str(tmp_path / "line")).rows())
    by_link = {tuple(r["failure"][0]): r for r in rows}
    assert by_link[("node2", "node3")]["withdrawn"] == 1


def test_metric_world_reroutes_without_withdrawing(tmp_path):
    clock = SimClock()
    d, _edges = build_decision(clock)
    ex, _ = make_executor(tmp_path, "metric", clock=clock, d=d)
    # grid world: scaling one link's metric reroutes but never
    # withdraws (the grid is 2-connected)
    ex.prepare(
        ScenarioSpec(
            metric_perturbations=(("node5|node6", 10.0),),
        )
    )
    ex.run()
    rows = list(SpillReader(str(tmp_path / "metric")).rows())
    worlds = {r["world"] for r in rows}
    assert len(worlds) == 2
    assert all(r["withdrawn"] == 0 for r in rows)
    assert ex.summary()["summary"]["spof_links"] == []


def test_cancel_leaves_resumable_checkpoint(tmp_path):
    ex, d = make_executor(tmp_path, "cancel")
    ex.prepare(SPEC)

    done = 0

    def cancel_after_two():
        nonlocal done
        done += 1
        if done >= 2:
            ex.cancelled = True

    ex.run(yield_cb=cancel_after_two)
    assert 0 < len(ex.completed) < len(ex.shards)
    resumed, _ = make_executor(tmp_path, "cancel", d=d)
    rep = resumed.prepare(SPEC)
    assert rep["resumed_shards"] == len(ex.completed)
    resumed.run()
    assert not resumed.pending_shards()


def test_multi_area_executor_path(tmp_path):
    from tests.test_whatif_multiarea import make_prefixes, two_area_world

    als = two_area_world("b0")
    ps = make_prefixes()

    def inputs():
        return SweepInputs(
            area_link_states=als,
            prefix_state=ps,
            change_seq=1,
            root="b0",
        )

    ex = SweepExecutor(
        inputs, str(tmp_path / "ma"), clock=SimClock(), shard_scenarios=5
    )
    ex.prepare(ScenarioSpec())
    ex.run()
    st = ex.status()
    assert st["scenarios_completed"] == st["scenarios_total"] == 7
    rows = list(SpillReader(str(tmp_path / "ma")).rows())
    by_link = {tuple(r["failure"][0]): r for r in rows}
    # a0's only prefix path is via area 1: cutting (a0, a1) AND
    # (a0, b0) partitions it — singly each leaves a detour, so neither
    # alone withdraws 10.0/24; the stub link (a1, b0) carries b0's
    # direct reach of a1
    assert all(r["solve"] == "device" for r in rows)
    assert by_link[("a0", "a1")]["changed"] >= 1
    # determinism across a second run
    ex2 = SweepExecutor(
        inputs, str(tmp_path / "ma2"), clock=SimClock(), shard_scenarios=5
    )
    ex2.prepare(ScenarioSpec())
    ex2.run()
    assert (
        ex.summary()["summary_digest"] == ex2.summary()["summary_digest"]
    )


# ---------------------------------------------------------------------------
# the service actor + ctrl surface
# ---------------------------------------------------------------------------


def make_service(clock, d, tmp_path, **cfg_overrides):
    from openr_tpu.config import SweepConfig

    cfg = SweepConfig(
        spill_dir=str(tmp_path / "svc"),
        shard_scenarios=cfg_overrides.pop("shard_scenarios", 16),
        **cfg_overrides,
    )
    return SweepService("node0", clock, cfg, d, counters=d.counters)


def test_sweep_service_lifecycle(tmp_path):
    async def main():
        clock = SimClock()
        d, _edges = build_decision(clock)
        svc = make_service(clock, d, tmp_path)
        svc.start()
        rep = svc.start_sweep(
            {"drain_node_sets": [[], ["node5"]], "combo_k": 0}
        )
        assert rep["state"] == "running" and rep["scenarios"] > 0
        with pytest.raises(SweepError):
            svc.start_sweep({})
        while svc.state == "running":
            await clock.run_for(0.05)
        assert svc.state == "done"
        st = svc.get_sweep_status()
        assert st["scenarios_completed"] == st["scenarios_total"]
        summary = svc.get_sweep_summary()
        assert summary["complete"] is True
        assert summary["summary"]["scenarios"] == st["scenarios_total"]
        assert d.counters.get("sweep.sweeps_completed") == 1
        gauges = svc.gauges()
        assert gauges["sweep.running"] == 0.0
        assert gauges["sweep.scenarios_done"] == st["scenarios_total"]
        # a second start over the SAME grammar resumes instantly (all
        # shards committed)
        rep2 = svc.start_sweep(
            {"drain_node_sets": [[], ["node5"]], "combo_k": 0}
        )
        assert rep2["resumed_shards"] == rep2["shards"]
        while svc.state == "running":
            await clock.run_for(0.05)
        assert svc.state == "done"

    run(main())


def test_sweep_service_cancel_and_refusal(tmp_path):
    async def main():
        clock = SimClock()
        d, _edges = build_decision(clock)
        svc = make_service(clock, d, tmp_path, shard_scenarios=4)
        svc.start()
        svc.start_sweep({})
        svc.cancel_sweep()
        while svc.state == "running":
            await clock.run_for(0.05)
        assert svc.state == "cancelled"
        # a drained-vantage grammar is refused, not crashed
        with pytest.raises(SweepError):
            svc.start_sweep({"drain_node_sets": [["node0"]]})

    run(main())


# ---------------------------------------------------------------------------
# the bounded plan cache (satellite (c))
# ---------------------------------------------------------------------------


def test_plan_cache_cap_holds_under_world_churn(tmp_path):
    from openr_tpu.ops import repair

    old_cap = repair.set_plan_cache_cap(3)
    try:
        clock = SimClock()
        d, _edges = build_decision(clock)
        ex, _ = make_executor(
            tmp_path, "cap", clock=clock, d=d, shard_scenarios=64
        )
        # 6 worlds > cap 3: the sweep churns the cache; the cap holds
        ex.prepare(
            ScenarioSpec(
                drain_node_sets=(
                    (), ("node5",), ("node6",), ("node9",),
                    ("node10",), ("node12",),
                ),
            )
        )
        ex.run()
        gauges = repair.plan_cache_gauges()
        assert gauges["plan_cache.cap"] == 3.0
        assert gauges["plan_cache.size"] <= 3.0
        assert gauges["plan_cache.evictions"] >= 3.0
        # the backend exports them under decision.backend.plan_cache.*
        snap = d.backend.counter_snapshot()
        assert snap["decision.backend.plan_cache.size"] <= 3.0
        assert "decision.backend.plan_cache.hits" in snap
        assert "decision.backend.plan_cache.evictions" in snap
    finally:
        repair.set_plan_cache_cap(0)
        repair.set_plan_cache_cap(old_cap)


def test_plan_cache_cap_is_config_wired():
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.ops import repair

    before = repair.plan_cache_gauges()["plan_cache.cap"]
    try:
        TpuBackend(SpfSolver("node0"), plan_cache_entries=5)
        assert repair.plan_cache_gauges()["plan_cache.cap"] == 5.0
    finally:
        repair.set_plan_cache_cap(int(before))


# ---------------------------------------------------------------------------
# streaming satellites: per-row what-if deltas + shared wire-encode
# ---------------------------------------------------------------------------


def streaming_world(clock):
    from openr_tpu.decision.backend import ScalarBackend

    from tests.test_serving import make_serving
    from tests.test_streaming import make_streaming

    d, _edges = build_decision(clock, backend_cls=ScalarBackend)
    sv = make_serving(clock, d)
    st = make_streaming(clock, d, sv)
    sv.start()
    st.start()
    return d, sv, st


def test_whatif_feed_emits_per_scenario_row_deltas(tmp_path):
    async def main():
        clock = SimClock()
        d, sv, st = streaming_world(clock)
        pairs = [["node0", "node1"], ["node14", "node15"]]
        from tests.test_streaming import bump_prefix, poll

        sub = st.subscribe(
            "whatif", {"link_failures": pairs}, client_id="c1"
        )
        snap = await poll(clock, st, sub)
        assert snap["type"] == "snapshot" and "scenario" in snap
        from openr_tpu.serving import apply_emission

        state = apply_emission({}, snap)
        assert ("w", "node0|node1") in state
        assert ("w", "node14|node15") in state
        # a prefix advertised AT node1 changes what failing (node0,
        # node1) reroutes, but not the far corner's failure row: the
        # delta carries ONLY the changed scenario row, never the whole
        # scenario result (PR-13 remnant (a))
        bump_prefix(d, "10.55.0.0/24", node="node1")
        delta = await poll(clock, st, sub)
        assert delta["type"] == "delta"
        assert "scenario" not in delta
        updated_keys = {
            "|".join(sorted(r["link"]))
            for r in delta["scenario_updated"]
        }
        assert updated_keys == {"node0|node1"}
        assert delta["scenario_removed"] == []
        state = apply_emission(state, delta)
        _gen, live = sv.snapshot_for(
            "whatif",
            {"link_failures": [tuple(p) for p in pairs]},
        )
        assert state == scenario_rows(live), (
            "applied per-row deltas must reproduce the live scenario"
        )

    run(main())


def test_shared_payload_render_and_wire_encode(tmp_path):
    async def main():
        clock = SimClock()
        d, sv, st = streaming_world(clock)
        from tests.test_streaming import bump_prefix

        got_a, got_b, wire = [], [], []
        st.subscribe(
            "route_db", {"node": "node1"}, client_id="a",
            deliver=got_a.append,
        )
        st.subscribe(
            "route_db", {"node": "node1"}, client_id="b",
            deliver=got_b.append,
        )
        st.subscribe(
            "route_db", {"node": "node1"}, client_id="w",
            deliver_wire=wire.append,
        )
        with pytest.raises(Exception):
            st.subscribe(
                "route_db", {"node": "node1"},
                deliver=got_a.append, deliver_wire=wire.append,
            )
        await clock.run_for(0.1)
        bump_prefix(d, "10.55.0.0/24")
        await clock.run_for(0.5)
        assert got_a[-1]["type"] == "delta"
        # the delta BODY was rendered once and shared by reference
        assert (
            got_a[-1]["unicast_updated"] is got_b[-1]["unicast_updated"]
        )
        assert d.counters.get("streaming.rendered_payloads") == 1
        assert d.counters.get("streaming.shared_payloads") >= 2
        # the wire subscriber's bytes parse back to the same delta, and
        # its body bytes were encoded once (shared thereafter)
        parsed = json.loads(wire[-1].decode())
        assert parsed == json.loads(
            json.dumps(got_a[-1], sort_keys=True, default=str)
        )
        assert d.counters.get("streaming.wire.body_encodes") == 1
        bump_prefix(d, "10.56.0.0/24")
        await clock.run_for(0.5)
        assert d.counters.get("streaming.wire.body_encodes") == 2
        # second delta: another wire sub would share... assert the
        # filtered path still renders per-sub
        st.subscribe(
            "route_db", {"node": "node1"}, client_id="f",
            prefix_filters=("10.55.",), deliver=[].append,
        )
        bump_prefix(d, "10.57.0.0/24")
        await clock.run_for(0.5)
        assert d.counters.get("streaming.shared_payloads") >= 4

    run(main())
