"""Slot-stable structural encode (ISSUE 12): tombstone/free-list
mechanics, decline reasons, dense-reduction exclusion of tombstoned
rows, the per-platform kernel preference hook, and the load-bearing
property end to end — a structural warm rebuild's RouteDb is
BIT-IDENTICAL to both the cold device build and the scalar oracle over
a seeded join/leave churn sweep."""

import numpy as np
import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import ResilienceConfig
from openr_tpu.decision.backend import TpuBackend
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
from openr_tpu.ops.csr import (
    encode_link_state,
    patch_encoded_multi_area_slots,
    patch_encoded_topology_slots,
)
from openr_tpu.types import PrefixEntry


def make_world(side=4, seed_prefix="10.8"):
    edges = grid_edges(side)
    adj = build_adj_dbs(edges)
    ls = LinkState("0", "node0")
    for db in adj.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(side * side):
        ps.update_prefix(
            f"node{i}", "0", PrefixEntry(f"{seed_prefix}.{i}.0/24")
        )
    return adj, ls, ps


def make_backend(**kw):
    kw.setdefault("warm_rebuild", True)
    return TpuBackend(
        SpfSolver("node0"),
        clock=SimClock(),
        resilience=ResilienceConfig(enabled=False),
        **kw,
    )


def norm_db(db):
    return {
        p: (
            sorted((nh.neighbor_node_name, nh.metric) for nh in e.nexthops),
            float(e.igp_cost),
        )
        for p, e in db.unicast_routes.items()
    }


def solve_dense(topo, root_name):
    """Cold dense-kernel (dist, lanes) for one encoding."""
    import jax.numpy as jnp

    from openr_tpu.ops.spf import dense_spf_one

    root = topo.node_id(root_name)
    dist, nh = dense_spf_one(
        jnp.asarray(topo.in_src),
        jnp.asarray(topo.in_w),
        jnp.asarray(topo.in_ok),
        jnp.asarray(topo.in_rank),
        jnp.asarray(topo.in_has),
        jnp.asarray(topo.overloaded),
        jnp.int32(root),
        max_degree=8,
    )
    return np.asarray(dist), np.asarray(nh)


# ---------------------------------------------------------------------------
# slot patch mechanics
# ---------------------------------------------------------------------------


def test_leave_tombstones_in_place_layout_shared():
    adj, ls, _ps = make_world()
    old = encode_link_state(ls)
    ls.delete_adjacency_database("node15")
    patched, reason = patch_encoded_topology_slots(old, ls, "node0")
    assert reason is None
    # layout arrays are the SAME OBJECTS — the O(touched links) contract
    assert patched.src is old.src
    assert patched.dst is old.dst
    assert patched.link_index is old.link_index
    assert patched.link_edge_pos is old.link_edge_pos
    assert patched.in_src is old.in_src
    assert patched.in_rank is old.in_rank
    assert patched.in_edge_pos is old.in_edge_pos
    assert patched.node_ids is old.node_ids  # no renames: symbols shared
    assert patched.tombstoned_nodes == frozenset({"node15"})
    # node15 was a corner: both its links' rows are invalidated
    assert len(patched.tombstoned_links) == 2
    nid = old.node_id("node15")
    for li in patched.tombstoned_links:
        for e in old.link_edge_pos[li]:
            assert not patched.edge_ok[e]
            assert patched.w[e] == np.float32(np.inf)
    assert patched.slot_changed[nid]


def test_rejoin_revives_rows_and_matches_original():
    adj, ls, _ps = make_world()
    old = encode_link_state(ls)
    ls.delete_adjacency_database("node15")
    left, _ = patch_encoded_topology_slots(old, ls, "node0")
    # rejoin: identical adjacencies re-advertised
    ls.update_adjacency_database(adj["node15"])
    for n in ("node11", "node14"):
        ls.update_adjacency_database(adj[n])
    back, reason = patch_encoded_topology_slots(left, ls, "node0")
    assert reason is None
    assert back.tombstoned_nodes == frozenset()
    assert back.tombstoned_links == frozenset()
    # revived planes are value-identical to the original encoding
    np.testing.assert_array_equal(back.w, old.w)
    np.testing.assert_array_equal(back.edge_ok, old.edge_ok)
    np.testing.assert_array_equal(back.in_w, old.in_w)
    np.testing.assert_array_equal(back.in_ok, old.in_ok)
    assert back.src is old.src  # still the original layout objects


def test_slot_exhaustion_and_new_link_decline():
    adj, ls, _ps = make_world()
    old = encode_link_state(ls)
    # a brand-new node with no tombstoned slot to reclaim
    from openr_tpu.types import Adjacency, AdjacencyDatabase

    fresh = AdjacencyDatabase(
        "nodeX",
        area="0",
        adjacencies=[
            Adjacency(
                other_node_name="node0",
                if_name="if_x_0",
                other_if_name="if_0_x",
                metric=1,
            )
        ],
    )
    db0 = adj["node0"]
    db0.adjacencies.append(
        Adjacency(
            other_node_name="nodeX",
            if_name="if_0_x",
            other_if_name="if_x_0",
            metric=1,
        )
    )
    ls.update_adjacency_database(fresh)
    ls.update_adjacency_database(db0)
    enc, reason = patch_encoded_topology_slots(old, ls, "node0")
    assert enc is None and reason == "slot_exhaustion"
    # with a free (tombstoned) slot the name is admitted, but its link
    # joins a pair no tombstoned row serves -> new_link decline
    ls2 = LinkState("0", "node0")
    for db in build_adj_dbs(grid_edges(4)).values():
        ls2.update_adjacency_database(db)
    old2 = encode_link_state(ls2)
    ls2.delete_adjacency_database("node15")
    left2, _ = patch_encoded_topology_slots(old2, ls2, "node0")
    fresh2 = AdjacencyDatabase(
        "nodeY",
        area="0",
        adjacencies=[
            Adjacency(
                other_node_name="node0",
                if_name="if_y_0",
                other_if_name="if_0_y",
                metric=1,
            )
        ],
    )
    db0b = build_adj_dbs(grid_edges(4))["node0"]
    db0b.adjacencies.append(
        Adjacency(
            other_node_name="nodeY",
            if_name="if_0_y",
            other_if_name="if_y_0",
            metric=1,
        )
    )
    ls2.update_adjacency_database(fresh2)
    ls2.update_adjacency_database(db0b)
    enc2, reason2 = patch_encoded_topology_slots(left2, ls2, "node0")
    assert enc2 is None and reason2 == "new_link"


def test_replacement_node_reclaims_slot_and_rows():
    """The autoscaling-replacement pattern: node15 dies forever, a NEW
    name joins with the same physical neighbors — it reclaims node15's
    tombstoned slot and its links reclaim the retained rows."""
    adj, ls, _ps = make_world()
    old = encode_link_state(ls)
    slot15 = old.node_id("node15")
    ls.delete_adjacency_database("node15")
    left, _ = patch_encoded_topology_slots(old, ls, "node0")
    from openr_tpu.types import Adjacency, AdjacencyDatabase

    # node15's grid neighbors are node11 and node14: the replacement
    # advertises the same two adjacencies under a new name
    repl = AdjacencyDatabase(
        "node99",
        area="0",
        adjacencies=[
            Adjacency(
                other_node_name=n,
                if_name=f"if_99_{n}",
                other_if_name=f"if_{n}_99",
                metric=1,
            )
            for n in ("node11", "node14")
        ],
    )
    for n in ("node11", "node14"):
        db = adj[n]
        db.adjacencies = [
            a for a in db.adjacencies if a.other_node_name != "node15"
        ] + [
            Adjacency(
                other_node_name="node99",
                if_name=f"if_{n}_99",
                other_if_name=f"if_99_{n}",
                metric=1,
            )
        ]
        ls.update_adjacency_database(db)
    ls.update_adjacency_database(repl)
    enc, reason = patch_encoded_topology_slots(left, ls, "node0")
    assert reason is None
    assert enc.node_id("node99") == slot15
    assert "node15" not in enc.node_ids
    assert enc.tombstoned_nodes == frozenset()
    assert enc.slot_changed[slot15]
    assert enc.src is old.src  # layout survived the rename
    # the reclaimed rows carry the replacement's links
    dist_p, nh_p = solve_dense(enc, "node0")
    fresh = encode_link_state(
        ls,
        node_bucket=enc.padded_nodes,
        edge_bucket=enc.padded_edges,
        extra_nodes=("node0",),
    )
    dist_f, _ = solve_dense(fresh, "node0")
    for name in fresh.node_ids:
        assert (
            dist_p[enc.node_id(name)] == dist_f[fresh.node_id(name)]
        ), name


def test_tombstoned_rows_excluded_from_dense_reductions():
    """A tombstoned node's rows read in_ok=False / in_w=INF: the dense
    kernels must produce, at every surviving slot, exactly the fresh
    re-encode's answer — and BIG (unreachable) at the tombstone."""
    from openr_tpu.ops.consts import BIG

    adj, ls, _ps = make_world()
    old = encode_link_state(ls)
    ls.delete_adjacency_database("node5")  # interior node: 4 links
    patched, reason = patch_encoded_topology_slots(old, ls, "node0")
    assert reason is None
    assert len(patched.tombstoned_links) == 4
    dist_p, _ = solve_dense(patched, "node0")
    fresh = encode_link_state(
        ls,
        node_bucket=old.padded_nodes,
        edge_bucket=old.padded_edges,
        extra_nodes=("node0",),
    )
    dist_f, _ = solve_dense(fresh, "node0")
    for name in fresh.node_ids:
        assert (
            dist_p[patched.node_id(name)] == dist_f[fresh.node_id(name)]
        ), name
    assert dist_p[patched.node_id("node5")] == np.float32(BIG)


def test_multi_area_slot_patch_kinds():
    adj, ls, _ps = make_world()
    from openr_tpu.ops.csr import encode_multi_area

    als = {"0": ls}
    prev = encode_multi_area(als, "node0")
    # pure weight churn -> "patch"
    db = adj["node3"]
    db.adjacencies[0].metric = 5
    ls.update_adjacency_database(db)
    enc, kind, reason = patch_encoded_multi_area_slots(prev, als, "node0")
    assert enc is not None and kind == "patch" and reason is None
    # membership churn -> "slot"
    ls.delete_adjacency_database("node15")
    enc2, kind2, reason2 = patch_encoded_multi_area_slots(
        enc, als, "node0"
    )
    assert enc2 is not None and kind2 == "slot" and reason2 is None
    # area membership change -> cold decline with the counted reason
    ls_b = LinkState("b", "node0")
    enc3, kind3, reason3 = patch_encoded_multi_area_slots(
        enc2, {"0": ls, "b": ls_b}, "node0"
    )
    assert enc3 is None and kind3 == "cold" and reason3 == "area_change"


# ---------------------------------------------------------------------------
# backend end to end: seeded membership churn, warm vs cold vs scalar
# ---------------------------------------------------------------------------


def test_seeded_membership_churn_warm_cold_scalar_parity():
    """The ISSUE-12 acceptance property live: over a seeded sweep of
    leaves, rejoins and weight perturbations, every structural warm
    rebuild is bit-parity with a cold device backend AND the scalar
    oracle — and the warm path actually engaged (slot patches +
    structural warm hits, zero fallbacks)."""
    adj, ls, ps = make_world(side=4)
    als = {"0": ls}
    warm = make_backend()
    cold = make_backend(warm_rebuild=False)
    oracle = SpfSolver("node0")
    warm.build_route_db(als, ps, force_full=True)
    cold.build_route_db(als, ps, force_full=True)

    rng = np.random.default_rng(12)
    bounceable = [f"node{i}" for i in range(1, 16)]
    down = []
    for step in range(14):
        op = int(rng.integers(3))
        structural = False
        if op == 0 and len(down) < 3:
            victim = bounceable[int(rng.integers(len(bounceable)))]
            if victim not in down and ls.has_node(victim):
                ls.delete_adjacency_database(victim)
                down.append(victim)
                structural = True
        elif op == 1 and down:
            back = down.pop(0)
            ls.update_adjacency_database(adj[back])
            for other in adj:
                if other != back and ls.has_node(other):
                    ls.update_adjacency_database(adj[other])
            structural = True
        else:
            alive = sorted(set(adj) - set(down))
            victim = alive[int(rng.integers(len(alive)))]
            db = adj[victim]
            a = db.adjacencies[int(rng.integers(len(db.adjacencies)))]
            a.metric = 1 + (a.metric % 3)
            ls.update_adjacency_database(db)
        db_w = warm.build_route_db(
            als,
            ps,
            changed_prefixes=set(),
            force_full=True,
            warm_delta=not structural,
            structural_delta=structural,
        )
        db_c = cold.build_route_db(als, ps, force_full=True)
        db_s = oracle.build_route_db(als, ps)
        assert norm_db(db_w) == norm_db(db_c) == norm_db(db_s), (
            f"generation {step} diverged"
        )
    assert warm._warm_class_builds["structural"] >= 4
    assert warm.num_warm_cold_fallbacks == 0
    assert warm.num_encode_slot_patches >= 4


def test_structural_selective_patch_object_identity():
    """A structural warm tick far from a prefix's advertiser must patch
    that prefix's RouteDb entry through OBJECT-IDENTICALLY — the
    selective-selection path proves it re-selected only the affected
    region."""
    adj, ls, ps = make_world(side=4)
    als = {"0": ls}
    warm = make_backend()
    db0 = warm.build_route_db(als, ps, force_full=True)
    # node15 (far corner) leaves; node1's prefix routes via node0's
    # immediate neighborhood and cannot be affected
    ls.delete_adjacency_database("node15")
    db1 = warm.build_route_db(
        als,
        ps,
        changed_prefixes=set(),
        force_full=True,
        structural_delta=True,
    )
    assert warm._warm_class_builds["structural"] == 1
    changed = warm.take_last_changed_prefixes()
    assert changed is not None
    assert "10.8.1.0/24" not in changed
    assert (
        db1.unicast_routes["10.8.1.0/24"]
        is db0.unicast_routes["10.8.1.0/24"]
    )
    # the departed node's own prefix is gone
    assert "10.8.15.0/24" not in db1.unicast_routes or (
        db1.unicast_routes.get("10.8.15.0/24") is None
    )


def test_purge_on_suspicion_still_forces_cold_after_structural():
    """PR-5/9 purge semantics survive ISSUE 12: corruption injection
    after structural warm builds purges the context, the next build is
    cold + shadow-verified, and a later structural tick re-warms."""
    adj, ls, ps = make_world()
    als = {"0": ls}
    warm = make_backend()
    warm.build_route_db(als, ps, force_full=True)
    ls.delete_adjacency_database("node15")
    warm.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True,
        structural_delta=True,
    )
    assert warm._warm_class_builds["structural"] == 1
    warm.inject_silent_corruption(True)
    assert warm._warm_ctx is None
    warm.inject_silent_corruption(False)
    ls.update_adjacency_database(adj["node15"])
    for n in ("node11", "node14"):
        ls.update_adjacency_database(adj[n])
    db = warm.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True,
        structural_delta=True,
    )
    # purged context: this structural tick fell back cold (counted)...
    assert warm._warm_class_fallbacks["structural"] == 1
    assert (
        warm._warm_class_fallback_reasons["structural"].get("no_context")
        == 1
    )
    assert norm_db(db) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    # ...and re-established it: the next leave warms again
    ls.delete_adjacency_database("node12")
    db = warm.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True,
        structural_delta=True,
    )
    assert warm._warm_class_builds["structural"] == 2
    assert norm_db(db) == norm_db(SpfSolver("node0").build_route_db(als, ps))


# ---------------------------------------------------------------------------
# per-platform kernel preference hook
# ---------------------------------------------------------------------------


def test_kernel_preference_hook_bit_parity():
    """The ROADMAP policy hook: forcing the segment path on this
    platform must produce the identical RouteDb (both kernel families
    are kept bit-parity); the default preference stays dense."""
    adj, ls, ps = make_world()
    als = {"0": ls}
    dense_be = make_backend()
    assert dense_be._spf_kernel_preference() == "dense"
    db_dense = dense_be.build_route_db(als, ps, force_full=True)
    seg_be = make_backend()
    seg_be.KERNEL_PREFERENCE = {"default": "segment"}
    assert seg_be._spf_kernel_preference() == "segment"
    db_seg = seg_be.build_route_db(als, ps, force_full=True)
    assert norm_db(db_dense) == norm_db(db_seg)
    # and the segment preference keeps full parity across a structural
    # warm tick too (the warm kernels are segment-based either way)
    for be, flag in ((dense_be, "dense"), (seg_be, "segment")):
        pass
    ls.delete_adjacency_database("node15")
    db_d2 = dense_be.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True,
        structural_delta=True,
    )
    db_s2 = seg_be.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True,
        structural_delta=True,
    )
    assert norm_db(db_d2) == norm_db(db_s2)
