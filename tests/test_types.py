"""Data-model round-trip and key-format tests."""

from openr_tpu.config import AreaConfig, OpenrConfig
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    PerfEvents,
    PrefixEntry,
    PrefixMetrics,
    Publication,
    Value,
    adj_key,
    normalize_prefix,
    parse_adj_key,
    parse_prefix_key,
    prefix_key,
)


def test_adjacency_db_wire_roundtrip():
    db = AdjacencyDatabase(
        this_node_name="node1",
        is_overloaded=True,
        adjacencies=[
            Adjacency("node2", "if_1_2_1", metric=10, adj_label=50001, rtt=1500),
            Adjacency("node3", "if_1_3_1", metric=20, is_overloaded=True),
        ],
        node_label=101,
        area="area1",
        node_metric_increment_val=5,
    )
    wire = db.to_wire()
    back = AdjacencyDatabase.from_wire(wire)
    assert back == db
    assert back.adjacencies[0].adj_label == 50001


def test_prefix_entry_normalizes_and_roundtrips():
    e = PrefixEntry(
        prefix="10.1.2.3/16",
        metrics=PrefixMetrics(path_preference=1000, source_preference=200),
        tags={"b", "a"},
        area_stack=["area1", "area2"],
    )
    assert e.prefix == "10.1.0.0/16"
    back = PrefixEntry.from_wire(e.to_wire())
    assert back == e
    assert back.tags == {"a", "b"}
    assert isinstance(back.metrics, PrefixMetrics)


def test_prefix_metrics_sort_key_ordering():
    # drain_metric (lower) > path_pref (higher) > src_pref (higher) > distance (lower)
    best = PrefixMetrics(drain_metric=0, path_preference=1000, source_preference=200)
    drained = PrefixMetrics(drain_metric=1, path_preference=9999)
    lower_pp = PrefixMetrics(drain_metric=0, path_preference=500, source_preference=999)
    farther = PrefixMetrics(
        drain_metric=0, path_preference=1000, source_preference=200, distance=4
    )
    ms = [drained, farther, best, lower_pp]
    ms.sort(key=lambda m: m.sort_key())
    assert ms == [best, farther, lower_pp, drained]


def test_value_bytes_wire_roundtrip():
    v = Value(version=3, originator_id="node1", value=b"\x00\xffbinary", ttl=300000)
    back = Value.from_wire(v.to_wire())
    assert back == v


def test_publication_roundtrip():
    p = Publication(
        key_vals={"adj:node1": Value(version=1, originator_id="node1", value=b"x")},
        expired_keys=["prefix:gone"],
        node_ids=["node1", "node2"],
        area="a1",
    )
    back = Publication.from_wire(p.to_wire())
    assert back == p
    assert back.key_vals["adj:node1"].value == b"x"


def test_key_formats():
    assert adj_key("node-1.pod1") == "adj:node-1.pod1"
    assert parse_adj_key("adj:node-1.pod1") == "node-1.pod1"
    assert parse_adj_key("prefix:x") is None
    k = prefix_key("node1", "2001:db8::1/128")
    assert k == "prefix:node1:[2001:db8::1/128]"
    assert parse_prefix_key(k) == ("node1", "2001:db8::1/128")
    # node names may contain ':' -- parser splits at the ':[' boundary
    k2 = prefix_key("rsw001.p001:x", "10.0.0.0/24")
    assert parse_prefix_key(k2) == ("rsw001.p001:x", "10.0.0.0/24")
    assert parse_prefix_key("prefix:no-bracket") is None


def test_normalize_prefix():
    assert normalize_prefix("10.0.0.5/8") == "10.0.0.0/8"
    assert normalize_prefix("2001:DB8::5/64") == "2001:db8::/64"


def test_perf_events_duration():
    pe = PerfEvents()
    pe.add("node1", "ADJ_RECEIVED", 100)
    pe.add("node1", "ROUTES_BUILT", 250)
    assert pe.total_duration_ms() == 150


def test_config_json_roundtrip():
    cfg = OpenrConfig(
        node_name="rsw001",
        areas=[AreaConfig(area_id="pod1"), AreaConfig(area_id="spine")],
    )
    cfg.decision_config.debounce_min_ms = 20
    cfg.spark_config.hold_time_s = 15.0
    text = cfg.to_json()
    back = OpenrConfig.from_json(text)
    assert back.node_name == "rsw001"
    assert back.area_ids() == ["pod1", "spine"]
    assert back.decision_config.debounce_min_ms == 20
    assert back.spark_config.hold_time_s == 15.0
    assert back.tpu_compute_config.node_buckets == [16, 64, 256, 1024, 4096, 16384]


def test_config_validation():
    import pytest

    with pytest.raises(ValueError):
        OpenrConfig(areas=[])
    with pytest.raises(ValueError):
        OpenrConfig(areas=[AreaConfig("a"), AreaConfig("a")])


def test_enum_fields_reconstruct_from_wire():
    from openr_tpu.types import MplsAction, MplsActionCode, NeighborEvent, NeighborEventType

    ev = NeighborEvent(NeighborEventType.NEIGHBOR_UP, "node2")
    back = NeighborEvent.from_wire(ev.to_wire())
    assert back.event_type is NeighborEventType.NEIGHBOR_UP
    assert back.event_type.name == "NEIGHBOR_UP"
    act = MplsAction(MplsActionCode.SWAP, swap_label=100)
    back2 = MplsAction.from_wire(act.to_wire())
    assert back2.action is MplsActionCode.SWAP


def test_link_status_records_roundtrip():
    from openr_tpu.types import LinkStatusRecords

    db = AdjacencyDatabase(
        this_node_name="n1",
        link_status_records=LinkStatusRecords({"eth0": (1, 1234), "eth1": (0, 99)}),
    )
    back = AdjacencyDatabase.from_wire(db.to_wire())
    assert back == db
    assert back.link_status_records.link_status_map["eth0"] == (1, 1234)


def test_config_originated_prefix_tags_set_roundtrip():
    from openr_tpu.config import OriginatedPrefix

    cfg = OpenrConfig(
        originated_prefixes=[OriginatedPrefix("10.0.0.0/8", tags={"b", "a"})]
    )
    back = OpenrConfig.from_json(cfg.to_json())
    assert back.originated_prefixes[0].tags == {"a", "b"}
    assert isinstance(back.originated_prefixes[0].tags, set)
