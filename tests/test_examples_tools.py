"""Examples + tools tests: KvStorePoller fan-out scrape, SetRibPolicy
example, KvStoreSnooper live stream.

Reference parity: examples/KvStorePoller.h, examples/SetRibPolicyExample.cpp,
openr/kvstore/tools/KvStoreSnooper.cpp.
"""

import asyncio

from openr_tpu.common.runtime import WallClock
from openr_tpu.ctrl.client import OpenrCtrlClient
from openr_tpu.ctrl.server import OpenrCtrlServer
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import line_edges
from openr_tpu.examples.kvstore_poller import KvStorePoller
from openr_tpu.examples.set_rib_policy import build_policy
from openr_tpu.kvstore.tools.snooper import KvStoreSnooper


async def wall_net(n=2, converge_s=8.0):
    net = EmulatedNetwork(WallClock())
    net.build(line_edges(n))
    net.start()
    deadline = asyncio.get_running_loop().time() + converge_s
    while asyncio.get_running_loop().time() < deadline:
        await asyncio.sleep(0.25)
        ok, _why = net.converged_full_mesh()
        if ok:
            return net
    ok, why = net.converged_full_mesh()
    assert ok, why
    return net


def test_kvstore_poller_fanout_and_unreachable():
    async def run():
        net = await wall_net(2)
        servers = []
        try:
            for name in sorted(net.nodes):
                s = OpenrCtrlServer(net.nodes[name], port=0)
                await s.start()
                servers.append(s)
            endpoints = [("127.0.0.1", s.port) for s in servers]
            # one dead endpoint on a port nobody listens on
            endpoints.append(("127.0.0.1", 1))
            poller = KvStorePoller(endpoints, timeout_s=5.0)
            dbs, unreachable = await poller.get_prefix_dbs()
            assert unreachable == [("127.0.0.1", 1)]
            assert len(dbs) == 2
            # every reachable node serves the full prefix LSDB
            for keys in dbs.values():
                assert any(k.startswith("prefix:node0") for k in keys)
                assert any(k.startswith("prefix:node1") for k in keys)
        finally:
            for s in servers:
                await s.stop()
            await net.stop()

    asyncio.run(run())


def test_set_rib_policy_example_shape():
    async def run():
        net = await wall_net(2)
        server = OpenrCtrlServer(net.nodes["node0"], port=0)
        await server.start()
        try:
            policy = build_policy(
                prefixes=["10.0.0.0/8"],
                area_weights={"0": 7},
                neighbor_weights={},
                ttl_s=60.0,
            )
            async with OpenrCtrlClient(port=server.port) as client:
                await client.call("set_rib_policy", policy=policy)
                echoed = await client.call("get_rib_policy")
            assert echoed is not None
            assert echoed["statements"][0]["prefixes"] == ["10.0.0.0/8"]
            assert 0 < echoed["ttl_remaining_s"] <= 60.0
        finally:
            await server.stop()
            await net.stop()

    asyncio.run(run())


def test_kvstore_snooper_snapshot_then_delta():
    async def run():
        net = await wall_net(2)
        server = OpenrCtrlServer(net.nodes["node1"], port=0)
        await server.start()
        try:
            snooper = KvStoreSnooper(port=server.port, key_prefixes=["adj:"])
            seen_snapshot_keys = set()
            got_delta = asyncio.Event()

            async def consume():
                async for is_snap, key, _value in snooper.snoop():
                    if is_snap:
                        seen_snapshot_keys.add(key)
                    elif key.startswith("adj:"):
                        got_delta.set()
                        return

            task = asyncio.ensure_future(consume())
            await asyncio.sleep(1.0)
            assert any(
                k.startswith("adj:node0") for k in seen_snapshot_keys
            ), seen_snapshot_keys
            # force an adjacency re-advertisement -> delta publication
            net.nodes["node0"].link_monitor.set_link_metric("if_0_1", 77)
            await asyncio.wait_for(got_delta.wait(), timeout=10.0)
            task.cancel()
        finally:
            await server.stop()
            await net.stop()

    asyncio.run(run())
