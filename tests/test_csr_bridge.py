"""CSR bridge tests: native fill vs pure-Python fallback equivalence,
topology-seq encoder caching, and failure-mask expansion.

Reference context: SURVEY §7 hard-part 4 (host<->device bridge inside the
debounce budget); native/csr_bridge.cc is the C fill path.
"""

import numpy as np
import pytest

import openr_tpu.ops.csr as csr_mod
from openr_tpu.decision.link_state import LinkState
from openr_tpu.emulation.topology import (
    build_adj_dbs,
    grid_edges,
    random_connected_edges,
)
from openr_tpu.ops.csr import encode_link_state, link_failure_batch


def make_ls(edges):
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    return ls


def encode_both(ls, **kw):
    """Encode with the native path and with the fallback; return both."""
    native = csr_mod._get_native()
    assert native is not None, "native csr_bridge must build in CI"
    with_native = encode_link_state(ls, **kw)
    saved = csr_mod._native
    csr_mod._native = False  # force fallback
    try:
        fallback = encode_link_state(ls, **kw)
    finally:
        csr_mod._native = saved
    return with_native, fallback


class TestNativeFill:
    def test_native_matches_fallback(self):
        ls = make_ls(random_connected_edges(64, 96, seed=11))
        a, b = encode_both(ls)
        for field in ("src", "dst", "w", "edge_ok", "link_index"):
            np.testing.assert_array_equal(
                getattr(a, field), getattr(b, field), err_msg=field
            )
        assert a.node_ids == b.node_ids
        assert a.num_edges == b.num_edges

    def test_layout_invariants(self):
        ls = make_ls(grid_edges(3))
        topo, _ = encode_both(ls)
        pad = topo.link_index < 0
        # padding carries inf weight, no validity
        assert np.all(np.isinf(topo.w[pad]))
        assert not topo.edge_ok[pad].any()
        assert int(pad.sum()) == topo.padded_edges - topo.num_edges
        # dst-sorted: the kernels' segment reductions require it
        assert np.all(np.diff(topo.dst) >= 0)
        # link_edge_pos maps every link to exactly its two directed edges
        for li, (e0, e1) in enumerate(topo.link_edge_pos):
            assert topo.link_index[e0] == li
            assert topo.link_index[e1] == li
            assert {topo.src[e0], topo.dst[e0]} == {topo.src[e1], topo.dst[e1]}

    def test_non_positive_metric_rejected(self):
        ls = make_ls([("a", "b", 1)])
        link = ls.all_links()[0]
        link.metric1 = 0
        link.metric2 = 0
        with pytest.raises(ValueError):
            encode_link_state(ls)

    def test_failure_masks_native_matches_fallback(self):
        ls = make_ls(random_connected_edges(32, 48, seed=5))
        topo = encode_link_state(ls)
        fails = [[0], [1, 2], [], [len(topo.links) - 1, 0]]
        native_mask = link_failure_batch(topo, fails)
        saved = csr_mod._native
        csr_mod._native = False
        try:
            fallback_mask = link_failure_batch(topo, fails)
        finally:
            csr_mod._native = saved
        np.testing.assert_array_equal(native_mask, fallback_mask)


class TestTopologySeqCache:
    def test_seq_bumps_on_topology_change_only(self):
        ls = make_ls(grid_edges(3))
        seq0 = ls.topology_seq
        dbs = build_adj_dbs(grid_edges(3))
        node = sorted(dbs)[0]
        # identical re-advertisement: no change
        ls.update_adjacency_database(dbs[node])
        assert ls.topology_seq == seq0
        # metric change: topology change
        for adj in dbs[node].adjacencies:
            adj.metric = 42
        ls.update_adjacency_database(dbs[node])
        assert ls.topology_seq > seq0
        # delete: topology change
        seq1 = ls.topology_seq
        ls.delete_adjacency_database(node)
        assert ls.topology_seq > seq1

    def test_backend_encoder_cache_hits_on_prefix_churn(self):
        from openr_tpu.decision.backend import TpuBackend
        from openr_tpu.decision.prefix_state import PrefixState
        from openr_tpu.decision.spf_solver import SpfSolver
        from openr_tpu.types import PrefixEntry

        ls = make_ls(grid_edges(3))
        nodes = sorted(build_adj_dbs(grid_edges(3)))
        ps = PrefixState()
        ps.update_prefix(nodes[-1], "0", PrefixEntry(prefix="10.0.0.0/24"))
        backend = TpuBackend(SpfSolver(nodes[0]))
        backend.build_route_db({"0": ls}, ps)
        assert backend.num_encodes == 1
        # prefix churn, same topology -> cache hit
        ps.update_prefix(nodes[-2], "0", PrefixEntry(prefix="10.0.1.0/24"))
        backend.build_route_db({"0": ls}, ps)
        assert backend.num_encodes == 1
        assert backend.num_encode_hits == 1
        # topology change -> re-encode
        dbs = build_adj_dbs(grid_edges(3))
        for adj in dbs[nodes[0]].adjacencies:
            adj.metric = 9
        ls.update_adjacency_database(dbs[nodes[0]])
        backend.build_route_db({"0": ls}, ps)
        assert backend.num_encodes == 2
