"""Sharded flagship engine: mesh-parallel warm-start sweep + selection.

The tests run on the conftest's 8-device virtual CPU mesh and hold the
sharded code paths (shard_map over the batch axis — ops/repair.py,
ops/sweep_select.py, ops/fleet_tables.py) to BIT parity with the
unsharded kernels.  Both relaxation loops reach unique fixed points, so
sharding must not change a single bit of any output (see the
ops/repair.py module docstring for the argument); these tests enforce
that, including non-multiple batch sizes that ride the bucket padding.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.multichip

from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
from openr_tpu.ops.csr import encode_link_state
from openr_tpu.ops.sweep_select import SweepCandidates, SweepRouteSelector
from openr_tpu.ops.whatif import LinkFailureSweep
from openr_tpu.types import PrefixEntry


@pytest.fixture(scope="module")
def world():
    ls = LinkState("0")
    for db in build_adj_dbs(grid_edges(5)).values():
        ls.update_adjacency_database(db)
    return ls, encode_link_state(ls)


def _mesh(n):
    import jax

    from openr_tpu.parallel.mesh import make_mesh, shard_map_supported

    if not shard_map_supported():
        # version-gated: this jax predates the stable jax.shard_map the
        # sharded kernels target (see parallel/mesh.py) — skip, don't red
        pytest.skip("this jax has no stable jax.shard_map")
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")
    return make_mesh(n)


def test_sharded_sweep_bit_parity(world):
    _ls, topo = world
    L = len(topo.links)
    fails = np.asarray([b % L for b in range(197)], np.int32)  # odd size
    r1 = LinkFailureSweep(topo, "node0").run(fails, fetch=True)
    r8 = LinkFailureSweep(topo, "node0", mesh=_mesh(8)).run(
        fails, fetch=True
    )
    assert np.array_equal(r1.snap_row, r8.snap_row)
    assert np.array_equal(r1.dist, r8.dist)
    assert np.array_equal(r1.nh, r8.nh)


def test_sharded_selector_delta_parity(world):
    _ls, topo = world
    V = 25
    L = len(topo.links)
    fails = np.asarray([b % L for b in range(101)], np.int32)
    cands = SweepCandidates.single_advertiser(np.arange(V))

    def deltas(mesh):
        eng = LinkFailureSweep(topo, "node0", mesh=mesh)
        sel = SweepRouteSelector(
            topo, "node0", cands, max_degree=eng.D, mesh=mesh
        )
        return sel.run(eng.run(fails, fetch=False))

    d1, d8 = deltas(None), deltas(_mesh(8))
    for f in (
        "snap_row",
        "base_valid",
        "base_metric",
        "base_lanes",
        "delta_row",
        "delta_prefix",
        "delta_valid",
        "delta_metric",
        "delta_lanes",
    ):
        assert np.array_equal(getattr(d1, f), getattr(d8, f)), f
    assert d8.num_deltas > 0  # the parity must cover a non-trivial stream


def test_sharded_sweep_odd_mesh_size(world):
    """A 3-device mesh: granularity 96, buckets round up to multiples."""
    _ls, topo = world
    L = len(topo.links)
    fails = np.asarray([b % L for b in range(50)], np.int32)
    eng = LinkFailureSweep(topo, "node0", mesh=_mesh(3))
    assert eng.batch_granularity == 96
    assert all(b % 96 == 0 for b in eng.solve_buckets)
    r3 = eng.run(fails, fetch=True)
    r1 = LinkFailureSweep(topo, "node0").run(fails, fetch=True)
    assert np.array_equal(r1.dist, r3.dist)
    assert np.array_equal(r1.nh, r3.nh)


def test_sharded_fleet_matches_scalar_for_every_root():
    """FleetRibEngine(mesh=...) must equal the scalar per-node solver —
    the same bar the unsharded fleet test holds (Decision.cpp:342)."""
    from openr_tpu.decision.fleet import FleetRibEngine
    from openr_tpu.decision.rib import route_db_summary
    from openr_tpu.decision.spf_solver import SpfSolver

    ls = LinkState("0")
    for db in build_adj_dbs(
        grid_edges(4), soft_drained={"node10": 60}, overloaded=["node5"]
    ).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(16):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))
    als = {"0": ls}
    eng = FleetRibEngine(SpfSolver("node0"), mesh=_mesh(8))
    assert eng.eligible(als, ps, change_seq=1)
    for i in range(16):
        node = f"node{i}"
        got = eng.compute_for_node(node, als, ps, change_seq=1)
        want = SpfSolver(node).build_route_db(als, ps)
        assert route_db_summary(got) == route_db_summary(want), node
    assert eng.num_batched_solves == 1


def test_sharded_multi_chunk_sweep_parity(world):
    """Chunked dispatch under a mesh: max_chunk forces several chunks
    per sweep; rows must land at the right offsets regardless of
    sharded bucket padding."""
    _ls, topo = world
    L = len(topo.links)
    fails = np.asarray([b % L for b in range(160)], np.int32)
    r1 = LinkFailureSweep(topo, "node0", max_chunk=16).run(
        fails, fetch=True
    )
    r8 = LinkFailureSweep(
        topo, "node0", max_chunk=16, mesh=_mesh(8)
    ).run(fails, fetch=True)
    assert np.array_equal(r1.snap_row, r8.snap_row)
    assert np.array_equal(r1.dist, r8.dist)
    assert np.array_equal(r1.nh, r8.nh)


def test_sharded_multiarea_whatif_engine_parity():
    """MultiAreaWhatIfEngine(mesh=...) must return the IDENTICAL result
    dict as the unsharded engine — singles, parallel bundles, and a
    simultaneous set all ride the failure-batch-sharded kernel
    (ops.fleet_tables.sharded_whatif_tables)."""
    import dataclasses

    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.decision.whatif_api import MultiAreaWhatIfEngine
    from openr_tpu.emulation.topology import ring_edges

    me = "a0"

    def make_ls(area, edges):
        ls = LinkState(area, me)
        for db in build_adj_dbs(edges).values():
            ls.update_adjacency_database(dataclasses.replace(db, area=area))
        return ls

    als = {
        "1": make_ls("1", ring_edges(5, prefix="a")),
        "2": make_ls("2", [("a0", "b0", 1), ("b0", "b1", 1),
                           ("b1", "b2", 1), ("a0", "b2", 4)]),
    }
    ps = PrefixState()
    for node, area in (("a2", "1"), ("a3", "1"), ("b1", "2"), ("b2", "2")):
        ps.update_prefix(node, area, PrefixEntry(f"10.{ord(node[0])}.{node[1]}.0/24"))
    queries = [
        ([("a0", "a1"), ("b0", "b1"), ("a2", "a3")], False),
        ([("a0", "a1"), ("b1", "b2")], True),  # simultaneous set
    ]
    for failures, sim in queries:
        r1 = MultiAreaWhatIfEngine(SpfSolver(me)).run(
            failures, als, ps, 1, simultaneous=sim
        )
        r8 = MultiAreaWhatIfEngine(SpfSolver(me), mesh=_mesh(8)).run(
            failures, als, ps, 1, simultaneous=sim
        )
        assert r1 == r8, (sim, r1, r8)
