"""Differential validation: batched JAX SPF + route selection vs the
scalar oracle (LinkState/SpfSolver).  Runs on the 8-device virtual CPU
mesh configured in conftest.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import (
    build_adj_dbs,
    grid_edges,
    random_connected_edges,
    ring_edges,
)
from openr_tpu.ops.csr import (
    encode_link_state,
    encode_prefix_candidates,
    link_failure_batch,
)
from openr_tpu.ops.route_select import batched_select_routes, spf_and_select
from openr_tpu.ops.spf import BIG, batched_spf, spf_one
from openr_tpu.types import PrefixEntry, PrefixMetrics


def make_ls(edges, **kwargs) -> LinkState:
    ls = LinkState("0")
    for db in build_adj_dbs(edges, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def scalar_spf_arrays(ls: LinkState, topo, root: str):
    """Scalar oracle → (dist array, nexthop-neighbor-set list) in id space."""
    res = ls.run_spf(root)
    V = topo.padded_nodes
    dist = np.full(V, np.inf)
    nhs = [set() for _ in range(V)]
    for node, r in res.items():
        i = topo.node_id(node)
        dist[i] = r.metric
        nhs[i] = set(r.next_hops)
    return dist, nhs


def kernel_spf(ls: LinkState, root: str, **enc_kwargs):
    topo = encode_link_state(ls, **enc_kwargs)
    D = max(topo.max_out_degree(), 1)
    dist, nh = spf_one(
        jnp.asarray(topo.src),
        jnp.asarray(topo.dst),
        jnp.asarray(topo.w),
        jnp.asarray(topo.edge_ok),
        jnp.asarray(topo.overloaded),
        jnp.int32(topo.node_id(root)),
        D,
    )
    return topo, np.asarray(dist), np.asarray(nh)


def decode_nh_neighbors(topo, root, nh_row) -> set:
    out_edges = topo.root_out_edges(root)
    return {
        neighbor
        for lane, (_, neighbor) in enumerate(out_edges)
        if lane < nh_row.shape[0] and nh_row[lane]
    }


def assert_spf_parity(ls: LinkState, root: str):
    topo, kdist, knh = kernel_spf(ls, root)
    sdist, snhs = scalar_spf_arrays(ls, topo, root)
    for i in range(topo.num_nodes):
        if np.isinf(sdist[i]):
            assert kdist[i] >= BIG, f"node {topo.id_to_node[i]} reachability"
        else:
            assert kdist[i] == pytest.approx(sdist[i]), topo.id_to_node[i]
            got = decode_nh_neighbors(topo, root, knh[i])
            assert got == snhs[i], (
                f"nexthops for {topo.id_to_node[i]}: kernel {got} vs "
                f"scalar {snhs[i]}"
            )


def test_parity_line():
    assert_spf_parity(make_ls([("a", "b", 1), ("b", "c", 2)]), "a")


def test_parity_ecmp_diamond():
    edges = [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
    assert_spf_parity(make_ls(edges), "a")


def test_parity_grid():
    assert_spf_parity(make_ls(grid_edges(4)), "node0")
    assert_spf_parity(make_ls(grid_edges(4)), "node5")


def test_parity_overloaded_transit():
    edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 10)]
    assert_spf_parity(make_ls(edges, overloaded=["b"]), "a")
    # overloaded root still relaxes
    assert_spf_parity(make_ls(edges, overloaded=["a"]), "a")


def test_parity_asymmetric_metrics_max_rule():
    edges = [("a", "b", 1), ("b", "a", 10), ("b", "c", 1), ("a", "c", 5)]
    assert_spf_parity(make_ls(edges), "a")


def test_parity_partitioned_graph():
    edges = [("a", "b", 1), ("x", "y", 1)]
    assert_spf_parity(make_ls(edges), "a")


def test_parity_random_wans():
    for seed in range(6):
        n = 24
        edges = random_connected_edges(n, 30, seed=seed)
        rng = np.random.default_rng(seed)
        overloaded = [f"node{i}" for i in rng.choice(n, 3, replace=False)]
        ls = make_ls(edges, overloaded=overloaded)
        for root in ("node0", f"node{n - 1}"):
            assert_spf_parity(ls, root)


def test_batched_what_if_link_failures_match_scalar():
    """Fail each ring link in its own snapshot; kernel batch must match a
    scalar re-solve with that link removed."""
    n = 6
    edges = ring_edges(n)
    ls = make_ls(edges)
    topo = encode_link_state(ls)
    D = max(topo.max_out_degree(), 1)
    B = len(topo.links)
    mask = link_failure_batch(topo, [[li] for li in range(B)])
    dist, nh = batched_spf(
        jnp.asarray(topo.src),
        jnp.asarray(topo.dst),
        jnp.asarray(topo.w),
        jnp.asarray(topo.edge_ok),
        jnp.asarray(mask),
        jnp.tile(jnp.asarray(topo.overloaded), (B, 1)),
        jnp.zeros(B, jnp.int32),  # root node0 everywhere
        D,
    )
    dist = np.asarray(dist)
    for b, link in enumerate(topo.links):
        # scalar: remove the failed link by running spf with links_to_ignore
        res = ls.run_spf("node0", links_to_ignore=frozenset([link]))
        for node, r in res.items():
            assert dist[b, topo.node_id(node)] == pytest.approx(r.metric)
        reached = {topo.node_id(x) for x in res}
        for i in range(topo.num_nodes):
            if i not in reached:
                assert dist[b, i] >= BIG


def select_parity_case(edges, advertisements, root, **ls_kwargs):
    """advertisements: list of (node, prefix, metrics_kwargs)."""
    ls = make_ls(edges, **ls_kwargs)
    ps = PrefixState()
    for node, prefix, mk in advertisements:
        extra = {}
        if "min_nexthop" in mk:
            extra["min_nexthop"] = mk.pop("min_nexthop")
        ps.update_prefix(
            node, "0", PrefixEntry(prefix, metrics=PrefixMetrics(**mk), **extra)
        )
    solver = SpfSolver(root)
    route_db = solver.build_route_db({"0": ls}, ps)

    topo = encode_link_state(ls)
    cands = encode_prefix_candidates(ps, topo, "0")
    D = max(topo.max_out_degree(), 1)
    valid, metric, nh_out, num_nh, _winners = spf_and_select(
        jnp.asarray(topo.src),
        jnp.asarray(topo.dst),
        jnp.asarray(topo.w),
        jnp.asarray(topo.edge_ok),
        jnp.ones((1, topo.padded_edges), bool),
        jnp.asarray(topo.overloaded)[None],
        jnp.asarray(topo.soft)[None],
        jnp.asarray([topo.node_id(root)], jnp.int32),
        jnp.asarray(cands.cand_node),
        jnp.asarray(cands.cand_ok),
        jnp.asarray(cands.drain_metric),
        jnp.asarray(cands.path_pref),
        jnp.asarray(cands.source_pref),
        jnp.asarray(cands.distance),
        jnp.asarray(cands.min_nexthop),
        max_degree=D,
    )
    valid = np.asarray(valid)[0]
    metric = np.asarray(metric)[0]
    nh_out = np.asarray(nh_out)[0]

    for p, prefix in enumerate(cands.prefixes):
        scalar_route = route_db.unicast_routes.get(prefix) if route_db else None
        if scalar_route is None:
            assert not valid[p], f"{prefix}: kernel has route, scalar doesn't"
            continue
        assert valid[p], f"{prefix}: scalar has route, kernel doesn't"
        assert metric[p] == pytest.approx(scalar_route.igp_cost), prefix
        kernel_neighbors = decode_nh_neighbors(topo, root, nh_out[p])
        scalar_neighbors = {
            nh.neighbor_node_name for nh in scalar_route.nexthops
        }
        assert kernel_neighbors == scalar_neighbors, prefix


def test_select_parity_basic_and_ecmp():
    edges = [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
    select_parity_case(
        edges,
        [("d", "10.0.0.0/24", {}), ("b", "10.1.0.0/24", {})],
        "a",
    )


def test_select_parity_preferences_and_self_skip():
    edges = [("a", "b", 1), ("b", "c", 1), ("c", "d", 1)]
    select_parity_case(
        edges,
        [
            ("b", "10.0.0.0/24", {"path_preference": 500}),
            ("d", "10.0.0.0/24", {"path_preference": 1000}),
            ("a", "10.3.0.0/24", {}),  # self-advertised -> no route
            ("c", "10.4.0.0/24", {"min_nexthop": 2}),  # gate fails
        ],
        "a",
    )


def test_select_parity_drains():
    edges = [("a", "b", 1), ("a", "c", 1), ("a", "d", 1)]
    select_parity_case(
        edges,
        [
            ("b", "10.0.0.0/24", {}),
            ("c", "10.0.0.0/24", {}),
            ("d", "10.0.0.0/24", {}),
        ],
        "a",
        overloaded=["b"],
        soft_drained={"c": 50},
    )


def test_select_parity_random():
    rng = np.random.default_rng(42)
    n = 16
    edges = random_connected_edges(n, 16, seed=3)
    ads = []
    for p in range(12):
        prefix = f"10.{p}.0.0/24"
        for node in rng.choice(n, rng.integers(1, 4), replace=False):
            ads.append(
                (
                    f"node{node}",
                    prefix,
                    {
                        "path_preference": int(rng.choice([500, 1000])),
                        "source_preference": int(rng.choice([100, 200])),
                        "distance": int(rng.integers(0, 3)),
                    },
                )
            )
    select_parity_case(edges, ads, "node0")


def test_sharded_kernel_on_virtual_mesh():
    """The 8-device CPU mesh path: batch sharded across devices."""
    from openr_tpu.parallel.mesh import make_mesh, shard_batch, sharded_spf_and_select

    assert len(jax.devices()) == 8, jax.devices()
    ls = make_ls(grid_edges(4))
    ps = PrefixState()
    ps.update_prefix("node15", "0", PrefixEntry("10.0.0.0/24"))
    topo = encode_link_state(ls)
    cands = encode_prefix_candidates(ps, topo, "0")
    D = max(topo.max_out_degree(), 1)
    mesh = make_mesh()
    B = 16  # 2 per device
    mask = np.ones((B, topo.padded_edges), bool)
    # fail a different link in each snapshot (first 16 links)
    for b in range(B):
        mask[b, np.asarray(topo.link_index) == (b % len(topo.links))] = False
    edge_en, ovl, soft, roots = shard_batch(
        mesh,
        mask,
        np.tile(topo.overloaded, (B, 1)),
        np.tile(topo.soft, (B, 1)),
        np.zeros(B, np.int32),
    )
    kernel = sharded_spf_and_select(mesh, D)
    valid, metric, nh, num, _w = kernel(
        topo.src,
        topo.dst,
        topo.w,
        topo.edge_ok,
        edge_en,
        ovl,
        soft,
        roots,
        cands.cand_node,
        cands.cand_ok,
        cands.drain_metric,
        cands.path_pref,
        cands.source_pref,
        cands.distance,
        cands.min_nexthop,
    )
    assert valid.shape == (B, 1)
    assert bool(np.asarray(valid).all())  # grid survives any single failure
    # output actually sharded over the mesh
    assert len(valid.sharding.device_set) == 8
    # spot-check one snapshot against scalar
    li = 3
    link = topo.links[li]
    res = ls.run_spf("node0", links_to_ignore=frozenset([link]))
    assert np.asarray(metric)[li, 0] == pytest.approx(res["node15"].metric)


def test_select_parity_min_nexthop_on_farther_winner():
    """min-nexthop must be the max over ALL selection winners, including
    those losing the IGP tie (SpfSolver.cpp getMinNextHopThreshold)."""
    edges = [("a", "b", 1), ("b", "c", 1)]
    select_parity_case(
        edges,
        [
            ("b", "10.0.0.0/24", {}),
            ("c", "10.0.0.0/24", {"min_nexthop": 2}),  # farther winner gates
        ],
        "a",
    )


def test_batched_select_routes_on_precomputed_spf():
    """Exercise the standalone selection kernel (select over already-solved
    SPF state) and the zero-metric encode guard."""
    edges = [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
    ls = make_ls(edges)
    ps = PrefixState()
    ps.update_prefix("d", "0", PrefixEntry("10.0.0.0/24"))
    topo = encode_link_state(ls)
    cands = encode_prefix_candidates(ps, topo, "0")
    D = max(topo.max_out_degree(), 1)
    B = 2
    dist, nh = batched_spf(
        jnp.asarray(topo.src),
        jnp.asarray(topo.dst),
        jnp.asarray(topo.w),
        jnp.asarray(topo.edge_ok),
        jnp.ones((B, topo.padded_edges), bool),
        jnp.tile(jnp.asarray(topo.overloaded), (B, 1)),
        jnp.full(B, topo.node_id("a"), jnp.int32),
        D,
    )
    valid, metric, nh_out, num, _w = batched_select_routes(
        jnp.asarray(cands.cand_node),
        jnp.asarray(cands.cand_ok),
        jnp.asarray(cands.drain_metric),
        jnp.asarray(cands.path_pref),
        jnp.asarray(cands.source_pref),
        jnp.asarray(cands.distance),
        jnp.asarray(cands.min_nexthop),
        dist,
        nh,
        jnp.tile(jnp.asarray(topo.overloaded), (B, 1)),
        jnp.tile(jnp.asarray(topo.soft), (B, 1)),
        jnp.full(B, topo.node_id("a"), jnp.int32),
    )
    assert bool(np.asarray(valid).all())
    assert np.asarray(metric)[0, 0] == 2.0
    assert np.asarray(num)[0, 0] == 2  # ECMP over b and c


def test_encode_rejects_zero_metric():
    ls = make_ls([("a", "b", 0)])
    with pytest.raises(ValueError, match="non-positive metric"):
        encode_link_state(ls)


def test_shard_batch_pads_non_multiple_batches():
    """B % mesh != 0: shard_batch pads by replicating the last snapshot;
    kernel outputs for the real rows must match an unsharded run."""
    from openr_tpu.parallel.mesh import (
        make_mesh,
        padded_batch_size,
        shard_batch,
        sharded_spf_and_select,
    )

    assert len(jax.devices()) == 8, jax.devices()
    ls = make_ls(grid_edges(4))
    ps = PrefixState()
    ps.update_prefix("node15", "0", PrefixEntry("10.0.0.0/24"))
    topo = encode_link_state(ls)
    cands = encode_prefix_candidates(ps, topo, "0")
    D = max(topo.max_out_degree(), 1)
    mesh = make_mesh()
    B = 13  # deliberately not a multiple of 8
    assert padded_batch_size(mesh, B) == 16
    mask = np.ones((B, topo.padded_edges), bool)
    for b in range(B):
        mask[b, np.asarray(topo.link_index) == (b % len(topo.links))] = False
    shared = (
        topo.src, topo.dst, topo.w, topo.edge_ok,
    )
    cand_args = (
        cands.cand_node, cands.cand_ok, cands.drain_metric,
        cands.path_pref, cands.source_pref, cands.distance,
        cands.min_nexthop,
    )
    edge_en, ovl, soft, roots = shard_batch(
        mesh,
        mask,
        np.tile(topo.overloaded, (B, 1)),
        np.tile(topo.soft, (B, 1)),
        np.zeros(B, np.int32),
    )
    assert edge_en.shape[0] == 16
    kernel = sharded_spf_and_select(mesh, D)
    out_sharded = kernel(*shared, edge_en, ovl, soft, roots, *cand_args)
    out_plain = spf_and_select(
        *(jnp.asarray(a) for a in shared),
        jnp.asarray(mask),
        jnp.tile(jnp.asarray(topo.overloaded), (B, 1)),
        jnp.tile(jnp.asarray(topo.soft), (B, 1)),
        jnp.zeros(B, jnp.int32),
        *(jnp.asarray(a) for a in cand_args),
        max_degree=D,
    )
    for a_s, a_p in zip(out_sharded, out_plain):
        assert np.array_equal(np.asarray(a_s)[:B], np.asarray(a_p))
    # padded rows replicate snapshot B-1
    assert np.array_equal(
        np.asarray(out_sharded[1])[B:], np.tile(np.asarray(out_plain[1])[-1], (3, 1))
    )
