"""Fleet RIB engine — every node's RouteDb from one device batch.

Parity bar: for EVERY vantage node, the batch-decoded RouteDb must equal
the scalar per-node computation (the reference's getRouteDbComputed
semantics, Decision.cpp:342), including drains, anycast winners and
ECMP sets; the cache must invalidate on LSDB change; ineligible
configurations must fall back scalar."""

import random

from openr_tpu.common.runtime import SimClock
from openr_tpu.decision.backend import TpuBackend
from openr_tpu.decision.decision import Decision
from openr_tpu.decision.fleet import FleetRibEngine
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import route_db_summary
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.config import DecisionConfig
from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import (
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixMetrics,
)


def build_world(soft=None, overloaded=None):
    edges = grid_edges(4)
    dbs = build_adj_dbs(
        edges, soft_drained=soft or {}, overloaded=overloaded or []
    )
    ls = LinkState("0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    rng = random.Random(4)
    for i in range(16):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))
    # anycast with preference spread + a v6 prefix
    ps.update_prefix("node3", "0", PrefixEntry(
        "10.100.0.0/24", metrics=PrefixMetrics(path_preference=1000)))
    ps.update_prefix("node12", "0", PrefixEntry(
        "10.100.0.0/24", metrics=PrefixMetrics(path_preference=1000)))
    ps.update_prefix("node7", "0", PrefixEntry("2001:db8::/64"))
    del rng
    return ls, ps


def scalar_for(node, als, ps):
    return SpfSolver(node).build_route_db(als, ps)


def test_fleet_matches_scalar_for_every_root():
    ls, ps = build_world(soft={"node10": 60}, overloaded=["node5"])
    als = {"0": ls}
    eng = FleetRibEngine(SpfSolver("node0"))
    assert eng.eligible(als, ps, change_seq=1)
    for i in range(16):
        node = f"node{i}"
        dev = eng.compute_for_node(node, als, ps, change_seq=1)
        oracle = scalar_for(node, als, ps)
        assert route_db_summary(dev) == route_db_summary(oracle), node
    assert eng.num_batched_solves == 1  # one batch served all 16 decodes
    assert eng.num_decodes == 16


def test_fleet_cache_invalidation_on_change_seq():
    ls, ps = build_world()
    als = {"0": ls}
    eng = FleetRibEngine(SpfSolver("node0"))
    eng.compute_for_node("node1", als, ps, change_seq=1)
    assert eng.num_batched_solves == 1
    eng.compute_for_node("node2", als, ps, change_seq=1)
    assert eng.num_batched_solves == 1  # cached
    ps.update_prefix("node9", "0", PrefixEntry("10.200.0.0/24"))
    db = eng.compute_for_node("node1", als, ps, change_seq=2)
    assert eng.num_batched_solves == 2  # re-solved
    assert "10.200.0.0/24" in db.unicast_routes
    oracle = scalar_for("node1", als, ps)
    assert route_db_summary(db) == route_db_summary(oracle)


def test_fleet_ineligible_on_ksp2():
    ls, ps = build_world()
    ps.update_prefix(
        "node2",
        "0",
        PrefixEntry(
            "10.250.0.0/24",
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        ),
    )
    eng = FleetRibEngine(SpfSolver("node0"))
    assert not eng.eligible({"0": ls}, ps, change_seq=1)


def test_decision_actor_fleet_summary():
    """Through the Decision actor: compute_route_db_for_node uses the
    fleet engine (one batch, many decodes) and the fleet summary reports
    every node."""
    ls, ps = build_world()
    clock = SimClock()
    solver = SpfSolver("node0")
    d = Decision(
        "node0",
        clock,
        DecisionConfig(),
        ReplicateQueue("routes"),
        backend=TpuBackend(solver),
        solver=solver,
    )
    d.area_link_states = {"0": ls}
    d.prefix_state = ps
    for i in (0, 5, 15):
        dev = d.compute_route_db_for_node(f"node{i}")
        oracle = scalar_for(f"node{i}", d.area_link_states, d.prefix_state)
        assert route_db_summary(dev) == route_db_summary(oracle), i
    assert d._fleet_engine.num_batched_solves == 1
    summary = d.get_fleet_rib_summary()
    assert summary is not None and len(summary) == 16
    assert summary["node0"]["num_routes"] == len(
        scalar_for("node0", d.area_link_states, d.prefix_state).unicast_routes
    )


def test_fleet_summary_applies_v4_gate():
    """Summary counts must match the decoded RouteDbs when v4 is
    disabled (code-review regression: the v4 family gate applies to
    counts too)."""
    ls, ps = build_world()
    als = {"0": ls}
    solver = SpfSolver("node0", enable_v4=False, v4_over_v6_nexthop=False)
    eng = FleetRibEngine(solver)
    summary = eng.fleet_summary(als, ps, change_seq=1)
    db = eng.compute_for_node("node0", als, ps, change_seq=1)
    assert summary["node0"]["num_routes"] == len(db.unicast_routes)
    # only the single v6 prefix survives the gate (advertised by node7)
    assert summary["node0"]["num_routes"] == 1


def test_scalar_backend_never_touches_fleet_engine():
    from openr_tpu.decision.backend import ScalarBackend

    ls, ps = build_world()
    clock = SimClock()
    solver = SpfSolver("node0")
    d = Decision(
        "node0",
        clock,
        DecisionConfig(),
        ReplicateQueue("routes"),
        backend=ScalarBackend(solver),
        solver=solver,
    )
    d.area_link_states = {"0": ls}
    d.prefix_state = ps
    assert d.get_fleet_rib_summary() is None
    d.compute_route_db_for_node("node3")  # scalar path
    assert d._fleet_engine is None  # engine never even constructed


def test_fleet_multi_area_parity_every_vantage():
    """Two areas joined by a border: EVERY vantage node (incl. ones
    absent from one area — the KeyError the ctrl drive caught) must
    decode to the scalar oracle's RouteDb, and summary counts must match
    the decoded tables."""
    from openr_tpu.emulation.topology import ring_edges

    def mk_ls(edges, area):
        ls = LinkState(area)
        for db in build_adj_dbs(edges, area=area).values():
            ls.update_adjacency_database(db)
        return ls

    als = {
        "1": mk_ls(grid_edges(3), "1"),
        "2": mk_ls(ring_edges(6, prefix="b") + [("b0", "node0", 1)], "2"),
    }
    ps = PrefixState()
    ps.update_prefix("node8", "1", PrefixEntry("10.0.0.0/24"))
    ps.update_prefix("b3", "2", PrefixEntry("10.1.0.0/24"))
    ps.update_prefix("b4", "2", PrefixEntry("10.2.0.0/24"))
    # anycast ACROSS areas exercises the cross-area min-metric merge
    ps.update_prefix("node2", "1", PrefixEntry("10.77.0.0/24"))
    ps.update_prefix("b2", "2", PrefixEntry("10.77.0.0/24"))

    eng = FleetRibEngine(SpfSolver("node0"))
    assert eng.eligible(als, ps, change_seq=1)
    summary = eng.fleet_summary(als, ps, change_seq=1)
    names = sorted(summary)
    assert len(names) == 15  # 9 grid + 6 ring (node0 in both)
    for name in names:
        dev = eng.compute_for_node(name, als, ps, change_seq=1)
        oracle = SpfSolver(name).build_route_db(als, ps)
        assert route_db_summary(dev) == route_db_summary(oracle), name
        assert summary[name]["num_routes"] == len(oracle.unicast_routes), name
    assert eng.num_batched_solves == 1


def test_fleet_summary_min_nexthop_gates_winners_only():
    """A LOSING advertiser's min_nexthop requirement must not gate the
    winner's route in the summary counts (code-review repro: node8
    advertises with min_nexthop=4 but loses selection to node0)."""
    ls, _ = build_world()
    als = {"0": ls}
    ps = PrefixState()
    ps.update_prefix("node8", "0", PrefixEntry(
        "10.50.0.0/24", min_nexthop=4,
        metrics=PrefixMetrics(path_preference=100)))
    ps.update_prefix("node3", "0", PrefixEntry(
        "10.50.0.0/24", metrics=PrefixMetrics(path_preference=200)))
    eng = FleetRibEngine(SpfSolver("node0"))
    summary = eng.fleet_summary(als, ps, change_seq=1)
    for name in ("node0", "node15"):
        oracle = SpfSolver(name).build_route_db(als, ps)
        db = eng.compute_for_node(name, als, ps, change_seq=1)
        assert route_db_summary(db) == route_db_summary(oracle), name
        assert summary[name]["num_routes"] == len(oracle.unicast_routes), (
            name, summary[name])
    # the winner (node3) has no min-nexthop requirement: route exists
    assert summary["node0"]["num_routes"] == 1
