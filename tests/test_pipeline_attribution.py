"""Pipeline attribution (ISSUE 7 tentpole): the phase registry, the
PipelineProbe, the instrumented dispatch lifecycle in the Decision
backend and the fleet/what-if engines, per-chip busy gauges, and the
per-device Chrome-trace lanes."""

import pytest

from openr_tpu.common.runtime import CounterMap, SimClock
from openr_tpu.config import ParallelConfig, ResilienceConfig
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, ring_edges
from openr_tpu.tracing import PipelineProbe, Tracer, disabled_probe, pipeline
from openr_tpu.types import PrefixEntry

pytestmark = pytest.mark.multichip


def make_world(n=12):
    ls = LinkState("0")
    for db in build_adj_dbs(ring_edges(n)).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(n):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.7.{i}.0/24"))
    return {"0": ls}, ps


def make_backend(clock=None, counters=None, tracer=None):
    from openr_tpu.decision.backend import TpuBackend

    return TpuBackend(
        SpfSolver("node0"),
        clock=clock,
        counters=counters,
        tracer=tracer,
        resilience=ResilienceConfig(enabled=False),
        parallel=ParallelConfig(min_shard_rows=0),
    )


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


def test_registry_names_and_validation():
    assert pipeline.span_name(pipeline.DECODE) == "pipeline.decode"
    assert pipeline.hist_key(pipeline.HOST_FETCH) == "pipeline.host_fetch.ms"
    with pytest.raises(ValueError):
        pipeline.span_name("decod")
    with pytest.raises(ValueError):
        pipeline.hist_key("not_a_phase")
    # host/device split covers the registry exactly, with no overlap
    assert set(pipeline.HOST_PHASES) | set(pipeline.DEVICE_PHASES) == set(
        pipeline.PHASES
    )
    assert not set(pipeline.HOST_PHASES) & set(pipeline.DEVICE_PHASES)


def test_warm_phases_registered():
    """ISSUE-9 satellite: the warm-start rebuild phases are first-class
    registry members (hist/span spellings come from the registry, the
    host/device split covers them, and the cold-lifecycle bench treats
    them as optional coverage via WARM_PHASES)."""
    assert pipeline.WARM_PLAN in pipeline.PHASES
    assert pipeline.WARM_REPAIR in pipeline.PHASES
    assert set(pipeline.WARM_PHASES) == {
        pipeline.WARM_PLAN, pipeline.WARM_REPAIR
    }
    assert pipeline.span_name(pipeline.WARM_REPAIR) == "pipeline.warm_repair"
    assert pipeline.hist_key(pipeline.WARM_PLAN) == "pipeline.warm_plan.ms"
    assert pipeline.WARM_PLAN in pipeline.HOST_PHASES
    assert pipeline.WARM_REPAIR in pipeline.DEVICE_PHASES


def test_warm_rebuild_records_warm_phases():
    """A warm generation-delta rebuild lands samples under BOTH warm
    phases (plus the shared lifecycle phases), so BENCH_PIPELINE-style
    attribution stays fully explained on warm ticks."""
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.emulation.topology import build_adj_dbs

    clock = SimClock()
    counters = CounterMap()
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.spf_solver import SpfSolver

    backend = TpuBackend(
        SpfSolver("node0"),
        clock=clock,
        counters=counters,
        resilience=ResilienceConfig(enabled=False),
        parallel=ParallelConfig(max_devices=1),
    )
    adj = build_adj_dbs(ring_edges(8))
    ls = LinkState("0", "node0")
    for db in adj.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(8):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.9.{i}.0/24"))
    als = {"0": ls}
    backend.build_route_db(als, ps, force_full=True)
    for phase in pipeline.WARM_PHASES:
        h = counters.histogram(pipeline.hist_key(phase))
        assert h is None or h.count == 0  # cold build: no warm samples
    adj["node2"].adjacencies[0].metric = 5
    ls.update_adjacency_database(adj["node2"])
    backend.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True, warm_delta=True
    )
    assert backend.num_warm_builds == 1
    for phase in pipeline.WARM_PHASES:
        h = counters.histogram(pipeline.hist_key(phase))
        assert h is not None and h.count >= 1, phase


def test_stream_phases_registered():
    """ISSUE-11 satellite: the streaming phases are first-class registry
    members — stream_drain is device time (the host blocked on ONE
    chip's in-flight shard), device_select is the on-device
    delta-extraction dispatch, and the bench treats DELTA_PHASES as
    optional coverage exactly like WARM_PHASES."""
    assert pipeline.STREAM_DRAIN in pipeline.PHASES
    assert pipeline.DEVICE_SELECT in pipeline.PHASES
    assert pipeline.DELTA_PHASES == (pipeline.DEVICE_SELECT,)
    assert pipeline.span_name(pipeline.STREAM_DRAIN) == "pipeline.stream_drain"
    assert pipeline.hist_key(pipeline.DEVICE_SELECT) == (
        "pipeline.device_select.ms"
    )
    assert pipeline.STREAM_DRAIN in pipeline.DEVICE_PHASES
    assert pipeline.DEVICE_SELECT in pipeline.DEVICE_PHASES


def test_streamed_build_attributes_drain_to_completing_chip():
    """Each stream_drain span carries exactly ONE device attr (the
    completing chip) — never the whole in-flight set the old device_get
    barrier charged."""
    als, ps = make_world()
    clock = SimClock()
    counters = CounterMap()
    tracer = Tracer("node0", clock=clock, counters=counters)
    backend = make_backend(clock, counters, tracer)
    backend.build_route_db(als, ps)
    drains = [
        s for s in tracer.get_spans() if s.name == "pipeline.stream_drain"
    ]
    assert drains, "streamed build recorded no stream_drain spans"
    plan_devs = {d for d, _lo, _hi in backend._attr_plan}
    assert {s.attrs["device"] for s in drains} == plan_devs
    # one drain window per shard; busy ledger covers every planned chip
    assert len(drains) == len(plan_devs)


def test_device_gauge_keys():
    assert pipeline.device_busy_key(3) == "pipeline.dev3.busy_ms"
    assert pipeline.device_utilization_key(0) == "pipeline.dev0.utilization"


# ---------------------------------------------------------------------------
# the probe
# ---------------------------------------------------------------------------


def test_disabled_probe_is_a_noop():
    probe = disabled_probe()
    assert not probe.enabled
    with probe.phase(pipeline.ENCODE) as scope:
        assert scope is None
    assert probe.gauges() == {}
    # clock-less explicit construction is also disabled
    assert not PipelineProbe(counters=CounterMap()).enabled


def test_probe_records_histograms_spans_and_busy():
    clock = SimClock()
    counters = CounterMap()
    tracer = Tracer("node0", clock=clock, counters=counters)
    probe = PipelineProbe(clock, counters, tracer)
    with probe.phase(pipeline.ENCODE):
        pass
    with probe.phase(pipeline.DEVICE_COMPUTE, device=2):
        clock._now += 0.005  # 5 virtual ms inside the phase
    with probe.phase(pipeline.DEVICE_GET, devices=[2, 5]):
        clock._now += 0.001
    h = counters.histogram(pipeline.hist_key(pipeline.ENCODE))
    assert h is not None and h.count == 1
    h2 = counters.histogram(pipeline.hist_key(pipeline.DEVICE_COMPUTE))
    assert h2 is not None and h2.total == pytest.approx(5.0)
    # spans: named pipeline.{phase}, chip-attributed where applicable
    names = [s.name for s in tracer.get_spans()]
    assert "pipeline.encode" in names and "pipeline.device_compute" in names
    dc = [s for s in tracer.get_spans() if s.name == "pipeline.device_compute"]
    assert dc[0].attrs["device"] == 2
    # busy ledger: the committed dispatch charged dev2; the blocking
    # drain charged both chips it covered
    busy = probe.busy_snapshot()
    assert busy[2] == pytest.approx(6.0)
    assert busy[5] == pytest.approx(1.0)
    gauges = probe.gauges()
    assert pipeline.device_busy_key(2) in gauges
    assert 0.0 <= gauges[pipeline.device_utilization_key(2)] <= 1.0


def test_probe_phase_records_error_attr():
    clock = SimClock()
    tracer = Tracer("node0", clock=clock)
    probe = PipelineProbe(clock, CounterMap(), tracer)
    with pytest.raises(RuntimeError):
        with probe.phase(pipeline.DECODE):
            raise RuntimeError("boom")
    sp = tracer.get_spans()[-1]
    assert sp.name == "pipeline.decode" and sp.attrs["error"] == "RuntimeError"


def test_probe_without_tracer_still_observes():
    clock = SimClock()
    counters = CounterMap()
    probe = PipelineProbe(clock, counters)
    with probe.phase(pipeline.TRANSFER):
        pass
    assert counters.histogram(pipeline.hist_key(pipeline.TRANSFER)).count == 1


# ---------------------------------------------------------------------------
# the instrumented backend
# ---------------------------------------------------------------------------


def test_sharded_full_build_attributes_every_phase_and_chip():
    als, ps = make_world()
    clock = SimClock()
    counters = CounterMap()
    tracer = Tracer("node0", clock=clock, counters=counters)
    backend = make_backend(clock, counters, tracer)
    assert backend.probe.enabled
    db = backend.build_route_db(als, ps)
    assert db is not None and db.unicast_routes
    # every lifecycle phase of a sharded full build recorded samples
    for phase in (
        pipeline.HOST_FETCH,
        pipeline.ENCODE,
        pipeline.PAD_PACK,
        pipeline.TRANSFER,
        pipeline.DEVICE_COMPUTE,
        pipeline.DEVICE_GET,
        pipeline.DECODE,
    ):
        h = counters.histogram(pipeline.hist_key(phase))
        assert h is not None and h.count >= 1, phase
    # device_compute samples are chip-attributed spans; the shard plan's
    # chips and the span-attributed chips agree
    plan_devs = {d for d, _lo, _hi in backend._attr_plan}
    span_devs = {
        s.attrs["device"]
        for s in tracer.get_spans()
        if s.name == "pipeline.device_compute" and "device" in s.attrs
    }
    assert span_devs == plan_devs and len(plan_devs) > 1
    # the pool counted one committed dispatch per planned shard
    for d in plan_devs:
        assert backend.pool.num_dispatches[d] == 1
    # per-chip busy gauges exist for every dispatched chip
    gauges = backend.probe.gauges()
    for d in plan_devs:
        assert pipeline.device_busy_key(d) in gauges
    # pool counter_snapshot exports the per-chip dispatch tallies
    snap = backend.counter_snapshot()
    assert snap["decision.backend.pool.dev0.dispatches"] >= 1.0


def test_kernel_spans_carry_the_dispatch_device():
    """`decision.spf_kernel` spans inside a traced build inherit the
    pool chip from the per-shard dispatch loop (jit_guard.dispatch_device)
    — the Chrome-trace chip lanes depend on it."""
    from openr_tpu.ops import jit_guard

    als, ps = make_world()
    clock = SimClock()
    counters = CounterMap()
    tracer = Tracer("node0", clock=clock, counters=counters)
    backend = make_backend(clock, counters, tracer)
    with jit_guard.trace_scope(tracer, None):
        backend.build_route_db(als, ps)
    kernel_devs = {
        s.attrs.get("device")
        for s in tracer.get_spans()
        if s.name == "decision.spf_kernel"
    }
    # the SPF-tables build is unattributed (replicated input), but every
    # selection shard dispatch carries its chip
    assert len(kernel_devs - {None}) > 1


def test_incremental_gather_attributes_the_lead_chip():
    als, ps = make_world()
    clock = SimClock()
    counters = CounterMap()
    backend = make_backend(clock, counters)
    backend.build_route_db(als, ps)
    before = list(backend.pool.num_dispatches)
    ps.update_prefix("node3", "0", PrefixEntry("10.99.3.0/24"))
    db = backend.build_route_db(
        als, ps, changed_prefixes={"10.99.3.0/24"}
    )
    assert db is not None
    after = backend.pool.num_dispatches
    assert sum(after) == sum(before) + 1  # ONE chip rode the gather
    h = counters.histogram(pipeline.hist_key(pipeline.DELTA_EXTRACT))
    assert h is not None and h.count >= 1  # the patch path is the tail


# ---------------------------------------------------------------------------
# the engines share the ledger
# ---------------------------------------------------------------------------


def test_fleet_engine_records_phases_on_the_shared_probe():
    from openr_tpu.decision.fleet import FleetRibEngine

    als, ps = make_world()
    clock = SimClock()
    counters = CounterMap()
    backend = make_backend(clock, counters)
    pool = backend.dispatch_pool()
    assert pool is not None
    eng = FleetRibEngine(SpfSolver("node0"), pool=pool, probe=backend.probe)
    summary = eng.fleet_summary(als, ps, change_seq=1)
    assert len(summary) == 12
    for phase in (
        pipeline.ENCODE,
        pipeline.HOST_FETCH,
        pipeline.PAD_PACK,
        pipeline.DEVICE_COMPUTE,
        pipeline.DEVICE_GET,
    ):
        h = counters.histogram(pipeline.hist_key(phase))
        assert h is not None and h.count >= 1, phase
    # root chunks spread over the pool and were tallied there
    assert sum(pool.num_dispatches) == eng.num_pool_dispatches > 0
    db = eng.compute_for_node("node5", als, ps, change_seq=1)
    assert db is not None
    assert counters.histogram(pipeline.hist_key(pipeline.DECODE)).count >= 1


def test_whatif_engine_records_phases_on_the_shared_probe():
    from openr_tpu.decision.whatif_api import MultiAreaWhatIfEngine

    als, ps = make_world()
    clock = SimClock()
    counters = CounterMap()
    backend = make_backend(clock, counters)
    pool = backend.dispatch_pool()
    eng = MultiAreaWhatIfEngine(
        SpfSolver("node0"), pool=pool, probe=backend.probe
    )
    failures = [(f"node{i}", f"node{i + 1}") for i in range(8)]
    result = eng.run(failures, als, ps, change_seq=1)
    assert result["eligible"] and len(result["failures"]) == 8
    for phase in (
        pipeline.PAD_PACK,
        pipeline.TRANSFER,
        pipeline.DEVICE_COMPUTE,
        pipeline.DEVICE_GET,
        pipeline.DECODE,
    ):
        h = counters.histogram(pipeline.hist_key(phase))
        assert h is not None and h.count >= 1, phase
    assert sum(pool.num_dispatches) == eng.num_pool_dispatches > 0


def test_decision_hands_engines_the_backend_probe():
    from openr_tpu.common.runtime import SimClock as SC
    from openr_tpu.config import DecisionConfig
    from openr_tpu.decision.decision import Decision
    from openr_tpu.messaging.queue import ReplicateQueue

    als, ps = make_world()
    clock = SC()
    solver = SpfSolver("node0")
    backend = make_backend(clock, CounterMap())
    d = Decision(
        "node0",
        clock,
        DecisionConfig(),
        ReplicateQueue("routes"),
        backend=backend,
        solver=solver,
    )
    d.area_link_states = als
    d.prefix_state = ps
    d._change_seq = 1
    assert d._backend_probe() is backend.probe
    assert d._fleet().probe is backend.probe


# ---------------------------------------------------------------------------
# per-device Chrome-trace lanes (satellite)
# ---------------------------------------------------------------------------


def test_chrome_trace_emits_per_device_lanes():
    from openr_tpu.tracing import chrome_trace_events

    clock = SimClock()
    tracer = Tracer("node0", clock=clock)
    s0 = tracer.start_span("decision.spf_kernel", module="decision", device=0)
    tracer.end_span(s0)
    s1 = tracer.start_span("decision.spf_kernel", module="decision", device=3)
    tracer.end_span(s1)
    s2 = tracer.start_span("resilience.probe", module="resilience", device=3)
    tracer.end_span(s2)
    s3 = tracer.start_span("decision.rebuild", module="decision")
    tracer.end_span(s3)
    events = chrome_trace_events(tracer.get_spans())
    threads = {
        e["args"]["name"]: (e["pid"], e["tid"])
        for e in events
        if e.get("name") == "thread_name" and e.get("ph") == "M"
    }
    # chip-attributed spans get one lane per (module, chip); the plain
    # rebuild span stays on the module lane
    assert "decision.dev0" in threads and "decision.dev3" in threads
    assert "resilience.dev3" in threads and "decision" in threads
    assert threads["decision.dev0"][1] != threads["decision.dev3"][1]
    lane_of = {}
    for e in events:
        if e.get("ph") == "X":
            lane_of.setdefault((e["pid"], e["tid"]), []).append(e["name"])
    # the two kernel spans on different chips landed on different lanes
    kernel_lanes = [
        lane for lane, names in lane_of.items()
        if "decision.spf_kernel" in names
    ]
    assert len(kernel_lanes) == 2
