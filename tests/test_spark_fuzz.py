"""Spark wire-codec fuzzing — corpus + mutation over the real parsers.

Reference parity: Spark::setThrowParserErrors (Spark.h:88,582-584) lets a
fuzzer surface parse exceptions as crashes; production swallows + counts
them.  Three layers here:

  1. a hand-built corpus of hostile payload dicts through the REAL
     ingress path (`Spark._on_packet` — rate limit, _unpack, FSM
     dispatch) with the throw hook off: nothing may escape, every reject
     is counted, and the neighbor table stays sane
  2. the throw hook on: a malformed packet must RAISE (the fuzzer's
     crash signal)
  3. seeded random mutation of valid wire datagrams through the REAL
     UDP JSON codec boundary (json.loads + _unpack exactly as
     UdpIoProvider.recvmsg does): ~500 mutants, no crash, bounded
     rejects

Plus: a parser crash must not kill the ingress — a valid neighbor
established BEFORE a malformed flood must still be ESTABLISHED after.
"""

import dataclasses
import json
import random

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.spark.spark import Spark, _pack, _unpack
from openr_tpu.types import SparkNeighState

from test_spark import Rig, fast_config, run, wire  # noqa: E402


def valid_hello_payload(node="evil", seq=1):
    return {
        "kind": "SparkHelloMsg",
        "body": {
            "node_name": node,
            "if_name": "if1",
            "seq_num": seq,
            "neighbor_infos": {},
            "version": 20240101,
            "solicit_response": False,
            "restarting": False,
            "sent_ts_us": 1,
        },
    }


#: hand-built hostile corpus (the shapes a fuzzer finds first)
CORPUS = [
    {},  # empty
    {"kind": "SparkHelloMsg"},  # no body
    {"kind": "NoSuchMsg", "body": {}},  # unknown kind
    {"kind": "SparkHelloMsg", "body": {}},  # missing every field
    {"kind": "SparkHelloMsg", "body": None},  # body wrong type
    {"kind": None, "body": {}},  # kind wrong type
    {"kind": ["SparkHelloMsg"], "body": {}},  # kind unhashable-ish
    {  # neighbor_infos wrong shape
        **valid_hello_payload(),
        "body": {**valid_hello_payload()["body"], "neighbor_infos": [1, 2]},
    },
    {  # neighbor_infos values wrong shape
        **valid_hello_payload(),
        "body": {
            **valid_hello_payload()["body"],
            "neighbor_infos": {"x": {"bogus_field": 1}},
        },
    },
    {  # unexpected extra field
        **valid_hello_payload(),
        "body": {**valid_hello_payload()["body"], "extra": "field"},
    },
    {  # hostile values in well-formed fields (process-stage, not parse)
        **valid_hello_payload(),
        "body": {**valid_hello_payload()["body"], "seq_num": "NaN"},
    },
    {
        **valid_hello_payload(),
        "body": {**valid_hello_payload()["body"], "sent_ts_us": "yesterday"},
    },
    {
        "kind": "SparkHandshakeMsg",
        "body": {"node_name": "evil", "area": {"nested": "dict"}},
    },
    {
        "kind": "SparkHeartbeatMsg",
        "body": {"node_name": "evil", "seq_num": None, "hold_time_s": -1e308},
    },
]


def make_spark(clock):
    from openr_tpu.spark.io_provider import MockIoProvider

    io = MockIoProvider(clock)
    q = ReplicateQueue("fuzz.neighborEvents")
    spark = Spark(
        node_name="victim",
        clock=clock,
        config=fast_config(),
        io=io,
        neighbor_updates_queue=q,
    )
    spark.start()
    return spark


def test_corpus_swallowed_and_counted():
    async def main():
        clock = SimClock()
        spark = make_spark(clock)
        from openr_tpu.types import InterfaceDatabase, InterfaceInfo

        spark._on_interface_db(
            InterfaceDatabase(
                interfaces={
                    "if1": InterfaceInfo(
                        if_name="if1", is_up=True, if_index=1,
                        networks=["fe80::1/64"],
                    )
                }
            )
        )
        for payload in CORPUS:
            await spark._on_packet("if1", payload, clock.now())
        errs = spark.counters.get("spark.packet_parse_error") or 0
        perrs = spark.counters.get("spark.packet_process_error") or 0
        # 12 of 14 are rejected at parse; the two hostile-value payloads
        # (string seq/timestamp) parse into dataclasses and process
        # benignly — they must NOT establish anything
        assert errs + perrs == len(CORPUS) - 2, (errs, perrs)
        assert not spark.get_neighbors() or all(
            n.state != SparkNeighState.ESTABLISHED
            for n in spark.get_neighbors()
        )
        await spark.stop()

    run(main())


def test_throw_parser_errors_hook():
    async def main():
        clock = SimClock()
        spark = make_spark(clock)
        from openr_tpu.types import InterfaceDatabase, InterfaceInfo

        spark._on_interface_db(
            InterfaceDatabase(
                interfaces={
                    "if1": InterfaceInfo(
                        if_name="if1", is_up=True, if_index=1,
                        networks=["fe80::1/64"],
                    )
                }
            )
        )
        spark.set_throw_parser_errors(True)
        with pytest.raises(ValueError):
            await spark._on_packet("if1", {"kind": "Nope", "body": {}}, 0.0)
        with pytest.raises(TypeError):
            await spark._on_packet(
                "if1", {"kind": "SparkHelloMsg", "body": None}, 0.0
            )
        spark.set_throw_parser_errors(False)
        await spark._on_packet("if1", {"kind": "Nope", "body": {}}, 0.0)
        await spark.stop()

    run(main())


def test_established_neighbor_survives_malformed_flood():
    """A real adjacency must hold while the victim is bombarded with the
    corpus + 200 random mutants on the same interface."""

    async def main():
        clock = SimClock()
        rig = Rig(clock, ["a", "b"])
        wire(rig, "a", "if1", "b", "if2")
        await clock.run_for(5.0)
        assert (
            rig.sparks["b"].get_neighbors()[0].state
            == SparkNeighState.ESTABLISHED
        )
        rng = random.Random(99)
        base = json.dumps(valid_hello_payload("a", seq=7))
        victim = rig.sparks["b"]
        for i in range(200):
            if i % 3 == 0:
                payload = CORPUS[i % len(CORPUS)]
            else:
                mutant = mutate(rng, base)
                try:
                    payload = json.loads(mutant)
                except ValueError:
                    continue  # UdpIoProvider would drop non-JSON
                if not isinstance(payload, dict):
                    continue
            await victim._on_packet("if2", payload, clock.now())
            # respect the 50pps token bucket so the flood isn't dropped
            # by rate limiting alone
            if i % 25 == 0:
                await clock.run_for(1.0)
        await clock.run_for(3.0)
        assert (
            rig.sparks["b"].get_neighbors()[0].state
            == SparkNeighState.ESTABLISHED
        ), "malformed flood broke a live adjacency"
        await rig.stop()

    run(main())


def mutate(rng: random.Random, text: str) -> str:
    """Random wire-level mutation: byte flips, truncation, duplication,
    token swaps — what a dumb fuzzer does to a captured datagram."""
    data = bytearray(text.encode())
    op = rng.random()
    if op < 0.4:  # flip bytes
        for _ in range(rng.randint(1, 8)):
            data[rng.randrange(len(data))] = rng.randrange(256)
    elif op < 0.6:  # truncate
        del data[rng.randrange(1, len(data)) :]
    elif op < 0.8:  # duplicate a slice
        i = rng.randrange(len(data))
        j = rng.randrange(i, len(data))
        data[i:i] = data[i:j]
    else:  # token swap
        return (
            text.replace(rng.choice(['"', ":", "{", "}"]), "", 1)
            .replace("SparkHelloMsg", rng.choice(["", "X" * 1000, "null"]))
        )
    return data.decode(errors="replace")


def test_mutation_fuzz_real_codec():
    """500 seeded mutants through the exact UdpIoProvider decode chain
    (json.loads -> Spark._on_packet): no exception escapes, and every
    fully-parsed-but-rejected packet is visible in counters."""

    async def main():
        clock = SimClock()
        spark = make_spark(clock)
        from openr_tpu.types import InterfaceDatabase, InterfaceInfo

        spark._on_interface_db(
            InterfaceDatabase(
                interfaces={
                    "if1": InterfaceInfo(
                        if_name="if1", is_up=True, if_index=1,
                        networks=["fe80::1/64"],
                    )
                }
            )
        )
        rng = random.Random(1234)
        base = json.dumps(valid_hello_payload())
        delivered = 0
        for i in range(500):
            mutant = mutate(rng, base)
            try:
                payload = json.loads(mutant)
            except ValueError:
                continue  # the UDP provider drops non-JSON datagrams
            if not isinstance(payload, dict):
                continue
            await spark._on_packet("if1", payload, clock.now())
            delivered += 1
            if i % 40 == 0:
                await clock.run_for(1.0)  # refill the 50pps bucket
        assert delivered > 20, "mutation corpus never reached the parser"
        # round-trip sanity: the unmutated base must still parse
        assert _unpack(json.loads(base)).node_name == "evil"
        await spark.stop()

    run(main())


def test_pack_unpack_roundtrip_all_kinds():
    """Every message kind survives its own wire round trip (the property
    the fuzzer is probing the edges of)."""
    from openr_tpu.spark.spark import (
        SparkHandshakeMsg,
        SparkHeartbeatMsg,
        SparkHelloMsg,
    )

    msgs = [
        SparkHelloMsg(
            node_name="n1", if_name="if1", seq_num=5, neighbor_infos={},
            version=1, solicit_response=True, restarting=False, sent_ts_us=9,
        ),
        SparkHandshakeMsg(
            node_name="n1",
            is_adj_established=True,
            hold_time_ms=30_000,
            graceful_restart_time_ms=30_000,
        ),
        SparkHeartbeatMsg(node_name="n1", seq_num=2),
    ]
    for msg in msgs:
        wire_form = json.loads(json.dumps(_pack(msg), default=str))
        assert _unpack(wire_form) == dataclasses.replace(msg)
