"""SimClock, Actor, backoff/debounce/throttle/step-detector tests
(reference behavior: openr/common/tests/*)."""

import asyncio

from openr_tpu.common.runtime import Actor, CounterMap, SimClock
from openr_tpu.common.utils import (
    AsyncDebounce,
    AsyncThrottle,
    ExponentialBackoff,
    StepDetector,
)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_simclock_orders_sleepers():
    async def main():
        clock = SimClock()
        order = []

        async def sleeper(tag, dt):
            await clock.sleep(dt)
            order.append((tag, clock.now()))

        t1 = asyncio.ensure_future(sleeper("b", 2.0))
        t2 = asyncio.ensure_future(sleeper("a", 1.0))
        await clock.run_for(3.0)
        assert order == [("a", 1.0), ("b", 2.0)]
        assert clock.now() == 3.0
        await t1
        await t2

    run(main())


def test_simclock_chained_sleeps():
    async def main():
        clock = SimClock()
        ticks = []

        async def ticker():
            for _ in range(5):
                await clock.sleep(1.0)
                ticks.append(clock.now())

        t = asyncio.ensure_future(ticker())
        await clock.run_for(10.0)
        assert ticks == [1.0, 2.0, 3.0, 4.0, 5.0]
        await t

    run(main())


def test_actor_schedule_and_stop():
    async def main():
        clock = SimClock()
        a = Actor("mod", clock)
        fired = []
        a.schedule(5.0, lambda: fired.append(clock.now()))
        await clock.run_for(4.0)
        assert fired == []
        await clock.run_for(2.0)
        assert fired == [5.0]
        await a.stop()

    run(main())


def test_exponential_backoff_doubles_and_resets():
    clock = SimClock()
    b = ExponentialBackoff(0.064, 8.192, clock)
    assert b.can_try_now()
    b.report_error()
    assert b.get_current_backoff() == 0.064
    b.report_error()
    b.report_error()
    assert b.get_current_backoff() == 0.256
    assert not b.can_try_now()
    for _ in range(10):
        b.report_error()
    assert b.at_max_backoff()
    assert b.get_current_backoff() == 8.192
    b.report_success()
    assert b.can_try_now()
    assert b.get_current_backoff() == 0.0


def test_backoff_time_remaining_advances_with_clock():
    async def main():
        clock = SimClock()
        b = ExponentialBackoff(1.0, 8.0, clock)
        b.report_error()
        assert abs(b.time_remaining_until_retry() - 1.0) < 1e-9
        await clock.run_for(0.5)
        assert abs(b.time_remaining_until_retry() - 0.5) < 1e-9
        await clock.run_for(1.0)
        assert b.can_try_now()

    run(main())


def test_async_throttle_coalesces():
    async def main():
        clock = SimClock()
        a = Actor("m", clock)
        calls = []
        th = AsyncThrottle(a, 1.0, lambda: calls.append(clock.now()))
        th()
        th()
        th()
        assert th.is_active()
        await clock.run_for(1.5)
        assert calls == [1.0]  # three invocations -> one call
        th()
        await clock.run_for(1.5)
        assert calls == [1.0, 2.5]
        await a.stop()

    run(main())


def test_async_debounce_backs_off_and_fires_once():
    async def main():
        clock = SimClock()
        a = Actor("m", clock)
        calls = []
        db = AsyncDebounce(a, 0.010, 0.250, lambda: calls.append(clock.now()))
        # rapid-fire invocations double the hold-off: 10ms, 20ms, 40ms...
        db()
        assert db.is_scheduled()
        await clock.run_for(0.005)
        db()  # reschedules to now+20ms
        await clock.run_for(0.015)
        assert calls == []  # original 10ms deadline was superseded
        await clock.run_for(0.010)
        assert calls == [0.025]
        # after firing, backoff resets to min
        db()
        await clock.run_for(0.010)
        assert len(calls) == 2
        await a.stop()

    run(main())


def test_async_debounce_max_backoff_still_fires():
    async def main():
        clock = SimClock()
        a = Actor("m", clock)
        calls = []
        db = AsyncDebounce(a, 0.010, 0.250, lambda: calls.append(clock.now()))

        async def hammer():
            for _ in range(100):
                db()
                await clock.sleep(0.01)

        t = asyncio.ensure_future(hammer())
        await clock.run_for(2.0)
        # Max debounce is 250ms: invocations every 10ms for 1s must still
        # produce at least one call within the max window.
        assert calls and calls[0] <= 0.6
        await t
        await a.stop()

    run(main())


def test_counter_map():
    c = CounterMap()
    c.bump("decision.spf_runs")
    c.bump("decision.spf_runs", 2)
    c.set("kvstore.num_keys", 7)
    assert c.get("decision.spf_runs") == 3
    assert c.dump("decision") == {"decision.spf_runs": 3}


def test_step_detector_detects_step():
    steps = []
    sd = StepDetector(
        steps.append,
        fast_window_size=4,
        slow_window_size=16,
        lower_threshold_pct=2.0,
        upper_threshold_pct=5.0,
        abs_threshold=500.0,
    )
    for _ in range(20):
        sd.add_value(1000.0)
    assert steps == []  # stable signal -> no step
    for _ in range(30):
        sd.add_value(2000.0)
    assert steps, "large sustained change must be reported"
    assert abs(steps[0] - 2000.0) < 300


def test_step_detector_ignores_noise():
    steps = []
    sd = StepDetector(steps.append, fast_window_size=4, slow_window_size=16)
    vals = [1000, 1010, 995, 1005, 990, 1008, 1002, 997] * 8
    for v in vals:
        sd.add_value(float(v))
    assert steps == []


def test_actor_tasks_pruned_on_completion():
    async def main():
        clock = SimClock()
        a = Actor("m", clock)
        for _ in range(100):
            a.schedule(0.001, lambda: None)
        await clock.run_for(1.0)
        assert len(a._tasks) == 0  # completed timers must not accumulate
        await a.stop()

    run(main())


def test_persistent_compile_cache_gating(monkeypatch, tmp_path):
    """enable_persistent_compile_cache: OPENR_TPU_COMPILE_CACHE=off
    disables, an explicit path wins, and the virtual-CPU-mesh test mode
    (xla_force_host_platform_device_count) skips by default (cross-host
    XLA:CPU AOT reloads can warn or SIGILL)."""
    import openr_tpu.ops.platform_env as pe

    calls = []

    class FakeConfig:
        @staticmethod
        def update(k, v):
            calls.append((k, v))

    class FakeJax:
        config = FakeConfig()

    monkeypatch.setattr(pe, "_COMPILE_CACHE_ENABLED", False)
    import sys

    monkeypatch.setitem(sys.modules, "jax", FakeJax())

    # off
    monkeypatch.setenv("OPENR_TPU_COMPILE_CACHE", "off")
    pe.enable_persistent_compile_cache()
    assert not calls and not pe._COMPILE_CACHE_ENABLED

    # virtual-mesh mode skips when no explicit path
    monkeypatch.delenv("OPENR_TPU_COMPILE_CACHE", raising=False)
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    pe.enable_persistent_compile_cache()
    assert not calls and not pe._COMPILE_CACHE_ENABLED

    # explicit path wins even in virtual-mesh mode
    monkeypatch.setenv("OPENR_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    pe.enable_persistent_compile_cache()
    assert ("jax_compilation_cache_dir", str(tmp_path / "cc")) in calls
    assert pe._COMPILE_CACHE_ENABLED
    assert (tmp_path / "cc").is_dir()
    # idempotent
    n = len(calls)
    pe.enable_persistent_compile_cache()
    assert len(calls) == n
