"""Sweep → on-device route selection: delta-only pipeline parity.

The SweepRouteSelector must reproduce, for every snapshot, exactly the
route table a from-scratch scalar computation yields: selection chain
over the perturbed SPF (reach, preference tie-breaks, min-distance,
igp-tie ECMP lane union), with deltas fetched only for changed rows."""

import numpy as np

from openr_tpu.decision.link_state import LinkState
from openr_tpu.emulation.topology import (
    build_adj_dbs,
    grid_edges,
    random_connected_edges,
)
from openr_tpu.ops.csr import encode_link_state
from openr_tpu.ops.sweep_select import (
    SweepCandidates,
    SweepRouteDeltas,
    SweepRouteSelector,
)
from openr_tpu.ops.whatif import LinkFailureSweep

BIG = 3.0e38


def build_world(seed=3, n_nodes=48, n_links=96):
    edges = random_connected_edges(n_nodes, n_links, seed=seed)
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    return encode_link_state(ls)


def scalar_routes(topo, eng, cands, snapshot_fail):
    """Oracle: selection chain in numpy over a from-scratch solve."""
    from openr_tpu.ops.native_spf import NativeSpf

    native = NativeSpf(topo, "node0")
    native.solve(failed_link=int(snapshot_fail))
    dist = native.dist
    lanes = native.lanes_dense(eng.D)  # [V, D]

    P, C = cands.cand_node.shape
    valid = np.zeros(P, bool)
    metric = np.full(P, BIG, np.float32)
    out_lanes = np.zeros((P, eng.D), np.int8)
    for p in range(P):
        cand = [
            (int(cands.cand_node[p, c]))
            for c in range(C)
            if cands.cand_ok[p, c]
        ]
        reach = [n for n in cand if np.isfinite(dist[n])]
        if not reach:
            continue
        # equal preference attributes in these tests: all reachable win
        # selection; igp tie-break picks min-distance advertisers
        best = min(dist[n] for n in reach)
        winners = [n for n in reach if dist[n] == best]
        ln = np.zeros(eng.D, np.int8)
        for n in winners:
            ln |= lanes[n].astype(np.int8)
        if not ln.any():
            continue
        valid[p] = True
        metric[p] = best
        out_lanes[p] = ln
    return valid, metric, out_lanes


def test_sweep_route_deltas_match_scalar_oracle():
    topo = build_world()
    eng = LinkFailureSweep(topo, "node0")
    rng = np.random.default_rng(5)
    fails = rng.integers(-1, len(topo.links), size=96).astype(np.int32)

    # anycast pairs: prefix p advertised by node p AND node (p*7+13)%V
    V = topo.num_nodes
    a = np.arange(V, dtype=np.int32)
    b = (a * 7 + 13) % V
    cands = SweepCandidates(
        cand_node=np.stack([a, b], axis=1),
        cand_ok=np.ones((V, 2), bool),
        drain_metric=np.zeros((V, 2), np.int32),
        path_pref=np.zeros((V, 2), np.int32),
        source_pref=np.zeros((V, 2), np.int32),
        distance=np.zeros((V, 2), np.int32),
        min_nexthop=np.zeros((V, 2), np.int32),
    )
    sel = SweepRouteSelector(topo, "node0", cands, max_degree=eng.D)
    sweep = eng.run(fails, fetch=False)
    deltas = sel.run(sweep)
    assert isinstance(deltas, SweepRouteDeltas)
    assert deltas.fetch_bytes > 0

    for s in [0, 7, 23, 50, 95]:
        valid, metric, lanes = deltas.routes_of(s)
        ev, em, el = scalar_routes(topo, eng, cands, fails[s])
        assert np.array_equal(valid, ev), f"valid mismatch snapshot {s}"
        assert np.array_equal(metric[ev], em[ev]), f"metric snapshot {s}"
        assert np.array_equal(lanes[ev], el[ev]), f"lanes snapshot {s}"


def test_sweep_route_deltas_sparse():
    """Most single-link failures change few routes: the delta payload
    must be a small fraction of B x P, and off-DAG snapshots contribute
    zero deltas."""
    topo = build_world(seed=11)
    eng = LinkFailureSweep(topo, "node0")
    V = topo.num_nodes
    cands = SweepCandidates.single_advertiser(np.arange(V))
    sel = SweepRouteSelector(topo, "node0", cands, max_degree=eng.D)

    fails = np.arange(len(topo.links), dtype=np.int32)
    sweep = eng.run(fails, fetch=False)
    deltas = sel.run(sweep)
    B, P = len(fails), V
    assert 0 < deltas.num_deltas < 0.25 * B * P
    # off-DAG snapshots alias the base row: zero deltas
    off_dag = ~eng.on_dag_links()
    for s in np.nonzero(off_dag)[0][:5]:
        assert deltas.snap_row[s] == 0
        v, m, ln = deltas.routes_of(int(s))
        assert np.array_equal(v, deltas.base_valid)


def test_base_select_eager_workaround_regression():
    """Pin the jax-0.9.0 executable-cache corruption dodge (VERDICT r3
    weak #6): `_base_select` must run EAGER.  Minimal repro of the
    trigger: compile the fleet kernels FIRST, then build two selectors'
    base tables back to back — under a jitted wrapper the second build
    intermittently drew a corrupted cache entry ('Execution supplied 12
    buffers but compiled program expected 15').  This test (a) asserts
    the workaround is still in place (no jit cache on _base_select) and
    (b) drives the exact trigger sequence, asserting correct output
    either way, so removing the workaround while the bug persists fails
    here rather than in production sweeps.
    """
    import jax

    from openr_tpu.decision.fleet import FleetRibEngine
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.ops import sweep_select as ss
    from openr_tpu.types import PrefixEntry

    # (a) the workaround: _base_select must not be a jit wrapper
    assert not hasattr(ss._base_select, "lower"), (
        "_base_select is jitted again — only safe once the jax 0.9 "
        "executable-cache corruption (see its docstring) is fixed; "
        "re-verify with this test's trigger sequence before removing"
    )

    # (b) the trigger sequence: fleet kernels compile first...
    ls = LinkState("0")
    for db in build_adj_dbs(grid_edges(4)).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(16):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))
    als = {"0": ls}
    fleet = FleetRibEngine(SpfSolver("node0"))
    assert fleet.compute_for_node("node1", als, ps, change_seq=1) is not None

    # ...then two selector base-table builds back to back
    topo = encode_link_state(ls)
    for root in ("node0", "node1"):
        eng = LinkFailureSweep(topo, root)
        sel = SweepRouteSelector(
            topo,
            root,
            SweepCandidates.single_advertiser(np.arange(16)),
            max_degree=eng.D,
        )
        base_dist, base_nh = eng.base_solve()
        valid, metric, lanes = sel.base_routes(base_dist, base_nh)
        # correct output either way: metric == base distance for every
        # valid single-advertiser prefix, self-prefix invalid
        rid = topo.node_id(root)
        for p in range(16):
            if p == rid:
                assert not valid[p]
                continue
            assert valid[p], (root, p)
            assert metric[p] == base_dist[p], (root, p)


def test_sweep_fetch_is_one_round_trip_multi_chunk():
    """A multi-chunk sweep must cost ONE blocking device->host fetch
    (a single device_get over all chunk compactions overlaps every
    copy): per-chunk round trips were the e2e latency floor over a
    tunneled chip (~75 ms x chunks).  fetch_groups counts the blocking
    fetch rounds."""
    topo = build_world(seed=3)
    eng = LinkFailureSweep(topo, "node0", max_chunk=32)
    V = topo.num_nodes
    cands = SweepCandidates.single_advertiser(np.arange(V))
    sel = SweepRouteSelector(topo, "node0", cands, max_degree=eng.D)
    fails = np.arange(len(topo.links), dtype=np.int32)
    sweep = eng.run(fails, fetch=False)
    assert len(sweep.chunks) > 1, "test needs a multi-chunk sweep"
    deltas = sel.run(sweep)
    assert deltas.fetch_groups == 1
    # parity unaffected by the fused fetch
    v, m, ln = deltas.routes_of(0)
    ev, em, el = scalar_routes(topo, eng, cands, fails[0])
    assert np.array_equal(v, ev)


def test_pipelined_start_finish_matches_run():
    """The overlapped fetch path (start() + copy_to_host_async +
    finish()) must be byte-identical to the synchronous run(), including
    with several sweeps in flight — the steady-state what-if service
    keeps a pipeline of pending fetches so the tunnel round trip
    overlaps the next sweeps' SPF + selection."""
    topo = build_world(seed=11)
    eng = LinkFailureSweep(topo, "node0")
    V = topo.num_nodes
    cands = SweepCandidates.single_advertiser(np.arange(V))
    sel = SweepRouteSelector(topo, "node0", cands, max_degree=eng.D)
    rng = np.random.default_rng(5)
    sweeps = [
        rng.integers(0, len(topo.links), size=60).astype(np.int32)
        for _ in range(4)
    ]
    expected = [sel.run(eng.run(f, fetch=False)) for f in sweeps]
    # pipelined: all four in flight before the first finish
    pend = [sel.start(eng.run(f, fetch=False)) for f in sweeps]
    got = [p.finish() for p in pend]
    for e, g in zip(expected, got):
        assert np.array_equal(e.snap_row, g.snap_row)
        assert np.array_equal(e.delta_row, g.delta_row)
        assert np.array_equal(e.delta_prefix, g.delta_prefix)
        assert np.array_equal(e.delta_valid, g.delta_valid)
        assert np.array_equal(e.delta_metric, g.delta_metric)
        assert np.array_equal(e.delta_lanes, g.delta_lanes)
        assert g.fetch_groups == 1


def test_greedy_chunk_decomposition_covers_and_reuses_buckets():
    """_chunk_sizes must exactly cover the unique-solve count with
    bucket-sized chunks, largest first, with padding below the smallest
    bucket — 1125 uniques must NOT pad to a 4096 batch (3.6x wasted
    SPF+selection compute at the headline scale)."""
    topo = build_world(seed=3)
    eng = LinkFailureSweep(topo, "node0")
    assert eng._chunk_sizes(1125) == [1024, 64, 64]
    assert eng._chunk_sizes(64) == [64]
    assert eng._chunk_sizes(1) == [64]
    assert eng._chunk_sizes(0) == []
    assert eng._chunk_sizes(4096) == [4096]
    assert eng._chunk_sizes(10240) == [4096, 4096, 2048]
    for n in (1, 63, 65, 1000, 5000, 12345):
        sizes = eng._chunk_sizes(n)
        assert sum(sizes) >= n
        assert sum(sizes) - n < 64  # waste below the smallest bucket
        assert all(s in eng.solve_buckets for s in sizes)


def test_pending_deltas_pin_their_base_across_engine_rebuilds():
    """A PendingDeltas started against base A must decode against base A
    even if the selector serves a rebuilt engine (base B) before
    finish() — the on-device diff ran against A, so patching B's table
    with A's deltas would corrupt every prefix that differs between the
    generations (review finding on the depth-N pipeline)."""
    edges_a = random_connected_edges(48, 96, seed=21)
    # generation B: same node table, one link metric bumped hard enough
    # to move base routes
    edges_b = [
        (u, v, (w + 900 if i == 0 else w))
        for i, (u, v, w) in enumerate(edges_a)
    ]

    def encode(edges):
        ls = LinkState("0")
        for db in build_adj_dbs(edges).values():
            ls.update_adjacency_database(db)
        return encode_link_state(ls)

    topo_a, topo_b = encode(edges_a), encode(edges_b)
    eng_a = LinkFailureSweep(topo_a, "node0")
    eng_b = LinkFailureSweep(topo_b, "node0")
    V = topo_a.num_nodes
    cands = SweepCandidates.single_advertiser(np.arange(V))
    sel = SweepRouteSelector(topo_a, "node0", cands, max_degree=eng_a.D)
    rng = np.random.default_rng(9)
    fails = rng.integers(0, len(topo_a.links), size=50).astype(np.int32)

    ref_sel = SweepRouteSelector(topo_a, "node0", cands, max_degree=eng_a.D)
    expected = ref_sel.run(eng_a.run(fails, fetch=False))

    pend = sel.start(eng_a.run(fails, fetch=False))
    sel.run(eng_b.run(fails, fetch=False))  # base B replaces sel._base
    got = pend.finish()
    assert np.array_equal(got.base_metric, expected.base_metric)
    assert np.array_equal(got.base_lanes, expected.base_lanes)
    for s in range(0, 50, 7):
        for e, g in zip(expected.routes_of(s), got.routes_of(s)):
            assert np.array_equal(e, g)
    # double-finish must fail loudly, not return "no changes"
    try:
        pend.finish()
    except RuntimeError:
        pass
    else:
        raise AssertionError("second finish() did not raise")
