"""Metrics export tier (ISSUE 7 tentpole): MetricsSnapshot capture,
Prometheus text-exposition render + strict parse round-trip, the
SimClock-deterministic JSONL writer, and the 9-node emulation
acceptance (pipeline histograms + per-device gauges + serving/
resilience counters all present and round-tripping)."""

import asyncio
import json

import pytest

from openr_tpu.common.runtime import CounterMap, SimClock
from openr_tpu.monitor.metrics import (
    NONDETERMINISTIC_PREFIXES,
    MetricsJsonlWriter,
    MetricsSnapshot,
    parse_prometheus,
    render_prometheus,
)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# snapshot capture
# ---------------------------------------------------------------------------


def make_counters():
    c = CounterMap()
    c.bump("decision.route_build_runs", 3)
    c.set("resilience.backend.quarantines", 1.0)
    c.set("process.memory.rss", 123456.0)
    c.observe("pipeline.decode.ms", 1.5)
    c.observe("pipeline.decode.ms", 40.0)
    c.observe("serving.queue_wait_ms", 0.2)
    return c


def test_capture_from_counter_map():
    clock = SimClock(5.0)
    snap = MetricsSnapshot.capture(
        counters=make_counters(), node_name="node0", clock=clock,
        generation=[7, [["0", 3]]],
    )
    assert snap.node == "node0" and snap.ts_ms == 5000
    assert snap.generation == [7, [["0", 3]]]
    assert snap.counters["decision.route_build_runs"] == 3
    h = snap.histograms["pipeline.decode.ms"]
    assert h["count"] == 2 and h["min"] == 1.5 and h["max"] == 40.0
    assert sum(c for _edge, c in h["buckets"]) == 2
    assert h["min_bound"] > 0 and h["num_buckets"] >= 1
    assert snap.env["python"]


def test_capture_exclusion_drops_nondeterministic_prefixes():
    snap = MetricsSnapshot.capture(
        counters=make_counters(), node_name="n", clock=SimClock(),
        exclude=NONDETERMINISTIC_PREFIXES,
    )
    assert "process.memory.rss" not in snap.counters
    assert "decision.route_build_runs" in snap.counters


def test_capture_requires_a_source():
    with pytest.raises(ValueError):
        MetricsSnapshot.capture()


# ---------------------------------------------------------------------------
# Prometheus text exposition: render + strict parse round-trip
# ---------------------------------------------------------------------------


def test_prometheus_round_trip_preserves_values():
    snap = MetricsSnapshot.capture(
        counters=make_counters(), node_name="node0", clock=SimClock()
    )
    text = render_prometheus([snap])
    parsed = parse_prometheus(text)
    g = parsed["openr_decision_route_build_runs"]
    assert g["type"] == "gauge"
    key = ("openr_decision_route_build_runs", ("node", "node0"))
    assert g["samples"][key] == 3.0
    hist = parsed["openr_pipeline_decode_ms"]
    assert hist["type"] == "histogram"
    count_key = ("openr_pipeline_decode_ms_count", ("node", "node0"))
    sum_key = ("openr_pipeline_decode_ms_sum", ("node", "node0"))
    assert hist["samples"][count_key] == 2.0
    assert hist["samples"][sum_key] == pytest.approx(41.5)
    # cumulative buckets end at the total count on the +Inf edge
    bucket_samples = [
        (labels, v)
        for (name, *labels), v in hist["samples"].items()
        if name == "openr_pipeline_decode_ms_bucket"
    ]
    assert bucket_samples
    cums = [v for _l, v in bucket_samples]
    assert cums == sorted(cums) and cums[-1] == 2.0


def test_prometheus_multi_node_groups_families():
    snaps = []
    for name in ("node0", "node1"):
        c = CounterMap()
        c.set("kvstore.keys", 4.0)
        snaps.append(
            MetricsSnapshot.capture(
                counters=c, node_name=name, clock=SimClock()
            )
        )
    text = render_prometheus(snaps)
    # one TYPE header, both nodes' samples under it
    assert text.count("# TYPE openr_kvstore_keys gauge") == 1
    parsed = parse_prometheus(text)
    samples = parsed["openr_kvstore_keys"]["samples"]
    assert ("openr_kvstore_keys", ("node", "node0")) in samples
    assert ("openr_kvstore_keys", ("node", "node1")) in samples


@pytest.mark.parametrize(
    "bad",
    [
        "openr_orphan 1.0\n",  # sample before its TYPE header
        "# TYPE openr_x gauge\nopenr_x{node=unquoted} 1\n",
        "# TYPE openr_x gauge\nopenr_x notafloat\n",
        "# TYPE openr_x\n",  # malformed header
        "# HELP openr_x\n",  # malformed HELP (no text)
        "# HELP openr_x a doc\nopenr_x 1\n",  # HELP alone opens no family
    ],
)
def test_prometheus_parser_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_prometheus(bad)


# ---------------------------------------------------------------------------
# ISSUE 8 satellites: per-device gauges promoted to ONE labeled family,
# and # HELP emission from the metric-description registry — both must
# survive the strict parser round trip
# ---------------------------------------------------------------------------


def test_device_gauges_render_as_one_labeled_family():
    from openr_tpu.tracing.pipeline import (
        device_busy_key,
        device_utilization_key,
    )

    c = CounterMap()
    for dev in range(3):
        c.set(device_busy_key(dev), 100.0 * (dev + 1))
        c.set(device_utilization_key(dev), 0.1 * (dev + 1))
    c.set("decision.backend.pool.dev1.dispatches", 7.0)
    c.set("resilience.backend.dev2.state", 1.0)
    c.set("decision.route_build_runs", 3.0)  # un-promoted control
    snap = MetricsSnapshot.capture(
        counters=c, node_name="node0", clock=SimClock()
    )
    text = render_prometheus([snap])
    # one TYPE header for the whole device family, not one per chip
    assert text.count("# TYPE openr_pipeline_device_busy_ms gauge") == 1
    assert "openr_pipeline_dev0_busy_ms" not in text
    parsed = parse_prometheus(text)
    busy = parsed["openr_pipeline_device_busy_ms"]["samples"]
    assert len(busy) == 3
    for dev in range(3):
        key = (
            "openr_pipeline_device_busy_ms",
            ("node", "node0"),
            ("device", str(dev)),
        )
        assert busy[key] == 100.0 * (dev + 1)
    pool = parsed["openr_decision_backend_pool_device_dispatches"]["samples"]
    assert pool[
        (
            "openr_decision_backend_pool_device_dispatches",
            ("node", "node0"),
            ("device", "1"),
        )
    ] == 7.0
    res = parsed["openr_resilience_backend_device_state"]["samples"]
    assert dict(list(res)[0][1:])["device"] == "2"
    # non-device keys untouched
    assert (
        "openr_decision_route_build_runs",
        ("node", "node0"),
    ) in parsed["openr_decision_route_build_runs"]["samples"]


def test_help_lines_emitted_and_preserved_by_parser():
    c = CounterMap()
    c.observe("convergence.event_to_fib_ms", 12.0)
    c.set("watchdog.crashes", 0.0)
    c.set("some.unknown.counter", 1.0)
    snap = MetricsSnapshot.capture(
        counters=c, node_name="n", clock=SimClock()
    )
    text = render_prometheus([snap])
    assert "# HELP openr_watchdog_crashes " in text
    # HELP precedes TYPE for the same family (exposition-format order)
    lines = text.splitlines()
    h = lines.index(
        "# HELP openr_convergence_event_to_fib_ms "
        "end-to-end convergence latency: origin event to FIB ack"
    )
    assert lines[h + 1].startswith(
        "# TYPE openr_convergence_event_to_fib_ms histogram"
    )
    parsed = parse_prometheus(text)
    assert parsed["openr_convergence_event_to_fib_ms"]["help"] == (
        "end-to-end convergence latency: origin event to FIB ack"
    )
    # an unregistered family renders with no HELP and no invented text
    assert "# HELP openr_some_unknown_counter" not in text
    assert "help" not in parsed["openr_some_unknown_counter"]
    # the alert-name registry feeds HELP for health.alert.* counters
    from openr_tpu.health.alerts import alert_counter_key

    c2 = CounterMap()
    c2.bump(alert_counter_key("chip_quarantine"))
    text2 = render_prometheus(
        [
            MetricsSnapshot.capture(
                counters=c2, node_name="n", clock=SimClock()
            )
        ]
    )
    assert "# HELP openr_health_alert_chip_quarantine " in text2


# ---------------------------------------------------------------------------
# JSONL writer
# ---------------------------------------------------------------------------


class _FakeNode:
    def __init__(self, name, counters, clock):
        self.name = name
        self.counters = counters
        self.clock = clock
        self.monitor = None


def test_jsonl_writer_one_sorted_line_per_node(tmp_path):
    clock = SimClock(1.0)
    nodes = [
        _FakeNode("b", make_counters(), clock),
        _FakeNode("a", make_counters(), clock),
    ]
    path = tmp_path / "metrics.jsonl"
    w = MetricsJsonlWriter(str(path))
    assert w.write_nodes(nodes) == 2
    lines = path.read_text().splitlines()
    assert [json.loads(ln)["node"] for ln in lines] == ["a", "b"]
    doc = json.loads(lines[0])
    assert doc["histograms"]["pipeline.decode.ms"]["count"] == 2


# ---------------------------------------------------------------------------
# SimClock determinism (satellite): two identical seeded emulation runs
# produce byte-identical JSONL snapshot files
# ---------------------------------------------------------------------------


async def _seeded_emulation_jsonl(path: str) -> bytes:
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import line_edges

    clock = SimClock()
    net = EmulatedNetwork(clock)
    net.build(line_edges(4))
    net.start()
    await clock.run_for(15.0)
    net.fail_link("node1", "node2")
    await clock.run_for(5.0)
    net.restore_link("node1", "node2")
    await clock.run_for(5.0)
    net.export_metrics_jsonl(path, exclude=NONDETERMINISTIC_PREFIXES)
    await net.stop()
    with open(path, "rb") as f:
        return f.read()


def test_two_seeded_runs_write_byte_identical_jsonl(tmp_path):
    a = run(_seeded_emulation_jsonl(str(tmp_path / "a.jsonl")))
    b = run(_seeded_emulation_jsonl(str(tmp_path / "b.jsonl")))
    assert a, "export wrote nothing"
    assert a == b
    # and it is real content: every node line parses with counters
    docs = [json.loads(ln) for ln in a.decode().splitlines()]
    assert [d["node"] for d in docs] == ["node0", "node1", "node2", "node3"]
    for d in docs:
        assert d["counters"] and d["generation"] is not None
        assert not any(
            k.startswith(NONDETERMINISTIC_PREFIXES) for k in d["counters"]
        )


# ---------------------------------------------------------------------------
# 9-node emulation acceptance: the full exposition round-trips and
# carries the pipeline/per-device/serving/resilience surfaces
# ---------------------------------------------------------------------------


@pytest.mark.multichip
def test_nine_node_emulation_prometheus_round_trip():
    from openr_tpu.config import ParallelConfig, ResilienceConfig
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import grid_edges

    def overrides(cfg):
        cfg.tpu_compute_config.min_device_prefixes = 0  # always device
        cfg.parallel_config = ParallelConfig(min_shard_rows=0)
        cfg.resilience_config = ResilienceConfig(
            shadow_sample_every=4, jitter_pct=0.0, seed=3
        )

    async def scenario():
        clock = SimClock()
        net = EmulatedNetwork(
            clock, use_tpu_backend=True, config_overrides=overrides
        )
        net.build(grid_edges(3))
        net.start()
        await clock.run_for(18.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        # one flap so rebuild + serving + resilience surfaces all move
        net.fail_link("node0", "node1")
        await clock.run_for(3.0)
        net.restore_link("node0", "node1")
        await clock.run_for(3.0)
        text = net.render_prometheus()
        snaps = net.metrics_snapshots()
        await net.stop()
        return text, snaps

    text, snaps = run(scenario())
    assert len(snaps) == 9
    parsed = parse_prometheus(text)  # strict: malformed would raise
    # pipeline phase histograms (device builds ran on every node)
    assert parsed["openr_pipeline_device_compute_ms"]["type"] == "histogram"
    assert parsed["openr_pipeline_decode_ms"]["type"] == "histogram"
    # per-device pipeline gauges (the probe's busy ledger, swept at
    # capture): ONE labeled family per (head, tail), device="N" labels
    # (ISSUE 8 satellite) — every node dispatched on chip 0 at least
    busy = parsed["openr_pipeline_device_busy_ms"]["samples"]
    util = parsed["openr_pipeline_device_utilization"]["samples"]
    assert any(dict(labels).get("device") == "0" for (_n, *labels) in busy)
    assert any(dict(labels).get("device") == "0" for (_n, *labels) in util)
    # the dotted per-chip spelling no longer leaks as its own family
    assert "openr_pipeline_dev0_busy_ms" not in parsed
    # existing serving + resilience counter surfaces ride along
    assert "openr_serving_queue_depth" in parsed
    assert "openr_resilience_backend_quarantined" in parsed
    # tracer drop accounting is exported (satellite: operator-visible)
    assert "openr_trace_dropped_spans" in parsed
    assert "openr_trace_spans_evicted" in parsed
    # known families carry their registry HELP text through the parser
    assert parsed["openr_convergence_event_to_fib_ms"]["help"]
    # fleet health plane gauges ride the same surface
    assert "openr_health_sweeps" in parsed
    # every node labeled every family it reported
    nodes = {dict(labels).get("node") for (_name, *labels) in busy}
    assert len(nodes) == 9
