"""Long randomized chaos sweeps (-m slow): seeded FaultPlans over a ring,
every transient fault healed before the horizon, full invariant suite at
the end.  Tier-1 runs the fixed scenarios in test_chaos_smoke.py /
test_chaos_recovery.py instead.
"""

import asyncio

import pytest

from openr_tpu.chaos import ChaosController, FaultPlan, InvariantChecker, Supervisor
from openr_tpu.common.runtime import SimClock
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import ring_edges


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def fast_watchdog(cfg):
    cfg.watchdog_config.interval_s = 1.0


async def _sweep(seed: int) -> dict:
    clock = SimClock()
    net = EmulatedNetwork(clock, config_overrides=fast_watchdog)
    edges = ring_edges(6)
    net.build(edges)
    net.start()
    sup = Supervisor(clock, initial_backoff_s=0.25, max_backoff_s=4.0)
    sup.start()
    for name, node in net.nodes.items():
        sup.supervise(name, node, net.restart_node)
    plan = FaultPlan.seeded(
        seed,
        nodes=sorted(net.nodes),
        edges=[(a, b) for a, b, _ in edges],
        num_faults=8,
        horizon_s=50.0,
        # half the tpu faults draw a per-chip device_index, exercising
        # the per-device quarantine/re-pack/probe path under the sweep
        # (scalar-backend nodes fall back to the whole-backend latch)
        num_devices=8,
    )
    checker = InvariantChecker(net)
    controller = ChaosController(net, plan, seed=seed)
    await clock.run_for(15.0)
    ok, why = net.converged_full_mesh()
    assert ok, why
    controller.start()
    for _ in range(12):
        await clock.run_for(5.0)
        checker.sample()
    assert controller.done
    await clock.run_for(40.0)  # post-heal convergence (incl. restarts)
    checker.check_all()
    dump = controller.counter_dump()
    await sup.stop()
    await controller.stop()
    await net.stop()
    return dump


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_randomized_sweep_recovers(seed):
    run(_sweep(seed))


@pytest.mark.slow
@pytest.mark.chaos
def test_randomized_sweep_is_reproducible():
    assert run(_sweep(9)) == run(_sweep(9))
