"""Consolidated bench-artifact gates, driven by the benchtrack manifest.

One parametrized suite replaces the six per-family
``test_*_bench_schema.py`` files: for every family in
``openr_tpu.benchtrack.manifest.MANIFEST`` the LATEST round must match
its shared validator (the same one its bench emitter runs, so artifact
and gate can never drift) plus its acceptance floors, and the
family's validator must actually REJECT a minimally-spoiled document.

The meta-sweep closes the orphan gap: every checked-in ``BENCH_*.json``
must parse, match a manifest entry, and carry the
platform/jax/device_count env stamp unless its entry explicitly
grandfathers a pre-env-stamp capture.  Regenerate any artifact with the
bench mode named in its manifest description.
"""

import json
import pathlib

import pytest

from openr_tpu.benchtrack import run_check
from openr_tpu.benchtrack.manifest import MANIFEST, env_triple, spec_for
from openr_tpu.benchtrack.timeline import artifact_files, discover

ROOT = pathlib.Path(__file__).resolve().parent.parent
DISC = discover(ROOT)


def _family_params(require_spoil=False):
    out = []
    for spec in MANIFEST:
        if require_spoil and (spec.spoil is None or spec.validate is None):
            continue
        marks = [getattr(pytest.mark, m) for m in spec.markers]
        out.append(pytest.param(spec, id=spec.family, marks=marks))
    return out


@pytest.mark.parametrize("spec", _family_params())
def test_latest_round_matches_schema_and_acceptance(spec):
    latest = DISC.latest(spec.family)
    assert latest is not None, (
        f"no artifacts for family {spec.family} — either restore them "
        "or remove the manifest entry"
    )
    assert latest.doc is not None, latest.parse_error
    if spec.validate is not None:
        spec.validate(latest.doc)
    if spec.acceptance is not None:
        spec.acceptance(latest.doc)


@pytest.mark.parametrize("spec", _family_params(require_spoil=True))
def test_validator_rejects_malformed_doc(spec):
    latest = DISC.latest(spec.family)
    assert latest is not None
    doc = json.loads(latest.path.read_text())
    spec.spoil(doc)
    with pytest.raises((AssertionError, KeyError)):
        spec.validate(doc)


@pytest.mark.parametrize(
    "name",
    [p.name for p in artifact_files(ROOT) if p.name.startswith("BENCH_")],
)
def test_every_bench_artifact_parses_and_is_manifested(name):
    """The orphan meta-sweep: parses as JSON, matches a manifest entry,
    carries the env stamp its entry requires."""
    hit = spec_for(name)
    assert hit is not None, (
        f"{name} matches no manifest entry (add an ArtifactSpec to "
        "openr_tpu/benchtrack/manifest.py)"
    )
    spec, rnd = hit
    assert rnd >= 1
    doc = json.loads((ROOT / name).read_text())
    if spec.requires_env:
        triple = env_triple(doc, spec)
        assert triple is not None, (
            f"{name}: missing platform/jax/device_count at "
            f"{spec.env_path}"
        )
        assert triple["device_count"] >= 1


def test_no_orphan_artifacts():
    assert DISC.orphans == [], DISC.orphans


def test_benchtrack_check_passes_on_checked_in_artifacts():
    """The --check gate itself must be green at HEAD: schemas, env
    stamps, no orphans, and every ratcheted headline within tolerance
    of its blessing (benchtrack_ratchet.json)."""
    res = run_check(ROOT)
    assert res.ok, json.dumps(res.problems, indent=2)
