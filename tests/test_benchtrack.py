"""benchtrack unit tests: the content-matched ratchet's one-way
contract, orphan detection, the timeline, and the CLI surfaces.

The ISSUE-10 acceptance pair lives here: ``--check`` FAILS on a
synthetically regressed artifact (headline metric past its manifest
tolerance) and passes again only after an explicit
``--update-ratchet``.
"""

import json
import pathlib
import shutil

import pytest

from openr_tpu.benchtrack import (
    load_ratchet,
    run_check,
    update_ratchet,
)
from openr_tpu.benchtrack.__main__ import main as benchtrack_main
from openr_tpu.benchtrack.manifest import HeadlineMetric, extract
from openr_tpu.benchtrack.timeline import build_timeline, render_timeline

REPO = pathlib.Path(__file__).resolve().parent.parent
#: a small virtual-time artifact (deterministic, 15% ratchet tolerance)
CONV = "BENCH_CONVERGENCE_r01.json"
RESIL = "BENCH_RESILIENCE_r01.json"


@pytest.fixture
def mini_root(tmp_path):
    """A miniature artifact root: two real families + a fresh ratchet
    blessing, isolated from the repo's own ratchet file."""
    for name in (CONV, RESIL):
        shutil.copy(REPO / name, tmp_path / name)
    update_ratchet(tmp_path)
    return tmp_path


def _write_regressed_round(root, factor=3.0):
    """A schema-VALID convergence round r02 whose p50 regressed by
    ``factor`` — the ratchet, not the validator, must catch it."""
    doc = json.loads((root / CONV).read_text())
    d = doc["detail"]
    for key in ("p50_ms", "p95_ms", "p99_ms", "max_ms"):
        d[key] = round(d[key] * factor, 2)
    doc["value"] = d["p50_ms"]
    path = root / "BENCH_CONVERGENCE_r02.json"
    path.write_text(json.dumps(doc))
    return path


def test_headline_metric_bounds():
    lower = HeadlineMetric("value", "lower", tolerance_pct=10.0)
    assert not lower.regressed(100.0, 109.0)
    assert lower.regressed(100.0, 111.0)
    assert lower.improved(100.0, 90.0)
    higher = HeadlineMetric("value", "higher", tolerance_abs=5.0)
    assert not higher.regressed(100.0, 96.0)
    assert higher.regressed(100.0, 94.0)
    with pytest.raises(ValueError):
        HeadlineMetric("value", "sideways")


def test_extract_dotted_paths():
    doc = {"a": {"b": [{"c": 7}]}}
    assert extract(doc, "a.b.0.c") == 7
    with pytest.raises(KeyError):
        extract(doc, "a.x")


def test_check_green_on_blessed_mini_root(mini_root):
    res = run_check(mini_root)
    assert res.ok, res.problems
    assert res.families_checked == 2
    assert not res.improvements


def test_check_fails_on_regressed_round_then_passes_after_update(
    mini_root,
):
    """THE acceptance pair: a new round regressing a ratcheted headline
    past tolerance fails --check; --update-ratchet (the deliberate
    re-blessing) makes it pass again."""
    _write_regressed_round(mini_root)
    res = run_check(mini_root)
    assert not res.ok
    kinds = {p["kind"] for p in res.problems}
    assert kinds == {"regression"}, res.problems
    [prob] = res.problems
    assert prob["family"] == "convergence"
    assert prob["current"] > prob["bound"] > prob["blessed"]
    update_ratchet(mini_root)
    res = run_check(mini_root)
    assert res.ok, res.problems


def test_improvement_passes_but_does_not_move_ratchet(mini_root):
    doc = json.loads((mini_root / CONV).read_text())
    d = doc["detail"]
    for key in ("p50_ms", "p95_ms", "p99_ms", "max_ms"):
        d[key] = round(d[key] / 2.0, 2)
    doc["value"] = d["p50_ms"]
    (mini_root / "BENCH_CONVERGENCE_r02.json").write_text(json.dumps(doc))
    res = run_check(mini_root)
    assert res.ok
    assert any(
        i["family"] == "convergence" for i in res.improvements
    ), "an improvement should be reported, pending --update-ratchet"
    blessed = {
        (e["family"], e["metric"]): e["value"]
        for e in load_ratchet(mini_root)["entries"]
    }
    assert blessed[("convergence", "value")] == json.loads(
        (REPO / CONV).read_text()
    )["value"], "the blessing must only move via --update-ratchet"


def test_content_drift_of_blessed_artifact_fails(mini_root):
    """Editing the blessed artifact in place — even WITHOUT regressing
    the headline — breaks the content match."""
    doc = json.loads((mini_root / CONV).read_text())
    doc["detail"]["note"] = "quietly rewritten"
    (mini_root / CONV).write_text(json.dumps(doc))
    res = run_check(mini_root)
    assert not res.ok
    assert any(p["kind"] == "content_drift" for p in res.problems), (
        res.problems
    )


def test_missing_blessing_fails(mini_root):
    (mini_root / "benchtrack_ratchet.json").unlink()
    res = run_check(mini_root)
    assert not res.ok
    assert {p["kind"] for p in res.problems} == {"ratchet_missing"}


def test_stale_blessing_fails(mini_root):
    """Blessings for artifacts that vanished are dead weight the check
    forces out (the orlint stale-baseline contract)."""
    for path in mini_root.glob("BENCH_CONVERGENCE_*.json"):
        path.unlink()
    res = run_check(mini_root)
    assert not res.ok
    assert any(p["kind"] == "stale" for p in res.problems), res.problems


def test_orphan_artifact_fails(mini_root):
    (mini_root / "BENCH_BOGUS_r01.json").write_text("{}")
    res = run_check(mini_root)
    assert not res.ok
    assert any(p["kind"] == "orphan" for p in res.problems)


def test_unparseable_artifact_fails(mini_root):
    (mini_root / "BENCH_CONVERGENCE_r02.json").write_text("{nope")
    res = run_check(mini_root)
    assert not res.ok
    assert any(p["kind"] == "invalid" for p in res.problems)


def test_env_stamp_required_by_manifest(mini_root):
    doc = json.loads((mini_root / CONV).read_text())
    del doc["detail"]["env"]["platform"]
    (mini_root / "BENCH_CONVERGENCE_r02.json").write_text(json.dumps(doc))
    res = run_check(mini_root)
    assert any(p["kind"] == "env_missing" for p in res.problems), (
        res.problems
    )


def test_timeline_rounds_and_deltas(mini_root):
    _write_regressed_round(mini_root, factor=2.0)
    tl = build_timeline(mini_root)
    conv = tl["families"]["convergence"]
    assert [r["round"] for r in conv["rounds"]] == [1, 2]
    delta = conv["rounds"][1]["deltas"]["value"]
    assert delta["pct"] == pytest.approx(100.0, abs=1.0)
    assert delta["better"] is False
    text = render_timeline(tl)
    assert "convergence" in text and "WORSE" in text
    assert "value [lower is better, ratcheted]" in text


def test_cli_check_report_update(mini_root, capsys):
    root = str(mini_root)
    assert benchtrack_main(["--check", "--root", root]) == 0
    _write_regressed_round(mini_root)
    assert benchtrack_main(["--check", "--root", root]) == 1
    out = capsys.readouterr().out
    assert "regression" in out
    assert (
        benchtrack_main(["--update-ratchet", "--root", root]) == 0
    )
    assert "blessed" in capsys.readouterr().out
    assert (
        benchtrack_main(["--check", "--format", "json", "--root", root])
        == 0
    )
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True
    assert benchtrack_main(["--report", "--root", root]) == 0
    assert "convergence" in capsys.readouterr().out
