"""Multi-store KvStore integration tests in virtual time — the
KvStoreTest.cpp pattern (several real stores, real sync/flood over an
in-process transport) without wall-clock flakiness."""

import asyncio

import pytest

from openr_tpu import constants as C
from openr_tpu.common.runtime import SimClock
from openr_tpu.config import KvStoreConfig
from openr_tpu.kvstore.kv_store import KvStore
from openr_tpu.kvstore.merge import generate_hash
from openr_tpu.kvstore.transport import InProcessTransport
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import (
    InitializationEvent,
    KeyValueRequest,
    KvRequestType,
    KvStorePeerState,
    PeerEvent,
    PeerSpec,
    Publication,
    Value,
)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class Net:
    """N KvStores over one InProcessTransport."""

    def __init__(self, names, clock, latency=0.001, config=None):
        self.clock = clock
        self.transport = InProcessTransport(clock, latency_s=latency)
        self.stores = {}
        self.pubs = {}
        self.peer_qs = {}
        self.kv_qs = {}
        self.init_events = {n: [] for n in names}
        per_node = config if isinstance(config, dict) else {}
        for n in names:
            if per_node:
                config = per_node.get(n) or KvStoreConfig()
            pub_q = ReplicateQueue(f"{n}.kvStoreUpdates")
            peer_q = ReplicateQueue(f"{n}.peerUpdates")
            kv_q = ReplicateQueue(f"{n}.kvRequests")
            store = KvStore(
                node_name=n,
                clock=clock,
                config=config or KvStoreConfig(),
                areas=["0"],
                transport=self.transport,
                publications_queue=pub_q,
                peer_updates_reader=peer_q.get_reader(),
                kv_request_reader=kv_q.get_reader(),
                initialization_cb=lambda ev, n=n: self.init_events[n].append(ev),
            )
            self.transport.register(n, store)
            self.stores[n] = store
            self.pubs[n] = pub_q
            self.peer_qs[n] = peer_q
            self.kv_qs[n] = kv_q
            store.start()

    def peer(self, a, b, bidir=True):
        """Declare b as a's peer (and vice versa).  The flood-optimization
        capability bit mirrors what LinkMonitor learns from the Spark
        handshake: it reflects the REMOTE store's config."""

        def spec(remote):
            return PeerSpec(
                peer_addr=remote,
                supports_flood_optimization=self.stores[
                    remote
                ].config.enable_flood_optimization,
            )

        self.peer_qs[a].push(
            PeerEvent(area="0", peers_to_add={b: spec(b)})
        )
        if bidir:
            self.peer_qs[b].push(
                PeerEvent(area="0", peers_to_add={a: spec(a)})
            )

    async def stop(self):
        for s in self.stores.values():
            await s.stop()


def mkval(version=1, originator="x", data=b"d", ttl=300000):
    val = Value(version=version, originator_id=originator, value=data, ttl=ttl)
    val.hash = generate_hash(val)
    return val


def test_full_sync_three_way():
    async def main():
        clock = SimClock()
        net = Net(["a", "b"], clock)
        # a knows k1 (newer), k2; b knows k1 (older) and k3 (which a lacks)
        net.stores["a"].set_key_vals("0", {"k1": mkval(2, data=b"new")})
        net.stores["a"].set_key_vals("0", {"k2": mkval(1)})
        net.stores["b"].set_key_vals("0", {"k1": mkval(1, data=b"old")})
        net.stores["b"].set_key_vals("0", {"k3": mkval(1)})
        net.peer("a", "b")
        await clock.run_for(10.0)
        for n in ("a", "b"):
            kv = net.stores[n].dump_all("0")
            assert set(kv) == {"k1", "k2", "k3"}, n
            assert kv["k1"].value == b"new", n
        assert net.stores["a"].peer_state("0", "b") == KvStorePeerState.INITIALIZED
        assert net.stores["b"].peer_state("0", "a") == KvStorePeerState.INITIALIZED
        await net.stop()

    run(main())


def test_flood_through_line_topology():
    async def main():
        clock = SimClock()
        net = Net(["a", "b", "c"], clock)
        net.peer("a", "b")
        net.peer("b", "c")
        await clock.run_for(10.0)
        calls_before = net.transport.num_calls
        net.stores["a"].set_key_vals("0", {"route": mkval(1, "a", b"payload")})
        await clock.run_for(5.0)
        assert net.stores["c"].dump_all("0")["route"].value == b"payload"
        # ttl decremented along the flood path (a->b->c: 2 hops)
        assert net.stores["c"].dump_all("0")["route"].ttl == 300000 - 2
        # no flood storm: bounded number of messages for one update
        assert net.transport.num_calls - calls_before <= 6
        await net.stop()

    run(main())


def test_flood_loop_prevention_in_cycle():
    async def main():
        clock = SimClock()
        net = Net(["a", "b", "c"], clock)
        net.peer("a", "b")
        net.peer("b", "c")
        net.peer("c", "a")
        await clock.run_for(10.0)
        calls_before = net.transport.num_calls
        net.stores["a"].set_key_vals("0", {"k": mkval(1, "a")})
        await clock.run_for(5.0)
        for n in ("a", "b", "c"):
            assert "k" in net.stores[n].dump_all("0")
        # cycle must not echo forever
        assert net.transport.num_calls - calls_before <= 10
        await net.stop()

    run(main())


def test_publication_pushed_to_local_subscribers():
    async def main():
        clock = SimClock()
        net = Net(["a", "b"], clock)
        reader = net.pubs["b"].get_reader()
        net.peer("a", "b")
        await clock.run_for(10.0)
        net.stores["a"].set_key_vals("0", {"adj:a": mkval(1, "a")})
        await clock.run_for(5.0)
        pubs = []
        while (p := reader.try_get()) is not None:
            pubs.append(p)
        assert any("adj:a" in p.key_vals for p in pubs)
        await net.stop()

    run(main())


def test_ttl_expiry_publishes_expired_keys():
    async def main():
        clock = SimClock()
        net = Net(["a"], clock)
        reader = net.pubs["a"].get_reader()
        net.stores["a"].set_key_vals("0", {"ephemeral": mkval(1, ttl=2000)})
        await clock.run_for(1.0)
        assert "ephemeral" in net.stores["a"].dump_all("0")
        await clock.run_for(3.0)
        assert "ephemeral" not in net.stores["a"].dump_all("0")
        expired = []
        while (p := reader.try_get()) is not None:
            expired.extend(p.expired_keys)
        assert "ephemeral" in expired
        await net.stop()

    run(main())


def test_self_originated_persist_and_ttl_refresh():
    async def main():
        clock = SimClock()
        cfg = KvStoreConfig(self_originated_key_ttl_ms=4000)
        net = Net(["a", "b"], clock, config=cfg)
        net.peer("a", "b")
        await clock.run_for(10.0)
        net.kv_qs["a"].push(
            KeyValueRequest(KvRequestType.PERSIST_KEY, "0", "adj:a", b"mydata")
        )
        await clock.run_for(2.0)
        assert net.stores["b"].dump_all("0")["adj:a"].value == b"mydata"
        # survive well past the 4s ttl thanks to refreshes
        await clock.run_for(20.0)
        assert "adj:a" in net.stores["a"].dump_all("0")
        assert "adj:a" in net.stores["b"].dump_all("0")
        assert net.stores["b"].dump_all("0")["adj:a"].ttl_version > 0
        # erase: stops refreshing, expires everywhere
        net.kv_qs["a"].push(
            KeyValueRequest(KvRequestType.CLEAR_KEY, "0", "adj:a")
        )
        await clock.run_for(10.0)
        assert "adj:a" not in net.stores["a"].dump_all("0")
        assert "adj:a" not in net.stores["b"].dump_all("0")
        await net.stop()

    run(main())


def test_self_originated_key_guard_against_override():
    async def main():
        clock = SimClock()
        net = Net(["a", "b"], clock)
        net.peer("a", "b")
        await clock.run_for(10.0)
        net.kv_qs["a"].push(
            KeyValueRequest(KvRequestType.PERSIST_KEY, "0", "adj:a", b"mine")
        )
        await clock.run_for(2.0)
        v1 = net.stores["a"].dump_all("0")["adj:a"].version
        # intruder advertises the same key with a higher version
        net.stores["b"].set_key_vals(
            "0", {"adj:a": mkval(v1 + 3, "zzz-intruder", b"stolen")}
        )
        await clock.run_for(5.0)
        for n in ("a", "b"):
            kv = net.stores[n].dump_all("0")["adj:a"]
            assert kv.originator_id == "a", n
            assert kv.value == b"mine", n
            assert kv.version > v1 + 3, n
        await net.stop()

    run(main())


def test_peer_failure_backoff_and_recovery():
    async def main():
        clock = SimClock()
        net = Net(["a", "b"], clock)
        net.transport.fail("a", "b")
        net.peer("a", "b", bidir=False)
        await clock.run_for(2.0)
        assert net.stores["a"].peer_state("0", "b") == KvStorePeerState.IDLE
        failures_early = net.stores["a"].areas["0"].peers["b"].num_failures
        assert failures_early >= 1
        # stays failing with exponential backoff (not hot-looping)
        await clock.run_for(60.0)
        failures_late = net.stores["a"].areas["0"].peers["b"].num_failures
        assert failures_late < 12  # 4s initial backoff doubling
        net.transport.heal("a", "b")
        await clock.run_for(300.0)  # max backoff is 256s
        assert net.stores["a"].peer_state("0", "b") == KvStorePeerState.INITIALIZED
        await net.stop()

    run(main())


def test_kvstore_synced_initialization_event():
    async def main():
        clock = SimClock()
        net = Net(["a", "b", "c"], clock)
        net.peer("a", "b")
        net.peer("a", "c")
        await clock.run_for(15.0)
        assert InitializationEvent.KVSTORE_SYNCED in net.init_events["a"]
        assert net.init_events["a"].count(InitializationEvent.KVSTORE_SYNCED) == 1
        await net.stop()

    run(main())


def test_no_peer_store_synced_after_grace():
    async def main():
        clock = SimClock()
        net = Net(["lonely"], clock)
        await clock.run_for(1.0)
        # must NOT claim sync before the link-discovery grace window
        assert InitializationEvent.KVSTORE_SYNCED not in net.init_events["lonely"]
        await clock.run_for(10.0)
        assert InitializationEvent.KVSTORE_SYNCED in net.init_events["lonely"]
        await net.stop()

    run(main())


def test_area_isolation():
    async def main():
        clock = SimClock()
        transport = InProcessTransport(clock)
        pub_q = ReplicateQueue("pub")
        store = KvStore(
            node_name="a",
            clock=clock,
            config=KvStoreConfig(),
            areas=["area1", "area2"],
            transport=transport,
            publications_queue=pub_q,
        )
        transport.register("a", store)
        store.start()
        store.set_key_vals("area1", {"k": mkval()})
        await clock.run_for(1.0)
        assert "k" in store.dump_all("area1")
        assert "k" not in store.dump_all("area2")
        summaries = store.summaries()
        assert summaries["area1"].key_vals_count == 1
        assert summaries["area2"].key_vals_count == 0
        await store.stop()

    run(main())


def test_repersist_identical_data_is_noop():
    async def main():
        clock = SimClock()
        net = Net(["a", "b"], clock)
        net.peer("a", "b")
        await clock.run_for(10.0)
        for _ in range(3):
            net.kv_qs["a"].push(
                KeyValueRequest(KvRequestType.PERSIST_KEY, "0", "adj:a", b"same")
            )
            await clock.run_for(1.0)
        assert net.stores["a"].dump_all("0")["adj:a"].version == 1
        assert net.stores["b"].dump_all("0")["adj:a"].version == 1
        # changed data DOES bump
        net.kv_qs["a"].push(
            KeyValueRequest(KvRequestType.PERSIST_KEY, "0", "adj:a", b"new")
        )
        await clock.run_for(1.0)
        assert net.stores["a"].dump_all("0")["adj:a"].version == 2
        await net.stop()

    run(main())


def test_restarted_originator_reclaims_its_own_fossil_key():
    """Incarnation guard (ISSUE 12): a restarted node re-originates at
    version 1 while the network still holds its previous incarnation's
    higher-version key.  Without re-origination the fossil wins every
    merge, the fresh node's TTL refreshes are rejected as stale, and
    the key starves fleet-wide one TTL after the restart — a rolling
    upgrade would silently withdraw every bounced node's prefixes.
    The guard must adopt a version above the fossil and re-advertise
    the CURRENT data."""

    async def main():
        clock = SimClock()
        net = Net(["a", "b"], clock)
        net.peer("a", "b")
        await clock.run_for(5.0)
        # two generations of adj:a -> the fleet remembers version 2
        for data in (b"gen1", b"gen2"):
            net.kv_qs["a"].push(
                KeyValueRequest(
                    KvRequestType.PERSIST_KEY, "0", "adj:a", data
                )
            )
            await clock.run_for(1.0)
        assert net.stores["b"].dump_all("0")["adj:a"].version == 2
        # "a" restarts: fresh store, empty, re-advertises at version 1
        await net.stores["a"].stop()
        net.transport.unregister("a")
        pub_q = ReplicateQueue("a.kvStoreUpdates")
        peer_q = ReplicateQueue("a.peerUpdates")
        kv_q = ReplicateQueue("a.kvRequests")
        fresh = KvStore(
            node_name="a",
            clock=clock,
            config=KvStoreConfig(),
            areas=["0"],
            transport=net.transport,
            publications_queue=pub_q,
            peer_updates_reader=peer_q.get_reader(),
            kv_request_reader=kv_q.get_reader(),
        )
        net.transport.register("a", fresh)
        net.stores["a"] = fresh
        net.pubs["a"] = pub_q
        net.peer_qs["a"] = peer_q
        net.kv_qs["a"] = kv_q
        fresh.start()
        net.peer("a", "b")
        kv_q.push(
            KeyValueRequest(
                KvRequestType.PERSIST_KEY, "0", "adj:a", b"gen3"
            )
        )
        await clock.run_for(10.0)
        # the fossil (v2, gen2) flooded back; the guard must have
        # re-originated the CURRENT data above it, fleet-wide
        for store in ("a", "b"):
            val = net.stores[store].dump_all("0")["adj:a"]
            assert val.value == b"gen3", store
            assert val.version == 3, store
        assert (
            net.stores["a"].counters.get(
                "kvstore.self_originated_incarnation_guard"
            )
            >= 1
        )
        # and the reclaimed key stays ALIVE past the fossil's ttl (the
        # fresh incarnation's refreshes are accepted again)
        short = KvStoreConfig()
        await clock.run_for(short.key_ttl_ms / 1000.0 + 5.0)
        assert net.stores["b"].dump_all("0")["adj:a"].value == b"gen3"
        await net.stop()

    run(main())


def test_restarted_originator_ttl_clock_stays_monotone():
    """Second face of the incarnation problem: the restarted node
    re-advertises the IDENTICAL key (same version, same data) but a
    zero-seeded ttl_version clock would restart at 0 — every refresh it
    sends would be rejected as stale against the fleet's
    higher-ttl_version copies, which then silently age out one TTL
    after the bounce (the 3-way sync's hash digest cannot see the
    divergence, so nothing heals it).  The incarnation-monotone ttl
    clock (`_ttl_clock`) must keep the fresh refreshes ahead of the
    fossil's."""

    async def main():
        clock = SimClock()
        net = Net(["a", "b"], clock)
        net.peer("a", "b")
        await clock.run_for(5.0)
        net.kv_qs["a"].push(
            KeyValueRequest(
                KvRequestType.PERSIST_KEY, "0", "prefix:a", b"lo"
            )
        )
        # let several refresh intervals pass so the fleet's ttl_version
        # is well above a fresh incarnation's
        ttl_s = KvStoreConfig().key_ttl_ms / 1000.0
        await clock.run_for(ttl_s * 1.5)
        assert net.stores["b"].dump_all("0")["prefix:a"].ttl_version >= 4
        # "a" restarts and re-advertises the IDENTICAL data
        await net.stores["a"].stop()
        net.transport.unregister("a")
        pub_q = ReplicateQueue("a.kvStoreUpdates")
        peer_q = ReplicateQueue("a.peerUpdates")
        kv_q = ReplicateQueue("a.kvRequests")
        fresh = KvStore(
            node_name="a",
            clock=clock,
            config=KvStoreConfig(),
            areas=["0"],
            transport=net.transport,
            publications_queue=pub_q,
            peer_updates_reader=peer_q.get_reader(),
            kv_request_reader=kv_q.get_reader(),
        )
        net.transport.register("a", fresh)
        net.stores["a"] = fresh
        net.pubs["a"] = pub_q
        net.peer_qs["a"] = peer_q
        net.kv_qs["a"] = kv_q
        fresh.start()
        # the daemon's ordering: the reborn node advertises its own
        # keys at boot, THEN Spark discovers neighbors and peers the
        # store — the fossil arrives by full sync after the sov exists
        kv_q.push(
            KeyValueRequest(
                KvRequestType.PERSIST_KEY, "0", "prefix:a", b"lo"
            )
        )
        await clock.run_for(1.0)
        # the fresh incarnation's ttl clock already exceeds the
        # fossil's (time-seeded: the old incarnation advanced it at the
        # same one-per-interval rate it was alive)
        fossil_ttlv = net.stores["b"].dump_all("0")["prefix:a"].ttl_version
        sov = net.stores["a"].areas["0"].self_originated["prefix:a"]
        assert sov.value.ttl_version > fossil_ttlv
        net.peer("a", "b")
        await clock.run_for(10.0)
        # the key must survive well past the fossil's remaining TTL:
        # the fresh incarnation's refreshes are accepted fleet-wide
        await clock.run_for(ttl_s * 1.5)
        assert net.stores["b"].dump_all("0").get("prefix:a") is not None
        assert net.stores["b"].dump_all("0")["prefix:a"].value == b"lo"
        assert (
            net.stores["b"].dump_all("0")["prefix:a"].ttl_version
            > fossil_ttlv
        )
        await net.stop()

    run(main())


def test_flap_counter_counts_once_per_flap():
    async def main():
        clock = SimClock()
        net = Net(["a", "b"], clock)
        net.peer("a", "b", bidir=False)
        await clock.run_for(5.0)
        assert net.stores["a"].peer_state("0", "b") == KvStorePeerState.INITIALIZED
        net.transport.fail("a", "b")
        net.stores["a"].set_key_vals("0", {"k": mkval(1, "a")})
        await clock.run_for(1.0)
        assert net.stores["a"].areas["0"].peers["b"].flaps == 1
        await net.stop()

    run(main())


def test_flood_fanout_order_is_name_sorted_not_session_order():
    """ISSUE-15 regression (orlint unordered-emission): flood fan-out
    iterated the live session table, so the emission order every peer's
    arrival sequence inherits was session-ADD order — stable across
    replays only because both replays happened to re-add peers
    identically.  The fan-out now walks peers in sorted name order
    regardless of how the session table was built."""

    async def main():
        clock = SimClock()
        net = Net(["hub", "s3", "s1", "s2"], clock)
        for spoke in ("s3", "s1", "s2"):  # deliberately unsorted add order
            net.peer("hub", spoke)
        await clock.run_for(5.0)
        hub = net.stores["hub"]
        order = []
        orig_spawn = hub.spawn

        def spy(coro, name=""):
            if ".flood." in name:
                order.append(name.rsplit(".", 1)[-1])
            return orig_spawn(coro, name)

        hub.spawn = spy
        hub.set_key_vals("0", {"zz": mkval(1, "hub")})
        await clock.run_for(1.0)
        hub.spawn = orig_spawn
        assert order, "no flood fan-out observed"
        assert order == sorted(order), order
        await net.stop()

    run(main())
