"""Watchdog/supervisor robustness satellites (ISSUE 1):

  * regression: one crash signal per watchdog sweep, even when several
    checks trip at once (a dead fiber backing up queues is ONE root cause);
  * memory-cap path with a stubbed SystemMetrics (deterministic RSS);
  * watchdog thresholds flow config JSON -> OpenrConfig -> OpenrNode;
  * TcpKvStoreTransport._drop_client close tasks don't leak;
  * Supervisor crash-loop backoff + drain-state replay through restart;
  * KvStore.request_full_sync forces every peer back through full sync.
"""

import asyncio

import pytest

from openr_tpu.chaos import Supervisor
from openr_tpu.common.runtime import Actor, CounterMap, SimClock
from openr_tpu.config import OpenrConfig
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import line_edges
from openr_tpu.kvstore.transport import TcpKvStoreTransport
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import KvStorePeerState
from openr_tpu.watchdog.watchdog import Watchdog


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class _CrashingActor(Actor):
    async def run(self):
        raise RuntimeError("boom")


# ---------------------------------------------------------------------------
# Watchdog: first crash per sweep short-circuits
# ---------------------------------------------------------------------------


def test_watchdog_fires_once_per_sweep_on_multiple_conditions():
    async def main():
        clock = SimClock()
        crashes = []
        counters = CounterMap()
        wd = Watchdog(
            "node1",
            clock,
            counters,
            interval_s=20,
            max_queue_size=10,
            fire_crash=crashes.append,
        )
        # two simultaneous conditions: a dead module fiber AND an
        # over-limit queue — the sweep must report the FIRST reason only
        dead = _CrashingActor("dead_mod", clock)
        q = ReplicateQueue("backedUp")
        q.get_reader()  # never drained
        wd.add_actor(dead)
        wd.add_queue(q)
        dead.start()
        for i in range(11):
            q.push(i)
        wd.start()
        await clock.run_for(25)  # exactly one sweep
        assert len(crashes) == 1
        assert "dead_mod" in crashes[0]  # first reason in scan order wins
        assert wd.crashed == crashes[0]
        assert counters.get("watchdog.crashes") == 1
        # gauges for everything are still maintained on the crashing sweep
        assert counters.get("watchdog.queue_backlog.backedUp") == 11
        # next sweep fires again (still broken) — one per sweep, not zero
        await clock.run_for(20)
        assert len(crashes) == 2
        await dead.stop()
        await wd.stop()

    run(main())


def test_watchdog_memory_cap_with_stubbed_metrics():
    class StubMetrics:
        def __init__(self):
            self.rss = 0

        def rss_bytes(self):
            return self.rss

    async def main():
        clock = SimClock()
        crashes = []
        counters = CounterMap()
        metrics = StubMetrics()
        wd = Watchdog(
            "node1",
            clock,
            counters,
            interval_s=20,
            max_memory_mb=100,
            fire_crash=crashes.append,
            metrics=metrics,
        )
        wd.start()
        metrics.rss = 99 * 1024 * 1024  # under the cap: quiet
        await clock.run_for(25)
        assert crashes == []
        assert counters.get("watchdog.rss_bytes") == metrics.rss
        metrics.rss = 101 * 1024 * 1024  # over the cap: crash
        await clock.run_for(20)
        assert len(crashes) == 1 and "Memory" in crashes[0]
        assert str(101 * 1024 * 1024) in crashes[0]
        await wd.stop()

    run(main())


# ---------------------------------------------------------------------------
# Config wiring: thresholds flow JSON -> OpenrConfig -> node watchdog
# ---------------------------------------------------------------------------


def test_watchdog_thresholds_wired_from_config_json():
    cfg = OpenrConfig.from_json(
        """
        {"node_name": "wired",
         "persistent_store_path": "",
         "rib_policy_file": "",
         "watchdog_config": {"interval_s": 5.0,
                             "thread_timeout_s": 42.0,
                             "max_memory_mb": 512,
                             "max_queue_size": 777}}
        """
    )
    assert cfg.watchdog_config.interval_s == 5.0

    async def main():
        from openr_tpu.kvstore.transport import InProcessTransport
        from openr_tpu.main import OpenrNode
        from openr_tpu.spark.io_provider import MockIoProvider

        clock = SimClock()
        node = OpenrNode(
            config=cfg,
            clock=clock,
            io_provider=MockIoProvider(clock),
            kv_transport=InProcessTransport(clock),
        )
        wd = node.watchdog
        assert wd is not None
        assert wd._interval == 5.0
        assert wd._thread_timeout == 42.0
        assert wd._max_memory_bytes == 512 * 1024 * 1024
        assert wd._max_queue_size == 777

    run(main())


# ---------------------------------------------------------------------------
# TcpKvStoreTransport: dropped clients must not leak close tasks
# ---------------------------------------------------------------------------


def test_drop_client_close_tasks_do_not_leak():
    class _Client:
        def __init__(self, fail=False):
            self.fail = fail
            self.closed = False

        async def close(self):
            self.closed = True
            if self.fail:
                raise OSError("broken pipe during close")

    async def main():
        transport = TcpKvStoreTransport()
        good, bad = _Client(), _Client(fail=True)
        transport._clients["peer_ok"] = good
        transport._clients["peer_bad"] = bad
        transport._drop_client("peer_ok")
        transport._drop_client("peer_bad")
        assert len(transport._close_tasks) == 2  # strong refs while in flight
        for _ in range(5):
            await asyncio.sleep(0)
        assert good.closed and bad.closed
        # done-callback discards the task AND consumes the exception —
        # nothing retained, no 'exception was never retrieved' spew
        assert transport._close_tasks == set()
        assert transport._clients == {}

    run(main())


# ---------------------------------------------------------------------------
# Supervisor: crash-loop backoff + drain-state replay
# ---------------------------------------------------------------------------


def test_supervisor_crash_loop_backs_off():
    async def main():
        clock = SimClock()
        sup = Supervisor(
            clock, initial_backoff_s=1.0, max_backoff_s=8.0, stable_after_s=60.0
        )
        sup.start()
        restarts = []

        class _Node:
            watchdog = None
            kv_store = None

        async def restart(name):
            restarts.append(clock.now())
            return _Node()

        sup.supervise("crashy", _Node(), restart)
        for _ in range(4):
            sup.on_crash("crashy", "boom")
            await clock.run_for(20.0)
        assert len(restarts) == 4
        gaps = [restarts[0]] + [
            b - a for a, b in zip(restarts, restarts[1:])
        ]
        # each restart of a crash-looping node waits longer: 1,2,4,8 of
        # backoff inside 20s windows -> the wait component doubles
        waits = [g - 20.0 * i for i, g in enumerate(gaps)]
        assert waits[0] == pytest.approx(1.0)
        assert sup.num_crashes == 4 and sup.num_restarts == 4
        await sup.stop()

    run(main())


def test_supervisor_restart_replays_drain_state_from_persistent_store(tmp_path):
    def overrides(cfg):
        cfg.watchdog_config.interval_s = 1.0
        cfg.persistent_store_path = str(
            tmp_path / f"store.{cfg.node_name}.bin"
        )

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=overrides)
        net.build(line_edges(2))
        net.start()
        sup = Supervisor(clock, initial_backoff_s=0.25, max_backoff_s=2.0)
        sup.start()
        for name, node in net.nodes.items():
            sup.supervise(name, node, net.restart_node)
        await clock.run_for(12.0)
        # operator drains node0; intent lands in the persistent store
        net.nodes["node0"].set_node_overload(True)
        await clock.run_for(1.0)
        old = net.nodes["node0"]
        # crash it (dead fiber -> watchdog -> supervisor)
        async def _die():
            raise RuntimeError("chaos kill")

        old.link_monitor.spawn(_die(), name="test.kill")
        await clock.run_for(15.0)
        fresh = net.nodes["node0"]
        assert fresh is not old and sup.num_restarts == 1
        # the operator's drain intent survived the crash-restart
        assert fresh.link_monitor.get_drain_state()["node_overloaded"] is True
        await sup.stop()
        await net.stop()

    run(main())


# ---------------------------------------------------------------------------
# TpuBackend: injected device outage -> scalar fallback
# ---------------------------------------------------------------------------


def test_tpu_backend_injected_outage_falls_back_scalar():
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver

    backend = TpuBackend(SpfSolver("me"))
    backend.inject_device_failure(True)
    backend.build_route_db({}, PrefixState())
    assert backend.num_fallback_injected == 1
    snap = backend.counter_snapshot()
    assert snap["decision.backend.device_failed"] == 1.0
    assert snap["decision.backend.num_fallback_injected"] == 1.0
    backend.inject_device_failure(False)
    backend.build_route_db({}, PrefixState())
    # outage cleared: no further injected fallbacks (empty topology still
    # routes through the ordinary scalar path, not the injected one)
    assert backend.num_fallback_injected == 1
    assert backend.counter_snapshot()["decision.backend.device_failed"] == 0.0


# ---------------------------------------------------------------------------
# KvStore: forced cold-boot full sync
# ---------------------------------------------------------------------------


def test_request_full_sync_rewalks_every_peer():
    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(line_edges(2))
        net.start()
        await clock.run_for(12.0)
        kv = net.nodes["node0"].kv_store
        area = next(iter(kv.areas))
        assert kv.peer_state(area, "node1") == KvStorePeerState.INITIALIZED
        syncs_before = kv.counters.get("kvstore.thrift.num_full_sync")
        n = kv.request_full_sync()
        assert n == 1
        await clock.run_for(2.0)
        assert kv.peer_state(area, "node1") == KvStorePeerState.INITIALIZED
        assert kv.counters.get("kvstore.thrift.num_full_sync") > syncs_before
        assert kv.counters.get("kvstore.full_sync_requests") == 1
        await net.stop()

    run(main())
