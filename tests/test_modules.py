"""LinkMonitor / Dispatcher / Fib / PrefixManager module tests
(patterns from link-monitor/tests, fib/tests, prefix-manager/tests)."""

import asyncio
import json

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import FibConfig, LinkMonitorConfig, OriginatedPrefix
from openr_tpu.decision.rib import (
    DecisionRouteUpdate,
    DecisionRouteUpdateType,
    RibUnicastEntry,
)
from openr_tpu.dispatcher.dispatcher import Dispatcher
from openr_tpu.fib.fib import Fib, FibAgentError, MockFibAgent
from openr_tpu.link_monitor.link_monitor import LinkMonitor, rtt_to_metric
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.prefix_manager.prefix_manager import (
    PrefixManager,
    deserialize_prefix_db,
)
from openr_tpu.types import (
    AdjacencyDatabase,
    InitializationEvent,
    InterfaceInfo,
    KvRequestType,
    NeighborEvent,
    NeighborEventType,
    NextHop,
    PrefixEntry,
    Publication,
    Value,
)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def neighbor_up(node="nbr1", area="0", local_if="if1", rtt=1000):
    return NeighborEvent(
        event_type=NeighborEventType.NEIGHBOR_UP,
        node_name=node,
        area=area,
        local_if_name=local_if,
        remote_if_name=f"r_{local_if}",
        neighbor_addr_v6="fe80::99",
        ctrl_port=2018,
        rtt_us=rtt,
    )


class LmRig:
    def __init__(self, clock, areas=None, config=None):
        self.if_q = ReplicateQueue("ifaces")
        self.peer_q = ReplicateQueue("peers")
        self.kv_q = ReplicateQueue("kvreq")
        self.nbr_q = ReplicateQueue("nbrs")
        self.if_r = self.if_q.get_reader()
        self.peer_r = self.peer_q.get_reader()
        self.kv_r = self.kv_q.get_reader()
        self.init_events = []
        self.lm = LinkMonitor(
            node_name="me",
            clock=clock,
            config=config or LinkMonitorConfig(linkflap_initial_backoff_ms=1000),
            interface_updates_queue=self.if_q,
            peer_updates_queue=self.peer_q,
            kv_request_queue=self.kv_q,
            neighbor_updates_reader=self.nbr_q.get_reader(),
            area_ids=areas or ["0"],
            node_labels={"0": 101},
            initialization_cb=self.init_events.append,
        )
        self.lm.start()

    def drain(self, reader):
        out = []
        while (x := reader.try_get()) is not None:
            out.append(x)
        return out

    def last_adj_db(self):
        reqs = self.drain(self.kv_r)
        assert reqs, "no kv requests"
        req = reqs[-1]
        assert req.key == "adj:me"
        return AdjacencyDatabase.from_wire(json.loads(req.value.decode()))


def test_link_monitor_neighbor_up_advertises_adj_and_peer():
    async def main():
        clock = SimClock()
        rig = LmRig(clock)
        rig.nbr_q.push(neighbor_up(rtt=2500))
        await clock.run_for(3.0)
        peers = rig.drain(rig.peer_r)
        assert peers and peers[0].peers_to_add["nbr1"].ctrl_port == 2018
        db = rig.last_adj_db()
        assert db.node_label == 101
        assert len(db.adjacencies) == 1
        adj = db.adjacencies[0]
        assert adj.other_node_name == "nbr1"
        assert adj.metric == rtt_to_metric(2500) == 25
        assert adj.next_hop_v6 == "fe80::99"
        assert db.perf_events is not None
        await rig.lm.stop()

    run(main())


def test_link_monitor_neighbor_down_withdraws():
    async def main():
        clock = SimClock()
        rig = LmRig(clock)
        rig.nbr_q.push(neighbor_up())
        await clock.run_for(3.0)
        rig.drain(rig.peer_r)
        rig.drain(rig.kv_r)
        down = neighbor_up()
        down.event_type = NeighborEventType.NEIGHBOR_DOWN
        rig.nbr_q.push(down)
        await clock.run_for(3.0)
        peers = rig.drain(rig.peer_r)
        assert peers and peers[0].peers_to_del == ["nbr1"]
        assert rig.last_adj_db().adjacencies == []
        await rig.lm.stop()

    run(main())


def test_link_monitor_restarting_keeps_adjacency_drops_peer():
    async def main():
        clock = SimClock()
        rig = LmRig(clock)
        rig.nbr_q.push(neighbor_up())
        await clock.run_for(3.0)
        rig.drain(rig.peer_r)
        rig.drain(rig.kv_r)
        ev = neighbor_up()
        ev.event_type = NeighborEventType.NEIGHBOR_RESTARTING
        rig.nbr_q.push(ev)
        await clock.run_for(1.0)
        peers = rig.drain(rig.peer_r)
        assert peers and peers[0].peers_to_del == ["nbr1"]
        # adjacency still advertised (GR hold)
        assert rig.lm.build_adjacency_database("0").adjacencies != []
        await rig.lm.stop()

    run(main())


def test_link_monitor_drain_ops():
    async def main():
        clock = SimClock()
        rig = LmRig(clock)
        rig.nbr_q.push(neighbor_up())
        await clock.run_for(3.0)
        rig.drain(rig.kv_r)
        rig.lm.set_node_overload(True)
        db = rig.last_adj_db()
        assert db.is_overloaded
        rig.lm.set_node_metric_increment(50)
        assert rig.last_adj_db().node_metric_increment_val == 50
        rig.lm.set_link_metric("if1", 999)
        assert rig.last_adj_db().adjacencies[0].metric == 999
        rig.lm.set_link_overload("if1", True)
        assert rig.last_adj_db().adjacencies[0].is_overloaded
        # drain state round-trips through persistence
        state = rig.lm.get_drain_state()
        rig.lm.set_node_overload(False)
        rig.lm.restore_drain_state(state)
        assert rig.lm.node_overloaded
        await rig.lm.stop()

    run(main())


def test_link_monitor_interface_flap_backoff():
    async def main():
        clock = SimClock()
        rig = LmRig(clock)
        up = InterfaceInfo("eth0", is_up=True, if_index=3, networks=["fe80::1/64"])
        down = InterfaceInfo("eth0", is_up=False, if_index=3)
        rig.lm.set_interfaces([up])
        await clock.run_for(1.0)
        assert InitializationEvent.LINK_DISCOVERED in rig.init_events
        dbs = rig.drain(rig.if_r)
        assert dbs and "eth0" in dbs[-1].interfaces
        # flap: down then up -> activation delayed by backoff (1s)
        rig.lm._on_interface_event(down)
        rig.lm._on_interface_event(up)
        await clock.run_for(0.5)
        dbs = rig.drain(rig.if_r)
        assert all("eth0" not in d.interfaces for d in dbs)
        await clock.run_for(1.0)
        dbs = rig.drain(rig.if_r)
        assert dbs and "eth0" in dbs[-1].interfaces
        await rig.lm.stop()

    run(main())


def test_dispatcher_prefix_filtering():
    async def main():
        clock = SimClock()
        src = ReplicateQueue("kvpubs")
        d = Dispatcher(clock, src.get_reader())
        adj_r = d.get_reader(["adj:"])
        all_r = d.get_reader()
        d.start()
        src.push(
            Publication(
                key_vals={
                    "adj:n1": Value(1, "n1", b"a"),
                    "prefix:n1:[10.0.0.0/24]": Value(1, "n1", b"p"),
                },
                area="0",
            )
        )
        src.push(Publication(key_vals={"prefix:n2:[10.1.0.0/24]": Value(1, "n2", b"p")}))
        src.push(Publication(expired_keys=["adj:n3", "prefix:n3:[::/0]"]))
        await clock.run_for(0.5)
        adj_pubs = []
        while (p := adj_r.try_get()) is not None:
            adj_pubs.append(p)
        # pub 2 had no adj keys -> not delivered at all
        assert len(adj_pubs) == 2
        assert set(adj_pubs[0].key_vals) == {"adj:n1"}  # narrowed
        assert adj_pubs[1].expired_keys == ["adj:n3"]
        all_pubs = []
        while (p := all_r.try_get()) is not None:
            all_pubs.append(p)
        assert len(all_pubs) == 3
        assert d.get_filters() == [("adj:",), ()]
        await d.stop()

    run(main())


class FibRig:
    def __init__(self, clock, dryrun=False, agent=None):
        self.routes_q = ReplicateQueue("routeUpdates")
        self.fib_out_q = ReplicateQueue("fibUpdates")
        self.fib_out_r = self.fib_out_q.get_reader()
        self.agent = agent if agent is not None else MockFibAgent(clock)
        self.init_events = []
        self.fib = Fib(
            node_name="me",
            clock=clock,
            config=FibConfig(route_delete_delay_ms=1000),
            agent=None if dryrun else self.agent,
            route_updates_reader=self.routes_q.get_reader(),
            fib_route_updates_queue=self.fib_out_q,
            initialization_cb=self.init_events.append,
            dryrun=dryrun,
        )
        self.fib.start()


def route(prefix, nh="fe80::1"):
    return RibUnicastEntry(prefix=prefix, nexthops={NextHop(address=nh, if_name="if1")})


def test_fib_programs_and_publishes():
    async def main():
        clock = SimClock()
        rig = FibRig(clock)
        rig.routes_q.push(
            DecisionRouteUpdate(
                type=DecisionRouteUpdateType.FULL_SYNC,
                unicast_routes_to_update={"10.0.0.0/24": route("10.0.0.0/24")},
            )
        )
        await clock.run_for(1.0)
        assert "10.0.0.0/24" in rig.agent.unicast
        assert rig.agent.num_sync == 1
        assert InitializationEvent.FIB_SYNCED in rig.init_events
        assert rig.fib_out_r.try_get() is not None  # republished downstream
        # incremental add
        rig.routes_q.push(
            DecisionRouteUpdate(
                unicast_routes_to_update={"10.1.0.0/24": route("10.1.0.0/24")}
            )
        )
        await clock.run_for(1.0)
        assert "10.1.0.0/24" in rig.agent.unicast
        await rig.fib.stop()

    run(main())


def test_fib_delete_is_delayed():
    async def main():
        clock = SimClock()
        rig = FibRig(clock)
        rig.routes_q.push(
            DecisionRouteUpdate(
                type=DecisionRouteUpdateType.FULL_SYNC,
                unicast_routes_to_update={"10.0.0.0/24": route("10.0.0.0/24")},
            )
        )
        await clock.run_for(0.5)
        rig.routes_q.push(
            DecisionRouteUpdate(unicast_routes_to_delete=["10.0.0.0/24"])
        )
        await clock.run_for(0.5)
        assert "10.0.0.0/24" in rig.agent.unicast  # still there (delay 1s)
        await clock.run_for(1.0)
        assert "10.0.0.0/24" not in rig.agent.unicast
        await rig.fib.stop()

    run(main())


def test_fib_retry_on_agent_failure():
    async def main():
        clock = SimClock()
        rig = FibRig(clock)
        rig.agent.fail = True
        rig.routes_q.push(
            DecisionRouteUpdate(
                type=DecisionRouteUpdateType.FULL_SYNC,
                unicast_routes_to_update={"10.0.0.0/24": route("10.0.0.0/24")},
            )
        )
        await clock.run_for(2.0)
        assert rig.agent.unicast == {}
        assert rig.fib.counters.get("fib.programming_failures") >= 1
        rig.agent.fail = False
        await clock.run_for(10.0)  # backoff max 4s
        assert "10.0.0.0/24" in rig.agent.unicast
        await rig.fib.stop()

    run(main())


def test_fib_agent_restart_triggers_resync():
    async def main():
        clock = SimClock()
        rig = FibRig(clock)
        rig.routes_q.push(
            DecisionRouteUpdate(
                type=DecisionRouteUpdateType.FULL_SYNC,
                unicast_routes_to_update={"10.0.0.0/24": route("10.0.0.0/24")},
            )
        )
        await clock.run_for(3.0)
        rig.agent.restart()
        assert rig.agent.unicast == {}
        await clock.run_for(3.0)  # keepalive every 1s
        assert "10.0.0.0/24" in rig.agent.unicast
        assert rig.fib.counters.get("fib.agent_restarts") == 1
        await rig.fib.stop()

    run(main())


def test_fib_dryrun_mode():
    async def main():
        clock = SimClock()
        rig = FibRig(clock, dryrun=True)
        rig.routes_q.push(
            DecisionRouteUpdate(
                type=DecisionRouteUpdateType.FULL_SYNC,
                unicast_routes_to_update={"10.0.0.0/24": route("10.0.0.0/24")},
            )
        )
        await clock.run_for(1.0)
        assert InitializationEvent.FIB_SYNCED in rig.init_events
        assert rig.fib.get_route_db().keys() == {"10.0.0.0/24"}
        assert rig.agent.unicast == {}  # nothing touched the agent
        await rig.fib.stop()

    run(main())


class PmRig:
    def __init__(self, clock, areas=None, originated=None):
        self.kv_q = ReplicateQueue("kvreq")
        self.kv_r = self.kv_q.get_reader()
        self.static_q = ReplicateQueue("static")
        self.static_r = self.static_q.get_reader()
        self.prefix_q = ReplicateQueue("prefixEvents")
        self.fib_q = ReplicateQueue("fibUpdates")
        self.init_events = []
        self.pm = PrefixManager(
            node_name="me",
            clock=clock,
            kv_request_queue=self.kv_q,
            static_route_updates_queue=self.static_q,
            prefix_updates_reader=self.prefix_q.get_reader(),
            fib_route_updates_reader=self.fib_q.get_reader(),
            areas=areas or ["0"],
            originated_prefixes=originated,
            initialization_cb=self.init_events.append,
        )
        self.pm.start()

    def drain_kv(self):
        out = []
        while (x := self.kv_r.try_get()) is not None:
            out.append(x)
        return out


def test_prefix_manager_advertise_withdraw():
    async def main():
        clock = SimClock()
        rig = PmRig(clock)
        await clock.run_for(0.5)
        assert InitializationEvent.PREFIX_DB_SYNCED in rig.init_events
        rig.drain_kv()
        rig.pm.advertise([PrefixEntry("10.1.0.0/16")])
        reqs = rig.drain_kv()
        assert len(reqs) == 1
        assert reqs[0].request_type == KvRequestType.PERSIST_KEY
        assert reqs[0].key == "prefix:me:[10.1.0.0/16]"
        db = deserialize_prefix_db(reqs[0].value)
        assert db.prefix_entries[0].prefix == "10.1.0.0/16"
        rig.pm.withdraw([PrefixEntry("10.1.0.0/16")])
        reqs = rig.drain_kv()
        assert any(r.request_type == KvRequestType.CLEAR_KEY for r in reqs)
        await rig.pm.stop()

    run(main())


def test_prefix_manager_originated_aggregation():
    async def main():
        clock = SimClock()
        rig = PmRig(
            clock,
            originated=[
                OriginatedPrefix(
                    "10.0.0.0/8", minimum_supporting_routes=2, install_to_fib=True
                )
            ],
        )
        await clock.run_for(0.5)
        rig.drain_kv()
        # one supporting route: not advertised yet
        rig.fib_q.push(
            DecisionRouteUpdate(
                unicast_routes_to_update={"10.1.0.0/24": route("10.1.0.0/24")}
            )
        )
        await clock.run_for(0.5)
        assert not rig.pm.get_originated_prefixes()["10.0.0.0/8"]["advertised"]
        # second: advertised + static route emitted
        rig.fib_q.push(
            DecisionRouteUpdate(
                unicast_routes_to_update={"10.2.0.0/24": route("10.2.0.0/24")}
            )
        )
        await clock.run_for(0.5)
        assert rig.pm.get_originated_prefixes()["10.0.0.0/8"]["advertised"]
        reqs = rig.drain_kv()
        assert any(r.key == "prefix:me:[10.0.0.0/8]" for r in reqs)
        st = rig.static_r.try_get()
        assert st is not None and "10.0.0.0/8" in st.unicast_routes_to_update
        # lose one: withdrawn
        rig.fib_q.push(
            DecisionRouteUpdate(unicast_routes_to_delete=["10.1.0.0/24"])
        )
        await clock.run_for(0.5)
        assert not rig.pm.get_originated_prefixes()["10.0.0.0/8"]["advertised"]
        reqs = rig.drain_kv()
        assert any(r.request_type == KvRequestType.CLEAR_KEY for r in reqs)
        await rig.pm.stop()

    run(main())


def test_prefix_manager_area_redistribution():
    async def main():
        clock = SimClock()
        rig = PmRig(clock, areas=["A", "B"])
        await clock.run_for(0.5)
        rig.drain_kv()
        # fib confirms a route learned in area A
        entry = RibUnicastEntry(
            prefix="10.5.0.0/24",
            nexthops={NextHop(address="fe80::1")},
            best_prefix_entry=PrefixEntry("10.5.0.0/24"),
            best_area="A",
            igp_cost=3,
        )
        rig.fib_q.push(
            DecisionRouteUpdate(unicast_routes_to_update={"10.5.0.0/24": entry})
        )
        await clock.run_for(0.5)
        reqs = rig.drain_kv()
        assert len(reqs) == 1
        assert reqs[0].area == "B"  # only into the other area
        db = deserialize_prefix_db(reqs[0].value)
        assert db.prefix_entries[0].area_stack == ["A"]
        assert db.prefix_entries[0].metrics.distance == 3
        # loop prevention: entry already through B never goes back into B
        entry2 = RibUnicastEntry(
            prefix="10.6.0.0/24",
            nexthops={NextHop(address="fe80::1")},
            best_prefix_entry=PrefixEntry("10.6.0.0/24", area_stack=["B"]),
            best_area="A",
            igp_cost=1,
        )
        rig.fib_q.push(
            DecisionRouteUpdate(unicast_routes_to_update={"10.6.0.0/24": entry2})
        )
        await clock.run_for(0.5)
        assert rig.drain_kv() == []
        # route deleted -> redistribution withdrawn
        rig.fib_q.push(
            DecisionRouteUpdate(unicast_routes_to_delete=["10.5.0.0/24"])
        )
        await clock.run_for(0.5)
        reqs = rig.drain_kv()
        assert any(r.request_type == KvRequestType.CLEAR_KEY for r in reqs)
        await rig.pm.stop()

    run(main())


def test_link_monitor_reflap_does_not_bypass_backoff():
    async def main():
        clock = SimClock()
        rig = LmRig(clock)
        up = InterfaceInfo("eth0", is_up=True, if_index=3, networks=["fe80::1/64"])
        down = InterfaceInfo("eth0", is_up=False, if_index=3)
        rig.lm.set_interfaces([up])
        await clock.run_for(0.5)
        rig.drain(rig.if_r)
        # flap 1: backoff 1s, activation at t+1
        rig.lm._on_interface_event(down)
        rig.lm._on_interface_event(up)
        await clock.run_for(0.6)
        # flap 2 at t+0.6: backoff 2s, activation must be at t+2.6 ONLY
        rig.lm._on_interface_event(down)
        rig.lm._on_interface_event(up)
        await clock.run_for(1.0)  # t+1.6: stale timer would have fired
        dbs = rig.drain(rig.if_r)
        assert all("eth0" not in d.interfaces for d in dbs), "stale activation"
        await clock.run_for(1.5)  # t+3.1 > t+2.6
        dbs = rig.drain(rig.if_r)
        assert dbs and "eth0" in dbs[-1].interfaces
        await rig.lm.stop()

    run(main())


def test_prefix_manager_same_prefix_two_types_deterministic():
    async def main():
        from openr_tpu.types import PrefixMetrics, PrefixType

        clock = SimClock()
        rig = PmRig(clock)
        await clock.run_for(0.5)
        rig.drain_kv()
        rig.pm.advertise(
            [PrefixEntry("10.1.0.0/16", metrics=PrefixMetrics(path_preference=100))],
            type=PrefixType.LOOPBACK,
        )
        rig.pm.advertise(
            [PrefixEntry("10.1.0.0/16", metrics=PrefixMetrics(path_preference=900))],
            type=PrefixType.BREEZE,
        )
        reqs = rig.drain_kv()
        db = deserialize_prefix_db(reqs[-1].value)
        # best metrics (higher path_preference) wins regardless of order
        assert db.prefix_entries[0].metrics.path_preference == 900
        await rig.pm.stop()

    run(main())


def test_fib_do_not_install_transition_withdraws():
    async def main():
        clock = SimClock()
        rig = FibRig(clock)
        rig.routes_q.push(
            DecisionRouteUpdate(
                type=DecisionRouteUpdateType.FULL_SYNC,
                unicast_routes_to_update={"10.0.0.0/24": route("10.0.0.0/24")},
            )
        )
        await clock.run_for(0.5)
        assert "10.0.0.0/24" in rig.agent.unicast
        # flip to do_not_install: must be withdrawn from the agent
        flipped = route("10.0.0.0/24")
        flipped.do_not_install = True
        rig.routes_q.push(
            DecisionRouteUpdate(unicast_routes_to_update={"10.0.0.0/24": flipped})
        )
        await clock.run_for(2.0)  # delete delay 1s
        assert "10.0.0.0/24" not in rig.agent.unicast
        # agent restart resync must NOT resurrect it
        rig.agent.restart()
        await clock.run_for(3.0)
        assert "10.0.0.0/24" not in rig.agent.unicast
        await rig.fib.stop()

    run(main())


def test_emulate_bringup_skips_occupied_ports():
    """`python -m openr_tpu --emulate N` must survive a foreign process
    holding a port in its ctrl range: skip forward, print each node's
    ACTUAL port, and quote the first node's real port in the hint
    (regression: a squatted port crashed bring-up mid-way on a shared
    host)."""
    import re
    import socket
    import subprocess
    import sys
    import time

    squat = socket.socket()
    squat.bind(("127.0.0.1", 0))
    base = squat.getsockname()[1]  # node0's port is taken
    squat.listen(1)
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "openr_tpu", "--emulate", "2",
             "--topology", "line", "--ctrl-base-port", str(base)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        lines = []
        t0 = time.time()
        while time.time() - t0 < 60:
            line = proc.stdout.readline()
            if not line:
                break
            lines.append(line.strip())
            if "nodes up" in line:
                break
        out = "\n".join(lines)
        ports = [int(m) for m in re.findall(r"127\.0\.0\.1:(\d+)", out)]
        assert len(ports) == 2, out
        assert base not in ports, out  # the squatted port was skipped
        assert f"--port {ports[0]} " in out, out  # hint quotes real port
    finally:
        proc.kill()
        proc.wait(timeout=10)
        squat.close()
