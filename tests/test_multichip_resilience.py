"""Per-device mesh health governance (ISSUE 6): the DevicePool shard
plane, per-chip shadow attribution — ONE lying chip is quarantined
individually, its shard re-packs onto the survivors, and the node keeps
serving — plus per-chip probed recovery and the 9-node emulation
acceptance with a ``tpu_corrupt(node, device_index=k)`` chaos fault,
deterministic from one seed.
"""

import asyncio

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import ParallelConfig, ResilienceConfig
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, ring_edges
from openr_tpu.parallel.mesh import DevicePool, make_mesh
from openr_tpu.types import PrefixEntry

pytestmark = pytest.mark.multichip


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# DevicePool + make_mesh validation (satellite)
# ---------------------------------------------------------------------------


def test_make_mesh_validates_and_pins_devices():
    import jax

    devices = jax.devices()
    assert len(devices) == 8  # the conftest's forced virtual mesh
    with pytest.raises(ValueError, match="only 8"):
        make_mesh(9)
    with pytest.raises(ValueError, match=">= 1"):
        make_mesh(0)
    # explicit devices= pins placement (survivor meshes, tests)
    mesh = make_mesh(devices=[devices[3], devices[5]])
    assert list(mesh.devices.flat) == [devices[3], devices[5]]
    with pytest.raises(ValueError, match="contradicts"):
        make_mesh(3, devices=devices[:2])
    with pytest.raises(ValueError, match="at least one"):
        make_mesh(devices=[])


def test_device_pool_shard_packing_and_health():
    pool = DevicePool()
    assert pool.size == 8 and pool.num_healthy == 8
    with pytest.raises(ValueError):
        DevicePool(max_devices=99)
    # even contiguous packing, remainder on the leading shards
    assert pool.shard_ranges(10) == [
        (0, 0, 2), (1, 2, 4), (2, 4, 5), (3, 5, 6), (4, 6, 7),
        (5, 7, 8), (6, 8, 9), (7, 9, 10),
    ]
    # devices that would get zero rows are dropped
    assert pool.shard_ranges(3) == [(0, 0, 1), (1, 1, 2), (2, 2, 3)]
    # quarantine re-packs onto survivors deterministically
    assert pool.quarantine_device(2)
    assert not pool.quarantine_device(2)  # idempotent
    assert pool.num_healthy == 7 and pool.lead_index() == 0
    assert 2 not in [d for d, _lo, _hi in pool.shard_ranges(14)]
    assert pool.restore_device(2)
    assert pool.healthy_mask() == [True] * 8
    assert pool.num_quarantines == 1 and pool.num_restores == 1


def test_device_pool_survivor_mesh_is_version_gated():
    from openr_tpu.parallel.mesh import shard_map_supported

    pool = DevicePool()
    if not shard_map_supported():
        assert pool.survivor_mesh() is None
    else:
        assert pool.survivor_mesh().devices.size == 8


# ---------------------------------------------------------------------------
# TpuBackend per-chip governance (small ring LSDB, forced sharding)
# ---------------------------------------------------------------------------


def make_world(n=6):
    ls = LinkState("0", "node0")
    for db in build_adj_dbs(ring_edges(n)).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(n):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.7.{i}.0/24"))
    return {"0": ls}, ps


def make_backend(clock, **kw):
    from openr_tpu.decision.backend import TpuBackend

    kw.setdefault("shadow_sample_every", 1)
    kw.setdefault("failure_threshold", 2)
    kw.setdefault("probe_backoff_initial_s", 1.0)
    kw.setdefault("probe_backoff_max_s", 8.0)
    kw.setdefault("jitter_pct", 0.0)
    return TpuBackend(
        SpfSolver("node0"),
        clock=clock,
        resilience=ResilienceConfig(**kw),
        # min_shard_rows=0: the tiny test world must actually shard
        # across the 8-chip pool so per-chip attribution is exercised
        parallel=ParallelConfig(min_shard_rows=0),
    )


def norm_db(db):
    return {
        p: (sorted((nh.neighbor_node_name, nh.metric) for nh in e.nexthops),
            float(e.igp_cost))
        for p, e in db.unicast_routes.items()
    }


def test_full_build_shards_across_the_pool_with_parity():
    als, ps = make_world()
    backend = make_backend(SimClock())
    db = backend.build_route_db(als, ps)
    assert backend._attr_plan is not None
    devs = [d for d, _lo, _hi in backend._attr_plan]
    assert len(devs) > 1, "tiny world must still shard (min_shard_rows=0)"
    assert norm_db(db) == norm_db(SpfSolver("node0").build_route_db(als, ps))


def test_mid_stream_chip_failure_quarantines_and_recovery_is_probed():
    """ISSUE-11 satellite: a shard failing at streamed drain time
    quarantines ITS chip via ``governor.record_stream_failure`` (unlike
    the old unattributable barrier raise, which scored the WHOLE-backend
    breaker), the build re-packs its exact row range onto survivors with
    no rows dropped or duplicated, and the chip earns its way back
    through the normal per-chip half-open probe cycle — no fault owner
    heal needed."""
    clock = SimClock()
    als, ps = make_world()
    backend = make_backend(clock)
    fired = []

    def fault(dev_index):
        if dev_index == 2 and not fired:
            fired.append(dev_index)
            raise RuntimeError("injected stream failure")

    backend._stream_fault = fault
    db = backend.build_route_db(als, ps)
    assert fired == [2]
    assert backend.num_stream_repacks == 1
    assert not backend.pool.is_healthy(2)
    assert norm_db(db) == norm_db(
        SpfSolver("node0").build_route_db(als, ps)
    )
    backend._stream_fault = None
    # the next build excludes the chip and stays correct
    db2 = backend.build_route_db(als, ps)
    assert 2 not in {d for d, _lo, _hi in (backend._attr_plan or ())}
    assert norm_db(db2) == norm_db(
        SpfSolver("node0").build_route_db(als, ps)
    )
    # after the breaker hold elapses, the chip probes back in on its
    # own (NOT injected-latched like chaos tpu_fail) and is restored
    clock._now += 60.0
    for _ in range(4):
        backend.build_route_db(als, ps)
        clock._now += 60.0
    assert backend.pool.is_healthy(2)


def test_one_corrupt_chip_is_quarantined_individually():
    als, ps = make_world()
    backend = make_backend(SimClock())
    gov = backend.governor
    oracle = norm_db(SpfSolver("node0").build_route_db(als, ps))
    backend.build_route_db(als, ps)
    backend.inject_silent_corruption(True, device_index=3)
    db = backend.build_route_db(als, ps, force_full=True)
    # detected on the sampled build; ONLY chip 3 quarantined; the
    # verified scalar answer is served; the node-level latch stays DOWN
    assert gov.num_shadow_mismatches == 1
    assert gov.num_chip_quarantines == 1 and gov.num_quarantines == 0
    assert not backend.device_failed
    assert backend.pool.healthy_mask() == [
        True, True, True, False, True, True, True, True
    ]
    assert norm_db(db) == oracle
    # the quarantine swap forces a whole-RIB diff (corrupt-entry purge)
    assert backend.take_full_replace()
    # survivors keep serving: the next build re-packs without chip 3
    db2 = backend.build_route_db(als, ps, force_full=True)
    assert 3 not in [d for d, _lo, _hi in backend._attr_plan]
    assert norm_db(db2) == oracle


def test_device_scoped_corrupt_purges_warm_context_and_repacks():
    """ISSUE-9 purge semantics, per-chip scope: a ``tpu_corrupt``
    targeting ONE chip during a warm-rebuild regime invalidates the
    warm context, the next build is cold and scalar-verified (catching
    the lying chip, which quarantines INDIVIDUALLY while its shard
    re-packs), and warm rebuilds resume on the survivors — with the
    quarantined chip's stale table replica dropped."""
    from openr_tpu.emulation.topology import build_adj_dbs as _adj

    adj = _adj(ring_edges(6))
    ls = LinkState("0", "node0")
    for db in adj.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(6):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.7.{i}.0/24"))
    als = {"0": ls}
    backend = make_backend(SimClock(), shadow_sample_every=100)
    gov = backend.governor

    def perturb(metric):
        db = adj["node3"]
        db.adjacencies[0].metric = metric
        ls.update_adjacency_database(db)

    backend.build_route_db(als, ps)  # first build (verified, cold)
    perturb(2)
    backend.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True, warm_delta=True
    )
    assert backend.num_warm_builds == 1
    assert backend._warm_ctx is not None
    # chip-scoped corruption: warm context purged IMMEDIATELY, and the
    # purge arms a forced shadow check for the next device build
    backend.inject_silent_corruption(True, device_index=3)
    assert backend._warm_ctx is None
    assert backend.num_warm_purges == 1
    db = backend.build_route_db(als, ps, force_full=True)
    assert gov.num_shadow_mismatches == 1
    assert gov.num_chip_quarantines == 1 and not backend.device_failed
    assert not backend.pool.is_healthy(3)
    assert norm_db(db) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    backend.inject_silent_corruption(False, device_index=3)
    # next perturbation: cold (context purged; the quarantine listener
    # purged again — idempotent), then the re-established context warms
    perturb(3)
    backend.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True, warm_delta=True
    )
    assert backend.num_warm_builds == 1
    assert backend._warm_fallback_reasons.get("no_context", 0) >= 1
    # the re-pack dropped the quarantined chip's table replica
    assert 3 not in backend._spf_replicas
    perturb(4)
    db = backend.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True, warm_delta=True
    )
    assert backend.num_warm_builds == 2
    assert norm_db(db) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    assert backend._warm_purge_reasons.get("tpu_corrupt", 0) >= 1
    assert backend._warm_purge_reasons.get("quarantine", 0) >= 1


def test_chip_probe_spans_carry_the_device_attr():
    """`resilience.probe` spans gain a `device` attr (ISSUE 6 tracing
    surface): per-chip probes are distinguishable in a trace."""
    from openr_tpu.tracing import Tracer

    als, ps = make_world()
    clock = SimClock()
    tracer = Tracer("node0", clock=clock)
    from openr_tpu.decision.backend import TpuBackend

    backend = TpuBackend(
        SpfSolver("node0"),
        clock=clock,
        tracer=tracer,
        resilience=ResilienceConfig(shadow_sample_every=1, jitter_pct=0.0),
        parallel=ParallelConfig(min_shard_rows=0),
    )
    gov = backend.governor
    backend.build_route_db(als, ps)
    gov.force_quarantine_device(4, reason="drill")
    gov.request_probe_device(4)
    backend.build_route_db(als, ps, force_full=True)
    probes = [s for s in tracer._done if s.name == "resilience.probe"]
    assert probes, "chip probe did not record a resilience.probe span"
    assert probes[-1].attrs.get("device") == 4
    assert probes[-1].attrs.get("passed") is True


def test_failed_chip_probe_doubles_backoff_then_recovery_is_probed():
    als, ps = make_world()
    clock = SimClock()
    backend = make_backend(clock)
    gov = backend.governor
    oracle = norm_db(SpfSolver("node0").build_route_db(als, ps))
    backend.build_route_db(als, ps)
    backend.inject_silent_corruption(True, device_index=3)
    backend.build_route_db(als, ps, force_full=True)
    br3 = gov._chip_breaker(3)
    hold0 = br3.current_hold_s()
    # hold elapses while the chip is STILL lying: the probe shard rides
    # a survivor build, fails verification, and the backoff doubles —
    # the rest of the pool keeps serving throughout
    clock._now += hold0 + 0.5
    db = backend.build_route_db(als, ps, force_full=True)
    assert br3.num_probe_failures == 1
    assert br3.current_hold_s() == 2 * hold0
    assert not backend.pool.is_healthy(3) and not backend.device_failed
    assert norm_db(db) == oracle
    # heal: recovery happens ONLY via a shadow-verified probe on chip 3
    backend.inject_silent_corruption(False, device_index=3)
    gov.request_probe_device(3)
    db2 = backend.build_route_db(als, ps, force_full=True)
    assert backend.pool.is_healthy(3)
    assert gov.num_chip_restores == 1
    assert gov.last_probe.get("device") == 3 and gov.last_probe["passed"]
    assert norm_db(db2) == oracle


def test_chip_tpu_fail_is_injected_no_probes_until_requested():
    als, ps = make_world()
    clock = SimClock()
    backend = make_backend(clock)
    gov = backend.governor
    backend.build_route_db(als, ps)
    gov.force_quarantine_device(5, reason="chaos")
    assert not backend.pool.is_healthy(5) and not backend.device_failed
    # injected chip outage: NO probe shards, however long the clock runs
    clock._now += 500.0
    backend.build_route_db(als, ps, force_full=True)
    assert 5 not in [d for d, _lo, _hi in backend._attr_plan]
    assert not backend.pool.is_healthy(5)
    # the heal is probed, never trusted blindly
    gov.request_probe_device(5, reason="chaos_heal")
    assert not backend.pool.is_healthy(5)
    backend.build_route_db(als, ps, force_full=True)
    assert backend.pool.is_healthy(5) and gov.num_chip_restores == 1


def test_zero_healthy_chips_is_the_degenerate_whole_device_outage():
    als, ps = make_world()
    clock = SimClock()
    backend = make_backend(clock)
    gov = backend.governor
    backend.build_route_db(als, ps)
    for k in range(backend.pool.size):
        gov.force_quarantine_device(k, reason="drain")
    # every chip out == the whole device is out: the same latch route
    # builds/serving/what-if already degrade on
    assert backend.device_failed
    before = backend.num_device_builds
    db = backend.build_route_db(als, ps)
    assert backend.num_device_builds == before  # scalar fallback
    assert norm_db(db) == norm_db(SpfSolver("node0").build_route_db(als, ps))
    # chips recover one at a time via their own probed breakers
    gov.request_probe_device(2, reason="heal")
    db2 = backend.build_route_db(als, ps, force_full=True)
    assert backend.pool.is_healthy(2)
    assert not backend.device_failed
    assert norm_db(db2) == norm_db(
        SpfSolver("node0").build_route_db(als, ps)
    )


def test_legacy_all_shard_corruption_still_trips_the_backend_latch():
    """Unattributable corruption (every exercised chip lying) keeps the
    PR-5 whole-backend semantics: scalar serve + aggregate quarantine,
    converging within a couple of sampled builds even when the batch
    was sharded."""
    als, ps = make_world()
    backend = make_backend(SimClock())
    oracle = norm_db(SpfSolver("node0").build_route_db(als, ps))
    backend.build_route_db(als, ps)
    backend.inject_silent_corruption(True)
    for _ in range(4):
        db = backend.build_route_db(als, ps, force_full=True)
        assert norm_db(db) == oracle  # the scalar answer is ALWAYS served
        if backend.device_failed:
            break
    assert backend.device_failed


def test_per_device_sdc_chaos_plan_wiring():
    """tpu_corrupt/tpu_fail carry device_index through plan + label."""
    from openr_tpu.chaos import FaultPlan

    plan = FaultPlan()
    plan.tpu_corrupt("node4", at=1.0, duration=5.0, device_index=3)
    plan.tpu_fail("node2", at=2.0, duration=5.0, device_index=1)
    labels = [f.label() for f in plan.faults]
    assert labels == ["tpu_corrupt.3.node4", "tpu_fail.1.node2"]
    # seeded sweeps draw per-chip faults only when num_devices is given
    a = FaultPlan.seeded(7, ["n0", "n1"], [("n0", "n1")], num_faults=24)
    b = FaultPlan.seeded(7, ["n0", "n1"], [("n0", "n1")], num_faults=24)
    assert a.faults == b.faults  # same seed, same plan
    c = FaultPlan.seeded(
        7, ["n0", "n1"], [("n0", "n1")], num_faults=64, num_devices=8
    )
    assert any(
        "device_index" in f.args
        for f in c.faults
        if f.kind in ("tpu_fail", "tpu_corrupt")
    )


# ---------------------------------------------------------------------------
# 9-node emulation acceptance: per-chip tpu_corrupt under chaos —
# detect -> quarantine chip k only -> survivors keep serving -> probed
# per-chip recovery, deterministic from one seed
# ---------------------------------------------------------------------------

VICTIM = "node4"
BAD_CHIP = 3
SAMPLE_EVERY = 2


def _overrides(cfg):
    cfg.watchdog_config.interval_s = 1.0
    cfg.tpu_compute_config.min_device_prefixes = 0  # always device
    cfg.parallel_config = ParallelConfig(min_shard_rows=0)
    cfg.resilience_config = ResilienceConfig(
        shadow_sample_every=SAMPLE_EVERY,
        failure_threshold=2,
        probe_backoff_initial_s=0.5,
        probe_backoff_max_s=4.0,
        jitter_pct=0.1,
        seed=7,
    )


async def _per_chip_corrupt_run():
    from openr_tpu.chaos import ChaosController, FaultPlan, InvariantChecker
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import grid_edges

    clock = SimClock()
    net = EmulatedNetwork(
        clock, use_tpu_backend=True, config_overrides=_overrides
    )
    net.build(grid_edges(3))  # 9 nodes
    net.start()
    checker = InvariantChecker(net)
    plan = FaultPlan().tpu_corrupt(
        VICTIM, at=2.0, duration=14.0, device_index=BAD_CHIP
    )
    controller = ChaosController(net, plan, seed=7)

    await clock.run_for(18.0)
    ok, why = net.converged_full_mesh()
    assert ok, why
    victim = net.nodes[VICTIM]
    backend = victim.decision.backend
    gov = backend.governor
    assert gov is not None and not gov.quarantined
    assert backend.pool.size == 8  # the conftest's forced host devices
    # widen the candidate table so EVERY chip's shard holds at least two
    # real prefix rows (9 loopbacks over 8 chips would leave single-row
    # shards, and a shard holding only the victim's own self-skipped
    # prefix would make its corruption invisible by construction)
    net.nodes["node0"].advertise_prefixes(
        [PrefixEntry(f"10.99.{i}.0/24") for i in range(9)]
    )
    await clock.run_for(3.0)

    controller.start()
    await clock.run_for(3.0)  # corruption live at t=2 on chip 3 only
    # drive FULL rebuilds during the corrupt window (a link-down is a
    # topology change, so every node runs a sharded full build; a
    # DIFFERENT link each time — a refailed link whose adjacency never
    # re-formed would be a no-op publication).  Detection must land
    # within ONE shadow-sample interval of device builds.
    flapped = [("node0", "node1"), ("node1", "node2")][:SAMPLE_EVERY]
    for a, b in flapped:
        net.fail_link(a, b)
        await clock.run_for(2.0)
        checker.sample()
        if gov.num_shadow_mismatches:
            break
    assert gov.num_shadow_mismatches >= 1, (
        "per-chip silent corruption escaped shadow verification"
    )
    # ONLY chip k is quarantined: 7 survivors, node latch DOWN
    assert gov.num_chip_quarantines >= 1
    assert not backend.pool.is_healthy(BAD_CHIP)
    assert backend.pool.num_healthy == 7
    assert not backend.device_failed
    assert gov.num_quarantines == 0  # no whole-backend quarantine
    # ...so serving and what-if queries KEEP using the device engines
    assert victim.decision.device_available()
    summary = victim.decision.get_fleet_rib_summary()
    assert summary is not None and len(summary) == 9
    edges = [["node3", "node4"], ["node1", "node4"]]
    whatif = victim.decision.get_link_failure_whatif(edges)
    assert whatif is not None and whatif["eligible"]
    # the victim's FIB stays exact (scalar swap on the mismatch build,
    # survivor shards after): routes match a fresh oracle, no blackholes
    checker.check_no_blackholes()
    oracle = SpfSolver(VICTIM).build_route_db(
        victim.decision.area_link_states, victim.decision.prefix_state
    )
    assert norm_db(victim.decision.route_db) == norm_db(oracle)

    # restore the failed links and let the mesh re-converge (these full
    # rebuilds run on the 7 survivors; chip-3 probe shards that ride
    # them FAIL verification while the corruption is live, doubling its
    # backoff — recovery must wait for the heal)
    for a, b in flapped:
        net.restore_link(a, b)
    await clock.run_for(5.0)
    # heal fires at t=16 on the chaos clock (chaos requests a probe on
    # chip 3); drive one more full rebuild to carry the probe shard
    await clock.run_for(6.0)
    net.fail_link("node6", "node7")
    await clock.run_for(2.0)
    net.restore_link("node6", "node7")
    await clock.run_for(3.0)
    assert backend.pool.is_healthy(BAD_CHIP), (
        "chip not restored after heal + probe"
    )
    assert gov.num_chip_restores >= 1
    assert gov._chip_breaker(BAD_CHIP).num_probes >= 1
    assert not backend.device_failed

    await clock.run_for(8.0)
    checker.check_all()
    assert controller.done

    chaos_dump = controller.counter_dump()
    resilience_dump = victim.counters.dump("resilience.")
    assert (
        resilience_dump.get("resilience.backend.shadow_mismatches", 0) >= 1
    )
    await controller.stop()
    await net.stop()
    return chaos_dump, resilience_dump


@pytest.mark.chaos
def test_per_chip_corrupt_quarantine_survivors_serve_deterministic():
    a = run(_per_chip_corrupt_run())
    b = run(_per_chip_corrupt_run())
    # reproducibility contract: same seed => byte-identical dumps
    assert a == b
    chaos_dump, _ = a
    assert chaos_dump["chaos.injects"] == 1
    assert chaos_dump["chaos.heals"] == 1
    assert f"chaos.inject.tpu_corrupt.{BAD_CHIP}.{VICTIM}" in chaos_dump
