"""Chaos-verified alert fidelity (ISSUE 8 acceptance): every alert rule
is provably wired to a real failure mode before anyone trusts it.

For each seeded FaultPlan fault family the suite asserts the EXACT
expected alert set fires within a bounded number of sweeps on a live
9-node grid emulation; a clean seeded run fires ZERO alerts (the
false-positive gate); and two replays of one seed produce byte-identical
alert JSONL (the same contract the chaos counter dumps and flight
recorder already honor).

Fault family -> expected alert set:

  partition                     {generation_skew}        (resolves on heal)
  tpu_corrupt(device_index=3)   {chip_quarantine}        (resolves on probe)
  fib_burst                     {breaker_open}           (resolves on heal)
  actor_kill + supervisor       {node_crash}             (latched: crashes
                                                          don't un-happen)
  degraded convergence SLO      {slo_convergence_p99}    (+ page dump)
"""

import asyncio
import json

import pytest

from openr_tpu.chaos import ChaosController, FaultPlan, Supervisor
from openr_tpu.common.runtime import SimClock
from openr_tpu.config import ParallelConfig, ResilienceConfig, SloSpecConfig
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import grid_edges
from openr_tpu.types import PrefixEntry

pytestmark = [pytest.mark.health, pytest.mark.chaos]

SEED = 7
CONVERGE_S = 18.0
SWEEP_S = 2.0
#: alert must land within this many aggregator sweeps of fault onset
DETECTION_SWEEP_BOUND = 8


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def health_overrides(cfg, tpu=False):
    hc = cfg.health_config
    hc.sweep_interval_s = SWEEP_S
    hc.skew_min_generations = 2
    hc.skew_hold_s = 4.0
    cfg.watchdog_config.interval_s = 1.0
    if tpu:
        cfg.tpu_compute_config.min_device_prefixes = 0  # always device
        cfg.parallel_config = ParallelConfig(min_shard_rows=0)
        cfg.resilience_config = ResilienceConfig(
            shadow_sample_every=2,
            failure_threshold=2,
            probe_backoff_initial_s=0.5,
            probe_backoff_max_s=4.0,
            jitter_pct=0.1,
            seed=SEED,
        )


def fired_names(net, watcher="node0"):
    h = net.nodes[watcher].health
    return sorted({json.loads(line)["name"] for line in h.alert_log()})


def active_names(net, watcher="node0"):
    return sorted(
        a["name"] for a in net.nodes[watcher].health.active_alerts()
    )


async def converge(net, clock):
    await clock.run_for(CONVERGE_S)
    ok, why = net.converged_full_mesh()
    assert ok, why


async def sweeps_until(net, clock, predicate, bound=DETECTION_SWEEP_BOUND):
    """Advance one sweep interval at a time until `predicate(net)`;
    returns the sweep count consumed.  Failing the bound fails the
    detection-latency acceptance for the family under test."""
    for i in range(bound):
        if predicate(net):
            return i
        await clock.run_for(SWEEP_S)
    assert predicate(net), (
        f"expected alerts not present within {bound} sweeps; "
        f"fired={fired_names(net)}"
    )
    return bound


# ---------------------------------------------------------------------------
# false-positive gate: a clean seeded run fires ZERO alerts
# ---------------------------------------------------------------------------


def test_clean_run_fires_zero_alerts():
    async def scenario():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=health_overrides)
        net.build(grid_edges(3))
        net.start()
        await converge(net, clock)
        # ordinary life: prefix churn, an uneventful link flap, idle time
        for i in range(3):
            net.nodes["node0"].advertise_prefixes(
                [PrefixEntry(f"10.90.{i}.0/24")]
            )
            await clock.run_for(4.0)
        net.fail_link("node0", "node1")
        await clock.run_for(4.0)
        net.restore_link("node0", "node1")
        await clock.run_for(20.0)
        for name, node in net.nodes.items():
            assert node.health.alert_log() == [], (
                f"{name} logged alerts on a clean run"
            )
            assert node.health.active_alerts() == []
        status = net.nodes["node0"].health.status()
        assert status["sweeps"] >= 10
        assert all(not s["firing"] for s in status["slos"])
        await net.stop()

    run(scenario())


# ---------------------------------------------------------------------------
# fault family: partition -> generation_skew, resolved on heal
# ---------------------------------------------------------------------------


def test_partition_fires_exactly_generation_skew():
    async def scenario():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=health_overrides)
        net.build(grid_edges(3))
        net.start()
        await converge(net, clock)
        others = [f"node{i}" for i in range(8)]
        plan = FaultPlan().partition(others, ["node8"], at=1.0, duration=16.0)
        controller = ChaosController(net, plan, seed=SEED)
        controller.start()
        await clock.run_for(2.0)
        # LSDB churn on the majority side that node8 cannot see
        for i in range(DETECTION_SWEEP_BOUND):
            if "generation_skew" in active_names(net):
                break
            net.nodes["node0"].advertise_prefixes(
                [PrefixEntry(f"10.91.{i}.0/24")]
            )
            await clock.run_for(SWEEP_S)
        assert active_names(net) == ["generation_skew"]
        h = net.nodes["node0"].health
        assert h.sink.active["generation_skew"]["stale_nodes"] == ["node8"]
        # heal at t=+17; node8 full-syncs and advances again -> resolved
        await clock.run_for(10.0)
        for i in range(4):
            net.nodes["node0"].advertise_prefixes(
                [PrefixEntry(f"10.92.{i}.0/24")]
            )
            await clock.run_for(SWEEP_S)
        assert active_names(net) == []
        assert fired_names(net) == ["generation_skew"]
        events = [json.loads(line)["event"] for line in h.alert_log()]
        assert events == ["fired", "resolved"]
        await controller.stop()
        await net.stop()

    run(scenario())


# ---------------------------------------------------------------------------
# fault family: per-chip silent corruption -> chip_quarantine, probed back
# ---------------------------------------------------------------------------

VICTIM = "node4"
BAD_CHIP = 3


async def _chip_corrupt_run():
    clock = SimClock()
    net = EmulatedNetwork(
        clock,
        use_tpu_backend=True,
        config_overrides=lambda cfg: health_overrides(cfg, tpu=True),
    )
    net.build(grid_edges(3))
    net.start()
    await converge(net, clock)
    # widen the candidate table so every chip's shard holds real rows
    net.nodes["node0"].advertise_prefixes(
        [PrefixEntry(f"10.99.{i}.0/24") for i in range(9)]
    )
    await clock.run_for(3.0)
    plan = FaultPlan().tpu_corrupt(
        VICTIM, at=2.0, duration=14.0, device_index=BAD_CHIP
    )
    controller = ChaosController(net, plan, seed=SEED)
    controller.start()
    await clock.run_for(3.0)  # corruption live on chip 3
    gov = net.nodes[VICTIM].decision.backend.governor
    detect_sweeps = 0
    for a, b in [("node0", "node1"), ("node1", "node2")]:
        net.fail_link(a, b)
        await clock.run_for(SWEEP_S)
        detect_sweeps += 1
        if gov.num_shadow_mismatches:
            break
    assert gov.num_chip_quarantines >= 1
    await sweeps_until(
        net, clock, lambda n: "chip_quarantine" in active_names(n)
    )
    assert active_names(net) == ["chip_quarantine"]
    h = net.nodes["node0"].health
    assert h.sink.active["chip_quarantine"]["nodes"] == [VICTIM]
    chips = h.status()["chips"]
    assert chips["quarantined"] == 1
    assert chips["per_node"][VICTIM]["healthy"] == chips["per_node"][VICTIM][
        "size"
    ] - 1
    # page severity: the watcher froze a detection-time post-mortem
    assert h.sink.num_page_dumps == 1
    # heal at t=+16 requests a probe; churn drives the probe build and
    # the chip earns its way back -> alert resolves
    await clock.run_for(14.0)
    for i in range(6):
        if active_names(net) == []:
            break
        net.nodes["node0"].advertise_prefixes(
            [PrefixEntry(f"10.93.{i}.0/24")]
        )
        await clock.run_for(SWEEP_S)
    assert active_names(net) == []
    assert fired_names(net) == ["chip_quarantine"]
    log = h.sink.log_bytes()
    await controller.stop()
    await net.stop()
    return log


@pytest.mark.multichip
def test_chip_corrupt_fires_exactly_chip_quarantine_and_replays():
    """The per-chip SDC family AND the determinism acceptance: two
    replays of one seed produce byte-identical alert JSONL."""
    log_a = run(_chip_corrupt_run())
    log_b = run(_chip_corrupt_run())
    assert log_a == log_b, "same seed must produce byte-identical logs"
    events = [json.loads(line) for line in log_a.decode().splitlines()]
    assert [e["event"] for e in events] == ["fired", "resolved"]
    assert events[0]["name"] == "chip_quarantine"
    assert events[0]["severity"] == "page"


# ---------------------------------------------------------------------------
# fault family: fib-agent burst -> breaker_open, resolved after heal
# ---------------------------------------------------------------------------


def test_fib_burst_fires_exactly_breaker_open():
    async def scenario():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=health_overrides)
        net.build(grid_edges(3))
        net.start()
        await converge(net, clock)
        plan = FaultPlan().fib_burst(VICTIM, at=1.0, duration=8.0)
        controller = ChaosController(net, plan, seed=SEED)
        controller.start()
        await clock.run_for(1.5)  # burst live at t=+1
        # route churn forces FIB programming attempts into the burst
        detect = DETECTION_SWEEP_BOUND
        for i in range(DETECTION_SWEEP_BOUND):
            if "breaker_open" in active_names(net):
                detect = i
                break
            net.nodes["node0"].advertise_prefixes(
                [PrefixEntry(f"10.94.{i}.0/24")]
            )
            await clock.run_for(SWEEP_S)
        assert detect <= DETECTION_SWEEP_BOUND
        assert active_names(net) == ["breaker_open"]
        h = net.nodes["node0"].health
        edges = h.sink.active["breaker_open"]["edges"]
        assert any(VICTIM in e and "fib_agent" in e for e in edges)
        # heal at t=+9: retries probe the breaker closed -> resolved
        await clock.run_for(12.0)
        net.nodes["node0"].advertise_prefixes([PrefixEntry("10.94.1.0/24")])
        for _ in range(6):
            if active_names(net) == []:
                break
            await clock.run_for(SWEEP_S)
        assert active_names(net) == []
        assert fired_names(net) == ["breaker_open"]
        await controller.stop()
        await net.stop()

    run(scenario())


# ---------------------------------------------------------------------------
# fault family: crash-kill under supervision -> node_crash (latched)
# ---------------------------------------------------------------------------


def test_actor_kill_fires_exactly_node_crash():
    async def scenario():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=health_overrides)
        net.build(grid_edges(3))
        net.start()
        supervisor = Supervisor(
            clock, initial_backoff_s=0.25, max_backoff_s=5.0
        )
        supervisor.start()
        for name, node in net.nodes.items():
            supervisor.supervise(name, node, net.restart_node)
        await converge(net, clock)
        plan = FaultPlan().actor_kill(VICTIM, "decision", at=1.0)
        controller = ChaosController(net, plan, seed=SEED)
        controller.start()
        detect = await sweeps_until(
            net, clock, lambda n: "node_crash" in active_names(n)
        )
        assert detect <= DETECTION_SWEEP_BOUND
        assert supervisor.num_restarts >= 1
        assert active_names(net) == ["node_crash"]
        h = net.nodes["node0"].health
        detail = h.sink.active["node_crash"]
        assert detail["crashes_seen"] + detail["restarts_seen"] >= 1
        # crashes do not un-happen: still latched after full recovery
        await clock.run_for(20.0)
        assert active_names(net) == ["node_crash"]
        assert fired_names(net) == ["node_crash"]
        await supervisor.stop()
        await controller.stop()
        await net.stop()

    run(scenario())


# ---------------------------------------------------------------------------
# SLO burn-rate family: degraded convergence objective pages + dumps
# ---------------------------------------------------------------------------


def test_degraded_convergence_slo_burns_and_pages():
    """With the convergence p99 objective tightened below real protocol
    latency, sustained flap churn must burn both windows, page, and
    freeze a detection-time flight dump — proving the burn-rate engine
    is wired to the real SLI, not a synthetic."""

    def overrides(cfg):
        health_overrides(cfg)
        cfg.health_config.slos = [
            SloSpecConfig(
                name="slo_convergence_p99",
                metric="convergence.event_to_fib_ms",
                threshold=50.0,  # impossibly tight: protocol time is ~1s
                objective=0.05,
                fast_window_s=4.0,
                slow_window_s=8.0,
                burn_threshold=2.0,
            )
        ]

    async def scenario():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=overrides)
        net.build(grid_edges(3))
        net.start()
        await converge(net, clock)
        edges = [("node0", "node1"), ("node3", "node4"), ("node6", "node7")]
        for i in range(DETECTION_SWEEP_BOUND):
            if "slo_convergence_p99" in active_names(net):
                break
            a, b = edges[i % len(edges)]
            net.fail_link(a, b)
            await clock.run_for(SWEEP_S)
            net.restore_link(a, b)
            await clock.run_for(SWEEP_S)
        assert "slo_convergence_p99" in active_names(net)
        h = net.nodes["node0"].health
        detail = h.sink.active["slo_convergence_p99"]
        assert detail["fast_burn"] >= 2.0 and detail["slow_burn"] >= 2.0
        assert detail["value"] > 50.0
        # page severity -> detection-time post-mortem on the watcher
        assert h.sink.num_page_dumps == 1
        assert net.nodes["node0"].flight_recorder.last_reason == (
            "health_page_alert"
        )
        await net.stop()

    run(scenario())
