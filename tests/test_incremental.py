"""Per-prefix incremental rebuild — differential tests vs full rebuild.

Reference parity: openr/decision/Decision.cpp:908-952 recomputes only
changed prefixes on prefix-only deltas.  Both backends must produce a
RouteDb identical to a from-scratch full build after ANY interleaving of
prefix adds/updates/deletes (and topology changes, which force the full
path)."""

import random

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.decision.backend import ScalarBackend, TpuBackend
from openr_tpu.decision.cand_table import CandidateTable
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import route_db_summary
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
from openr_tpu.types import PrefixEntry, PrefixMetrics


def make_link_state(n=4, **kwargs):
    edges = grid_edges(n)
    dbs = build_adj_dbs(edges, **kwargs)
    ls = LinkState("0", "node0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    return ls


def rand_entry(rng, prefix):
    return PrefixEntry(
        prefix,
        metrics=PrefixMetrics(
            path_preference=rng.choice([500, 1000]),
            source_preference=rng.choice([100, 200]),
            distance=rng.randint(0, 3),
            drain_metric=rng.choice([0, 0, 0, 1]),
        ),
        min_nexthop=rng.choice([None, None, None, 1, 5]),
    )


def churn_once(rng, ps, num_nodes, prefixes):
    """One random prefix mutation; returns the changed-prefix set."""
    op = rng.random()
    prefix = rng.choice(prefixes)
    node = f"node{rng.randrange(num_nodes)}"
    if op < 0.6:
        return ps.update_prefix(node, "0", rand_entry(rng, prefix))
    if op < 0.85:
        return ps.delete_prefix(node, "0", prefix)
    # delete every advertisement of the prefix
    changed = set()
    for (n, a) in list(ps.prefixes().get(prefix, {})):
        changed |= ps.delete_prefix(n, a, prefix)
    return changed


# -- CandidateTable ---------------------------------------------------------


def test_candidate_table_dirty_equals_full():
    """Dirty application over random churn must equal a fresh full sync
    (per-prefix row content, not row placement)."""
    from openr_tpu.ops.csr import encode_multi_area

    rng = random.Random(11)
    ls = make_link_state(4)
    enc = encode_multi_area({"0": ls}, "node0")
    prefixes = [f"10.{i}.0.0/24" for i in range(20)]
    ps = PrefixState()
    for p in prefixes[:10]:
        ps.update_prefix(f"node{rng.randrange(16)}", "0", rand_entry(rng, p))

    inc = CandidateTable()
    inc.full_sync(ps)
    inc.derived(enc)
    for _ in range(200):
        changed = churn_once(rng, ps, 16, prefixes)
        inc.apply_dirty(ps, changed)
        d_inc = inc.derived(enc)

        fresh = CandidateTable()
        fresh.full_sync(ps)
        d_fresh = fresh.derived(enc)

        def row_view(table, d, prefix):
            r = table.pid.get(prefix)
            if r is None:
                return None
            cands = []
            for c in range(table.C):
                if not d.cand_ok[r, c]:
                    continue
                cands.append(
                    (
                        int(d.cand_area[r, c]),
                        int(d.cand_node[r, c]),
                        int(d.drain_metric[r, c]),
                        int(d.path_pref[r, c]),
                        int(d.source_pref[r, c]),
                        int(d.distance[r, c]),
                        int(d.min_nexthop[r, c]),
                        tuple(int(x) for x in d.cand_node_in_area[r, c]),
                    )
                )
            return sorted(cands)

        for p in prefixes:
            assert row_view(inc, d_inc, p) == row_view(fresh, d_fresh, p), p


def test_candidate_table_row_reuse_and_widening():
    ps = PrefixState()
    ps.update_prefix("node1", "0", PrefixEntry("10.0.0.0/24"))
    t = CandidateTable()
    t.full_sync(ps)
    assert t.num_prefixes == 1
    # delete frees the row
    changed = ps.delete_prefix("node1", "0", "10.0.0.0/24")
    t.apply_dirty(ps, changed)
    assert t.num_prefixes == 0
    free_before = len(t._free)
    # new prefix reuses it
    changed = ps.update_prefix("node2", "0", PrefixEntry("10.1.0.0/24"))
    t.apply_dirty(ps, changed)
    assert t.num_prefixes == 1
    assert len(t._free) == free_before - 1
    # widening: 3 candidates exceeds C=1, widens to bucket 4
    assert t.C == 1
    for n in ("node3", "node4", "node5"):
        t.apply_dirty(
            ps, ps.update_prefix(n, "0", PrefixEntry("10.1.0.0/24"))
        )
    assert t.C == 4
    assert (t.adv_gid[t.pid["10.1.0.0/24"]] >= 0).sum() == 4


# -- backend differentials --------------------------------------------------


@pytest.mark.parametrize("backend_cls", [ScalarBackend, TpuBackend])
def test_backend_incremental_matches_full(backend_cls):
    rng = random.Random(23)
    ls = make_link_state(4, soft_drained={"node10": 60})
    als = {"0": ls}
    prefixes = [f"10.{i}.0.0/24" for i in range(24)] + ["2001:db8::/64"]
    ps = PrefixState()
    for p in prefixes[:12]:
        ps.update_prefix(f"node{rng.randrange(16)}", "0", rand_entry(rng, p))

    backend = backend_cls(SpfSolver("node0"))
    db = backend.build_route_db(als, ps)  # initial full
    assert db is not None
    for step in range(60):
        changed = set()
        for _ in range(rng.randint(1, 4)):
            changed |= churn_once(rng, ps, 16, prefixes)
        db = backend.build_route_db(als, ps, changed_prefixes=changed)
        oracle = ScalarBackend(SpfSolver("node0")).build_route_db(als, ps)
        assert route_db_summary(db) == route_db_summary(oracle), step
    if backend_cls is TpuBackend:
        assert backend.num_incremental_builds >= 50
        assert backend.num_scalar_builds == 0


def test_tpu_incremental_across_topology_change():
    """Topology churn mid-sequence: Decision passes force_full, the
    backend re-encodes, and subsequent prefix-only deltas patch again."""
    rng = random.Random(5)
    edges = grid_edges(4)
    dbs = build_adj_dbs(edges)
    ls = LinkState("0", "node0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    als = {"0": ls}
    prefixes = [f"10.{i}.0.0/24" for i in range(10)]
    ps = PrefixState()
    for p in prefixes:
        ps.update_prefix(f"node{rng.randrange(16)}", "0", rand_entry(rng, p))

    backend = TpuBackend(SpfSolver("node0"))
    backend.build_route_db(als, ps)
    ch = churn_once(rng, ps, 16, prefixes)
    backend.build_route_db(als, ps, changed_prefixes=ch)
    inc_before = backend.num_incremental_builds
    assert inc_before >= 1

    # drop node15's adjacencies → topology change → force_full
    ls.delete_adjacency_database("node15")
    db = backend.build_route_db(als, ps, changed_prefixes=set(), force_full=True)
    oracle = ScalarBackend(SpfSolver("node0")).build_route_db(als, ps)
    assert route_db_summary(db) == route_db_summary(oracle)
    assert backend.num_incremental_builds == inc_before

    # prefix-only churn after the topology change patches again
    ch = churn_once(rng, ps, 15, prefixes)
    db = backend.build_route_db(als, ps, changed_prefixes=ch)
    oracle = ScalarBackend(SpfSolver("node0")).build_route_db(als, ps)
    assert route_db_summary(db) == route_db_summary(oracle)
    assert backend.num_incremental_builds == inc_before + 1


def test_tpu_table_resync_after_me_absent_tick():
    """A tick where the local node vanishes from every area returns None
    BEFORE the candidate table sees that tick's prefix churn; the table
    must be marked stale so the next build re-reads PrefixState instead
    of serving stale candidate rows (code-review regression)."""
    ls = make_link_state(4)
    als = {"0": ls}
    ps = PrefixState()
    ps.update_prefix(
        "node8",
        "0",
        PrefixEntry("10.0.0.1/32", metrics=PrefixMetrics(path_preference=100)),
    )
    backend = TpuBackend(SpfSolver("node0"))
    backend.build_route_db(als, ps)

    # tick 1: node0 leaves the graph AND the prefix gains a better
    # advertiser — the me-absent early return consumes this delta
    saved_db = ls.get_adjacency_databases()["node0"]
    ls.delete_adjacency_database("node0")
    changed = ps.update_prefix(
        "node4",
        "0",
        PrefixEntry("10.0.0.1/32", metrics=PrefixMetrics(path_preference=1000)),
    )
    assert (
        backend.build_route_db(als, ps, changed_prefixes=changed) is None
    )

    # tick 2: node0 returns (topology change → force_full, empty delta)
    ls.update_adjacency_database(saved_db)
    db = backend.build_route_db(
        als, ps, changed_prefixes=set(), force_full=True
    )
    oracle = ScalarBackend(SpfSolver("node0")).build_route_db(als, ps)
    assert route_db_summary(db) == route_db_summary(oracle)
    assert (
        db.unicast_routes["10.0.0.1/32"].best_prefix_entry.metrics
        .path_preference
        == 1000
    )


def test_decision_actor_incremental_builds():
    """End-to-end through the Decision actor: prefix-only publications
    after the first build run the incremental path and the final RouteDb
    matches a fresh scalar oracle."""
    import asyncio
    import json

    from openr_tpu.config import DecisionConfig
    from openr_tpu.decision.decision import Decision
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.types import (
        InitializationEvent,
        PrefixDatabase,
        Publication,
        Value,
        prefix_key,
    )
    from openr_tpu.emulation.topology import build_adj_dbs as bad

    async def main():
        clock = SimClock()
        solver = SpfSolver("node0")
        backend = TpuBackend(solver)
        out_q = ReplicateQueue("routes")
        kv_q = ReplicateQueue("kv")
        d = Decision(
            "node0",
            clock,
            DecisionConfig(debounce_min_ms=10, debounce_max_ms=250),
            out_q,
            kv_store_updates_reader=kv_q.get_reader(),
            backend=backend,
            solver=solver,
        )
        d.start()
        d.on_initialization_event(InitializationEvent.KVSTORE_SYNCED)

        def adj_pub():
            kvs = {}
            for node, db in bad(grid_edges(3)).items():
                kvs[f"adj:{node}"] = Value(
                    version=1,
                    originator_id=node,
                    value=json.dumps(db.to_wire()).encode(),
                )
            return Publication(key_vals=kvs)

        def prefix_pub(node, prefix, version=1, pp=1000):
            pdb = PrefixDatabase(
                this_node_name=node,
                prefix_entries=[
                    PrefixEntry(
                        prefix,
                        metrics=PrefixMetrics(path_preference=pp),
                    )
                ],
            )
            return Publication(
                key_vals={
                    prefix_key(node, prefix): Value(
                        version=version,
                        originator_id=node,
                        value=json.dumps(pdb.to_wire()).encode(),
                    )
                }
            )

        kv_q.push(adj_pub())
        kv_q.push(prefix_pub("node8", "10.0.0.0/24"))
        await clock.run_for(2.0)
        assert d._first_build_done
        base_inc = backend.num_incremental_builds

        # prefix-only churn → incremental
        kv_q.push(prefix_pub("node4", "10.1.0.0/24"))
        await clock.run_for(2.0)
        kv_q.push(prefix_pub("node8", "10.0.0.0/24", version=2, pp=2000))
        kv_q.push(prefix_pub("node7", "10.2.0.0/24"))
        await clock.run_for(2.0)
        assert backend.num_incremental_builds >= base_inc + 2
        assert d.counters.get("decision.incremental_route_builds") >= 2

        oracle = ScalarBackend(SpfSolver("node0")).build_route_db(
            d.area_link_states, d.prefix_state
        )
        assert route_db_summary(d.route_db) == route_db_summary(oracle)
        await d.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(main())
    finally:
        loop.close()
