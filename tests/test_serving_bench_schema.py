"""Tier-1 smoke: the checked-in BENCH_SERVING artifact obeys the schema
the bench emits (shared validator — bench.validate_serving_bench), and
holds the acceptance floor: batched serving throughput >= 3x the
unbatched path at 64 concurrent clients.

The validator lives in bench.py so the emitter and this gate can never
drift apart; regenerate the artifact with `python bench.py --serving`.
"""

import json
import pathlib

import pytest

import bench

pytestmark = pytest.mark.serving

ARTIFACT = pathlib.Path(__file__).resolve().parent.parent / (
    "BENCH_SERVING_r01.json"
)


def test_artifact_exists_and_matches_schema():
    doc = json.loads(ARTIFACT.read_text())
    bench.validate_serving_bench(doc)


def test_batched_at_64_clients_meets_3x_floor():
    doc = json.loads(ARTIFACT.read_text())
    r64 = next(
        r for r in doc["detail"]["rounds"] if r["clients"] == 64
    )
    assert doc["vs_baseline"] == r64["speedup_steady"]
    assert doc["vs_baseline"] >= 3.0, (
        "serving acceptance: batched >= 3x unbatched at 64 clients"
    )


def test_validator_rejects_malformed_doc():
    doc = json.loads(ARTIFACT.read_text())
    doc["detail"]["rounds"][0]["steady"]["qps"] = 0
    with pytest.raises(AssertionError):
        bench.validate_serving_bench(doc)
