"""netns lab test — REAL daemons in kernel network namespaces.

Reference parity: openr/orie/labs (netns topologies, one daemon per
namespace).  This is the deployment-grade end-to-end: Spark discovers
neighbors over actual IPv6 link-local UDP multicast on veth pairs,
KvStore syncs over actual TCP, Decision computes, and Fib programs
actual kernel routes (proto 99, RFC 5549 v4-over-v6 nexthops) through
the native netlink codec into each namespace's FIB.

Requires CAP_NET_ADMIN; skipped where namespaces can't be created.
"""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])

from labs.netns_lab import NetnsLab, have_netns_caps  # noqa: E402

pytestmark = pytest.mark.skipif(
    not have_netns_caps(), reason="needs CAP_NET_ADMIN for netns"
)


def test_three_node_line_full_stack():
    """node0 -- node1 -- node2: every kernel must hold proto-99 routes to
    both other nodes' prefixes, with the remote one via the transit node's
    link-local gateway (multi-hop forwarding)."""
    lab = NetnsLab(num_nodes=3, topology="line")
    with lab:
        lab.wait_converged(timeout_s=180)
        routes0 = "\n".join(lab.kernel_routes(0))
        # direct neighbor
        assert "10.77.1.0/24" in routes0
        # multi-hop: must carry a v6 gateway (RFC 5549), not be dev-only
        remote = [r for r in lab.kernel_routes(0) if "10.77.2.0/24" in r]
        assert remote, routes0
        assert "via inet6 fe80::" in remote[0], remote[0]
        assert "dev ve0_1" in remote[0], remote[0]
        # transit node routes both edge prefixes out opposite interfaces
        routes1 = lab.kernel_routes(1)
        ifaces = {
            r.split("dev ")[1].split()[0] for r in routes1 if "dev" in r
        }
        assert ifaces == {"ve1_0", "ve1_2"}, routes1
