"""netns lab test — REAL daemons in kernel network namespaces.

Reference parity: openr/orie/labs (netns topologies, one daemon per
namespace).  This is the deployment-grade end-to-end: Spark discovers
neighbors over actual IPv6 link-local UDP multicast on veth pairs,
KvStore syncs over actual TCP, Decision computes, and Fib programs
actual kernel routes (proto 99, RFC 5549 v4-over-v6 nexthops) through
the native netlink codec into each namespace's FIB.

Requires CAP_NET_ADMIN; skipped where namespaces can't be created.
"""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])

from labs.netns_lab import NetnsLab, have_netns_caps  # noqa: E402

pytestmark = pytest.mark.skipif(
    not have_netns_caps(), reason="needs CAP_NET_ADMIN for netns"
)


def test_three_node_line_full_stack():
    """node0 -- node1 -- node2: every kernel must hold proto-99 routes to
    both other nodes' prefixes, with the remote one via the transit node's
    link-local gateway (multi-hop forwarding)."""
    lab = NetnsLab(num_nodes=3, topology="line")
    with lab:
        lab.wait_converged(timeout_s=300)
        routes0 = "\n".join(lab.kernel_routes(0))
        # direct neighbor
        assert "10.77.1.0/24" in routes0
        # multi-hop: must carry a v6 gateway (RFC 5549), not be dev-only
        remote = [r for r in lab.kernel_routes(0) if "10.77.2.0/24" in r]
        assert remote, routes0
        assert "via inet6 fe80::" in remote[0], remote[0]
        assert "dev ve0_1" in remote[0], remote[0]
        # transit node routes both edge prefixes out opposite interfaces
        routes1 = lab.kernel_routes(1)
        ifaces = {
            r.split("dev ")[1].split()[0] for r in routes1 if "dev" in r
        }
        assert ifaces == {"ve1_0", "ve1_2"}, routes1


def test_multiarea_redistribution_and_policy():
    """8 nodes, 3 areas (pod1 0-3, spine 3-4, pod2 4-7) — reference labs
    201 (areas) + 202 (policy) on real kernels: prefixes cross TWO area
    borders via FIB-confirmed redistribution, and node4's pod2 import
    policy drops node1's prefix at the boundary while the border itself
    (which learned it in the spine area) keeps it."""
    lab = NetnsLab(num_nodes=8, topology="multiarea")
    with lab:
        lab.wait_converged(timeout_s=300)
        # cross-area chain: pod2's far leaf reaches pod1's far leaf
        r7 = "\n".join(lab.kernel_routes(7))
        assert "10.77.0.0/24" in r7, r7
        # policy: the dropped prefix never enters pod2's interior...
        assert lab.POLICY_DROPPED_PREFIX not in r7, r7
        for i in (5, 6):
            routes = "\n".join(lab.kernel_routes(i))
            assert lab.POLICY_DROPPED_PREFIX not in routes, (i, routes)
        # ...but the border node itself learned it in the spine area
        r4 = "\n".join(lab.kernel_routes(4))
        assert lab.POLICY_DROPPED_PREFIX in r4, r4
        # reverse redistribution: pod1's far leaf reaches pod2's far leaf
        r0 = "\n".join(lab.kernel_routes(0))
        assert "10.77.7.0/24" in r0, r0
        # border forwards pod2-bound traffic out the spine interface
        spine_bound = [
            r for r in lab.kernel_routes(3) if "10.77.7.0/24" in r
        ]
        assert spine_bound and "dev ve3_4" in spine_bound[0], spine_bound


def test_multiarea_whatif_and_validate_on_lab():
    """The multi-area what-if engine + the validate commands, exercised
    against REAL daemons on the 3-area kernel lab from the border
    node's vantage (VERDICT r4: multi-area what-if proven on the
    netns topology)."""
    lab = NetnsLab(num_nodes=8, topology="multiarea")
    with lab:
        lab.wait_converged(timeout_s=300)
        # pod1 leaf node0 (single-area vantage, scalar daemon): the
        # NATIVE what-if engine serves it without loading jax in the
        # namespace process.  Failing its only uplink must change
        # routes; an off-path removal must say so.
        out = lab.breeze(0, "decision", "whatif", "node0,node1")
        assert "not eligible" not in out, out
        assert "node0-node1" in out, out
        assert "route(s) change" in out, out
        out2 = lab.breeze(7, "decision", "whatif", "node5,node6")
        assert "node5-node6" in out2, out2
        assert "not eligible" not in out2, out2
        # scriptable health checks hold on live daemons, including the
        # multi-area border
        for node, cmd in (
            (4, ("decision", "validate")),
            (4, ("fib", "validate")),
            (4, ("spark", "validate")),
            (0, ("prefixmgr", "validate")),
        ):
            out3 = lab.breeze(node, *cmd)
            assert "OK" in out3, (node, cmd, out3)


def test_mixed_wire_format_lab_converges():
    """Real kernels, real UDP multicast + TCP sync, MIXED LSDB flood
    encodings: even nodes flood thrift-compact (the reference's
    CompactSerializer bytes), odd nodes flood JSON — the migration /
    federation shape. Every kernel must still hold routes to every
    other node's prefix, and node1's store must visibly hold both
    encodings."""
    lab = NetnsLab(num_nodes=3, topology="line", lsdb_wire_format="mixed")
    with lab:
        lab.wait_converged(timeout_s=300)
        for i in range(3):
            routes = "\n".join(lab.kernel_routes(i))
            for j in range(3):
                if i != j:
                    assert f"10.77.{j}.0/24" in routes, (i, routes)
        # the store on node1 carries adj values in BOTH encodings
        import json as _json

        out = lab.breeze(1, "kvstore", "key-vals", "adj:node0",
                         "adj:node1")
        blobs = _json.loads(out)
        fmts = set()
        for key, v in blobs.items():
            raw = v.get("value")
            blob = bytes.fromhex(raw) if v.get("_value_hex") else (
                raw.encode() if isinstance(raw, str) else raw
            )
            fmts.add("json" if blob[:1] == b"{" else "compact")
        assert fmts == {"json", "compact"}, fmts


def test_rocket_transport_lab_converges():
    """The reference's FULL wire stack on real kernels: LSDB values are
    thrift-compact (CompactSerializer bytes) AND every KvStore peer RPC
    rides fbthrift Rocket framing (rsocket frames + Compact
    RequestRpcMetadata) on the ctrl port — the live-sync proof the
    round-4 review asked for, one layer short of pointing a real
    fbthrift binary at it.  Kernel routes must converge end-to-end and
    the rocket RPC counters must show peer sync actually used it."""
    lab = NetnsLab(
        num_nodes=3,
        topology="line",
        lsdb_wire_format="thrift-compact",
        lsdb_rpc_transport="rocket",
    )
    with lab:
        lab.wait_converged(timeout_s=300)
        for i in range(3):
            routes = "\n".join(lab.kernel_routes(i))
            for j in range(3):
                if i != j:
                    assert f"10.77.{j}.0/24" in routes, (i, routes)
        # transit node served rocket RPCs from both neighbors
        import json as _json

        out = lab.breeze(1, "monitor", "counters", "--prefix", "ctrl.rocket")
        counters = _json.loads(out)
        assert counters.get("ctrl.rocket.getKvStoreKeyValsFilteredArea", 0) >= 1, counters
        assert counters.get("ctrl.rocket.setKvStoreKeyVals", 0) >= 1, counters


def test_32_node_grid_lab_chaos_churn():
    """32 REAL daemons in kernel namespaces (8x4 grid) — 4x the prior
    lab scale, toward the reference's 1000-node emulation practice
    (DeveloperGuide.md:51) — surviving randomized link churn driven at
    the KERNEL level (veth carrier down/up -> netlink events ->
    LinkMonitor -> reflood -> reroute), the netns analogue of the
    in-process chaos test.  After every round and after healing all,
    every kernel must hold proto-99 routes to every other node's
    prefix.  The grid guarantees alternate paths around any single
    failed link."""
    import random

    from labs.netns_lab import topology_edges

    rng = random.Random(42)
    lab = NetnsLab(num_nodes=32, topology="grid")
    edges = topology_edges("grid", 32)
    with lab:
        lab.wait_converged(timeout_s=600)
        def connected_without(down):
            """BFS over surviving edges — the churn driver only commits
            cuts that keep the fabric connected, making the every-pair
            reachability invariant structural rather than seed luck."""
            adj = {}
            for x, y in edges:
                if (x, y) in down:
                    continue
                adj.setdefault(x, []).append(y)
                adj.setdefault(y, []).append(x)
            seen, stack = {0}, [0]
            while stack:
                for nxt in adj.get(stack.pop(), []):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            return len(seen) == 32

        failed = set()
        for _ in range(5):
            # fail up to 2 new links; heal one previously failed
            for a, b in rng.sample(edges, 2):
                if (a, b) not in failed and connected_without(
                    failed | {(a, b)}
                ):
                    lab.fail_link(a, b)
                    failed.add((a, b))
            if failed and rng.random() < 0.7:
                pair = rng.choice(sorted(failed))
                lab.heal_link(*pair)
                failed.discard(pair)
            # the grid is 2-edge-connected for these cuts; every node
            # pair must stay mutually reachable
            lab.wait_converged(timeout_s=240)
        for pair in sorted(failed):
            lab.heal_link(*pair)
        lab.wait_converged(timeout_s=240)
        # spot-check the operator invariant checker on three nodes
        for i in (0, 15, 31):
            out = lab.breeze(i, "openr", "validate")
            assert "FAIL" not in out, (i, out)


def test_rocket_grid_lab_churn_at_scale():
    """The two headline wire features COMBINED at scale: a 16-node
    kernel-netns grid whose every LSDB byte is thrift-compact and whose
    every peer RPC rides fbthrift-Rocket framing, surviving kernel-level
    link churn (32 nodes verified manually: converged 109 s, reroute
    ~1 s, 116 rocket floods served by a transit node; 16 here for suite
    wall time)."""
    import json as _json

    lab = NetnsLab(
        num_nodes=16,
        topology="grid",
        lsdb_wire_format="thrift-compact",
        lsdb_rpc_transport="rocket",
    )
    with lab:
        lab.wait_converged(timeout_s=420)
        lab.fail_link(5, 6)
        lab.wait_converged(timeout_s=180)
        lab.heal_link(5, 6)
        lab.wait_converged(timeout_s=180)
        out = lab.breeze(5, "monitor", "counters", "--prefix", "ctrl.rocket")
        counters = _json.loads(out)
        assert (
            counters.get("ctrl.rocket.getKvStoreKeyValsFilteredArea", 0) >= 1
        ), counters
        assert counters.get("ctrl.rocket.setKvStoreKeyVals", 0) >= 1, counters
