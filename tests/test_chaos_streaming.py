"""Chaos × streaming: the watch plane under faults (ISSUE 13).

Acceptance properties:

* **partition/heal never streams a pre-partition generation**: every
  emission a subscriber sees carries a monotone generation seq; after
  the partition bumps the generation, no emission may re-assert a
  pre-partition one — and the applied emission chain reproduces the
  live route-db byte-identically at every checkpoint;
* **mid-stream chip quarantine keeps deltas flowing**: a seeded
  ``tpu_corrupt(device_index=…)`` quarantines exactly one chip of the
  victim's pool while its subscribers keep receiving survivor-computed
  deltas that match the scalar oracle;
* **a clean seeded run fires ZERO alerts** with streaming load attached
  (the health false-positive gate, extended to the watch plane);
* **byte-identical seeded replays**: two runs of one seeded scenario
  produce byte-identical emission logs (the chaos reproducibility
  contract the counter dumps, alert JSONL and flight recorder already
  honor).
"""

import asyncio
import json

import pytest

from openr_tpu.chaos import ChaosController, FaultPlan
from openr_tpu.common.runtime import SimClock
from openr_tpu.config import ParallelConfig, ResilienceConfig
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import grid_edges, ring_edges
from openr_tpu.serving import apply_emission
from openr_tpu.types import PrefixEntry

pytestmark = [pytest.mark.chaos, pytest.mark.serving, pytest.mark.streaming]

SEED = 7
CONVERGE_S = 18.0


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        pending = asyncio.all_tasks(loop)
        for t in pending:
            t.cancel()
        if pending:
            loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True)
            )
        loop.close()


def fast_stream_overrides(cfg):
    """Tight publish window so chaos scenarios see emissions promptly."""
    cfg.serving_config.stream_publish_min_ms = 5
    cfg.serving_config.stream_publish_max_ms = 20


class Collector:
    """Push transport: records every emission and the running applied
    state (the reference client reducer)."""

    def __init__(self) -> None:
        self.emissions = []
        self.state = {}

    def __call__(self, emission: dict) -> None:
        self.emissions.append(emission)
        self.state = apply_emission(self.state, emission)

    def seqs(self):
        return [e["seq"] for e in self.emissions]

    def log_bytes(self) -> bytes:
        return b"\n".join(
            json.dumps(e, sort_keys=True, default=str).encode()
            for e in self.emissions
        )


def live_rows(node, vantage: str):
    _gen, res = node.serving.snapshot_for("route_db", {"node": vantage})
    rows = {("u", r["dest"]): r for r in res["unicast_routes"]}
    rows.update({("m", r["top_label"]): r for r in res["mpls_routes"]})
    return rows


def canon(rows) -> str:
    return json.dumps(
        {"|".join(map(str, k)): v for k, v in rows.items()},
        sort_keys=True,
        default=str,
    )


# ---------------------------------------------------------------------------
# partition/heal: generation correctness end to end
# ---------------------------------------------------------------------------


async def _partition_heal_run():
    clock = SimClock()
    net = EmulatedNetwork(clock, config_overrides=fast_stream_overrides)
    net.build(ring_edges(4))
    net.start()
    await clock.run_for(CONVERGE_S)
    ok, why = net.converged_full_mesh()
    assert ok, why

    n0 = net.nodes["node0"]
    watcher = Collector()
    n0.streaming.subscribe(
        "route_db", {"node": "node2"}, client_id="chaos", deliver=watcher
    )
    assert watcher.emissions[0]["type"] == "snapshot"
    assert canon(watcher.state) == canon(live_rows(n0, "node2"))

    # pre-partition churn: a couple of ordinary deltas
    for i in range(2):
        net.nodes["node2"].advertise_prefixes(
            [PrefixEntry(f"10.80.{i}.0/24")]
        )
        await clock.run_for(2.0)
    seq_pre = n0.decision.generation_key()[0]
    n_pre = len(watcher.emissions)

    # partition node0 away; hold timers expire -> its LSDB changes
    net.partition(("node0",), ("node1", "node2", "node3"))
    await clock.run_for(10.0)
    assert n0.decision.generation_key()[0] > seq_pre
    assert len(watcher.emissions) > n_pre, (
        "the partition's own LSDB change must stream as a delta"
    )
    # THE property: nothing emitted after the partition carries a
    # pre-partition generation
    for e in watcher.emissions[n_pre:]:
        assert e["seq"] > seq_pre, e
    assert canon(watcher.state) == canon(live_rows(n0, "node2"))

    net.heal_partition(("node0",), ("node1", "node2", "node3"))
    await clock.run_for(25.0)
    ok, why = net.converged_full_mesh()
    assert ok, why
    await clock.run_for(2.0)

    # monotone end to end, applied state byte-identical to live
    seqs = watcher.seqs()
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert canon(watcher.state) == canon(live_rows(n0, "node2"))
    stats = net.streaming_stats()
    assert (
        stats["node0"]["counters"].get(
            "streaming.num_invariant_violations", 0
        )
        == 0
    )
    log = watcher.log_bytes()
    await net.stop()
    return log


def test_partition_heal_never_streams_pre_partition_generation():
    """Partition/heal generation correctness AND the determinism
    acceptance: two seeded replays produce byte-identical emission
    logs."""
    log_a = run(_partition_heal_run())
    log_b = run(_partition_heal_run())
    assert log_a == log_b, "same scenario must replay byte-identically"


# ---------------------------------------------------------------------------
# mid-stream chip quarantine: deltas keep flowing from survivors
# ---------------------------------------------------------------------------

VICTIM = "node4"
BAD_CHIP = 3


def tpu_overrides(cfg):
    fast_stream_overrides(cfg)
    cfg.tpu_compute_config.min_device_prefixes = 0  # always device
    cfg.parallel_config = ParallelConfig(min_shard_rows=0)
    cfg.resilience_config = ResilienceConfig(
        shadow_sample_every=2,
        failure_threshold=2,
        probe_backoff_initial_s=0.5,
        probe_backoff_max_s=4.0,
        jitter_pct=0.1,
        seed=SEED,
    )


@pytest.mark.multichip
def test_chip_quarantine_mid_stream_keeps_survivor_deltas_flowing():
    async def scenario():
        clock = SimClock()
        net = EmulatedNetwork(
            clock, use_tpu_backend=True, config_overrides=tpu_overrides
        )
        net.build(grid_edges(3))
        net.start()
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why
        # widen the candidate table so every chip's shard holds rows
        net.nodes["node0"].advertise_prefixes(
            [PrefixEntry(f"10.99.{i}.0/24") for i in range(9)]
        )
        await clock.run_for(3.0)

        victim = net.nodes[VICTIM]
        watcher = Collector()
        victim.streaming.subscribe(
            "route_db", {"node": "node0"}, client_id="chaos",
            deliver=watcher,
        )
        assert watcher.emissions[0]["type"] == "snapshot"

        plan = FaultPlan().tpu_corrupt(
            VICTIM, at=2.0, duration=60.0, device_index=BAD_CHIP
        )
        controller = ChaosController(net, plan, seed=SEED)
        controller.start()
        await clock.run_for(3.0)  # corruption live on chip 3

        # LSDB churn drives shadow-checked rebuilds until the chip is
        # caught, AND streams deltas to the watcher throughout
        gov = victim.decision.backend.governor
        for a, b in [("node0", "node1"), ("node1", "node2")]:
            net.fail_link(a, b)
            await clock.run_for(2.5)
            if gov.num_shadow_mismatches:
                break
        assert gov.num_chip_quarantines >= 1, "chip 3 must quarantine"
        n_at_quarantine = len(watcher.emissions)
        # the victim's pool keeps serving on 7 survivors: the DEVICE
        # path stays up for its watchers
        assert victim.decision.device_available()

        # mid-stream deltas AFTER the quarantine, computed by survivors
        # (advertised AWAY from the watched vantage, so node0's computed
        # routes actually gain the prefixes)
        for i in range(3):
            net.nodes["node8"].advertise_prefixes(
                [PrefixEntry(f"10.81.{i}.0/24")]
            )
            await clock.run_for(2.0)
        assert len(watcher.emissions) > n_at_quarantine, (
            "deltas must keep flowing from the surviving chips"
        )

        # the applied stream matches the SCALAR oracle (the corrupted
        # chip's lies never reached a subscriber)
        from openr_tpu.decision.spf_solver import SpfSolver

        oracle = (
            SpfSolver("node0")
            .build_route_db(
                victim.decision.area_link_states,
                victim.decision.prefix_state,
            )
            .to_route_database("node0")
            .to_wire()
        )
        want = {("u", r["dest"]): r for r in oracle["unicast_routes"]}
        want.update(
            {("m", r["top_label"]): r for r in oracle["mpls_routes"]}
        )
        got = {
            k: v for k, v in watcher.state.items() if k[0] in ("u", "m")
        }
        assert canon(got) == canon(want)

        seqs = watcher.seqs()
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        assert victim.streaming.num_invariant_violations == 0
        await controller.stop()
        await net.stop()

    run(scenario())


# ---------------------------------------------------------------------------
# false-positive gate: clean seeded run with streaming load -> ZERO alerts
# ---------------------------------------------------------------------------


def test_clean_run_with_streaming_load_fires_zero_alerts():
    def overrides(cfg):
        fast_stream_overrides(cfg)
        cfg.health_config.sweep_interval_s = 2.0
        cfg.health_config.skew_min_generations = 2
        cfg.health_config.skew_hold_s = 4.0
        cfg.watchdog_config.interval_s = 1.0

    async def scenario():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=overrides)
        net.build(grid_edges(3))
        net.start()
        await clock.run_for(CONVERGE_S)
        ok, why = net.converged_full_mesh()
        assert ok, why

        n0 = net.nodes["node0"]
        watchers = []
        for i in range(8):
            w = Collector()
            n0.streaming.subscribe(
                "route_db",
                {"node": f"node{i % 4}"},
                client_id=f"w{i}",
                deliver=w,
            )
            watchers.append(w)
        # ordinary life: prefix churn, a link flap, subscriber churn
        for i in range(3):
            net.nodes["node0"].advertise_prefixes(
                [PrefixEntry(f"10.90.{i}.0/24")]
            )
            await clock.run_for(4.0)
        churn = n0.streaming.subscribe(
            "route_db", {"node": "node1"}, client_id="churn"
        )
        n0.streaming.unsubscribe(churn)
        net.fail_link("node0", "node1")
        await clock.run_for(4.0)
        net.restore_link("node0", "node1")
        await clock.run_for(20.0)

        for name, node in net.nodes.items():
            assert node.health.alert_log() == [], (
                f"{name} logged alerts on a clean streaming run"
            )
        assert all(len(w.emissions) >= 2 for w in watchers), (
            "every watcher saw its snapshot plus churn deltas"
        )
        for w in watchers:
            seqs = w.seqs()
            assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        stats = n0.streaming.stats()
        assert stats["counters"].get(
            "streaming.num_invariant_violations", 0
        ) == 0
        await net.stop()

    run(scenario())
