"""Chaos acceptance: a seeded 9-node grid run that partitions the mesh,
kills an actor (supervisor restarts it — no SystemExit), and bursts
fib-agent failures, ending with every invariant green and a byte-identical
``chaos.*`` counter dump when replayed from the same seed.

This is the composed version of what the repo previously only had as
fragments: InProcessTransport.fail/heal, MockFibAgent.fail, watchdog
SystemExit — now driven as one declarative FaultPlan with machine-checked
recovery (ISSUE 1 tentpole).
"""

import asyncio

import pytest

from openr_tpu.chaos import ChaosController, FaultPlan, InvariantChecker, Supervisor
from openr_tpu.common.runtime import SimClock
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import grid_edges

SEED = 7
CONVERGE_S = 18.0

LEFT = ("node0", "node3", "node6")  # grid column cut off by the partition
RIGHT = ("node1", "node2", "node4", "node5", "node7", "node8")


def chaos_overrides(cfg):
    # fast watchdog sweeps so crash->restart happens in test time
    cfg.watchdog_config.interval_s = 1.0


def build_plan() -> FaultPlan:
    plan = FaultPlan()
    # cut the left column off (Spark + KvStore RPC), heal 12s later
    plan.partition(LEFT, RIGHT, at=2.0, duration=12.0)
    # asymmetric loss on a surviving link while partitioned
    plan.spark_loss("node1", "node2", prob=0.5, at=3.0, duration=8.0)
    # peer-RPC latency injection on the kvstore plane
    plan.kv_rpc_latency("node1", "node4", extra_s=0.2, at=2.0, duration=10.0)
    # fib-agent failure burst on the center node
    plan.fib_burst("node4", at=4.0, duration=6.0)
    # and kill one of its module fibers outright mid-burst
    plan.actor_kill("node4", "decision", at=6.0)
    return plan


async def _one_run():
    clock = SimClock()
    net = EmulatedNetwork(clock, config_overrides=chaos_overrides)
    net.build(grid_edges(3))  # 9 nodes
    net.start()
    supervisor = Supervisor(
        clock, initial_backoff_s=0.25, max_backoff_s=5.0
    )
    supervisor.start()
    for name, node in net.nodes.items():
        supervisor.supervise(name, node, net.restart_node)
    checker = InvariantChecker(net)
    controller = ChaosController(net, build_plan(), seed=SEED)

    await clock.run_for(CONVERGE_S)
    ok, why = net.converged_full_mesh()
    assert ok, why
    pre_chaos_node4 = net.nodes["node4"]

    controller.start()
    # step through the chaos window, sampling invariants between steps
    for _ in range(8):
        await clock.run_for(2.5)
        checker.sample()
    assert controller.done
    # mid-run checks: the partitioned majority side must stay internally
    # consistent even while the minority column is unreachable
    checker.check_lsdb_converged(nodes=RIGHT)

    # post-heal convergence window (restart + re-discovery + full sync)
    await clock.run_for(30.0)

    # -- acceptance: everything recovered ---------------------------------
    checker.check_all()  # LSDB converged, FIBs blackhole-free, full mesh
    assert net.num_node_restarts >= 1
    assert supervisor.num_restarts >= 1
    assert supervisor.num_crashes >= 1
    # the supervisor replaced the node in place — new incarnation, alive
    assert net.nodes["node4"] is not pre_chaos_node4
    assert net.nodes["node4"].initialized
    # crash reason reached the supervisor instead of SystemExit
    assert any("node4" == n for _, n, _ in supervisor.crash_log)

    dump = controller.counter_dump()
    await supervisor.stop()
    await controller.stop()
    await net.stop()
    return dump


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


@pytest.mark.chaos
def test_seeded_grid_chaos_recovers_and_replays():
    dump_a = run(_one_run())
    dump_b = run(_one_run())
    # the injected faults actually happened and were recorded
    assert dump_a["chaos.injects"] == 5
    assert dump_a["chaos.heals"] == 4
    assert dump_a["chaos.spark.packets_dropped"] > 0
    # reproducibility contract: same seed => identical chaos.* dump
    assert dump_a == dump_b
