"""Race-detection / scheduling-stress harness.

SURVEY §5: the reference has no sanitizer CI — its safety comes from the
actor architecture (single-writer threads, queue-only sharing); the
rebuild was told to keep that discipline AND add race detection as a new
capability.  The Python analogue of TSAN here is three-fold:

  1. **queue-layer stress**: many concurrent producers + readers with
     mid-stream attach/detach and close propagation — every reader must
     observe a per-producer-ordered subsequence, nothing deadlocks, and
     closed readers raise
  2. **seeded scheduling fuzz**: the full multi-node network on a
     virtual clock with randomized link latencies, failure windows and
     flap timing — 8 seeds; each interleaving must still converge
     (elastic recovery under arbitrary timing)
  3. **asyncio sanitizer mode**: a full convergence run with the event
     loop in debug mode, warnings-as-errors for 'coroutine was never
     awaited' and 'exception was never retrieved' — leaked tasks and
     swallowed failures become hard test failures
"""

import asyncio
import random
import warnings

from openr_tpu.common.runtime import SimClock
from openr_tpu.messaging.queue import QueueClosedError, ReplicateQueue


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# -- 1. queue-layer stress --------------------------------------------------


def test_replicate_queue_concurrent_stress():
    """4 producers x 3 persistent readers + 20 transient readers attach/
    detach mid-stream; per-producer ordering must survive replication and
    close() must wake everyone exactly once."""

    async def main():
        rng = random.Random(17)
        q = ReplicateQueue("stress")
        NP, NI = 4, 500
        persistent = [q.get_reader(name=f"r{i}") for i in range(3)]
        seen = {i: [] for i in range(3)}
        transient_results = []

        async def producer(pid):
            for i in range(NI):
                q.push((pid, i))
                if rng.random() < 0.2:
                    await asyncio.sleep(0)

        async def persistent_reader(ridx, r):
            try:
                while True:
                    seen[ridx].append(await r.get())
            except QueueClosedError:
                return

        async def transient_reader():
            r = q.get_reader(name="transient")
            got = []
            try:
                for _ in range(rng.randint(1, 50)):
                    got.append(await r.get())
            except QueueClosedError:
                pass
            finally:
                q.remove_reader(r)
            transient_results.append(got)

        readers = [
            asyncio.ensure_future(persistent_reader(i, r))
            for i, r in enumerate(persistent)
        ]
        prods = [asyncio.ensure_future(producer(p)) for p in range(NP)]
        transients = []
        for _ in range(20):
            transients.append(asyncio.ensure_future(transient_reader()))
            await asyncio.sleep(0)
        await asyncio.gather(*prods)
        # let readers drain, then close
        while any(r.size() for r in persistent):
            await asyncio.sleep(0)
        q.close()
        await asyncio.gather(*readers)
        await asyncio.gather(*transients)

        for ridx in range(3):
            assert len(seen[ridx]) == NP * NI, (ridx, len(seen[ridx]))
            # per-producer FIFO order is preserved through replication
            for pid in range(NP):
                stream = [i for (p, i) in seen[ridx] if p == pid]
                assert stream == sorted(stream)
        # transient readers saw per-producer-ordered subsequences too
        for got in transient_results:
            for pid in range(NP):
                stream = [i for (p, i) in got if p == pid]
                assert stream == sorted(stream)
        # closed queue: an awaited read RAISES (try_get would mask this:
        # it returns None on a drained closed queue), pushes deliver to
        # nobody
        raised = False
        try:
            await persistent[0].get()
        except QueueClosedError:
            raised = True
        assert raised, "get() on a closed queue must raise"
        assert q.push(("late", 0)) == 0

    run(main())


# -- 2. seeded scheduling fuzz ---------------------------------------------


def one_scheduling_fuzz(seed: int) -> None:
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import ring_edges

    async def main():
        rng = random.Random(seed)
        clock = SimClock()
        net = EmulatedNetwork(
            clock,
            link_latency_s=rng.choice([0.0005, 0.002, 0.01]),
            kv_latency_s=rng.choice([0.0005, 0.002, 0.01]),
        )
        net.build(ring_edges(4))
        net.start()
        await clock.run_for(rng.uniform(20.0, 40.0))

        # random flap storm: links fail and heal at random virtual times
        edges = [("node0", "node1"), ("node1", "node2"), ("node2", "node3")]
        for _ in range(rng.randint(1, 4)):
            a, b = rng.choice(edges)
            net.fail_link(a, b)
            await clock.run_for(rng.uniform(0.5, 15.0))
            net.restore_link(a, b)
            await clock.run_for(rng.uniform(0.5, 5.0))

        await clock.run_for(60.0)
        ok, why = net.converged_full_mesh()
        assert ok, f"seed {seed}: {why}"
        await net.stop()

    run(main())


def test_scheduling_fuzz_seeds():
    for seed in range(8):
        one_scheduling_fuzz(seed)


# -- 3. asyncio sanitizer mode ----------------------------------------------


def test_convergence_under_asyncio_debug_sanitizer():
    """Full 9-node grid convergence with the loop in debug mode and
    'never awaited' / 'never retrieved' warnings promoted to errors —
    leaked coroutines and silently-dropped task exceptions fail loudly."""
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import grid_edges

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock, use_tpu_backend=False)
        net.build(grid_edges(3))
        net.start()
        await clock.run_for(40.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        net.fail_link("node0", "node1")
        await clock.run_for(15.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        await net.stop()

    import gc
    import sys

    unretrieved = []
    unraisable = []
    loop = asyncio.new_event_loop()
    loop.set_debug(True)
    loop.slow_callback_duration = 10.0  # virtual-time tests batch work

    def exc_handler(lp, context):
        # "exception was never retrieved" and task-crash reports land here
        unretrieved.append(context)

    loop.set_exception_handler(exc_handler)
    # 'coroutine was never awaited' fires during coroutine GC inside
    # __del__, where a warnings-as-errors exception is swallowed by the
    # unraisable hook — capture THAT hook, or leaks pass silently
    prev_unraisable = sys.unraisablehook
    sys.unraisablehook = lambda args: unraisable.append(args)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            loop.run_until_complete(main())
            # drain callbacks scheduled at teardown before judging leaks
            loop.run_until_complete(asyncio.sleep(0))
            gc.collect()  # force __del__ of any leaked coroutine NOW
    finally:
        sys.unraisablehook = prev_unraisable
        loop.close()
    assert not unretrieved, f"leaked task exceptions: {unretrieved[:3]}"
    assert not unraisable, (
        f"unraisable errors (leaked coroutines?): "
        f"{[str(a.exc_value) for a in unraisable[:3]]}"
    )
