"""Atomicity pass (rule family 11) — interprocedural behavior + the
cache contract it adds (ISSUE 17 tentpole, static half).

The per-rule trip/suppression fixtures and the validated ``--explain``
examples live in ``test_orlint.py`` (the FIXTURES meta-suite covers
every registered rule).  This file pins the parts that depend on the
PROJECT, not just the snippet:

* suspension is interprocedural — an awaited call suspends (or not)
  according to the callee's own body, through helpers and overrides;
* the ``--cache`` contract extends to suspension facts: editing a
  HELPER so it starts suspending must invalidate the cached atomicity
  verdict of an UNCHANGED caller file, because the per-function
  ``suspends`` flag rides in the module summary and therefore in the
  project facts digest.
"""

from openr_tpu.analysis import analyze_paths, analyze_source

# ---------------------------------------------------------------------------
# interprocedural suspension
# ---------------------------------------------------------------------------

ACTOR_CTX = """\
from openr_tpu.common.runtime import Actor

class Spark(Actor):
    pass
"""


def _rules(findings):
    return [(f.rule, f.line) for f in findings]


def test_awaiting_non_suspending_internal_helper_is_clean():
    """``await helper()`` where the helper's body never yields is NOT a
    suspension point — the turn is still atomic, no finding."""
    src = (
        "from openr_tpu.common.runtime import Actor\n"
        "\n"
        "async def classify(key):\n"
        "    return len(key)\n"
        "\n"
        "class Cache(Actor):\n"
        "    async def lookup(self, key):\n"
        "        if key not in self._entries:\n"
        "            kind = await classify(key)\n"
        "            self._entries[key] = kind\n"
        "        return self._entries[key]\n"
    )
    assert analyze_source(src) == []


def test_transitively_suspending_helper_trips():
    """The same caller trips once the helper suspends — two hops deep,
    through a helper that itself only awaits another suspender."""
    src = (
        "from openr_tpu.common.runtime import Actor\n"
        "\n"
        "async def fetch(store, key):\n"
        "    return await store.rpc_get(key)\n"
        "\n"
        "async def classify(store, key):\n"
        "    return await fetch(store, key)\n"
        "\n"
        "class Cache(Actor):\n"
        "    async def lookup(self, key):\n"
        "        if key not in self._entries:\n"
        "            kind = await classify(self._store, key)\n"
        "            self._entries[key] = kind\n"
        "        return self._entries[key]\n"
    )
    assert _rules(analyze_source(src)) == [("await-atomicity", 13)]


def test_revalidation_after_await_is_clean():
    """Reading the guarded attribute again after the suspension is the
    sanctioned fix — the stale pre-await verdict is refreshed."""
    src = (
        "from openr_tpu.common.runtime import Actor\n"
        "\n"
        "class Cache(Actor):\n"
        "    async def lookup(self, key):\n"
        "        if key not in self._entries:\n"
        "            value = await self._fetch(key)\n"
        "            if key not in self._entries:\n"
        "                self._entries[key] = value\n"
        "        return self._entries[key]\n"
    )
    assert analyze_source(src) == []


def test_suspension_is_a_may_property_across_overrides():
    """An awaited method resolved through a base class suspends if ANY
    override suspends — the abstract base's stub body must not launder
    the subclass's sleep into a non-suspension."""
    src = (
        "from openr_tpu.common.runtime import Actor\n"
        "\n"
        "class Backend:\n"
        "    async def fetch(self, key):\n"
        "        raise NotImplementedError\n"
        "\n"
        "class RpcBackend(Backend):\n"
        "    async def fetch(self, key):\n"
        "        return await self.transport.call(key)\n"
        "\n"
        "class Cache(Actor):\n"
        "    def __init__(self, backend: Backend):\n"
        "        self._backend = backend\n"
        "\n"
        "    async def lookup(self, key):\n"
        "        if key not in self._entries:\n"
        "            value = await self._backend.fetch(key)\n"
        "            self._entries[key] = value\n"
        "        return self._entries[key]\n"
    )
    assert _rules(analyze_source(src)) == [("await-atomicity", 18)]


# ---------------------------------------------------------------------------
# the --cache contract: suspension facts ride in the project digest
# ---------------------------------------------------------------------------

CALLER_SRC = (
    "from openr_tpu.common.runtime import Actor\n"
    "from helpers import classify\n"
    "\n"
    "class Cache(Actor):\n"
    "    async def lookup(self, key):\n"
    "        if key not in self._entries:\n"
    "            kind = await classify(key)\n"
    "            self._entries[key] = kind\n"
    "        return self._entries[key]\n"
)

HELPER_PURE = "async def classify(key):\n    return len(key)\n"

HELPER_SUSPENDS = (
    "import asyncio\n"
    "\n"
    "async def classify(key):\n"
    "    await asyncio.sleep(0)  # orlint: disable=clock-sleep (fixture)\n"
    "    return len(key)\n"
)


def test_cache_helper_turning_suspending_invalidates_caller(
    tmp_path, monkeypatch
):
    """The suspension-summary digest contract: cache.py keys cached
    per-file findings on the PROJECT facts digest, and a function's
    ``suspends`` flag is part of its summary — so a helper edit that
    flips the flag must re-run the unchanged caller and surface the
    atomicity finding its cached (clean) verdict would have hidden."""
    d = tmp_path / "src"
    d.mkdir()
    # root the analysis at the tree so rel paths ("caller.py") double as
    # module names and `from helpers import classify` resolves in-tree
    from openr_tpu.analysis import engine

    monkeypatch.setattr(engine, "repo_root", lambda: d)
    (d / "caller.py").write_text(CALLER_SRC)
    (d / "helpers.py").write_text(HELPER_PURE)
    cache = tmp_path / "cache.json"

    r1 = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r1.files_parsed == 2
    assert r1.findings == []  # helper is pure: the await never yields

    # warm re-run: nothing changed, nothing re-parsed, still clean
    r2 = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r2.files_parsed == 0
    assert r2.findings == []

    # the helper starts suspending; caller.py is byte-identical, but its
    # cached verdict is stale — the digest shift must force a live run
    (d / "helpers.py").write_text(HELPER_SUSPENDS)
    r3 = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r3.files_parsed == 2, "caller must re-run on suspension shift"
    assert [(f.path, f.rule) for f in r3.findings] == [
        ("caller.py", "await-atomicity")
    ]

    # and the new verdict is itself cached: warm run, same finding
    r4 = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r4.files_parsed == 0
    assert [f.key() for f in r4.findings] == [f.key() for f in r3.findings]
