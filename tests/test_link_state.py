"""LinkState scalar-core tests — golden semantics ported in spirit from
openr/decision/tests/LinkStateTest.cpp."""

import pytest

from openr_tpu.decision.link_state import LinkState
from openr_tpu.emulation.topology import (
    build_adj_dbs,
    grid_edges,
    line_edges,
    make_adjacency,
    ring_edges,
)
from openr_tpu.types import Adjacency, AdjacencyDatabase


def make_link_state(edges, area="0", **kwargs) -> LinkState:
    ls = LinkState(area)
    for db in build_adj_dbs(edges, area=area, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls


def test_unidirectional_adjacency_makes_no_link():
    ls = LinkState("0")
    db_a = AdjacencyDatabase("a", adjacencies=[make_adjacency("a", "b")], area="0")
    ls.update_adjacency_database(db_a)
    assert ls.num_links() == 0
    # b confirms -> link appears, topology changed
    db_b = AdjacencyDatabase("b", adjacencies=[make_adjacency("b", "a")], area="0")
    change = ls.update_adjacency_database(db_b)
    assert change.topology_changed
    assert ls.num_links() == 1


def test_spf_line_metrics_and_nexthops():
    ls = make_link_state(line_edges(4))  # node0-node1-node2-node3
    res = ls.run_spf("node0")
    assert res["node0"].metric == 0
    assert res["node1"].metric == 1
    assert res["node3"].metric == 3
    assert res["node1"].next_hops == {"node1"}
    assert res["node3"].next_hops == {"node1"}


def test_spf_ecmp_diamond_all_shortest_paths():
    #    a
    #   / \
    #  b   c
    #   \ /
    #    d
    edges = [("a", "b", 1), ("a", "c", 1), ("b", "d", 1), ("c", "d", 1)]
    ls = make_link_state(edges)
    res = ls.run_spf("a")
    assert res["d"].metric == 2
    assert res["d"].next_hops == {"b", "c"}  # both equal-cost first-hops
    assert len(res["d"].path_links) == 2


def test_spf_asymmetric_metric_uses_max():
    # soft-drain semantics: one side raises its metric, SPF uses max
    edges = [("a", "b", 1), ("b", "a", 10), ("b", "c", 1), ("a", "c", 5)]
    ls = make_link_state(edges)
    res = ls.run_spf("a")
    # a->b direct costs max(1,10)=10; a->c direct = 5; a->c->b = 5+1=6
    assert res["b"].metric == 6
    assert res["b"].next_hops == {"c"}


def test_spf_node_overload_no_transit():
    # b overloaded: a can still reach b but not THROUGH b
    edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 10)]
    ls = make_link_state(edges, overloaded=["b"])
    res = ls.run_spf("a")
    assert res["b"].metric == 1  # reachable
    assert res["c"].metric == 10  # forced around b
    assert res["c"].next_hops == {"c"}
    # overloaded root still routes out of itself
    res_b = ls.run_spf("b")
    assert res_b["a"].metric == 1 and res_b["c"].metric == 1


def test_spf_interface_overload_excludes_link():
    edges = [("a", "b", 1), ("b", "c", 1), ("a", "c", 10)]
    ls = make_link_state(edges)
    # hard-drain interface a->b from a's side
    db = AdjacencyDatabase(
        "a",
        adjacencies=[
            make_adjacency("a", "b", 1, is_overloaded=True),
            make_adjacency("a", "c", 10),
        ],
        area="0",
    )
    change = ls.update_adjacency_database(db)
    assert change.topology_changed
    res = ls.run_spf("a")
    assert res["b"].metric == 11  # via c
    assert res["c"].metric == 10


def test_spf_hop_count_mode():
    edges = [("a", "b", 100), ("b", "c", 100), ("a", "c", 500)]
    ls = make_link_state(edges)
    res = ls.run_spf("a", use_link_metric=False)
    assert res["c"].metric == 1  # direct edge = 1 hop
    assert res["b"].metric == 1


def test_spf_memoization_and_invalidation():
    ls = make_link_state(ring_edges(6))
    ls.get_spf_result("node0")
    ls.get_spf_result("node0")
    assert ls.num_spf_runs == 1  # memoized
    ls.get_spf_result("node0", use_link_metric=False)
    assert ls.num_spf_runs == 2  # different key
    # attribute-only change (adj label) does NOT invalidate
    dbs = build_adj_dbs(ring_edges(6))
    db = dbs["node0"]
    for adj in db.adjacencies:
        adj.adj_label = 50001
    change = ls.update_adjacency_database(db)
    assert change.link_attributes_changed and not change.topology_changed
    ls.get_spf_result("node0")
    assert ls.num_spf_runs == 2
    # metric change DOES invalidate
    for adj in db.adjacencies:
        adj.metric = 7
    change = ls.update_adjacency_database(db)
    assert change.topology_changed
    ls.get_spf_result("node0")
    assert ls.num_spf_runs == 3


def test_delete_adjacency_database():
    ls = make_link_state(line_edges(3))
    assert ls.has_node("node1")
    change = ls.delete_adjacency_database("node1")
    assert change.topology_changed
    res = ls.run_spf("node0")
    assert "node2" not in res  # partitioned


def test_get_metric_a_to_b():
    ls = make_link_state(line_edges(4))
    assert ls.get_metric_from_a_to_b("node0", "node3") == 3
    assert ls.get_metric_from_a_to_b("node0", "node0") == 0
    ls.delete_adjacency_database("node3")
    assert ls.get_metric_from_a_to_b("node0", "node3") is None


def test_kth_paths_ring():
    # square ring: two edge-disjoint paths between opposite corners
    ls = make_link_state(ring_edges(4))
    p1 = ls.get_kth_paths("node0", "node2", 1)
    p2 = ls.get_kth_paths("node0", "node2", 2)
    # k=1: both equal-cost 2-hop paths are edge-disjoint -> both traced
    assert len(p1) == 2
    assert all(len(p) == 2 for p in p1)
    # k=2: all links already used by k=1 paths
    assert p2 == []


def test_kth_paths_unequal_cost_disjoint():
    # path1: a-b-d (cost 2); path2: a-c-d (cost 4): k=2 finds the longer one
    edges = [("a", "b", 1), ("b", "d", 1), ("a", "c", 2), ("c", "d", 2)]
    ls = make_link_state(edges)
    p1 = ls.get_kth_paths("a", "d", 1)
    assert len(p1) == 1 and len(p1[0]) == 2
    nodes1 = {l.n1 for l in p1[0]} | {l.n2 for l in p1[0]}
    assert nodes1 == {"a", "b", "d"}
    p2 = ls.get_kth_paths("a", "d", 2)
    assert len(p2) == 1
    nodes2 = {l.n1 for l in p2[0]} | {l.n2 for l in p2[0]}
    assert nodes2 == {"a", "c", "d"}
    # k=3: exhausted
    assert ls.get_kth_paths("a", "d", 3) == []


def test_adj_only_used_by_other_node():
    # b is initializing: adj a->b marked adjOnlyUsedByOtherNode.
    # From a's perspective (my_node_name=a) the link is unusable;
    # from b's perspective it is usable.
    adj_ab = make_adjacency("a", "b", 1, adj_only_used_by_other_node=True)
    adj_ba = make_adjacency("b", "a", 1)
    db_a = AdjacencyDatabase("a", adjacencies=[adj_ab], area="0")
    db_b = AdjacencyDatabase("b", adjacencies=[adj_ba], area="0")

    ls_a = LinkState("0", my_node_name="a")
    ls_a.update_adjacency_database(db_a)
    ls_a.update_adjacency_database(db_b)
    res_a = ls_a.run_spf("a")
    assert "b" not in res_a  # a must not route to/through initializing b

    ls_b = LinkState("0", my_node_name="b")
    ls_b.update_adjacency_database(db_a)
    ls_b.update_adjacency_database(db_b)
    res_b = ls_b.run_spf("b")
    assert res_b["a"].metric == 1  # b may route through a


def test_grid_spf_corner_to_corner():
    n = 4
    ls = make_link_state(grid_edges(n))
    res = ls.run_spf("node0")
    # manhattan distance to far corner
    assert res[f"node{n * n - 1}"].metric == 2 * (n - 1)
    # both directions out of the corner are equal-cost first hops
    assert res[f"node{n * n - 1}"].next_hops == {"node1", f"node{n}"}


def test_spf_root_missing_returns_root_only():
    ls = make_link_state(line_edges(3))
    res = ls.run_spf("ghost")
    assert set(res) == {"ghost"}
    assert res["ghost"].metric == 0


def test_random_connected_edges_clamps_extra():
    from openr_tpu.emulation.topology import random_connected_edges

    edges = random_connected_edges(3, extra_edges=99, seed=1)
    assert len(edges) == 3  # spanning tree (2) + max 1 chord, no hang


def test_make_adjacency_deterministic_across_calls():
    a1 = make_adjacency("x", "y")
    a2 = make_adjacency("x", "y")
    assert a1.next_hop_v6 == a2.next_hop_v6
    assert a1.next_hop_v6.startswith("fe80::")
