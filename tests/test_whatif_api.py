"""Operator what-if API — per-failure route deltas vs scalar recompute.

For each candidate link failure, the API's reported changes must match
the difference between the scalar oracle's RouteDb on the intact
topology and on a topology with the link actually removed."""

import pytest

from openr_tpu.common.runtime import SimClock
from openr_tpu.config import DecisionConfig
from openr_tpu.decision.backend import ScalarBackend, TpuBackend
from openr_tpu.decision.decision import Decision
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import PrefixEntry


def build_decision(backend_cls=TpuBackend):
    edges = grid_edges(4)
    dbs = build_adj_dbs(edges)
    ls = LinkState("0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(16):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))
    solver = SpfSolver("node0")
    d = Decision(
        "node0",
        SimClock(),
        DecisionConfig(),
        ReplicateQueue("routes"),
        backend=backend_cls(solver),
        solver=solver,
    )
    d.area_link_states = {"0": ls}
    d.prefix_state = ps
    return d, dbs


def scalar_routes_without_link(d, dbs, n1, n2):
    """Oracle: rebuild the LSDB with the link removed, solve scalar."""
    ls = LinkState("0")
    for node, db in dbs.items():
        import dataclasses

        filtered = dataclasses.replace(
            db,
            adjacencies=[
                a
                for a in db.adjacencies
                if {db.this_node_name, a.other_node_name} != {n1, n2}
            ],
        )
        ls.update_adjacency_database(filtered)
    return SpfSolver("node0").build_route_db({"0": ls}, d.prefix_state)


def routes_view(db):
    return {
        p: (round(e.igp_cost, 1), sorted(n.neighbor_node_name for n in e.nexthops))
        for p, e in db.unicast_routes.items()
    }


def test_whatif_matches_scalar_link_removal():
    d, dbs = build_decision()
    base = SpfSolver("node0").build_route_db(d.area_link_states, d.prefix_state)
    base_view = routes_view(base)

    cases = [("node0", "node1"), ("node1", "node2"), ("node14", "node15")]
    resp = d.get_link_failure_whatif([list(c) for c in cases])
    assert resp is not None and resp["eligible"]
    assert resp["vantage"] == "node0"

    for f, (n1, n2) in zip(resp["failures"], cases):
        oracle = scalar_routes_without_link(d, dbs, n1, n2)
        oracle_view = routes_view(oracle)
        expected = {}
        for p in set(base_view) | set(oracle_view):
            was, now = p in base_view, p in oracle_view
            if was and not now:
                expected[p] = ("removed", base_view[p][1], [])
            elif now and not was:
                expected[p] = ("added", [], oracle_view[p][1])
            elif base_view[p] != oracle_view[p]:
                expected[p] = ("rerouted", base_view[p][1], oracle_view[p][1])
        got = {
            ch["prefix"]: (
                ch["change"],
                sorted(ch["old_nexthops"]),
                sorted(ch["new_nexthops"]),
            )
            for ch in f["changes"]
        }
        assert got == expected, (f["link"], got, expected)


def test_whatif_off_dag_link_reports_no_changes():
    """In a unit-metric grid EVERY link is on some shortest path from the
    corner, so force one off-DAG by giving it a heavy metric: the engine
    must classify it off the DAG and report zero route changes (base
    aliasing), matching the scalar recompute."""

    edges = [
        (a, b, 10 if {a, b} == {"node14", "node15"} else m)
        for (a, b, m) in grid_edges(4)
    ]
    dbs = build_adj_dbs(edges)
    ls = LinkState("0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(16):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))
    solver = SpfSolver("node0")
    d = Decision(
        "node0",
        SimClock(),
        DecisionConfig(),
        ReplicateQueue("routes"),
        backend=TpuBackend(solver),
        solver=solver,
    )
    d.area_link_states = {"0": ls}
    d.prefix_state = ps
    resp = d.get_link_failure_whatif([["node14", "node15"]])
    f = resp["failures"][0]
    assert f["on_shortest_path_dag"] is False  # heavy link beats no path
    assert f["routes_changed"] == 0


def test_whatif_unknown_link_and_scalar_backend():
    d, _dbs = build_decision()
    resp = d.get_link_failure_whatif([["node0", "node15"]])  # not adjacent
    assert resp["failures"][0]["error"] == "unknown link"

    # scalar-only deployments now serve single-area what-if via the
    # NATIVE engine (no jax loads) — same answers as the device path
    d2, _ = build_decision(backend_cls=ScalarBackend)
    scalar_resp = d2.get_link_failure_whatif([["node0", "node1"]])
    assert scalar_resp is not None and scalar_resp["eligible"]
    assert d2._whatif_native_engine is not None
    assert d2._whatif_engine is None  # device engine never constructed
    tpu_resp = d.get_link_failure_whatif([["node0", "node1"]])
    assert scalar_resp == tpu_resp


def test_whatif_engine_cached_across_calls():
    d, _dbs = build_decision()
    d.get_link_failure_whatif([["node0", "node1"]])
    # the auto choice may pick either warm-start engine; both cache per
    # LSDB generation
    eng = d._whatif_engine or d._whatif_native_engine
    assert eng.num_engine_builds == 1
    d.get_link_failure_whatif([["node1", "node2"]])
    assert eng.num_engine_builds == 1  # cached until LSDB changes
    d.prefix_state.update_prefix("node3", "0", PrefixEntry("10.99.0.0/24"))
    d._change_seq += 1
    d.get_link_failure_whatif([["node1", "node2"]])
    assert eng.num_engine_builds == 2


def test_native_engine_matches_device_engine():
    """NativeWhatIfEngine (C++ warm sweep + numpy selection) must give
    BYTE-identical operator output to the device engine on the same
    world — the two are auto-chosen per deployment, so any drift is an
    operator-visible inconsistency."""
    import numpy as np

    from openr_tpu.decision.whatif_api import (
        NativeWhatIfEngine,
        WhatIfApiEngine,
    )
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import (
        build_adj_dbs,
        random_connected_edges,
    )
    from openr_tpu.types import PrefixEntry, PrefixMetrics

    ls = LinkState("0")
    for db in build_adj_dbs(
        random_connected_edges(48, 70, seed=5),
        soft_drained={"node7": 50},
        overloaded=["node11"],
    ).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(48):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))
    # anycast with preference spread
    ps.update_prefix("node3", "0", PrefixEntry(
        "10.99.0.0/24", metrics=PrefixMetrics(path_preference=900)))
    ps.update_prefix("node40", "0", PrefixEntry(
        "10.99.0.0/24", metrics=PrefixMetrics(path_preference=900)))
    als = {"0": ls}
    failures = [("node0", "node1"), ("node5", "node9"), ("nope", "x")]
    # every real link too, for breadth
    from openr_tpu.ops.csr import encode_link_state

    topo = encode_link_state(ls)
    failures += [(l.n1, l.n2) for l in topo.links[:40]]

    dev = WhatIfApiEngine(SpfSolver("node0")).run(failures, als, ps, 1)
    nat = NativeWhatIfEngine(SpfSolver("node0")).run(failures, als, ps, 1)
    # engines self-identify; everything else must be byte-identical
    assert nat.pop("engine") == "native" and dev.pop("engine") == "device"
    assert nat == dev


def test_decision_auto_picks_native_for_small_queries():
    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import DecisionConfig
    from openr_tpu.decision.backend import TpuBackend
    from openr_tpu.decision.decision import Decision
    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.emulation.topology import build_adj_dbs, grid_edges
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.types import PrefixEntry

    ls = LinkState("0")
    for db in build_adj_dbs(grid_edges(4)).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(16):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))
    backend = TpuBackend(SpfSolver("node0"))
    d = Decision(
        "node0", SimClock(), DecisionConfig(), ReplicateQueue(),
        backend=backend,
    )
    d.area_link_states = {"0": ls}
    d.prefix_state = ps
    d._change_seq = 1
    # tunnel-like dispatch: native engine must serve the query
    backend.auto_dispatch_rt_ms = 75.0
    res = d.get_link_failure_whatif([("node0", "node1")])
    assert res is not None and res["eligible"]
    assert d._whatif_native_engine is not None
    assert d._whatif_engine is None
    # collocated device: large batches go to the device engine
    backend.auto_dispatch_rt_ms = 0.01
    res2 = d.get_link_failure_whatif([("node0", "node1")] * 24)
    assert res2 is not None
    assert d._whatif_engine is not None
    # and both engines agreed on the single-failure answer
    assert res["failures"][0] == res2["failures"][0]


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_native_vs_device_engines_random_worlds(seed):
    """Property check: on random weighted topologies with random drains
    and anycast, the auto-selectable engines agree byte for byte."""
    import numpy as np

    from openr_tpu.decision.link_state import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.decision.whatif_api import (
        NativeWhatIfEngine,
        WhatIfApiEngine,
    )
    from openr_tpu.emulation.topology import (
        build_adj_dbs,
        random_connected_edges,
    )
    from openr_tpu.ops.csr import encode_link_state
    from openr_tpu.types import PrefixEntry, PrefixMetrics

    rng = np.random.default_rng(seed)
    n = int(rng.integers(24, 56))
    edges = random_connected_edges(n, n + int(rng.integers(8, 40)), seed=seed)
    drained = {f"node{int(rng.integers(1, n))}": 40}
    over = [f"node{int(rng.integers(1, n))}"]
    ls = LinkState("0")
    for db in build_adj_dbs(
        edges, soft_drained=drained, overloaded=over
    ).values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(n):
        ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))
    a1, a2 = rng.integers(1, n, size=2)
    ps.update_prefix(f"node{a1}", "0", PrefixEntry(
        "10.200.0.0/24", metrics=PrefixMetrics(source_preference=150)))
    ps.update_prefix(f"node{a2}", "0", PrefixEntry(
        "10.200.0.0/24", metrics=PrefixMetrics(source_preference=150)))
    als = {"0": ls}
    topo = encode_link_state(ls)
    failures = [(l.n1, l.n2) for l in topo.links]
    dev = WhatIfApiEngine(SpfSolver("node0")).run(failures, als, ps, 1)
    nat = NativeWhatIfEngine(SpfSolver("node0")).run(failures, als, ps, 1)
    # engines self-identify; everything else must be byte-identical
    assert nat.pop("engine") == "native" and dev.pop("engine") == "device"
    assert nat == dev


def test_scalar_whatif_never_touches_device_stack():
    """A scalar-only deployment serving an operator what-if must stay
    off the device stack entirely: no openr_tpu device module imported,
    no PJRT backend initialized (over a tunneled TPU, backend init
    alone stalls for seconds — this regressed once via a module-level
    jnp constant in ops.spf pulled in through ops.route_select)."""
    import subprocess
    import sys

    script = r"""
import sys
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.decision.decision import Decision
from openr_tpu.decision.backend import ScalarBackend
from openr_tpu.common.runtime import SimClock
from openr_tpu.config import DecisionConfig
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.emulation.topology import grid_edges, build_adj_dbs
from openr_tpu.types import PrefixEntry

ls = LinkState("0")
for db in build_adj_dbs(grid_edges(4)).values():
    ls.update_adjacency_database(db)
ps = PrefixState()
for i in range(16):
    ps.update_prefix(f"node{i}", "0", PrefixEntry(f"10.{i}.0.0/24"))
solver = SpfSolver("node0")
d = Decision("node0", SimClock(), DecisionConfig(), ReplicateQueue("r"),
             backend=ScalarBackend(solver), solver=solver)
d.area_link_states = {"0": ls}
d.prefix_state = ps
resp = d.get_link_failure_whatif([["node0", "node1"]])
assert resp and resp["eligible"], resp
assert resp["failures"][0]["routes_changed"] > 0, resp
for mod in ("openr_tpu.ops.spf", "openr_tpu.ops.route_select",
            "openr_tpu.ops.repair", "openr_tpu.ops.sweep_select"):
    assert mod not in sys.modules, f"device module leaked: {mod}"
if "jax" in sys.modules:  # the axon shim preloads jax at startup
    from jax._src import xla_bridge
    assert not xla_bridge._backends, (
        "PJRT backend initialized: %s" % list(xla_bridge._backends))
print("CLEAN")
"""
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "CLEAN" in out.stdout


# ---- simultaneous (set) failures -------------------------------------------


def scalar_routes_without_links(d, dbs, pairs):
    """Oracle: rebuild the LSDB with ALL listed links removed."""
    import dataclasses

    ls = LinkState("0")
    sets = [frozenset(p) for p in pairs]
    for node, db in dbs.items():
        filtered = dataclasses.replace(
            db,
            adjacencies=[
                a
                for a in db.adjacencies
                if frozenset((db.this_node_name, a.other_node_name))
                not in sets
            ],
        )
        ls.update_adjacency_database(filtered)
    return SpfSolver("node0").build_route_db({"0": ls}, d.prefix_state)


def apply_whatif_changes(base_view, failure):
    got = dict(base_view)
    for ch in failure["changes"]:
        if ch["change"] == "withdrawn":
            got.pop(ch["prefix"], None)
        else:
            got[ch["prefix"]] = (
                round(ch["new_metric"], 1),
                sorted(ch["new_nexthops"]),
            )
    return got


@pytest.mark.parametrize("engine", ["device", "native"])
def test_whatif_simultaneous_matches_scalar_multi_removal(engine):
    """--simultaneous: the combined answer must equal the scalar oracle
    with EVERY listed link removed at once, through both the device
    (run_sets) and native (spf_scalar_solve_set) engines."""
    d, dbs = build_decision()
    # force the engine choice via the dispatch-RT calibration override
    # (expensive RT -> native, free RT -> device)
    d._whatif_rt_ms = 1000.0 if engine == "native" else 1e-6

    base = SpfSolver("node0").build_route_db(
        d.area_link_states, d.prefix_state
    )
    base_view = routes_view(base)

    pairs = [("node0", "node1"), ("node5", "node6"), ("node10", "node14")]
    resp = d.get_link_failure_whatif(
        [list(p) for p in pairs], simultaneous=True
    )
    assert resp is not None and resp["eligible"]
    assert resp.get("simultaneous") is True
    (f,) = resp["failures"]
    assert f["links"] == [list(p) for p in pairs]

    oracle = routes_view(scalar_routes_without_links(d, dbs, pairs))
    got = apply_whatif_changes(base_view, f)
    assert got == oracle, engine


def test_whatif_simultaneous_unknown_link_errors():
    d, _dbs = build_decision()
    resp = d.get_link_failure_whatif(
        [["node0", "node1"], ["node0", "nope"]], simultaneous=True
    )
    assert resp["eligible"]
    assert resp["failures"][0]["error"] == "unknown link"


def test_whatif_simultaneous_multiarea_uses_device_kernel():
    """Set-failure analysis on a multi-area vantage runs on the
    multi-area DEVICE kernel since r5 (per-snapshot failure SETS are
    masked on device); parity vs the scalar oracle is asserted."""
    d, dbs = build_decision()
    d.area_link_states["1"] = LinkState("1")
    resp = d.get_link_failure_whatif(
        [["node0", "node1"], ["node5", "node6"]], simultaneous=True
    )
    assert resp is not None and resp["eligible"]
    assert resp["engine"] == "multiarea"
    (f,) = resp["failures"]
    # parity vs the scalar oracle with both links removed
    base_view = routes_view(
        SpfSolver("node0").build_route_db(d.area_link_states, d.prefix_state)
    )
    oracle = routes_view(
        scalar_routes_without_links(
            d, dbs, [("node0", "node1"), ("node5", "node6")]
        )
    )
    assert apply_whatif_changes(base_view, f) == oracle


def test_scalar_only_high_fanout_uses_generic_engine():
    """A scalar-only vantage with more out-links than the native
    engine's 64-lane limit must answer through the jax-free generic
    engine, not return ineligible (code-review r4): previously this
    configuration had NO eligible engine."""
    star = [("node0", f"leaf{i}", 1) for i in range(70)]
    dbs = build_adj_dbs(star)
    ls = LinkState("0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for i in range(70):
        ps.update_prefix(f"leaf{i}", "0", PrefixEntry(f"10.0.{i}.0/24"))
    solver = SpfSolver("node0")
    d = Decision(
        "node0",
        SimClock(),
        DecisionConfig(),
        ReplicateQueue("routes"),
        backend=ScalarBackend(solver),
        solver=solver,
    )
    d.area_link_states = {"0": ls}
    d.prefix_state = ps
    resp = d.get_link_failure_whatif([["node0", "leaf3"]])
    assert resp is not None and resp["eligible"]
    assert resp["engine"] == "generic-solver"
    assert d._whatif_engine is None  # device engine never constructed
    (f,) = resp["failures"]
    assert f["routes_changed"] == 1
    assert f["changes"][0]["prefix"] == "10.0.3.0/24"
    assert f["changes"][0]["change"] == "removed"


def _parallel_world():
    """a ==2 parallel links== b -- c; prefixes on b and c."""
    from openr_tpu.types import Adjacency, AdjacencyDatabase

    def db(me, adjs):
        return AdjacencyDatabase(
            this_node_name=me,
            adjacencies=[
                Adjacency(
                    other_node_name=o,
                    if_name=i,
                    metric=m,
                    other_if_name=ri,
                )
                for (o, i, m, ri) in adjs
            ],
        )

    ls = LinkState("0")
    ls.update_adjacency_database(
        db("a", [("b", "if_ab1", 1, "if_ba1"), ("b", "if_ab2", 2, "if_ba2")])
    )
    ls.update_adjacency_database(
        db(
            "b",
            [
                ("a", "if_ba1", 1, "if_ab1"),
                ("a", "if_ba2", 2, "if_ab2"),
                ("c", "if_bc", 1, "if_cb"),
            ],
        )
    )
    ls.update_adjacency_database(db("c", [("b", "if_cb", 1, "if_bc")]))
    ps = PrefixState()
    ps.update_prefix("b", "0", PrefixEntry("10.0.1.0/24"))
    ps.update_prefix("c", "0", PrefixEntry("10.0.2.0/24"))
    return ls, ps


@pytest.mark.parametrize("engine", ["device", "native"])
def test_whatif_parallel_bundle_fails_as_set(engine):
    """A (n1, n2) pair with PARALLEL links no longer errors: the engines
    fail the whole bundle as one simultaneous set (failing just one
    would shift traffic to the survivors and mislead)."""
    ls, ps = _parallel_world()
    assert len(ls.all_links()) == 3  # 2 parallel a-b + 1 b-c
    solver = SpfSolver("a")
    d = Decision(
        "a",
        SimClock(),
        DecisionConfig(),
        ReplicateQueue("routes"),
        backend=(TpuBackend if engine == "device" else ScalarBackend)(
            solver
        ),
        solver=solver,
    )
    d.area_link_states = {"0": ls}
    d.prefix_state = ps
    d._whatif_rt_ms = 1000.0 if engine == "native" else 1e-6
    resp = d.get_link_failure_whatif([["a", "b"]])
    assert resp is not None and resp["eligible"]
    (f,) = resp["failures"]
    assert "error" not in f
    assert f["links_failed"] == 2
    # both a-b links down => b and c unreachable: both prefixes removed
    assert f["routes_changed"] == 2
    assert {c["prefix"] for c in f["changes"]} == {
        "10.0.1.0/24",
        "10.0.2.0/24",
    }
    assert all(c["change"] == "removed" for c in f["changes"])


def test_whatif_parallel_bundle_generic_engine_matches():
    """The generic solver engine answers the same bundle identically."""
    from openr_tpu.decision.whatif_api import GenericSolverWhatIfEngine

    ls, ps = _parallel_world()
    eng = GenericSolverWhatIfEngine(SpfSolver("a"))
    resp = eng.run([("a", "b")], {"0": ls}, ps, change_seq=1)
    (f,) = resp["failures"]
    assert f["links_failed"] == 2
    assert {c["prefix"] for c in f["changes"]} == {
        "10.0.1.0/24",
        "10.0.2.0/24",
    }
    assert all(c["change"] == "removed" for c in f["changes"])


def test_link_criticality_matches_per_link_whatif():
    """The criticality report's per-link counts must equal what the
    per-link what-if reports, link by link."""
    d, _dbs = build_decision()
    crit = d.get_link_criticality()
    assert crit is not None
    assert len(crit["links"]) == 24  # 4x4 grid undirected links
    # cross-check three links against the what-if answers
    for e in crit["links"][:3]:
        n1, n2 = e["link"]
        resp = d.get_link_failure_whatif([[n1, n2]])
        (f,) = resp["failures"]
        assert f["routes_changed"] == e["routes_changed"], e
        removed = sum(
            1 for c in f["changes"] if c["change"] == "removed"
        )
        assert removed == e["routes_withdrawn"], e
    # ranking is by withdrawn desc
    w = [e["routes_withdrawn"] for e in crit["links"]]
    assert w == sorted(w, reverse=True)


def test_link_criticality_pair_scan_finds_partitions():
    """Double-failure scan: pairs that withdraw routes beyond their
    single failures must match a brute-force oracle on a small world."""
    from openr_tpu.ops.native_spf import NativeSpf
    from openr_tpu.ops.csr import encode_link_state

    d, _dbs = build_decision()
    crit = d.get_link_criticality(max_pairs=10_000)
    p = crit["pairs"]
    assert p is not None and not p["truncated"]
    # oracle: for every scanned on-DAG pair, removed = prefixes whose
    # advertiser becomes unreachable from node0 (single-advertiser
    # world, all preferences equal)
    ls = d.area_link_states["0"]
    topo = encode_link_state(ls)
    nat = NativeSpf(topo, "node0")
    base_removed = {}
    import itertools

    import numpy as np

    from openr_tpu.ops.whatif import LinkFailureSweep

    eng = LinkFailureSweep(topo, "node0")
    on_dag = eng.on_dag_links()
    # same universe the engine scans: pairs with >= 1 on-DAG member
    # (a pure off-DAG pair provably changes nothing)
    pair_universe = [
        (a, b)
        for a, b in itertools.combinations(range(len(topo.links)), 2)
        if on_dag[a] or on_dag[b]
    ]
    want_risky = 0
    for a, b in pair_universe:
        def removed_for(lids):
            nd, _ = nat.solve_set(list(lids))
            lanes = nat.lanes_dense(eng.D)
            return sum(
                1
                for v in range(16)
                if v != topo.node_id("node0")
                and not (np.isfinite(nd[v]) and lanes[v].any())
            )

        extra = removed_for([a, b]) - removed_for([a]) - removed_for([b])
        if extra > 0:
            want_risky += 1
    assert p["risky_count"] == want_risky


def test_link_criticality_catches_primary_plus_backup_pairs():
    """The canonical partition-risk case pairs an ON-DAG primary with
    an OFF-DAG backup: each single failure merely reroutes (or changes
    nothing), but together they partition.  The pair scan must include
    on x off pairs (code-review r4: an on-DAG-only scan missed
    exactly these)."""
    edges = [
        ("node0", "a", 1), ("a", "v", 1),      # cheap primary
        ("node0", "b", 10), ("b", "v", 10),    # expensive backup
    ]
    dbs = build_adj_dbs(edges)
    ls = LinkState("0")
    for db in dbs.values():
        ls.update_adjacency_database(db)
    ps = PrefixState()
    for n in ("a", "b", "v"):
        ps.update_prefix(n, "0", PrefixEntry(f"10.0.{ord(n[0])}.0/24"))
    solver = SpfSolver("node0")
    d = Decision(
        "node0",
        SimClock(),
        DecisionConfig(),
        ReplicateQueue("routes"),
        backend=TpuBackend(solver),
        solver=solver,
    )
    d.area_link_states = {"0": ls}
    d.prefix_state = ps
    crit = d.get_link_criticality(max_pairs=100)
    # single failures withdraw NOTHING (the ring reroutes everything)
    by_link = {tuple(e["link"]): e for e in crit["links"]}
    assert by_link[("a", "node0")]["routes_withdrawn"] == 0
    assert by_link[("b", "node0")]["routes_withdrawn"] == 0
    # the (node0-a, node0-b) pair isolates node0 -> partition risk found
    risky_pairs = {
        frozenset(tuple(l) for l in e["links"])
        for e in crit["pairs"]["risky"]
    }
    assert frozenset(
        {("a", "node0"), ("b", "node0")}
    ) in risky_pairs, crit["pairs"]
