"""Native bulk LSDB ingest: the kernel may only be faster, never
different.  Parity is pinned by running the same publications through
the bulk path (native/lsdb_decode.cc) and the scalar path
(lsdb_codec + generic from_wire) and requiring identical PrefixState.

Reference analogue: the C++ thrift decode feeding mergeKeyValues
(openr/kvstore/KvStoreUtil.cpp:391) — decode speed is an implementation
property, semantics live in one place."""

import json
import random

import pytest

from openr_tpu.decision.ingest import (
    ST_DELETE,
    ST_FALLBACK,
    ST_FAST,
    BulkPrefixDecoder,
    get_bulk_decoder,
)
from openr_tpu.lsdb_codec import deserialize_prefix_db, serialize_prefix_db
from openr_tpu.types import (
    PerfEvent,
    PerfEvents,
    PrefixDatabase,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    PrefixMetrics,
    PrefixType,
)


@pytest.fixture(scope="module")
def dec():
    d = get_bulk_decoder()
    if d is None:
        pytest.skip("native lsdb decoder unavailable")
    return d


def _random_db(rng: random.Random) -> PrefixDatabase:
    v6 = rng.random() < 0.3
    if v6:
        plen = rng.randint(16, 128)
        addr = f"2001:db8:{rng.randint(0, 0xFFFF):x}::{rng.randint(1, 0xFFFF):x}"
        prefix = f"{addr}/{plen}"
    else:
        plen = rng.randint(8, 32)
        prefix = (
            f"{rng.randint(1, 223)}.{rng.randint(0, 255)}."
            f"{rng.randint(0, 255)}.{rng.randint(0, 255)}/{plen}"
        )
    entry = PrefixEntry(
        prefix,
        type=rng.choice(list(PrefixType)),
        forwarding_type=rng.choice(list(PrefixForwardingType)),
        forwarding_algorithm=rng.choice(list(PrefixForwardingAlgorithm)),
        min_nexthop=rng.choice([None, 1, 4]),
        metrics=PrefixMetrics(
            version=rng.randint(1, 3),
            drain_metric=rng.randint(0, 1),
            path_preference=rng.randint(0, 2000),
            source_preference=rng.randint(0, 200),
            distance=rng.randint(0, 8),
        ),
        weight=rng.choice([None, 10]),
    )
    return PrefixDatabase(f"node{rng.randint(0, 63)}", [entry])


def test_fast_rows_match_scalar_decoder_exactly(dec):
    rng = random.Random(1234)
    dbs = [_random_db(rng) for _ in range(300)]
    payloads = []
    for db in dbs:
        payloads.append(serialize_prefix_db(db, "json"))
        payloads.append(serialize_prefix_db(db, "thrift-compact"))
    status, entries = dec.decode(payloads)
    fast = 0
    for i, payload in enumerate(payloads):
        want_db = deserialize_prefix_db(payload)
        if status[i] == ST_FAST:
            fast += 1
            assert entries[i] == want_db.prefix_entries[0], (
                i,
                entries[i],
                want_db.prefix_entries[0],
            )
        # fallback rows are allowed — scalar path serves them — but the
        # canonical shapes must overwhelmingly hit the fast path
    assert fast >= len(payloads) * 0.95, fast


def test_off_shape_payloads_fall_back_not_misdecode(dec):
    odd = [
        # multi-entry
        PrefixDatabase("a", [PrefixEntry("1.2.3.0/24"), PrefixEntry("1.2.4.0/24")]),
        # tags / area_stack
        PrefixDatabase("b", [PrefixEntry("10.0.0.0/8", tags={"x"})]),
        PrefixDatabase("c", [PrefixEntry("10.0.0.0/8", area_stack=["0", "1"])]),
        # perf events ride-along
        PrefixDatabase(
            "d",
            [PrefixEntry("10.1.0.0/16")],
            perf_events=PerfEvents([PerfEvent("d", "ORIGINATED", 1)]),
        ),
        # v4-mapped v6 (text form differs between inet_ntop and ipaddress)
        PrefixDatabase("e", [PrefixEntry("::ffff:1.2.3.4/128")]),
    ]
    for db in odd:
        for fmt in ("json", "thrift-compact"):
            payload = serialize_prefix_db(db, fmt)
            status, entries = dec.decode([payload])
            assert status[0] == ST_FALLBACK, (db.this_node_name, fmt, status)
    # garbage payloads must fall back, never crash
    status, _ = dec.decode([b"", b"\xff\x00garbage", b"{not json", b"\x18"])
    assert all(s == ST_FALLBACK for s in status)


def test_delete_and_normalization(dec):
    delete = serialize_prefix_db(PrefixDatabase("n", [], delete_prefix=True))
    empty = serialize_prefix_db(PrefixDatabase("n", []))
    status, _ = dec.decode([delete, empty])
    assert status == [ST_DELETE, ST_DELETE]

    # host bits zeroed + canonical v6 text, same as normalize_prefix
    raw = json.dumps(
        {
            "this_node_name": "n",
            "prefix_entries": [
                {
                    "prefix": "10.1.2.3/24",
                    "type": 1,
                    "forwarding_type": 0,
                    "forwarding_algorithm": 0,
                    "min_nexthop": None,
                    "metrics": {
                        "version": 1,
                        "drain_metric": 0,
                        "path_preference": 0,
                        "source_preference": 0,
                        "distance": 0,
                    },
                    "tags": [],
                    "area_stack": [],
                    "weight": None,
                }
            ],
            "delete_prefix": False,
        }
    ).encode()
    status, entries = dec.decode([raw])
    assert status == [ST_FAST]
    assert entries[0].prefix == "10.1.2.0/24"
    raw6 = raw.replace(b"10.1.2.3/24", b"2001:DB8:0:0:0:0:0:5/64")
    status, entries = dec.decode([raw6])
    assert status == [ST_FAST]
    assert entries[0].prefix == "2001:db8::/64"


def test_unknown_json_fields_skipped_like_from_wire(dec):
    obj = json.loads(
        serialize_prefix_db(
            PrefixDatabase("n", [PrefixEntry("10.2.0.0/16")])
        ).decode()
    )
    obj["future_field"] = {"nested": [1, 2, {"x": "y"}]}
    obj["prefix_entries"][0]["future_entry_field"] = "ok"
    status, entries = dec.decode([json.dumps(obj).encode()])
    assert status == [ST_FAST]
    assert entries[0].prefix == "10.2.0.0/16"


def test_decision_bulk_and_scalar_paths_converge_identically(monkeypatch):
    """Drive TWO Decision instances with the same >=32-key publications —
    one bulk (native), one scalar-forced — and require identical
    PrefixState contents."""
    if get_bulk_decoder() is None:
        pytest.skip("native lsdb decoder unavailable")

    from openr_tpu.common.runtime import SimClock
    from openr_tpu.config import DecisionConfig
    from openr_tpu.decision import decision as dmod
    from openr_tpu.messaging.queue import ReplicateQueue
    from openr_tpu.types import Publication, Value, prefix_key

    rng = random.Random(77)

    def make_decision():
        return dmod.Decision(
            "node0",
            SimClock(),
            DecisionConfig(),
            ReplicateQueue("routes"),
        )

    d_bulk = make_decision()
    d_scalar = make_decision()

    pubs = []
    for p in range(3):
        kvs = {}
        for i in range(60):
            db = _random_db(rng)
            if i % 17 == 0:  # sprinkle off-shape rows into the batch
                db.prefix_entries[0].tags = {"odd"}
            if i % 23 == 0:
                db = PrefixDatabase(db.this_node_name, [], delete_prefix=True)
            fmt = "json" if i % 2 else "thrift-compact"
            pfx = (
                db.prefix_entries[0].prefix
                if db.prefix_entries
                else f"10.{p}.{i}.0/24"
            )
            kvs[prefix_key(db.this_node_name, pfx)] = Value(
                version=1,
                originator_id=db.this_node_name,
                value=serialize_prefix_db(db, fmt),
            )
        pubs.append(Publication(key_vals=kvs))

    assert len(pubs[0].key_vals) >= dmod.Decision.BULK_INGEST_MIN
    for pub in pubs:
        d_bulk._on_publication(pub)

    # force the scalar path by hiding the decoder
    monkeypatch.setattr(dmod, "Decision", dmod.Decision)  # anchor
    import openr_tpu.decision.ingest as ing

    monkeypatch.setattr(ing, "get_bulk_decoder", lambda: None)
    for pub in pubs:
        d_scalar._on_publication(pub)

    assert d_bulk.prefix_state.prefixes() == d_scalar.prefix_state.prefixes()
    assert (
        d_bulk._pending_prefix_changes == d_scalar._pending_prefix_changes
    )


def test_missing_node_name_matches_scalar_rejection(dec):
    """JSON payloads the scalar decoder REJECTS (no this_node_name:
    from_wire raises) must fall back, not fast-decode — a value's effect
    must not depend on whether it arrived in a >=32-key batch (r5
    review)."""
    bare = b"{}"
    noname = json.dumps(
        {"prefix_entries": [], "delete_prefix": True}
    ).encode()
    status, _ = dec.decode([bare, noname])
    assert status == [ST_FALLBACK, ST_FALLBACK]
    # compact WITHOUT thisNodeName is accepted by the scalar decoder
    # (defaults to "") — the kernel mirrors that asymmetry
    from openr_tpu.interop.compact import encode_struct
    from openr_tpu.interop.openr_wire import PREFIX_DATABASE

    compact_noname = encode_struct(PREFIX_DATABASE, {"deletePrefix": True})
    want = deserialize_prefix_db(compact_noname)
    assert want.delete_prefix is True  # scalar path accepts
    status, _ = dec.decode([compact_noname])
    assert status == [ST_DELETE]


def test_compact_type_mismatch_falls_back(dec):
    """A foreign encoder changing a scalar field's wire type (e.g.
    forwardingType as binary) must fall back, never misdecode."""
    from openr_tpu.interop.compact import encode_struct

    # craft a PrefixEntry whose field 4 is a STRING (ct 8)
    entry_spec = (
        (1, "prefix", "struct", (
            (1, "prefixAddress", "struct", ((1, "addr", "binary", None),)),
            (2, "prefixLength", "i16", None),
        )),
        (4, "forwardingType", "string", None),
    )
    db_spec = (
        (1, "thisNodeName", "string", None),
        (3, "prefixEntries", "list", ("struct", entry_spec)),
    )
    payload = encode_struct(db_spec, {
        "thisNodeName": "n",
        "prefixEntries": [{
            "prefix": {"prefixAddress": {"addr": b"\x0a\x00\x00\x00"},
                       "prefixLength": 8},
            "forwardingType": "XX",
        }],
    })
    status, _ = dec.decode([payload])
    assert status == [ST_FALLBACK]


def test_unknown_enum_values_fall_back_like_scalar(dec):
    """An out-of-range PrefixType/forwarding enum must not fast-decode
    into a bare int — the scalar path raises and drops the row, so the
    kernel defers to it (r5 review)."""
    obj = json.loads(
        serialize_prefix_db(
            PrefixDatabase("n", [PrefixEntry("10.3.0.0/16")])
        ).decode()
    )
    obj["prefix_entries"][0]["type"] = 99  # unknown PrefixType
    status, entries = dec.decode([json.dumps(obj).encode()])
    assert status == [ST_FALLBACK] and entries[0] is None
