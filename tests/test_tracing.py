"""Convergence tracing: histogram math, tracer determinism, the no-op
fast path, queue telemetry, monitor ring-eviction counting, Chrome-trace
export, and the 9-node grid end-to-end acceptance (multi-node span tree
from a link event to the FIB ack with a TPU/XLA SPF-kernel child span).
All timing runs on SimClock — traces replay identically across hosts."""

import asyncio
import json

import pytest

from openr_tpu.common.runtime import CounterMap, Histogram, SimClock
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.tracing import (
    NOOP_SPAN,
    Tracer,
    chrome_trace_events,
    disabled_tracer,
    write_chrome_trace,
)
from openr_tpu.types import TraceContext


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# histogram math
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_bucket_boundaries(self):
        h = Histogram(min_bound=1.0, growth=2.0, num_buckets=4)
        assert h.edges == [1.0, 2.0, 4.0, 8.0]
        # bucket 0 is [0, min_bound]; upper edges are inclusive
        assert h.bucket_index(0.0) == 0
        assert h.bucket_index(1.0) == 0
        assert h.bucket_index(1.0001) == 1
        assert h.bucket_index(2.0) == 1
        assert h.bucket_index(2.0001) == 2
        assert h.bucket_index(8.0) == 3
        assert h.bucket_index(8.0001) == 4  # overflow bucket
        assert h.bucket_bounds(0) == (0.0, 1.0)
        assert h.bucket_bounds(2) == (2.0, 4.0)

    def test_observe_counts_and_stats(self):
        h = Histogram(min_bound=1.0, growth=2.0, num_buckets=4)
        for v in (0.5, 1.5, 3.0, 3.5, 100.0):
            h.observe(v)
        assert h.counts == [1, 1, 2, 0, 1]
        assert h.count == 5
        assert h.total == pytest.approx(108.5)
        assert h.vmin == 0.5 and h.vmax == 100.0

    def test_percentile_interpolation(self):
        h = Histogram(min_bound=1.0, growth=2.0, num_buckets=4)
        h.observe(1.5)  # bucket 1: (1, 2]
        h.observe(3.0)  # bucket 2: (2, 4]
        # rank(p50) = 1 -> falls at the end of bucket 1 -> its upper edge
        assert h.percentile(50) == pytest.approx(2.0)
        # rank(p100) = 2 -> end of bucket 2, clamped to observed max 3.0
        assert h.percentile(100) == pytest.approx(3.0)
        # p25: rank .5 -> halfway through bucket 1 -> clamped to vmin 1.5
        assert h.percentile(25) == pytest.approx(1.5)

    def test_percentile_single_value_is_exact(self):
        h = Histogram()
        for _ in range(10):
            h.observe(7.0)
        # interpolation is clamped to [min, max] so a single-valued
        # population reports exactly that value at every percentile
        assert h.percentile(50) == 7.0
        assert h.percentile(99) == 7.0
        assert h.percentiles() == {"p50": 7.0, "p95": 7.0, "p99": 7.0}

    def test_empty_percentile_is_none(self):
        assert Histogram().percentile(50) is None
        assert CounterMap().percentiles("nope") is None

    def test_merge_equals_union(self):
        a, b, u = Histogram(), Histogram(), Histogram()
        for v in (1, 2, 3, 50):
            a.observe(v)
            u.observe(v)
        for v in (0.5, 10, 200):
            b.observe(v)
            u.observe(v)
        a.merge(b)
        assert a.counts == u.counts
        assert a.count == u.count and a.total == pytest.approx(u.total)
        assert (a.vmin, a.vmax) == (u.vmin, u.vmax)
        for p in (50, 95, 99):
            assert a.percentile(p) == pytest.approx(u.percentile(p))

    def test_merge_config_mismatch_raises(self):
        with pytest.raises(ValueError):
            Histogram(growth=1.5).merge(Histogram(growth=2.0))
        with pytest.raises(ValueError):
            Histogram(min_bound=0.01).merge(Histogram(min_bound=0.1))

    # -- ISSUE 7 satellite: empty / mismatched-width behavior is DEFINED
    # (the happy path above was the only coverage before)

    def test_empty_histogram_percentiles_are_all_none(self):
        h = Histogram()
        assert h.percentiles() == {"p50": None, "p95": None, "p99": None}
        snap = h.snapshot()
        assert snap["count"] == 0 and snap["p50"] is None
        assert snap["min"] is None and snap["max"] is None
        assert h.bucket_items() == []

    def test_merge_from_and_into_empty(self):
        a, b = Histogram(), Histogram()
        b.observe(3.0)
        a.merge(b)  # empty += populated
        assert a.count == 1 and a.percentile(50) == 3.0
        c = Histogram()
        a.merge(c)  # populated += empty: unchanged
        assert a.count == 1 and a.percentile(50) == 3.0

    def test_merge_mismatched_widths_widens(self):
        # the 20-bucket grid's edges (a PREFIX of the 160-bucket grid's)
        # top out at ~0.142; values below that add positionally exact
        narrow = Histogram(num_buckets=20)
        wide = Histogram(num_buckets=160)
        for v in (0.02, 0.1):
            narrow.observe(v)
        for v in (2.0, 500.0):
            wide.observe(v)
        # wide += narrow: shared geometric edges add positionally
        w2 = wide.copy()
        w2.merge(narrow)
        assert w2.count == 4 and len(w2.counts) == 161
        assert w2.vmin == 0.02 and w2.vmax == 500.0
        u = Histogram(num_buckets=160)
        for v in (0.02, 0.1, 2.0, 500.0):
            u.observe(v)
        assert w2.counts == u.counts
        # narrow += wide: self WIDENS to the larger grid, overflow counts
        # stay conservative (narrow's overflow -> merged overflow)
        n2 = Histogram(num_buckets=20)
        n2.observe(0.05)
        n2.observe(999.0)  # overflow of the 20-bucket grid
        n2.merge(wide)
        assert len(n2.counts) == 161 and len(n2.edges) == 160
        assert n2.count == 4
        # 999.0 sat in narrow's overflow: it stays in the MERGED
        # overflow (conservative — the narrow grid no longer knows
        # which of the newly-exposed buckets it belonged to)
        assert n2.counts[-1] == 1
        assert n2.edges == u.edges
        assert n2.percentile(99) <= n2.vmax

    def test_bucket_items_and_config(self):
        h = Histogram()
        h.observe(0.005)  # bucket 0
        h.observe(1e12)  # overflow
        items = h.bucket_items()
        assert items[0] == (h.min_bound, 1)
        assert items[-1] == (float("inf"), 1)
        assert h.config() == {
            "min_bound": 0.01, "growth": 1.15, "num_buckets": 160,
        }

    def test_counter_map_histograms(self):
        c = CounterMap()
        c.observe("x.ms", 5.0)
        c.observe("x.ms", 5.0)
        c.observe("y.ms", 1.0)
        assert c.percentiles("x.ms")["p50"] == 5.0
        dump = c.dump_histograms()
        assert set(dump) == {"x.ms", "y.ms"}
        assert dump["x.ms"]["count"] == 2
        assert c.dump_histograms("y.") == {"y.ms": dump["y.ms"]}
        c.clear()
        assert c.dump_histograms() == {}


# ---------------------------------------------------------------------------
# tracer: deterministic spans on SimClock
# ---------------------------------------------------------------------------


class TestTracer:
    def test_simclock_deterministic_durations(self):
        async def main():
            clock = SimClock()
            tracer = Tracer("n0", clock, counters=CounterMap())
            ctx = tracer.start_trace("origin", module="test")
            assert ctx.trace_id.startswith("n0:")
            assert ctx.origin_node == "n0"
            span = tracer.start_span("stage", ctx, module="test")

            async def sleeper():
                await clock.sleep(1.5)
                tracer.end_span(span)

            task = asyncio.get_running_loop().create_task(sleeper())
            await clock.run_for(2.0)
            await task
            return tracer

        tracer = run(main())
        spans = tracer.get_spans()
        assert [s.name for s in spans] == ["origin", "stage"]
        stage = spans[1]
        assert stage.duration_ms() == pytest.approx(1500.0)
        assert stage.parent_id == spans[0].span_id
        assert stage.trace_id == spans[0].trace_id
        # replay: a fresh SimClock run produces the identical trace
        spans2 = run(main()).get_spans()
        assert [s.to_wire() for s in spans2] == [s.to_wire() for s in spans]

    def test_child_ctx_rebases_span_and_pins_origin(self):
        clock = SimClock(start=1.0)
        tracer = Tracer("n0", clock)
        ctx = tracer.start_trace("origin")
        span = tracer.start_span("mid", ctx)
        child = tracer.child_ctx(span, ctx)
        assert child.trace_id == ctx.trace_id
        assert child.span_id == span.span_id != ctx.span_id
        assert child.origin_event == "origin"
        assert child.t0_ms == ctx.t0_ms == 1000
        tracer.end_span(span)

    def test_ring_eviction_and_open_span_drop_counting(self):
        clock = SimClock()
        counters = CounterMap()
        tracer = Tracer(
            "n0", clock, counters=counters, max_spans=4, max_open_spans=2
        )
        for i in range(10):
            tracer.instant(f"e{i}")
        assert len(tracer.get_spans()) == 4
        assert tracer.num_evicted == 6
        assert counters.get("trace.spans_evicted") == 6
        # opening past the cap drops the OLDEST open span
        s1 = tracer.start_span("a")
        tracer.start_span("b")
        tracer.start_span("c")
        assert tracer.num_dropped == 1
        assert counters.get("trace.dropped_spans") == 1
        # the dropped span is sealed: a late end is a no-op and it never
        # reaches the completed ring
        tracer.end_span(s1)
        assert all(s.name != "a" for s in tracer.get_spans())
        assert tracer.stats()["trace.dropped_spans"] == 1.0

    def test_span_scope_records_errors(self):
        tracer = Tracer("n0", SimClock())
        with pytest.raises(RuntimeError):
            with tracer.span("boom") as sp:
                raise RuntimeError("x")
        assert sp.attrs["error"] == "RuntimeError"
        assert sp.end_ms is not None


class TestNoopFastPath:
    def test_disabled_tracer_is_free(self):
        tracer = disabled_tracer()
        assert tracer.start_trace("ev") is None
        assert tracer.start_span("x") is NOOP_SPAN
        assert tracer.instant("x") is NOOP_SPAN
        tracer.end_span(NOOP_SPAN)  # no-op
        assert tracer.child_ctx(NOOP_SPAN, None) is None
        ctx = TraceContext(trace_id="t", span_id="s")
        assert tracer.child_ctx(NOOP_SPAN, ctx) is ctx
        with tracer.span("y") as sp:
            assert sp is NOOP_SPAN
        assert tracer.get_spans() == []
        assert tracer.stats()["trace.spans_completed"] == 0.0

    def test_enabled_tracer_requires_clock(self):
        with pytest.raises(ValueError):
            Tracer("n0", clock=None, enabled=True)

    def test_disabled_pipeline_records_nothing(self):
        """Whole-pipeline no-op: with tracing disabled the network
        converges with zero spans, no contexts on queue items, and no
        convergence histogram — the disabled overhead is one flag check."""
        from openr_tpu.emulation.network import EmulatedNetwork
        from openr_tpu.emulation.topology import line_edges

        def no_tracing(cfg):
            cfg.tracing_config.enabled = False

        async def main():
            clock = SimClock()
            net = EmulatedNetwork(clock, config_overrides=no_tracing)
            net.build(line_edges(3))
            net.start()
            await clock.run_for(12.0)
            ok, why = net.converged_full_mesh()
            assert ok, why
            net.fail_link("node0", "node1")
            await clock.run_for(5.0)
            for node in net.nodes.values():
                assert node.tracer.get_spans() == []
                assert node.tracer.stats()["trace.spans_completed"] == 0.0
                assert (
                    node.counters.histogram("convergence.event_to_fib_ms")
                    is None
                )
            await net.stop()

        run(main())


# ---------------------------------------------------------------------------
# queue telemetry + monitor ring eviction
# ---------------------------------------------------------------------------


def test_queue_high_watermark_and_stats():
    q = ReplicateQueue("testq")
    r = q.get_reader()
    for i in range(5):
        q.push(i)
    assert q.max_backlog() == 5
    assert q.high_watermark() == 5
    for _ in range(5):
        assert r.try_get() is not None
    # backlog drained but the high watermark records the peak
    assert q.max_backlog() == 0
    assert q.high_watermark() == 5
    stats = q.stats()
    assert stats == {
        "depth": 0.0,
        "high_watermark": 5.0,
        "writes": 5.0,
        "readers": 1.0,
    }
    # a removed reader cannot regress the peak
    q.remove_reader(r)
    assert q.high_watermark() == 5


def test_node_queue_gauges_reach_counters():
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import line_edges

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock)
        net.build(line_edges(2))
        net.start()
        await clock.run_for(12.0)
        node = net.nodes["node0"]
        node.monitor.sample_system_metrics()
        dump = node.counters.dump("messaging.queue.")
        assert any(
            k == "messaging.queue.kvStoreUpdates.writes" and v > 0
            for k, v in dump.items()
        )
        assert "messaging.queue.routeUpdates.high_watermark" in dump
        # dispatcher subscriber queues are covered too
        assert any(".depth" in k and "dispatcher" not in k for k in dump)
        assert node.counters.get("trace.enabled") == 1.0
        await net.stop()

    run(main())


def test_monitor_counts_ring_evictions():
    from openr_tpu.messaging.queue import ReplicateQueue as RQ
    from openr_tpu.monitor.monitor import Monitor
    from openr_tpu.types import LogSample

    clock = SimClock()
    q = RQ("logSamples")
    reader = q.get_reader()
    counters = CounterMap()
    mon = Monitor(
        "n0",
        clock,
        log_sample_reader=reader,
        counters=counters,
        max_event_log_size=3,
    )
    for i in range(5):
        mon.process_log_sample(LogSample(event=f"e{i}"))
    assert counters.get("monitor.log.sample_received") == 5
    # ring holds 3; the 2 oldest fell off and are now counted
    assert len(mon.get_event_logs()) == 3
    assert counters.get("monitor.log.sample_evicted") == 2
    # disabled-submission drops stay a separate counter
    assert counters.get("monitor.log.sample_dropped") == 0


# ---------------------------------------------------------------------------
# chrome-trace export
# ---------------------------------------------------------------------------


def test_chrome_trace_export_schema(tmp_path):
    clock = SimClock()
    tracer = Tracer("nodeA", clock)
    ctx = tracer.start_trace("origin", module="spark")
    span = tracer.start_span("stage", ctx, module="decision")
    tracer.end_span(span)
    leaked = tracer.start_span("leak", ctx)  # open: must be skipped
    events = chrome_trace_events(tracer.get_spans())
    # metadata records name the process/thread lanes
    metas = [e for e in events if e["ph"] == "M"]
    assert {m["name"] for m in metas} >= {"process_name", "thread_name"}
    xs = [e for e in events if e["ph"] == "X"]
    assert len(xs) == 2  # origin + stage; the open span is skipped
    for e in xs:
        assert set(e) >= {"name", "cat", "ph", "ts", "dur", "pid", "tid", "args"}
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["dur"] >= 0
        assert e["args"]["trace_id"] == ctx.trace_id
    # file form: one event per line inside a single valid JSON array
    path = tmp_path / "trace.json"
    n = write_chrome_trace(str(path), tracer.get_spans())
    text = path.read_text()
    parsed = json.loads(text)
    assert len(parsed) == n == len(events)
    assert text.splitlines()[0] == "["
    tracer.end_span(leaked)


# ---------------------------------------------------------------------------
# 9-node grid acceptance: link event -> FIB ack, TPU kernel child span
# ---------------------------------------------------------------------------


def _tpu_device_always(cfg):
    cfg.tpu_compute_config.min_device_prefixes = 0


def test_nine_node_grid_end_to_end_trace():
    """The acceptance run: one emulated 9-node grid with tracing enabled
    produces (a) a complete multi-node span tree from a link event to the
    FIB ack with a `decision.spf_kernel` child span, (b) p50/p95/p99 for
    `convergence.event_to_fib_ms` and `decision.spf_kernel_ms` via the
    get_histograms ctrl surface, (c) a validating Chrome-trace JSONL
    export — deterministically, on SimClock."""
    from openr_tpu.ctrl.handler import OpenrCtrlHandler
    from openr_tpu.emulation.network import EmulatedNetwork
    from openr_tpu.emulation.topology import grid_edges

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(
            clock, use_tpu_backend=True, config_overrides=_tpu_device_always
        )
        net.build(grid_edges(3))
        net.start()
        await clock.run_for(20.0)
        ok, why = net.converged_full_mesh()
        assert ok, why
        net.fail_link("node0", "node1")
        await clock.run_for(8.0)

        spans = net.all_spans()
        by_id = {s.span_id: s for s in spans}

        def root_of(s):
            seen = set()
            while s.parent_id and s.parent_id in by_id and s.span_id not in seen:
                seen.add(s.span_id)
                s = by_id[s.parent_id]
            return s

        # (a) a multi-node span tree: some fib.ack on a REMOTE node whose
        # parent chain walks back to the ORIGIN node's link event, with a
        # TPU kernel child span inside the same trace
        complete = []
        for s in spans:
            if s.name != "fib.ack":
                continue
            root = root_of(s)
            trace_nodes = {t.node for t in spans if t.trace_id == s.trace_id}
            names = {t.name for t in spans if t.trace_id == s.trace_id}
            if (
                root.name.startswith(("link_monitor.interface", "spark."))
                and len(trace_nodes) >= 2
                and "decision.spf_kernel" in names
                and "decision.rebuild" in names
            ):
                complete.append((s, root, trace_nodes))
        assert complete, "no complete multi-node link-event->FIB-ack trace"
        s, root, trace_nodes = complete[0]
        assert root.node != s.node or len(trace_nodes) >= 2
        # the kernel span is a CHILD of the decision.spf dispatch span
        kernel = next(
            t
            for t in spans
            if t.trace_id == s.trace_id and t.name == "decision.spf_kernel"
        )
        assert by_id[kernel.parent_id].name == "decision.spf"
        assert kernel.attrs.get("kernel")
        # every span in the tree is closed (end-to-end completeness)
        assert all(
            t.end_ms is not None for t in spans if t.trace_id == s.trace_id
        )

        # (b) histograms through the ctrl surface
        handler = OpenrCtrlHandler(net.nodes[s.node])
        hists = handler.get_histograms()
        for key in ("convergence.event_to_fib_ms", "decision.spf_kernel_ms"):
            assert key in hists, f"missing histogram {key}"
            for p in ("p50", "p95", "p99"):
                assert hists[key][p] is not None
        assert hists["convergence.event_to_fib_ms"]["p50"] > 0
        # ctrl trace surface returns the same trace
        got = handler.get_traces(trace_id=s.trace_id)
        assert any(t["name"] == "fib.ack" for t in got)
        assert handler.get_trace_ids()

        # (c) Chrome-trace export validates
        import tempfile

        with tempfile.NamedTemporaryFile("r", suffix=".json") as f:
            n = net.export_trace(f.name)
            events = json.load(open(f.name))
            assert n == len(events) > 0
            xs = [e for e in events if e["ph"] == "X"]
            assert xs and all(
                set(e) >= {"name", "ph", "ts", "dur", "pid", "tid"}
                for e in xs
            )
            # one pid lane per emitting node
            pids = {e["pid"] for e in events}
            assert len(pids) >= 9

        # bounded-drop invariant on the healthy path
        for node in net.nodes.values():
            assert node.tracer.num_dropped == 0
        await net.stop()

    run(main())
