"""Rolling-restart survival (ISSUE 12): the supervisor's restart-storm
guard, deliberate-restart queue, the crash-latch/incarnation contract
across back-to-back restarts, and the tier-1 smoke of the rolling sweep
scenario (structural warm-hit, zero alerts, byte-identical replay) —
the full-scale round lives in ``bench.py --rolling``."""

import asyncio

import pytest

import bench
from openr_tpu.chaos import RollingRestartSweep, Supervisor
from openr_tpu.common.runtime import SimClock
from openr_tpu.emulation.network import EmulatedNetwork
from openr_tpu.emulation.topology import grid_edges, topology_nodes

pytestmark = [pytest.mark.chaos]


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# restart-storm guard
# ---------------------------------------------------------------------------


def test_storm_guard_caps_concurrency_and_queues_fifo():
    async def main():
        clock = SimClock()
        sup = Supervisor(clock, initial_backoff_s=1.0)
        sup.start()
        order = []

        class _Node:
            watchdog = None
            kv_store = None

        async def restart(name):
            await clock.sleep(2.0)  # a slow restart holds the slot
            order.append((round(clock.now(), 1), name))
            return _Node()

        for n in ("a", "b", "c"):
            sup.supervise(n, _Node(), restart)
        # three crashes land at once: with the default cap of 1 they
        # must restart strictly one at a time, in arrival order
        for n in ("a", "b", "c"):
            sup.on_crash(n, "storm")
        assert sup.queue_depth() == 2
        await clock.run_for(30.0)
        assert [n for _t, n in order] == ["a", "b", "c"]
        assert sup.max_observed_concurrency == 1
        assert sup.num_restarts == 3
        # restarts never overlapped: completion times are spaced by at
        # least the restart duration
        times = [t for t, _n in order]
        assert all(b - a >= 2.0 for a, b in zip(times, times[1:]))
        await sup.stop()

    run(main())


def test_storm_guard_configurable_cap():
    async def main():
        clock = SimClock()
        sup = Supervisor(
            clock, initial_backoff_s=1.0, max_concurrent_restarts=2
        )
        sup.start()
        done = []

        class _Node:
            watchdog = None
            kv_store = None

        async def restart(name):
            await clock.sleep(2.0)
            done.append(name)
            return _Node()

        for n in ("a", "b", "c", "d"):
            sup.supervise(n, _Node(), restart)
            sup.on_crash(n, "storm")
        await clock.run_for(30.0)
        assert sorted(done) == ["a", "b", "c", "d"]
        assert sup.max_observed_concurrency == 2
        await sup.stop()

    run(main())


def test_request_restart_is_deliberate_not_a_crash():
    async def main():
        clock = SimClock()
        sup = Supervisor(clock)
        sup.start()
        stopped = []

        class _Node:
            watchdog = None
            kv_store = None

        async def restart(name):
            return _Node()

        async def stop(name):
            stopped.append((round(clock.now(), 1), name))

        sup.supervise("a", _Node(), restart, stop=stop)
        assert sup.request_restart("a", down_s=3.0) is True
        # double-request while queued/in-flight dedupes
        assert sup.request_restart("a", down_s=3.0) is False
        assert sup.request_restart("ghost") is False
        await clock.run_for(10.0)
        assert stopped == [(0.0, "a")]
        assert sup.num_requested_restarts == 1
        assert sup.num_restarts == 1
        assert sup.num_crashes == 0 and sup.crash_log == []
        assert sup.restart_log[0][1:] == ("a", "request")
        # the down window was honored before the replacement came up
        assert sup.restart_log[0][0] >= 3.0
        await sup.stop()

    run(main())


# ---------------------------------------------------------------------------
# crash latch + incarnation stamp across back-to-back restarts
# ---------------------------------------------------------------------------


def test_crash_latch_and_incarnation_across_back_to_back_restarts():
    def overrides(cfg):
        cfg.watchdog_config.interval_s = 1.0

    async def main():
        clock = SimClock()
        net = EmulatedNetwork(clock, config_overrides=overrides)
        net.build(grid_edges(2))
        net.start()
        sup = Supervisor(clock, initial_backoff_s=0.25, max_backoff_s=2.0)
        sup.start()
        for name, node in net.nodes.items():
            sup.supervise(name, node, net.restart_node)
        await clock.run_for(12.0)
        victim = sorted(net.nodes)[1]
        incarnations = [net.nodes[victim].counters.get("node.start_ms")]
        for round_i in range(2):
            old = net.nodes[victim]

            async def _die():
                raise RuntimeError("chaos kill")

            old.spark.spawn(_die(), name="spark.die")
            for _ in range(40):
                await clock.run_for(1.0)
                if net.nodes[victim] is not old and victim not in (
                    sup.restarting()
                ):
                    break
            assert net.nodes[victim] is not old, f"round {round_i}"
            incarnations.append(
                net.nodes[victim].counters.get("node.start_ms")
            )
            await clock.run_for(4.0)
        # two crashes, two restarts, and the watchdog of EACH fresh
        # incarnation stayed wired to the supervisor (the second crash
        # was caught too)
        assert sup.num_crashes >= 2
        assert sup.num_restarts == 2
        # the incarnation stamp strictly advances across restarts (the
        # health plane's crash latch relies on it to tell a counter
        # wipe from a silent reset)
        assert incarnations[0] < incarnations[1] < incarnations[2]
        # fresh incarnations start with a clean crash counter — the
        # LATCH (health aggregator) carries history, not the node
        assert (
            net.nodes[victim].counters.get("watchdog.crashes") or 0
        ) == 0
        await sup.stop()
        await net.stop()

    run(main())


# ---------------------------------------------------------------------------
# the sweep scenario, tier-1 smoke
# ---------------------------------------------------------------------------


def test_rolling_sweep_smoke_structural_warm_and_quiet():
    """Tiny rolling sweep through the full scenario harness: every
    non-observer node bounced once, every structural tick warm, SLO
    held, zero alerts, serving load answered."""
    detail, fingerprint = bench.rolling_sweep_world(16, seed=11)
    assert detail["sweep"]["nodes_bounced"] == detail["nodes"] - 1
    assert detail["sweep"]["crashes"] == 0
    assert detail["sweep"]["max_concurrent_observed"] == 1
    w = detail["warm"]
    assert w["structural_hits"] >= detail["sweep"]["nodes_bounced"]
    assert w["structural_hit_ratio"] > 0.8
    assert w["slot_patches"] >= w["structural_hits"]
    assert detail["slo"]["p99_within_slo"] is True
    assert detail["alerts"]["unexpected"] == 0
    assert detail["serving"]["queries"] > 0
    assert detail["serving"]["errors"] == 0
    assert fingerprint


def test_rolling_sweep_replay_byte_identical():
    runs = [bench.rolling_sweep_world(9, seed=7) for _ in range(2)]
    assert runs[0][1] == runs[1][1]
    assert runs[0][0] == runs[1][0]


def test_rolling_sweep_seed_sensitivity():
    a, fp_a = bench.rolling_sweep_world(9, seed=7)
    b, fp_b = bench.rolling_sweep_world(9, seed=8)
    # a different seed shuffles the bounce order: fingerprints differ
    assert fp_a != fp_b
