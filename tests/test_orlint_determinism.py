"""Call-graph engine + replay-determinism family + result cache (ISSUE 15).

Three layers:

* callgraph.py units: summary round-trip, constructor-assignment
  attribute typing, callback harvesting, reachability with hop counts
  and the Clock barrier;
* determinism rule behavior beyond the FIXTURES smoke in test_orlint.py:
  the acceptance pair (a wall-clock call two hops from an actor run loop
  trips ``wallclock-reachability``; the same call behind an injected
  Clock does not), unordered-emission's sink transitivity and its
  sanctioned ``sorted(..)`` spelling, seeded-vs-global randomness,
  identity sort keys;
* the ``--cache`` contract: a warm run re-parses ZERO unchanged files, a
  content edit re-runs exactly the edited file when the cross-module
  facts are unchanged, and a summary change or rule-set bump re-runs
  everything — with findings byte-equal to the uncached engine.
"""

import json

import pytest

from openr_tpu.analysis import (
    analyze_modules,
    analyze_paths,
    analyze_source,
    build_project,
)
from openr_tpu.analysis.callgraph import ModuleSummary
from openr_tpu.analysis.passes.base import ParsedModule

# ---------------------------------------------------------------------------
# call graph units
# ---------------------------------------------------------------------------

GRAPH_SRC = """\
from openr_tpu.common.runtime import Actor

class Helper:
    def work(self):
        return inner()

def inner():
    return 1

class Node(Actor):
    def __init__(self):
        self.helper = Helper()
        self.register(self.on_tick)

    async def run(self):
        self.helper.work()

    def on_tick(self):
        return inner()
"""


def _project_for(*sources):
    mods = [
        ParsedModule.parse(f"m{i}.py", src) for i, src in enumerate(sources)
    ]
    return build_project(mods), mods


def test_summary_round_trip_and_digest_stability():
    pm = ParsedModule.parse("m0.py", GRAPH_SRC)
    s = pm.summary()
    doc = s.to_json()
    restored = ModuleSummary.from_json(json.loads(json.dumps(doc)))
    assert restored.to_json() == doc
    assert restored.content_hash() == s.content_hash()
    # the facts a pass would query
    assert s.classes["Node"].bases == ["Actor"]
    assert s.classes["Node"].attrs["helper"] == "Helper"
    assert "Node.run" in s.functions and "inner" in s.functions


def test_project_round_trip_is_edge_identical_over_the_repo():
    """Cache soundness hangs on this: a Project built from JSON-round-
    tripped summaries must resolve EXACTLY the same call edges as one
    built from fresh parses — otherwise a ``--cache`` run with any warm
    entries analyzes a different program than a cold run (the bug this
    test pins: FunctionInfo reconstruction corrupted the method index,
    so by-name/typed dispatch silently vanished on warm paths)."""
    from openr_tpu.analysis import load_modules, repo_root

    mods = load_modules([repo_root() / "openr_tpu"])
    fresh = [m.summary() for m in mods]
    rt = [
        ModuleSummary.from_json(json.loads(json.dumps(s.to_json())))
        for s in fresh
    ]
    from openr_tpu.analysis.callgraph import Project

    p1, p2 = Project(fresh), Project(rt)
    assert p1.methods.keys() == p2.methods.keys()
    assert p1.functions.keys() == p2.functions.keys()
    assert p1.edges == p2.edges


def test_constructor_attr_typing_resolves_method_edges():
    proj, _ = _project_for(GRAPH_SRC)
    edges = proj.edges["m0.Node.run"]
    assert "m0.Helper.work" in edges
    # and the method's own body chains on
    assert "m0.inner" in proj.edges["m0.Helper.work"]


def test_callback_harvesting_makes_registration_an_edge():
    """`self.register(self.on_tick)` — passing a bound method is how
    every fiber/listener is born; it must be a call edge."""
    proj, _ = _project_for(GRAPH_SRC)
    assert "m0.Node.on_tick" in proj.edges["m0.Node.__init__"]


def test_reachability_reports_root_and_hops():
    proj, _ = _project_for(GRAPH_SRC)
    reach = proj.reachable_from(["m0.Node.run"])
    assert reach["m0.Helper.work"].hops == 1
    assert reach["m0.inner"].hops == 2
    assert reach["m0.inner"].root == "m0.Node.run"
    assert "m0.Node.on_tick" not in reach  # only registered from __init__


def test_subclasses_of_is_transitive():
    proj, _ = _project_for(
        "class A:\n    pass\n\nclass B(A):\n    pass\n\nclass C(B):\n    pass\n"
    )
    assert proj.subclasses_of("A") == {"A", "B", "C"}


# ---------------------------------------------------------------------------
# wallclock-reachability: the acceptance pair
# ---------------------------------------------------------------------------

#: a Clock lookalike whose now() IS a wall-clock read — the barrier test
#: needs the forbidden call to live INSIDE the injected-clock class
CLOCK_CTX = """\
import time

class Clock:
    def now(self):
        return time.monotonic()
"""

BEHIND_CLOCK = """\
from openr_tpu.common.runtime import Actor
from ctx0 import Clock

class Poller(Actor):
    def __init__(self, clock: Clock):
        self.clock = clock

    async def run(self):
        return self._stamp()

    def _stamp(self):
        return self.clock.now()
"""


def _all_findings(snippet, *ctx):
    mods = [ParsedModule.parse("snippet.py", snippet)]
    for i, src in enumerate(ctx):
        mods.append(ParsedModule.parse(f"ctx{i}.py", src))
    return analyze_modules(mods).findings


def test_wallclock_two_hops_from_run_loop_trips():
    """Acceptance: `datetime.now()` two call hops below an actor run
    loop trips, and the message names the root and the distance."""
    src = (
        "from openr_tpu.common.runtime import Actor\n"
        "from datetime import datetime\n"
        "\n"
        "class Poller(Actor):\n"
        "    async def run(self):\n"
        "        self._tick()\n"
        "\n"
        "    def _tick(self):\n"
        "        return self._stamp()\n"
        "\n"
        "    def _stamp(self):\n"
        "        return datetime.now()\n"
    )
    hits = [
        f for f in analyze_source(src) if f.rule == "wallclock-reachability"
    ]
    assert [f.line for f in hits] == [12]
    assert "2 call hops" in hits[0].message
    assert "snippet.Poller.run" in hits[0].message


def test_wallclock_behind_injected_clock_is_a_barrier():
    """Acceptance: the SAME wall-clock read behind an injected Clock
    does not trip anywhere — Clock-subclass methods are the sanctioned
    discipline and traversal stops at the barrier."""
    hits = [
        f
        for f in _all_findings(BEHIND_CLOCK, CLOCK_CTX)
        if f.rule == "wallclock-reachability"
    ]
    assert hits == []


def test_wallclock_barrier_is_the_clock_name_not_luck():
    """Control for the barrier test: the identical wiring through a
    class NOT named into the Clock hierarchy DOES trip (inside the
    helper class, reached from the actor loop)."""
    ctx = CLOCK_CTX.replace("class Clock:", "class Stamper:")
    src = BEHIND_CLOCK.replace("Clock", "Stamper")
    hits = [
        f
        for f in _all_findings(src, ctx)
        if f.rule == "wallclock-reachability"
    ]
    assert [(f.path, f.line) for f in hits] == [("ctx0.py", 5)]


def test_wallclock_unreachable_helper_is_clean():
    """No root reaches it ⇒ the interprocedural rule stays quiet (the
    per-site clock-now rule still governs protocol-plane sites)."""
    src = (
        "from datetime import datetime\n"
        "\n"
        "def stamp():\n"
        "    return datetime.now()\n"
    )
    assert [
        f.rule for f in analyze_source(src) if f.rule == "wallclock-reachability"
    ] == []


# ---------------------------------------------------------------------------
# unordered-emission: sinks, transitivity, sanctioned spellings
# ---------------------------------------------------------------------------


def test_unordered_emission_set_param_feeding_digest_trips():
    src = (
        "import hashlib\n"
        "\n"
        "def digest(tags: set):\n"
        "    h = hashlib.sha256()\n"
        "    for t in tags:\n"
        "        h.update(str(t).encode())\n"
        "    return h.hexdigest()\n"
    )
    hits = [f for f in analyze_source(src) if f.rule == "unordered-emission"]
    assert [f.line for f in hits] == [5]
    assert "set `tags`" in hits[0].message


def test_unordered_emission_transitive_through_helper():
    """The loop body's call chain — not just the direct call — reaches
    the sink (the call-graph upgrade the per-file linter couldn't do)."""
    src = (
        "from openr_tpu.sweep.scenario import canonical_json\n"
        "\n"
        "def _encode(row):\n"
        "    return canonical_json(row)\n"
        "\n"
        "def emit(rows, out):\n"
        "    for k, v in rows.items():\n"
        "        out.append(_encode({k: v}))\n"
    )
    hits = [f for f in analyze_source(src) if f.rule == "unordered-emission"]
    assert [f.line for f in hits] == [7]
    assert "canonical_json" in hits[0].message


def test_unordered_emission_sorted_is_the_sanctioned_spelling():
    src = (
        "from openr_tpu.sweep.scenario import canonical_json\n"
        "\n"
        "def emit(rows, out):\n"
        "    for key, val in sorted(rows.items()):\n"
        "        out.append(canonical_json({key: val}))\n"
    )
    assert analyze_source(src) == []


def test_unordered_iteration_without_a_sink_is_not_a_finding():
    src = (
        "def tally(rows):\n"
        "    n = 0\n"
        "    for _k, v in rows.items():\n"
        "        n += v\n"
        "    return n\n"
    )
    assert analyze_source(src) == []


def test_unordered_emission_self_attr_set_trips():
    src = (
        "from openr_tpu.sweep.scenario import canonical_json\n"
        "\n"
        "class Reducer:\n"
        "    def __init__(self):\n"
        "        self.spof = set()\n"
        "\n"
        "    def summary(self, out):\n"
        "        for link in self.spof:\n"
        "            out.append(canonical_json(link))\n"
    )
    hits = [f for f in analyze_source(src) if f.rule == "unordered-emission"]
    assert [f.line for f in hits] == [8]
    assert "set `self.spof`" in hits[0].message


def test_unordered_emission_deliver_wire_callback_is_a_sink():
    src = (
        "def fanout(subs: dict, payload, deliver_wire):\n"
        "    for sub in subs.values():\n"
        "        deliver_wire(payload)\n"
    )
    hits = [f for f in analyze_source(src) if f.rule == "unordered-emission"]
    assert [f.line for f in hits] == [2]


# ---------------------------------------------------------------------------
# unseeded-random / unstable-sort-key
# ---------------------------------------------------------------------------


def test_seeded_random_instances_are_clean():
    src = (
        "import random\n"
        "\n"
        "def draws(seed: int):\n"
        "    rng = random.Random(seed)\n"
        "    return rng.random(), rng.randint(0, 7)\n"
    )
    assert analyze_source(src) == []


def test_unseeded_random_instance_and_global_seed_trip():
    src = (
        "import random\n"
        "\n"
        "def setup():\n"
        "    random.seed(42)\n"
        "    return random.Random()\n"
    )
    assert [f.rule for f in analyze_source(src)] == [
        "unseeded-random",
        "unseeded-random",
    ]


def test_numpy_global_draw_trips_but_seeded_generator_is_clean():
    src = (
        "import numpy as np\n"
        "\n"
        "def noise(n):\n"
        "    return np.random.rand(n)\n"
    )
    assert [f.rule for f in analyze_source(src)] == ["unseeded-random"]
    clean = (
        "import numpy as np\n"
        "\n"
        "def noise(n, seed):\n"
        "    return np.random.default_rng(seed).random(n)\n"
    )
    assert analyze_source(clean) == []


def test_unstable_sort_key_lambda_and_method_forms():
    src = (
        "def order(rows, cohorts):\n"
        "    rows.sort(key=lambda r: hash(r))\n"
        "    return max(cohorts, key=id)\n"
    )
    assert [f.rule for f in analyze_source(src)] == [
        "unstable-sort-key",
        "unstable-sort-key",
    ]


def test_content_sort_keys_are_clean():
    src = (
        "def order(rows):\n"
        "    rows.sort(key=lambda r: (r.name, r.seq))\n"
        "    return sorted(rows, key=str)\n"
    )
    assert analyze_source(src) == []


# ---------------------------------------------------------------------------
# the --cache contract
# ---------------------------------------------------------------------------

A_SRC = "import time\n\ndef f():\n    return time.time()\n"
B_SRC = "def g():\n    return 1\n"


def _tree(tmp_path):
    d = tmp_path / "src"
    d.mkdir(exist_ok=True)
    (d / "a.py").write_text(A_SRC)
    (d / "b.py").write_text(B_SRC)
    return d, tmp_path / "cache.json"


def test_cache_warm_run_parses_zero_files(tmp_path):
    d, cache = _tree(tmp_path)
    r1 = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r1.files_parsed == 2
    assert [f.rule for f in r1.findings] == ["clock-now"]
    r2 = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r2.files_parsed == 0, "warm run must re-parse zero files"
    assert [f.key() for f in r2.findings] == [f.key() for f in r1.findings]
    # and matches the uncached engine byte for byte
    r3 = analyze_paths([d], use_baseline=False)
    assert [f.to_json() for f in r3.findings] == [
        f.to_json() for f in r2.findings
    ]


def test_cache_content_edit_reruns_only_that_file(tmp_path):
    """An edit whose module summary is unchanged (a string constant —
    constants carry no cross-module facts) re-runs exactly one file and
    still surfaces the new finding."""
    d, cache = _tree(tmp_path)
    analyze_paths([d], use_baseline=False, cache_path=cache)
    # module-level constant: no function extents move, no calls change —
    # the summary (cross-module facts) is byte-identical
    (d / "b.py").write_text(B_SRC + "\n'pipeline.decode.ms'\n")
    r = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r.files_parsed == 1
    assert sorted(f.rule for f in r.findings) == [
        "clock-now",
        "pipeline-phase-registry",
    ]


def test_cache_summary_change_reruns_everything(tmp_path):
    """Adding a function changes the project facts digest — every file's
    interprocedural findings could have moved, so everything re-runs."""
    d, cache = _tree(tmp_path)
    analyze_paths([d], use_baseline=False, cache_path=cache)
    (d / "b.py").write_text(B_SRC + "\ndef h():\n    return g()\n")
    r = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r.files_parsed == 2


def test_cache_ruleset_bump_invalidates_everything(tmp_path):
    d, cache = _tree(tmp_path)
    analyze_paths([d], use_baseline=False, cache_path=cache)
    doc = json.loads(cache.read_text())
    doc["ruleset"] = "0" * 64  # a rule-set version bump
    cache.write_text(json.dumps(doc))
    r = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r.files_parsed == 2
    assert [f.rule for f in r.findings] == ["clock-now"]


def test_cache_tolerates_garbage_file(tmp_path):
    d, cache = _tree(tmp_path)
    cache.write_text("{ not json")
    r = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r.files_parsed == 2
    assert [f.rule for f in r.findings] == ["clock-now"]


def test_cache_preserves_suppressions(tmp_path):
    d = tmp_path / "src"
    d.mkdir()
    (d / "a.py").write_text(
        "import time\n\ndef f():\n"
        "    return time.time()  # orlint: disable=clock-now (why)\n"
    )
    cache = tmp_path / "cache.json"
    r1 = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r1.findings == [] and len(r1.suppressed) == 1
    r2 = analyze_paths([d], use_baseline=False, cache_path=cache)
    assert r2.files_parsed == 0
    assert r2.findings == [] and len(r2.suppressed) == 1


# ---------------------------------------------------------------------------
# the determinism pass and the repo itself
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "rel",
    [
        "openr_tpu/kvstore/merge.py",
        "openr_tpu/kvstore/kv_store.py",
        "openr_tpu/sweep/executor.py",
        "openr_tpu/daemon.py",
    ],
)
def test_cleaned_modules_stay_clean(rel):
    """The ISSUE-15 cleanup pinned: the modules whose unordered
    emissions were fixed must stay free of determinism findings."""
    from openr_tpu.analysis import load_modules, repo_root

    mods = load_modules([repo_root() / "openr_tpu"])
    report = analyze_modules(mods)
    offenders = [
        f
        for f in report.findings
        if f.path == rel
        and f.rule in ("unordered-emission", "unstable-sort-key")
    ]
    assert offenders == []
