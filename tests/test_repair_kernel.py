"""Warm-start repair kernel exactness (ops/repair.py).

The repair kernel must produce bit-identical results to the cold batched
kernel (ops/spf.py) for every snapshot: the warm start is an exact
optimization (affected-set over-estimate + Bellman-Ford-from-over-
estimate + unique-fixed-point reset lanes), not an approximation.
"""

import numpy as np
import pytest

from openr_tpu.decision.link_state import LinkState
from openr_tpu.emulation.topology import (
    build_adj_dbs,
    grid_edges,
    line_edges,
    random_connected_edges,
)
from openr_tpu.ops.csr import encode_link_state
from openr_tpu.ops.repair import (
    RepairSweep,
    build_repair_plan,
    sort_by_depth,
)
from openr_tpu.ops.whatif import LinkFailureSweep


def make_topo(edges, **kwargs):
    ls = LinkState("0")
    for db in build_adj_dbs(edges, **kwargs).values():
        ls.update_adjacency_database(db)
    return ls, encode_link_state(ls)


def cold_solve(topo, fails, root_id, D):
    import jax.numpy as jnp

    from openr_tpu.ops.spf import sweep_spf_link_failures

    d, nh = sweep_spf_link_failures(
        jnp.asarray(topo.src),
        jnp.asarray(topo.dst),
        jnp.asarray(topo.w),
        jnp.asarray(topo.edge_ok),
        jnp.asarray(topo.link_index),
        jnp.asarray(fails),
        jnp.asarray(topo.overloaded),
        jnp.int32(root_id),
        max_degree=D,
        packed=False,
    )
    return np.asarray(d), np.asarray(nh)  # [V, B], [V, B, D]


def repair_engine(topo, root="node0"):
    eng = LinkFailureSweep(topo, root)
    base_dist, base_nh = eng.base_solve()
    plan = build_repair_plan(
        topo, topo.node_id(root), base_dist, base_nh
    )
    return plan, RepairSweep(topo, plan)


def assert_repair_matches_cold(topo, fails, root="node0"):
    plan, rs = repair_engine(topo, root)
    B = len(fails)
    assert B % 32 == 0
    d, nh, _, _ = rs.solve(fails)
    d, nh = np.asarray(d), np.asarray(nh)
    dcold, nhcold = cold_solve(
        topo, fails, topo.node_id(root), topo.max_out_degree()
    )
    assert np.array_equal(d, dcold)
    for s in range(B):
        dense = (
            (nh[:, :, s // 32] >> np.uint32(s % 32)) & 1
        ).astype(np.int8)
        ref = (nhcold[:, s, : plan.lanes] > 0).astype(np.int8)
        assert np.array_equal(dense, ref), f"lanes s={s} fail={fails[s]}"
        # no lanes beyond root out-degree
        assert not (nhcold[:, s, plan.lanes :] > 0).any()


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_repair_matches_cold_random_wan(seed):
    _, topo = make_topo(random_connected_edges(64, 96, seed=seed))
    rng = np.random.default_rng(seed)
    fails = rng.integers(-1, len(topo.links), size=64).astype(np.int32)
    assert_repair_matches_cold(topo, fails)


def test_repair_matches_cold_grid_all_links():
    # uniform grid: every link on some shortest path; heavy ECMP ties
    _, topo = make_topo(grid_edges(5))
    L = len(topo.links)
    fails = np.full(64, -1, np.int32)
    fails[:L] = np.arange(L)
    assert_repair_matches_cold(topo, fails)


def test_repair_with_overloaded_nodes():
    _, topo = make_topo(
        random_connected_edges(48, 64, seed=5), overloaded=["node7", "node9"]
    )
    rng = np.random.default_rng(5)
    fails = rng.integers(0, len(topo.links), size=32).astype(np.int32)
    assert_repair_matches_cold(topo, fails)


def test_repair_disconnecting_failure():
    # line topology: every link is a bridge; failing it disconnects the
    # tail, whose distances must become +inf and lanes empty
    ls, topo = make_topo(line_edges(8))
    fails = np.full(32, -1, np.int32)
    fails[:7] = np.arange(7)
    assert_repair_matches_cold(topo, fails)
    plan, rs = repair_engine(topo)
    d, nh, _, _ = rs.solve(fails)
    d = np.asarray(d)
    # failing link 2 (node2-node3) cuts nodes 3.. from node0
    for v in range(topo.num_nodes):
        vid = topo.node_id(f"node{v}")
        if v >= 3:
            assert d[vid, 2] >= 3.0e38
        else:
            assert d[vid, 2] == v


def test_depth_sort_preserves_results_through_engine():
    # many duplicate failures: engine dedups, depth-sorts, and must map
    # every snapshot back to the right row
    ls, topo = make_topo(random_connected_edges(48, 64, seed=77))
    eng = LinkFailureSweep(topo, "node0")
    rng = np.random.default_rng(77)
    fails = rng.integers(0, len(topo.links), size=200).astype(np.int32)
    res = eng.run(fails)
    for s in range(0, 200, 13):
        ref = ls.run_spf(
            "node0", links_to_ignore=frozenset([topo.links[int(fails[s])]])
        )
        dist = res.dist_of(s)
        for node, r in ref.items():
            assert dist[topo.node_id(node)] == np.float32(r.metric)
        reached = {topo.node_id(n) for n in ref}
        for v in range(topo.num_nodes):
            if v not in reached:
                assert dist[v] >= 3.0e38


def test_sort_by_depth_roundtrip():
    _, topo = make_topo(random_connected_edges(32, 48, seed=3))
    plan, _ = repair_engine(topo)
    rng = np.random.default_rng(3)
    fails = rng.integers(-1, len(topo.links), size=100).astype(np.int32)
    sfails, order = sort_by_depth(plan, fails)
    assert np.array_equal(sfails[np.argsort(order, kind="stable")], fails)
    keys = np.where(
        sfails >= 0, plan.repair_depth[np.clip(sfails, 0, None)], 0
    )
    assert (np.diff(keys) >= 0).all()


def test_batch_must_be_multiple_of_32():
    _, topo = make_topo(random_connected_edges(16, 10, seed=1))
    _, rs = repair_engine(topo)
    with pytest.raises(ValueError):
        rs.solve(np.zeros(33, np.int32))
