"""orlint self-tests + the tier-1 static-invariant gate.

Three layers:

* fixture snippets that must trip each rule, and the same snippets with a
  suppression comment that must pass — the linter's own regression suite;
* baseline machinery round-trips (dump/load/apply, stale detection) and a
  meta-test that every checked-in ``analysis/baseline.json`` entry still
  points at a real file whose text still contains the offending line;
* the gate itself: ``python -m openr_tpu.analysis --check`` must exit 0
  on the repo as committed.  A new violation anywhere in ``openr_tpu/``
  fails THIS test — fix it or suppress it with a justification; only
  regenerate the baseline after fixing, never instead of fixing.
"""

import json

import pytest

from openr_tpu.analysis import (
    Baseline,
    StaleSuppression,
    analyze_modules,
    analyze_source,
    build_project,
    default_baseline_path,
    findings_from_sarif,
    load_modules,
    render_sarif,
    repo_root,
)
from openr_tpu.analysis.suppress import strip_stale
from openr_tpu.analysis.__main__ import main as orlint_main
from openr_tpu.analysis.passes import all_rules, rule_example, rule_families
from openr_tpu.analysis.passes.base import ParsedModule

# ---------------------------------------------------------------------------
# fixtures: one per rule — (source, context sources, line that must trip)
# ---------------------------------------------------------------------------

ACTOR_CTX = """\
from openr_tpu.common.runtime import Actor

class Spark(Actor):
    pass

class KvStore(Actor):
    pass
"""

JIT_CTX = """\
import jax

@jax.jit
def kernel(x):
    return x * 2
"""

FIXTURES = {
    "clock-sleep": (
        "import asyncio\n"
        "\n"
        "async def retry_loop():\n"
        "    await asyncio.sleep(0.5)\n",
        (),
        4,
    ),
    "clock-now": (
        "import time as _time\n"
        "\n"
        "def deadline():\n"
        "    return _time.monotonic() + 5.0\n",
        (),
        4,
    ),
    "clock-call-later": (
        "def arm(loop, cb):\n"
        "    loop.call_later(1.0, cb)\n",
        (),
        2,
    ),
    "actor-cross-write": (
        "from ctx0 import Spark\n"
        "\n"
        "def poke(spark: Spark) -> None:\n"
        "    spark.neighbors = {}\n",
        (ACTOR_CTX,),
        4,
    ),
    "actor-private-access": (
        "from ctx0 import KvStore\n"
        "\n"
        "def peek(kv: KvStore):\n"
        "    return kv._db\n",
        (ACTOR_CTX,),
        4,
    ),
    "jit-unguarded-call": (
        "from ctx0 import kernel\n"
        "\n"
        "def run(v):\n"
        "    return kernel(v)\n",
        (JIT_CTX,),
        4,
    ),
    "jit-traced-branch": (
        "import jax\n"
        "\n"
        "@jax.jit\n"
        "def clamp(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n",
        (),
        5,
    ),
    "jit-host-sync": (
        "import jax\n"
        "\n"
        "@jax.jit\n"
        "def bad(x):\n"
        "    return x.block_until_ready()\n",
        (),
        5,
    ),
    "async-blocking": (
        "class Loader:\n"
        "    async def load(self, path):\n"
        "        return open(path).read()\n",
        (),
        3,
    ),
    "resilience-latch": (
        "def drain(backend):\n"
        "    backend.device_failed = True\n",
        (),
        2,
    ),
    "slot-table": (
        "def churn(enc, ls):\n"
        "    return patch_encoded_topology_slots(enc, ls, 'me')\n",
        (),
        2,
    ),
    "pipeline-phase-registry": (
        "def record(counters):\n"
        '    counters.observe("pipeline.decode.ms", 1.0)\n',
        (),
        2,
    ),
    "alert-name-registry": (
        "def fire(counters):\n"
        '    counters.bump("health.alert.chip_quarantine")\n',
        (),
        2,
    ),
    "sweep-spill-ownership": (
        "def shortcut(spill, rows):\n"
        "    spill.spill_rows(rows)\n",
        (),
        2,
    ),
    "fleet-directory": (
        "def evict(membership, name):\n"
        "    membership.node_down(name)\n",
        (),
        2,
    ),
    "fleet-liveness": (
        "def fence(membership):\n"
        "    membership.bump_epoch()\n",
        (),
        2,
    ),
    "protection-table": (
        "def shortcut(table, doc, prefix_state):\n"
        "    table.apply_patch(doc, prefix_state)\n",
        (),
        2,
    ),
    # -- replay-determinism family (ISSUE 15) ------------------------------
    "unordered-emission": (
        "from openr_tpu.sweep.scenario import canonical_json\n"
        "\n"
        "def emit(rows, out):\n"
        "    for key, val in rows.items():\n"
        "        out.append(canonical_json({key: val}))\n",
        (),
        4,
    ),
    "wallclock-reachability": (
        "from openr_tpu.common.runtime import Actor\n"
        "from datetime import datetime\n"
        "\n"
        "class Poller(Actor):\n"
        "    async def run(self):\n"
        "        self._tick()\n"
        "\n"
        "    def _tick(self):\n"
        "        return self._stamp()\n"
        "\n"
        "    def _stamp(self):\n"
        "        return datetime.now()\n",
        (),
        12,
    ),
    "unseeded-random": (
        "import random\n"
        "\n"
        "def jitter():\n"
        "    return random.random()\n",
        (),
        4,
    ),
    "unstable-sort-key": (
        "def order(rows):\n"
        "    return sorted(rows, key=id)\n",
        (),
        2,
    ),
    # -- await-atomicity family (ISSUE 17) ---------------------------------
    "await-atomicity": (
        "from openr_tpu.common.runtime import Actor\n"
        "\n"
        "class Cache(Actor):\n"
        "    async def lookup(self, key):\n"
        "        if key not in self._entries:\n"
        "            value = await self._fetch(key)\n"
        "            self._entries[key] = value\n"
        "        return self._entries[key]\n",
        (),
        7,
    ),
    "await-aliasing": (
        "from openr_tpu.common.runtime import Actor\n"
        "\n"
        "class Publisher(Actor):\n"
        "    def __init__(self, updates_q):\n"
        "        self._routes = {}\n"
        "        self._q = updates_q\n"
        "\n"
        "    def publish(self):\n"
        "        self._q.push(self._routes)\n",
        (),
        9,
    ),
    "await-iteration": (
        "from openr_tpu.common.runtime import Actor\n"
        "\n"
        "class Flusher(Actor):\n"
        "    def __init__(self):\n"
        "        self._pending = {}\n"
        "\n"
        "    async def flush(self):\n"
        "        for key, value in self._pending.items():\n"
        "            await self._send(key, value)\n",
        (),
        8,
    ),
}


def test_fixtures_cover_every_rule():
    assert set(FIXTURES) == set(all_rules())


def test_pipeline_registry_rule_covers_warm_phase_names():
    """ISSUE-9 satellite: the new warm_plan/warm_repair names are
    registry-governed like every other phase — a free spelling anywhere
    outside the registry trips pipeline-phase-registry."""
    for spelled in (
        '"pipeline.warm_plan.ms"',
        '"pipeline.warm_repair.ms"',
        '"pipeline.warm_repair"',
    ):
        src = f"def record(counters):\n    counters.observe({spelled}, 1.0)\n"
        findings = analyze_source(src)
        assert [f.rule for f in findings] == ["pipeline-phase-registry"], (
            spelled
        )


def test_pipeline_registry_rule_covers_stream_phase_names():
    """ISSUE-11 satellite: the streaming phases (stream_drain,
    device_select) are registry-governed — a free spelling anywhere
    outside the registry trips pipeline-phase-registry."""
    for spelled in (
        '"pipeline.stream_drain.ms"',
        '"pipeline.device_select.ms"',
        '"pipeline.stream_drain"',
    ):
        src = f"def record(counters):\n    counters.observe({spelled}, 1.0)\n"
        findings = analyze_source(src)
        assert [f.rule for f in findings] == ["pipeline-phase-registry"], (
            spelled
        )
    # and the registry itself exposes them (no free spelling needed)
    from openr_tpu.tracing import pipeline

    assert pipeline.hist_key(pipeline.WARM_PLAN).startswith("pipeline.")
    assert pipeline.hist_key(pipeline.STREAM_DRAIN).startswith("pipeline.")
    assert pipeline.span_name(pipeline.DEVICE_SELECT).startswith("pipeline.")


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_trips_on_fixture(rule):
    src, ctx, line = FIXTURES[rule]
    findings = analyze_source(src, context=ctx)
    assert [
        (f.rule, f.line) for f in findings
    ] == [(rule, line)], f"{rule} fixture: {findings}"
    # finding carries the offending line text for baseline matching
    assert findings[0].snippet == src.splitlines()[line - 1].strip()


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_line_suppression_silences_rule(rule):
    src, ctx, line = FIXTURES[rule]
    lines = src.splitlines()
    lines[line - 1] += f"  # orlint: disable={rule} (test justification)"
    assert analyze_source("\n".join(lines) + "\n", context=ctx) == []


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_file_suppression_silences_rule(rule):
    src, ctx, _ = FIXTURES[rule]
    src = f"# orlint: disable-file={rule}\n" + src
    assert analyze_source(src, context=ctx) == []


def test_suppressed_findings_are_reported_not_dropped():
    src, ctx, line = FIXTURES["clock-sleep"]
    lines = src.splitlines()
    lines[line - 1] += "  # orlint: disable=clock-sleep (why)"
    mods = [ParsedModule.parse("snippet.py", "\n".join(lines) + "\n")]
    report = analyze_modules(mods)
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["clock-sleep"]


# ---------------------------------------------------------------------------
# negatives: the idioms each rule must NOT flag
# ---------------------------------------------------------------------------


def test_asyncio_sleep_zero_is_a_yield_not_a_sleep():
    src = "import asyncio\n\nasync def f():\n    await asyncio.sleep(0)\n"
    assert analyze_source(src) == []


def test_clock_sleep_through_injected_clock_is_clean():
    src = (
        "async def f(clock):\n"
        "    await clock.sleep(1.0)\n"
        "    return clock.now()\n"
    )
    assert analyze_source(src) == []


def test_same_class_private_access_is_exempt():
    src = (
        "from openr_tpu.common.runtime import Actor\n"
        "\n"
        "class KvStore(Actor):\n"
        "    def merge(self, other: 'KvStore'):\n"
        "        other._db = {}\n"
    )
    assert analyze_source(src) == []


def test_public_read_of_actor_attr_is_clean():
    src = (
        "from ctx0 import Spark\n"
        "\n"
        "def describe(spark: Spark):\n"
        "    return spark.name\n"
    )
    assert analyze_source(src, context=(ACTOR_CTX,)) == []


def test_call_jit_guarded_dispatch_is_clean():
    src = (
        "from ctx0 import kernel\n"
        "from openr_tpu.ops.jit_guard import call_jit_guarded\n"
        "\n"
        "def run(v):\n"
        "    return call_jit_guarded(kernel, v)\n"
    )
    assert analyze_source(src, context=(JIT_CTX,)) == []


def test_jitted_call_inside_jitted_body_is_exempt():
    src = (
        "import jax\n"
        "\n"
        "@jax.jit\n"
        "def inner(x):\n"
        "    return x + 1\n"
        "\n"
        "@jax.jit\n"
        "def outer(x):\n"
        "    return inner(x)\n"
    )
    assert analyze_source(src) == []


def test_local_direct_jitted_call_trips():
    src = (
        "import jax\n"
        "\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    return x + 1\n"
        "\n"
        "def run(v):\n"
        "    return kernel(v)\n"
    )
    assert [f.rule for f in analyze_source(src)] == ["jit-unguarded-call"]


def test_jit_assignment_form_is_tracked():
    src = (
        "import jax\n"
        "\n"
        "def _impl(x):\n"
        "    return x + 1\n"
        "\n"
        "kernel = jax.jit(_impl, static_argnames=('n',))\n"
        "\n"
        "def run(v):\n"
        "    return kernel(v)\n"
    )
    assert [f.rule for f in analyze_source(src)] == ["jit-unguarded-call"]


def test_shape_branch_is_static_not_traced():
    src = (
        "import jax\n"
        "\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x.ndim > 1:\n"
        "        return x.sum()\n"
        "    return x\n"
    )
    assert analyze_source(src) == []


def test_static_argnames_param_branch_is_clean():
    src = (
        "import functools\n"
        "import jax\n"
        "\n"
        "@functools.partial(jax.jit, static_argnames=('n',))\n"
        "def f(x, n):\n"
        "    if n > 3:\n"
        "        return x * n\n"
        "    return x\n"
    )
    assert analyze_source(src) == []


def test_awaited_recv_is_an_async_transport_not_blocking():
    src = (
        "class T:\n"
        "    async def pump(self, sock):\n"
        "        return await sock.recv(1024)\n"
    )
    assert analyze_source(src) == []


def test_sync_helper_nested_in_async_def_is_skipped():
    src = (
        "class T:\n"
        "    async def load(self, loop, path):\n"
        "        def _read():\n"
        "            return open(path).read()\n"
        "        return await loop.run_in_executor(None, _read)\n"
    )
    assert analyze_source(src) == []


def test_non_protocol_trees_are_out_of_scope():
    src = "import time\n\ndef fmt():\n    return time.time()\n"
    mods = [ParsedModule.parse("openr_tpu/cli/breeze.py", src)]
    assert analyze_modules(mods).findings == []


def test_resilience_latch_call_form_trips():
    src = (
        "def heal(node):\n"
        "    node.decision.backend.inject_device_failure(False)\n"
    )
    assert [f.rule for f in analyze_source(src)] == ["resilience-latch"]
    src2 = "def corrupt(b):\n    b.inject_silent_corruption(True)\n"
    assert [f.rule for f in analyze_source(src2)] == ["resilience-latch"]


def test_resilience_latch_reads_are_clean():
    # device_available() and counter snapshots READ the latch — only
    # writes are owned by the governor
    src = (
        "def available(backend):\n"
        "    return not getattr(backend, 'device_failed', False)\n"
        "\n"
        "def gauge(backend):\n"
        "    return 1.0 if backend.device_failed else 0.0\n"
    )
    assert analyze_source(src) == []


@pytest.mark.parametrize(
    "rel",
    [
        "openr_tpu/decision/backend.py",
        "openr_tpu/resilience/governor.py",
        "openr_tpu/chaos/controller.py",
    ],
)
def test_resilience_latch_owners_are_exempt(rel):
    """The latch's legitimate owners (backend, governor, chaos) write it
    freely — the rule only polices everyone else."""
    src = (
        "def flip(backend):\n"
        "    backend.device_failed = True\n"
        "    backend.inject_device_failure(True)\n"
    )
    mods = [ParsedModule.parse(rel, src)]
    assert analyze_modules(mods).findings == []


def test_resilience_latch_pool_mutators_trip():
    """The per-device quarantine-mask mutators (DevicePool, ISSUE 6) are
    governor-owned exactly like the whole-backend latch."""
    src = "def drain(pool):\n    pool.quarantine_device(3)\n"
    assert [f.rule for f in analyze_source(src)] == ["resilience-latch"]
    src2 = "def heal(pool):\n    pool.restore_device(3)\n"
    assert [f.rule for f in analyze_source(src2)] == ["resilience-latch"]


def test_sweep_ownership_owners_are_exempt():
    """The sweep package writes its own spill/checkpoint state freely —
    the rule polices everyone else (ISSUE 14)."""
    src = (
        "def commit(spill, checkpoint, rows):\n"
        "    spill.spill_rows(rows)\n"
        "    checkpoint.commit_shard(0, {'rows': len(rows)})\n"
        "    checkpoint.reset('id', 'hash', {}, 1)\n"
    )
    mods = [ParsedModule.parse("openr_tpu/sweep/executor.py", src)]
    assert analyze_modules(mods).findings == []
    assert [f.rule for f in analyze_source(src)] == [
        "sweep-spill-ownership"
    ] * 3


@pytest.mark.parametrize(
    "rel",
    [
        "openr_tpu/protection/service.py",
        "openr_tpu/decision/decision.py",
    ],
)
def test_protection_table_owners_are_exempt(rel):
    """The protection package and Decision's apply path mutate the
    table freely — the rule polices everyone else (ISSUE 16)."""
    src = (
        "def lifecycle(table, doc, prefix_state):\n"
        "    table.begin_mint({'seq': 1}, 'hash')\n"
        "    table.mark_ready('hash', 4, 4)\n"
        "    table.mark_stale()\n"
        "    table.abort_mint()\n"
        "    table.purge_table('mismatch')\n"
        "    table.apply_patch(doc, prefix_state)\n"
    )
    mods = [ParsedModule.parse(rel, src)]
    assert analyze_modules(mods).findings == []
    assert [f.rule for f in analyze_source(src)] == [
        "protection-table"
    ] * 6


def test_protection_table_reads_are_clean():
    """Lookups, status and classification are read-only everywhere —
    only mutation is gated."""
    src = (
        "def watch(svc, prev_key):\n"
        "    status, doc = svc.lookup(prev_key, 'a|b')\n"
        "    svc.classify_pairs({('a', 'b')})\n"
        "    return svc.get_protection_status()\n"
    )
    assert analyze_source(src) == []


@pytest.mark.parametrize(
    "rel",
    [
        "openr_tpu/fleet/coordinator.py",
        "openr_tpu/chaos/controller.py",
        "openr_tpu/emulation/fabric.py",
    ],
)
def test_fleet_directory_owners_are_exempt(rel):
    """The fleet tier owns membership; chaos and the emulation fabric
    cross the boundary on purpose (ISSUE 19) — the rule polices
    everyone else."""
    src = (
        "def churn(membership):\n"
        "    membership.node_down('fab1')\n"
        "    membership.drain_node('fab2')\n"
        "    membership.undrain_node('fab2')\n"
        "    membership.node_up('fab1')\n"
    )
    mods = [ParsedModule.parse(rel, src)]
    assert analyze_modules(mods).findings == []
    assert [f.rule for f in analyze_source(src)] == [
        "fleet-directory"
    ] * 4


def test_fleet_directory_needs_membership_receiver():
    """The mutator names are generic enough that an unrelated receiver
    (``link.node_up()``) must not trip — only fleet-hinted receivers
    do; reads stay clean everywhere."""
    src = (
        "def poke(link, fleet_membership):\n"
        "    link.node_up()\n"
        "    fleet_membership.node_up('fab0')\n"
        "    return fleet_membership.live_nodes()\n"
    )
    assert [f.rule for f in analyze_source(src)] == ["fleet-directory"]


def test_fleet_liveness_single_writer_is_fleet_package_only():
    """The epoch/suspicion/damping mutators (ISSUE 20) are STRICTER
    than fleet-directory: only openr_tpu/fleet/ itself is exempt.
    Chaos and the emulation fabric — exempt from fleet-directory —
    must perturb the heartbeat plane and let the tracker conclude,
    so the same source trips fleet-liveness there."""
    src = (
        "def force(membership, tracker):\n"
        "    membership.bump_epoch()\n"
        "    membership.mark_suspect('fab1')\n"
        "    tracker.set_damped_until('fab1', 99.0)\n"
        "    tracker.record_incarnation('fab1', 7)\n"
    )
    owner = [ParsedModule.parse("openr_tpu/fleet/liveness.py", src)]
    assert analyze_modules(owner).findings == []
    for rel in (
        "openr_tpu/chaos/controller.py",
        "openr_tpu/emulation/fabric.py",
        "openr_tpu/serving/query.py",
    ):
        mods = [ParsedModule.parse(rel, src)]
        assert [f.rule for f in analyze_modules(mods).findings] == [
            "fleet-liveness"
        ] * 4, rel


def test_fleet_liveness_needs_fleet_receiver_and_reads_are_clean():
    """Receiver-hint discipline carries over: ``clock.bump_epoch()`` on
    an unrelated object must not trip, and the read surface (``epoch``,
    ``suspects()``, ``member_state``) stays clean everywhere."""
    src = (
        "def poke(sim, liveness_tracker, membership):\n"
        "    sim.bump_epoch()\n"
        "    liveness_tracker.record_incarnation('fab0', 3)\n"
        "    liveness_tracker.member_state('fab0')\n"
        "    return membership.epoch, membership.suspects()\n"
    )
    assert [f.rule for f in analyze_source(src)] == ["fleet-liveness"]


def test_sweep_ownership_reset_needs_checkpoint_receiver():
    """Plain ``x.reset()`` on unrelated objects must not trip — only a
    receiver whose name marks it as the checkpoint manifest does."""
    src = (
        "def clear(breaker, manifest):\n"
        "    breaker.reset()\n"
        "    manifest.reset('id', 'hash', {}, 1)\n"
    )
    assert [f.rule for f in analyze_source(src)] == [
        "sweep-spill-ownership"
    ]


def test_slot_table_mutator_calls_trip():
    """Slot-stable structural patches (ISSUE 12) are backend-owned —
    anyone else calling them breaks the encode chain's single-owner
    discipline."""
    src = (
        "def churn(enc, ls):\n"
        "    return patch_encoded_topology_slots(enc, ls, 'me')\n"
    )
    assert [f.rule for f in analyze_source(src)] == ["slot-table"]
    src2 = (
        "def churn(prev, als):\n"
        "    from openr_tpu.ops import csr\n"
        "    return csr.patch_encoded_multi_area_slots(prev, als, 'me')\n"
    )
    assert [f.rule for f in analyze_source(src2)] == ["slot-table"]


def test_slot_table_metadata_writes_trip_reads_are_clean():
    src = (
        "def fabricate(enc):\n"
        "    enc.tombstoned_nodes = frozenset({'ghost'})\n"
        "    enc.slot_changed = None\n"
    )
    assert [f.rule for f in analyze_source(src)] == [
        "slot-table",
        "slot-table",
    ]
    # reads are how the warm planner and tests consume the metadata
    src2 = (
        "def inspect(enc):\n"
        "    return (enc.tombstoned_nodes, enc.tombstoned_links,\n"
        "            enc.slot_changed)\n"
    )
    assert analyze_source(src2) == []


@pytest.mark.parametrize(
    "rel",
    [
        "openr_tpu/ops/csr.py",
        "openr_tpu/decision/backend.py",
    ],
)
def test_slot_table_owners_are_exempt(rel):
    src = (
        "def patch(old, ls):\n"
        "    enc, reason = patch_encoded_topology_slots(old, ls, 'me')\n"
        "    enc.slot_changed = None\n"
        "    return enc\n"
    )
    mods = [ParsedModule.parse(rel, src)]
    assert analyze_modules(mods).findings == []


def test_alert_registry_fstring_head_trips():
    """A dynamically-built alert name is exactly the bug the rule
    exists for — the f-string HEAD carries the prefix."""
    src = (
        "def fire(counters, name):\n"
        '    counters.bump(f"health.alert.{name}")\n'
    )
    assert [f.rule for f in analyze_source(src)] == ["alert-name-registry"]


def test_alert_registry_owner_module_is_exempt():
    """The registry itself (health/alerts.py) spells the prefix — the
    rule only polices everyone else."""
    src = 'ALERT_COUNTER_PREFIX = "health.alert."\n'
    mods = [ParsedModule.parse("openr_tpu/health/alerts.py", src)]
    assert analyze_modules(mods).findings == []
    # the same text anywhere else trips
    mods2 = [ParsedModule.parse("openr_tpu/health/aggregator.py", src)]
    assert [f.rule for f in analyze_modules(mods2).findings] == [
        "alert-name-registry"
    ]


def test_alert_registry_reads_through_the_api_are_clean():
    src = (
        "from openr_tpu.health.alerts import alert_counter_key\n"
        "\n"
        "def fire(counters):\n"
        '    counters.bump(alert_counter_key("chip_quarantine"))\n'
    )
    assert analyze_source(src) == []


def test_resilience_latch_pool_reads_and_governor_api_are_clean():
    """Health READS and the governor's counted/probed per-chip API are
    exactly what everyone else is supposed to use."""
    src = (
        "def watch(pool, gov):\n"
        "    gov.force_quarantine_device(1, reason='drain')\n"
        "    gov.request_probe_device(1)\n"
        "    return pool.healthy_indices(), pool.is_healthy(1)\n"
    )
    assert analyze_source(src) == []


@pytest.mark.parametrize(
    "rel",
    [
        "openr_tpu/parallel/mesh.py",
        "openr_tpu/resilience/governor.py",
        "openr_tpu/chaos/controller.py",
    ],
)
def test_resilience_latch_pool_owners_are_exempt(rel):
    src = (
        "def flip(pool):\n"
        "    pool.quarantine_device(0)\n"
        "    pool.restore_device(0)\n"
    )
    mods = [ParsedModule.parse(rel, src)]
    assert analyze_modules(mods).findings == []


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------


def _mods_for(rule):
    src, ctx, _ = FIXTURES[rule]
    mods = [ParsedModule.parse("snippet.py", src)]
    for i, c in enumerate(ctx):
        mods.append(ParsedModule.parse(f"ctx{i}.py", c))
    return mods


def test_baseline_round_trip(tmp_path):
    mods = _mods_for("clock-sleep")
    found = analyze_modules(mods).findings
    assert found
    path = tmp_path / "baseline.json"
    Baseline.from_findings(found).dump(path)
    report = analyze_modules(mods, Baseline.load(path))
    assert report.findings == []
    assert [f.rule for f in report.baselined] == ["clock-sleep"]
    assert report.stale_baseline == []


def test_baseline_matching_survives_line_drift(tmp_path):
    src, _, _ = FIXTURES["clock-sleep"]
    path = tmp_path / "baseline.json"
    found = analyze_modules([ParsedModule.parse("snippet.py", src)]).findings
    Baseline.from_findings(found).dump(path)
    # unrelated edit above the grandfathered hit must not resurrect it
    drifted = "import os  # new unrelated import\n" + src
    report = analyze_modules(
        [ParsedModule.parse("snippet.py", drifted)], Baseline.load(path)
    )
    assert report.findings == []
    assert len(report.baselined) == 1


def test_baseline_goes_stale_when_finding_is_fixed(tmp_path):
    src, _, line = FIXTURES["clock-sleep"]
    path = tmp_path / "baseline.json"
    found = analyze_modules([ParsedModule.parse("snippet.py", src)]).findings
    Baseline.from_findings(found).dump(path)
    fixed = src.replace("asyncio.sleep(0.5)", "clock.sleep(0.5)")
    report = analyze_modules(
        [ParsedModule.parse("snippet.py", fixed)], Baseline.load(path)
    )
    assert report.findings == []
    assert [e.rule for e in report.stale_baseline] == ["clock-sleep"]


def test_checked_in_baseline_entries_are_fresh():
    """Meta-test: every baseline.json entry must still point at an
    existing file whose text still contains the offending line — the
    ratchet that forces dead entries out after a fix."""
    baseline = Baseline.load(default_baseline_path())
    root = repo_root()
    for e in baseline.entries:
        target = root / e.path
        assert target.is_file(), f"baseline entry for vanished file {e.path}"
        lines = [ln.strip() for ln in target.read_text().splitlines()]
        assert e.snippet in lines, (
            f"baseline entry {e.rule}@{e.path} no longer matches any line; "
            "fix was landed — regenerate with --update-baseline"
        )
        assert 1 <= e.line <= len(lines), f"baseline line out of range: {e}"


# ---------------------------------------------------------------------------
# the gate + CLI surfaces
# ---------------------------------------------------------------------------


def test_repo_is_clean_under_check():
    """THE tier-1 gate: the repo as committed has no unsuppressed,
    unbaselined invariant violations.  ``--cache`` is part of the
    canonical invocation (ISSUE 15): correctness must be identical with
    the result cache in the loop."""
    assert orlint_main(["--check", "--cache"]) == 0


def test_actor_registry_rides_the_symbol_table():
    """The project-wide Actor registry is now a symbol-table query
    (callgraph.Project.subclasses_of) — the serving/streaming/sweep
    actors must all land in it, with zero new baseline entries (the gate
    above stays empty-baselined)."""
    mods = load_modules([repo_root() / "openr_tpu"])
    proj = build_project(mods)
    actors = proj.subclasses_of("Actor")
    assert "QueryService" in actors, "serving actor missing from registry"
    assert {
        "Decision",
        "KvStore",
        "Monitor",
        "StreamingService",
        "SweepService",
    } <= actors
    # and the serving tree is protocol-plane (scanned, not exempted)
    assert any(
        m.rel.startswith("openr_tpu/serving/") and m.is_protocol_plane()
        for m in mods
    )
    # the jitted-kernel registry rides the same summaries (jax_hygiene
    # consolidation): spot-check a known kernel family
    jitted = proj.jitted_registry()
    assert any(v for v in jitted.values()), "no jitted kernels collected"


def test_check_fails_on_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES["clock-sleep"][0])
    assert orlint_main([str(bad), "--check", "--no-baseline"]) == 1


def test_json_format_reports_counts(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES["clock-now"][0])
    rc = orlint_main([str(bad), "--format=json", "--no-baseline"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0  # json mode without --check reports, never gates
    assert doc["files_scanned"] == 1
    assert doc["counts"] == {"clock-now": 1}
    assert doc["findings"][0]["rule"] == "clock-now"
    assert {"path", "line", "col", "message", "snippet"} <= set(
        doc["findings"][0]
    )


def test_rule_filter(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES["clock-now"][0] + FIXTURES["clock-call-later"][0])
    rc = orlint_main(
        [str(bad), "--format=json", "--no-baseline", "--rule", "clock-now"]
    )
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["counts"] == {"clock-now": 1}


def test_list_rules(capsys):
    """Every rule with its pass FAMILY tag + one-line description
    (ISSUE-15 satellite: the determinism family must be discoverable)."""
    assert orlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in FIXTURES:
        assert rule in out
    for family in ("determinism", "clock-discipline", "actor-isolation"):
        assert f"[{family}]" in out
    families = rule_families()
    assert families["unordered-emission"] == "determinism"
    assert families["clock-sleep"] == "clock-discipline"


def test_github_format_emits_error_annotations(tmp_path, capsys):
    """``--format=github``: one ``::error`` workflow command per finding
    (JSON mode untouched — covered above)."""
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES["clock-now"][0])
    rc = orlint_main([str(bad), "--format=github", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 0
    (line,) = [ln for ln in out.splitlines() if ln]
    assert line.startswith("::error file=")
    assert "line=4" in line
    assert "title=orlint clock-now" in line
    assert "::`time.monotonic` reads host time" in line
    # gating semantics match text mode
    assert orlint_main([str(bad), "--format=github", "--no-baseline", "--check"]) == 1


def test_explain_prints_trip_and_fix(capsys):
    assert orlint_main(["--explain", "unordered-emission"]) == 0
    out = capsys.readouterr().out
    assert "unordered-emission [determinism]" in out
    assert "trips:" in out and "fixed:" in out
    assert "sorted(rows.items())" in out
    assert "orlint: disable=unordered-emission" in out


def test_explain_unknown_rule_fails(capsys):
    assert orlint_main(["--explain", "no-such-rule"]) == 2
    assert "unknown rule" in capsys.readouterr().out


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_every_rule_ships_a_validated_explain_example(rule):
    """META-TEST (ISSUE-15 satellite): every registered rule MUST carry
    an ``--explain`` example whose trip snippet actually trips the rule
    and whose fixed twin is completely clean — the next contributor
    cannot add a rule without documentation that provably works."""
    found = rule_example(rule)
    assert found is not None, f"rule {rule} has no --explain example"
    _family, ex = found
    ctx = tuple(ex.get("context", ()))
    tripped = {f.rule for f in analyze_source(ex["trip"], context=ctx)}
    assert rule in tripped, f"{rule} example trip does not trip: {tripped}"
    fixed = analyze_source(ex["fix"], context=ctx)
    assert fixed == [], f"{rule} example fix is not clean: {fixed}"


def test_fixture_and_example_coverage_is_total():
    """META-TEST: a rule without BOTH a trip fixture (FIXTURES — which
    the parametrized trip/suppression tests consume) and an --explain
    example fails here by name, not by silent omission."""
    rules = set(all_rules())
    assert set(FIXTURES) == rules
    missing = {r for r in rules if rule_example(r) is None}
    assert not missing, f"rules without --explain examples: {missing}"


def test_module_entry_point():
    """`python -m openr_tpu.analysis --check --cache` is THE canonical
    tier-1 invocation CI scripts call."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "openr_tpu.analysis", "--check", "--cache"],
        capture_output=True,
        text=True,
        timeout=300,
        cwd=str(repo_root()),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# stale-suppression audit (ISSUE 17 satellite)
# ---------------------------------------------------------------------------


def _report_for(src, rules=None):
    return analyze_modules([ParsedModule.parse("m.py", src)], rules=rules)


def test_stale_suppression_detected_when_rule_never_fires():
    """A marker naming a rule that does not fire on its line is dead
    weight hiding future violations — the audit names it precisely."""
    src = (
        "import time\n"
        "\n"
        "def f():\n"
        "    return time.monotonic()  # orlint: disable=clock-sleep (wrong rule)\n"
    )
    report = _report_for(src)
    # the marker suppressed nothing: the clock-now finding survives
    assert [f.rule for f in report.findings] == ["clock-now"]
    assert report.stale_suppressions == [
        StaleSuppression(path="m.py", line=4, rules=("clock-sleep",))
    ]


def test_live_suppression_is_not_stale():
    src = (
        "import time\n"
        "\n"
        "def f():\n"
        "    return time.monotonic()  # orlint: disable=clock-now (why)\n"
    )
    report = _report_for(src)
    assert report.findings == [] and len(report.suppressed) == 1
    assert report.stale_suppressions == []


def test_partially_stale_marker_reports_only_the_dead_rule():
    src = (
        "import time\n"
        "\n"
        "def f():\n"
        "    return time.monotonic()  # orlint: disable=clock-now,clock-sleep (why)\n"
    )
    report = _report_for(src)
    assert report.findings == []
    assert report.stale_suppressions == [
        StaleSuppression(path="m.py", line=4, rules=("clock-sleep",))
    ]


def test_disable_all_is_live_while_anything_fires():
    live = (
        "import time\n"
        "\n"
        "def f():\n"
        "    return time.monotonic()  # orlint: disable=all (why)\n"
    )
    assert _report_for(live).stale_suppressions == []
    dead = "def f():\n    return 1  # orlint: disable=all (nothing fires)\n"
    assert _report_for(dead).stale_suppressions == [
        StaleSuppression(path="m.py", line=2, rules=("all",))
    ]


def test_file_level_stale_suppression_reports_line_zero():
    src = (
        "# orlint: disable-file=clock-sleep\n"
        "\n"
        "def f():\n"
        "    return 1\n"
    )
    assert _report_for(src).stale_suppressions == [
        StaleSuppression(path="m.py", line=0, rules=("clock-sleep",))
    ]


def test_rule_filter_skips_the_stale_audit():
    """Under --rule only some passes ran: a marker for an unexecuted
    rule would look dead without being dead.  No audit, no false calls."""
    src = "def f():\n    return 1  # orlint: disable=clock-sleep (x)\n"
    assert _report_for(src).stale_suppressions != []
    assert _report_for(src, rules=["clock-now"]).stale_suppressions == []


def test_docstring_marker_is_documentation_not_a_directive():
    """Marker text inside a string literal neither suppresses nor
    registers in the audit — only real COMMENT tokens count."""
    src = (
        '"""Docs show: use `x  # orlint: disable=clock-now (why)` here,\n'
        "or `# orlint: disable-file=clock-now` for whole files.\n"
        '"""\n'
        "import time\n"
        "\n"
        "def f():\n"
        "    return time.monotonic()\n"
    )
    report = _report_for(src)
    assert [f.rule for f in report.findings] == ["clock-now"]
    assert report.suppressed == []
    assert report.stale_suppressions == []


def test_strip_stale_narrows_and_removes_markers():
    src = (
        "import time\n"
        "\n"
        "def f():\n"
        "    return time.monotonic()  # orlint: disable=clock-now,clock-sleep (epoch)\n"
        "\n"
        "def g():\n"
        "    return 1  # orlint: disable=clock-sleep (fully stale)\n"
    )
    out, edits = strip_stale(
        src, [(4, ("clock-sleep",)), (7, ("clock-sleep",))]
    )
    assert edits == 2
    lines = out.splitlines()
    # partially stale: narrowed to the live rule, justification kept
    assert lines[3] == "    return time.monotonic()  # orlint: disable=clock-now (epoch)"
    # fully stale: the whole comment goes, the code stays
    assert lines[6] == "    return 1"


def test_strip_stale_deletes_marker_only_lines_and_file_markers():
    src = (
        "# orlint: disable-file=clock-sleep,clock-now\n"
        "def f():\n"
        "    return 1\n"
    )
    out, edits = strip_stale(src, [(0, ("clock-sleep", "clock-now"))])
    assert edits == 1
    assert out == "def f():\n    return 1\n"
    # narrowing keeps the marker line with the surviving rule
    out2, _ = strip_stale(src, [(0, ("clock-sleep",))])
    assert out2.splitlines()[0] == "# orlint: disable-file=clock-now"


def test_strip_stale_leaves_docstring_examples_alone():
    src = (
        '"""Use `# orlint: disable-file=clock-sleep` sparingly."""\n'
        "def f():\n"
        "    return 1\n"
    )
    out, edits = strip_stale(src, [(0, ("clock-sleep",))])
    assert edits == 0
    assert out == src


def test_check_warns_on_stale_suppressions_but_stays_green(tmp_path, capsys):
    f = tmp_path / "m.py"
    f.write_text("def f():\n    return 1  # orlint: disable=clock-now (stale)\n")
    rc = orlint_main([str(f), "--check", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 0, "stale suppressions warn, they do not gate"
    assert "[stale-suppression]" in out
    assert "1 stale suppression(s)" in out


def test_fix_stale_suppressions_cli_rewrites_files(tmp_path, capsys):
    f = tmp_path / "m.py"
    f.write_text(
        "import time\n"
        "\n"
        "def f():\n"
        "    return time.monotonic()  # orlint: disable=clock-now (live)\n"
        "\n"
        "def g():\n"
        "    return 1  # orlint: disable=clock-sleep (stale)\n"
    )
    rc = orlint_main([str(f), "--fix-stale-suppressions", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "removed 1 stale marker(s)" in out
    text = f.read_text()
    assert "disable=clock-now (live)" in text, "live marker must survive"
    assert "clock-sleep" not in text
    # the tree is now audit-clean
    rc = orlint_main([str(f), "--check", "--no-baseline"])
    assert rc == 0
    assert "[stale-suppression]" not in capsys.readouterr().out


def test_fix_stale_suppressions_refuses_rule_filter(capsys):
    rc = orlint_main(
        ["--fix-stale-suppressions", "--rule", "clock-now"]
    )
    assert rc == 2
    assert "full run" in capsys.readouterr().out


def test_repo_has_no_stale_suppressions():
    """The one-time sweep, pinned: every suppression comment in the
    repo still suppresses something real."""
    report = analyze_modules(load_modules([repo_root() / "openr_tpu"]))
    assert report.stale_suppressions == []


# ---------------------------------------------------------------------------
# SARIF (ISSUE 17 satellite)
# ---------------------------------------------------------------------------


def test_sarif_round_trips_findings_exactly():
    src = FIXTURES["clock-now"][0] + FIXTURES["clock-call-later"][0]
    report = _report_for(src)
    assert len(report.findings) == 2
    doc = render_sarif(report, all_rules())
    assert doc["version"] == "2.1.0"
    assert findings_from_sarif(doc) == report.findings


def test_sarif_driver_lists_only_fired_rules_with_rationale():
    report = _report_for(FIXTURES["clock-now"][0])
    doc = render_sarif(report, all_rules())
    (run,) = doc["runs"]
    rules = run["tool"]["driver"]["rules"]
    assert [r["id"] for r in rules] == ["clock-now"]
    assert rules[0]["shortDescription"]["text"] == all_rules()["clock-now"]
    (res,) = run["results"]
    assert res["ruleId"] == "clock-now" and res["ruleIndex"] == 0
    region = res["locations"][0]["physicalLocation"]["region"]
    assert region["startLine"] == 4
    assert region["startColumn"] >= 1  # SARIF columns are 1-based


def test_sarif_cli_output_parses(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES["clock-now"][0])
    rc = orlint_main([str(bad), "--format=sarif", "--no-baseline"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["version"] == "2.1.0"
    (found,) = findings_from_sarif(doc)
    assert found.rule == "clock-now" and found.line == 4
    # gating semantics match text mode
    assert (
        orlint_main([str(bad), "--format=sarif", "--no-baseline", "--check"])
        == 1
    )
    capsys.readouterr()
